"""The live QT-Opt cycle: a device-resident actor <-> learner loop.

ISSUE 12's tentpole, closing ROADMAP item 1. One process runs the whole
off-policy organ set the previous PRs built, concurrently:

  * **Actor** — ONE jitted program per acting step (``make_act_step``):
    the CEM selector runs over every env slot (each slot its own full
    CEM loop, the ``make_batched_select_action`` megabatch shape),
    epsilon-exploration mixes in random actions per slot, and the
    vectorized environment (envs/) advances all B slots with auto-reset
    — collect-on-device, Anakin-style (arXiv:2104.06272). The actor
    acts under an atomically-swapped immutable ``(version, variables)``
    snapshot (the drain-free PR-7 serving pattern: a swap lands between
    acting steps, never inside one).
  * **Replay** — completed episodes flush as per-transition packed
    replay records (replay/wire.py) through a ``ReplayClient`` /
    ``LocalReplayClient``; timeouts are written with ``done=0``
    (bootstrap through the time limit), terminals with ``done=1`` —
    the grasping_sim convention, preserved end to end.
  * **Learner** — the Bellman trainer (rl/offpolicy.py) samples
    megabatches back via ``ReplayBatchIterator`` and steps CONCURRENTLY
    with the actor (its XLA dispatches release the GIL), publishing
    fresh ``(version, variables)`` snapshots on a cadence the actor
    polls — ``learner.swap`` drops one poll deterministically to prove
    the retry path.
  * **Observability** — a ``kind="rl"`` (``t2r.rl.v1``) record each
    report window (episodes/sec, per-scenario-bucket success,
    actor/learner step rates, swap versions — observability/
    rl_metrics.py), heartbeats, and the loop's own Watchdog +
    AutoProfiler: an ``actor.stall`` shows up as a step-time regression
    and claims exactly one budgeted capture while the learner keeps
    stepping (tests/test_rl_loop.py).

``bin/t2r_rl_loop`` is the entry point; ``bench.py`` publishes the
closed-loop axis (``RL_LOOP_BENCH_KEYS``); docs/rl_loop.md is the
operator contract.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.observability import TelemetryLogger, get_registry
from tensor2robot_tpu.observability import rl_metrics
from tensor2robot_tpu.observability.autoprofiler import AutoProfiler
from tensor2robot_tpu.observability.watchdog import Watchdog, WatchdogConfig
from tensor2robot_tpu.parallel import sharding as sharding_lib
from tensor2robot_tpu.reliability import fault_injection
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.replay import wire as replay_wire
from tensor2robot_tpu.replay.client import LocalReplayClient, ReplayClient
from tensor2robot_tpu.replay.feed import ReplayBatchIterator
from tensor2robot_tpu.replay.service import ReplayEmpty, ReplayService
from tensor2robot_tpu.research.qtopt.grasping_sim import CLOSE_INDEX
from tensor2robot_tpu.research.qtopt.t2r_models import (
    ACTION_DIM_LAYOUT,
    CEM_ACTION_SIZE,
)
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.utils import cross_entropy

__all__ = ['RLLoop', 'RLLoopConfig', 'ParamBus', 'make_cem_select_fn',
           'make_act_step', 'build_transition_record',
           'build_grasping_loop']

_STATUS_KEYS = ('gripper_closed', 'height_to_bottom')


@dataclasses.dataclass
class RLLoopConfig:
  """Knobs for one closed loop (docs/rl_loop.md).

  Attributes:
    cem_samples / cem_iters / num_elites: the per-slot CEM budget.
    explore_prob: per-slot probability an acting step takes a random
      action instead of the CEM argmax (epsilon exploration).
    explore_close_prob: close-gripper probability of a random action
      (the SimGraspingRandomPolicy balance).
    batch_size: learner megabatch size (also the replay sample size).
    num_candidates: K for the Bellman target max (rl/offpolicy.py).
    gamma: discount.
    target_update_steps: lagged-target export interval (learner steps).
    publish_every_steps: learner snapshot-publish cadence.
    swap_poll_steps: actor weight-poll cadence (acting steps).
    min_resident_examples: replay occupancy the learner waits for
      before its first step (collectors boot first).
    report_interval_s: cadence of ``t2r.rl.v1`` telemetry windows.
    auto_profile / profile_window_steps / max_captures: the loop's own
      budgeted capture loop (an armed ``actor.stall`` claims exactly
      one window).
    artifact_workload: when set, the acting step cold-starts through
      the unified ``CompiledArtifact`` store (tensor2robot_tpu/compile,
      docs/performance.md "Cold start") under this workload name: a
      warm start DESERIALIZES the persisted acting executable — the
      first acting step executes without an XLA compile — and a miss
      compiles once and persists for the next process. The key carries
      the lowered-program hash, so a changed env/CEM config is a miss,
      never a wrong load.
    artifact_cache_path: the store location (default: the process
      tuning cache's directory).
    seed: all loop-side randomness.
  """

  cem_samples: int = 16
  cem_iters: int = 2
  num_elites: int = 4
  explore_prob: float = 0.15
  explore_close_prob: float = 0.4
  batch_size: int = 16
  num_candidates: int = 16
  gamma: float = 0.8
  target_update_steps: int = 20
  publish_every_steps: int = 10
  swap_poll_steps: int = 4
  min_resident_examples: int = 32
  report_interval_s: float = 5.0
  auto_profile: bool = False
  profile_window_steps: int = 2
  max_captures: int = 1
  artifact_workload: Optional[str] = None
  artifact_cache_path: Optional[str] = None
  seed: int = 0


class ParamBus:
  """One-slot atomic ``(version, variables)`` hand-off, learner->actor.

  The PR-7 snapshot pattern: the pair is ONE immutable tuple assigned
  atomically, so a reader can never observe version N paired with
  version M's weights. The learner publishes a COPY of its params
  (the jitted Bellman step donates its state buffers — a snapshot
  aliasing them would be invalidated one step later).
  """

  def __init__(self):
    self._snapshot: Tuple[int, Optional[Any]] = (0, None)

  def publish(self, version: int, variables) -> None:
    self._snapshot = (int(version), variables)

  @property
  def snapshot(self) -> Tuple[int, Optional[Any]]:
    return self._snapshot

  @property
  def version(self) -> int:
    return self._snapshot[0]


def make_cem_select_fn(model, cem_samples: int = 16, cem_iters: int = 2,
                       num_elites: int = 4):
  """One-slot CEM action selector over any Grasping44-shaped critic.

  The generic twin of ``Grasping44...make_on_device_select_action``:
  works for every model sharing the flagship's spec keys (the sim
  critic included) — the image tower runs once per state, each CEM
  iteration scores ``cem_samples`` candidates through the megabatch
  contract, the whole loop is one traceable function.

  Returns ``select(variables, obs, rng) -> (action [8], q)`` with
  ``obs`` = {'image' uint8 [H, W, 3], 'gripper_closed',
  'height_to_bottom'} (the env observation contract).
  """

  def select(variables, obs, rng):
    variables = dict(variables)
    avg_params = variables.pop('avg_params', None)
    if getattr(model, 'use_avg_model_params', False) and \
        avg_params is not None:
      variables['params'] = avg_params
    placeholder = SpecStruct()
    placeholder['state/image'] = jnp.asarray(obs['image'])[None]
    for key, size in ACTION_DIM_LAYOUT:
      placeholder['action/' + key] = jnp.zeros((1, size), jnp.float32)
    for key in _STATUS_KEYS:
      placeholder['action/' + key] = jnp.asarray(
          obs[key], jnp.float32).reshape(1, 1)
    processed, _ = model.preprocessor.preprocess(
        placeholder, None, ModeKeys.PREDICT, rng=None)
    image = processed['state/image']

    def objective(samples):
      features = SpecStruct()
      features['state/image'] = image
      offset = 0
      for key, size in ACTION_DIM_LAYOUT:
        features['action/' + key] = samples[:, offset:offset + size]
        offset += size
      for key in _STATUS_KEYS:
        features['action/' + key] = jnp.broadcast_to(
            jnp.asarray(obs[key], jnp.float32).reshape(1, 1),
            (samples.shape[0], 1))
      outputs, _ = model.inference_network_fn(
          variables, features, None, ModeKeys.PREDICT, None)
      return outputs['q_predicted']

    _, _, best = cross_entropy.jax_normal_cem(
        objective, jnp.zeros((CEM_ACTION_SIZE,), jnp.float32),
        jnp.ones((CEM_ACTION_SIZE,), jnp.float32), rng,
        num_samples=cem_samples, num_elites=num_elites,
        num_iterations=cem_iters)
    return best, objective(best[None])[0]

  return select


def env_sharding(mesh, num_envs: int):
  """Where env slots live: sharded over the data axis when it is
  non-trivial and divides B (env slots spread across chips, the Anakin
  layout), replicated otherwise. On a trivial data axis GSPMD
  canonicalizes ``P('data')`` outputs to ``P()`` — pinning the carry to
  batch sharding there would guarantee a signature mismatch, so the
  single-device case stays replicated."""
  if mesh is None:
    return None
  data_size = mesh.shape.get('data', 1)
  if data_size > 1 and num_envs % data_size == 0:
    return sharding_lib.batch_sharding(mesh)
  return sharding_lib.replicated(mesh)


def make_act_step(model, env, cem_samples: int = 16, cem_iters: int = 2,
                  num_elites: int = 4, explore_prob: float = 0.0,
                  explore_close_prob: float = 0.4, out_sharding=None):
  """The fused acting program: select + explore + step B envs, one jit.

  ``act(variables, env_state, obs, rng) -> (env_state', obs',
  transition)`` where ``transition`` carries everything the replay
  writer needs per slot (acted-from obs fields, action, reward,
  terminal/done, pre-reset successor fields, elite q). One call = one
  XLA dispatch; the jit cache must stay at ONE executable after warmup
  (``recompiles/act_step``) — which is why ``out_sharding`` pins the
  carried (env_state, obs) outputs to the sharding the caller places
  fresh env buffers with: jit cache keys include input shardings, so
  the carry must leave each call exactly as it arrives.
  """
  select = make_cem_select_fn(model, cem_samples=cem_samples,
                              cem_iters=cem_iters, num_elites=num_elites)
  batched_select = jax.vmap(select, in_axes=(None, 0, 0))
  explore_prob = float(explore_prob)
  num_envs = env.num_envs

  def act(variables, env_state, obs, rng):
    rng = jnp.asarray(rng)
    r_select, r_explore, r_uniform, r_close = jax.random.split(rng, 4)
    keys = jax.random.split(r_select, num_envs)
    action, q = batched_select(variables, obs, keys)
    if explore_prob > 0.0:
      uniform = jax.random.uniform(
          r_uniform, (num_envs, CEM_ACTION_SIZE), jnp.float32,
          minval=-1.0, maxval=1.0)
      close = jax.random.bernoulli(
          r_close, explore_close_prob, (num_envs,)).astype(jnp.float32)
      uniform = uniform.at[:, CLOSE_INDEX].set(close)
      explore = jax.random.bernoulli(r_explore, explore_prob, (num_envs,))
      action = jnp.where(explore[:, None], uniform, action)
    result = env.step(env_state, action)
    state_out, obs_out = result.state, result.obs
    if out_sharding is not None:
      state_out, obs_out = jax.lax.with_sharding_constraint(
          (state_out, obs_out), out_sharding)
    next_obs = result.info['next_obs']
    transition = {
        'obs_image': obs['image'],
        'obs_height': obs['height_to_bottom'],
        'action': action,
        'q': q,
        'reward': result.reward,
        'done': result.done,
        'terminal': result.info['terminal'],
        'next_image': next_obs['image'],
        'next_height': next_obs['height_to_bottom'],
    }
    return state_out, obs_out, transition

  return jax.jit(act)


def build_transition_record(obs_image: np.ndarray,
                            obs_height: float,
                            action: np.ndarray,
                            reward: float,
                            terminal: bool,
                            next_image: np.ndarray,
                            next_height: float) -> Dict[str, np.ndarray]:
  """One flushed transition as a flat replay-record dict.

  Keys are ``features/<critic spec key>`` + the off-policy extras
  (``features/next/...``, ``features/done``) and ``labels/reward`` —
  exactly what ``ReplayBatchIterator`` hands back as the learner batch
  (rl/offpolicy.split_offpolicy_batch's key convention). ``done`` on
  the wire is the env-TERMINAL flag, not episode end: timeouts
  bootstrap through (grasping_sim module docstring).
  """
  action = np.asarray(action, np.float32).ravel()
  entries: Dict[str, np.ndarray] = {
      'features/state/image': np.ascontiguousarray(obs_image),
      'features/next/state/image': np.ascontiguousarray(next_image),
      'features/next/action/gripper_closed': np.zeros((1,), np.float32),
      'features/next/action/height_to_bottom': np.asarray(
          [next_height], np.float32),
      'features/done': np.asarray([1.0 if terminal else 0.0], np.float32),
      'labels/reward': np.asarray([reward], np.float32),
  }
  offset = 0
  for key, size in ACTION_DIM_LAYOUT:
    entries['features/action/' + key] = action[offset:offset + size]
    offset += size
  entries['features/action/gripper_closed'] = np.zeros((1,), np.float32)
  entries['features/action/height_to_bottom'] = np.asarray(
      [obs_height], np.float32)
  return entries


class RLLoop:
  """Actor + learner + swap + telemetry for one closed run.

  ``model``/``trainer``/``learner`` are the critic, its harness
  ``Trainer``, and a ``BellmanQTOptTrainer``; ``env`` a ``VecEnv``;
  ``client`` a replay client (the append AND sample side). The loop
  owns no jax state at construction beyond the jitted acting program —
  ``run()`` is the lifecycle.
  """

  def __init__(self,
               model,
               env,
               client,
               trainer,
               learner,
               model_dir: str,
               config: Optional[RLLoopConfig] = None,
               telemetry: Optional[TelemetryLogger] = None,
               registry=None,
               owned_service: Optional[ReplayService] = None):
    self.model = model
    self.env = env
    self.client = client
    self.trainer = trainer
    self.learner = learner
    self.model_dir = model_dir
    self.config = config or RLLoopConfig()
    self._registry = registry or get_registry()
    self._owns_telemetry = telemetry is None
    self.telemetry = telemetry or TelemetryLogger(model_dir)
    self._owned_service = owned_service
    cfg = self.config
    self._env_sharding = env_sharding(trainer.mesh, env.num_envs)
    self._act = make_act_step(
        model, env, cem_samples=cfg.cem_samples, cem_iters=cfg.cem_iters,
        num_elites=cfg.num_elites, explore_prob=cfg.explore_prob,
        explore_close_prob=cfg.explore_close_prob,
        out_sharding=self._env_sharding)
    self._act_loaded = None  # CompiledArtifact when artifact_workload set
    self._greedy_act = None  # built lazily by measure_success
    self.watchdog = Watchdog(WatchdogConfig(), registry=self._registry)
    self.profiler = AutoProfiler(
        model_dir, window_steps=cfg.profile_window_steps,
        max_captures=cfg.max_captures if cfg.auto_profile else 0,
        min_interval_secs=0.0, emit_reports=False,
        registry=self._registry)

    registry = self._registry
    self._episode_counters = registry.counter_family(
        rl_metrics.RL_EPISODES_COUNTER, ('bucket',))
    self._success_counters = registry.counter_family(
        rl_metrics.RL_SUCCESSES_COUNTER, ('bucket',))
    self._env_steps = registry.counter(rl_metrics.RL_ENV_STEPS_COUNTER)
    self._actor_steps = registry.counter(rl_metrics.RL_ACTOR_STEPS_COUNTER)
    self._learner_steps_counter = registry.counter(
        rl_metrics.RL_LEARNER_STEPS_COUNTER)
    self._transitions = registry.counter(rl_metrics.RL_TRANSITIONS_COUNTER)
    self._swap_counter = registry.counter(rl_metrics.RL_SWAPS_COUNTER)
    self._dropped_counter = registry.counter(
        rl_metrics.RL_DROPPED_SWAPS_COUNTER)
    self._actor_version_gauge = registry.gauge(
        rl_metrics.RL_ACTOR_VERSION_GAUGE)
    self._learner_version_gauge = registry.gauge(
        rl_metrics.RL_LEARNER_VERSION_GAUGE)
    self._act_ms = registry.histogram(rl_metrics.RL_ACT_MS_HISTOGRAM)
    self._act_cache_gauge = registry.gauge(rl_metrics.ACT_RECOMPILE_GAUGE)

    # Host-side run state (re-zeroed by _reset_run_state per run()).
    self._stop = threading.Event()
    self._report_lock = threading.Lock()
    self._reset_run_state()

  def _reset_run_state(self) -> None:
    """Fresh per-run bookkeeping: a second run() must not inherit the
    first run's totals, windows, or — critically — its actor version
    (a stale high version would make _poll_swap silently reject every
    new publish until the fresh count caught up). Registry counters
    are process-cumulative by design, so the run reads them as deltas
    against baselines captured here."""
    self._actor_version = 0
    self._actor_variables = None
    self._swaps = 0
    self._dropped_swaps = 0
    self._episodes = 0
    self._successes = 0
    self._learner_steps = 0
    self._bucket_episodes: Dict[int, int] = {}
    self._bucket_successes: Dict[int, int] = {}
    self._windows: List[Dict[str, Any]] = []
    self.bus = ParamBus()
    self._counter_base = {
        'env_steps': self._env_steps.value,
        'actor_steps': self._actor_steps.value,
        'transitions': self._transitions.value,
    }
    # Shared report marks (actor reporter + learner stand-in): when the
    # last rl window landed, and the learner steps it covered through.
    self._last_report_mark = time.perf_counter()
    self._learner_steps_at_report = 0
    self._learner_errors: List[BaseException] = []
    self._actor_done = threading.Event()
    self._learner_done = threading.Event()

  # -- learner side ----------------------------------------------------------

  def _init_batch(self):
    """A synthetic in-spec batch: init_state needs shapes before any
    replay exists (the actor must act before the first transition)."""
    batch = self.config.batch_size
    height, width = self.env.height, self.env.width
    features: Dict[str, np.ndarray] = {
        'state/image': np.zeros((batch, height, width, 3), np.uint8)}
    for key, size in ACTION_DIM_LAYOUT:
      features['action/' + key] = np.zeros((batch, size), np.float32)
    for key in _STATUS_KEYS:
      features['action/' + key] = np.zeros((batch, 1), np.float32)
    labels = SpecStruct(reward=np.zeros((batch, 1), np.float32))
    return SpecStruct(**features), labels

  def _snapshot_variables(self, state):
    """An immutable on-device COPY of the serving variables (ParamBus)."""
    variables = {'params': state.params}
    if state.model_state:
      variables.update(state.model_state)
    return jax.tree.map(jnp.copy, variables)

  def _learner_loop(self, state, deadline: Optional[float],
                    max_learner_steps: Optional[int],
                    errors: List[BaseException]) -> None:
    cfg = self.config
    try:
      # Wait for the collectors: the actor is filling the store RIGHT
      # NOW, so poll occupancy instead of failing the first sample.
      # At least min_resident_examples AND at least one full batch —
      # a large knob must actually delay the first step (training on a
      # near-empty buffer is the failure mode the knob exists to avoid).
      resident_floor = max(cfg.min_resident_examples, cfg.batch_size, 1)
      while not self._stop.is_set():
        occupancy = self.client.stats().get('occupancy_examples', 0)
        if occupancy >= resident_floor:
          break
        if deadline is not None and time.perf_counter() >= deadline:
          return
        time.sleep(0.02)
      iterator = ReplayBatchIterator(self.client, cfg.batch_size,
                                     wait_timeout_s=60.0)
      rng = jax.random.PRNGKey(cfg.seed + 1)
      while not self._stop.is_set():
        if deadline is not None and time.perf_counter() >= deadline:
          break
        if max_learner_steps is not None and \
            self._learner_steps >= max_learner_steps:
          break
        try:
          features, labels = next(iterator)
        except ReplayEmpty:
          time.sleep(0.05)
          continue
        host_batch = {
            'features': {key: features[key] for key in features},
            'labels': {key: labels[key] for key in labels},
        }
        state, _ = self.learner.train_step(state, host_batch, rng)
        self._learner_steps += 1
        self._learner_steps_counter.inc()
        if self._learner_steps % cfg.publish_every_steps == 0:
          version = self.bus.version + 1
          self.bus.publish(version, self._snapshot_variables(state))
          self._learner_version_gauge.set(float(version))
        # Actor gone quiet? Keep the rl window stream (and heartbeat)
        # alive from this side so a wedged actor is a NAMED doctor
        # CRITICAL, not an anonymous stale heartbeat.
        self._learner_standin_report()
      # Final publish so a short run still hands the actor its last
      # learned weights (and the swap acceptance test converges).
      version = self.bus.version + 1
      self.bus.publish(version, self._snapshot_variables(state))
      self._learner_version_gauge.set(float(version))
    except BaseException as e:  # noqa: BLE001 — surfaced after join
      errors.append(e)
    finally:
      self._learner_done.set()
      self._check_targets()

  # -- actor side ------------------------------------------------------------

  def _place_env(self, env_state, obs):
    """Commits fresh env buffers to the acting carry's pinned sharding.

    jit cache keys include input shardings: the acting program pins its
    (env_state, obs) outputs to ``env_sharding(...)`` and the reset
    buffers must arrive committed to the SAME placement, or the first
    steady-state call compiles a second executable
    (``recompiles/act_step`` must stay at 1).
    """
    if self._env_sharding is None:
      return env_state, obs
    return jax.device_put((env_state, obs), self._env_sharding)

  def _poll_swap(self) -> None:
    version, variables = self.bus.snapshot
    if variables is None or version <= self._actor_version:
      return
    if fault_injection.fires(fault_injection.SITE_LEARNER_SWAP):
      # A dropped poll: the snapshot stays on the bus, the NEXT poll
      # adopts it — at-least-once, not exactly-once.
      self._dropped_swaps += 1
      self._dropped_counter.inc()
      return
    self._actor_variables = variables
    self._actor_version = version
    self._swaps += 1
    self._swap_counter.inc()
    self._actor_version_gauge.set(float(version))

  def _flush_slot(self, transition, slot: int,
                  buffers: List[List[Dict[str, np.ndarray]]]) -> None:
    buffers[slot].append(build_transition_record(
        obs_image=transition['obs_image'][slot],
        obs_height=float(transition['obs_height'][slot]),
        action=transition['action'][slot],
        reward=float(transition['reward'][slot]),
        terminal=bool(transition['terminal'][slot]),
        next_image=transition['next_image'][slot],
        next_height=float(transition['next_height'][slot])))
    if not bool(transition['done'][slot]):
      return
    # Episode complete: flush its transitions, book the outcome.
    for record in buffers[slot]:
      self.client.append(replay_wire.encode_example(record))
    self._transitions.inc(len(buffers[slot]))
    buffers[slot].clear()
    bucket = int(self.env.buckets[slot])
    success = bool(transition['terminal'][slot]) and \
        float(transition['reward'][slot]) > 0.5
    self._episodes += 1
    self._bucket_episodes[bucket] = \
        self._bucket_episodes.get(bucket, 0) + 1
    self._episode_counters.series(str(bucket)).inc()
    if success:
      self._successes += 1
      self._bucket_successes[bucket] = \
          self._bucket_successes.get(bucket, 0) + 1
      self._success_counters.series(str(bucket)).inc()

  def _bind_act_artifact(self, env_state, obs, base_rng) -> None:
    """Acting-step cold start through the CompiledArtifact store.

    Called once per process, right after the env buffers are committed
    to the carry's pinned sharding — the example args ARE the
    steady-state call's (variables, env_state, obs, rng), so the loaded
    executable serves every acting step. Best-effort: any store failure
    degrades to the stock jit path (one compile at the first call).
    """
    try:
      from tensor2robot_tpu.compile import artifact as artifact_lib

      self._act_loaded = artifact_lib.load_or_compile(
          self.config.artifact_workload, self._act,
          (self._actor_variables, env_state, obs,
           jax.random.fold_in(base_rng, 0)),
          cache_path=self.config.artifact_cache_path,
          telemetry=self.telemetry, program_key=True)
      log_warning('Acting step bound from CompiledArtifact store: %s '
                  '(%s).', self.config.artifact_workload,
                  'deserialized' if self._act_loaded.from_cache
                  else 'compiled + persisted')
    except Exception as e:  # noqa: BLE001 — never kill the loop
      log_warning('Acting-step artifact bind failed (%s); using the '
                  'stock jit path.', e)
      self._act_loaded = None

  def _sample_act_cache(self) -> float:
    if self._act_loaded is not None:
      # AOT path: exactly one executable exists by construction and the
      # jit cache stays empty — report the healthy 1 (same convention
      # as Trainer._sample_recompiles).
      self._act_cache_gauge.set(1.0)
      return 1.0
    try:
      size = float(self._act._cache_size())  # noqa: SLF001 — same probe
      # as Trainer._sample_recompiles; absent on some jax versions.
    except Exception:  # noqa: BLE001
      return self._act_cache_gauge.value
    self._act_cache_gauge.set(size)
    return size

  def _make_record(self, window_s: float, actor_steps: int,
                   episodes: int, successes: int, transitions: int,
                   act_seconds: float, learner_steps: int,
                   act_jit_cache: float, buckets,
                   reporter: str) -> Dict[str, Any]:
    """ONE t2r.rl.v1 record builder for both reporters — the actor's
    window reports and the learner's stand-ins must stay field-for-
    field identical or the jax-free readers see schema drift."""
    num_envs = self.env.num_envs
    window_s = max(window_s, 1e-9)
    record = {
        'schema': rl_metrics.RL_RECORD_SCHEMA,
        'window_seconds': round(window_s, 3),
        'num_envs': num_envs,
        'actor_steps': int(actor_steps),
        'actor_steps_per_sec': round(actor_steps / window_s, 2),
        'env_steps': int(actor_steps * num_envs),
        'env_steps_per_sec': round(actor_steps * num_envs / window_s, 2),
        'episodes': int(episodes),
        'episodes_per_sec': round(episodes / window_s, 2),
        'success_rate': round(successes / episodes, 4) if episodes else 0.0,
        'success_rate_cumulative': round(
            self._successes / self._episodes, 4) if self._episodes else 0.0,
        'transitions': int(transitions),
        'learner_steps': int(learner_steps),
        'learner_steps_per_sec': round(learner_steps / window_s, 2),
        'actor_version': int(self._actor_version),
        'learner_version': int(self.bus.version),
        'swaps': int(self._swaps),
        'dropped_swaps': int(self._dropped_swaps),
        'act_step_ms': round(act_seconds / actor_steps * 1e3, 3)
                       if actor_steps else 0.0,
        'act_jit_cache': act_jit_cache,
        'buckets': buckets,
        'reporter': reporter,
        # Completion flags, so the doctor can tell a side that FINISHED
        # its configured target (healthy, by design) from one that
        # stalled — zero steps from a finished side must not page.
        'actor_done': self._actor_done.is_set(),
        'learner_done': self._learner_done.is_set(),
    }
    spread = rl_metrics.scenario_success_spread(buckets)
    if spread is not None:
      record['scenario_success_spread'] = round(spread, 4)
    return record

  def _covered_learner_steps(self) -> int:
    """Learner steps since the LAST report of either reporter (shared
    mark — per-reporter baselines would double-count a stand-in's
    steps into the recovering actor's next window)."""
    steps = self._learner_steps - self._learner_steps_at_report
    self._learner_steps_at_report = self._learner_steps
    self._last_report_mark = time.perf_counter()
    return steps

  def _report_window(self, step_i: int, window: Dict[str, Any],
                     window_s: float) -> Dict[str, Any]:
    with self._report_lock:
      learner_steps = self._covered_learner_steps()
    buckets = rl_metrics.bucket_table(
        self._bucket_episodes, self._bucket_successes,
        window_episodes=window['bucket_episodes'])
    record = self._make_record(
        window_s, window['actor_steps'], window['episodes'],
        window['successes'], window['transitions'],
        window['act_seconds'], learner_steps,
        self._sample_act_cache(), buckets, reporter='actor')
    self.telemetry.log(rl_metrics.RL_RECORD_KIND, step=step_i, **record)
    # The loop's own symptom->capture path: the acting step time is the
    # actor's "step time"; an armed actor.stall inflates one window and
    # must claim exactly one budgeted capture while the learner keeps
    # stepping (docs/rl_loop.md).
    step_time_s = (window['act_seconds'] / window['actor_steps']
                   if window['actor_steps'] else None)
    for anomaly in self.watchdog.observe(step_i, step_time_s):
      log_warning('RL watchdog anomaly: %s', anomaly.message)
      self.telemetry.log('anomaly', step=step_i, anomaly=anomaly.kind,
                         message=anomaly.message, detail=anomaly.detail)
      self.profiler.request_capture(anomaly.kind, step_i, anomaly.detail)
    self.telemetry.heartbeat(step_i)
    self.telemetry.flush()
    self._windows.append(record)
    return record

  def _learner_standin_report(self) -> None:
    """A learner-side ``kind="rl"`` window when the actor has gone
    quiet for several report intervals.

    The actor thread owns the report cadence; an actor that stops
    stepping — wedged, or legitimately finished while the learner runs
    to its own target — would otherwise emit no windows and no
    heartbeats at all, so a live actor stall would degrade to an
    anonymous heartbeat_stale and a healthy learner tail would page the
    same way. The stand-in carries zero actor/episode activity by
    construction (the actor is the only episode bookkeeper), the
    learner's step delta since the last window (whoever wrote it), and
    the completion flags the doctor uses to tell 'finished' from
    'stalled'.
    """
    cfg = self.config
    with self._report_lock:
      now = time.perf_counter()
      window_s = now - self._last_report_mark
      if window_s < 3 * cfg.report_interval_s:
        return  # the actor reported recently (or another stand-in did)
      learner_steps = self._covered_learner_steps()
    step_i = int(self._actor_steps.value
                 - self._counter_base['actor_steps'])
    buckets = rl_metrics.bucket_table(self._bucket_episodes,
                                      self._bucket_successes)
    record = self._make_record(
        window_s, 0, 0, 0, 0, 0.0, learner_steps,
        self._act_cache_gauge.value, buckets, reporter='learner')
    self.telemetry.log(rl_metrics.RL_RECORD_KIND, step=step_i, **record)
    self.telemetry.heartbeat(step_i)
    self.telemetry.flush()
    self._windows.append(record)

  def _actor_loop(self, deadline: Optional[float],
                  max_episodes: Optional[int]) -> None:
    cfg = self.config
    base_rng = jax.random.PRNGKey(cfg.seed)
    env_state, obs = self._place_env(
        *self.env.reset(jax.random.fold_in(base_rng, 2**16)))
    if cfg.artifact_workload and self._act_loaded is None:
      self._bind_act_artifact(env_state, obs, base_rng)
    act_fn = (self._act_loaded.executable
              if self._act_loaded is not None else self._act)
    buffers: List[List[Dict[str, np.ndarray]]] = [
        [] for _ in range(self.env.num_envs)]
    step_i = 0
    window = self._fresh_window()
    window_start = time.perf_counter()
    try:
      while not self._stop.is_set():
        if deadline is not None and time.perf_counter() >= deadline:
          break
        if max_episodes is not None and self._episodes >= max_episodes:
          break
        if self._learner_errors:
          # Fail fast: a dead learner means nobody learns from these
          # episodes — collecting for the rest of a deadline-only run
          # and surfacing the error only at join would waste it all.
          break
        report_path = self.profiler.maybe_profile(step_i)
        if report_path is not None:
          self.telemetry.log('forensics', step=step_i, report=report_path)
          self.telemetry.flush()
        if step_i % cfg.swap_poll_steps == 0:
          self._poll_swap()
        stall_s = fault_injection.actor_stall_seconds()
        if stall_s > 0.0:
          time.sleep(stall_s)
        t0 = time.perf_counter()
        env_state, obs, transition = act_fn(
            self._actor_variables, env_state, obs,
            jax.random.fold_in(base_rng, step_i))
        fetched = jax.device_get(transition)
        act_s = time.perf_counter() - t0 + stall_s
        self._act_ms.record(act_s * 1e3)
        step_i += 1
        self._actor_steps.inc()
        self._env_steps.inc(self.env.num_envs)
        window['actor_steps'] += 1
        window['act_seconds'] += act_s
        episodes_before = self._episodes
        successes_before = self._successes
        transitions_before = self._transitions.value
        for slot in np.flatnonzero(np.asarray(fetched['done'])):
          bucket = int(self.env.buckets[int(slot)])
          window['bucket_episodes'][bucket] = \
              window['bucket_episodes'].get(bucket, 0) + 1
        for slot in range(self.env.num_envs):
          self._flush_slot(fetched, slot, buffers)
        window['episodes'] += self._episodes - episodes_before
        window['successes'] += self._successes - successes_before
        window['transitions'] += \
            self._transitions.value - transitions_before
        self._check_targets()
        now = time.perf_counter()
        if now - window_start >= cfg.report_interval_s:
          self._report_window(step_i, window, now - window_start)
          window = self._fresh_window()
          window_start = now
    finally:
      now = time.perf_counter()
      if window['actor_steps']:
        self._report_window(step_i, window, max(now - window_start, 1e-9))
      self.profiler.finish(step_i)
      self._actor_done.set()
      self._check_targets()

  def _fresh_window(self) -> Dict[str, Any]:
    return {'actor_steps': 0, 'act_seconds': 0.0, 'episodes': 0,
            'successes': 0, 'transitions': 0, 'bucket_episodes': {}}

  # -- lifecycle -------------------------------------------------------------

  def _check_targets(self) -> None:
    """Sets the shared stop flag once every SPECIFIED target is met.

    A deadline-only run (no episode/step targets) never stops early —
    both sides run to the deadline. With both targets set, whichever
    side finishes first keeps the other running until its own target.
    """
    max_episodes = self._targets['max_episodes']
    max_learner_steps = self._targets['max_learner_steps']
    if max_episodes is None and max_learner_steps is None:
      return
    episodes_done = (max_episodes is None
                     or self._episodes >= max_episodes
                     or self._actor_done.is_set())
    learner_done = (max_learner_steps is None
                    or self._learner_steps >= max_learner_steps
                    or self._learner_done.is_set())
    if episodes_done and learner_done:
      self._stop.set()

  def run(self,
          max_seconds: Optional[float] = None,
          max_episodes: Optional[int] = None,
          max_learner_steps: Optional[int] = None) -> Dict[str, Any]:
    """Runs the closed loop until every configured target is met (or
    the deadline passes); returns the run summary.

    At least one bound must be given. The actor runs in THIS thread
    (it owns the telemetry/watchdog cadence); the learner runs in a
    daemon thread whose exceptions re-raise here after join.
    """
    if max_seconds is None and max_episodes is None and \
        max_learner_steps is None:
      raise ValueError('give at least one of max_seconds / max_episodes /'
                       ' max_learner_steps')
    cfg = self.config
    self._stop.clear()
    self._reset_run_state()
    self._targets = {'max_episodes': max_episodes,
                     'max_learner_steps': max_learner_steps}
    start = time.perf_counter()
    deadline = None if max_seconds is None else start + max_seconds

    state = self.trainer.init_state(*self._init_batch())
    self.bus.publish(1, self._snapshot_variables(state))
    self._learner_version_gauge.set(1.0)
    # Bootstrap adoption is direct: v1 (init weights) is the loop's
    # starting point, not a hot swap — it neither counts in ``swaps``
    # nor passes the learner.swap drop site (the actor must never act
    # from nothing).
    self._actor_version, self._actor_variables = self.bus.snapshot
    self._actor_version_gauge.set(float(self._actor_version))
    self.telemetry.log(
        'rl_start', num_envs=self.env.num_envs,
        episode_length=self.env.episode_length,
        num_buckets=getattr(self.env, 'num_buckets', 1),
        config={'cem_samples': cfg.cem_samples,
                'cem_iters': cfg.cem_iters,
                'batch_size': cfg.batch_size,
                'explore_prob': cfg.explore_prob,
                'swap_poll_steps': cfg.swap_poll_steps,
                'publish_every_steps': cfg.publish_every_steps})
    self.telemetry.flush()

    self._learner_errors = []
    learner_thread = threading.Thread(
        target=self._learner_loop,
        args=(state, deadline, max_learner_steps, self._learner_errors),
        name='t2r-rl-learner', daemon=True)
    learner_thread.start()
    try:
      self._actor_loop(deadline, max_episodes)
    except BaseException:
      self._stop.set()
      raise
    finally:
      # The learner keeps running toward ITS target after the actor
      # finishes (both-targets runs); only deadline/targets stop it.
      learner_thread.join(timeout=300.0)
      self._stop.set()
    if self._learner_errors:
      raise self._learner_errors[0]
    if learner_thread.is_alive():
      raise RuntimeError('learner thread failed to stop')

    elapsed = max(time.perf_counter() - start, 1e-9)
    buckets = rl_metrics.bucket_table(self._bucket_episodes,
                                      self._bucket_successes)
    env_steps = self._env_steps.value - self._counter_base['env_steps']
    actor_steps = (self._actor_steps.value
                   - self._counter_base['actor_steps'])
    transitions = (self._transitions.value
                   - self._counter_base['transitions'])
    summary = {
        'seconds': round(elapsed, 3),
        'num_envs': self.env.num_envs,
        'episodes': self._episodes,
        'successes': self._successes,
        'success_rate': round(self._successes / self._episodes, 4)
                        if self._episodes else 0.0,
        'episodes_per_sec': round(self._episodes / elapsed, 3),
        'env_steps': int(env_steps),
        'env_steps_per_sec': round(env_steps / elapsed, 2),
        'actor_steps': int(actor_steps),
        'learner_steps': self._learner_steps,
        'transitions': int(transitions),
        'swaps': self._swaps,
        'dropped_swaps': self._dropped_swaps,
        'actor_version': self._actor_version,
        'learner_version': self.bus.version,
        'act_jit_cache': self._sample_act_cache(),
        'buckets': buckets,
        'windows': list(self._windows),
    }
    spread = rl_metrics.scenario_success_spread(buckets)
    if spread is not None:
      summary['scenario_success_spread'] = round(spread, 4)
    self.telemetry.log('rl_stop', **{
        key: summary[key] for key in
        ('episodes', 'success_rate', 'learner_steps', 'swaps',
         'dropped_swaps', 'actor_version')})
    self.telemetry.flush()
    return summary

  def measure_success(self, variables=None, episodes: int = 32,
                      seed: int = 1234, max_steps: int = 1000) -> float:
    """Greedy (no-exploration) success rate over fresh episodes.

    Probes a snapshot OUTSIDE the training loop — the before/after
    criterion the loop test uses ("success measurably rises"). Uses a
    separate jitted program (explore_prob=0), leaving the acting-path
    jit cache untouched.
    """
    if variables is None:
      variables = self._actor_variables
      if variables is None:
        raise ValueError('no variables: run() first or pass variables')
    if self._greedy_act is None:
      cfg = self.config
      self._greedy_act = make_act_step(
          self.model, self.env, cem_samples=cfg.cem_samples,
          cem_iters=cfg.cem_iters, num_elites=cfg.num_elites,
          explore_prob=0.0, out_sharding=self._env_sharding)
    rng = jax.random.PRNGKey(seed)
    env_state, obs = self._place_env(
        *self.env.reset(jax.random.fold_in(rng, 1)))
    done_episodes = 0
    wins = 0
    for step in range(max_steps):
      env_state, obs, transition = self._greedy_act(
          variables, env_state, obs, jax.random.fold_in(rng, 2 + step))
      fetched = jax.device_get({key: transition[key]
                                for key in ('reward', 'done', 'terminal')})
      done = np.asarray(fetched['done'])
      wins += int(((np.asarray(fetched['reward']) > 0.5)
                   & np.asarray(fetched['terminal'])).sum())
      done_episodes += int(done.sum())
      if done_episodes >= episodes:
        break
    return wins / max(done_episodes, 1)

  def close(self) -> None:
    self.trainer.close()
    if self._owns_telemetry:
      self.telemetry.close()
    if self._owned_service is not None:
      self._owned_service.close()


def build_grasping_loop(model_dir: str,
                        num_envs: int = 16,
                        height: int = 48,
                        width: int = 64,
                        episode_length: int = 3,
                        scenario_config=None,
                        replay=None,
                        config: Optional[RLLoopConfig] = None,
                        num_shards: int = 2,
                        mesh=None,
                        seed: int = 0) -> RLLoop:
  """Wires the whole closed loop over the sim grasping MDP.

  ``replay``: None (an in-process ReplayService is created and owned by
  the loop), a ``host:port``/URL endpoint string, a ReplayService, or
  any client-API object. The critic is the test-scale sim critic at the
  env resolution with the adam recipe the off-policy bench uses; the
  env randomizes scenarios per slot unless ``scenario_config`` pins
  them.
  """
  import optax

  from tensor2robot_tpu.envs import ScenarioConfig, VecGraspingEnv
  from tensor2robot_tpu.replay.service import ReplayConfig
  from tensor2robot_tpu.research.qtopt import grasping_sim
  from tensor2robot_tpu.rl.offpolicy import BellmanQTOptTrainer
  from tensor2robot_tpu.trainer import Trainer

  config = config or RLLoopConfig(seed=seed)
  if scenario_config is None:
    scenario_config = ScenarioConfig.randomized()
  env = VecGraspingEnv(num_envs, height=height, width=width,
                       episode_length=episode_length,
                       scenario_config=scenario_config, seed=seed)
  owned_service = None
  if replay is None:
    owned_service = ReplayService(ReplayConfig(
        num_shards=num_shards, batch_size=config.batch_size,
        seed=seed))
    client = LocalReplayClient(owned_service)
  elif isinstance(replay, str):
    client = ReplayClient(replay)
  elif isinstance(replay, ReplayService):
    client = LocalReplayClient(replay)
  else:
    client = replay
  model = grasping_sim.make_sim_critic_model(
      height, width, create_optimizer_fn=lambda: optax.adam(3e-3))
  trainer = Trainer(model, model_dir, mesh=mesh, async_checkpoints=False,
                    save_checkpoints_steps=10**9,
                    log_every_n_steps=10**9, auto_profile=False,
                    enable_watchdog=False, enable_pipeline_xray=False,
                    write_metrics=False)
  learner = BellmanQTOptTrainer(
      model, trainer,
      grasping_sim.make_candidate_actions_fn(config.num_candidates),
      num_candidates=config.num_candidates, gamma=config.gamma,
      target_update_steps=config.target_update_steps)
  return RLLoop(model, env, client, trainer, learner, model_dir,
                config=config, owned_service=owned_service)
