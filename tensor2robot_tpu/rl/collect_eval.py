"""Continuous collect/eval loop: the robot-side half of distributed RL.

Parity target: /root/reference/utils/continuous_collect_eval.py:32-113.
Polls the policy's predictor for new weights (exported by the trainer's
hooks), runs collect + eval episodes, and writes replay TFRecords — the
filesystem actor↔learner transport of SURVEY.md §2.9.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable, Optional

from tensor2robot_tpu.rl import run_env as run_env_lib

_POLL_SLEEP_SECS = 10


def collect_eval_loop(collect_env,
                      eval_env,
                      policy_class: Callable,
                      num_collect: int = 2000,
                      num_eval: int = 100,
                      run_agent_fn: Optional[Callable] = None,
                      root_dir: str = '',
                      continuous: bool = False,
                      min_collect_eval_step: int = 0,
                      max_steps: int = 1,
                      pre_collect_eval_fn: Optional[Callable] = None,
                      record_eval_env_video: bool = False,
                      init_with_random_variables: bool = False,
                      poll_sleep_secs: float = _POLL_SLEEP_SECS,
                      max_poll_attempts: Optional[int] = None) -> None:
  """Collect/eval a policy against live envs (ref collect_eval_loop :32).

  Args:
    collect_env: env to collect training data from (None disables collect).
    eval_env: env to evaluate on (None disables eval).
    policy_class: zero-arg factory for the policy.
    num_collect: collect episodes per policy version.
    num_eval: eval episodes per policy version.
    run_agent_fn: override for run_env.run_env.
    root_dir: base dir; run_env writes policy_collect/ and policy_eval/
      under it (the reference passes root_dir straight through,
      ref continuous_collect_eval.py:100-107).
    continuous: keep polling for newer policies until step > max_steps.
    min_collect_eval_step: skip policy versions below this step.
    max_steps: stop once the policy's step exceeds this (continuous mode).
    pre_collect_eval_fn: runs once before the loop (e.g. replay seeding).
    record_eval_env_video: route env video output per policy version.
    init_with_random_variables: random-init instead of restore (tests).
    poll_sleep_secs / max_poll_attempts: waiting knobs (the reference
      hardcodes 10s sleeps and polls forever; tests need bounds).
  """
  if pre_collect_eval_fn:
    pre_collect_eval_fn()
  owns_envs = run_agent_fn is None
  if owns_envs:
    # The default run_env closes its env after every call (close_env=True),
    # which would hand continuous-mode iteration 2 a closed env; keep envs
    # open across versions and close them once on exit.
    run_agent_fn = functools.partial(run_env_lib.run_env, close_env=False)

  try:
    _collect_eval(collect_env, eval_env, policy_class, num_collect, num_eval,
                  run_agent_fn, root_dir, continuous, min_collect_eval_step,
                  max_steps, record_eval_env_video,
                  init_with_random_variables, poll_sleep_secs,
                  max_poll_attempts)
  finally:
    if owns_envs:
      for env in (collect_env, eval_env):
        if env is not None and hasattr(env, 'close'):
          env.close()


def _collect_eval(collect_env, eval_env, policy_class, num_collect, num_eval,
                  run_agent_fn, root_dir, continuous, min_collect_eval_step,
                  max_steps, record_eval_env_video,
                  init_with_random_variables, poll_sleep_secs,
                  max_poll_attempts) -> None:
  policy = policy_class()
  prev_global_step = -1
  attempts = 0
  while True:
    restored = True
    if init_with_random_variables:
      policy.init_randomly()
    else:
      restored = policy.restore()
    global_step = policy.global_step

    # restored is False when the predictor timed out with nothing to load —
    # running episodes would hit an unloaded predictor, so keep polling.
    if (restored is False or global_step is None
        or global_step < min_collect_eval_step
        or global_step <= prev_global_step):
      attempts += 1
      if max_poll_attempts is not None and attempts >= max_poll_attempts:
        return
      time.sleep(poll_sleep_secs)
      continue
    attempts = 0

    if collect_env:
      run_agent_fn(collect_env, policy=policy, num_episodes=num_collect,
                   root_dir=root_dir, global_step=global_step,
                   tag='collect')
    if eval_env:
      if record_eval_env_video and hasattr(eval_env, 'set_video_output_dir'):
        eval_env.set_video_output_dir(
            os.path.join(root_dir, 'videos', str(global_step)))
      run_agent_fn(eval_env, policy=policy, num_episodes=num_eval,
                   root_dir=root_dir, global_step=global_step, tag='eval')
    if not continuous or global_step >= max_steps:
      return

    prev_global_step = global_step
