"""RL: collect/eval loops, off-policy Bellman training, and the closed
device-resident actor<->learner loop (rl/loop.py, docs/rl_loop.md).

The ``t2r.rl.v1`` telemetry vocabulary lives jax-free in
``observability/rl_metrics.py`` (this package imports jax at init)."""

from tensor2robot_tpu.rl.run_env import run_env
from tensor2robot_tpu.rl.collect_eval import collect_eval_loop
from tensor2robot_tpu.rl.offpolicy import (
    BellmanQTOptTrainer,
    concat_ranking_pairs,
    pairwise_ranking_accuracy,
    ranking_accuracy_from_scores,
)
from tensor2robot_tpu.rl.loop import (
    RLLoop,
    RLLoopConfig,
    build_grasping_loop,
)

__all__ = ['collect_eval_loop', 'run_env', 'BellmanQTOptTrainer',
           'concat_ranking_pairs', 'pairwise_ranking_accuracy',
           'ranking_accuracy_from_scores', 'RLLoop', 'RLLoopConfig',
           'build_grasping_loop']
