"""RL collect/eval: run policies in environments, write replay TFRecords."""

from tensor2robot_tpu.rl.run_env import run_env
from tensor2robot_tpu.rl.collect_eval import collect_eval_loop
from tensor2robot_tpu.rl.offpolicy import (
    BellmanQTOptTrainer,
    concat_ranking_pairs,
    pairwise_ranking_accuracy,
    ranking_accuracy_from_scores,
)

__all__ = ['collect_eval_loop', 'run_env', 'BellmanQTOptTrainer',
           'concat_ranking_pairs', 'pairwise_ranking_accuracy',
           'ranking_accuracy_from_scores']
