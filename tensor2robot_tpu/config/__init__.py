"""Configuration system: gin-file-compatible bindings for the framework.

Use like gin (ref /root/reference/bin/run_t2r_trainer.py:33):

    from tensor2robot_tpu import config
    config.register_framework_configurables()
    config.parse_config_files_and_bindings(['train_qtopt.gin'], bindings)
    train_eval_model = config.get_configurable('train_eval_model')
    results = train_eval_model(model_dir='/tmp/run')
"""

from tensor2robot_tpu.config.ginlike import (
    ConfigError,
    ConfigurableReference,
    add_config_file_search_path,
    clear_config,
    config_str,
    configurable,
    external_configurable,
    get_configurable,
    operative_config_str,
    parse_config,
    parse_config_files_and_bindings,
    query_parameter,
)


def register_framework_configurables() -> None:
  """Registers the public framework + workload API (idempotent).

  The reference decorates everything with @gin.configurable in-source;
  here registration is centralized so library modules stay import-light.
  """
  from tensor2robot_tpu.config import registry
  registry.register_all()


__all__ = [
    'ConfigError',
    'ConfigurableReference',
    'add_config_file_search_path',
    'clear_config',
    'config_str',
    'configurable',
    'external_configurable',
    'get_configurable',
    'operative_config_str',
    'parse_config',
    'parse_config_files_and_bindings',
    'query_parameter',
    'register_framework_configurables',
]
