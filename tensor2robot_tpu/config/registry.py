"""Registers the framework's public API as configurables.

The reference sprinkles @gin.configurable across every module
(ref models/abstract_model.py:70-85, utils/train_eval.py:61); here the
whole registration surface lives in one place so the config system stays
optional and library modules import nothing from it.
"""

from __future__ import annotations

import threading

from tensor2robot_tpu.config import ginlike

_REGISTERED = False
_LOCK = threading.Lock()


def register_all() -> None:
  global _REGISTERED
  with _LOCK:
    if _REGISTERED:
      return
    _REGISTERED = True

  from tensor2robot_tpu import parallel
  from tensor2robot_tpu.data import input_generators
  from tensor2robot_tpu.export import exporters as exporters_lib
  from tensor2robot_tpu.export import export_generators
  from tensor2robot_tpu.hooks import async_export_hook_builder
  from tensor2robot_tpu.hooks import td3
  from tensor2robot_tpu.hooks import variable_logger_hook
  from tensor2robot_tpu.meta_learning import maml_inner_loop
  from tensor2robot_tpu.meta_learning import maml_model
  from tensor2robot_tpu.meta_learning import meta_data
  from tensor2robot_tpu.meta_learning import preprocessors as meta_preproc
  from tensor2robot_tpu.models import optimizers
  from tensor2robot_tpu.policies import policies
  from tensor2robot_tpu.research.grasp2vec import grasp2vec_model
  from tensor2robot_tpu.research.grasp2vec import losses as g2v_losses
  from tensor2robot_tpu.research.pose_env import pose_env
  from tensor2robot_tpu.research.pose_env import pose_env_maml_models
  from tensor2robot_tpu.research.pose_env import pose_env_models
  from tensor2robot_tpu.research.qtopt import networks as qtopt_networks
  from tensor2robot_tpu.research.qtopt import optimizer_builder
  from tensor2robot_tpu.research.qtopt import t2r_models as qtopt_models
  from tensor2robot_tpu.research.vrgripper import decoders
  from tensor2robot_tpu.research.vrgripper import vrgripper_env_models
  from tensor2robot_tpu.research.vrgripper import vrgripper_env_meta_models
  from tensor2robot_tpu.research.vrgripper import vrgripper_env_wtl_models
  import importlib

  from tensor2robot_tpu.rl import collect_eval
  # rl/__init__ rebinds the name 'run_env' to the function, which shadows
  # the submodule for attribute-style imports; go through importlib.
  run_env_module = importlib.import_module('tensor2robot_tpu.rl.run_env')
  from tensor2robot_tpu.trainer import train_eval

  register = ginlike.external_configurable

  # Trainer / harness (ref utils/train_eval.py:61).
  register(train_eval.train_eval_model, 'train_eval_model')
  register(train_eval.Trainer, 'Trainer')
  register(parallel.create_mesh, 'create_mesh')
  register(exporters_lib.create_default_exporters,
           'create_default_exporters')
  register(export_generators.DefaultExportGenerator,
           'DefaultExportGenerator')
  from tensor2robot_tpu.export import tf_savedmodel
  register(tf_savedmodel.TFSavedModelExportGenerator,
           'TFSavedModelExportGenerator')
  register(async_export_hook_builder.AsyncExportHookBuilder,
           'AsyncExportHookBuilder')
  register(td3.TD3Hooks, 'TD3Hooks')
  register(variable_logger_hook.VariableLoggerHook, 'VariableLoggerHook')

  # Reliability layer (docs/reliability.md): arm deterministic faults and
  # tune retry backoff from a config file alone.
  from tensor2robot_tpu.reliability import fault_injection
  # reliability/__init__ rebinds the name 'retry' to the function (same
  # shadowing as rl.run_env above); import the class from its module.
  from tensor2robot_tpu.reliability.retry import RetryPolicy
  register(fault_injection.configure_fault_injector,
           'configure_fault_injector')
  register(RetryPolicy, 'RetryPolicy')

  # Input generators (ref input_generators/default_input_generator.py).
  register(input_generators.DefaultRecordInputGenerator,
           'DefaultRecordInputGenerator')
  register(input_generators.FractionalRecordInputGenerator,
           'FractionalRecordInputGenerator')
  register(input_generators.MultiEvalRecordInputGenerator,
           'MultiEvalRecordInputGenerator')
  register(input_generators.DefaultRandomInputGenerator,
           'DefaultRandomInputGenerator')
  from tensor2robot_tpu.replay import feed as replay_feed
  register(replay_feed.ReplayInputGenerator, 'ReplayInputGenerator')
  register(input_generators.DefaultConstantInputGenerator,
           'DefaultConstantInputGenerator')
  register(meta_data.MetaRecordInputGenerator, 'MetaRecordInputGenerator')
  register(meta_data.MAMLRandomInputGenerator, 'MAMLRandomInputGenerator')

  # Optimizers (ref models/optimizers.py:29-52).
  register(optimizers.create_adam_optimizer, 'create_adam_optimizer')
  register(optimizers.create_sgd_optimizer, 'create_sgd_optimizer')
  register(optimizers.create_momentum_optimizer,
           'create_momentum_optimizer')
  register(optimizers.create_rms_prop_optimizer,
           'create_rms_prop_optimizer')
  register(optimizers.create_constant_learning_rate,
           'create_constant_learning_rate')
  register(optimizers.create_exponential_decay_learning_rate,
           'create_exponential_decay_learning_rate')

  # Meta learning.
  register(maml_model.MAMLRegressionModel, 'MAMLRegressionModel')
  register(maml_inner_loop.MAMLInnerLoopGradientDescent,
           'MAMLInnerLoopGradientDescent')
  from tensor2robot_tpu.preprocessors import device_decode
  register(device_decode.DeviceDecodePreprocessor,
           'DeviceDecodePreprocessor')
  register(device_decode.wrap_model_with_device_decode,
           'wrap_model_with_device_decode')
  register(meta_preproc.MAMLPreprocessorV2, 'MAMLPreprocessorV2')
  register(meta_preproc.FixedLenMetaExamplePreprocessor,
           'FixedLenMetaExamplePreprocessor')

  # Policies + collect/eval loop.
  register(policies.CEMPolicy, 'CEMPolicy')
  register(policies.RegressionPolicy, 'RegressionPolicy')
  register(policies.OUExploreRegressionPolicy, 'OUExploreRegressionPolicy')
  register(policies.ScheduledExplorationRegressionPolicy,
           'ScheduledExplorationRegressionPolicy')
  register(policies.PerEpisodeSwitchPolicy, 'PerEpisodeSwitchPolicy')
  register(collect_eval.collect_eval_loop, 'collect_eval_loop')
  register(run_env_module.run_env, 'run_env')

  # QT-Opt workload (ref research/qtopt).
  register(
      qtopt_models.Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
      'Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom')
  register(qtopt_models.DefaultGrasping44ImagePreprocessor,
           'DefaultGrasping44ImagePreprocessor')
  register(optimizer_builder.build_opt, 'build_opt')
  register(qtopt_networks.Grasping44Network, 'Grasping44Network')

  # Grasp2Vec workload.
  register(grasp2vec_model.Grasp2VecModel, 'Grasp2VecModel')
  register(grasp2vec_model.Grasp2VecPreprocessor, 'Grasp2VecPreprocessor')
  register(g2v_losses.n_pairs_loss, 'NPairsLoss')
  register(g2v_losses.triplet_loss, 'TripletLoss')

  # VRGripper / WTL workload.
  register(vrgripper_env_models.VRGripperRegressionModel,
           'VRGripperRegressionModel')
  register(vrgripper_env_models.VRGripperDomainAdaptiveModel,
           'VRGripperDomainAdaptiveModel')
  register(vrgripper_env_models.DefaultVRGripperPreprocessor,
           'DefaultVRGripperPreprocessor')
  register(vrgripper_env_meta_models.VRGripperEnvRegressionModelMAML,
           'VRGripperEnvRegressionModelMAML')
  register(vrgripper_env_meta_models.VRGripperEnvTecModel,
           'VRGripperEnvTecModel')
  register(vrgripper_env_meta_models.VRGripperEnvSequentialModel,
           'VRGripperEnvSequentialModel')
  register(vrgripper_env_wtl_models.VRGripperEnvSimpleTrialModel,
           'VRGripperEnvSimpleTrialModel')
  register(vrgripper_env_wtl_models.VRGripperEnvVisionTrialModel,
           'VRGripperEnvVisionTrialModel')
  register(decoders.MSEDecoder, 'MSEDecoder')
  register(decoders.MDNActionDecoder, 'MDNActionDecoder')
  register(decoders.MAFDecoder, 'MAFDecoder')
  register(decoders.DiscreteDecoder, 'DiscreteDecoder')

  # Pose env workload.
  register(pose_env.PoseToyEnv, 'PoseToyEnv')
  register(pose_env.PoseEnvRandomPolicy, 'PoseEnvRandomPolicy')
  register(pose_env_models.PoseEnvRegressionModel, 'PoseEnvRegressionModel')
  register(pose_env_models.PoseEnvContinuousMCModel,
           'PoseEnvContinuousMCModel')
  register(pose_env_maml_models.PoseEnvRegressionModelMAML,
           'PoseEnvRegressionModelMAML')
  from tensor2robot_tpu.data import writer as replay_writer_module
  from tensor2robot_tpu.research.pose_env import episode_to_transitions
  register(replay_writer_module.TFRecordReplayWriter, 'TFRecordReplayWriter')
  register(episode_to_transitions.episode_to_transitions_pose_toy,
           'episode_to_transitions_pose_toy')

  # Seq2Act transformer BC workload (RT-1-style, BASELINE config #5).
  from tensor2robot_tpu.research import seq2act
  register(seq2act.Seq2ActBCModel, 'Seq2ActBCModel')
  register(seq2act.Seq2ActPreprocessor, 'Seq2ActPreprocessor')

  # Parallelism rule sets for train_eval_model.tp_rules (zero-arg
  # factories so configs can bind @TP_RULES_TRANSFORMER() etc.; they
  # concatenate in any order — docs/parallelism.md).
  from tensor2robot_tpu.parallel import sharding as sharding_rules

  def _tp_rules_transformer():
    return sharding_rules.TP_RULES_TRANSFORMER

  def _ep_rules_moe():
    return sharding_rules.EP_RULES_MOE

  def _pp_rules_transformer():
    return sharding_rules.PP_RULES_TRANSFORMER

  register(_tp_rules_transformer, 'TP_RULES_TRANSFORMER')
  register(_ep_rules_moe, 'EP_RULES_MOE')
  register(_pp_rules_transformer, 'PP_RULES_TRANSFORMER')
