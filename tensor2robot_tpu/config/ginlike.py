"""A dependency-free, gin-syntax-compatible configuration engine.

Parity target: the reference's use of gin-config end-to-end
(/root/reference/utils/train_eval.py:52-61, models/abstract_model.py:70-85,
research/*/configs/*.gin). gin is not available in this environment, so the
subset the reference's configs actually use is implemented natively with
identical file syntax:

  * ``name.param = value`` bindings, with dotted names matched by suffix
    (``DefaultRecordInputGenerator`` == ``data.DefaultRecordInputGenerator``)
  * explicit scopes: ``train_input_generator/Cls.param = ...`` applied via
    ``@train_input_generator/Cls()`` references
  * macros: ``TRAIN_DATA = '/path*'`` / ``%TRAIN_DATA``
  * configurable references ``@name`` (the callable itself) and ``@name()``
    (called each time the binding is injected)
  * ``include 'other.gin'`` (relative to the including file or the
    configured search paths)
  * python-literal values incl. tuples/lists/dicts/scientific notation
  * ``operative_config_str()`` — what was actually consumed, for the
    config snapshot written into model_dir (ref GinConfigSaverHook).

API mirrors gin: ``configurable``, ``external_configurable``,
``parse_config``, ``parse_config_files_and_bindings``, ``clear_config``,
``query_parameter``, ``config_str``, ``operative_config_str``.
"""

from __future__ import annotations

import ast
import functools
import inspect
import os
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, Callable] = {}
_BINDINGS: Dict[Tuple[str, str, str], Any] = {}  # (scope, name, param) -> raw
_MACROS: Dict[str, Any] = {}
_OPERATIVE: Dict[Tuple[str, str, str], Any] = {}
_SEARCH_PATHS: List[str] = ['']
_LOCK = threading.RLock()
_SCOPE_STACK = threading.local()


class ConfigError(Exception):
  pass


def add_config_file_search_path(path: str) -> None:
  if path not in _SEARCH_PATHS:
    _SEARCH_PATHS.append(path)


def clear_config(clear_registry: bool = False) -> None:
  with _LOCK:
    _BINDINGS.clear()
    _MACROS.clear()
    _OPERATIVE.clear()
    if clear_registry:
      _REGISTRY.clear()


def _current_scopes() -> List[str]:
  return getattr(_SCOPE_STACK, 'scopes', [])


class _ScopeContext:
  def __init__(self, scope: str):
    self._scope = scope

  def __enter__(self):
    scopes = getattr(_SCOPE_STACK, 'scopes', [])
    _SCOPE_STACK.scopes = scopes + [self._scope]
    return self

  def __exit__(self, *exc):
    _SCOPE_STACK.scopes = _SCOPE_STACK.scopes[:-1]
    return False


def _resolve_name(name: str) -> str:
  """Finds the registered full name matching ``name`` by dotted suffix."""
  if name in _REGISTRY:
    return name
  matches = [full for full in _REGISTRY
             if full == name or full.endswith('.' + name)]
  if len(matches) == 1:
    return matches[0]
  if not matches:
    raise ConfigError('No configurable matching {!r}.'.format(name))
  raise ConfigError('Ambiguous configurable {!r}: {}.'.format(name, matches))


class ConfigurableReference:
  """A ``@[scope/]name`` value: the configurable, with its scope attached."""

  def __init__(self, name: str, scope: str = '', evaluate: bool = False):
    self.name = name
    self.scope = scope
    self.evaluate = evaluate

  def __repr__(self):
    prefix = self.scope + '/' if self.scope else ''
    return '@{}{}{}'.format(prefix, self.name, '()' if self.evaluate else '')

  def resolve(self):
    fn = _REGISTRY[_resolve_name(self.name)]
    if not self.scope:
      return fn

    @functools.wraps(fn)
    def scoped(*args, **kwargs):
      with _ScopeContext(self.scope):
        return fn(*args, **kwargs)

    return scoped


def _materialize(value):
  """Raw parsed value -> runtime value (resolve refs/macros, recurse)."""
  if isinstance(value, ConfigurableReference):
    fn = value.resolve()
    return fn() if value.evaluate else fn
  if isinstance(value, _MacroReference):
    if value.name not in _MACROS:
      raise ConfigError('Undefined macro %{}.'.format(value.name))
    return _materialize(_MACROS[value.name])
  if isinstance(value, list):
    return [_materialize(v) for v in value]
  if isinstance(value, tuple):
    return tuple(_materialize(v) for v in value)
  if isinstance(value, dict):
    return {k: _materialize(v) for k, v in value.items()}
  return value


class _MacroReference:
  def __init__(self, name: str):
    self.name = name

  def __repr__(self):
    return '%' + self.name


def _bindings_for(full_name: str, short_name: str) -> Dict[str, Any]:
  """Applicable bindings for a call: unscoped then active-scope overrides."""
  out: Dict[str, Any] = {}
  keys: Dict[str, Tuple[str, str, str]] = {}
  with _LOCK:
    for (scope, name, param), raw in _BINDINGS.items():
      if name not in (full_name, short_name):
        continue
      if scope == '':
        if param not in out:
          out[param] = raw
          keys[param] = (scope, name, param)
    for active in _current_scopes():
      for (scope, name, param), raw in _BINDINGS.items():
        if scope == active and name in (full_name, short_name):
          out[param] = raw
          keys[param] = (scope, name, param)
  return {param: (raw, keys[param]) for param, raw in out.items()}


def _make_configurable(fn: Callable, full_name: str) -> Callable:
  short_name = full_name.rsplit('.', 1)[-1]
  if inspect.isclass(fn):
    signature_target = fn.__init__
  else:
    signature_target = fn
  try:
    signature = inspect.signature(signature_target)
    has_var_kwargs = any(
        p.kind == inspect.Parameter.VAR_KEYWORD
        for p in signature.parameters.values())
    accepted = {p.name for p in signature.parameters.values()
                if p.kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD,
                              inspect.Parameter.KEYWORD_ONLY)}
  except (TypeError, ValueError):
    signature, has_var_kwargs, accepted = None, True, set()

  @functools.wraps(fn)
  def wrapper(*args, **kwargs):
    injected = {}
    for param, (raw, key) in _bindings_for(full_name, short_name).items():
      if param in kwargs:
        continue
      if not has_var_kwargs and param not in accepted:
        raise ConfigError(
            '{} got an unknown configured parameter {!r}.'.format(
                full_name, param))
      value = _materialize(raw)
      injected[param] = value
      with _LOCK:
        _OPERATIVE[key] = value
    # Positionally-passed args win over bindings (gin semantics).
    if signature is not None and args:
      positional = [p.name for p in signature.parameters.values()
                    if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                                  inspect.Parameter.POSITIONAL_OR_KEYWORD)]
      if inspect.isclass(fn) and positional and positional[0] == 'self':
        positional = positional[1:]
      for name in positional[:len(args)]:
        injected.pop(name, None)
    kwargs = {**injected, **kwargs}
    return fn(*args, **kwargs)

  wrapper.__wrapped_configurable__ = fn
  return wrapper


def configurable(name_or_fn=None, module: Optional[str] = None):
  """Decorator registering a function/class as configurable (gin API)."""

  def _register(fn, name=None):
    base = name or fn.__name__
    full_name = '{}.{}'.format(module, base) if module else base
    wrapped = _make_configurable(fn, full_name)
    with _LOCK:
      _REGISTRY[full_name] = wrapped
    return wrapped

  if callable(name_or_fn):
    return _register(name_or_fn)

  def decorator(fn):
    return _register(fn, name=name_or_fn)

  return decorator


def external_configurable(fn: Callable, name: Optional[str] = None,
                          module: Optional[str] = None) -> Callable:
  """Registers third-party/library callables without modifying them."""
  base = name or fn.__name__
  full_name = '{}.{}'.format(module, base) if module else base
  wrapped = _make_configurable(fn, full_name)
  with _LOCK:
    _REGISTRY[full_name] = wrapped
  return wrapped


def get_configurable(name: str) -> Callable:
  return _REGISTRY[_resolve_name(name)]


# -- parsing ------------------------------------------------------------------

_BINDING_RE = re.compile(r'^(?:(?P<scope>[\w./]+)/)?(?P<name>[\w.]+)\.'
                         r'(?P<param>\w+)\s*=\s*(?P<value>.+)$', re.S)
_MACRO_RE = re.compile(r'^(?P<name>\w+)\s*=\s*(?P<value>.+)$', re.S)
_INCLUDE_RE = re.compile(r'''^include\s+['"](?P<path>[^'"]+)['"]$''')


class _ValueParser:
  """Recursive-descent parser for gin value expressions."""

  def __init__(self, text: str):
    self.text = text
    self.pos = 0

  def parse(self):
    value = self._parse_value()
    self._skip_ws()
    if self.pos != len(self.text):
      raise ConfigError('Trailing characters in value: {!r}'.format(
          self.text[self.pos:]))
    return value

  def _skip_ws(self):
    while self.pos < len(self.text) and self.text[self.pos] in ' \t\n\r':
      self.pos += 1

  def _parse_value(self):
    self._skip_ws()
    if self.pos >= len(self.text):
      raise ConfigError('Empty value.')
    ch = self.text[self.pos]
    if ch == '@':
      return self._parse_reference()
    if ch == '%':
      self.pos += 1
      match = re.match(r'[\w.]+', self.text[self.pos:])
      if not match:
        raise ConfigError('Bad macro reference in {!r}.'.format(self.text))
      self.pos += match.end()
      return _MacroReference(match.group(0))
    if ch == '[':
      return self._parse_sequence(']', list)
    if ch == '(':
      return self._parse_sequence(')', tuple)
    if ch == '{':
      return self._parse_dict()
    return self._parse_literal()

  def _parse_reference(self):
    self.pos += 1  # consume '@'
    match = re.match(r'(?:(?P<scope>[\w./]+)/)?(?P<name>[\w.]+)',
                     self.text[self.pos:])
    if not match:
      raise ConfigError('Bad reference in {!r}.'.format(self.text))
    self.pos += match.end()
    evaluate = False
    if self.text[self.pos:self.pos + 2] == '()':
      evaluate = True
      self.pos += 2
    return ConfigurableReference(match.group('name'),
                                 match.group('scope') or '', evaluate)

  def _parse_sequence(self, closing: str, factory):
    self.pos += 1
    items = []
    while True:
      self._skip_ws()
      if self.pos >= len(self.text):
        raise ConfigError('Unterminated sequence in {!r}.'.format(self.text))
      if self.text[self.pos] == closing:
        self.pos += 1
        return factory(items)
      items.append(self._parse_value())
      self._skip_ws()
      if self.pos < len(self.text) and self.text[self.pos] == ',':
        self.pos += 1

  def _parse_dict(self):
    self.pos += 1
    out = {}
    while True:
      self._skip_ws()
      if self.pos >= len(self.text):
        raise ConfigError('Unterminated dict in {!r}.'.format(self.text))
      if self.text[self.pos] == '}':
        self.pos += 1
        return out
      key = self._parse_value()
      self._skip_ws()
      if self.text[self.pos] != ':':
        raise ConfigError('Expected : in dict {!r}.'.format(self.text))
      self.pos += 1
      out[key] = self._parse_value()
      self._skip_ws()
      if self.pos < len(self.text) and self.text[self.pos] == ',':
        self.pos += 1

  def _parse_literal(self):
    rest = self.text[self.pos:]
    # Strings: delegate to ast for proper escape handling.
    if rest[0] in '\'"':
      quote = rest[0]
      end = 1
      while end < len(rest):
        if rest[end] == '\\':
          end += 2
          continue
        if rest[end] == quote:
          break
        end += 1
      literal = rest[:end + 1]
      self.pos += end + 1
      return ast.literal_eval(literal)
    match = re.match(r'[^,\]\)\}:\s]+', rest)
    if not match:
      raise ConfigError('Bad literal in {!r}.'.format(self.text))
    token = match.group(0)
    self.pos += match.end()
    try:
      return ast.literal_eval(token)
    except (SyntaxError, ValueError):
      return token  # bare identifier -> string (gin tolerates for enums)


def _strip_comment(line: str) -> str:
  """Removes a trailing # comment, ignoring # inside quoted strings."""
  quote = None
  i = 0
  while i < len(line):
    ch = line[i]
    if quote:
      if ch == '\\':
        i += 2
        continue
      if ch == quote:
        quote = None
    elif ch in '\'"':
      quote = ch
    elif ch == '#':
      return line[:i]
    i += 1
  return line


def _logical_lines(text: str):
  """Joins continuation lines (open brackets or trailing backslash)."""
  pending = ''
  depth = 0
  for raw_line in text.splitlines():
    line = _strip_comment(raw_line).rstrip()
    if not line.strip() and not pending:
      continue
    pending = (pending + '\n' + line) if pending else line
    depth = (pending.count('[') - pending.count(']') +
             pending.count('(') - pending.count(')') +
             pending.count('{') - pending.count('}'))
    if depth > 0 or pending.endswith('\\'):
      pending = pending.rstrip('\\')
      continue
    yield pending.strip()
    pending = ''
  if pending.strip():
    yield pending.strip()


def parse_config(config: str, base_dir: str = '') -> None:
  """Parses gin-format binding text (gin.parse_config)."""
  for line in _logical_lines(config):
    include = _INCLUDE_RE.match(line)
    if include:
      _parse_file(include.group('path'), base_dir)
      continue
    binding = _BINDING_RE.match(line)
    if binding:
      raw = _ValueParser(binding.group('value')).parse()
      with _LOCK:
        _BINDINGS[(binding.group('scope') or '', binding.group('name'),
                   binding.group('param'))] = raw
      continue
    macro = _MACRO_RE.match(line)
    if macro:
      raw = _ValueParser(macro.group('value')).parse()
      with _LOCK:
        _MACROS[macro.group('name')] = raw
      continue
    raise ConfigError('Unparseable config line: {!r}'.format(line))


def _parse_file(path: str, base_dir: str = '') -> None:
  candidates = [os.path.join(base_dir, path)] if base_dir else []
  candidates += [os.path.join(p, path) for p in _SEARCH_PATHS]
  for candidate in candidates:
    if os.path.isfile(candidate):
      with open(candidate) as f:
        parse_config(f.read(), base_dir=os.path.dirname(candidate))
      return
  raise ConfigError('Config file {!r} not found (searched {}).'.format(
      path, candidates))


def parse_config_files_and_bindings(
    config_files: Optional[Sequence[str]] = None,
    bindings: Optional[Sequence[str]] = None) -> None:
  """gin.parse_config_files_and_bindings (ref utils/train_eval.py:52-59)."""
  for path in config_files or []:
    _parse_file(path)
  if bindings:
    parse_config('\n'.join(bindings))


def query_parameter(binding_key: str):
  """Current value of '[scope/]name.param' (gin.query_parameter)."""
  match = _BINDING_RE.match(binding_key + ' = 0')
  if not match:
    raise ConfigError('Bad binding key {!r}.'.format(binding_key))
  key = (match.group('scope') or '', match.group('name'),
         match.group('param'))
  with _LOCK:
    if key not in _BINDINGS:
      raise ConfigError('No binding for {!r}.'.format(binding_key))
    return _materialize(_BINDINGS[key])


def _format(value) -> str:
  return repr(value)


def config_str() -> str:
  """All current bindings, as re-parseable text."""
  lines = []
  with _LOCK:
    for name, value in sorted(_MACROS.items()):
      lines.append('{} = {}'.format(name, _format(value)))
    for (scope, name, param), raw in sorted(_BINDINGS.items()):
      prefix = scope + '/' if scope else ''
      lines.append('{}{}.{} = {}'.format(prefix, name, param, _format(raw)))
  return '\n'.join(lines) + '\n'


def operative_config_str() -> str:
  """Bindings actually consumed by configurable calls so far."""
  lines = []
  with _LOCK:
    for (scope, name, param), value in sorted(_OPERATIVE.items()):
      prefix = scope + '/' if scope else ''
      lines.append('{}{}.{} = {}'.format(prefix, name, param,
                                         _format(value)))
  return '\n'.join(lines) + '\n'
