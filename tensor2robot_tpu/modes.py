"""Run-mode keys shared across the framework (analog of tf.estimator.ModeKeys)."""


class ModeKeys:
  TRAIN = 'train'
  EVAL = 'eval'
  PREDICT = 'predict'

  ALL = (TRAIN, EVAL, PREDICT)


def assert_valid_mode(mode: str) -> str:
  if mode not in ModeKeys.ALL:
    raise ValueError('Invalid mode {!r}; expected one of {}.'.format(
        mode, ModeKeys.ALL))
  return mode
