"""The bounded compile-config search space.

A candidate is one :class:`CompileConfig`: a set of per-compile XLA
options (shipped through ``lowered.compile(compiler_options=...)`` — no
process-global ``XLA_FLAGS`` mutation, so candidates are hermetic within
one process) plus optional model-layer overrides (conv
``dimension_numbers``/layout variants, e.g. Grasping44's
``conv_variant``/``space_to_depth`` network kwargs) and a donation
toggle for harnesses that rebuild the step per candidate.

The flag sets are CURATED, not exhaustive: the sweep is meant to run in
minutes on one chip, so each candidate must have a mechanism story
(scheduler, vmem budget, fusion aggressiveness, layout). Flags that the
local jaxlib does not recognize fail that candidate's compile with
INVALID_ARGUMENT — the autotuner records the failure and moves on, so a
curated list can safely name flags newer (or older) than the installed
toolchain.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

__all__ = ['CompileConfig', 'candidate_configs', 'BASELINE_CONFIG_ID']

BASELINE_CONFIG_ID = 'baseline'


@dataclasses.dataclass(frozen=True)
class CompileConfig:
  """One sweep candidate / one cached winner.

  Attributes:
    config_id: short stable identifier ('vmem-96m', 'latency-sched', ...).
      Forensics reports and bench records carry it verbatim.
    compiler_options: per-compile XLA options. Values keep their native
      python types (bool/int/str) — the PJRT layer rejects stringified
      bools ("'true' is not a valid bool value").
    model_overrides: model-constructor kwargs for layout variants (e.g.
      {'conv_variant': 'nchw'} or {'space_to_depth': True} for
      Grasping44's network_kwargs). Applied by harnesses that rebuild
      the model per candidate (bench.py); the trainer hook applies
      compiler_options only — a layout override changes the program, so
      it must come in through the model, not the compile.
    donate: whether the candidate step donates its state argument.
    notes: one-line mechanism story, for the sweep record.
  """

  config_id: str
  compiler_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
  model_overrides: Dict[str, Any] = dataclasses.field(default_factory=dict)
  donate: bool = True
  notes: str = ''

  def to_dict(self) -> Dict[str, Any]:
    return dataclasses.asdict(self)

  @classmethod
  def from_dict(cls, data: Dict[str, Any]) -> 'CompileConfig':
    known = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in dict(data).items() if k in known})


def _tpu_candidates(include_layouts: bool) -> List[CompileConfig]:
  """The curated TPU set: scheduler / vmem / fusion / layout levers.

  Sources: the pjit-era tuning literature (arxiv 2204.06514 §4: compiler
  scheduling + fusion flags moved their MFU), public XLA:TPU flag surveys
  (t5x/maxtext launch configs), and this repo's own per-op ceiling case
  (docs/performance.md): the headline is conv-emitter-bound, so the
  plausible levers are vmem budget (deeper conv pipelining), the
  latency-hiding scheduler (dispatch/overlap), and fusion aggressiveness
  around the convs.
  """
  out = [
      CompileConfig(BASELINE_CONFIG_ID, notes='stock compile, no options'),
      CompileConfig(
          'latency-sched',
          compiler_options={'xla_tpu_enable_latency_hiding_scheduler': True},
          notes='latency-hiding scheduler: overlap copies with compute'),
      CompileConfig(
          'vmem-64m',
          compiler_options={'xla_tpu_scoped_vmem_limit_kib': 65536},
          notes='raise scoped vmem budget (deeper conv operand pipelining)'),
      CompileConfig(
          'vmem-96m',
          compiler_options={'xla_tpu_scoped_vmem_limit_kib': 98304},
          notes='vmem budget, upper point'),
      CompileConfig(
          'no-multilevel-fusion',
          compiler_options={'xla_tpu_enable_multi_level_nested_loop_fusion':
                            False},
          notes='disable nested-loop fusion: isolates the conv emitter'),
      CompileConfig(
          'async-collectives',
          compiler_options={
              'xla_tpu_enable_async_collective_fusion': True,
              'xla_tpu_enable_async_collective_fusion_fuse_all_gather': True,
          },
          notes='async collective fusion (multi-chip steps only; single-'
                'chip programs compile identically)'),
      CompileConfig(
          'flm-bounds',
          compiler_options={'xla_tpu_licm_size_inflation_ratio': 1},
          notes='pin LICM size inflation: smaller loop bodies, less vmem '
                'pressure around the crop loop'),
  ]
  if include_layouts:
    out.extend([
        CompileConfig('conv-nchw',
                      model_overrides={'conv_variant': 'nchw'},
                      notes='body convs via NCHW/OIHW dimension_numbers '
                            '(layout-assignment alternative)'),
        CompileConfig('stem-space-to-depth',
                      model_overrides={'space_to_depth': True},
                      notes='stem conv as 3x3/1 on the 2x2 packed grid '
                            '(re-tried per-flag-set: a scheduler change '
                            'can flip the round-2 verdict)'),
    ])
  return out


def _cpu_candidates(include_layouts: bool) -> List[CompileConfig]:
  """CPU set: small but real — exists so the whole sweep->cache->apply
  path runs (and is tested) without a TPU attached."""
  out = [
      CompileConfig(BASELINE_CONFIG_ID, notes='stock compile, no options'),
      CompileConfig(
          'fast-min-max',
          compiler_options={'xla_cpu_enable_fast_min_max': True},
          notes='non-strict NaN semantics in min/max lowering'),
      CompileConfig(
          'no-fast-min-max',
          compiler_options={'xla_cpu_enable_fast_min_max': False},
          notes='strict min/max lowering'),
  ]
  if include_layouts:
    out.append(CompileConfig('conv-nchw',
                             model_overrides={'conv_variant': 'nchw'},
                             notes='NCHW/OIHW body convs'))
  return out


def candidate_configs(backend: Optional[str] = None,
                      include_layouts: bool = True
                      ) -> List[CompileConfig]:
  """The curated candidate list for ``backend`` ('tpu'/'cpu'/'gpu').

  ``backend`` defaults to the live jax backend. The first entry is always
  the baseline (empty) config; ``include_layouts=False`` drops the
  model-override candidates for harnesses that cannot rebuild the model.
  """
  if backend is None:
    import jax
    backend = jax.default_backend()
  backend = (backend or 'cpu').lower()
  if backend == 'tpu':
    return _tpu_candidates(include_layouts)
  return _cpu_candidates(include_layouts)
