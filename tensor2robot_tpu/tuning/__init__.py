"""Offline compile-config autotuning for jitted train steps.

The round-5 VERDICT named the one headline lever never pulled: a
systematic sweep of ``xla_tpu_*`` scheduler/vmem/fusion flags and conv
``dimension_numbers``/layout variants on the batch-512 step — the
compiler-level tuning pjit-era TPU stacks report as decisive
(arxiv 2204.06514). This package is that sweep, made a reusable tool:

  * ``search_space``      — curated, bounded candidate sets per backend
                            (compiler options + model layout overrides);
  * ``autotuner``         — compile each candidate via per-compile
                            ``compiler_options``, time it with warmup +
                            chained block-free dispatch (one sync at the
                            end, so dispatch overlap is measured rather
                            than lost), pick the winner deterministically;
  * ``cache``             — persist the winner to a JSON config cache
                            keyed by (workload, abstract shapes/dtypes,
                            device_kind, jax version) so production runs
                            pay for the sweep once.

``trainer/train_eval.py`` (the ``tuned_config`` arg) and ``bench.py``
load cache entries at startup and apply them to the train-step compile;
forensics reports carry the active config id so a regression is
attributable to the config that produced it.

``kernelbench`` (ISSUE 19) turns the same chained timing harness on
individual kernels: registered candidates (``layers/pallas_wgrad`` is
the first) vs their fused-XLA baselines, publishing schema-locked
``KERNEL_BENCH_KEYS`` rows appended to ``kernelbench.json`` next to the
tuning cache (``bin/t2r_kernelbench``) — the rig ROADMAP item 1's
kernel work lands numbers against.
"""

from tensor2robot_tpu.tuning.autotuner import (
    CandidateResult,
    SweepResult,
    measure_chained,
    sweep,
)
from tensor2robot_tpu.tuning.kernelbench import (
    KERNEL_BENCH_KEYS,
    KERNEL_BENCH_SCHEMA,
    default_results_path,
    read_results,
    register,
)
from tensor2robot_tpu.tuning.kernelbench import run as run_kernelbench
from tensor2robot_tpu.tuning.cache import (
    ConfigCache,
    abstract_signature,
    cache_key,
    default_cache_path,
)
from tensor2robot_tpu.tuning.search_space import (
    CompileConfig,
    candidate_configs,
)

__all__ = [
    'CandidateResult',
    'CompileConfig',
    'ConfigCache',
    'KERNEL_BENCH_KEYS',
    'KERNEL_BENCH_SCHEMA',
    'SweepResult',
    'abstract_signature',
    'cache_key',
    'candidate_configs',
    'default_cache_path',
    'default_results_path',
    'measure_chained',
    'read_results',
    'register',
    'run_kernelbench',
    'sweep',
]
