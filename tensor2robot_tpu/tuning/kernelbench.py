"""Kernel microbench rig: XLA-fused baseline vs registered candidates.

ROADMAP item 1 says the next headline wins come from inside the device
step, and the roofline observatory (observability/roofline.py) names
WHICH op families are memory-bound — but landing a kernel against that
evidence needs a rig that times a candidate against the XLA baseline
under the SAME harness every published number already uses. This is
that rig:

  * A tiny registry of kernel entries. Each entry builds, for the
    current backend, a ``(candidate, baseline, flops, shape, dtype)``
    case — ``layers/pallas_wgrad.py`` (the round-4 measured record:
    23.7 ms vs XLA's 10.3 ms at [512,79,79,64] bf16 on v5e) is the
    first, so the rig reproduces a known verdict out of the box and a
    future kernel attempt starts by beating a number, not a feeling.
  * Timing is ``tuning/autotuner.measure_chained`` — chained dispatch,
    one block per repetition, ``robust_median_spread`` dispersion — the
    identical block-free discipline bench.py and the compile-config
    sweep publish with, so kernelbench rows are comparable with both.
  * Results are schema-locked ``KERNEL_BENCH_KEYS`` rows persisted
    (appended, bounded history) to ``kernelbench.json`` NEXT TO the
    tuning cache, so cross-round regressions are a file diff:
    ``bin/t2r_kernelbench`` is the CLI.

CPU backends run candidates in Pallas interpret mode at small default
shapes — the schema and the speedup_vs_xla plumbing are exercised
end-to-end everywhere, while % peak honestly degrades to the -1.0
sentinel when the device kind has no peaks-table entry.

Import-time jax-free (jax loads inside builders/run) so the gate
``bin/check_roofline_doctor`` can schema-lock ``KERNEL_BENCH_KEYS``
on any box.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu.tuning import autotuner
from tensor2robot_tpu.tuning import cache as cache_lib

__all__ = ['KERNEL_BENCH_KEYS', 'KERNEL_BENCH_SCHEMA', 'REGISTRY',
           'default_results_path', 'register', 'run', 'read_results']

KERNEL_BENCH_SCHEMA = 't2r.kernelbench.v1'

# One row per timed kernel; every row carries every key (numeric
# failures hold the -1.0 sentinel, the self-check convention bench.py
# established with E2E_WIRE_BENCH_KEYS). speedup_vs_xla > 1.0 means the
# candidate BEAT the fused XLA baseline.
KERNEL_BENCH_KEYS = (
    'kernel',
    'device_kind',
    'dtype',
    'shape',
    'ms',
    'ms_spread',
    'xla_ms',
    'xla_ms_spread',
    'gflops',
    'gflop_per_s',
    'xla_gflop_per_s',
    'pct_peak',
    'speedup_vs_xla',
)

_HISTORY_CAP = 50  # runs kept in kernelbench.json

# name -> builder(shape, dtype) returning the case dict below.
REGISTRY: Dict[str, Callable] = {}


def register(name: str):
  """Decorator adding a kernel case builder to the rig's registry.

  A builder takes ``(shape, dtype)`` (either may be None for the
  backend's default) and returns::

      {'candidate': zero-arg fn dispatching the candidate kernel,
       'baseline':  zero-arg fn dispatching the fused-XLA reference,
       'flops':     analytic flops of ONE invocation,
       'shape':     the concrete shape tuple used,
       'dtype':     the concrete dtype name used}

  Both fns must dispatch WITHOUT blocking and return the output (the
  chained harness syncs once per repetition).
  """
  def deco(fn):
    REGISTRY[name] = fn
    return fn
  return deco


def default_results_path() -> str:
  """kernelbench.json next to the tuning cache (same env override)."""
  return os.path.join(os.path.dirname(cache_lib.default_cache_path()),
                      'kernelbench.json')


@register('pallas_wgrad')
def _build_pallas_wgrad(shape: Optional[Tuple[int, ...]] = None,
                        dtype: Optional[str] = None) -> Dict[str, object]:
  """The 5x5 conv weight-gradient record kernel vs XLA's emitter.

  Device default is the measured-record configuration from the
  pallas_wgrad docstring ([512,79,79,64] bf16, 654 GFLOP); CPU runs
  interpret mode at a small shape (the rig is about plumbing there, not
  performance).
  """
  import jax
  import jax.numpy as jnp

  from tensor2robot_tpu.layers import pallas_wgrad

  on_cpu = jax.default_backend() == 'cpu'
  if shape is None:
    shape = (2, 8, 8, 8) if on_cpu else (512, 79, 79, 64)
  if dtype is None:
    dtype = 'float32' if on_cpu else 'bfloat16'
  b, h, w, c = shape
  batch_tile = 2 if b % 2 == 0 else 1
  rng = jax.random.PRNGKey(0)
  x = jax.random.normal(rng, shape, jnp.float32).astype(dtype)
  dy = jax.random.normal(jax.random.fold_in(rng, 1), shape,
                         jnp.float32).astype(dtype)

  def candidate():
    return pallas_wgrad.conv5x5_wgrad(x, dy, batch_tile=batch_tile,
                                      interpret=on_cpu)

  @jax.jit
  def _xla_wgrad(x_, dy_):
    def conv(w_):
      return jax.lax.conv_general_dilated(
          x_, w_, (1, 1), 'SAME',
          dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
    _, vjp = jax.vjp(
        conv, jnp.zeros((pallas_wgrad.KH, pallas_wgrad.KW, c, c),
                        x_.dtype))
    return vjp(dy_)[0]

  def baseline():
    return _xla_wgrad(x, dy)

  flops = 2.0 * b * h * w * c * c * pallas_wgrad.KH * pallas_wgrad.KW
  return {'candidate': candidate, 'baseline': baseline, 'flops': flops,
          'shape': tuple(shape), 'dtype': str(dtype)}


def _time_ms(fn, n_steps: int, reps: int) -> Tuple[float, float]:
  import jax

  # Warm up: compile + first dispatch stay out of the timed chains.
  jax.block_until_ready(fn())
  median_s, spread_s = autotuner.measure_chained(
      fn, jax.block_until_ready, n_steps, reps)
  return median_s / max(n_steps, 1) * 1e3, spread_s / max(n_steps, 1) * 1e3


def run(kernels: Optional[Sequence[str]] = None,
        shape: Optional[Tuple[int, ...]] = None,
        dtype: Optional[str] = None,
        n_steps: int = 4,
        reps: int = 3,
        out_path: Optional[str] = None,
        persist: bool = True) -> Dict[str, object]:
  """Times the selected kernels vs their XLA baselines; one run record.

  Returns ``{'schema', 'device_kind', 'n_steps', 'reps', 'results'}``
  where every results row carries every ``KERNEL_BENCH_KEYS`` key. A
  kernel whose build or timing raises still produces a row — numeric
  fields at -1.0 and the error message attached — so a broken candidate
  is a visible regression, not a silently missing line.
  """
  from tensor2robot_tpu.observability import roofline as roofline_lib
  from tensor2robot_tpu.observability import signals as signals_lib

  device_kind = str(signals_lib.host_identity().get('device_kind',
                                                    'unknown'))
  peaks = roofline_lib.device_peaks(device_kind)
  names = list(kernels) if kernels else sorted(REGISTRY)
  results: List[Dict[str, object]] = []
  for name in names:
    row: Dict[str, object] = {key: -1.0 for key in KERNEL_BENCH_KEYS}
    row.update(kernel=name, device_kind=device_kind, dtype='', shape=[])
    try:
      builder = REGISTRY[name]
      case = builder(shape=shape, dtype=dtype)
      ms, ms_spread = _time_ms(case['candidate'], n_steps, reps)
      xla_ms, xla_ms_spread = _time_ms(case['baseline'], n_steps, reps)
      flops = float(case['flops'])
      row.update(
          dtype=case['dtype'],
          shape=list(case['shape']),
          ms=round(ms, 4),
          ms_spread=round(ms_spread, 4),
          xla_ms=round(xla_ms, 4),
          xla_ms_spread=round(xla_ms_spread, 4),
          gflops=round(flops / 1e9, 6),
          gflop_per_s=round(flops / (ms / 1e3) / 1e9, 2) if ms > 0
          else -1.0,
          xla_gflop_per_s=round(flops / (xla_ms / 1e3) / 1e9, 2)
          if xla_ms > 0 else -1.0,
          pct_peak=round(flops / (ms / 1e3) / (peaks[0] * 1.0), 6)
          if (peaks and ms > 0) else -1.0,
          speedup_vs_xla=round(xla_ms / ms, 4) if ms > 0 else -1.0,
      )
    except Exception as e:  # noqa: BLE001 — a broken kernel is a result
      row['error'] = '{}: {}'.format(type(e).__name__, e)
    missing = [key for key in KERNEL_BENCH_KEYS if key not in row]
    if missing:
      row['schema_missing'] = missing
    results.append(row)
  record: Dict[str, object] = {
      'schema': KERNEL_BENCH_SCHEMA,
      'device_kind': device_kind,
      'n_steps': int(n_steps),
      'reps': int(reps),
      'results': results,
  }
  if persist:
    record['path'] = write_results(record, out_path)
  return record


def write_results(record: Dict[str, object],
                  out_path: Optional[str] = None) -> str:
  """Appends one run record to kernelbench.json (atomic, bounded)."""
  path = out_path or default_results_path()
  runs = read_results(path)
  runs.append(record)
  runs = runs[-_HISTORY_CAP:]
  os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
  tmp = path + '.tmp'
  with open(tmp, 'w', encoding='utf-8') as f:
    json.dump(runs, f, indent=2, sort_keys=True)
  os.replace(tmp, path)
  return path


def read_results(path: Optional[str] = None) -> List[Dict[str, object]]:
  """All persisted run records (oldest first); [] when absent/torn."""
  path = path or default_results_path()
  try:
    with open(path, encoding='utf-8') as f:
      runs = json.load(f)
    return runs if isinstance(runs, list) else []
  except (OSError, ValueError):
    return []


def format_results(record: Dict[str, object]) -> str:
  """Human table for the CLI: one line per kernel row."""
  lines = ['kernelbench [{}] n_steps={} reps={}'.format(
      record.get('device_kind'), record.get('n_steps'),
      record.get('reps'))]
  for row in record.get('results') or []:
    if row.get('error'):
      lines.append('  {:<16} ERROR {}'.format(row.get('kernel'),
                                              row.get('error')))
      continue
    pct = row.get('pct_peak')
    lines.append(
        '  {:<16} {:>9.3f} ms (±{:.3f})  xla {:>9.3f} ms  '
        '{:>9.1f} GFLOP/s  {}  speedup_vs_xla {:.2f}x'.format(
            row.get('kernel'), row.get('ms'), row.get('ms_spread'),
            row.get('xla_ms'), row.get('gflop_per_s'),
            '{:.1%} peak'.format(pct) if isinstance(pct, float) and
            pct >= 0 else 'peak n/a',
            row.get('speedup_vs_xla')))
  return '\n'.join(lines)
