"""The sweep engine: compile each candidate, time it, persist the winner.

Measurement method — chained block-free dispatch: each repetition
dispatches ``n_steps`` steps back to back and synchronizes ONCE at the
end, so host->device dispatch overlaps device compute exactly as it does
in the real training loop. Timing every step individually with
``block_until_ready`` would serialize dispatch against compute and
charge the per-dispatch round trip (measured ~4-5% of the headline step
on this environment's tunneled chip, and the whole step for ms-scale
programs) to every candidate equally — hiding exactly the
scheduler-flag effects the sweep exists to find. The spread statistic is
max-min over the best ``reps - 1`` repetitions (one hiccup cannot blow
up the field; same statistic as bench.py).

Candidates that fail to COMPILE (e.g. a curated flag the local jaxlib
does not know) are recorded with their error and excluded from winner
selection — a curated search space may safely name flags newer than the
installed toolchain. Winner selection is deterministic: lowest median,
ties broken by candidate order — except that a candidate whose
post-optimization HLO fingerprint equals the baseline's compiled to the
IDENTICAL program and can never beat baseline (its delta is noise by
construction).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu.tuning import cache as cache_lib
from tensor2robot_tpu.tuning import search_space

__all__ = ['StepCase', 'CandidateResult', 'SweepResult',
           'robust_median_spread', 'measure_chained', 'compile_with_config',
           'sweep']

_logv = None


def _log(msg: str, *args) -> None:
  global _logv
  if _logv is None:
    from absl import logging as _absl_logging  # deferred: absl optional
    _logv = _absl_logging.info
  _logv(msg, *args)


@dataclasses.dataclass
class StepCase:
  """What ``build(config)`` hands the sweep for one candidate.

  Attributes:
    jitted: the ``jax.jit`` object for the step (donation and shardings
      already applied by the caller).
    args: concrete example arguments for lower/compile and timing.
    advance: ``(out, args) -> args`` threading one call's output into the
      next call's arguments — REQUIRED when the step donates a buffer
      (the donated input is dead after the call); defaults to reusing
      ``args`` unchanged.
  """

  jitted: Any
  args: Tuple
  advance: Optional[Callable[[Any, Tuple], Tuple]] = None


@dataclasses.dataclass
class CandidateResult:
  config: search_space.CompileConfig
  compile_ok: bool
  error: str = ''
  compile_s: float = 0.0
  median_s: float = float('inf')
  spread_s: float = 0.0
  steps_per_s: float = 0.0
  # Post-optimization HLO fingerprint (hlo_analysis.program_fingerprint):
  # a candidate whose fingerprint equals the baseline's compiled to the
  # IDENTICAL program — its timing delta is noise and the flag is a
  # measured no-op for this workload.
  hlo_fingerprint: str = ''

  def record(self) -> Dict[str, Any]:
    return {
        'compile_ok': self.compile_ok,
        'error': self.error,
        'compile_s': round(self.compile_s, 3),
        'median_s': self.median_s if self.median_s != float('inf') else -1.0,
        'spread_s': self.spread_s,
        'steps_per_s': round(self.steps_per_s, 2),
        'hlo_fingerprint': self.hlo_fingerprint,
        'notes': self.config.notes,
    }


@dataclasses.dataclass
class SweepResult:
  workload: str
  key: str
  cache_hit: bool
  winner: Optional[search_space.CompileConfig]
  results: List[CandidateResult]
  entry: Dict[str, Any]


def robust_median_spread(times: Sequence[float]) -> Tuple[float, float]:
  """(median, max-min over the best ``len-1``) of raw repetition times.

  THE dispersion statistic for every published timing — bench.py's
  ``*_spread`` fields and the sweep's ``spread_s`` both call this, so
  they cannot drift apart. Dropping the single worst repetition before
  taking the range makes one tunnel hiccup unable to blow up the field,
  while a genuinely unstable measurement (2+ slow reps) still reports a
  large spread.
  """
  times = sorted(times)
  median = times[len(times) // 2]
  kept = times[:-1] if len(times) > 2 else times
  spread = kept[-1] - kept[0] if len(kept) > 1 else 0.0
  return median, spread


def measure_chained(step_once: Callable[[], Any],
                    sync: Callable[[Any], Any],
                    n_steps: int,
                    reps: int,
                    timer: Callable[[], float] = time.perf_counter
                    ) -> Tuple[float, float]:
  """(median_s, robust_spread_s) over ``reps`` chains of ``n_steps``.

  ``step_once`` dispatches one step WITHOUT blocking and returns the
  output to chain/sync on; ``sync`` blocks on it. Spread per
  :func:`robust_median_spread` (single-hiccup-proof).
  """
  times = []
  for _ in range(max(1, reps)):
    t0 = timer()
    out = None
    for _ in range(max(1, n_steps)):
      out = step_once()
    sync(out)
    times.append(timer() - t0)
  return robust_median_spread(times)


def compile_with_config(jitted, args,
                        config: Optional[search_space.CompileConfig]):
  """AOT-compiles ``jitted`` for ``args`` under a config's XLA options.

  Lowers, then delegates to ``compile/artifact.compile_lowered`` — the
  ONE options-to-compile site every consumer (this helper, the sweep,
  the artifact store) shares. Returns the compiled executable (callable
  with the same arguments).
  """
  from tensor2robot_tpu.compile import artifact as artifact_lib

  return artifact_lib.compile_lowered(
      jitted.lower(*args),
      dict(config.compiler_options) if config else {})


def _default_sync(out):
  import jax

  return jax.block_until_ready(out)


def sweep(workload: str,
          build: Callable[[search_space.CompileConfig], StepCase],
          candidates: Optional[Sequence[search_space.CompileConfig]] = None,
          example_args: Optional[Any] = None,
          cache: Optional[cache_lib.ConfigCache] = None,
          cache_path: Optional[str] = None,
          n_steps: int = 8,
          reps: int = 3,
          warmup_steps: int = 2,
          timer: Callable[[], float] = time.perf_counter,
          sync: Optional[Callable[[Any], Any]] = None,
          force: bool = False,
          persist_artifacts: bool = True) -> SweepResult:
  """Runs (or short-circuits via cache) one compile-config sweep.

  Args:
    workload: cache-key name ('qtopt_critic_b512', ...).
    build: ``config -> StepCase``. Called once per candidate — model
      layout overrides happen here (the caller rebuilds its model from
      ``config.model_overrides``); compiler options are applied by the
      sweep itself at its lower+compile step.
    candidates: search space; defaults to
      ``search_space.candidate_configs()`` for the live backend.
    example_args: pytree whose shapes/dtypes key the cache. Defaults to
      the baseline candidate's ``StepCase.args`` — pass it explicitly to
      guarantee a cache HIT performs zero builds/compiles.
    cache / cache_path: where winners persist. ``cache=None`` with
      ``cache_path=None`` uses the default path; pass
      ``cache=ConfigCache(path)`` to pin a file.
    n_steps/reps/warmup_steps: chained-dispatch timing shape.
    timer/sync: injectable for tests (a stubbed timer makes winner
      selection a pure function of its scripted values).
    force: re-sweep even on a cache hit.
    persist_artifacts: serialize every successfully-measured candidate's
      executable into the unified ``CompiledArtifact`` store next to
      the cache (tensor2robot_tpu/compile) — the sweep already paid for
      each AOT compile, so persisting them makes the winner's
      executable FREE at train time (the trainer's artifact cold-start
      path loads it by the same workload/shapes/config key).

  Returns a :class:`SweepResult`; ``.winner`` is None only when every
  candidate failed to compile.
  """
  import jax

  if candidates is None:
    candidates = search_space.candidate_configs()
  candidates = list(candidates)
  if not candidates:
    raise ValueError('sweep needs at least one candidate config.')
  if sync is None:
    sync = _default_sync
  if cache is None:
    cache = cache_lib.ConfigCache(cache_path)

  device_kind = getattr(jax.devices()[0], 'device_kind', 'unknown')
  built_baseline: Optional[StepCase] = None
  if example_args is None:
    built_baseline = build(candidates[0])
    example_args = built_baseline.args
  signature = cache_lib.abstract_signature(example_args)
  key = cache_lib.cache_key(workload, signature, device_kind)

  if not force:
    entry = cache.lookup(key)
    if entry is not None:
      # winner_ok=False entries (every candidate failed to compile) hit
      # the cache — the sweep is not re-run every startup — but report
      # winner=None, honoring the '.winner is None only when all
      # candidates failed' contract; the stored config is a placeholder.
      winner = None
      if entry.get('winner_ok', True):
        winner = search_space.CompileConfig.from_dict(entry['winner'])
      _log('Tuning cache HIT for %s (%s): %s', workload, key,
           winner.config_id if winner else '<no-winner>')
      return SweepResult(workload=workload, key=key, cache_hit=True,
                         winner=winner, results=[], entry=entry)

  results: List[CandidateResult] = []
  for i, config in enumerate(candidates):
    result = CandidateResult(config=config, compile_ok=False)
    results.append(result)
    try:
      if i == 0 and built_baseline is not None:
        case = built_baseline
      else:
        case = build(config)
      t0 = time.perf_counter()
      # Lowered kept explicitly (not via compile_with_config): its text
      # hash is the program-identity component of the candidate's
      # artifact key — model_overrides candidates compile a DIFFERENT
      # program and must persist under a different key.
      from tensor2robot_tpu.compile import artifact as artifact_lib
      lowered = case.jitted.lower(*case.args)
      options = dict(config.compiler_options) if config else {}
      compiled = artifact_lib.compile_lowered(lowered, options)
      result.compile_s = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001 — unknown flag, OOM, ...
      result.error = '{}: {}'.format(type(e).__name__, str(e)[:300])
      _log('Candidate %s failed to compile: %s', config.config_id,
           result.error)
      continue
    try:
      from tensor2robot_tpu.parallel import hlo_analysis
      result.hlo_fingerprint = hlo_analysis.program_fingerprint(compiled)
    except Exception:  # noqa: BLE001 — as_text unavailable on some paths
      pass
    advance = case.advance or (lambda out, args: args)
    state = {'args': case.args}

    def step_once(compiled=compiled, advance=advance, state=state):
      out = compiled(*state['args'])
      state['args'] = advance(out, state['args'])
      return out

    try:
      out = None
      for _ in range(max(0, warmup_steps)):
        out = step_once()
      if out is not None:
        sync(out)
      result.median_s, result.spread_s = measure_chained(
          step_once, sync, n_steps=n_steps, reps=reps, timer=timer)
      result.compile_ok = True
      result.steps_per_s = n_steps / max(result.median_s, 1e-12)
      _log('Candidate %s: %.2f steps/s (median %.4fs, spread %.4fs)',
           config.config_id, result.steps_per_s, result.median_s,
           result.spread_s)
      if persist_artifacts:
        # The sweep already paid for this AOT compile; persisting it
        # makes the eventual winner's executable a zero-compile load at
        # train time. Best-effort: a backend without serialization
        # still sweeps normally.
        try:
          from tensor2robot_tpu.compile import artifact as artifact_lib

          store = artifact_lib.ArtifactStore(cache.path)
          lowered_sha = artifact_lib.program_sha(lowered.as_text())
          artifact_key = artifact_lib.artifact_key(
              workload, signature, device_kind, lowered_sha=lowered_sha)
          store.persist(workload, artifact_key, config.config_id,
                        options, compiled, lowered_sha=lowered_sha,
                        fingerprint=result.hlo_fingerprint or None)
        except Exception as e:  # noqa: BLE001
          _log('Could not persist candidate %s artifact: %s',
               config.config_id, e)
    except Exception as e:  # noqa: BLE001 — runtime failure mid-timing
      result.error = '{}: {}'.format(type(e).__name__, str(e)[:300])
      result.compile_ok = False
      _log('Candidate %s failed at runtime: %s', config.config_id,
           result.error)

  ok = [r for r in results if r.compile_ok]
  # The fingerprint GOVERNS selection, not just the record: a candidate
  # that compiled to the baseline's identical program cannot beat it —
  # its timing delta is noise by construction, and caching it as the
  # winner would publish a provably inert flag as a live lever.
  base_fp = (results[0].hlo_fingerprint
             if results and results[0].compile_ok else '')
  contenders = [r for r in ok
                if r is results[0] or not base_fp
                or not r.hlo_fingerprint
                or r.hlo_fingerprint != base_fp]
  winner = min(contenders, key=lambda r: r.median_s).config \
      if contenders else None
  entry = {
      'schema_workload': workload,
      'device_kind': device_kind,
      'jax_version': jax.__version__,
      'signature_sha': key.rsplit('|', 1)[-1],
      'n_steps': n_steps,
      'reps': reps,
      'winner': (winner or candidates[0]).to_dict(),
      'winner_ok': winner is not None,
      'candidates': {r.config.config_id: r.record() for r in results},
  }
  cache.store(key, entry)
  return SweepResult(workload=workload, key=key, cache_hit=False,
                     winner=winner, results=results, entry=entry)
