"""Persistent per-workload compile-config cache.

One JSON file maps cache keys to winning configs plus the sweep evidence
that picked them. The key is the tuple that changes the compiled program
or its performance profile:

  (workload name, abstract shapes/dtypes of the step arguments,
   device_kind, jax version)

so a batch-size change, a different chip generation, or a jax upgrade
each re-tunes instead of silently applying a stale winner, while an
identical workload gets a cache HIT and never pays for the sweep again.

File writes are atomic (tmp + rename) and last-writer-wins — the cache
is advisory perf metadata, not coordination state.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Any, Dict, Optional

import numpy as np

__all__ = ['ConfigCache', 'abstract_signature', 'cache_key',
           'default_cache_path', 'CACHE_PATH_ENV']

CACHE_PATH_ENV = 'T2R_TUNING_CACHE'
CACHE_SCHEMA = 't2r.tuning.v1'


def default_cache_path() -> str:
  """$T2R_TUNING_CACHE, else ~/.cache/t2r/tuning_cache.json."""
  env = os.environ.get(CACHE_PATH_ENV)
  if env:
    return env
  return os.path.join(os.path.expanduser('~'), '.cache', 't2r',
                      'tuning_cache.json')


def _leaf_signature(leaf) -> str:
  shape = tuple(getattr(leaf, 'shape', ()) or ())
  dtype = getattr(leaf, 'dtype', None)
  dtype_name = np.dtype(dtype).name if dtype is not None else type(
      leaf).__name__
  return '{}{}'.format(dtype_name, list(shape))


def abstract_signature(args) -> str:
  """Canonical string of the step arguments' shapes/dtypes.

  ``args`` is any pytree of arrays / ShapeDtypeStructs (jax required
  only if jax types are present — plain numpy works too, so cache tests
  never need a device).
  """
  import jax

  leaves_with_paths = jax.tree_util.tree_flatten_with_path(args)[0]
  parts = []
  for path, leaf in leaves_with_paths:
    key = ''.join(str(p) for p in path)
    parts.append('{}={}'.format(key, _leaf_signature(leaf)))
  return ';'.join(parts)


def cache_key(workload: str, signature: str, device_kind: str,
              jax_version: Optional[str] = None) -> str:
  """Stable key string; the signature is hashed so keys stay readable."""
  if jax_version is None:
    import jax
    jax_version = jax.__version__
  digest = hashlib.sha1(signature.encode('utf-8')).hexdigest()[:16]
  return '{}|{}|jax-{}|{}'.format(workload, device_kind, jax_version,
                                  digest)


class ConfigCache:
  """Load/store winner entries in one JSON cache file."""

  def __init__(self, path: Optional[str] = None):
    self.path = path or default_cache_path()

  def _read_all(self) -> Dict[str, Any]:
    try:
      with open(self.path, encoding='utf-8') as f:
        data = json.load(f)
    except (OSError, ValueError):
      return {}
    if not isinstance(data, dict) or data.get('schema') != CACHE_SCHEMA:
      return {}
    entries = data.get('entries')
    return entries if isinstance(entries, dict) else {}

  def lookup(self, key: str) -> Optional[Dict[str, Any]]:
    """The stored entry for ``key`` (winner config + sweep table), or
    None — a miss, meaning this (workload, shapes, chip, jax) tuple has
    never been tuned and the caller should sweep."""
    return self._read_all().get(key)

  def store(self, key: str, entry: Dict[str, Any]) -> str:
    """Atomically merges ``{key: entry}`` into the cache file."""
    entries = self._read_all()
    entry = dict(entry)
    entry.setdefault('stored_unix_s', time.time())  # wall-clock: record
    entries[key] = entry
    payload = {'schema': CACHE_SCHEMA, 'entries': entries}
    directory = os.path.dirname(self.path) or '.'
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix='.tmp')
    try:
      with os.fdopen(fd, 'w', encoding='utf-8') as f:
        json.dump(payload, f, indent=2, sort_keys=True)
      os.replace(tmp, self.path)
    finally:
      if os.path.exists(tmp):
        os.unlink(tmp)
    return self.path
