"""Lease-based elastic membership over the PR 8 fleet-file layout.

The contract is FILES, not collectives (the same decision that made the
fleet observatory testable without ``jax.distributed``): every host in
an elastic run shares one ``base_dir`` and

  * renews a **lease** — ``lease.<host>.json``, atomically replaced —
    every ``renew_secs``; a lease whose wall-clock stamp is older than
    ``lease_ttl_secs`` has LAPSED (the host is presumed preempted or
    partitioned — the distinction from an orderly departure is the
    ``status`` field: a host that means to leave rewrites its lease as
    ``status='leaving'`` first, the same orderly-vs-dead split the
    fleet watchdog's ``host_dead`` latch draws from heartbeats);
  * reads the **world plan** — ``world_plan.json``, written only by the
    coordinator — at every checkpoint boundary. The plan is
    epoch-stamped; an epoch change is the rebuild signal (new mesh, new
    shard assignment, new trainer bound from the artifact store).

The **coordinator** is the lowest-indexed host holding a fresh active
lease. It is re-electable by construction: if host 0 dies, host 1's
``elect_coordinator`` answer changes on its next observation and it
takes over publishing (emitting an ``EVENT_COORDINATOR`` record so the
handover is visible in telemetry).

Membership changes are narrated into the shared telemetry stream as
``kind='elastic'`` records (``t2r.elastic.v1``):

  * ``join`` / ``leave``          — per-host lifecycle;
  * ``coordinator``               — a re-election;
  * ``shrink_begin``              — the coordinator declared hosts
    departed (``departed``, ``orderly``, ``world_before/after``);
  * ``shrink_phase``              — one completed rung of the shrink
    ladder (``SHRINK_PHASES``: emergency_save -> mesh_rebuild ->
    artifact_rebind), each with its measured seconds;
  * ``shrink``                    — the ladder completed and training
    resumed at the smaller world;
  * ``grow``                      — the plan re-admitted host(s) at a
    checkpoint boundary (``joined``, ``world_before/after``);
  * ``rebuild``                   — one host finished rebuilding for a
    new epoch (its artifact-store outcome + XLA-compile delta: the
    per-host zero-compile evidence).

Everything here is jax-free; wall-clock reads appear only for stamps
that cross process boundaries (leases, plans) and are annotated per the
``tests/test_no_wallclock.py`` contract.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

__all__ = ['ELASTIC_SCHEMA', 'EVENT_JOIN', 'EVENT_LEAVE',
           'EVENT_COORDINATOR', 'EVENT_SHRINK_BEGIN', 'EVENT_SHRINK_PHASE',
           'EVENT_SHRINK', 'EVENT_GROW', 'EVENT_REBUILD', 'SHRINK_PHASES',
           'ELASTIC_LAPSE_SIGNUM', 'LEASE_FILE', 'PLAN_FILE',
           'MembershipView', 'LeaseKeeper', 'write_lease', 'read_leases',
           'release_lease', 'observe', 'elect_coordinator', 'publish_plan',
           'read_plan', 'elastic_record']

ELASTIC_SCHEMA = 't2r.elastic.v1'

EVENT_JOIN = 'join'
EVENT_LEAVE = 'leave'
EVENT_COORDINATOR = 'coordinator'
EVENT_SHRINK_BEGIN = 'shrink_begin'
EVENT_SHRINK_PHASE = 'shrink_phase'
EVENT_SHRINK = 'shrink'
EVENT_GROW = 'grow'
EVENT_REBUILD = 'rebuild'

# The shrink ladder, in order. Doctor's stuck-rebuild rule names the
# FIRST rung missing after a shrink_begin as the stalled phase ('resume'
# when every rung completed but the terminal 'shrink' never landed).
SHRINK_PHASES = ('emergency_save', 'mesh_rebuild', 'artifact_rebind')

# Signum stamped into recovery records whose "signal" was a lease lapse
# observed by the coordinator (no signal was ever delivered anywhere —
# the departed host just stopped renewing). -1 is the injected
# host.preempt signum (fault_injection.INJECTED_PREEMPT_SIGNUM).
ELASTIC_LAPSE_SIGNUM = -2

LEASE_FILE = 'lease.{}.json'
PLAN_FILE = 'world_plan.json'


def lease_path(base_dir: str, host: int) -> str:
  return os.path.join(base_dir, LEASE_FILE.format(int(host)))


def plan_path(base_dir: str) -> str:
  return os.path.join(base_dir, PLAN_FILE)


def _write_atomic(path: str, payload: Dict[str, object]) -> str:
  tmp = '{}.tmp.{}'.format(path, os.getpid())
  with open(tmp, 'w', encoding='utf-8') as f:
    json.dump(payload, f)
  os.replace(tmp, path)
  return path


def _read_json(path: str) -> Optional[Dict[str, object]]:
  if not os.path.exists(path):
    return None
  try:
    with open(path, encoding='utf-8') as f:
      return json.load(f)
  except (OSError, ValueError):
    return None  # mid-replace race / torn tmp: treat as absent this read


def write_lease(base_dir: str, host: int, incarnation: int = 1,
                status: str = 'active',
                now: Optional[float] = None) -> str:
  """Atomically (re)writes one host's lease.

  ``now`` overrides the stamp — fixtures backdate it to simulate a
  lapse without waiting out a TTL.
  """
  if status not in ('active', 'leaving'):
    raise ValueError('lease status must be active|leaving; got '
                     '{!r}.'.format(status))
  os.makedirs(base_dir, exist_ok=True)
  lease = {
      'time': time.time() if now is None else float(now),  # wall-clock: cross-process freshness stamp
      'host': int(host),
      'incarnation': int(incarnation),
      'status': status,
      'pid': os.getpid(),
  }
  return _write_atomic(lease_path(base_dir, host), lease)


def release_lease(base_dir: str, host: int,
                  incarnation: int = 1) -> str:
  """Marks an ORDERLY departure: the lease flips to ``status='leaving'``.

  The file stays on disk deliberately — it is the evidence the
  coordinator (and doctor) use to classify the departure as orderly
  rather than a preemption.
  """
  return write_lease(base_dir, host, incarnation=incarnation,
                     status='leaving')


def read_leases(base_dir: str) -> Dict[int, Dict[str, object]]:
  """All readable leases under ``base_dir`` keyed by host index."""
  leases: Dict[int, Dict[str, object]] = {}
  try:
    names = sorted(os.listdir(base_dir))
  except OSError:
    return leases
  for name in names:
    if not (name.startswith('lease.') and name.endswith('.json')):
      continue
    middle = name[len('lease.'):-len('.json')]
    if not middle.isdigit():
      continue
    lease = _read_json(os.path.join(base_dir, name))
    if lease is not None:
      leases[int(middle)] = lease
  return leases


class MembershipView:
  """One observation of the lease table: who is active/leaving/lapsed."""

  def __init__(self, active: Sequence[int], leaving: Sequence[int],
               lapsed: Sequence[int],
               leases: Dict[int, Dict[str, object]]):
    self.active = tuple(sorted(int(h) for h in active))
    self.leaving = tuple(sorted(int(h) for h in leaving))
    self.lapsed = tuple(sorted(int(h) for h in lapsed))
    self.leases = dict(leases)

  @property
  def coordinator(self) -> Optional[int]:
    return self.active[0] if self.active else None

  def __repr__(self):
    return ('MembershipView(active={}, leaving={}, lapsed={})'
            .format(self.active, self.leaving, self.lapsed))


def observe(base_dir: str, lease_ttl_secs: float,
            now: Optional[float] = None) -> MembershipView:
  """Classifies every lease as active (fresh), leaving (orderly
  departure announced), or lapsed (stale while still claiming active —
  the preemption signature)."""
  if now is None:
    now = time.time()  # wall-clock: compared to cross-process lease stamps
  leases = read_leases(base_dir)
  active: List[int] = []
  leaving: List[int] = []
  lapsed: List[int] = []
  for host, lease in leases.items():
    if lease.get('status') == 'leaving':
      leaving.append(host)
    elif float(now) - float(lease.get('time', 0.0)) <= lease_ttl_secs:
      active.append(host)
    else:
      lapsed.append(host)
  return MembershipView(active, leaving, lapsed, leases)


def elect_coordinator(view: MembershipView) -> Optional[int]:
  """Lowest-indexed host with a fresh active lease (None: nobody)."""
  return view.coordinator


def publish_plan(base_dir: str, epoch: int, hosts: Sequence[int],
                 boundary_step: int = 0,
                 coordinator: Optional[int] = None) -> Dict[str, object]:
  """Atomically publishes the world plan (coordinator-only by protocol).

  ``hosts`` become the world; ``ranks`` assigns each its dense data
  rank (the native-loader shard index at this epoch).
  """
  hosts = sorted(int(h) for h in hosts)
  plan = {
      'epoch': int(epoch),
      'world_size': len(hosts),
      'hosts': hosts,
      'ranks': {str(host): rank for rank, host in enumerate(hosts)},
      'boundary_step': int(boundary_step),
      'coordinator': int(coordinator if coordinator is not None
                         else (hosts[0] if hosts else -1)),
      'time': time.time(),  # wall-clock: cross-process plan stamp
  }
  _write_atomic(plan_path(base_dir), plan)
  return plan


def read_plan(base_dir: str) -> Optional[Dict[str, object]]:
  return _read_json(plan_path(base_dir))


def plan_rank(plan: Dict[str, object], host: int) -> Optional[int]:
  rank = (plan.get('ranks') or {}).get(str(int(host)))
  return None if rank is None else int(rank)


def elastic_record(event: str, **fields) -> Dict[str, object]:
  """The ``t2r.elastic.v1`` payload for one membership event."""
  record: Dict[str, object] = {'schema': ELASTIC_SCHEMA, 'event': event}
  record.update(fields)
  return record


class LeaseKeeper:
  """Background lease renewal for one host (daemon thread).

  Renews every ``renew_secs`` until stopped; ``stop(orderly=True)``
  flips the lease to ``status='leaving'`` (the orderly-departure
  evidence), ``stop(orderly=False)`` just stops renewing — the lease
  then lapses naturally, which is how tests simulate a preemption
  without SIGKILL. Renewal pacing uses the monotonic clock (a
  wall-clock jump must not let a healthy host's lease lapse); only the
  STAMP written into the file is wall-clock.
  """

  def __init__(self, base_dir: str, host: int, renew_secs: float = 2.0,
               incarnation: Optional[int] = None):
    self.base_dir = base_dir
    self.host = int(host)
    self.renew_secs = float(renew_secs)
    if incarnation is None:
      previous = read_leases(base_dir).get(self.host)
      incarnation = int((previous or {}).get('incarnation', 0)) + 1
    self.incarnation = int(incarnation)
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None

  def start(self) -> 'LeaseKeeper':
    write_lease(self.base_dir, self.host, incarnation=self.incarnation)
    self._thread = threading.Thread(target=self._run, daemon=True,
                                    name='lease-keeper-{}'.format(self.host))
    self._thread.start()
    return self

  def _run(self) -> None:
    next_renew = time.monotonic() + self.renew_secs
    while not self._stop.wait(timeout=max(next_renew - time.monotonic(),
                                          0.05)):
      next_renew = time.monotonic() + self.renew_secs
      try:
        write_lease(self.base_dir, self.host,
                    incarnation=self.incarnation)
      except OSError:
        pass  # transient filesystem blip: the next renewal retries

  def stop(self, orderly: bool = True) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None
    if orderly:
      try:
        release_lease(self.base_dir, self.host,
                      incarnation=self.incarnation)
      except OSError:
        pass
