"""ElasticTrainer: the coordinator-led supervisor that survives resizes.

One ``ElasticTrainer`` drives ONE host's participation in an elastic
run. All hosts share a ``base_dir`` holding the membership leases, the
world plan, the per-host telemetry streams (``telemetry.<i>.jsonl``,
the PR 8 fleet layout), the shared compile cache + ``CompiledArtifact``
store, and one checkpoint tree per host (``base_dir/host<i>``). The
run proceeds in **boundary segments** (``boundary_steps`` trained steps
per segment, each ending in a committed checkpoint — the checkpoint
boundary every membership decision lands on):

  1. join: write a lease (``membership.LeaseKeeper`` renews it in the
     background), wait for the coordinator's world plan to admit us;
  2. build: realize the plan's mesh (``topology.build_mesh``), stand up
     a ``Trainer`` whose train step binds through the shared
     ``CompiledArtifact`` store (epoch > 1 deserializes what epoch 1
     persisted — the zero-compile rebuild), restore the newest local
     checkpoint (or bootstrap from a peer's on first join), and run a
     one-step probe that closes any pending recovery timeline;
  3. train a segment; at the boundary the COORDINATOR (lowest active
     lease, re-electable) compares the plan against the lease table:

       * a member whose lease LAPSED while still ``active`` was
         preempted -> **shrink**: emergency save, a ``t2r.recovery.v1``
         marker (the rebuilt trainer's first step closes the timeline,
         now carrying ``world_before``/``world_after``), a new plan at
         world N-1, and the ``shrink_begin -> shrink_phase* -> shrink``
         event ladder every survivor's rebuild is narrated through;
       * a member that flipped its lease to ``leaving`` departed
         ORDERLY -> the same shrink ladder, no recovery record (there
         was no outage) — and the doctor must NOT page host_dead for
         it (the shrink event is its alibi);
       * a fresh lease outside the plan is a joiner -> **grow** at this
         boundary: a new plan at world N+1; every host rebuilds into
         the larger world (another store hit — growing compiles
         nothing either).

  4. every host re-reads the plan at each boundary and rebuilds
     whenever the epoch moved; otherwise it just keeps training.

The CLI form (``python -m tensor2robot_tpu.elastic.driver``) is what
the subprocess federation runs (tests/test_elastic.py, the MULTICHIP
elastic phase via :mod:`~tensor2robot_tpu.elastic.axes`): each host is
a real OS process with its own jax runtime, sharing only the filesystem
— the same harness discipline as ``observability/fleet_sim.py``, with
real training inside.
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Any, Callable, Dict, Optional

from tensor2robot_tpu.elastic import membership
from tensor2robot_tpu.elastic import topology
from tensor2robot_tpu.reliability import fault_injection

__all__ = ['ElasticConfig', 'ElasticTrainer', 'maybe_stall_rebuild',
           'main']


def maybe_stall_rebuild() -> float:
  """The ``elastic.rebuild`` fault site: seconds THIS rebuild stalls.

  A wedged mesh rebuild (hung device init, a peer stuck in a barrier)
  is the elastic failure mode the doctor's stuck-rebuild rule pages on;
  this site stages it deterministically (docs/reliability.md).
  """
  seconds = fault_injection.elastic_rebuild_stall_seconds()
  if seconds > 0.0:
    time.sleep(seconds)
  return seconds


class ElasticConfig:
  """Knobs of one elastic run (shared by every host of the run).

  ``lease_ttl_secs`` must comfortably exceed ``renew_secs`` plus the
  longest boundary segment, or a merely-slow host reads as preempted.
  ``boundary_steps`` is both the segment length and the checkpoint
  cadence — membership changes only land on these boundaries.
  ``stop_file`` names a file whose appearance asks every host to leave
  orderly (how the test harness ends an open-ended run).
  """

  def __init__(self,
               target_world: int,
               min_world: Optional[int] = None,
               lease_ttl_secs: float = 6.0,
               renew_secs: float = 1.0,
               boundary_steps: int = 2,
               poll_secs: float = 0.25,
               max_run_seconds: float = 300.0,
               per_host_batch: int = 8,
               use_fsdp: bool = True,
               stop_file: Optional[str] = None,
               use_compiled_artifacts: bool = True,
               artifact_workload: str = 'elastic_step'):
    self.target_world = int(target_world)
    self.min_world = int(min_world if min_world is not None
                         else target_world)
    self.lease_ttl_secs = float(lease_ttl_secs)
    self.renew_secs = float(renew_secs)
    self.boundary_steps = max(1, int(boundary_steps))
    self.poll_secs = float(poll_secs)
    self.max_run_seconds = float(max_run_seconds)
    self.per_host_batch = int(per_host_batch)
    self.use_fsdp = bool(use_fsdp)
    self.stop_file = stop_file
    self.use_compiled_artifacts = bool(use_compiled_artifacts)
    self.artifact_workload = artifact_workload


class ElasticTrainer:
  """One host's elastic supervisor (see module docstring).

  ``model_factory``/``generator_factory`` are zero-arg callables so the
  heavy objects are built only once jax is configured;
  ``trainer_kwargs`` forwards extra knobs into every per-epoch Trainer.
  """

  def __init__(self,
               model_factory: Callable[[], Any],
               generator_factory: Callable[[], Any],
               base_dir: str,
               host: int,
               config: ElasticConfig,
               trainer_kwargs: Optional[Dict[str, Any]] = None):
    self.model_factory = model_factory
    self.generator_factory = generator_factory
    self.base_dir = base_dir
    self.host = int(host)
    self.config = config
    self.trainer_kwargs = dict(trainer_kwargs or {})
    self.host_dir = os.path.join(base_dir, 'host{}'.format(self.host))
    self.preempted = False
    self._telemetry = None
    self._identity: Optional[Dict[str, object]] = None
    self._model = None
    self._generator = None
    self._pending_shrink: Optional[Dict[str, object]] = None
    self._mesh_plan: Optional[topology.MeshPlan] = None
    self._announced_coordinator = False

  # -- shared lazy state -----------------------------------------------------

  @property
  def identity(self) -> Dict[str, object]:
    """This host's fleet identity: the ELASTIC coordinates, not jax's.

    Each simulated host is its own jax world (``jax.process_index()``
    is 0 everywhere on the CPU federation), so the elastic host index /
    target world REPLACE the jax coordinates in the telemetry stamp —
    which is exactly what routes each host to its own
    ``telemetry.<host>.jsonl`` under the shared base_dir.
    """
    if self._identity is None:
      from tensor2robot_tpu.observability import signals as signals_lib
      identity = signals_lib.host_identity()
      identity['process_index'] = self.host
      identity['process_count'] = max(self.config.target_world, 2)
      self._identity = identity
    return self._identity

  @property
  def telemetry(self):
    if self._telemetry is None:
      from tensor2robot_tpu.observability import TelemetryLogger
      self._telemetry = TelemetryLogger(self.base_dir,
                                        host_meta=self.identity)
    return self._telemetry

  def _log_event(self, event: str, step: int, **fields) -> None:
    record = membership.elastic_record(event, host=self.host, **fields)
    self.telemetry.log('elastic', step=step, **record)
    self.telemetry.flush()

  def _stop_requested(self) -> bool:
    return bool(self.config.stop_file
                and os.path.exists(self.config.stop_file))

  def _make_plan(self, plan: Dict[str, object]) -> topology.MeshPlan:
    import jax
    return topology.plan_mesh(
        int(plan['world_size']), len(jax.local_devices()),
        self.config.per_host_batch, use_fsdp=self.config.use_fsdp,
        epoch=int(plan['epoch']), hosts=plan['hosts'])

  # -- coordinator duties ----------------------------------------------------

  def _coordinate(self, view: membership.MembershipView,
                  plan: Optional[Dict[str, object]], step: int,
                  trainer, state) -> Optional[Dict[str, object]]:
    """One boundary's coordinator pass: publish/adjust the world plan."""
    if plan is None:
      if len(view.active) < self.config.min_world:
        return None
      plan = membership.publish_plan(self.base_dir, 1, view.active,
                                     boundary_step=step,
                                     coordinator=self.host)
      self._log_event(membership.EVENT_GROW, step, epoch=1,
                      world_before=0, world_after=len(view.active),
                      joined=list(view.active))
      return plan
    if int(plan.get('coordinator', -1)) != self.host \
        and not self._announced_coordinator:
      # Re-election: the planned coordinator's lease is no longer the
      # lowest active one (it died or left) — announce the handover
      # once; the shrink that removes it follows below.
      self._announced_coordinator = True
      self._log_event(membership.EVENT_COORDINATOR, step,
                      previous=plan.get('coordinator'))
    members = set(int(h) for h in plan.get('hosts') or [])
    lapsed = sorted(members & set(view.lapsed))
    leaving = sorted(members & set(view.leaving))
    joiners = sorted(set(view.active) - members)
    if lapsed or leaving:
      return self._declare_shrink(view, plan, step, lapsed, leaving,
                                  trainer, state)
    if joiners:
      epoch = int(plan['epoch']) + 1
      hosts = sorted(members | set(joiners))
      new_plan = membership.publish_plan(self.base_dir, epoch, hosts,
                                         boundary_step=step,
                                         coordinator=self.host)
      old = self._mesh_plan or self._make_plan(plan)
      self._log_event(
          membership.EVENT_GROW, step, epoch=epoch,
          world_before=len(members), world_after=len(hosts),
          joined=joiners,
          reshard=topology.reshard_plan(old, self._make_plan(new_plan)))
      return new_plan
    return plan

  def _declare_shrink(self, view: membership.MembershipView,
                      plan: Dict[str, object], step: int,
                      lapsed, leaving, trainer, state
                      ) -> Dict[str, object]:
    """The shrink ladder's coordinator half: save -> marker -> new plan.

    The remaining rungs (mesh_rebuild, artifact_rebind, the terminal
    ``shrink`` event and — for a preemption — the recovery record) land
    in ``_rebuild``, which every survivor runs when it sees the new
    epoch; only the coordinator narrates them.
    """
    from tensor2robot_tpu.observability import fleet as fleet_lib

    departed = sorted(set(lapsed) | set(leaving))
    orderly = not lapsed
    members = [int(h) for h in plan.get('hosts') or []]
    world_before = len(members)
    # Survivors = plan members minus the departed, PLUS every host with
    # a fresh active lease: a coordinator re-elected from outside the
    # plan (the old one died before admitting it) and any joiner racing
    # the shrink fold in here instead of being orphaned — and the world
    # can never shrink to zero while someone is alive to declare it.
    survivors = sorted((set(members) - set(departed)) | set(view.active))
    epoch = int(plan['epoch']) + 1
    self._log_event(membership.EVENT_SHRINK_BEGIN, step, epoch=epoch,
                    world_before=world_before,
                    world_after=len(survivors), departed=departed,
                    orderly=orderly, lapsed=lapsed, leaving=leaving)
    save_t0 = time.perf_counter()
    if trainer is not None and state is not None:
      try:
        trainer.save_checkpoint(state, force=True)
        trainer.checkpoint_manager.wait_until_finished()
      except Exception as e:  # noqa: BLE001 — a failed extra save must
        # not kill the shrink: the boundary checkpoint already committed.
        self._log_event(membership.EVENT_SHRINK_PHASE, step, epoch=epoch,
                        phase='emergency_save', error=str(e))
    save_s = time.perf_counter() - save_t0
    self._log_event(membership.EVENT_SHRINK_PHASE, step, epoch=epoch,
                    phase='emergency_save', seconds=save_s)
    if not orderly:
      # The preemption timeline: the marker the REBUILT trainer consumes
      # at its first completed step, closing t2r.recovery.v1 with
      # phases that sum to the outage — now carrying the world change.
      fleet_lib.write_recovery_marker(
          self.host_dir, step, membership.ELASTIC_LAPSE_SIGNUM, save_s,
          process_index=self.host, world_before=world_before,
          world_after=len(survivors), departed=departed, elastic=True)
    new_plan = membership.publish_plan(self.base_dir, epoch, survivors,
                                       boundary_step=step,
                                       coordinator=self.host)
    old = self._mesh_plan or self._make_plan(plan)
    self._pending_shrink = {
        'epoch': epoch, 'world_before': world_before,
        'world_after': len(survivors), 'departed': departed,
        'orderly': orderly,
        'reshard': topology.reshard_plan(old, self._make_plan(new_plan)),
    }
    return new_plan

  # -- build/rebuild ---------------------------------------------------------

  def _bootstrap_state(self, trainer, plan: Dict[str, object]):
    """First-join bootstrap: restore a PEER's checkpoint into MY tree.

    The checkpoint-resharding story made concrete: a checkpoint written
    at world N (under the old mesh) restores through a template built
    on THIS epoch's mesh — Orbax lays the unchanged global arrays onto
    the new device set. Returns a TrainState, or None when there is
    nothing to bootstrap from (a genuinely fresh run) or a local
    checkpoint already exists (the normal restore path handles it).
    """
    if trainer.checkpoint_manager.all_steps():
      return None
    peers = [int(h) for h in plan.get('hosts') or []
             if int(h) != self.host]
    source = None
    for peer in sorted(peers):
      peer_dir = os.path.join(self.base_dir, 'host{}'.format(peer))
      if os.path.isdir(peer_dir):
        source = peer_dir
        break
    if source is None:
      return None
    try:
      from tensor2robot_tpu.trainer import Trainer
      from tensor2robot_tpu.trainer.train_eval import (
          provide_input_generator_with_model_information,
      )
      from tensor2robot_tpu.modes import ModeKeys

      generator = provide_input_generator_with_model_information(
          self._generator, self._model, ModeKeys.TRAIN)
      features, labels = next(generator.create_dataset_iterator(
          mode=ModeKeys.TRAIN))
      # A read-only probe trainer over the PEER's tree: same model, THIS
      # epoch's mesh, no quarantine, no writers.
      probe = Trainer(self._model, source, mesh=trainer.mesh,
                      use_fsdp=trainer.use_fsdp, async_checkpoints=False,
                      write_metrics=False, owns_checkpoint_dir=False,
                      enable_fleet=False, auto_profile=False,
                      save_checkpoints_steps=10**9,
                      log_every_n_steps=10**9)
      try:
        if not probe.checkpoint_manager.all_steps():
          return None
        state = probe.init_state(features, labels)
      finally:
        probe.close()
      return state
    except Exception as e:  # noqa: BLE001 — bootstrap is best-effort: a
      # fresh init is always a valid (if colder) join.
      self._log_event(membership.EVENT_REBUILD, 0,
                      epoch=int(plan['epoch']), bootstrap_error=str(e))
      return None

  def _rebuild(self, plan: Dict[str, object], old_trainer, registry):
    """Mesh + trainer rebuild for a new plan epoch, plus the one-step
    probe that binds the artifact store and closes any pending recovery
    timeline. Returns ``(trainer, state)``."""
    import jax

    from tensor2robot_tpu.trainer import Trainer

    shrink = self._pending_shrink
    epoch = int(plan['epoch'])
    if old_trainer is not None:
      old_trainer.close()
    rebuild_t0 = time.perf_counter()
    maybe_stall_rebuild()
    mesh_plan = self._make_plan(plan)
    self._mesh_plan = mesh_plan
    mesh = topology.build_mesh(mesh_plan)
    kwargs = dict(
        mesh=mesh, use_fsdp=mesh_plan.use_fsdp, async_checkpoints=False,
        save_checkpoints_steps=10**9,
        log_every_n_steps=self.config.boundary_steps,
        enable_fleet=False, auto_profile=False,
        use_compiled_artifacts=self.config.use_compiled_artifacts,
        artifact_workload=self.config.artifact_workload,
        tuning_cache_path=os.path.join(self.base_dir,
                                       'compile_cache.json'),
        shared_telemetry=self.telemetry,
        host_identity=self.identity)
    kwargs.update(self.trainer_kwargs)
    trainer = Trainer(self._model, self.host_dir, **kwargs)
    rebuild_s = time.perf_counter() - rebuild_t0
    if shrink is not None:
      self._log_event(membership.EVENT_SHRINK_PHASE, 0,
                      epoch=shrink['epoch'], phase='mesh_rebuild',
                      seconds=rebuild_s)
    state = self._bootstrap_state(trainer, plan)
    # One-step probe: binds the train step through the artifact store
    # (epoch > 1 must deserialize — the zero-compile rebuild) and, on
    # the coordinator's preemption path, consumes the recovery marker so
    # the t2r.recovery.v1 record closes on a genuinely trained step.
    rank, world = topology.shard_assignment(mesh_plan, self.host)
    latest = trainer.checkpoint_manager.latest_step()
    if state is not None:
      latest = int(jax.device_get(state.step))
    start = int(latest or 0)
    compiles_before = float(
        registry.scalars().get('jax/compiles', 0.0))
    state = trainer.train(self._generator, max_train_steps=start + 1,
                          state=state, shard_index=rank,
                          num_shards=world)
    compiles_delta = float(
        registry.scalars().get('jax/compiles', 0.0)) - compiles_before
    artifact = getattr(trainer, '_train_step_artifact', None)
    outcome = 'none'
    if artifact is not None:
      outcome = 'hit' if getattr(artifact, 'from_cache', False) else 'miss'
    step = int(jax.device_get(state.step))
    self._log_event(membership.EVENT_REBUILD, step, epoch=epoch,
                    world_size=mesh_plan.world_size, rank=rank,
                    artifact_outcome=outcome,
                    compiles_delta=compiles_delta)
    if shrink is not None:
      self._log_event(membership.EVENT_SHRINK_PHASE, step,
                      epoch=shrink['epoch'], phase='artifact_rebind',
                      artifact_outcome=outcome,
                      compiles_delta=compiles_delta)
      recovery_s = None
      if not shrink.get('orderly'):
        from tensor2robot_tpu.observability import fleet as fleet_lib
        value = registry.gauge(fleet_lib.RECOVERY_GAUGE).value
        recovery_s = value if value > 0.0 else None
      self._log_event(membership.EVENT_SHRINK, step, **dict(
          shrink, recovery_seconds=recovery_s))
      self._pending_shrink = None
    return trainer, state

  # -- the run ---------------------------------------------------------------

  def run(self, total_steps: int):
    """Participates until ``total_steps``, a stop request, preemption,
    or ``max_run_seconds``; returns the last trained step."""
    import jax

    from tensor2robot_tpu.observability import get_registry
    from tensor2robot_tpu.reliability.errors import TrainingPreempted

    config = self.config
    registry = get_registry()
    deadline = time.monotonic() + config.max_run_seconds
    self._model = self.model_factory()
    self._generator = self.generator_factory()
    # A previous incarnation that died through the injected host.preempt
    # path left its own recovery marker behind. In an elastic run the
    # COORDINATOR's shrink record is the one t2r.recovery.v1 account of
    # that outage — consuming the stale marker here keeps "exactly one
    # record per preemption" true across the victim's rejoin.
    from tensor2robot_tpu.observability import fleet as fleet_lib
    fleet_lib.consume_recovery_marker(self.host_dir,
                                      process_index=self.host)
    keeper = membership.LeaseKeeper(self.base_dir, self.host,
                                    renew_secs=config.renew_secs)
    keeper.start()
    self._log_event(membership.EVENT_JOIN, 0,
                    incarnation=keeper.incarnation,
                    target_world=config.target_world)
    trainer = None
    state = None
    built_epoch = None
    step = 0
    try:
      while time.monotonic() < deadline:
        if self._stop_requested():
          break
        view = membership.observe(self.base_dir, config.lease_ttl_secs)
        plan = membership.read_plan(self.base_dir)
        if membership.elect_coordinator(view) == self.host:
          plan = self._coordinate(view, plan, step, trainer, state)
        if plan is None or self.host not in [
            int(h) for h in plan.get('hosts') or []]:
          time.sleep(config.poll_secs)
          continue
        if built_epoch != int(plan['epoch']):
          trainer, state = self._rebuild(plan, trainer, registry)
          built_epoch = int(plan['epoch'])
          step = int(jax.device_get(state.step))
          continue  # fresh boundary: re-observe before the next segment
        if step >= total_steps:
          break
        boundary = config.boundary_steps
        target = min((step // boundary + 1) * boundary, total_steps)
        rank, world = topology.shard_assignment(self._mesh_plan,
                                                self.host)
        state = trainer.train(self._generator, max_train_steps=target,
                              state=state, shard_index=rank,
                              num_shards=world)
        step = int(jax.device_get(state.step))
    except TrainingPreempted:
      # The injected host.preempt path: die like a preempted host —
      # no orderly leave, the lease lapses, the coordinator shrinks.
      self.preempted = True
    finally:
      keeper.stop(orderly=not self.preempted)
      if not self.preempted:
        self._log_event(membership.EVENT_LEAVE, step,
                        incarnation=keeper.incarnation)
      if trainer is not None:
        trainer.close()
      if self._telemetry is not None:
        self._telemetry.close()
    return step


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--base_dir', required=True)
  parser.add_argument('--host', type=int, required=True)
  parser.add_argument('--world', type=int, default=3,
                      help='target world size (min_world defaults to it)')
  parser.add_argument('--min_world', type=int, default=None)
  parser.add_argument('--total_steps', type=int, default=10**6)
  parser.add_argument('--boundary_steps', type=int, default=2)
  parser.add_argument('--per_host_batch', type=int, default=8)
  parser.add_argument('--local_device_count', type=int, default=4)
  parser.add_argument('--lease_ttl_secs', type=float, default=6.0)
  parser.add_argument('--renew_secs', type=float, default=1.0)
  parser.add_argument('--max_run_seconds', type=float, default=300.0)
  parser.add_argument('--stop_file', default=None)
  parser.add_argument('--no_fsdp', action='store_true')
  parser.add_argument('--no_artifacts', action='store_true')
  parser.add_argument('--inject_preempt_after', type=int, default=None,
                      help='arm the host.preempt FaultInjector site to '
                      'fire after N trainer-loop passes (the injected '
                      'alternative to SIGKILL)')
  parser.add_argument('--rebuild_stall_secs', type=float, default=None,
                      help='arm the elastic.rebuild site with this '
                      'stall on the next rebuild')
  args = parser.parse_args(argv)

  # Device virtualization + platform pinning BEFORE the first jax import
  # (the multihost.py / conftest discipline).
  os.environ['JAX_PLATFORMS'] = 'cpu'
  flags = os.environ.get('XLA_FLAGS', '')
  if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count={}'.format(
            args.local_device_count)).strip()

  injector = None
  if args.inject_preempt_after is not None:
    injector = fault_injection.FaultInjector().fail(
        fault_injection.SITE_HOST_PREEMPT, times=1,
        after=args.inject_preempt_after)
  if args.rebuild_stall_secs is not None:
    fault_injection.ELASTIC_REBUILD_STALL_SECONDS = args.rebuild_stall_secs
    injector = (injector or fault_injection.FaultInjector()).fail(
        fault_injection.SITE_ELASTIC_REBUILD, times=1)
  if injector is not None:
    fault_injection.set_injector(injector)

  def model_factory():
    from tensor2robot_tpu.utils.mocks import MockT2RModel
    return MockT2RModel(device_type='cpu')

  def generator_factory():
    from tensor2robot_tpu.utils.mocks import MockInputGenerator
    return MockInputGenerator(batch_size=args.per_host_batch)

  config = ElasticConfig(
      target_world=args.world, min_world=args.min_world,
      lease_ttl_secs=args.lease_ttl_secs, renew_secs=args.renew_secs,
      boundary_steps=args.boundary_steps,
      max_run_seconds=args.max_run_seconds,
      per_host_batch=args.per_host_batch, use_fsdp=not args.no_fsdp,
      stop_file=args.stop_file,
      use_compiled_artifacts=not args.no_artifacts)
  elastic = ElasticTrainer(model_factory, generator_factory,
                           args.base_dir, args.host, config)
  step = elastic.run(args.total_steps)
  print('elastic host {} done at step {}{}'.format(
      args.host, step, ' (preempted)' if elastic.preempted else ''))
  return 0


if __name__ == '__main__':
  import sys
  sys.exit(main())
