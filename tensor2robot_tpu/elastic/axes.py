"""Elastic fleet orchestration + the ELASTIC bench axes (jax-free).

The elastic contract is proved the way the fleet observatory's was
(``observability/fleet_sim.py``): REAL OS processes sharing one
filesystem. ``run_elastic_fleet`` spawns N ``elastic.driver`` hosts
(each its own jax runtime on virtual CPU devices), lets them train,
SIGKILLs one mid-run (the preemption no marker ever narrates — the
lease lapse is the only evidence), waits for the coordinator's shrink +
``t2r.recovery.v1`` record, relaunches the victim, waits for the grow
back to N, and stops the run through the driver's stop-file. The same
harness backs tests/test_elastic.py's CPU acceptance run and the
MULTICHIP elastic phase (``__graft_entry__``), so the bench axes and
the test assertions are computed from identical evidence.

``collect_axes`` digests the shared base_dir's merged telemetry into
the ``ELASTIC_BENCH_KEYS`` schema the MULTICHIP artifact publishes
(host-count scaling curve + shrink/recovery axis), locked by
``bin/check_elastic_doctor``.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from tensor2robot_tpu.elastic import membership

__all__ = ['ELASTIC_BENCH_KEYS', 'collect_axes', 'run_elastic_fleet']

# The MULTICHIP elastic axes (schema-locked in bin/check_elastic_doctor):
#   elastic_hosts              peak world size observed
#   elastic_world_curve        {world_size: aggregate examples/sec} —
#                              the host-count scaling curve
#   elastic_world_before/after the shrink's world change (t2r.recovery.v1)
#   elastic_regrow_world       world size after the last grow
#   elastic_recovery_seconds   preemption_recovery_seconds of the shrink
#   elastic_recovery_phases    its phase split (sums to the total)
#   elastic_surviving_compiles XLA compiles across every epoch>1 WARM
#                              rebuild — rebuilds by hosts already
#                              training (each incarnation's first
#                              rebuild is a process cold start and
#                              excluded); 0 when the artifact store
#                              serves every survivor
#   elastic_rebind_outcomes    per-rebuild artifact outcomes ('hit'/'miss')
#   elastic_shrinks/_grows     completed ladder counts
ELASTIC_BENCH_KEYS = (
    'elastic_hosts',
    'elastic_world_curve',
    'elastic_world_before',
    'elastic_world_after',
    'elastic_regrow_world',
    'elastic_recovery_seconds',
    'elastic_recovery_phases',
    'elastic_surviving_compiles',
    'elastic_rebind_outcomes',
    'elastic_shrinks',
    'elastic_grows',
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _merged_records(base_dir: str) -> List[Dict[str, object]]:
  from tensor2robot_tpu.observability import fleet as fleet_lib
  try:
    return fleet_lib.merged_records(fleet_lib.read_fleet(base_dir))
  except OSError:
    return []


def collect_axes(base_dir: str) -> Dict[str, object]:
  """Digests one elastic run's shared dir into ELASTIC_BENCH_KEYS."""
  records = _merged_records(base_dir)
  elastic = [r for r in records if r.get('kind') == 'elastic']
  trains = [r for r in records if r.get('kind') == 'train']
  recoveries = [r for r in records if r.get('kind') == 'recovery'
                and r.get('world_before') is not None]

  # World timeline: each grow/shrink_begin sets the world from its
  # wall-clock stamp onward (plan publish and the event share the stamp
  # to within a write).
  timeline: List[Tuple[float, int]] = []
  for record in elastic:
    event = record.get('event')
    if event in (membership.EVENT_GROW, membership.EVENT_SHRINK_BEGIN):
      timeline.append((float(record.get('time', 0.0)),
                       int(record.get('world_after') or 0)))
  timeline.sort(key=lambda entry: entry[0])

  def world_at(stamp: float) -> Optional[int]:
    current = None
    for at, world in timeline:
      if at <= stamp:
        current = world
      else:
        break
    return current

  # Scaling curve: per world size, sum over hosts of that host's mean
  # examples/sec while the world held that size — the aggregate rate
  # the fleet actually delivered at each world.
  per_world_host: Dict[int, Dict[int, List[float]]] = {}
  for record in trains:
    rate = record.get('examples_per_sec')
    world = world_at(float(record.get('time', 0.0)))
    if not rate or not world:
      continue
    host = int(record.get('process_index') or 0)
    per_world_host.setdefault(world, {}).setdefault(host, []).append(
        float(rate))
  curve = {
      str(world): round(sum(sum(rates) / len(rates)
                            for rates in hosts.values()), 2)
      for world, hosts in sorted(per_world_host.items())}

  rebuilds = [r for r in elastic
              if r.get('event') == membership.EVENT_REBUILD
              and int(r.get('epoch') or 0) > 1]
  # Surviving-host rebuilds only: each incarnation's FIRST rebuild is a
  # process cold start (a rejoiner pays device-init/transfer compiles
  # even when its train step deserializes), so per host a 'join' resets
  # the warm flag and the next rebuild is excluded. What remains is the
  # zero-compile claim that matters: a host that was already training
  # rebuilds into the new world without compiling anything.
  warm_rebuilds = []
  warm: Dict[int, bool] = {}
  for record in sorted(elastic, key=lambda r: float(r.get('time', 0.0))):
    host = int(record.get('host', record.get('process_index')) or 0)
    event = record.get('event')
    if event == membership.EVENT_JOIN:
      warm[host] = False
    elif event == membership.EVENT_REBUILD:
      if warm.get(host) and int(record.get('epoch') or 0) > 1:
        warm_rebuilds.append(record)
      warm[host] = True
  recovery = recoveries[-1] if recoveries else {}
  grows = [r for r in elastic if r.get('event') == membership.EVENT_GROW]
  return {
      'elastic_hosts': max([int(w) for _, w in timeline] or [0]),
      'elastic_world_curve': curve,
      'elastic_world_before': recovery.get('world_before'),
      'elastic_world_after': recovery.get('world_after'),
      'elastic_regrow_world': (int(grows[-1].get('world_after') or 0)
                               if grows else None),
      'elastic_recovery_seconds': recovery.get(
          'preemption_recovery_seconds'),
      'elastic_recovery_phases': recovery.get('phases'),
      'elastic_surviving_compiles': sum(
          float(r.get('compiles_delta') or 0.0) for r in warm_rebuilds),
      'elastic_rebind_outcomes': [str(r.get('artifact_outcome'))
                                  for r in rebuilds],
      'elastic_shrinks': sum(
          1 for r in elastic if r.get('event') == membership.EVENT_SHRINK),
      'elastic_grows': len(grows),
  }


def _subprocess_env() -> Dict[str, str]:
  env = dict(os.environ)
  env.pop('PYTHONPATH', None)  # strip the axon TPU plugin sitecustomize
  env['JAX_PLATFORMS'] = 'cpu'
  env.pop('XLA_FLAGS', None)  # the driver sets its own device count
  return env


def launch_host(base_dir: str, host: int, world: int,
                local_device_count: int = 2, boundary_steps: int = 2,
                per_host_batch: int = 8, lease_ttl_secs: float = 4.0,
                renew_secs: float = 0.5, max_run_seconds: float = 240.0,
                extra_args: Tuple[str, ...] = ()) -> subprocess.Popen:
  """One elastic driver subprocess; stdout -> base_dir/driver.<host>.log."""
  os.makedirs(base_dir, exist_ok=True)
  log = open(os.path.join(base_dir, 'driver.{}.log'.format(host)), 'a')
  cmd = [sys.executable, '-m', 'tensor2robot_tpu.elastic.driver',
         '--base_dir', base_dir, '--host', str(host),
         '--world', str(world),
         '--local_device_count', str(local_device_count),
         '--boundary_steps', str(boundary_steps),
         '--per_host_batch', str(per_host_batch),
         '--lease_ttl_secs', str(lease_ttl_secs),
         '--renew_secs', str(renew_secs),
         '--max_run_seconds', str(max_run_seconds),
         '--stop_file', os.path.join(base_dir, 'STOP')]
  cmd.extend(extra_args)
  proc = subprocess.Popen(cmd, cwd=_REPO_ROOT, env=_subprocess_env(),
                          stdout=log, stderr=subprocess.STDOUT)
  proc._t2r_log = log  # keep the handle alive with the process
  return proc


def _wait_for(predicate: Callable[[], bool], timeout: float,
              what: str, poll_secs: float = 0.5) -> None:
  deadline = time.monotonic() + timeout
  while time.monotonic() < deadline:
    if predicate():
      return
    time.sleep(poll_secs)
  raise TimeoutError('elastic fleet: timed out waiting for ' + what)


def _host_max_step(records, host: int) -> int:
  steps = [int(r.get('step') or 0) for r in records
           if r.get('kind') == 'train'
           and int(r.get('process_index') or 0) == int(host)]
  return max(steps) if steps else -1


def run_elastic_fleet(base_dir: str, hosts: int = 3, kill_host: int = 1,
                      local_device_count: int = 2,
                      boundary_steps: int = 2, per_host_batch: int = 8,
                      lease_ttl_secs: float = 4.0,
                      renew_secs: float = 0.5,
                      kill_after_step: int = 2,
                      settle_boundaries: int = 2,
                      phase_timeout: float = 150.0
                      ) -> Dict[str, object]:
  """The full shrink-then-grow acceptance run (see module docstring).

  Returns ``{'axes': ELASTIC_BENCH_KEYS dict, 'pre_preempt_step',
  'post_resume_steps', 'exit_codes'}``. Raises TimeoutError when any
  phase fails to materialize — with every driver log left under
  ``base_dir/driver.<i>.log`` for the post-mortem.
  """
  stop_file = os.path.join(base_dir, 'STOP')
  survivors = [h for h in range(hosts) if h != kill_host]

  def spawn(host: int) -> subprocess.Popen:
    return launch_host(
        base_dir, host, hosts, local_device_count=local_device_count,
        boundary_steps=boundary_steps, per_host_batch=per_host_batch,
        lease_ttl_secs=lease_ttl_secs, renew_secs=renew_secs)

  procs = {host: spawn(host) for host in range(hosts)}
  rejoined = None
  try:
    _wait_for(
        lambda: all(_host_max_step(_merged_records(base_dir), h)
                    >= kill_after_step for h in range(hosts)),
        phase_timeout, 'all {} hosts to pass step {}'.format(
            hosts, kill_after_step))
    records = _merged_records(base_dir)
    pre_step = max(_host_max_step(records, h) for h in range(hosts))

    # The preemption: SIGKILL writes nothing anywhere — the lease lapse
    # is the only way the fleet can learn this host is gone.
    procs[kill_host].send_signal(signal.SIGKILL)
    procs[kill_host].wait(timeout=30)

    def shrunk() -> bool:
      recs = _merged_records(base_dir)
      return any(r.get('kind') == 'recovery'
                 and r.get('world_after') == hosts - 1 for r in recs)
    _wait_for(shrunk, phase_timeout + lease_ttl_secs,
              'the shrink recovery record (world {} -> {})'.format(
                  hosts, hosts - 1))
    _wait_for(
        lambda: all(_host_max_step(_merged_records(base_dir), h)
                    > pre_step for h in survivors),
        phase_timeout, 'survivors to resume past step {}'.format(pre_step))

    # Rejoin: a fresh incarnation of the killed host.
    rejoined = spawn(kill_host)

    def regrown() -> bool:
      recs = _merged_records(base_dir)
      grow = [r for r in recs if r.get('kind') == 'elastic'
              and r.get('event') == membership.EVENT_GROW
              and int(r.get('world_after') or 0) == hosts
              and int(r.get('epoch') or 0) > 1]
      if not grow:
        return False
      # The rejoined host must have REBUILT into the grown world and
      # trained (its rebuild event names the grow's epoch or later).
      epoch = max(int(r.get('epoch') or 0) for r in grow)
      return any(r.get('kind') == 'elastic'
                 and r.get('event') == membership.EVENT_REBUILD
                 and int(r.get('process_index') or -1) == kill_host
                 and int(r.get('epoch') or 0) >= epoch for r in recs)
    _wait_for(regrown, phase_timeout,
              'the grow back to world {}'.format(hosts))
    records = _merged_records(base_dir)
    resume_floor = max(_host_max_step(records, h) for h in survivors)
    _wait_for(
        lambda: all(
            _host_max_step(_merged_records(base_dir), h)
            >= resume_floor + settle_boundaries * boundary_steps
            for h in survivors),
        phase_timeout, 'post-grow settling')

    with open(stop_file, 'w') as f:
      f.write('stop\n')
    exit_codes = {}
    for host, proc in list(procs.items()) + [(kill_host, rejoined)]:
      if host == kill_host and proc is procs.get(kill_host):
        continue  # the SIGKILLed incarnation already reaped
      try:
        exit_codes[host] = proc.wait(timeout=90)
      except subprocess.TimeoutExpired:
        proc.kill()
        exit_codes[host] = 'timeout'
    records = _merged_records(base_dir)
    return {
        'axes': collect_axes(base_dir),
        'pre_preempt_step': pre_step,
        'post_resume_steps': {h: _host_max_step(records, h)
                              for h in range(hosts)},
        'exit_codes': exit_codes,
    }
  finally:
    for proc in list(procs.values()) + ([rejoined] if rejoined else []):
      if proc.poll() is None:
        proc.kill()
