"""World size -> mesh plan: DCN x ICI factoring, shards, resharding.

The planner answers the three questions an elastic run re-asks every
time the world changes size (Scalable Training with pjit on TPUv4,
arXiv:2204.06514 — the DCN x ICI layout this repo's
``parallel.create_hybrid_mesh`` builds):

  * **Mesh** — how do W hosts x D local devices factor into the
    flagship data x fsdp combo? DCN (cross-host) axes stay data-only —
    gradient psums then decompose into an ICI reduce-scatter plus a
    small DCN all-reduce, keeping the slow hops at O(params/host)
    bytes — while fsdp stays ICI-local. ``build_mesh`` returns the
    hybrid mesh when this process really spans the world
    (``jax.process_count() == world_size``) and the per-host local
    mesh otherwise (the CPU subprocess federation, where the DCN axis
    is carried by the plan: each simulated host owns its local slice
    and the cross-host axis lives in shard assignment + the shared
    checkpoint/artifact stores).
  * **Shards** — which slice of the input files does each host read?
    Dense ranks over the plan's sorted member list: host ranks are
    REASSIGNED on every epoch, so after a shrink the survivors re-cover
    the departed host's shard residue (the PER_HOST_V2 contract,
    ``Trainer.train(shard_index=, num_shards=)``).
  * **Checkpoints** — why does a checkpoint written at world N restore
    at world N±1? Orbax checkpoints store GLOBAL arrays; the restore
    template (``Trainer.init_state``) carries the NEW mesh's shardings,
    so the same global leaves are simply laid out onto the new device
    set. What changes is captured by ``reshard_plan``: the global batch
    (per-host batch x world) and the shard map — never the parameter
    tree. That invariant is what makes shrink/grow a restore, not a
    migration.

Import-light: jax is deferred into ``build_mesh`` so the doctor / CI
gates can import the planner's vocabulary (``ELASTIC_BENCH_KEYS`` lives
in :mod:`~tensor2robot_tpu.elastic.axes`) without a jax install.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

__all__ = ['MeshPlan', 'plan_mesh', 'build_mesh', 'shard_assignment',
           'reshard_plan']


class MeshPlan:
  """One epoch's world -> mesh factoring (plain data, jax-free)."""

  def __init__(self, world_size: int, local_device_count: int,
               per_host_batch: int, use_fsdp: bool = True,
               epoch: int = 1, hosts: Optional[Sequence[int]] = None):
    if world_size < 1:
      raise ValueError('world_size must be >= 1; got {}.'.format(
          world_size))
    if local_device_count < 1:
      raise ValueError('local_device_count must be >= 1; got {}.'.format(
          local_device_count))
    self.world_size = int(world_size)
    self.local_device_count = int(local_device_count)
    self.per_host_batch = int(per_host_batch)
    self.epoch = int(epoch)
    self.hosts = tuple(sorted(int(h) for h in hosts)) if hosts is not None \
        else tuple(range(world_size))
    if len(self.hosts) != self.world_size:
      raise ValueError('hosts {} disagree with world_size {}.'.format(
          self.hosts, world_size))
    # fsdp stays ICI-local (fast links); it never spans DCN.
    fsdp = 2 if (use_fsdp and self.local_device_count % 2 == 0
                 and self.local_device_count >= 2) else 1
    self.ici_axis_sizes = {'data': self.local_device_count // fsdp,
                           'fsdp': fsdp}
    self.dcn_axis_sizes = {'data': self.world_size}
    self.use_fsdp = fsdp > 1

  @property
  def global_batch(self) -> int:
    return self.per_host_batch * self.world_size

  @property
  def global_device_count(self) -> int:
    return self.local_device_count * self.world_size

  def rank(self, host: int) -> int:
    return self.hosts.index(int(host))

  def to_dict(self) -> Dict[str, object]:
    return {
        'epoch': self.epoch,
        'world_size': self.world_size,
        'hosts': list(self.hosts),
        'local_device_count': self.local_device_count,
        'ici_axis_sizes': dict(self.ici_axis_sizes),
        'dcn_axis_sizes': dict(self.dcn_axis_sizes),
        'per_host_batch': self.per_host_batch,
        'global_batch': self.global_batch,
    }

  def __repr__(self):
    return 'MeshPlan({})'.format(self.to_dict())


def plan_mesh(world_size: int, local_device_count: int,
              per_host_batch: int, use_fsdp: bool = True,
              epoch: int = 1,
              hosts: Optional[Sequence[int]] = None) -> MeshPlan:
  """The one constructor call sites use (kwargs documented on MeshPlan)."""
  return MeshPlan(world_size, local_device_count, per_host_batch,
                  use_fsdp=use_fsdp, epoch=epoch, hosts=hosts)


def build_mesh(plan: MeshPlan):
  """A jax Mesh realizing ``plan`` for THIS process.

  When the process genuinely spans the world (``jax.process_count() ==
  plan.world_size > 1`` — a real pod), the DCN x ICI hybrid mesh is
  built; otherwise (single-process — the CPU subprocess federation,
  where each simulated host is its own jax world) the per-host local
  data x fsdp mesh is built and the DCN 'data' axis lives in the plan's
  shard assignment instead. Either way the LOCAL program is identical —
  which is what lets the artifact store hand every world size the same
  persisted executable.
  """
  import jax

  from tensor2robot_tpu.parallel import mesh as mesh_lib

  if plan.world_size > 1 and jax.process_count() == plan.world_size:
    return mesh_lib.create_hybrid_mesh(
        ici_axis_sizes=dict(plan.ici_axis_sizes),
        dcn_axis_sizes=dict(plan.dcn_axis_sizes))
  return mesh_lib.create_mesh(dict(plan.ici_axis_sizes))


def shard_assignment(plan: MeshPlan, host: int) -> Tuple[int, int]:
  """(shard_index, num_shards) for one host under one plan epoch.

  Dense ranks over the sorted member list: after a shrink the surviving
  hosts' ranks close over the gap, so between them they read EVERY input
  shard again (no file orphaned with its departed reader).
  """
  return plan.rank(host), plan.world_size


def reshard_plan(old_plan: MeshPlan, new_plan: MeshPlan
                 ) -> Dict[str, object]:
  """What actually changes when a checkpoint crosses world sizes.

  The parameter tree is the invariant: Orbax stores GLOBAL arrays, and
  the restore template carries the new mesh's shardings, so restoring
  at the new world is a layout decision made at read time — no rewrite
  of the checkpoint. Everything that DOES change is named here, and the
  driver stamps the summary into its shrink/grow events so the
  telemetry carries the resharding story.
  """
  return {
      'params': 'global shapes unchanged; the restore template lays '
                'each leaf onto the new mesh (Orbax resharding-on-read)',
      'world_before': old_plan.world_size,
      'world_after': new_plan.world_size,
      'global_batch_before': old_plan.global_batch,
      'global_batch_after': new_plan.global_batch,
      'num_shards_before': old_plan.world_size,
      'num_shards_after': new_plan.world_size,
      'rank_moves': {
          str(host): {'before': old_plan.rank(host),
                      'after': new_plan.rank(host)}
          for host in new_plan.hosts if host in old_plan.hosts
          and old_plan.rank(host) != new_plan.rank(host)},
  }
