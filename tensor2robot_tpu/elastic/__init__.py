"""Elastic multi-host training: DCN x ICI mesh lifecycle that survives
losing (and regaining) a host.

ROADMAP item 4's last clause: everything distributed in this repo was
proven at a FIXED world size (the 8-device dryrun, the subprocess fleet
sim), while the pjit-era stacks this work measures itself against
(Scalable Training with pjit on TPUv4, arXiv:2204.06514) treat host
preemption as routine. This package composes the pieces that already
exist — the ``host.preempt`` site and ``t2r.recovery.v1`` timeline
(PR 8), hybrid DCN x ICI mesh construction (``parallel/mesh.py``),
cooperative Orbax checkpoints, and the ``CompiledArtifact`` store whose
AOT-as-the-only-path framing (arXiv:1810.09868) was built so N hosts
share one compile (PR 12) — into a run that keeps training when the
world changes size:

  * :mod:`~tensor2robot_tpu.elastic.membership` — lease-based
    membership over the PR 8 fleet files (jax-free): each host renews a
    lease; the coordinator (lowest surviving index, re-electable)
    declares a host departed when its lease lapses, distinguishing an
    orderly leave from a preemption; world membership is published as
    an epoch-stamped plan every host reads at checkpoint boundaries.
  * :mod:`~tensor2robot_tpu.elastic.topology` — world size -> mesh
    plan: DCN x ICI axis factoring, per-host native-loader shard
    reassignment, and the checkpoint resharding rules that let an
    Orbax checkpoint written at world N restore at world N-1 or N+1.
  * :mod:`~tensor2robot_tpu.elastic.driver` — the ``ElasticTrainer``
    supervisor wrapping the existing ``Trainer``: shrink-on-preemption
    (emergency save -> mesh rebuild at the smaller world ->
    artifact-store warm rebind -> resume, one ``t2r.recovery.v1``
    record carrying ``world_before``/``world_after``) and
    grow-on-rejoin at the next checkpoint boundary.
  * :mod:`~tensor2robot_tpu.elastic.axes` — the jax-free subprocess
    fleet orchestration + ``ELASTIC_BENCH_KEYS`` axes collector behind
    the MULTICHIP elastic phase and the CPU acceptance run.

``membership`` and ``axes`` import no jax; ``topology``/``driver``
defer their jax imports into the functions that need them, so importing
this package stays cheap and jax-free (the ``bin/t2r_telemetry`` /
CI-gate contract).
"""

from tensor2robot_tpu.elastic.membership import (  # noqa: F401
    ELASTIC_SCHEMA,
    EVENT_COORDINATOR,
    EVENT_GROW,
    EVENT_JOIN,
    EVENT_LEAVE,
    EVENT_REBUILD,
    EVENT_SHRINK,
    EVENT_SHRINK_BEGIN,
    EVENT_SHRINK_PHASE,
    LeaseKeeper,
    MembershipView,
    SHRINK_PHASES,
    elect_coordinator,
    observe,
    publish_plan,
    read_leases,
    read_plan,
    release_lease,
    write_lease,
)
from tensor2robot_tpu.elastic.topology import (  # noqa: F401
    MeshPlan,
    plan_mesh,
    reshard_plan,
    shard_assignment,
)
from tensor2robot_tpu.elastic.axes import (  # noqa: F401
    ELASTIC_BENCH_KEYS,
    collect_axes,
    run_elastic_fleet,
)
