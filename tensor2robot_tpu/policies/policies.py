"""Policies that use predictors for robot-time action selection.

Parity target: /root/reference/policies/policies.py:39-370. The full family:
Policy base (SelectAction/reset/restore/sample_action adapter), CEMPolicy
(+LSTM hidden-state variant), RegressionPolicy (+sequential/OU-noise/
scheduled-noise variants), and PerEpisodeSwitchPolicy.

The CEM hot loop (SURVEY.md §3.5: 3 iterations x 64 Q-evaluations per robot
action at 1-10 Hz) keeps the reference's numpy/predictor contract — each CEM
iteration is ONE batched predict call, so on TPU the 64 candidate actions
ride the MXU in a single forward pass; models exposing a traceable batched
apply can instead run the whole CEM loop on-device via
``utils.cross_entropy.jax_normal_cem`` (one dispatch per action).
"""

from __future__ import annotations

import abc
import functools
import time
from typing import Any, Callable, Optional

import jax
import numpy as np

from tensor2robot_tpu.observability import (
    DEFAULT_LATENCY_BUCKETS_MS,
    get_registry,
)
from tensor2robot_tpu.utils import cross_entropy

# Robot-control-loop latency: one observation per SelectAction call,
# labeled by the concrete policy class. At 1-10 Hz control (SURVEY §3.5)
# the p95/p99 of this histogram IS the product metric — a CEM policy
# whose three predictor round trips tail past the control period drops
# robot actions, which no throughput number will show.
POLICY_LATENCY_HISTOGRAM = 'policy/select_action_ms'

# (registry, class name) -> resolved series; the 1-10 Hz control loop
# must not pay a registry lock per action (same memo discipline — and
# the same registry-object key — as predictors/abstract_predictor.py).
_SERIES_CACHE: dict = {}


def _latency_series(policy_name: str):
  registry = get_registry()
  key = (registry, policy_name)
  series = _SERIES_CACHE.get(key)
  if series is None:
    series = registry.histogram_family(
        POLICY_LATENCY_HISTOGRAM, ('policy',),
        bounds=DEFAULT_LATENCY_BUCKETS_MS).series(policy_name)
    _SERIES_CACHE[key] = series
  return series


def _instrument_select_action(fn):
  """Times SelectAction into the policy latency histogram."""

  @functools.wraps(fn)
  def wrapper(self, state, context, timestep):
    start = time.perf_counter()
    action = fn(self, state, context, timestep)
    _latency_series(type(self).__name__).record(
        (time.perf_counter() - start) * 1e3)
    return action

  wrapper._t2r_instrumented = True  # noqa: SLF001 — idempotence marker
  return wrapper


class Policy(abc.ABC):
  """Base policy backed by an optional predictor (ref :39)."""

  def __init_subclass__(cls, **kwargs):
    # Every concrete policy's own SelectAction is wrapped at class
    # creation (same pattern as AbstractPredictor): latency telemetry is
    # structural, not something each policy remembers to add.
    super().__init_subclass__(**kwargs)
    fn = cls.__dict__.get('SelectAction')
    if fn is not None and callable(fn) and not getattr(
        fn, '_t2r_instrumented', False):
      cls.SelectAction = _instrument_select_action(fn)

  def __init__(self, predictor=None):
    self._predictor = predictor

  @abc.abstractmethod
  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    """Selects an action for the observed state (ref :47).

    Must not modify ``state`` or ``context``. ``timestep`` is the 0-indexed
    step within the episode.
    """

  def reset(self) -> None:
    """Called at episode boundaries (ref :63)."""

  def init_randomly(self) -> None:
    if self._predictor is not None:
      self._predictor.init_randomly()

  def restore(self):
    """Returns the predictor's success bool (True when nothing to restore)."""
    if self._predictor is not None:
      return self._predictor.restore()
    return True

  @property
  def model_path(self) -> str:
    if self._predictor is not None:
      return self._predictor.model_path
    return 'No model path defined.'

  @property
  def global_step(self) -> int:
    if self._predictor is not None:
      return self._predictor.global_step
    return 0

  def sample_action(self, obs, explore_prob):
    """run_env-compatible adapter (ref :89): returns (action, debug)."""
    del explore_prob
    action = self.SelectAction(obs, None, None)
    return action, None


class CEMPolicy(Policy):
  """CEM argmax over a critic's Q (ref :112).

  Each CEM iteration packs the state with ``cem_samples`` candidate actions
  and scores them in one predictor call.
  """

  def __init__(self,
               t2r_model,
               action_size: int = 2,
               cem_iters: int = 3,
               cem_samples: int = 64,
               num_elites: int = 10,
               pack_fn: Optional[Callable] = None,
               **parent_kwargs):
    super().__init__(**parent_kwargs)
    self._cem_iters = cem_iters
    self._cem_samples = cem_samples
    self._action_size = action_size
    self._num_elites = num_elites
    self.sample_fn = self._default_sample_fn
    self.pack_fn = pack_fn if pack_fn is not None else self._default_pack_fn
    self._t2r_model = t2r_model

  def _default_sample_fn(self, mean, stddev):
    return mean + stddev * np.random.standard_normal(
        (self._cem_samples, self._action_size))

  def get_cem_action(self, objective_fn):
    """CEM approximate argmax of ``objective_fn`` (ref :139-172)."""

    def update_fn(params, elite_samples):
      del params
      return {
          'mean': np.mean(elite_samples, axis=0),
          'stddev': np.std(elite_samples, axis=0, ddof=1),
      }

    initial_params = {
        'mean': np.zeros(self._action_size),
        'stddev': np.ones(self._action_size),
    }
    samples, values, final_params = cross_entropy.cross_entropy_method(
        self.sample_fn, objective_fn, update_fn, initial_params,
        num_elites=self._num_elites, num_iterations=self._cem_iters)
    idx = int(np.argmax(values))
    debug = {'q_predicted': values[idx], 'final_params': final_params,
             'best_idx': idx}
    return samples[idx], debug

  def _default_pack_fn(self, t2r_model, state, context, timestep, samples):
    return t2r_model.pack_features(state, context, timestep, samples)

  def _select_action_with_debug(self, state, context, timestep):

    def objective_fn(samples):
      np_inputs = self.pack_fn(self._t2r_model, state, context, timestep,
                               samples)
      return self._predictor.predict(np_inputs)['q_predicted']

    return self.get_cem_action(objective_fn)

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    action, _ = self._select_action_with_debug(state, context, timestep)
    return action

  def sample_action(self, obs, explore_prob):
    """run_env adapter surfacing the elite Q for per-step summaries
    (run_env.py:205 reads debug['q'])."""
    del explore_prob
    action, debug = self._select_action_with_debug(obs, None, None)
    return action, {'q': debug['q_predicted']}


class DeviceCEMPolicy(Policy):
  """CEM argmax with the WHOLE optimize loop on device (one dispatch).

  TPU-native upgrade over CEMPolicy's numpy loop (3 predictor round trips
  per action, ref :139-172): the model provides a traceable selector via
  ``make_on_device_select_action`` and every robot action is a single
  jitted call over the predictor's restored variables.
  """

  def __init__(self,
               t2r_model,
               cem_iters: int = 3,
               cem_samples: int = 64,
               num_elites: int = 10,
               seed: int = 0,
               **parent_kwargs):
    super().__init__(**parent_kwargs)
    self._t2r_model = t2r_model
    self._rng = jax.random.PRNGKey(seed)
    self._select = jax.jit(t2r_model.make_on_device_select_action(
        cem_samples=cem_samples, cem_iters=cem_iters,
        num_elites=num_elites))

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    del context, timestep
    self._rng, step_rng = jax.random.split(self._rng)
    action, _ = self._select(self._predictor.variables, dict(state),
                             step_rng)
    return np.asarray(jax.device_get(action))

  def sample_action(self, obs, explore_prob):
    """run_env adapter surfacing the elite Q (run_env.py reads debug['q'])."""
    del explore_prob
    self._rng, step_rng = jax.random.split(self._rng)
    action, q = self._select(self._predictor.variables, dict(obs), step_rng)
    action, q = jax.device_get((action, q))
    return np.asarray(action), {'q': float(q)}


class LSTMCEMPolicy(CEMPolicy):
  """CEMPolicy caching the critic's LSTM hidden state across steps (ref :194).

  The predictor returns the hidden-state batch for every candidate; after CEM
  picks the elite action its hidden state becomes next step's carry.
  """

  def __init__(self, hidden_state_size: int, **kwargs):
    self._hidden_state_size = hidden_state_size
    super().__init__(**kwargs)
    self.reset()

  def reset(self) -> None:
    self._hidden_state = np.zeros((self._hidden_state_size,), np.float32)

  def _select_action_with_debug(self, state, context, timestep):
    del context  # the hidden state takes the context slot in pack_fn

    def objective_fn(samples):
      np_inputs = self.pack_fn(self._t2r_model, state, self._hidden_state,
                               timestep, samples)
      predictions = self._predictor.predict(np_inputs)
      self._hidden_state_batch = predictions['lstm_hidden_state']
      return predictions['q_predicted']

    action, debug = self.get_cem_action(objective_fn)
    self._hidden_state = self._hidden_state_batch[debug['best_idx']]
    return action, debug


class RegressionPolicy(Policy):
  """Direct action regression (ref :228)."""

  def __init__(self, t2r_model, **parent_kwargs):
    super().__init__(**parent_kwargs)
    self._t2r_model = t2r_model

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    np_inputs = self._t2r_model.pack_features(state, context, timestep)
    return self._predictor.predict(np_inputs)['inference_output'][0]


class SequentialRegressionPolicy(RegressionPolicy):
  """Feeds the previous packed input back as context (ref :246)."""

  def reset(self) -> None:
    self._sequence_context = None

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    np_inputs = self._t2r_model.pack_features(
        state, self._sequence_context, timestep)
    self._sequence_context = np_inputs
    return self._predictor.predict(np_inputs)['inference_output'][0]


class OUExploreRegressionPolicy(Policy):
  """Regression + Ornstein-Uhlenbeck exploration noise (ref :264)."""

  def __init__(self,
               t2r_model,
               action_size: int = 2,
               theta: float = 0.2,
               sigma: float = 0.15,
               use_noise: bool = True,
               **parent_kwargs):
    super().__init__(**parent_kwargs)
    self._t2r_model = t2r_model
    self.theta, self.sigma, self.mu = theta, sigma, 0.0
    self._action_size = action_size
    self._x_t = np.zeros(action_size)
    self._use_noise = use_noise

  def ou_step(self):
    dx_t = (self.theta * (self.mu - self._x_t) +
            self.sigma * np.random.randn(*self._x_t.shape))
    self._x_t = self._x_t + dx_t
    return self._x_t

  def reset(self) -> None:
    self._x_t = np.zeros(self._action_size)

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    np_inputs = self._t2r_model.pack_features(state, context, timestep)
    action = self._predictor.predict(np_inputs)['inference_output'][0]
    noise = self.ou_step() if self._use_noise else 0
    return action + noise


class ScheduledExplorationRegressionPolicy(Policy):
  """Gaussian noise with a linear stddev schedule over global step (ref :301)."""

  def __init__(self,
               t2r_model,
               action_size: int = 2,
               stddev_0: float = 0.2,
               slope: float = 0.0,
               **parent_kwargs):
    super().__init__(**parent_kwargs)
    self._t2r_model = t2r_model
    self._action_size = action_size
    self._stddev_0 = stddev_0
    self._slope = slope

  def get_noise(self):
    stddev = max(self._stddev_0 + self.global_step * self._slope, 0)
    return stddev * np.random.randn(self._action_size)

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    np_inputs = self._t2r_model.pack_features(state, context, timestep)
    action = self._predictor.predict(np_inputs)['inference_output'][0]
    return action + self.get_noise()


class PerEpisodeSwitchPolicy(Policy):
  """Picks an explore or greedy sub-policy once per episode (ref :330)."""

  def __init__(self, explore_policy_class, greedy_policy_class,
               explore_prob: float, **parent_kwargs):
    super().__init__(**parent_kwargs)
    self._explore_policy = explore_policy_class()
    self._greedy_policy = greedy_policy_class()
    self._explore_prob = explore_prob
    self._active_policy = None

  def reset(self) -> None:
    self._explore_policy.reset()
    self._greedy_policy.reset()
    if np.random.random() < self._explore_prob:
      self._active_policy = self._explore_policy
    else:
      self._active_policy = self._greedy_policy

  def init_randomly(self) -> None:
    self._explore_policy.init_randomly()
    self._greedy_policy.init_randomly()

  def restore(self):
    explore_ok = self._explore_policy.restore()
    greedy_ok = self._greedy_policy.restore()
    return (explore_ok is not False) and (greedy_ok is not False)

  @property
  def global_step(self) -> int:
    """The greedy policy's step (ref :364)."""
    return self._greedy_policy.global_step

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    if self._active_policy is None:
      self.reset()
    return self._active_policy.SelectAction(state, context, timestep)
