"""Policies: predictor-backed action selection for robot control loops."""

from tensor2robot_tpu.policies.policies import (
    CEMPolicy,
    DeviceCEMPolicy,
    LSTMCEMPolicy,
    OUExploreRegressionPolicy,
    PerEpisodeSwitchPolicy,
    Policy,
    RegressionPolicy,
    ScheduledExplorationRegressionPolicy,
    SequentialRegressionPolicy,
)

__all__ = [
    'CEMPolicy',
    'DeviceCEMPolicy',
    'LSTMCEMPolicy',
    'OUExploreRegressionPolicy',
    'PerEpisodeSwitchPolicy',
    'Policy',
    'RegressionPolicy',
    'ScheduledExplorationRegressionPolicy',
    'SequentialRegressionPolicy',
]
