"""ReplayService: sharded packed-wire experience store + sampling front-end.

The organ between fleet-scale collection and the learner (ISSUE 11,
ROADMAP item 2): episodes arrive as per-example packed records
(replay/wire.py), are routed round-robin across N :class:`ShardStore`
shards where they stay packed at rest, and leave as megabatches whose
layout is byte-identical in signature to a native-loader disk batch —
the learner's ``SparseCoefFeed``/``PipelinedFeed`` path cannot tell the
difference.

Design invariants:

  * **Packed end to end.** Records are validated (decoded) once at
    append and stored as the raw bytes; sampling re-decodes into
    zero-copy views and assembles with one pad-to-bucket copy per
    stream. Nothing between the collector's wire and the learner's
    transfer hop ever materializes pixels.
  * **Bounded damage.** A corrupt append (fails
    :class:`~tensor2robot_tpu.replay.wire.ReplayWireError` validation)
    is charged to the receiving shard's quarantine budget
    (reliability/quarantine.py — the same bounded-tolerance/loud-
    exhaustion discipline as disk reads) and NEVER stored, so a bad
    writer cannot poison sampling; blowing the per-shard or global
    budget raises ``CorruptionBudgetExceeded`` naming the shard. The
    ``replay.append`` FaultInjector site corrupts arriving records
    deterministically to drive exactly this path in tests.
  * **The sampling front-end is the serving machinery.** Concurrent
    learner sample requests coalesce through the shared
    :class:`~tensor2robot_tpu.serving.batching.DeadlineBatcher` (one
    lock pass over the shards serves a burst of requests) behind
    depth-based admission control (``replay/rejected``) — the ISSUE 8
    batcher, reused without importing the policy server.
  * **Measured, not asserted.** Per-shard occupancy/append/sample/evict
    counters live in the registry as labeled series; a
    ``kind="replay"`` (``t2r.replay.v1``) record lands in
    ``telemetry.jsonl`` each report window with per-shard rates, which
    ``t2r_telemetry`` formats and ``doctor`` (+ the jax-free
    ``bin/check_replay_doctor`` gate) diagnose offline — a shard that
    stops sampling while others flow is a named CRITICAL.

The module imports no jax: append/sample are numpy + threads, so the
whole contract tests on any CPU box (tests/test_replay.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.observability import TelemetryLogger, get_registry
from tensor2robot_tpu.observability.spans import SPAN_BUCKETS_MS
from tensor2robot_tpu.reliability import fault_injection
from tensor2robot_tpu.reliability.logutil import log_warning
from tensor2robot_tpu.reliability.quarantine import RecordQuarantine
from tensor2robot_tpu.replay import wire
from tensor2robot_tpu.replay.sampling import make_policy
from tensor2robot_tpu.replay.store import ShardStore
from tensor2robot_tpu.serving.batching import (
    AdmissionController,
    DeadlineBatcher,
)

__all__ = ['ReplayConfig', 'ReplayService', 'ReplayEmpty', 'SampleBatch',
           'REPLAY_RECORD_KIND', 'REPLAY_RECORD_SCHEMA',
           'REPLAY_REJECTED_COUNTER', 'REPLAY_BENCH_KEYS']

REPLAY_RECORD_KIND = 'replay'
REPLAY_RECORD_SCHEMA = 't2r.replay.v1'
REPLAY_REJECTED_COUNTER = 'replay/rejected'

REPLAY_APPENDS_COUNTER = 'replay/appends'
REPLAY_APPEND_BYTES_COUNTER = 'replay/append_bytes'
REPLAY_CORRUPT_COUNTER = 'replay/corrupt_appends'
REPLAY_SAMPLES_COUNTER = 'replay/samples'
REPLAY_SAMPLE_BATCHES_COUNTER = 'replay/sample_batches'
REPLAY_OCCUPANCY_EXAMPLES_GAUGE = 'replay/occupancy_examples'
REPLAY_OCCUPANCY_BYTES_GAUGE = 'replay/occupancy_bytes'
REPLAY_QUEUE_DEPTH_GAUGE = 'replay/sample_queue_depth'
REPLAY_SAMPLE_MS_HISTOGRAM = 'replay/sample_ms'

# The replay bench axis keys a successful `bench.py` replay section must
# publish (bench self-checks against this tuple; the jax-free
# bin/check_replay_doctor gate schema-locks it — ISSUE 11 acceptance).
# Kept here, next to the record schema, because the parity bar these
# keys carry (learner e2e within 5% of local disk, at-rest bytes within
# 1.1x of the wire) IS the service's contract.
REPLAY_BENCH_KEYS = (
    'replay_writers',
    'replay_append_examples_per_sec',
    'replay_e2e_samples_per_sec',
    'replay_e2e_samples_per_sec_spread',
    'replay_e2e_vs_disk',
    'replay_sample_p99_ms',
    'replay_wire_bytes_per_example',
    'replay_at_rest_bytes_per_example',
    'replay_at_rest_overhead',
)


class ReplayEmpty(RuntimeError):
  """No resident examples anywhere; the learner should retry shortly."""


@dataclasses.dataclass
class ReplayConfig:
  """Knobs for one ReplayService.

  Attributes:
    num_shards: independent stores appends round-robin over; sampling
      draws from every shard proportionally to its occupancy.
    batch_size: default examples per sampled megabatch.
    retention: 'ring' (FIFO window) or 'reservoir' (uniform over the
      append stream) — replay/store.py.
    policy: 'uniform' or 'prioritized' — replay/sampling.py.
    priority_alpha: the prioritized policy's exponent.
    capacity_examples_per_shard / capacity_bytes_per_shard: per-shard
      bounds (whichever trips first evicts).
    coalesce_requests: how many concurrent sample REQUESTS one serve-
      loop pass may answer together (the DeadlineBatcher's batch size).
    max_wait_ms: deadline for an under-full request batch.
    max_queue_depth: admission bound on PENDING sample requests;
      arrivals beyond it are shed with RequestRejected.
    max_corrupt_appends / max_corrupt_appends_per_shard: quarantine
      budgets for appends failing wire validation.
    report_interval_s: cadence of ``kind="replay"`` telemetry records.
    seed: deterministic sampling/reservoir randomness (tests).
  """

  num_shards: int = 4
  batch_size: int = 32
  retention: str = 'ring'
  policy: str = 'uniform'
  priority_alpha: float = 0.6
  capacity_examples_per_shard: int = 4096
  capacity_bytes_per_shard: Optional[int] = None
  coalesce_requests: int = 8
  max_wait_ms: float = 5.0
  max_queue_depth: int = 64
  max_corrupt_appends: int = 100
  max_corrupt_appends_per_shard: int = 10
  report_interval_s: float = 10.0
  seed: Optional[int] = None


class SampleBatch(NamedTuple):
  """One assembled megabatch + the stable ids that produced it."""

  features: Dict[str, np.ndarray]
  labels: Dict[str, np.ndarray]
  record_ids: List[Tuple[int, int]]  # (shard, record_id) per row


def split_sides(flat: Dict[str, np.ndarray]
                ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
  """``{'features/x': a, 'labels/y': b}`` -> (features, labels) dicts."""
  features: Dict[str, np.ndarray] = {}
  labels: Dict[str, np.ndarray] = {}
  for key, value in flat.items():
    side, _, rest = key.partition('/')
    (features if side == 'features' else labels)[rest] = value
  return features, labels


class ReplayService:
  """Sharded packed-record store with a batched sampling front-end."""

  def __init__(self,
               config: Optional[ReplayConfig] = None,
               model_dir: Optional[str] = None,
               registry=None,
               telemetry: Optional[TelemetryLogger] = None,
               clock=time.monotonic):
    self.config = config or ReplayConfig()
    if self.config.num_shards < 1:
      raise ValueError('num_shards must be >= 1; got {}.'.format(
          self.config.num_shards))
    self._clock = clock
    self._registry = registry or get_registry()
    seed = self.config.seed
    self._rng = np.random.RandomState(seed)
    self._shards = [
        ShardStore(capacity_examples=self.config.capacity_examples_per_shard,
                   capacity_bytes=self.config.capacity_bytes_per_shard,
                   retention=self.config.retention,
                   seed=None if seed is None else seed + 1 + i)
        for i in range(self.config.num_shards)]
    self._policy = make_policy(self.config.policy,
                               alpha=self.config.priority_alpha)
    self._quarantine = RecordQuarantine(
        max_corrupt_records=self.config.max_corrupt_appends,
        max_corrupt_records_per_file=self.config.max_corrupt_appends_per_shard)
    self._append_lock = threading.Lock()
    self._append_cursor = 0

    self._owns_telemetry = telemetry is None and model_dir is not None
    self._telemetry = telemetry
    if self._owns_telemetry:
      self._telemetry = TelemetryLogger(model_dir)

    appends = self._registry.counter_family(REPLAY_APPENDS_COUNTER,
                                            ('shard',))
    samples = self._registry.counter_family(REPLAY_SAMPLES_COUNTER,
                                            ('shard',))
    self._append_counters = [appends.series(str(i))
                             for i in range(self.config.num_shards)]
    self._sample_counters = [samples.series(str(i))
                             for i in range(self.config.num_shards)]
    self._append_bytes = self._registry.counter(REPLAY_APPEND_BYTES_COUNTER)
    self._corrupt_counter = self._registry.counter(REPLAY_CORRUPT_COUNTER)
    self._batches_counter = self._registry.counter(
        REPLAY_SAMPLE_BATCHES_COUNTER)
    self._occupancy_gauge = self._registry.gauge(
        REPLAY_OCCUPANCY_EXAMPLES_GAUGE)
    self._bytes_gauge = self._registry.gauge(REPLAY_OCCUPANCY_BYTES_GAUGE)
    self._queue_gauge = self._registry.gauge(REPLAY_QUEUE_DEPTH_GAUGE)
    self._sample_ms = self._registry.histogram(REPLAY_SAMPLE_MS_HISTOGRAM,
                                               bounds=SPAN_BUCKETS_MS)

    self._batcher = DeadlineBatcher(self.config.coalesce_requests,
                                    self.config.max_wait_ms, clock=clock)
    self._admission = AdmissionController(
        self.config.max_queue_depth, registry=self._registry,
        counter_name=REPLAY_REJECTED_COUNTER)
    self._worker: Optional[threading.Thread] = None
    self._stop = False

    # Report-window state: per-shard counter snapshots, so window rates
    # are deltas even though the registry series stay cumulative.
    self._window_lock = threading.Lock()
    self._window_started = self._clock()
    self._last_shard_counters = [s.counters() for s in self._shards]
    self._last_corrupt = 0.0
    self._last_corrupt_by_shard = [0] * self.config.num_shards

  # -- lifecycle -------------------------------------------------------------

  def start(self) -> 'ReplayService':
    """Starts the sample serve loop (needed for ``submit_sample`` /
    the HTTP frontend; direct ``sample()`` works without it)."""
    if self._worker is not None:
      raise RuntimeError('ReplayService already started.')
    if self._telemetry is not None:
      self._telemetry.log(
          'replay_start',
          config={'num_shards': self.config.num_shards,
                  'batch_size': self.config.batch_size,
                  'retention': self.config.retention,
                  'policy': self.config.policy,
                  'capacity_examples_per_shard':
                      self.config.capacity_examples_per_shard})
    self._worker = threading.Thread(target=self._serve_loop,
                                    name='t2r-replay-service', daemon=True)
    self._worker.start()
    return self

  def __enter__(self) -> 'ReplayService':
    return self.start()

  def __exit__(self, *exc_info) -> None:
    self.close()

  def close(self) -> None:
    if self._worker is None:
      if self._owns_telemetry and self._telemetry is not None:
        self._telemetry.close()
      return
    self._stop = True
    self._batcher.close()
    self._worker.join()
    self._worker = None
    self._report(force=True)
    if self._telemetry is not None:
      self._telemetry.log('replay_stop',
                          occupancy_examples=self.occupancy_examples,
                          rejected_total=self._admission.rejected_total)
      self._telemetry.flush()
      if self._owns_telemetry:
        self._telemetry.close()
    self._queue_gauge.set(0.0)

  # -- append path -----------------------------------------------------------

  def append(self, blob: bytes, priority: float = 1.0,
             shard: Optional[int] = None) -> int:
    """Validates + stores one packed record; returns the shard index.

    Corrupt records (wire validation failure) are charged to the
    receiving shard's quarantine budget and re-raised as
    :class:`~tensor2robot_tpu.replay.wire.ReplayWireError` — the record
    is NEVER stored, so sampling stays clean; exhausting a budget
    raises ``CorruptionBudgetExceeded`` naming the shard. The
    ``replay.append`` FaultInjector site deterministically corrupts the
    arriving record (truncation) to drive this path.
    """
    if fault_injection.fires(fault_injection.SITE_REPLAY_APPEND):
      blob = blob[:max(1, len(blob) // 2)]  # injected wire corruption
    if shard is None:
      with self._append_lock:
        shard = self._append_cursor % len(self._shards)
        self._append_cursor += 1
    else:
      shard = int(shard) % len(self._shards)
    try:
      wire.decode_example(blob)
    except wire.ReplayWireError as e:
      self._corrupt_counter.inc()
      # record_index=None: every corrupt arrival counts (there is no
      # multi-epoch re-read of a network append to dedupe).
      self._quarantine.record_skipped('shard{}'.format(shard),
                                      reason=str(e))
      raise
    self._shards[shard].append(blob, priority=priority)
    self._append_counters[shard].inc()
    self._append_bytes.inc(len(blob))
    # Occupancy gauges refresh at the report window, NOT here: a
    # per-append refresh would take every shard's lock twice per call
    # and serialize the per-shard-lock concurrency N writers rely on.
    return shard

  def _update_occupancy_gauges(self) -> None:
    self._occupancy_gauge.set(float(self.occupancy_examples))
    self._bytes_gauge.set(float(self.occupancy_bytes))

  # -- sample path -----------------------------------------------------------

  def sample(self, batch_size: Optional[int] = None) -> SampleBatch:
    """Draws and assembles one megabatch across shards.

    Raises :class:`ReplayEmpty` when nothing is resident anywhere. The
    ``replay.sample`` FaultInjector site stalls here — the symptom the
    learner's pipeline X-ray must catch as ``pipeline_stall``.
    """
    stall_s = fault_injection.replay_sample_stall_seconds()
    if stall_s > 0.0:
      time.sleep(stall_s)
    batch_size = int(batch_size or self.config.batch_size)
    t0 = time.perf_counter()
    rows: List[Dict[str, np.ndarray]] = []
    record_ids: List[Tuple[int, int]] = []
    # Redraw loop: a draw is computed against an occupancy snapshot,
    # and concurrent byte-bounded appends can evict records between the
    # snapshot and the fetch (get_many skips dead slots). Each pass
    # re-reads occupancy and draws only the shortfall; a bounded number
    # of passes turns a pathological drain into a clean ReplayEmpty
    # instead of an infinite loop.
    for _ in range(8):
      if len(rows) >= batch_size:
        break
      need = batch_size - len(rows)
      occupancies = np.asarray(
          [shard.occupancy_examples for shard in self._shards],
          np.float64)
      total = float(occupancies.sum())
      if total <= 0.0:
        raise ReplayEmpty(
            'replay store is empty; retry after appends land')
      counts = self._rng.multinomial(need, occupancies / total)
      for shard_index, count in enumerate(counts):
        if count <= 0:
          continue
        store = self._shards[shard_index]
        # Draw against an atomic (ids, priorities) snapshot, fetch by
        # STABLE id: a ring slide between the two steps skips the dead
        # ids (redrawn next pass) instead of silently resolving a slot
        # to its neighbor — a shifted-slot fetch would bias prioritized
        # sampling in proportion to the append rate.
        ids_snapshot, priorities = store.snapshot()
        slots = self._policy.draw(priorities, int(count), self._rng)
        drawn = [ids_snapshot[slot] for slot in slots
                 if 0 <= slot < len(ids_snapshot)]
        blobs, ids = store.get_by_ids(drawn)
        self._sample_counters[shard_index].inc(len(blobs))
        for blob, record_id in zip(blobs, ids):
          rows.append(wire.decode_example(blob))
          record_ids.append((shard_index, record_id))
    if len(rows) < batch_size:
      raise ReplayEmpty('replay store drained mid-sample')
    flat = wire.assemble_batch(rows)
    features, labels = split_sides(flat)
    self._batches_counter.inc()
    self._sample_ms.record((time.perf_counter() - t0) * 1e3)
    return SampleBatch(features=features, labels=labels,
                       record_ids=record_ids)

  def update_priorities(self, record_ids: Sequence[Tuple[int, int]],
                        priorities: Sequence[float]) -> int:
    """Routes learner priority updates back to their shards by stable
    id; evicted ids are skipped. Returns how many landed."""
    by_shard: Dict[int, Tuple[List[int], List[float]]] = {}
    for (shard, record_id), priority in zip(record_ids, priorities):
      ids, values = by_shard.setdefault(int(shard), ([], []))
      ids.append(int(record_id))
      values.append(float(priority))
    landed = 0
    for shard, (ids, values) in by_shard.items():
      landed += self._shards[shard].update_priorities(ids, values)
    return landed

  # -- batched sample front-end ----------------------------------------------

  def submit_sample(self, batch_size: Optional[int] = None):
    """Enqueues one sample request; returns a Future[SampleBatch].

    Requires :meth:`start`. Depth check and enqueue are one atomic step
    under the batcher's lock (TOCTOU-free shedding, same contract as
    the policy server); saturation raises RequestRejected.
    """
    if self._worker is None:
      raise RuntimeError('ReplayService.start() the serve loop before '
                         'submit_sample().')
    request = self._batcher.submit(
        {'batch_size': int(batch_size or self.config.batch_size)},
        admission=self._admission)
    self._queue_gauge.set(float(self._batcher.pending_count()))
    return request.future

  def _serve_loop(self) -> None:
    while True:
      batch = self._batcher.next_batch(timeout=0.05)
      if batch is None:
        if self._stop:
          break  # closed AND drained
      else:
        for request in batch:
          try:
            result = self.sample(request.features.get('batch_size'))
          except Exception as e:  # noqa: BLE001 — answer THIS caller,
            # keep serving: a dead serve loop hangs every future caller.
            self._answer(request, error=e)
          else:
            self._answer(request, result=result)
        self._queue_gauge.set(float(self._batcher.pending_count()))
      try:
        self._maybe_report()
      except Exception as e:  # noqa: BLE001 — telemetry I/O must degrade
        log_warning('ReplayService report failed (kept serving): %s', e)

  def _answer(self, request, result=None, error=None) -> None:
    try:
      if error is not None:
        request.future.set_exception(error)
      else:
        request.future.set_result(result)
    except Exception:  # noqa: BLE001 — InvalidStateError on cancel
      pass

  # -- telemetry -------------------------------------------------------------

  def shard_occupancy(self, shard: int) -> int:
    """ONE shard's resident examples (one lock, append-path cheap)."""
    return self._shards[int(shard) % len(self._shards)].occupancy_examples

  @property
  def occupancy_examples(self) -> int:
    return sum(shard.occupancy_examples for shard in self._shards)

  @property
  def occupancy_bytes(self) -> int:
    return sum(shard.occupancy_bytes for shard in self._shards)

  def _maybe_report(self) -> None:
    if self._clock() - self._window_started >= \
        self.config.report_interval_s:
      self._report()

  def _report(self, force: bool = False) -> None:
    now = self._clock()
    window_s = now - self._window_started
    if window_s <= 0 and not force:
      return
    with self._window_lock:
      current = [shard.counters() for shard in self._shards]
      previous = self._last_shard_counters
      self._last_shard_counters = current
      corrupt_total = self._corrupt_counter.value
      corrupt_delta = corrupt_total - self._last_corrupt
      self._last_corrupt = corrupt_total
      # Per-shard corrupt counts are WINDOW DELTAS like their sibling
      # fields: a writer fixed days ago must stop tripping the
      # doctor's present-tense 'shipping damaged records' warning.
      corrupt_by_shard = [
          self._quarantine.skipped_in_file('shard{}'.format(i))
          for i in range(self.config.num_shards)]
      corrupt_shard_delta = [cur - prev for cur, prev in zip(
          corrupt_by_shard, self._last_corrupt_by_shard)]
      self._last_corrupt_by_shard = corrupt_by_shard
      self._window_started = now
    shards: Dict[str, Dict[str, float]] = {}
    appends = samples = evictions = 0
    for index, (cur, prev) in enumerate(zip(current, previous)):
      delta = {key: cur[key] - prev[key]
               for key in ('appends', 'samples', 'evictions')}
      appends += delta['appends']
      samples += delta['samples']
      evictions += delta['evictions']
      shards[str(index)] = {
          'occupancy_examples': cur['occupancy_examples'],
          'occupancy_bytes': cur['occupancy_bytes'],
          'appends': delta['appends'],
          'samples': delta['samples'],
          'evictions': delta['evictions'],
          'corrupt': corrupt_shard_delta[index],
      }
    occupancy = self.occupancy_examples
    occupancy_bytes = self.occupancy_bytes
    self._update_occupancy_gauges()
    record = {
        'schema': REPLAY_RECORD_SCHEMA,
        'window_seconds': round(window_s, 3),
        'appends': int(appends),
        'appends_per_sec': round(appends / window_s, 2) if window_s > 0
                           else 0.0,
        'samples': int(samples),
        'samples_per_sec': round(samples / window_s, 2) if window_s > 0
                           else 0.0,
        'evictions': int(evictions),
        'corrupt': int(corrupt_delta),
        'occupancy_examples': int(occupancy),
        'occupancy_bytes': int(occupancy_bytes),
        'bytes_per_example': round(occupancy_bytes / occupancy, 1)
                             if occupancy else 0.0,
        'sample_queue_depth': self._batcher.pending_count(),
        'rejected_total': self._admission.rejected_total,
        'shards': shards,
    }
    if self._telemetry is not None:
      self._telemetry.log(REPLAY_RECORD_KIND, **record)
      self._telemetry.heartbeat()
      self._telemetry.flush()

  # -- introspection ---------------------------------------------------------

  def stats(self) -> Dict[str, Any]:
    """Cumulative service stats (frontend /healthz + bench)."""
    shards = {str(i): shard.counters()
              for i, shard in enumerate(self._shards)}
    for index, entry in shards.items():
      entry['corrupt'] = self._quarantine.skipped_in_file(
          'shard{}'.format(index))
    return {
        'occupancy_examples': self.occupancy_examples,
        'occupancy_bytes': self.occupancy_bytes,
        'corrupt_appends_total': self._corrupt_counter.value,
        'rejected_total': self._admission.rejected_total,
        'sample_queue_depth': self._batcher.pending_count(),
        'retention': self.config.retention,
        'policy': self.config.policy,
        'num_shards': self.config.num_shards,
        'shards': shards,
    }
