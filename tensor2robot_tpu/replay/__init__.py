"""Sharded, packed-wire distributed replay (ISSUE 11, ROADMAP item 2).

The organ between fleet-scale collection and the learner: episodes
arrive as per-example packed records (`wire.py` — the ``coef_packed``
wire of PR 9, per example instead of per batch), stay packed at rest in
per-shard ring/reservoir stores (`store.py`, ~14k examples/GB of host
RAM), and leave as megabatches byte-identical in signature to a
native-loader disk batch — assembled by a sampling front-end built on
the serving batcher + admission machinery (`service.py`), shipped over
a stdlib HTTP door (`frontend.py`) or in-process, retried with backoff
on the client (`client.py`), and fed to the trainer through
``SparseCoefFeed``/``PipelinedFeed`` unchanged (`feed.py`). Sampling is
uniform or prioritized (`sampling.py`); corrupt appends charge
per-shard quarantine budgets; per-shard occupancy/append/sample/evict
rates land as ``t2r.replay.v1`` telemetry the doctor (and the jax-free
``bin/check_replay_doctor`` gate) diagnose offline.

``bin/t2r_replay`` is the entry point; ``--replay_endpoint`` on
bin/run_t2r_trainer points a learner at it. Contract + quickstart:
docs/replay.md. Everything here imports without jax.
"""

from tensor2robot_tpu.replay.client import (
    LocalReplayClient,
    ReplayClient,
    ReplayUnavailable,
)
from tensor2robot_tpu.replay.feed import (
    ReplayBatchIterator,
    ReplayInputGenerator,
)
from tensor2robot_tpu.replay.sampling import (
    POLICIES,
    PrioritizedPolicy,
    SamplePolicy,
    UniformPolicy,
    make_policy,
)
from tensor2robot_tpu.replay.service import (
    REPLAY_BENCH_KEYS,
    REPLAY_RECORD_KIND,
    REPLAY_RECORD_SCHEMA,
    ReplayConfig,
    ReplayEmpty,
    ReplayService,
    SampleBatch,
)
from tensor2robot_tpu.replay.store import RETENTIONS, ShardStore
from tensor2robot_tpu.replay.wire import (
    ReplayWireError,
    assemble_batch,
    decode_example,
    encode_example,
    split_batch,
)

__all__ = [
    'LocalReplayClient',
    'POLICIES',
    'PrioritizedPolicy',
    'REPLAY_BENCH_KEYS',
    'REPLAY_RECORD_KIND',
    'REPLAY_RECORD_SCHEMA',
    'RETENTIONS',
    'ReplayBatchIterator',
    'ReplayClient',
    'ReplayConfig',
    'ReplayEmpty',
    'ReplayInputGenerator',
    'ReplayService',
    'ReplayUnavailable',
    'ReplayWireError',
    'SampleBatch',
    'SamplePolicy',
    'ShardStore',
    'UniformPolicy',
    'assemble_batch',
    'decode_example',
    'encode_example',
    'make_policy',
    'split_batch',
]
