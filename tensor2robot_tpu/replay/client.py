"""Replay clients: the writer/learner half of the replay wire.

Two interchangeable clients behind one API:

  * :class:`ReplayClient` — stdlib HTTP against a ``t2r_replay``
    endpoint. Every call goes through ``reliability.retry`` with
    exponential backoff + jitter (sites ``replay.append`` /
    ``replay.sample``), so a collector fleet rides through a service
    restart instead of dying together; shed requests (503) and
    connection failures are transient, a 400 (corrupt record / bad
    request) is NOT — a deterministic error does not get better with
    sleep.
  * :class:`LocalReplayClient` — the same API over an in-process
    :class:`~tensor2robot_tpu.replay.service.ReplayService` (tests,
    single-host runs, bench preloads).

``sample`` can ``wait`` for the store to fill: a learner that starts
before its collectors is a normal boot order, not an error.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tensor2robot_tpu.reliability.retry import RetryPolicy, retry
from tensor2robot_tpu.replay import wire
from tensor2robot_tpu.replay.service import (
    ReplayEmpty,
    ReplayService,
    SampleBatch,
    split_sides,
)

__all__ = ['ReplayClient', 'LocalReplayClient', 'ReplayUnavailable']

RECORD_IDS_KEY = '__record_ids__'  # mirrors frontend.RECORD_IDS_KEY


class ReplayUnavailable(OSError):
  """Transient service failure (connection refused, shed, 5xx) — an
  OSError so the default RetryPolicy retries it."""


def _normalize_endpoint(endpoint: str) -> str:
  if not endpoint.startswith(('http://', 'https://')):
    endpoint = 'http://' + endpoint
  return endpoint.rstrip('/')


class ReplayClient:
  """HTTP replay client with bounded retry."""

  def __init__(self, endpoint: str,
               retry_policy: Optional[RetryPolicy] = None,
               timeout_s: float = 60.0):
    self.endpoint = _normalize_endpoint(endpoint)
    self._retry_policy = retry_policy or RetryPolicy(
        max_attempts=5, base_delay_secs=0.1, max_delay_secs=2.0)
    self._timeout_s = float(timeout_s)

  def _post(self, path: str, body: bytes, content_type: str) -> bytes:
    request = urllib.request.Request(
        self.endpoint + path, data=body, method='POST',
        headers={'Content-Type': content_type})
    try:
      with urllib.request.urlopen(request,
                                  timeout=self._timeout_s) as response:
        return response.read()
    except urllib.error.HTTPError as e:
      detail = e.read().decode('utf-8', 'replace')[:500]
      if e.code == 409:
        raise ReplayEmpty(detail) from e
      if e.code in (502, 503, 504):
        raise ReplayUnavailable('{} {}: {}'.format(
            e.code, path, detail)) from e
      # 400/404/500/507: deterministic — do not retry.
      raise RuntimeError('replay {} failed with {}: {}'.format(
          path, e.code, detail)) from e
    except urllib.error.URLError as e:
      raise ReplayUnavailable('{} unreachable: {}'.format(
          self.endpoint, e.reason)) from e

  def append(self, example, priority: float = 1.0) -> int:
    """Appends one example; returns the shard it landed on.

    ``example`` is either an encoded record (bytes) or a flat
    ``{key: array}`` dict to encode here.
    """
    blob = example if isinstance(example, (bytes, bytearray)) \
        else wire.encode_example(example)
    path = '/v1/append?priority={:.6g}'.format(float(priority))
    payload = retry(
        lambda: self._post(path, bytes(blob), 'application/octet-stream'),
        policy=self._retry_policy, site='replay.append')
    return int(json.loads(payload).get('shard', -1))

  def sample(self, batch_size: Optional[int] = None,
             wait: bool = False,
             wait_timeout_s: float = 60.0,
             poll_interval_s: float = 0.2) -> SampleBatch:
    """Draws one megabatch; with ``wait`` polls through ReplayEmpty."""
    body = b'' if batch_size is None else json.dumps(
        {'batch_size': int(batch_size)}).encode('utf-8')

    def _once() -> SampleBatch:
      payload = retry(
          lambda: self._post('/v1/sample', body, 'application/json'),
          policy=self._retry_policy, site='replay.sample')
      flat = dict(wire.decode_example(payload))
      ids = flat.pop(RECORD_IDS_KEY, None)
      features, labels = split_sides(flat)
      record_ids = [] if ids is None else \
          [(int(s), int(i)) for s, i in np.asarray(ids)]
      return SampleBatch(features=features, labels=labels,
                         record_ids=record_ids)

    if not wait:
      return _once()
    deadline = time.monotonic() + wait_timeout_s
    while True:
      try:
        return _once()
      except ReplayEmpty:
        if time.monotonic() >= deadline:
          raise
        time.sleep(poll_interval_s)

  def update_priorities(self, record_ids: Sequence[Tuple[int, int]],
                        priorities: Sequence[float]) -> int:
    body = json.dumps({
        'record_ids': [[int(s), int(i)] for s, i in record_ids],
        'priorities': [float(p) for p in priorities],
    }).encode('utf-8')
    payload = retry(
        lambda: self._post('/v1/update_priorities', body,
                           'application/json'),
        policy=self._retry_policy, site='replay.update_priorities')
    return int(json.loads(payload).get('landed', 0))

  def stats(self) -> Dict[str, object]:
    request = urllib.request.Request(self.endpoint + '/healthz')
    try:
      with urllib.request.urlopen(request,
                                  timeout=self._timeout_s) as response:
        return json.loads(response.read())
    except urllib.error.URLError as e:
      raise ReplayUnavailable('{} unreachable: {}'.format(
          self.endpoint, e)) from e


class LocalReplayClient:
  """The ReplayClient API over an in-process ReplayService."""

  def __init__(self, service: ReplayService):
    self._service = service

  def append(self, example, priority: float = 1.0) -> int:
    blob = example if isinstance(example, (bytes, bytearray)) \
        else wire.encode_example(example)
    return self._service.append(bytes(blob), priority=priority)

  def sample(self, batch_size: Optional[int] = None,
             wait: bool = False,
             wait_timeout_s: float = 60.0,
             poll_interval_s: float = 0.2) -> SampleBatch:
    if not wait:
      return self._service.sample(batch_size)
    deadline = time.monotonic() + wait_timeout_s
    while True:
      try:
        return self._service.sample(batch_size)
      except ReplayEmpty:
        if time.monotonic() >= deadline:
          raise
        time.sleep(poll_interval_s)

  def update_priorities(self, record_ids: Sequence[Tuple[int, int]],
                        priorities: Sequence[float]) -> int:
    return self._service.update_priorities(record_ids, priorities)

  def stats(self) -> Dict[str, object]:
    return self._service.stats()
