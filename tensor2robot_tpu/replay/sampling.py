"""Sampling policies: which resident examples a replay draw returns.

Policies are PURE functions over one shard's priority snapshot: the
service takes an atomic ``(ids, priorities)`` snapshot under the shard
lock, the policy draws slot indices against that snapshot, and the
fetch goes back through the STABLE ids — so a ring slide between the
snapshot and the fetch can never silently resolve a drawn slot to a
neighboring record (dead ids are skipped and redrawn instead). The
service owns the cross-shard split (proportional to occupancy) and the
assembly. Draws are with replacement — a learner batch may
legitimately repeat an example when the store is small or priorities
are concentrated, and with-replacement keeps every draw O(batch)
instead of O(occupancy).

  * ``uniform`` — every resident example equally likely. Over a
    reservoir store this makes the sampled distribution uniform over
    the whole APPEND STREAM (the store is already a uniform subsample);
    over a ring store it is uniform over the retained window.
  * ``prioritized`` — P(i) ∝ priority_i ** alpha (Schaul et al.,
    arXiv 1511.05952): alpha=0 degrades to uniform, alpha=1 is fully
    proportional. Weights refresh from the store at every draw, so
    ``update_priorities`` from the learner takes effect on the next
    batch without any rebuild.

Statistical contracts (draw frequencies within tolerance) are pinned in
tests/test_replay.py.
"""

from __future__ import annotations

from typing import List

import numpy as np

__all__ = ['SamplePolicy', 'UniformPolicy', 'PrioritizedPolicy',
           'make_policy', 'POLICIES']

POLICIES = ('uniform', 'prioritized')


class SamplePolicy:
  """Draws ``count`` slot indices against one priority snapshot."""

  name = 'abstract'

  def draw(self, priorities: np.ndarray, count: int,
           rng: np.random.RandomState) -> List[int]:
    raise NotImplementedError


class UniformPolicy(SamplePolicy):

  name = 'uniform'

  def draw(self, priorities: np.ndarray, count: int,
           rng: np.random.RandomState) -> List[int]:
    if priorities.size == 0:
      return []
    return rng.randint(0, priorities.size, size=count).tolist()


class PrioritizedPolicy(SamplePolicy):
  """P(i) ∝ priority_i ** alpha over the snapshot handed in per draw."""

  name = 'prioritized'

  def __init__(self, alpha: float = 0.6):
    if alpha < 0.0:
      raise ValueError('alpha must be >= 0; got {}.'.format(alpha))
    self.alpha = float(alpha)

  def draw(self, priorities: np.ndarray, count: int,
           rng: np.random.RandomState) -> List[int]:
    if priorities.size == 0:
      return []
    weights = np.power(np.maximum(priorities, 0.0), self.alpha)
    total = float(weights.sum())
    if total <= 0.0:  # all-zero priorities: degrade to uniform, not a crash
      return rng.randint(0, priorities.size, size=count).tolist()
    return rng.choice(priorities.size, size=count, replace=True,
                      p=weights / total).tolist()


def make_policy(name: str, alpha: float = 0.6) -> SamplePolicy:
  if name == 'uniform':
    return UniformPolicy()
  if name == 'prioritized':
    return PrioritizedPolicy(alpha=alpha)
  raise ValueError('unknown sampling policy {!r}; have {}.'.format(
      name, POLICIES))
