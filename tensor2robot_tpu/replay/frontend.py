"""Stdlib HTTP door for a ReplayService (``t2r_replay``).

One thread per connection (``ThreadingHTTPServer``) — appends from N
collectors land directly on the sharded stores (each shard has its own
lock), and sample requests coalesce through the service's
DeadlineBatcher front-end. The wire format IS the replay record format:
request/response bodies are the binary records of replay/wire.py
(``application/octet-stream``), never JSON-wrapped — base64'ing a 70 KB
packed example would hand back a third of the packed wire's win.

Endpoints:
  * ``POST /v1/append[?priority=<float>]`` — body: ONE packed example
    record. 200 -> ``{"shard": i, "shard_occupancy_examples": n}``;
    400 when
    the record fails wire validation (it was counted against the
    shard's quarantine budget and dropped — fix the writer); 507 when a
    quarantine budget is exhausted (the service refuses further damage).
  * ``POST /v1/sample`` — body: ``{"batch_size": n}`` JSON (empty body
    = the service default). 200 -> one encoded megabatch (decode with
    ``wire.decode_example``; keys are ``features/...``/``labels/...``
    plus a ``__record_ids__`` [B, 2] int64 array of (shard, record_id)
    for priority updates). 409 when the store is empty (retry after
    appends land), 503 when admission control sheds the request.
  * ``POST /v1/update_priorities`` — ``{"record_ids": [[shard, id]...],
    "priorities": [...]}`` JSON -> ``{"landed": n}``.
  * ``GET /healthz`` — cumulative :meth:`ReplayService.stats` JSON.
  * ``GET /metricz`` — the registry's ``replay/`` scalars.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Tuple
from urllib.parse import parse_qs, urlparse

import numpy as np

from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.reliability.errors import CorruptionBudgetExceeded
from tensor2robot_tpu.replay import wire
from tensor2robot_tpu.replay.service import ReplayEmpty, ReplayService
from tensor2robot_tpu.serving.batching import RequestRejected

__all__ = ['build_http_server', 'RECORD_IDS_KEY']

# Rides inside the sampled megabatch record: [B, 2] int64 (shard, id).
RECORD_IDS_KEY = '__record_ids__'


class _Handler(BaseHTTPRequestHandler):
  # Set by build_http_server on the subclass.
  replay_service: ReplayService = None
  request_timeout_s: float = 60.0

  def log_message(self, *args) -> None:  # quiet: telemetry is the log
    pass

  def _reply_json(self, status: int, payload: dict) -> None:
    body = json.dumps(payload).encode('utf-8')
    self.send_response(status)
    self.send_header('Content-Type', 'application/json')
    self.send_header('Content-Length', str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def _reply_record(self, blob: bytes) -> None:
    self.send_response(200)
    self.send_header('Content-Type', 'application/octet-stream')
    self.send_header('Content-Length', str(len(blob)))
    self.end_headers()
    self.wfile.write(blob)

  def _body(self) -> bytes:
    length = int(self.headers.get('Content-Length', 0))
    return self.rfile.read(length) if length else b''

  def do_GET(self) -> None:  # noqa: N802 — http.server API
    if self.path == '/healthz':
      self._reply_json(200, self.replay_service.stats())
    elif self.path == '/metricz':
      scalars = get_registry().scalars()
      self._reply_json(200, {tag: value
                             for tag, value in sorted(scalars.items())
                             if tag.startswith('replay/')})
    else:
      self._reply_json(404, {'error': 'unknown path {}'.format(self.path)})

  def do_POST(self) -> None:  # noqa: N802 — http.server API
    parsed = urlparse(self.path)
    if parsed.path == '/v1/append':
      self._append(parsed)
    elif parsed.path == '/v1/sample':
      self._sample()
    elif parsed.path == '/v1/update_priorities':
      self._update_priorities()
    else:
      self._reply_json(404, {'error': 'unknown path {}'.format(self.path)})

  def _append(self, parsed) -> None:
    try:
      priority = float(
          parse_qs(parsed.query).get('priority', ['1.0'])[0])
    except ValueError:
      self._reply_json(400, {'error': 'priority must be a float'})
      return
    blob = self._body()
    if not blob:
      self._reply_json(400, {'error': 'empty append body'})
      return
    try:
      shard = self.replay_service.append(blob, priority=priority)
    except CorruptionBudgetExceeded as e:
      self._reply_json(507, {'error': str(e)})
      return
    except wire.ReplayWireError as e:
      self._reply_json(400, {'error': 'corrupt record (quarantined): {}'
                             .format(e), 'quarantined': True})
      return
    # The RECEIVING shard's occupancy only: reporting the service total
    # would take every shard's lock on every append, serializing the
    # per-shard concurrency N writers rely on.
    self._reply_json(200, {
        'shard': shard,
        'shard_occupancy_examples':
            self.replay_service.shard_occupancy(shard)})

  def _sample(self) -> None:
    body = self._body()
    batch_size = None
    try:
      if body:
        payload = json.loads(body)
        if not isinstance(payload, dict):
          raise ValueError('body must be a JSON object')
        batch_size = payload.get('batch_size')
        if batch_size is not None:
          # Coerce HERE so a non-integer is a 400, not an exception
          # escaping the handler as a dropped connection (the PR-7 bug
          # class the serving frontend already fixed).
          batch_size = int(batch_size)
          if batch_size < 1:
            raise ValueError('batch_size must be >= 1')
    except (ValueError, TypeError) as e:
      self._reply_json(400, {'error': 'bad request: {}'.format(e)})
      return
    try:
      future = self.replay_service.submit_sample(batch_size)
    except RequestRejected as e:
      self._reply_json(503, {'error': str(e)})
      return
    except RuntimeError as e:  # racing shutdown: clean "try elsewhere"
      self._reply_json(503, {'error': str(e)})
      return
    try:
      result = future.result(timeout=self.request_timeout_s)
    except ReplayEmpty as e:
      self._reply_json(409, {'error': str(e)})
      return
    except Exception as e:  # noqa: BLE001 — surface the sample failure
      self._reply_json(500, {'error': '{}: {}'.format(type(e).__name__, e)})
      return
    flat = {}
    flat.update({'features/' + k: v for k, v in result.features.items()})
    flat.update({'labels/' + k: v for k, v in result.labels.items()})
    flat[RECORD_IDS_KEY] = np.asarray(result.record_ids, np.int64)
    self._reply_record(wire.encode_example(flat))

  def _update_priorities(self) -> None:
    try:
      payload = json.loads(self._body() or b'{}')
      record_ids = [(int(s), int(i)) for s, i in payload['record_ids']]
      priorities = [float(p) for p in payload['priorities']]
      if len(record_ids) != len(priorities):
        raise ValueError('record_ids and priorities disagree on length')
    except (ValueError, TypeError, KeyError) as e:
      self._reply_json(400, {'error': 'bad request: {}'.format(e)})
      return
    landed = self.replay_service.update_priorities(record_ids, priorities)
    self._reply_json(200, {'landed': landed})


def build_http_server(replay_service: ReplayService,
                      host: str = '127.0.0.1',
                      port: int = 0,
                      request_timeout_s: float = 60.0
                      ) -> Tuple[ThreadingHTTPServer, int]:
  """Binds the HTTP front end; returns ``(httpd, bound_port)``.

  ``port=0`` binds an ephemeral port (tests). Call
  ``httpd.serve_forever()`` (blocking) or drive it from a thread;
  ``httpd.shutdown()`` stops it — then close the ReplayService.
  """
  handler = type('ReplayHandler', (_Handler,), {
      'replay_service': replay_service,
      'request_timeout_s': request_timeout_s,
  })
  httpd = ThreadingHTTPServer((host, port), handler)
  return httpd, httpd.server_address[1]
