"""Per-example packed replay records: the ``coef_packed`` wire, at rest.

The replay service's storage unit is ONE example, serialized so that it
round-trips the native loader's packed batch layout bit-exactly
(ISSUE 11 tentpole). Three jobs live here:

  * ``encode_example`` / ``decode_example`` — a self-describing binary
    record: named numpy arrays (dtype + shape + raw bytes) behind a
    magic/version header, framed with the same varint primitives the
    Example codec uses (data/wire.py). No pickle — a replay shard must
    never execute bytes a collector sent it — and no JSON — base64'ing
    a 70 KB coefficient stream would undo the packed wire's 1.76x win.
  * ``split_batch`` — a native-loader ``coef_packed`` batch becomes B
    per-example records. The batch's bucketed stream buffers are
    TRIMMED back to each row's actual payload (the packed wire's
    trailing bytes are 0x00 no-op padding by construction, and escape
    entries are never 0 — an AC escape codes ``|v| > 7``, a DC escape
    ``|delta| > 7`` — so trailing zeros are provably padding), and the
    batch-hoisted ``[1, 3, 64]`` quant table is denormalized back onto
    every example so each record is self-contained.
  * ``assemble_batch`` — B records become one batch with EXACTLY the
    native loader's layout: streams zero-padded to the batch max,
    rounded up to the same ``PACKED_BUCKET`` / ``ESCAPE_BUCKET``
    granularities (bounded unpack-jit cache), quant tables re-hoisted
    under the same batch-uniformity contract (mismatch is a hard error
    naming ``coef_sparse`` as the remedy). A sampled batch is therefore
    byte-identical in signature to a disk batch — ``SparseCoefFeed``
    cannot tell them apart.

Corruption surfaces as :class:`ReplayWireError` (bad magic, truncation,
undeclared dtype, size mismatch) — the validation the service charges
against its per-shard quarantine budgets.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from tensor2robot_tpu.data.native_loader import ESCAPE_BUCKET, PACKED_BUCKET
from tensor2robot_tpu.data.wire import write_varint

__all__ = ['ReplayWireError', 'encode_example', 'decode_example',
           'split_batch', 'assemble_batch', 'packed_group_keys',
           'example_nbytes', 'REPLAY_WIRE_MAGIC', 'REPLAY_WIRE_VERSION']

REPLAY_WIRE_MAGIC = b'T2RX'
REPLAY_WIRE_VERSION = 1

# Dtypes a record may carry. An allowlist, not a passthrough: decode
# constructs dtypes from attacker-controllable strings, and np.dtype()
# accepts far more than arrays we ever ship (incl. object).
_ALLOWED_DTYPES = ('<f8', '<f4', '<f2', '<i8', '<i4', '<i2', '<u8',
                   '<u4', '<u2', '|i1', '|u1', '|b1')


class ReplayWireError(ValueError):
  """A replay record failed structural validation (corrupt append)."""


def encode_example(entries: Dict[str, np.ndarray]) -> bytes:
  """Serializes ``{key: array}`` into one self-describing record."""
  out = bytearray()
  out.extend(REPLAY_WIRE_MAGIC)
  write_varint(out, REPLAY_WIRE_VERSION)
  write_varint(out, len(entries))
  for key in sorted(entries):
    array = np.asarray(entries[key])
    if array.ndim:  # ascontiguousarray would promote a 0-d to rank 1
      array = np.ascontiguousarray(array)
    dtype = np.dtype(array.dtype).str
    if dtype not in _ALLOWED_DTYPES:
      # bfloat16 (and any other 2-byte extension type) ships as its raw
      # view; the consumer reinterprets from the spec, exactly like the
      # native loader's byte buffers.
      if array.dtype.itemsize == 2:
        array = array.view(np.uint16)
        dtype = '<u2'
      else:
        raise ReplayWireError(
            'cannot encode dtype {} for {!r}'.format(array.dtype, key))
    name = key.encode('utf-8')
    write_varint(out, len(name))
    out.extend(name)
    dt = dtype.encode('ascii')
    write_varint(out, len(dt))
    out.extend(dt)
    write_varint(out, array.ndim)
    for dim in array.shape:
      write_varint(out, int(dim))
    payload = array.tobytes()
    write_varint(out, len(payload))
    out.extend(payload)
  return bytes(out)


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
  result = 0
  shift = 0
  end = len(buf)
  while True:
    if pos >= end:
      raise ReplayWireError('record truncated inside a varint')
    b = buf[pos]
    pos += 1
    result |= (b & 0x7F) << shift
    if not b & 0x80:
      return result, pos
    shift += 7
    if shift > 63:
      raise ReplayWireError('malformed varint')


def decode_example(blob: bytes) -> Dict[str, np.ndarray]:
  """Parses one record back into ``{key: array}``; raises ReplayWireError.

  Array payloads are zero-copy views onto ``blob`` (frombuffer) — the
  store keeps records as bytes, so a sampled batch assembles without an
  extra copy per field.
  """
  buf = memoryview(blob)
  if len(buf) < 4 or bytes(buf[:4]) != REPLAY_WIRE_MAGIC:
    raise ReplayWireError('bad magic (not a replay record)')
  pos = 4
  version, pos = _read_varint(buf, pos)
  if version != REPLAY_WIRE_VERSION:
    raise ReplayWireError('unsupported record version {}'.format(version))
  count, pos = _read_varint(buf, pos)
  if count > 4096:
    raise ReplayWireError('implausible entry count {}'.format(count))
  entries: Dict[str, np.ndarray] = {}
  for _ in range(count):
    name_len, pos = _read_varint(buf, pos)
    if pos + name_len > len(buf):
      raise ReplayWireError('record truncated inside a name')
    key = bytes(buf[pos:pos + name_len]).decode('utf-8', 'strict')
    pos += name_len
    dt_len, pos = _read_varint(buf, pos)
    if pos + dt_len > len(buf):
      raise ReplayWireError('record truncated inside a dtype')
    dtype_str = bytes(buf[pos:pos + dt_len]).decode('ascii', 'strict')
    pos += dt_len
    if dtype_str not in _ALLOWED_DTYPES:
      raise ReplayWireError('undeclared dtype {!r} for {!r}'.format(
          dtype_str, key))
    dtype = np.dtype(dtype_str)
    ndim, pos = _read_varint(buf, pos)
    if ndim > 16:
      raise ReplayWireError('implausible rank {} for {!r}'.format(ndim, key))
    shape = []
    for _ in range(ndim):
      dim, pos = _read_varint(buf, pos)
      shape.append(dim)
    payload_len, pos = _read_varint(buf, pos)
    if pos + payload_len > len(buf):
      raise ReplayWireError('record truncated inside {!r}'.format(key))
    n_elems = int(np.prod(shape, dtype=np.int64)) if ndim else 1
    if payload_len != n_elems * dtype.itemsize:
      raise ReplayWireError(
          'payload size {} != shape {} x {} for {!r}'.format(
              payload_len, shape, dtype_str, key))
    if n_elems == 0:
      array = np.zeros(shape, dtype)
    else:
      array = np.frombuffer(buf, dtype=dtype, count=n_elems, offset=pos)
      array = array.reshape(shape) if ndim else array[0]
    pos += payload_len
    entries[key] = array
  if pos != len(buf):
    raise ReplayWireError('{} trailing bytes after the last entry'.format(
        len(buf) - pos))
  return entries


def example_nbytes(entries: Dict[str, np.ndarray]) -> int:
  """Payload bytes of one decoded record (at-rest accounting helper)."""
  return int(sum(np.asarray(v).nbytes for v in entries.values()))


def packed_group_keys(keys) -> List[str]:
  """Base keys of every packed image group present (``<base>/pw``)."""
  return sorted(key[:-3] for key in keys if key.endswith('/pw'))


def _trimmed_length(row: np.ndarray) -> int:
  """Length of ``row`` with trailing zeros removed (payload, not padding).

  Sound for ``pw`` (0x00 is the no-op padding byte, never emitted inside
  a stream) and ``se`` (escape values are never 0 — see module
  docstring). NOT generic: do not apply to dense features.
  """
  nonzero = np.flatnonzero(row)
  return int(nonzero[-1]) + 1 if nonzero.size else 0


def split_batch(features: Dict[str, np.ndarray],
                labels: Optional[Dict[str, np.ndarray]] = None
                ) -> List[bytes]:
  """One native-loader batch -> B per-example replay records.

  ``features``/``labels`` are flat ``{key: array}`` dicts (SpecStructs'
  ``to_dict()`` output). Packed stream buffers are trimmed per row; the
  batch-hoisted quant table is copied onto every example (records must
  be self-contained — a record sampled into a DIFFERENT batch needs its
  own table for the uniformity check).
  """
  sides = [('features', dict(features))]
  if labels:
    sides.append(('labels', dict(labels)))
  flat: Dict[str, np.ndarray] = {}
  batch = 0
  for side, values in sides:
    for key, value in values.items():
      array = np.asarray(value)
      flat[side + '/' + key] = array
  packed_bases = packed_group_keys(flat)
  for key, array in flat.items():
    if any(key == base + '/qt' for base in packed_bases):
      continue  # hoisted [1, 3, 64]: not a batch-dim array
    if array.ndim and (batch in (0, 1)):
      batch = int(array.shape[0])
      if batch > 1:
        break
  if not batch:
    raise ReplayWireError('cannot infer the batch dimension')
  records: List[bytes] = []
  for row in range(batch):
    entries: Dict[str, np.ndarray] = {}
    for key, array in flat.items():
      base = key[:-3] if key.endswith(('/pw', '/se')) else None
      if base in packed_bases:
        stream = array[row]
        entries[key] = stream[:_trimmed_length(stream)]
      elif any(key == b + '/qt' for b in packed_bases):
        entries[key] = array[0] if array.shape[0] == 1 else array[row]
      else:
        entries[key] = array[row]
    records.append(encode_example(entries))
  return records


def _bucket(length: int, granularity: int) -> int:
  return max(granularity, -(-length // granularity) * granularity)


def _hoist_quant_tables(rows: np.ndarray, base: str) -> np.ndarray:
  """Re-hoists per-example [3, 64] tables to the wire's [1, 3, 64].

  Same contract as the native loader's ``_hoisted_quant_table``:
  all-zero rows are empty payloads (skipped), a genuine mismatch is a
  hard error naming ``coef_sparse`` as the remedy, an all-empty batch
  ships 1s (the well-defined-dequant convention for zero images).
  """
  flat = rows.reshape(rows.shape[0], -1)
  present = flat.any(axis=1)
  if not present.any():
    return np.ones((1,) + rows.shape[1:], rows.dtype)
  first = int(np.argmax(present))
  if not (flat[present] == flat[first]).all():
    raise ReplayWireError(
        "replay sample: packed batch requires batch-uniform JPEG "
        "quantization tables for '{}' (the packed wire ships ONE table "
        "per batch); these examples mix qualities — collect with "
        "image_mode='coef_sparse' instead.".format(base))
  return rows[first:first + 1].copy()


def assemble_batch(examples: List[Dict[str, np.ndarray]]
                   ) -> Dict[str, np.ndarray]:
  """B decoded records -> one flat batch dict in native-loader layout.

  Every record must carry the same key set (one spec per service). The
  packed streams are padded with zeros to the batch max, rounded up to
  the loader's bucket granularities; quant tables re-hoist to [1, 3, 64].
  """
  if not examples:
    raise ReplayWireError('assemble_batch needs at least one example')
  keys = sorted(examples[0])
  for entry in examples[1:]:
    if sorted(entry) != keys:
      raise ReplayWireError(
          'examples disagree on keys: {} vs {}'.format(keys,
                                                       sorted(entry)))
  packed_bases = packed_group_keys(keys)
  out: Dict[str, np.ndarray] = {}
  for key in keys:
    rows = [np.asarray(entry[key]) for entry in examples]
    base = key[:-3] if key.endswith(('/pw', '/se')) else None
    if base in packed_bases:
      granularity = PACKED_BUCKET if key.endswith('/pw') else ESCAPE_BUCKET
      width = _bucket(max(row.shape[0] for row in rows), granularity)
      stacked = np.zeros((len(rows), width), rows[0].dtype)
      for i, row in enumerate(rows):
        stacked[i, :row.shape[0]] = row
      out[key] = stacked
    elif any(key == b + '/qt' for b in packed_bases):
      out[key] = _hoist_quant_tables(np.stack(rows, axis=0), key[:-3])
    else:
      out[key] = np.stack(rows, axis=0)
  return out
