"""Learner-side replay feed: sampled megabatches into the trainer.

The last hop of the replay tentpole: batches a client samples from the
service become ``(features, labels)`` SpecStructs with EXACTLY the
native loader's layout, so they drop into the existing trainer path
unchanged — prefetch wraps them (input_generators.prefetch_iterator),
``PipelinedFeed`` overlaps their transfer, and ``SparseCoefFeed``
unpacks their packed coefficient groups in the same per-bucket jit it
uses for disk batches. The train step's input signature is byte-
identical to reading from local disk; the jit cache cannot tell the
difference.

The replay hop meters the pipeline X-ray's ``read`` stage (the service
IS this learner's record source): a stalled replay service shows up as
a read-gated window and — through the existing watchdog loop — a
``pipeline_stall`` capture, exactly like a stalled disk.

``ReplayInputGenerator`` is the config-visible binding
(``--replay_endpoint`` in bin/run_t2r_trainer): an
AbstractInputGenerator whose iterator samples forever, so
``max_train_steps`` (not epochs) bounds the run — replay is a stream,
not a dataset.
"""

from __future__ import annotations

import time
from typing import Iterator, Optional, Union

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data.input_generators import AbstractInputGenerator
from tensor2robot_tpu.observability.pipeline_xray import StageMeter
from tensor2robot_tpu.replay.client import LocalReplayClient, ReplayClient
from tensor2robot_tpu.replay.service import ReplayService, SampleBatch
from tensor2robot_tpu.specs.struct import SpecStruct

__all__ = ['ReplayBatchIterator', 'ReplayInputGenerator', 'to_spec_structs']


def to_spec_structs(batch: SampleBatch):
  """A sampled batch as (features, labels) SpecStructs."""
  features = SpecStruct()
  labels = SpecStruct()
  for key, value in batch.features.items():
    features[key] = value
  for key, value in batch.labels.items():
    labels[key] = value
  return features, labels


def _batch_nbytes(batch: SampleBatch) -> int:
  return int(sum(np.asarray(v).nbytes for v in batch.features.values())
             + sum(np.asarray(v).nbytes for v in batch.labels.values()))


def _batch_examples(batch: SampleBatch) -> int:
  for value in batch.features.values():
    shape = getattr(value, 'shape', None)
    if shape and shape[0] > 1:
      return int(shape[0])
  return 1 if batch.features else 0


class ReplayBatchIterator:
  """Iterator of (features, labels) SpecStruct batches from a client.

  ``num_batches=None`` iterates forever (the replay stream has no
  epochs). The first draw ``wait``s for the store to fill (a learner
  booting before its collectors); later draws fail fast so a DRAINED
  store surfaces instead of hanging silently.
  """

  def __init__(self, client, batch_size: int,
               num_batches: Optional[int] = None,
               wait_timeout_s: float = 60.0):
    self._client = client
    self._batch_size = int(batch_size)
    self._num_batches = num_batches
    self._wait_timeout_s = float(wait_timeout_s)
    self._drawn = 0
    self._read_meter = StageMeter('read')

  def __iter__(self):
    return self

  def __next__(self):
    if self._num_batches is not None and self._drawn >= self._num_batches:
      raise StopIteration
    t0 = time.perf_counter()
    batch = self._client.sample(self._batch_size,
                                wait=self._drawn == 0,
                                wait_timeout_s=self._wait_timeout_s)
    self._read_meter.add(examples=_batch_examples(batch),
                         nbytes=_batch_nbytes(batch),
                         busy_s=time.perf_counter() - t0)
    self._drawn += 1
    return to_spec_structs(batch)


class ReplayInputGenerator(AbstractInputGenerator):
  """Feeds a trainer from a replay endpoint (or in-process service).

  ``endpoint``: an ``host:port`` / ``http://...`` replay service, an
  existing client, or a :class:`ReplayService` instance (wrapped in a
  LocalReplayClient). Batches are validated against the model's specs
  unless they carry packed coefficient groups (which intentionally
  mismatch the image specs — the device finishes the decode, same rule
  as the native loader's coef streams).
  """

  def __init__(self, endpoint: Union[str, ReplayService, object],
               batch_size: int = 32,
               prefetch: int = 2,
               wait_timeout_s: float = 60.0):
    super().__init__(batch_size=batch_size, prefetch=prefetch)
    if isinstance(endpoint, str):
      self._client = ReplayClient(endpoint)
    elif isinstance(endpoint, ReplayService):
      self._client = LocalReplayClient(endpoint)
    else:
      self._client = endpoint  # anything with the client API
    self._wait_timeout_s = float(wait_timeout_s)

  @property
  def client(self):
    return self._client

  def _create_iterator(self, mode, num_epochs, shard_index, num_shards,
                       seed) -> Iterator:
    # num_epochs bounds BATCHES here (a stream has no epoch); None runs
    # until the trainer's max_train_steps stops consuming.
    iterator = ReplayBatchIterator(self._client, self._batch_size,
                                   num_batches=num_epochs,
                                   wait_timeout_s=self._wait_timeout_s)
    if self._feature_spec is None:
      return iterator

    def _validated():
      for features, labels in iterator:
        if any(key.endswith('/pw') or key.endswith('/sd')
               for key in features):
          # Packed/sparse coefficient groups intentionally mismatch the
          # image specs (the device unpacks them) — same skip rule as
          # NativeBatchedStream._pack's coef branch.
          yield features, labels
          continue
        features = specs_lib.validate_and_pack(
            self._feature_spec, features, ignore_batch=True)
        if labels is not None and len(self._label_spec):
          labels = specs_lib.validate_and_pack(
              self._label_spec, labels, ignore_batch=True)
        yield features, labels

    return _validated()
