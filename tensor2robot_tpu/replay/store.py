"""In-RAM shard stores: packed records at rest, bounded, evicting.

One :class:`ShardStore` holds one shard's examples as the raw replay
records the wire delivered — packed bytes, never decoded copies, so the
~70 KB/example ``coef_packed`` economics carry through to host RAM
(~14k examples/GB). Two retention disciplines:

  * ``ring`` — FIFO sliding window: at capacity the OLDEST example is
    evicted. The classic off-policy replay window (QT-Opt's deployment
    kept the freshest N robot-hours).
  * ``reservoir`` — Vitter's Algorithm R over the append stream: at
    capacity each arriving example replaces a uniformly random slot
    with probability ``capacity / appends_seen``, else is dropped — the
    store remains a uniform sample of EVERYTHING ever appended, which
    is what keeps old successful grasps represented in a run that
    collects forever.

Capacity is bounded by examples AND bytes (whichever trips first): RAM
is the real budget, and packed records vary in size with scene entropy.

Priorities ride along per record (``priority`` at append,
``update_priorities`` after a learner step) for the prioritized
sampling policy; the store itself never interprets them. Records are
addressed by STABLE ids across evictions — a priority update racing a
ring slide must never land on the wrong example.

Thread-safe: every public method takes the shard lock. Sampling reads
under the same lock (index draw + blob refs are cheap; decode happens
outside the lock in the service).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ['ShardStore', 'RETENTIONS']

RETENTIONS = ('ring', 'reservoir')


class ShardStore:
  """Bounded packed-record store for one shard."""

  def __init__(self,
               capacity_examples: int = 4096,
               capacity_bytes: Optional[int] = None,
               retention: str = 'ring',
               seed: Optional[int] = None):
    if capacity_examples < 1:
      raise ValueError('capacity_examples must be >= 1; got {}.'.format(
          capacity_examples))
    if retention not in RETENTIONS:
      raise ValueError('retention must be one of {}; got {!r}.'.format(
          RETENTIONS, retention))
    self.capacity_examples = int(capacity_examples)
    self.capacity_bytes = None if capacity_bytes is None \
        else int(capacity_bytes)
    self.retention = retention
    self._lock = threading.Lock()
    self._rng = np.random.RandomState(seed)
    self._blobs: List[bytes] = []
    self._priorities: List[float] = []
    self._ids: List[int] = []           # stable per-record ids, slot-parallel
    self._id_to_slot: Dict[int, int] = {}
    self._next_id = 0
    self._bytes = 0
    self._appends = 0       # accepted appends (reservoir stream length)
    self._evictions = 0     # slots overwritten / dropped arrivals
    self._samples = 0       # examples drawn

  # -- occupancy -------------------------------------------------------------

  def __len__(self) -> int:
    with self._lock:
      return len(self._blobs)

  @property
  def occupancy_examples(self) -> int:
    with self._lock:
      return len(self._blobs)

  @property
  def occupancy_bytes(self) -> int:
    with self._lock:
      return self._bytes

  def counters(self) -> Dict[str, int]:
    with self._lock:
      return {
          'occupancy_examples': len(self._blobs),
          'occupancy_bytes': self._bytes,
          'appends': self._appends,
          'evictions': self._evictions,
          'samples': self._samples,
      }

  # -- append / evict --------------------------------------------------------

  def _over_bytes_locked(self, incoming: int) -> bool:
    return (self.capacity_bytes is not None
            and self._bytes + incoming > self.capacity_bytes
            and bool(self._blobs))

  def _insert_locked(self, blob: bytes, priority: float) -> None:
    slot = len(self._blobs)
    self._blobs.append(blob)
    self._priorities.append(float(priority))
    self._ids.append(self._next_id)
    if self.retention == 'reservoir':
      # Ring slots hold CONSECUTIVE ids (insert at tail, evict at head),
      # so their id->slot map is arithmetic; only reservoir replacement
      # scatters ids and needs the dict.
      self._id_to_slot[self._next_id] = slot
    self._next_id += 1
    self._bytes += len(blob)

  def append(self, blob: bytes, priority: float = 1.0) -> bool:
    """Stores one packed record; returns whether it is now resident.

    ``ring``: evicts from the FRONT until both capacity bounds admit the
    arrival. ``reservoir``: replaces a uniform random slot once full
    (with the Algorithm-R acceptance probability), so a False return
    means the arrival was sampled OUT, not lost to an error.
    """
    size = len(blob)
    with self._lock:
      self._appends += 1
      if self.retention == 'ring':
        while (len(self._blobs) >= self.capacity_examples
               or self._over_bytes_locked(size)):
          self._evict_front_locked()
        self._insert_locked(blob, priority)
        return True
      # reservoir
      if (len(self._blobs) < self.capacity_examples
          and not self._over_bytes_locked(size)):
        self._insert_locked(blob, priority)
        return True
      slot = int(self._rng.randint(0, self._appends))
      if slot >= len(self._blobs):
        self._evictions += 1  # arrival sampled out
        return False
      self._bytes += size - len(self._blobs[slot])
      self._blobs[slot] = blob
      self._priorities[slot] = float(priority)
      del self._id_to_slot[self._ids[slot]]
      self._ids[slot] = self._next_id
      self._id_to_slot[self._next_id] = slot
      self._next_id += 1
      self._evictions += 1
      # A replacement can GROW the byte footprint (records grow with
      # scene entropy); the byte bound must hold on this path too —
      # trim uniformly random slots (the reservoir is unordered, so a
      # uniform victim keeps the retained set a uniform sample) until
      # the documented 'whichever trips first' cap is honored again.
      while (self.capacity_bytes is not None
             and self._bytes > self.capacity_bytes
             and len(self._blobs) > 1):
        self._evict_reservoir_slot_locked(
            int(self._rng.randint(0, len(self._blobs))))
      return True

  def _evict_front_locked(self) -> None:
    victim = self._blobs.pop(0)
    self._priorities.pop(0)
    self._ids.pop(0)
    self._bytes -= len(victim)
    self._evictions += 1

  def _evict_reservoir_slot_locked(self, slot: int) -> None:
    """O(1) unordered removal: swap the last slot in, pop the tail."""
    victim = self._blobs[slot]
    del self._id_to_slot[self._ids[slot]]
    last = len(self._blobs) - 1
    if slot != last:
      self._blobs[slot] = self._blobs[last]
      self._priorities[slot] = self._priorities[last]
      self._ids[slot] = self._ids[last]
      self._id_to_slot[self._ids[slot]] = slot
    self._blobs.pop()
    self._priorities.pop()
    self._ids.pop()
    self._bytes -= len(victim)
    self._evictions += 1

  def _slot_for_locked(self, record_id: int) -> Optional[int]:
    if self.retention == 'reservoir':
      return self._id_to_slot.get(record_id)
    if not self._ids or not self._ids[0] <= record_id <= self._ids[-1]:
      return None
    return record_id - self._ids[0]

  # -- sampling --------------------------------------------------------------

  def priorities(self) -> np.ndarray:
    with self._lock:
      return np.asarray(self._priorities, np.float64)

  def snapshot(self) -> Tuple[List[int], np.ndarray]:
    """Atomic ``(stable ids, priorities)`` view for one draw.

    A policy draws slot indices against THIS snapshot and the fetch
    goes back through the ids (:meth:`get_by_ids`) — a ring slide
    between snapshot and fetch can therefore never resolve a drawn
    slot to a neighboring record; the dead id is skipped and the
    service redraws the shortfall.
    """
    with self._lock:
      return list(self._ids), np.asarray(self._priorities, np.float64)

  def get_many(self, slots: Sequence[int]) -> Tuple[List[bytes], List[int]]:
    """(blob refs, stable ids) for CURRENT slot indices; out-of-range
    slots are skipped. Direct-slot access for tests/tools — the
    sampling path goes through :meth:`snapshot` + :meth:`get_by_ids`."""
    with self._lock:
      n = len(self._blobs)
      live = [slot for slot in slots if 0 <= slot < n]
      blobs = [self._blobs[slot] for slot in live]
      ids = [self._ids[slot] for slot in live]
      self._samples += len(blobs)
      return blobs, ids

  def get_by_ids(self, record_ids: Sequence[int]
                 ) -> Tuple[List[bytes], List[int]]:
    """(blob refs, ids) for the drawn ids that are STILL resident
    (counted as samples); evicted ids are skipped, not an error — a
    concurrent append on a byte-bounded shard can evict several
    records for one arrival, and the caller redraws the shortfall
    (service.sample) instead of crashing the learner on a race."""
    with self._lock:
      blobs: List[bytes] = []
      live: List[int] = []
      for record_id in record_ids:
        slot = self._slot_for_locked(int(record_id))
        if slot is not None:
          blobs.append(self._blobs[slot])
          live.append(int(record_id))
      self._samples += len(blobs)
      return blobs, live

  def update_priorities(self, record_ids: Sequence[int],
                        priorities: Sequence[float]) -> int:
    """Re-weights resident records (prioritized replay's learner half).

    Ids evicted since the draw are skipped silently — a ring store may
    have slid past them, and a stale priority update must not crash the
    learner (or land on a DIFFERENT record: ids are stable, slots are
    not). Returns how many updates landed.
    """
    landed = 0
    with self._lock:
      for record_id, priority in zip(record_ids, priorities):
        slot = self._slot_for_locked(int(record_id))
        if slot is not None:
          self._priorities[slot] = float(priority)
          landed += 1
    return landed
