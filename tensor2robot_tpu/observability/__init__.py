"""Unified telemetry layer: metrics registry, spans, goodput, run files.

The measurement substrate every perf/reliability PR builds on (ISSUE 3):

  * ``TelemetryRegistry`` (`registry.py`) — process-wide, thread-safe
    counters/gauges/fixed-bucket histograms with labeled series; flat
    ``scalars()`` for the TensorBoard writer, structured ``snapshot()``
    (+ ``snapshot_delta``) for jsonl export. ``get_registry()`` is the
    default instance the built-in layers report to.
  * ``span`` (`spans.py`) — context-manager/decorator timing regions
    into ``span/<name>`` histograms and, when a profiler trace window is
    open (``set_trace_active``), into ``jax.profiler.TraceAnnotation``
    rows that line up with `utils/xplane.py` captures.
  * ``GoodputTracker`` (`goodput.py`) — every trainer-loop second
    charged to productive / data / checkpoint / retry; fractions sum to
    1.0 by construction.
  * ``TelemetryLogger`` (`telemetry_file.py`) — append-only
    ``telemetry.jsonl`` + atomically-replaced ``heartbeat.json`` under
    ``model_dir``; ``bin/t2r_telemetry`` tails and summarizes them.

Performance forensics (ISSUE 4) closes the loop from those numbers to
answers:

  * ``Watchdog`` (`watchdog.py`) — rolling-baseline anomaly detection
    (step-time regression, goodput drop, recompiles, HBM growth,
    heartbeat staleness) over the registry at the trainer's log cadence.
  * ``AutoProfiler`` (`autoprofiler.py`) — budgeted, rate-limited
    profiler capture windows triggered by the watchdog (static
    ``profile_steps`` windows stay supported); every window ends as a
    structured ``forensics/<step>.json`` report.
  * `signals.py` — ``jax.monitoring`` compile-event listeners and
    device-HBM/host-RSS watermark sampling into the registry.
  * `forensics.py` — the report builder (top-k ops via `utils/xplane`,
    collective stats via `parallel/hlo_analysis`, goodput attribution);
    degrades to warnings on torn captures, never raises in the trainer.
  * `doctor.py` — ranked offline diagnosis from telemetry.jsonl +
    forensics reports (``bin/t2r_telemetry doctor``; jax-free).

Pipeline X-ray (ISSUE 7) makes the host->device data path a measured,
per-stage quantity instead of a bench-time inference:

  * `pipeline_xray.py` — the stage model (read/decode/batch/transfer/
    device), source-side ``StageMeter`` counters every data layer
    reports into, the windowed ``PipelineXray`` bottleneck attribution
    (``t2r.pipeline.v1`` records in telemetry.jsonl), the
    ``attribute_stages`` rule bench.py shares, and the pipeline anomaly
    kinds (``pipeline_stall`` / ``worker_starvation`` /
    ``transfer_regression``) feeding the capture loop.

Fleet observatory (ISSUE 9) lifts all of it from one process to a
fleet:

  * `fleet.py` — per-host stream federation (``telemetry.<i>.jsonl``
    merged into aligned step-time/goodput series, fleet goodput as the
    min across hosts), the FleetWatchdog (``straggler`` /
    ``host_dead`` anomalies into the same capture loop), the live
    FleetObserver (``t2r.fleet.v1`` records from per-host heartbeats),
    and the preemption recovery timeline (``t2r.recovery.v1``,
    ``preemption_recovery_seconds``).
  * `fleet_sim.py` — the jax-free simulated-host writer fleet tests,
    ``bin/check_fleet_doctor``, and the MULTICHIP fleet phase share.

Roofline observatory (ISSUE 19) turns the measured-ms tables into
bound-class evidence and makes MFU a live signal:

  * `roofline.py` — the per-``device_kind`` peaks table, the
    ``t2r.roofline.v1`` record builder (measured op-family ms joined
    with the `parallel/hlo_analysis` per-op FLOPs/bytes cost model:
    arithmetic intensity, compute/memory/ragged bound class, % peak,
    fusion headroom; CPU degrades to intensity-only), and the
    ``perf/mfu`` / ``perf/hbm_bw_util`` gauges the trainer publishes
    every log window from the SAME shared cost helper bench.py uses.
    The watchdog's ``mfu_regression`` kind and doctor's roofline
    verdict (naming the gating memory-bound family) read them; the
    kernel microbench rig that consumes the ranking lives in
    `tuning/kernelbench.py` + ``bin/t2r_kernelbench``.

Metric name catalog, forensics report schema, and goodput definitions:
docs/observability.md.
"""

from tensor2robot_tpu.observability.autoprofiler import AutoProfiler
from tensor2robot_tpu.observability.fleet import (
    FLEET_RECORD_SCHEMA,
    FleetConfig,
    FleetObserver,
    FleetWatchdog,
    RECOVERY_SCHEMA,
    align_train_series,
    fleet_summary,
    read_fleet,
)
from tensor2robot_tpu.observability.forensics import (
    FORENSICS_DIRNAME,
    attribute_goodput,
    build_report,
    read_reports,
    split_collective_wait,
    write_report,
)
from tensor2robot_tpu.observability.goodput import (
    CATEGORIES as GOODPUT_CATEGORIES,
    GoodputTracker,
)
from tensor2robot_tpu.observability.pipeline_xray import (
    PIPELINE_RECORD_SCHEMA,
    PipelineXray,
    StageMeter,
    XrayConfig,
    attribute_stages,
)
from tensor2robot_tpu.observability.roofline import (
    HBM_BW_GAUGE,
    MFU_GAUGE,
    ROOFLINE_BENCH_KEYS,
    ROOFLINE_SCHEMA,
    build_record as build_roofline_record,
    classify_bound,
    device_peaks,
    publish_perf_gauges,
)
from tensor2robot_tpu.observability.signals import (
    host_identity,
    install_jax_listeners,
    sample_memory,
    uninstall_jax_listeners,
)
from tensor2robot_tpu.observability.watchdog import (
    Anomaly,
    Watchdog,
    WatchdogConfig,
)
from tensor2robot_tpu.observability.registry import (
    Counter,
    DEFAULT_LATENCY_BUCKETS_MS,
    DEFAULT_SECONDS_BUCKETS,
    Gauge,
    Histogram,
    SLO_LATENCY_BUCKETS_MS,
    TelemetryRegistry,
    exponential_buckets,
    get_registry,
    set_registry,
    snapshot_delta,
)
from tensor2robot_tpu.observability.spans import (
    set_trace_active,
    span,
    trace_active,
)
from tensor2robot_tpu.observability.telemetry_file import (
    HEARTBEAT_FILENAME,
    TELEMETRY_FILENAME,
    TelemetryLogger,
    discover_hosts,
    read_heartbeat,
    read_telemetry,
)

__all__ = [
    'Anomaly',
    'AutoProfiler',
    'Counter',
    'DEFAULT_LATENCY_BUCKETS_MS',
    'DEFAULT_SECONDS_BUCKETS',
    'FLEET_RECORD_SCHEMA',
    'FORENSICS_DIRNAME',
    'FleetConfig',
    'FleetObserver',
    'FleetWatchdog',
    'Gauge',
    'GOODPUT_CATEGORIES',
    'GoodputTracker',
    'HBM_BW_GAUGE',
    'HEARTBEAT_FILENAME',
    'Histogram',
    'MFU_GAUGE',
    'ROOFLINE_BENCH_KEYS',
    'ROOFLINE_SCHEMA',
    'PIPELINE_RECORD_SCHEMA',
    'PipelineXray',
    'RECOVERY_SCHEMA',
    'SLO_LATENCY_BUCKETS_MS',
    'StageMeter',
    'TELEMETRY_FILENAME',
    'TelemetryLogger',
    'TelemetryRegistry',
    'Watchdog',
    'WatchdogConfig',
    'XrayConfig',
    'align_train_series',
    'attribute_goodput',
    'attribute_stages',
    'build_report',
    'build_roofline_record',
    'classify_bound',
    'device_peaks',
    'discover_hosts',
    'exponential_buckets',
    'fleet_summary',
    'get_registry',
    'host_identity',
    'install_jax_listeners',
    'publish_perf_gauges',
    'read_fleet',
    'read_heartbeat',
    'read_reports',
    'read_telemetry',
    'sample_memory',
    'set_registry',
    'set_trace_active',
    'snapshot_delta',
    'span',
    'split_collective_wait',
    'trace_active',
    'uninstall_jax_listeners',
    'write_report',
]
