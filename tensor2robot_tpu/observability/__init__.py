"""Unified telemetry layer: metrics registry, spans, goodput, run files.

The measurement substrate every perf/reliability PR builds on (ISSUE 3):

  * ``TelemetryRegistry`` (`registry.py`) — process-wide, thread-safe
    counters/gauges/fixed-bucket histograms with labeled series; flat
    ``scalars()`` for the TensorBoard writer, structured ``snapshot()``
    (+ ``snapshot_delta``) for jsonl export. ``get_registry()`` is the
    default instance the built-in layers report to.
  * ``span`` (`spans.py`) — context-manager/decorator timing regions
    into ``span/<name>`` histograms and, when a profiler trace window is
    open (``set_trace_active``), into ``jax.profiler.TraceAnnotation``
    rows that line up with `utils/xplane.py` captures.
  * ``GoodputTracker`` (`goodput.py`) — every trainer-loop second
    charged to productive / data / checkpoint / retry; fractions sum to
    1.0 by construction.
  * ``TelemetryLogger`` (`telemetry_file.py`) — append-only
    ``telemetry.jsonl`` + atomically-replaced ``heartbeat.json`` under
    ``model_dir``; ``bin/t2r_telemetry`` tails and summarizes them.

Metric name catalog and goodput definitions: docs/observability.md.
"""

from tensor2robot_tpu.observability.goodput import (
    CATEGORIES as GOODPUT_CATEGORIES,
    GoodputTracker,
)
from tensor2robot_tpu.observability.registry import (
    Counter,
    DEFAULT_LATENCY_BUCKETS_MS,
    DEFAULT_SECONDS_BUCKETS,
    Gauge,
    Histogram,
    TelemetryRegistry,
    exponential_buckets,
    get_registry,
    set_registry,
    snapshot_delta,
)
from tensor2robot_tpu.observability.spans import (
    set_trace_active,
    span,
    trace_active,
)
from tensor2robot_tpu.observability.telemetry_file import (
    HEARTBEAT_FILENAME,
    TELEMETRY_FILENAME,
    TelemetryLogger,
    read_heartbeat,
    read_telemetry,
)

__all__ = [
    'Counter',
    'DEFAULT_LATENCY_BUCKETS_MS',
    'DEFAULT_SECONDS_BUCKETS',
    'Gauge',
    'GOODPUT_CATEGORIES',
    'GoodputTracker',
    'HEARTBEAT_FILENAME',
    'Histogram',
    'TELEMETRY_FILENAME',
    'TelemetryLogger',
    'TelemetryRegistry',
    'exponential_buckets',
    'get_registry',
    'read_heartbeat',
    'read_telemetry',
    'set_registry',
    'set_trace_active',
    'snapshot_delta',
    'span',
    'trace_active',
]
