"""Budgeted, watchdog-triggered profiler capture windows.

The trainer used to bracket ONE static ``profile_steps`` window chosen
before the run — useless for the regression that shows up at step 40k of
a job someone launched Friday night. The ``AutoProfiler`` closes the
loop: the watchdog names a symptom, this class decides whether a capture
is allowed (budget + rate limit, so a flapping anomaly cannot turn the
profiler into the slowdown it was meant to explain), brackets a
``window_steps``-long ``jax.profiler`` trace, and on stop feeds the raw
xplane through `observability/forensics.py` into ``forensics/<step>.json``
— symptom -> capture -> attribution with no human in the loop.

Static windows stay supported (the ``profile_steps`` trainer arg maps to
``static_window``) and do not consume the triggered-capture budget: a
deliberate pre-planned capture and an incident response are different
budgets.

All timing here is ``time.perf_counter`` (rate limiting is a duration,
and tests/test_no_wallclock.py enforces the monotonic discipline). All
jax imports are deferred and failures disable the profiler for the rest
of the run (``broken``) instead of raising into the train loop —
profiling is evidence collection, never a liveness risk.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence, Tuple

from tensor2robot_tpu.observability import forensics
from tensor2robot_tpu.observability import registry as registry_lib
from tensor2robot_tpu.observability.spans import set_trace_active, span

__all__ = ['AutoProfiler', 'CAPTURE_COUNTER']

CAPTURE_COUNTER = 'profiler/captures'

_logv = None


def _log(msg: str, *args) -> None:
  global _logv
  if _logv is None:
    from absl import logging as _absl_logging  # deferred: absl optional
    _logv = _absl_logging.info
  _logv(msg, *args)


class AutoProfiler:
  """Owns profiler trace windows for one model_dir: static + triggered."""

  def __init__(self,
               model_dir: str,
               static_window: Optional[Sequence[int]] = None,
               window_steps: int = 5,
               max_captures: int = 2,
               min_interval_secs: float = 600.0,
               emit_reports: bool = True,
               registry: Optional[registry_lib.TelemetryRegistry] = None):
    """max_captures / min_interval_secs bound TRIGGERED captures only:
    the budget caps a run's total profiling overhead, the rate limit
    keeps a flapping watchdog from capturing back-to-back windows of the
    same incident. ``emit_reports=False`` leaves raw protos (the
    pre-forensics behavior) for callers that post-process elsewhere."""
    self.model_dir = model_dir
    self._static = tuple(static_window) if static_window else None
    self._window_steps = max(1, int(window_steps))
    self._max_captures = int(max_captures)
    self._min_interval_secs = float(min_interval_secs)
    self._emit_reports = emit_reports
    self._registry = registry
    # Callbacks the trainer wires after compile / at train() start.
    self.hlo_text_fn: Optional[Callable[[], Optional[str]]] = None
    self.context_fn: Optional[Callable[[], Dict[str, object]]] = None

    self._active = False
    self._broken = False
    self._pending: Optional[Tuple[str, Dict[str, object], int]] = None
    self._reason: Optional[str] = None
    self._trigger: Dict[str, object] = {}
    self._start_step = 0
    self._stop_step = 0
    self._start_walltime: Optional[float] = None
    self._start_snapshot: Optional[Dict[str, Dict[str, object]]] = None
    self._start_pipeline: Optional[Dict[str, object]] = None
    self._captures_taken = 0
    self._last_capture_end: Optional[float] = None
    self.last_report_path: Optional[str] = None

  @property
  def registry(self) -> registry_lib.TelemetryRegistry:
    return self._registry or registry_lib.get_registry()

  @property
  def active(self) -> bool:
    return self._active

  @property
  def broken(self) -> bool:
    return self._broken

  @property
  def captures_taken(self) -> int:
    """Triggered captures completed (static windows not counted)."""
    return self._captures_taken

  # -- trigger side ----------------------------------------------------------

  def request_capture(self, reason: str, step: int,
                      detail: Optional[Dict[str, object]] = None) -> bool:
    """Asks for a window at the next loop iteration. Returns whether the
    request was accepted (budget, rate limit, and no window already
    open/pending — rejections are silent-by-design: the anomaly itself
    is already counted and logged by the watchdog path)."""
    if self._broken or self._active or self._pending is not None:
      return False
    if self._captures_taken >= self._max_captures:
      return False
    if self._last_capture_end is not None and \
        time.perf_counter() - self._last_capture_end \
        < self._min_interval_secs:
      return False
    self._pending = (reason, dict(detail or {}), int(step))
    return True

  # -- loop side -------------------------------------------------------------

  def maybe_profile(self, step: int) -> Optional[str]:
    """Trainer calls this once per iteration, BEFORE dispatching the
    step. Starts pending/static windows, stops finished ones; returns
    the forensics report path when a window just closed (else None)."""
    if self._broken:
      return None
    if self._active:
      if step >= self._stop_step:
        return self._stop(step)
      return None
    if self._static is not None:
      start, stop = self._static
      if step >= stop:
        self._static = None  # window already behind us (restored run)
      elif step >= start:
        self._static = None
        self._start(step, 'static', {}, stop_step=stop)
        return None
    if self._pending is not None:
      reason, detail, requested_step = self._pending
      self._pending = None
      detail.setdefault('requested_step', requested_step)
      self._start(step, reason, detail,
                  stop_step=step + self._window_steps)
    return None

  def finish(self, step: int) -> Optional[str]:
    """Run ended while a window was open: close it WITH a report."""
    if self._active:
      return self._stop(step)
    return None

  def abort(self) -> None:
    """Failure-path cleanup: stop any open trace, no report. A dangling
    trace breaks the next start_trace, so this must run on every unwind
    (the trainer's finally block)."""
    self._pending = None
    if not self._active:
      return
    self._active = False
    try:
      import jax

      jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001 — already unwinding
      _log('Profiler stop on failure path failed: %s', e)
    set_trace_active(False)

  # -- internals -------------------------------------------------------------

  def _start(self, step: int, reason: str, trigger: Dict[str, object],
             stop_step: int) -> None:
    try:
      import jax

      # start_trace appends plugins/profile/<run> itself — pass the
      # logdir root so TensorBoard's profile plugin finds the trace.
      jax.profiler.start_trace(self.model_dir)
    except Exception as e:  # noqa: BLE001 — profiling is best-effort
      _log('Profiler unavailable (%s); disabling capture for this run.', e)
      self._broken = True
      return
    self._active = True
    self._reason = reason
    self._trigger = trigger
    self._start_step = step
    self._stop_step = max(stop_step, step + 1)
    # wall-clock on purpose: compared against xplane file st_mtime, which
    # is wall time too — never used as a duration or deadline.
    self._start_walltime = time.time()  # wall-clock: mtime filter
    try:
      self._start_snapshot = self.registry.snapshot()
    except Exception:  # noqa: BLE001
      self._start_snapshot = None
    # The pipeline X-ray record is INCIDENT evidence: snapshot it as the
    # window opens (one iteration after the anomaly fired, before the
    # next log-cadence observe). By window close the newest record
    # describes the capture's own overhead window — profiler start/stop
    # is seconds on some backends — not the stall it answers.
    self._start_pipeline = None
    if self.context_fn is not None:
      try:
        self._start_pipeline = (self.context_fn() or {}).get('pipeline')
      except Exception as e:  # noqa: BLE001
        _log('Forensics context callback at window open failed: %s', e)
    self.registry.counter_family(CAPTURE_COUNTER, ('trigger',)) \
        .series(reason).inc()
    # Spans now also emit TraceAnnotations, so the host-side seams
    # (data.next, ckpt.save) show up as rows in this capture.
    set_trace_active(True)
    _log('Profiler window [%d, %d) opened (%s).', step, self._stop_step,
         reason)

  def _stop(self, step: int) -> Optional[str]:
    self._active = False
    set_trace_active(False)
    try:
      import jax

      jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001
      _log('Profiler stop failed: %s', e)
      self._broken = True
      return None
    if self._reason != 'static':
      self._captures_taken += 1
      # Static windows are a separate budget AND a separate rate limit:
      # a pre-planned capture must not delay the first incident response.
      self._last_capture_end = time.perf_counter()
    _log('Profiler trace written under %s', self.model_dir)
    if not self._emit_reports:
      return None
    try:
      with span('forensics.report'):
        return self._emit_report(step)
    except Exception as e:  # noqa: BLE001 — never raise into the loop
      _log('Forensics report for step %d failed: %s', step, e)
      return None

  def _emit_report(self, step: int) -> str:
    context: Dict[str, object] = {}
    if self.context_fn is not None:
      try:
        context = dict(self.context_fn() or {})
      except Exception as e:  # noqa: BLE001
        _log('Forensics context callback failed: %s', e)
    counters_delta: Dict[str, float] = {}
    if self._start_snapshot is not None:
      try:
        delta = registry_lib.snapshot_delta(self._start_snapshot,
                                            self.registry.snapshot())
        counters_delta = {name: value
                          for name, value in delta['counters'].items()
                          if value}
      except Exception:  # noqa: BLE001
        counters_delta = {}
    xplane_path = forensics.find_latest_xplane(
        self.model_dir, newer_than=self._start_walltime)
    report = forensics.build_report(
        step=step,
        reason=self._reason or 'static',
        trigger=self._trigger,
        window={'start_step': self._start_step, 'stop_step': step,
                'n_steps': max(step - self._start_step, 1)},
        xplane_path=xplane_path,
        n_steps=max(step - self._start_step, 1),
        hlo_text_fn=self.hlo_text_fn,
        goodput_fractions=context.get('goodput'),
        counters_delta=counters_delta,
        registry=self.registry,
        tuned_config=context.get('tuned_config'),
        pipeline=self._start_pipeline,
        host=context.get('host'))
    path = forensics.write_report(self.model_dir, step, report)
    self.last_report_path = path
    _log('Forensics report: %s (top op: %s)', path,
         report['top_ops'][0]['name'] if report['top_ops'] else 'n/a')
    return path
