"""Simulated-host telemetry writer: the fleet test/fixture harness.

The real two-process proof (``parallel/multihost.py`` under
``jax.distributed``) cannot run on this container — the CPU backend
does not implement multi-process computations — and the federation
layer must be testable without it anyway (its contract is files, not
collectives). This module is the harness that replaces it for fleet
tests: a **simulated host** is a plain process (or in-process call)
that emits exactly what a real trainer process emits — ``run_start``,
per-window ``train`` records with goodput + ``step_time_s``, heartbeats
carrying the window stats, ``run_end`` — through the SAME
``TelemetryLogger`` + ``host_meta`` path, under the same shared
model_dir. Two of these spawned as real subprocesses give the
federation round-trip (concurrent writers, separate per-host files,
merged fleet view) with none of jax.distributed's failure modes.

Used by ``tests/test_fleet.py`` (subprocess federation round-trip),
``bin/check_fleet_doctor`` (jax-free doctor fixtures), and the
MULTICHIP dryrun's fleet phase (the simulated peer host). Jax-free by
construction.
"""

from __future__ import annotations

import argparse
import time
from typing import Dict, Optional, Sequence

from tensor2robot_tpu.observability.telemetry_file import TelemetryLogger

__all__ = ['host_meta', 'write_host_run', 'main']


def host_meta(process_index: int, process_count: int,
              device_kind: str = 'sim-cpu',
              hostname: Optional[str] = None) -> Dict[str, object]:
  return {
      'process_index': int(process_index),
      'process_count': int(process_count),
      'device_kind': device_kind,
      'hostname': hostname or 'simhost{}'.format(int(process_index)),
  }


def write_host_run(model_dir: str,
                   process_index: int,
                   process_count: int,
                   step_times_s: Sequence[float],
                   steps_per_window: int = 100,
                   batch_size: int = 32,
                   productive: float = 0.9,
                   end: str = 'run_end',
                   heartbeat_time: Optional[float] = None,
                   sleep_per_window_s: float = 0.0,
                   device_kind: str = 'sim-cpu') -> TelemetryLogger:
  """Emits one simulated host's full stream under ``model_dir``.

  One ``train`` record + heartbeat per entry of ``step_times_s`` (the
  window's mean step time), at steps ``steps_per_window, 2x, ...`` —
  the same cadence/step alignment a real fleet shares, so two simulated
  hosts federate on identical steps. ``end`` is ``'run_end'``,
  ``'preempted'``, or ``'live'`` (no terminal record: the run looks
  in-flight, which is what dead-host/straggler CRITICAL gating needs).
  ``heartbeat_time`` overrides the final heartbeat's wall-clock stamp
  (a frozen/stale heartbeat is how a dead host looks from outside).
  ``sleep_per_window_s`` spaces the records in real time so concurrent
  writers interleave by timestamp.
  """
  meta = host_meta(process_index, process_count, device_kind=device_kind)
  logger = TelemetryLogger(model_dir, host_meta=meta)
  logger.log('run_start', step=0, batch_size=batch_size,
             max_train_steps=steps_per_window * len(step_times_s))
  step = 0
  for window, step_time_s in enumerate(step_times_s):
    step = steps_per_window * (window + 1)
    examples_per_sec = batch_size / max(step_time_s, 1e-9)
    goodput = {'productive': productive, 'data': 1.0 - productive,
               'checkpoint': 0.0, 'retry': 0.0}
    logger.log('train', step=step, loss=0.5, step_time_s=step_time_s,
               examples_per_sec=examples_per_sec, goodput=goodput,
               gauges={}, counters={})
    extra = {'step_time_s': step_time_s,
             'examples_per_sec': examples_per_sec,
             'productive_fraction': productive}
    if heartbeat_time is not None and window == len(step_times_s) - 1:
      extra['time'] = heartbeat_time
    logger.heartbeat(step, **extra)
    logger.flush()
    if sleep_per_window_s > 0.0:
      time.sleep(sleep_per_window_s)
  if end != 'live':
    logger.log(end, step=step, goodput={
        'productive': productive, 'data': 1.0 - productive,
        'checkpoint': 0.0, 'retry': 0.0})
  logger.close()
  return logger


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--model_dir', required=True)
  parser.add_argument('--process_index', type=int, required=True)
  parser.add_argument('--process_count', type=int, default=2)
  parser.add_argument('--step_times', default='0.01,0.01,0.01,0.01',
                      help='comma-separated window mean step times (s)')
  parser.add_argument('--steps_per_window', type=int, default=100)
  parser.add_argument('--end', default='run_end',
                      choices=('run_end', 'preempted', 'live'))
  parser.add_argument('--sleep_per_window_secs', type=float, default=0.0)
  args = parser.parse_args(argv)
  write_host_run(
      args.model_dir, args.process_index, args.process_count,
      [float(t) for t in args.step_times.split(',') if t],
      steps_per_window=args.steps_per_window, end=args.end,
      sleep_per_window_s=args.sleep_per_window_secs)


if __name__ == '__main__':
  main()
