"""Simulated-host telemetry writer: the fleet test/fixture harness.

The real two-process proof (``parallel/multihost.py`` under
``jax.distributed``) cannot run on this container — the CPU backend
does not implement multi-process computations — and the federation
layer must be testable without it anyway (its contract is files, not
collectives). This module is the harness that replaces it for fleet
tests: a **simulated host** is a plain process (or in-process call)
that emits exactly what a real trainer process emits — ``run_start``,
per-window ``train`` records with goodput + ``step_time_s``, heartbeats
carrying the window stats, ``run_end`` — through the SAME
``TelemetryLogger`` + ``host_meta`` path, under the same shared
model_dir. Two of these spawned as real subprocesses give the
federation round-trip (concurrent writers, separate per-host files,
merged fleet view) with none of jax.distributed's failure modes.

Used by ``tests/test_fleet.py`` (subprocess federation round-trip),
``bin/check_fleet_doctor`` (jax-free doctor fixtures), and the
MULTICHIP dryrun's fleet phase (the simulated peer host). Jax-free by
construction.

**Membership churn (ISSUE 15)**: ``write_member_run`` is the elastic
variant — the same telemetry windows plus a LEASE renewed per window
and ``t2r.elastic.v1`` join/leave events, ending in an orderly leave,
a lease LAPSE (the writer just stops renewing — the preemption
signature), or live. ``write_shrink_events`` writes a coordinator's
shrink ladder (``shrink_begin -> shrink_phase* -> shrink`` + optional
recovery record). Together they let the elastic federation + doctor
logic (orderly-departure downgrade, stuck-rebuild paging) test with
real processes and zero jax — ``bin/check_elastic_doctor`` and
tests/test_elastic.py both build their fixtures from these writers.
"""

from __future__ import annotations

import argparse
import time
from typing import Dict, Optional, Sequence

from tensor2robot_tpu.observability.telemetry_file import TelemetryLogger

__all__ = ['host_meta', 'write_host_run', 'write_member_run',
           'write_shrink_events', 'main']


def host_meta(process_index: int, process_count: int,
              device_kind: str = 'sim-cpu',
              hostname: Optional[str] = None) -> Dict[str, object]:
  return {
      'process_index': int(process_index),
      'process_count': int(process_count),
      'device_kind': device_kind,
      'hostname': hostname or 'simhost{}'.format(int(process_index)),
  }


def write_host_run(model_dir: str,
                   process_index: int,
                   process_count: int,
                   step_times_s: Sequence[float],
                   steps_per_window: int = 100,
                   batch_size: int = 32,
                   productive: float = 0.9,
                   end: str = 'run_end',
                   heartbeat_time: Optional[float] = None,
                   sleep_per_window_s: float = 0.0,
                   device_kind: str = 'sim-cpu') -> TelemetryLogger:
  """Emits one simulated host's full stream under ``model_dir``.

  One ``train`` record + heartbeat per entry of ``step_times_s`` (the
  window's mean step time), at steps ``steps_per_window, 2x, ...`` —
  the same cadence/step alignment a real fleet shares, so two simulated
  hosts federate on identical steps. ``end`` is ``'run_end'``,
  ``'preempted'``, or ``'live'`` (no terminal record: the run looks
  in-flight, which is what dead-host/straggler CRITICAL gating needs).
  ``heartbeat_time`` overrides the final heartbeat's wall-clock stamp
  (a frozen/stale heartbeat is how a dead host looks from outside).
  ``sleep_per_window_s`` spaces the records in real time so concurrent
  writers interleave by timestamp.
  """
  meta = host_meta(process_index, process_count, device_kind=device_kind)
  logger = TelemetryLogger(model_dir, host_meta=meta)
  logger.log('run_start', step=0, batch_size=batch_size,
             max_train_steps=steps_per_window * len(step_times_s))
  step = _write_windows(logger, step_times_s, steps_per_window,
                        batch_size, productive, heartbeat_time,
                        sleep_per_window_s)
  if end != 'live':
    logger.log(end, step=step, goodput={
        'productive': productive, 'data': 1.0 - productive,
        'checkpoint': 0.0, 'retry': 0.0})
  logger.close()
  return logger


def _write_windows(logger: TelemetryLogger,
                   step_times_s: Sequence[float],
                   steps_per_window: int,
                   batch_size: int,
                   productive: float,
                   heartbeat_time: Optional[float],
                   sleep_per_window_s: float,
                   per_window=None) -> int:
  """The per-window emission both simulated writers share.

  One ``train`` record + heartbeat per entry of ``step_times_s``, at
  steps ``steps_per_window, 2x, ...``; ``per_window(window, last)``
  runs between the heartbeat and the flush (the elastic member renews
  its lease there). Returns the final step.
  """
  step = 0
  for window, step_time_s in enumerate(step_times_s):
    step = steps_per_window * (window + 1)
    examples_per_sec = batch_size / max(step_time_s, 1e-9)
    logger.log('train', step=step, loss=0.5, step_time_s=step_time_s,
               examples_per_sec=examples_per_sec,
               goodput={'productive': productive,
                        'data': 1.0 - productive,
                        'checkpoint': 0.0, 'retry': 0.0},
               gauges={}, counters={})
    extra = {'step_time_s': step_time_s,
             'examples_per_sec': examples_per_sec,
             'productive_fraction': productive}
    last = window == len(step_times_s) - 1
    if heartbeat_time is not None and last:
      extra['time'] = heartbeat_time
    logger.heartbeat(step, **extra)
    if per_window is not None:
      per_window(window, last)
    logger.flush()
    if sleep_per_window_s > 0.0:
      time.sleep(sleep_per_window_s)
  return step


def write_member_run(model_dir: str,
                     process_index: int,
                     process_count: int,
                     step_times_s: Sequence[float],
                     steps_per_window: int = 100,
                     batch_size: int = 32,
                     productive: float = 0.9,
                     membership_end: str = 'leave',
                     sleep_per_window_s: float = 0.0,
                     heartbeat_time: Optional[float] = None,
                     lease_backdate_s: float = 3600.0,
                     device_kind: str = 'sim-cpu') -> TelemetryLogger:
  """One simulated ELASTIC member: telemetry windows + lease churn.

  Emits what an elastic host emits: a ``t2r.elastic.v1`` join event, a
  lease renewed once per window, the usual per-window ``train`` records
  + heartbeats, and one of three endings —

    * ``'leave'``  — orderly: ``run_end``, the lease flips to
      ``status='leaving'``, and a ``leave`` event lands (the departure
      the doctor must NOT page for once a shrink event names it);
    * ``'lapse'``  — preemption signature: NO terminal record, and the
      final lease stamp is BACKDATED ``lease_backdate_s`` so observers
      see it already lapsed (a subprocess writer need not outwait a
      TTL);
    * ``'live'``   — fresh lease, no terminal record: mid-run.
  """
  from tensor2robot_tpu.elastic import membership as membership_lib

  if membership_end not in ('leave', 'lapse', 'live'):
    raise ValueError('unknown membership_end {!r}'.format(membership_end))
  meta = host_meta(process_index, process_count, device_kind=device_kind)
  logger = TelemetryLogger(model_dir, host_meta=meta)
  previous = membership_lib.read_leases(model_dir).get(int(process_index))
  incarnation = int((previous or {}).get('incarnation', 0)) + 1
  membership_lib.write_lease(model_dir, process_index,
                             incarnation=incarnation)
  logger.log('elastic', step=0, **membership_lib.elastic_record(
      membership_lib.EVENT_JOIN, host=int(process_index),
      incarnation=incarnation, target_world=int(process_count)))
  def renew_lease(window, last):
    if last and membership_end == 'lapse':
      # The preemption signature: an ACTIVE lease that is already
      # stale — the writer died without saying anything.
      membership_lib.write_lease(
          model_dir, process_index, incarnation=incarnation,
          now=time.time() - lease_backdate_s)  # wall-clock: backdated stamp
    else:
      membership_lib.write_lease(model_dir, process_index,
                                 incarnation=incarnation)

  step = _write_windows(logger, step_times_s, steps_per_window,
                        batch_size, productive, heartbeat_time,
                        sleep_per_window_s, per_window=renew_lease)
  if membership_end == 'leave':
    logger.log('run_end', step=step, goodput={
        'productive': productive, 'data': 1.0 - productive,
        'checkpoint': 0.0, 'retry': 0.0})
    membership_lib.release_lease(model_dir, process_index,
                                 incarnation=incarnation)
    logger.log('elastic', step=step, **membership_lib.elastic_record(
        membership_lib.EVENT_LEAVE, host=int(process_index),
        incarnation=incarnation))
  logger.close()
  return logger


def write_shrink_events(model_dir: str,
                        coordinator: int,
                        epoch: int,
                        world_before: int,
                        world_after: int,
                        departed: Sequence[int],
                        orderly: bool = True,
                        phases: Optional[Sequence[str]] = None,
                        complete: bool = True,
                        recovery: bool = False,
                        step: int = 0,
                        process_count: Optional[int] = None
                        ) -> None:
  """One coordinator's shrink ladder, as fixture telemetry.

  ``phases`` truncates the ladder (``None`` = all of SHRINK_PHASES):
  a fixture with only ``('emergency_save',)`` and ``complete=False`` is
  the STUCK rebuild doctor pages on, naming ``mesh_rebuild`` as the
  stalled phase. ``recovery=True`` appends the ``t2r.recovery.v1``
  record a real (non-orderly) shrink closes with, phases summing to the
  total and carrying the world change.
  """
  from tensor2robot_tpu.elastic import membership as membership_lib

  if phases is None:
    phases = membership_lib.SHRINK_PHASES
  meta = host_meta(coordinator, process_count or world_before)
  logger = TelemetryLogger(model_dir, host_meta=meta)
  base = dict(epoch=int(epoch), world_before=int(world_before),
              world_after=int(world_after),
              departed=[int(h) for h in departed], orderly=bool(orderly))
  logger.log('elastic', step=step, **membership_lib.elastic_record(
      membership_lib.EVENT_SHRINK_BEGIN, host=int(coordinator), **base))
  for phase in phases:
    payload = {'phase': phase, 'seconds': 0.1}
    if phase == 'artifact_rebind':
      payload.update(artifact_outcome='hit', compiles_delta=0.0)
    logger.log('elastic', step=step, **membership_lib.elastic_record(
        membership_lib.EVENT_SHRINK_PHASE, host=int(coordinator),
        epoch=int(epoch), **payload))
  if complete:
    logger.log('elastic', step=step + 1, **membership_lib.elastic_record(
        membership_lib.EVENT_REBUILD, host=int(coordinator),
        epoch=int(epoch), world_size=int(world_after),
        artifact_outcome='hit', compiles_delta=0.0))
    logger.log('elastic', step=step + 1, **membership_lib.elastic_record(
        membership_lib.EVENT_SHRINK, host=int(coordinator), **base))
  if recovery:
    logger.log('recovery', step=step + 1,
               schema='t2r.recovery.v1', preempted_step=step,
               resume_step=step + 1,
               signum=membership_lib.ELASTIC_LAPSE_SIGNUM,
               phases={'emergency_save_s': 0.2, 'downtime_s': 1.0,
                       'restore_s': 0.5, 'first_step_s': 0.3},
               preemption_recovery_seconds=2.0,
               world_before=int(world_before),
               world_after=int(world_after),
               departed=[int(h) for h in departed], elastic=True)
  logger.close()


def main(argv=None):
  parser = argparse.ArgumentParser(description=__doc__)
  parser.add_argument('--model_dir', required=True)
  parser.add_argument('--process_index', type=int, required=True)
  parser.add_argument('--process_count', type=int, default=2)
  parser.add_argument('--step_times', default='0.01,0.01,0.01,0.01',
                      help='comma-separated window mean step times (s)')
  parser.add_argument('--steps_per_window', type=int, default=100)
  parser.add_argument('--end', default='run_end',
                      choices=('run_end', 'preempted', 'live'))
  parser.add_argument('--sleep_per_window_secs', type=float, default=0.0)
  parser.add_argument('--member', action='store_true',
                      help='elastic-member mode: renew a lease per '
                      'window and emit t2r.elastic.v1 join/leave events')
  parser.add_argument('--membership_end', default='leave',
                      choices=('leave', 'lapse', 'live'),
                      help='--member ending: orderly leave, lease '
                      'lapse (preemption signature), or live')
  args = parser.parse_args(argv)
  step_times = [float(t) for t in args.step_times.split(',') if t]
  if args.member:
    write_member_run(
        args.model_dir, args.process_index, args.process_count,
        step_times, steps_per_window=args.steps_per_window,
        membership_end=args.membership_end,
        sleep_per_window_s=args.sleep_per_window_secs)
    return
  write_host_run(
      args.model_dir, args.process_index, args.process_count,
      step_times, steps_per_window=args.steps_per_window, end=args.end,
      sleep_per_window_s=args.sleep_per_window_secs)


if __name__ == '__main__':
  main()
