"""New registry signal sources: XLA compile events + memory watermarks.

Two classes of signals the PR 3 registry could not see:

  * **Compilations.** ``jax.monitoring`` fires named events around every
    jaxpr trace and backend (XLA) compile. One module-level dispatcher is
    registered ONCE per process (jax's listener list has no unregister in
    its public API) and routes into whatever ``get_registry()`` currently
    is, gated by an enabled flag — so tests that swap registries or call
    ``uninstall_jax_listeners`` need no private-API surgery. A silent
    recompile mid-run (a shape-unstable batch reaching a jitted step) was
    previously invisible until someone noticed the step-time graph; now
    it is ``jax/compiles`` + ``jax/compile_ms`` landing in TensorBoard
    and telemetry.jsonl, and the watchdog's ``recompile`` trigger.
  * **Memory watermarks.** ``device.memory_stats()`` per accelerator
    (None on CPU — skipped, not faked) and host RSS from /proc (fallback
    ``resource.getrusage``), sampled by the trainer at its log cadence.
    A monotonically climbing ``memory/device_bytes_in_use`` is the leak
    signature the watchdog's ``hbm_growth`` detection consumes.

Everything here degrades to a no-op on hosts without jax (the doctor CLI
imports the observability package; it must stay jax-free), so jax is
imported lazily and failures are swallowed where noted.
"""

from __future__ import annotations

import os
import resource
import socket
from typing import Dict, Optional

from tensor2robot_tpu.observability import registry as registry_lib

__all__ = [
    'COMPILE_COUNTER', 'COMPILE_MS_HISTOGRAM', 'TRACE_MS_HISTOGRAM',
    'CACHE_MISS_COUNTER', 'HOST_RSS_GAUGE', 'HOST_PEAK_RSS_GAUGE',
    'DEVICE_BYTES_GAUGE', 'DEVICE_PEAK_BYTES_GAUGE',
    'install_jax_listeners', 'uninstall_jax_listeners', 'sample_memory',
    'host_identity',
]

COMPILE_COUNTER = 'jax/compiles'
COMPILE_MS_HISTOGRAM = 'jax/compile_ms'
TRACE_MS_HISTOGRAM = 'jax/trace_ms'
CACHE_MISS_COUNTER = 'jax/compilation_cache_misses'

HOST_RSS_GAUGE = 'memory/host_rss_bytes'
HOST_PEAK_RSS_GAUGE = 'memory/host_peak_rss_bytes'
DEVICE_BYTES_GAUGE = 'memory/device_bytes_in_use'
DEVICE_PEAK_BYTES_GAUGE = 'memory/device_peak_bytes'

# jax._src.dispatch event names (stable across 0.4.x; unknown events are
# simply never matched, so a rename degrades to "no signal", not a crash).
_BACKEND_COMPILE_EVENT = '/jax/core/compile/backend_compile_duration'
_JAXPR_TRACE_EVENT = '/jax/core/compile/jaxpr_trace_duration'
_CACHE_MISS_EVENT = '/jax/compilation_cache/cache_misses'

_installed = False
_enabled = False


def _on_duration(event: str, duration_secs: float, **kwargs) -> None:
  if not _enabled:
    return
  registry = registry_lib.get_registry()
  if event == _BACKEND_COMPILE_EVENT:
    registry.counter(COMPILE_COUNTER).inc()
    registry.histogram(
        COMPILE_MS_HISTOGRAM,
        bounds=registry_lib.DEFAULT_LATENCY_BUCKETS_MS).record(
            duration_secs * 1e3)
  elif event == _JAXPR_TRACE_EVENT:
    registry.histogram(
        TRACE_MS_HISTOGRAM,
        bounds=registry_lib.DEFAULT_LATENCY_BUCKETS_MS).record(
            duration_secs * 1e3)


def _on_event(event: str, **kwargs) -> None:
  if not _enabled:
    return
  if event == _CACHE_MISS_EVENT:
    registry_lib.get_registry().counter(CACHE_MISS_COUNTER).inc()


def install_jax_listeners() -> bool:
  """Enables compile-event accounting; returns False on jax-free hosts.

  Idempotent: the dispatcher is registered with jax.monitoring exactly
  once per process; repeat calls only flip the enabled flag back on.
  """
  global _installed, _enabled
  try:
    from jax import monitoring
  except Exception:  # noqa: BLE001 — jax-free host (doctor CLI)
    return False
  if not _installed:
    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)
    _installed = True
  _enabled = True
  return True


def uninstall_jax_listeners() -> None:
  """Disables the dispatcher (registration with jax remains; it is a
  no-op while disabled). Test hook."""
  global _enabled
  _enabled = False


def host_identity() -> Dict[str, object]:
  """This process's fleet identity: the ``host_meta`` dict every
  per-host telemetry record is stamped with (ISSUE 9).

  ``{'process_index', 'process_count', 'device_kind', 'device_count',
  'hostname'}`` — process coordinates from ``jax.distributed``'s view
  of the world, device kind + local chip count from the local device
  list (the roofline/MFU consumers need BOTH: per-device program flops
  are per-chip, the peaks table is per-``device_kind``). Degrades to
  the single-process identity (``0 of 1``, ``device_kind='unknown'``,
  ``device_count=0``) on jax-free hosts so the doctor/fleet tooling can
  call it too.
  """
  identity: Dict[str, object] = {
      'process_index': 0,
      'process_count': 1,
      'device_kind': 'unknown',
      'device_count': 0,
      'hostname': socket.gethostname(),
  }
  try:
    import jax

    identity['process_index'] = int(jax.process_index())
    identity['process_count'] = int(jax.process_count())
    local = jax.local_devices()
    identity['device_count'] = len(local)
    if local:
      identity['device_kind'] = str(
          getattr(local[0], 'device_kind', 'unknown'))
  except Exception:  # noqa: BLE001 — jax-free or uninitialized backend
    pass
  return identity


def _host_rss_bytes() -> Optional[float]:
  """Current resident set size; /proc first, portable-ish fallback."""
  try:
    with open('/proc/self/statm') as f:
      pages = int(f.read().split()[1])
    return float(pages * os.sysconf('SC_PAGE_SIZE'))
  except (OSError, ValueError, IndexError):
    pass
  try:
    # ru_maxrss is the PEAK (kilobytes on linux), not current — better
    # than nothing on /proc-less hosts; the peak gauge below is exact.
    return float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
  except Exception:  # noqa: BLE001
    return None


def sample_memory(
    registry: Optional[registry_lib.TelemetryRegistry] = None
) -> Dict[str, float]:
  """Samples device + host memory watermarks into gauges; returns them.

  Device stats come from ``device.memory_stats()`` (PJRT; ``None`` on
  the CPU backend — those devices are skipped so dashboards never show a
  fake 0-byte TPU). Gauge names: ``memory/device_bytes_in_use/<device>``,
  ``memory/device_peak_bytes/<device>``, ``memory/host_rss_bytes``,
  ``memory/host_peak_rss_bytes``.
  """
  registry = registry or registry_lib.get_registry()
  out: Dict[str, float] = {}
  try:
    import jax
    devices = jax.devices()
  except Exception:  # noqa: BLE001 — jax-free or uninitialized backend
    devices = []
  in_use = registry.gauge_family(DEVICE_BYTES_GAUGE, ('device',))
  peak = registry.gauge_family(DEVICE_PEAK_BYTES_GAUGE, ('device',))
  for device in devices:
    try:
      stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — backend without the PJRT API
      stats = None
    if not stats:
      continue
    label = str(device.id)
    value = float(stats.get('bytes_in_use', 0.0))
    in_use.series(label).set(value)
    out['{}/{}'.format(DEVICE_BYTES_GAUGE, label)] = value
    peak_value = float(stats.get('peak_bytes_in_use', 0.0))
    if peak_value:
      peak.series(label).set(peak_value)
      out['{}/{}'.format(DEVICE_PEAK_BYTES_GAUGE, label)] = peak_value
  rss = _host_rss_bytes()
  if rss is not None:
    registry.gauge(HOST_RSS_GAUGE).set(rss)
    out[HOST_RSS_GAUGE] = rss
  try:
    peak_rss = float(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
    registry.gauge(HOST_PEAK_RSS_GAUGE).set(peak_rss)
    out[HOST_PEAK_RSS_GAUGE] = peak_rss
  except Exception:  # noqa: BLE001
    pass
  return out
