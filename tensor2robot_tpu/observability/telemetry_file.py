"""Machine-readable run telemetry: ``telemetry.jsonl`` + heartbeat file.

TensorBoard events are for humans with a browser; fleet tooling (and
``bin/t2r_telemetry``) wants greppable, append-only JSON lines under
``model_dir``:

  * ``telemetry.jsonl`` — one JSON object per line:
    ``{"time": <unix>, "kind": "...", "step": <int|null>, ...payload}``.
    Kinds written by the trainer: ``run_start``, ``train`` (scalars +
    goodput at the log cadence), ``pipeline`` (the X-ray's
    ``t2r.pipeline.v1`` attribution record), ``anomaly``, ``forensics``,
    ``preempted``, ``rollback``, ``run_abort`` (any other exception
    escaping the loop), ``run_end``. The file is append-only across
    restarts — a preempted run's history survives its own resumption.
  * ``heartbeat.json`` — atomically replaced (tmp + rename) at the log
    cadence: ``{"time", "step", "pid", "hostname"}``. A watchdog that
    sees a stale heartbeat knows the process is wedged even when the
    jsonl tail looks healthy; readers never observe a half-written file.

**Rotation**: the live file is capped (``max_bytes``, default 256 MiB —
weeks-long runs with per-log-cadence ``pipeline`` records would
otherwise grow it unboundedly). At the cap the writer renames the live
file to ``telemetry.jsonl.1`` (shifting ``.1`` -> ``.2`` ... up to
``max_rotated`` generations, oldest dropped) and starts a fresh live
file — always at a LINE boundary, so rotated files never hold torn
interior records. The live file keeps its name, which is what lets
``t2r_telemetry tail --follow`` ride through a rotation (it sees the
size shrink and restarts from the new top). ``read_telemetry``
stitches rotated generations back in oldest-first, so doctor/summarize
keep the full retained history.

``read_telemetry`` tolerates a torn final line (the writer may be killed
mid-append) but raises on malformed interior lines — silent corruption
of history is worse than a crash in a tool.

**Fleet emission (ISSUE 9)**: a multi-process (multi-host) run shares one
``model_dir``, and two processes appending to the same ``telemetry.jsonl``
would interleave torn lines and race the rotation rename. Each process
therefore writes its OWN stream — ``telemetry.<process_index>.jsonl`` +
``heartbeat.<process_index>.json`` — named by ``host_meta`` (the
``process_index``/``process_count``/``device_kind``/``hostname`` identity
dict ``signals.host_identity()`` builds), and every record/heartbeat is
stamped with that identity so a merged fleet view can attribute each line
to its host. Single-process runs (``process_count`` absent or 1) keep the
bare filenames, so nothing downstream of a one-host run changes.
``discover_hosts``/``read_heartbeat(..., process_index=)`` are the
jax-free reading half ``observability/fleet.py`` federates over.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from typing import Dict, List, Optional

__all__ = ['TelemetryLogger', 'read_telemetry', 'read_heartbeat',
           'rotated_paths', 'discover_hosts', 'telemetry_filename',
           'heartbeat_filename', 'TELEMETRY_FILENAME', 'HEARTBEAT_FILENAME',
           'DEFAULT_MAX_BYTES', 'DEFAULT_MAX_ROTATED', 'HOST_META_KEYS']

TELEMETRY_FILENAME = 'telemetry.jsonl'
HEARTBEAT_FILENAME = 'heartbeat.json'

# Identity fields stamped into every record/heartbeat of a host-scoped
# stream (matching signals.host_identity()).
HOST_META_KEYS = ('process_index', 'process_count', 'device_kind',
                  'hostname')

DEFAULT_MAX_BYTES = 256 * 2**20
DEFAULT_MAX_ROTATED = 2

_HOST_TELEMETRY_RE = re.compile(r'^telemetry\.(\d+)\.jsonl$')
_HOST_HEARTBEAT_RE = re.compile(r'^heartbeat\.(\d+)\.json$')


def _is_fleet_meta(host_meta: Optional[Dict[str, object]]) -> bool:
  """Whether this identity names one host OF SEVERAL (indexed filenames)."""
  if not host_meta:
    return False
  return int(host_meta.get('process_count') or 1) > 1 and \
      host_meta.get('process_index') is not None


def telemetry_filename(host_meta: Optional[Dict[str, object]] = None) -> str:
  """Live telemetry filename for one host's stream.

  ``telemetry.<process_index>.jsonl`` when the identity names one host of
  a multi-process run; the historical bare name otherwise — a
  single-process run must keep today's layout so nothing downstream
  breaks.
  """
  if _is_fleet_meta(host_meta):
    return 'telemetry.{}.jsonl'.format(int(host_meta['process_index']))
  return TELEMETRY_FILENAME


def heartbeat_filename(host_meta: Optional[Dict[str, object]] = None) -> str:
  if _is_fleet_meta(host_meta):
    return 'heartbeat.{}.json'.format(int(host_meta['process_index']))
  return HEARTBEAT_FILENAME


class TelemetryLogger:
  """Appends telemetry records and maintains the heartbeat for one run.

  ``max_bytes`` caps the LIVE file; crossing it rotates (see module
  docstring). ``max_bytes=None`` disables rotation (the pre-cap
  behavior). ``max_rotated`` bounds retained generations, so total disk
  is ~``max_bytes * (1 + max_rotated)``.

  Thread-safe within one process: ``log``/``heartbeat``/``flush`` take
  an internal lock, so a PolicyServer's serve loop and its hot-swap
  poller (ISSUE 8 — the first multi-threaded writer) cannot interleave
  a record mid-line or race the rotation's close/reopen. Cross-PROCESS
  writers each need their own files — which is exactly what ``host_meta``
  provides: a multi-process identity routes this logger to
  ``telemetry.<process_index>.jsonl`` / ``heartbeat.<process_index>.json``
  and stamps every record/heartbeat with the identity fields
  (``HOST_META_KEYS``), so N hosts sharing one model_dir never contend
  for one file and every merged line names its writer.
  """

  def __init__(self, model_dir: str,
               max_bytes: Optional[int] = DEFAULT_MAX_BYTES,
               max_rotated: int = DEFAULT_MAX_ROTATED,
               host_meta: Optional[Dict[str, object]] = None):
    os.makedirs(model_dir, exist_ok=True)
    self.model_dir = model_dir
    self.max_bytes = None if max_bytes is None else int(max_bytes)
    self.max_rotated = max(1, int(max_rotated))
    self._lock = threading.Lock()
    self.host_meta = {key: host_meta[key] for key in HOST_META_KEYS
                      if key in host_meta} if host_meta else None
    self._path = os.path.join(model_dir, telemetry_filename(host_meta))
    self._heartbeat_path = os.path.join(model_dir,
                                        heartbeat_filename(host_meta))
    self._file = open(self._path, 'a', encoding='utf-8')
    # Tracked size, NOT self._file.tell(): tell() on a text append
    # stream flushes the write buffer, which would turn every log()
    # into a disk write and quietly change the buffered-append /
    # explicit-flush() (torn-tail) semantics.
    self._size = os.path.getsize(self._path)

  @property
  def path(self) -> str:
    return self._path

  def _maybe_rotate(self, incoming_bytes: int) -> None:
    if self.max_bytes is None:
      return
    if self._size == 0 or self._size + incoming_bytes <= self.max_bytes:
      return  # a fresh file always takes at least one record
    self._file.flush()
    self._file.close()
    # Shift .1 -> .2 -> ... (newest rotated is .1); the oldest falls off.
    for index in range(self.max_rotated, 1, -1):
      older = '{}.{}'.format(self._path, index - 1)
      if os.path.exists(older):
        os.replace(older, '{}.{}'.format(self._path, index))
    os.replace(self._path, self._path + '.1')
    self._file = open(self._path, 'a', encoding='utf-8')
    self._size = 0

  def log(self, kind: str, step: Optional[int] = None,
          **payload) -> Dict[str, object]:
    """Appends one record; returns it (tests and callers can reuse it)."""
    record: Dict[str, object] = {
        'time': time.time(),  # wall-clock timestamp (cross-process record)
        'kind': kind,
        'step': None if step is None else int(step)}
    if self.host_meta:
      record.update(self.host_meta)
    record.update(payload)
    line = json.dumps(record) + '\n'
    encoded = len(line.encode('utf-8'))
    with self._lock:
      self._maybe_rotate(encoded)
      self._file.write(line)
      self._size += encoded
    return record

  def heartbeat(self, step: Optional[int] = None, **extra) -> None:
    """Atomically replaces the heartbeat file (never half-written)."""
    beat: Dict[str, object] = {
        'time': time.time(),  # wall-clock timestamp (external readers)
        'step': None if step is None else int(step),
        'pid': os.getpid(),
        'hostname': socket.gethostname(),
    }
    if self.host_meta:
      beat.update(self.host_meta)
    beat.update(extra)
    tmp = self._heartbeat_path + '.tmp'
    with self._lock:  # two threads sharing one tmp path must serialize
      with open(tmp, 'w', encoding='utf-8') as f:
        json.dump(beat, f)
      os.replace(tmp, self._heartbeat_path)

  def flush(self) -> None:
    with self._lock:
      if not self._file.closed:
        self._file.flush()

  def close(self) -> None:
    with self._lock:
      if not self._file.closed:
        self._file.flush()
        self._file.close()


def rotated_paths(path: str) -> List[str]:
  """Existing generations of one telemetry file, OLDEST first.

  ``path`` is the live file; the result ends with it:
  ``[telemetry.jsonl.2, telemetry.jsonl.1, telemetry.jsonl]``.
  """
  out: List[str] = []
  index = 1
  while os.path.exists('{}.{}'.format(path, index)):
    out.append('{}.{}'.format(path, index))
    index += 1
  out.reverse()
  out.append(path)
  return out


def _read_one(path: str) -> List[Dict[str, object]]:
  records: List[Dict[str, object]] = []
  with open(path, encoding='utf-8') as f:
    lines = f.read().splitlines()
  for index, line in enumerate(lines):
    if not line.strip():
      continue
    try:
      records.append(json.loads(line))
    except ValueError as e:
      if index == len(lines) - 1:
        break  # torn tail from a killed writer: ignore
      raise ValueError('{}:{} holds malformed telemetry: {}'.format(
          path, index + 1, e)) from e
  return records


def read_telemetry(path: str) -> List[Dict[str, object]]:
  """Parses a telemetry.jsonl file (or the model_dir holding one),
  including any rotated generations (oldest first).

  A torn FINAL line (writer killed mid-append) is dropped silently —
  per generation, since a pre-rotation run may have died mid-append
  too; malformed interior lines raise ValueError naming the line
  number.
  """
  if os.path.isdir(path):
    path = os.path.join(path, TELEMETRY_FILENAME)
  generations = [p for p in rotated_paths(path) if os.path.exists(p)]
  if not generations:
    # Preserve the no-telemetry contract callers already handle.
    raise FileNotFoundError(path)
  records: List[Dict[str, object]] = []
  for generation in generations:
    records.extend(_read_one(generation))
  return records


def read_heartbeat(model_dir: str,
                   process_index: Optional[int] = None
                   ) -> Optional[Dict[str, object]]:
  """The last heartbeat written under ``model_dir``, or None.

  ``process_index`` selects one host's file in a fleet model_dir
  (``heartbeat.<i>.json``); the default reads the single-process
  ``heartbeat.json``, falling back to host 0's indexed file so existing
  callers (doctor, summarize) keep working on a fleet dir.
  """
  # Indexed-wins, same precedence as discover_hosts: a model_dir holding
  # BOTH names saw a single-process run before a fleet one, and the
  # fleet's (indexed) heartbeat is the live evidence — preferring the
  # bare leftover would page on a heartbeat nobody writes anymore.
  if process_index is not None:
    candidates = ['heartbeat.{}.json'.format(int(process_index))]
    if int(process_index) == 0:
      candidates.append(HEARTBEAT_FILENAME)
  else:
    candidates = ['heartbeat.0.json', HEARTBEAT_FILENAME]
  for name in candidates:
    path = os.path.join(model_dir, name)
    if os.path.exists(path):
      try:
        with open(path, encoding='utf-8') as f:
          return json.load(f)
      except ValueError:
        return None  # mid-replace race or torn tmp: treat as absent
  return None


def discover_hosts(model_dir: str) -> Dict[int, Dict[str, Optional[str]]]:
  """Per-host stream files under one (possibly fleet) model_dir.

  Returns ``{process_index: {'telemetry': path|None,
  'heartbeat': path|None}}`` from the LIVE filenames only (rotated
  ``.N`` generations belong to their live file and are stitched by
  ``read_telemetry``). The bare single-process names map to host 0; an
  explicitly indexed host-0 file wins over the bare name (a model_dir
  holding both saw a single-process run before a fleet one — the
  indexed stream is the fleet's).
  """
  hosts: Dict[int, Dict[str, Optional[str]]] = {}

  def slot(index: int) -> Dict[str, Optional[str]]:
    return hosts.setdefault(int(index), {'telemetry': None,
                                         'heartbeat': None})

  try:
    names = sorted(os.listdir(model_dir))
  except OSError:
    return hosts
  for name in names:
    match = _HOST_TELEMETRY_RE.match(name)
    if match:
      slot(int(match.group(1)))['telemetry'] = os.path.join(model_dir, name)
      continue
    match = _HOST_HEARTBEAT_RE.match(name)
    if match:
      slot(int(match.group(1)))['heartbeat'] = os.path.join(model_dir, name)
  bare_telemetry = os.path.join(model_dir, TELEMETRY_FILENAME)
  if os.path.exists(bare_telemetry) and not slot(0)['telemetry']:
    slot(0)['telemetry'] = bare_telemetry
  bare_heartbeat = os.path.join(model_dir, HEARTBEAT_FILENAME)
  if os.path.exists(bare_heartbeat) and not slot(0)['heartbeat']:
    slot(0)['heartbeat'] = bare_heartbeat
  # The bare probes above create an empty host-0 slot even when neither
  # bare file exists; drop it unless something real landed there.
  if not hosts.get(0, {}).get('telemetry') and \
      not hosts.get(0, {}).get('heartbeat'):
    hosts.pop(0, None)
  return hosts
