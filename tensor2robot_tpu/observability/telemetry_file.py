"""Machine-readable run telemetry: ``telemetry.jsonl`` + heartbeat file.

TensorBoard events are for humans with a browser; fleet tooling (and
``bin/t2r_telemetry``) wants greppable, append-only JSON lines under
``model_dir``:

  * ``telemetry.jsonl`` — one JSON object per line:
    ``{"time": <unix>, "kind": "...", "step": <int|null>, ...payload}``.
    Kinds written by the trainer: ``run_start``, ``train`` (scalars +
    goodput at the log cadence), ``preempted``, ``rollback``,
    ``run_abort`` (any other exception escaping the loop), ``run_end``.
    The file is append-only across restarts — a preempted run's history
    survives its own resumption.
  * ``heartbeat.json`` — atomically replaced (tmp + rename) at the log
    cadence: ``{"time", "step", "pid", "hostname"}``. A watchdog that
    sees a stale heartbeat knows the process is wedged even when the
    jsonl tail looks healthy; readers never observe a half-written file.

``read_telemetry`` tolerates a torn final line (the writer may be killed
mid-append) but raises on malformed interior lines — silent corruption
of history is worse than a crash in a tool.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, List, Optional

__all__ = ['TelemetryLogger', 'read_telemetry', 'read_heartbeat',
           'TELEMETRY_FILENAME', 'HEARTBEAT_FILENAME']

TELEMETRY_FILENAME = 'telemetry.jsonl'
HEARTBEAT_FILENAME = 'heartbeat.json'


class TelemetryLogger:
  """Appends telemetry records and maintains the heartbeat for one run."""

  def __init__(self, model_dir: str):
    os.makedirs(model_dir, exist_ok=True)
    self.model_dir = model_dir
    self._path = os.path.join(model_dir, TELEMETRY_FILENAME)
    self._heartbeat_path = os.path.join(model_dir, HEARTBEAT_FILENAME)
    self._file = open(self._path, 'a', encoding='utf-8')

  @property
  def path(self) -> str:
    return self._path

  def log(self, kind: str, step: Optional[int] = None,
          **payload) -> Dict[str, object]:
    """Appends one record; returns it (tests and callers can reuse it)."""
    record: Dict[str, object] = {
        'time': time.time(),  # wall-clock timestamp (cross-process record)
        'kind': kind,
        'step': None if step is None else int(step)}
    record.update(payload)
    self._file.write(json.dumps(record) + '\n')
    return record

  def heartbeat(self, step: Optional[int] = None, **extra) -> None:
    """Atomically replaces the heartbeat file (never half-written)."""
    beat: Dict[str, object] = {
        'time': time.time(),  # wall-clock timestamp (external readers)
        'step': None if step is None else int(step),
        'pid': os.getpid(),
        'hostname': socket.gethostname(),
    }
    beat.update(extra)
    tmp = self._heartbeat_path + '.tmp'
    with open(tmp, 'w', encoding='utf-8') as f:
      json.dump(beat, f)
    os.replace(tmp, self._heartbeat_path)

  def flush(self) -> None:
    self._file.flush()

  def close(self) -> None:
    if not self._file.closed:
      self._file.flush()
      self._file.close()


def read_telemetry(path: str) -> List[Dict[str, object]]:
  """Parses a telemetry.jsonl file (or the model_dir holding one).

  A torn FINAL line (writer killed mid-append) is dropped silently;
  malformed interior lines raise ValueError naming the line number.
  """
  if os.path.isdir(path):
    path = os.path.join(path, TELEMETRY_FILENAME)
  records: List[Dict[str, object]] = []
  with open(path, encoding='utf-8') as f:
    lines = f.read().splitlines()
  for index, line in enumerate(lines):
    if not line.strip():
      continue
    try:
      records.append(json.loads(line))
    except ValueError as e:
      if index == len(lines) - 1:
        break  # torn tail from a killed writer: ignore
      raise ValueError('{}:{} holds malformed telemetry: {}'.format(
          path, index + 1, e)) from e
  return records


def read_heartbeat(model_dir: str) -> Optional[Dict[str, object]]:
  """The last heartbeat written under ``model_dir``, or None."""
  path = os.path.join(model_dir, HEARTBEAT_FILENAME)
  if not os.path.exists(path):
    return None
  with open(path, encoding='utf-8') as f:
    return json.load(f)
