"""Pipeline X-ray: per-stage host->device dataflow tracing + attribution.

The host->device data path was a black box between bench runs: bench.py
measured stage rates offline (BENCH_r05 names ``e2e_bottleneck:
"transfer"`` at 24.6 MB/s) but a live run had no stage-level throughput,
queue-occupancy, or backpressure signal anywhere — a regression in the
input path only showed up as a mysterious goodput ``data`` fraction.
This module closes that gap with a stage model every data layer reports
into (docs/observability.md "Pipeline X-ray"):

  * ``read``     — record I/O: the C++ loader's reader thread
                   (record_loader.cc stats export) or the Python
                   TFRecord interleave (data/pipeline.py).
  * ``decode``   — proto parse + JPEG decode: the C++ worker pool
                   (per-pool busy/idle seconds, worker count) or the
                   Python ExampleParser fallback.
  * ``batch``    — batch assembly/handoff: the generators' prefetch
                   producers (data/input_generators.py); the native
                   stream's pack cost is the ``pipeline/batch/pack_ms``
                   histogram (busy-only — its rows are already counted
                   by the decode stage).
  * ``transfer`` — the host->device hop: ``data/device_feed.py``
                   (bytes, busy seconds, double-buffer occupancy).
  * ``device``   — the jitted step: derived from the trainer's goodput
                   ``productive`` seconds, no extra instrumentation.

Sources write MONOTONIC counters (``pipeline/<stage>/{examples,bytes,
busy_seconds}``); :class:`PipelineXray` windows them at the trainer's
log cadence into per-stage CAPACITY estimates
(``examples_processed / busy_seconds``, worker-count-normalized for the
decode pool). Capacity — not raw throughput — is the attributable
quantity: in steady state every stage's throughput equals the e2e rate
by construction, but busy-time-derived capacity names the stage that
would gate if everything upstream were infinite. The same attribution
rule (:func:`attribute_stages`) is what ``bench.py`` uses for its
``e2e_bottleneck`` field, so bench and live training report the SAME
quantity.

Each ``observe()`` yields a ``t2r.pipeline.v1`` record (written to
``telemetry.jsonl`` as kind ``pipeline``) naming the gating stage and
its headroom vs. the device rate, plus watchdog-style anomalies that
feed the symptom->capture->attribution loop (docs/observability.md):

  * ``pipeline_stall``       — the e2e flow rate collapsed below the
    rolling baseline while the trainer was data-starved: something in
    the host path stopped producing (detail names the gating stage).
  * ``worker_starvation``    — the decode pool sat mostly idle while
    the trainer starved: the stage UPSTREAM of the workers (record
    I/O) cannot feed them. Like every windowed detection here, it
    fires on the window in which the evidence lands — a wait that is
    still in progress commits its idle seconds when it returns, so a
    hard starvation is attributed on the first window after flow
    resumes (a TOTAL stall blocks the trainer loop itself, and is the
    ``pipeline_stall`` / heartbeat-staleness territory).
  * ``transfer_regression``  — the measured host->device MB/s fell
    below its rolling baseline (link contention, pathological batch).

Like the watchdog, anomalous windows never fold into the baselines, all
timing is ``time.perf_counter`` windows upstream, and ``observe()`` is
a pure in-memory pass — no threads, no I/O.
"""

from __future__ import annotations

import collections
import statistics
from typing import Deque, Dict, List, Optional, Tuple

from tensor2robot_tpu.observability import registry as registry_lib
from tensor2robot_tpu.observability.watchdog import ANOMALY_COUNTER, Anomaly

__all__ = [
    'PIPELINE_RECORD_SCHEMA',
    'PIPELINE_STALL',
    'WORKER_STARVATION',
    'TRANSFER_REGRESSION',
    'STAGES',
    'E2E_WIRE_BENCH_KEYS',
    'StageMeter',
    'XrayConfig',
    'PipelineXray',
    'attribute_stages',
    'stage_counter_names',
]

PIPELINE_RECORD_SCHEMA = 't2r.pipeline.v1'

# The transfer-path keys a successful bench e2e section must publish
# (bench.py emits them and self-checks against this tuple; the jax-free
# bin/check_pipeline_doctor gate schema-locks it — ISSUE 10). Kept here,
# next to attribute_stages, because the wire rate these keys carry is
# the 'transfer' input of the shared attribution rule.
E2E_WIRE_BENCH_KEYS = (
    'e2e_samples_per_sec',
    'e2e_samples_per_sec_spread',
    'e2e_bytes_per_example',
    'e2e_transfer_compression',
    'e2e_transfer_overlap',
    'e2e_transfer_overlap_spread',
    'transfer_mb_per_sec',
    'transfer_mb_per_sec_spread',
    'e2e_wire_examples_per_sec',
    'e2e_wire_examples_per_sec_spread',
    'e2e_bottleneck',
    'e2e_headroom_vs_device',
)

# New watchdog anomaly kinds (counted into watchdog/anomalies like the
# step-time/goodput/recompile/hbm kinds from observability/watchdog.py).
PIPELINE_STALL = 'pipeline_stall'
WORKER_STARVATION = 'worker_starvation'
TRANSFER_REGRESSION = 'transfer_regression'

# Canonical stage order, upstream -> downstream.
STAGES = ('read', 'decode', 'batch', 'transfer', 'device')

# Decode-pool size gauge (data/native_loader.py sets it; 0/absent means
# the single-threaded Python parser, normalized as 1 worker).
DECODE_WORKERS_GAUGE = 'pipeline/decode/workers'
DECODE_IDLE_COUNTER = 'pipeline/decode/idle_seconds'


def stage_counter_names(stage: str) -> Tuple[str, str, str]:
  """(examples, bytes, busy_seconds) counter names for one stage."""
  prefix = 'pipeline/' + stage + '/'
  return (prefix + 'examples', prefix + 'bytes', prefix + 'busy_seconds')


class StageMeter:
  """Source-side instrument bundle for one pipeline stage.

  Resolve once (construction registers the three counters), then
  ``add`` from the hot path — three lock-protected float adds, no
  allocation. Every example must be counted by AT MOST ONE call site
  per stage; busy seconds are the host seconds that stage actually
  spent processing (for a worker pool: summed across workers — the
  X-ray normalizes by the ``pipeline/decode/workers`` gauge).
  """

  __slots__ = ('stage', '_examples', '_bytes', '_busy')

  def __init__(self, stage: str,
               registry: Optional[registry_lib.TelemetryRegistry] = None):
    registry = registry or registry_lib.get_registry()
    examples, nbytes, busy = stage_counter_names(stage)
    self.stage = stage
    self._examples = registry.counter(examples)
    self._bytes = registry.counter(nbytes)
    self._busy = registry.counter(busy)

  def add(self, examples: float = 0.0, nbytes: float = 0.0,
          busy_s: float = 0.0) -> None:
    if examples:
      self._examples.inc(examples)
    if nbytes:
      self._bytes.inc(nbytes)
    if busy_s > 0.0:
      self._busy.inc(busy_s)


def attribute_stages(rates: Dict[str, Optional[float]]
                     ) -> Dict[str, object]:
  """Names the gating stage from per-stage examples/sec rates.

  THE shared attribution rule: ``bench.py`` feeds it separately-measured
  stage benches; :class:`PipelineXray` feeds it live busy-time capacity
  estimates — both report the same ``bottleneck`` semantics. Stages with
  missing/non-positive rates are skipped (an unmeasured stage is unknown,
  not infinitely fast — but it must not win the argmin by defaulting to
  zero). Ties break deterministically toward the lexicographically first
  stage name.

  Returns ``{'bottleneck': <stage|None>, 'headroom_vs_device': <float|
  None>, 'rates': {stage: rate}}`` where headroom is the gating stage's
  rate as a fraction of the device rate (1.0 == device-bound; < 1 means
  the pipeline, not the chip, caps end-to-end throughput).
  """
  valid = {stage: float(rate) for stage, rate in rates.items()
           if rate is not None and rate > 0.0}
  if not valid:
    return {'bottleneck': None, 'headroom_vs_device': None, 'rates': {}}
  bottleneck = min(sorted(valid), key=lambda stage: valid[stage])
  device = valid.get('device')
  headroom = (valid[bottleneck] / device) if device else None
  return {'bottleneck': bottleneck, 'headroom_vs_device': headroom,
          'rates': valid}


class XrayConfig:
  """Thresholds for the pipeline anomaly detections.

  Ratios follow the watchdog posture (docs/observability.md): fire on
  sustained ~2x collapses, not single-window jitter. The transfer
  detection additionally requires the transfer stage to be a
  non-negligible share of the window (``transfer_min_busy_fraction``) —
  a 100 us hop's MB/s estimate is pure jitter and could never gate the
  pipeline anyway.
  """

  def __init__(self,
               min_baseline_windows: int = 3,
               baseline_windows: int = 16,
               stall_ratio: float = 2.0,
               stall_data_fraction: float = 0.5,
               starvation_idle_fraction: float = 0.75,
               starvation_data_fraction: float = 0.5,
               transfer_regression_ratio: float = 2.0,
               transfer_min_busy_fraction: float = 0.05,
               min_stage_busy_seconds: float = 1e-3):
    if stall_ratio <= 1.0 or transfer_regression_ratio <= 1.0:
      raise ValueError('regression ratios must exceed 1.0; got {} / {}.'
                       .format(stall_ratio, transfer_regression_ratio))
    if not 0.0 < starvation_idle_fraction < 1.0:
      raise ValueError('starvation_idle_fraction must be in (0, 1); got {}.'
                       .format(starvation_idle_fraction))
    self.min_baseline_windows = int(min_baseline_windows)
    self.baseline_windows = int(baseline_windows)
    self.stall_ratio = float(stall_ratio)
    self.stall_data_fraction = float(stall_data_fraction)
    self.starvation_idle_fraction = float(starvation_idle_fraction)
    self.starvation_data_fraction = float(starvation_data_fraction)
    self.transfer_regression_ratio = float(transfer_regression_ratio)
    self.transfer_min_busy_fraction = float(transfer_min_busy_fraction)
    self.min_stage_busy_seconds = float(min_stage_busy_seconds)


class PipelineXray:
  """Windows the pipeline counters into live bottleneck attribution.

  The trainer calls ``observe(step, examples, window_seconds,
  goodput_seconds)`` once per log window; each call returns the
  ``t2r.pipeline.v1`` record for ``telemetry.jsonl`` plus any fired
  anomalies (handled exactly like watchdog detections: logged, recorded,
  and answered with a budgeted capture). ``last_record`` feeds the
  forensics report's ``pipeline`` stage table.
  """

  def __init__(self, config: Optional[XrayConfig] = None,
               registry: Optional[registry_lib.TelemetryRegistry] = None):
    self.config = config or XrayConfig()
    self._registry = registry
    # Seed the counter baseline at construction: the registry is
    # process-wide, so a prior Trainer/eval/bench phase in the same
    # process may already hold pipeline counters — diffing the first
    # window against zero would fold that whole history into one
    # window's rates (busy fractions over 1.0, garbage capacities).
    try:
      self._last_counters: Optional[Dict[str, float]] = dict(
          self.registry.snapshot().get('counters', {}))
    except Exception:  # noqa: BLE001 — never fail trainer construction
      self._last_counters = None
    self._last_goodput: Optional[Dict[str, float]] = None
    self._windows_seen = 0
    self._rate_baseline: Deque[float] = collections.deque(
        maxlen=self.config.baseline_windows)
    self._transfer_baseline: Deque[float] = collections.deque(
        maxlen=self.config.baseline_windows)
    self.last_record: Optional[Dict[str, object]] = None

  @property
  def registry(self) -> registry_lib.TelemetryRegistry:
    return self._registry or registry_lib.get_registry()

  # -- internals -------------------------------------------------------------

  def _snapshot(self) -> Tuple[Dict[str, float], Dict[str, float]]:
    snapshot = self.registry.snapshot()
    return (dict(snapshot.get('counters', {})),
            dict(snapshot.get('gauges', {})))

  def _stage_window(self, counters: Dict[str, float], stage: str
                    ) -> Dict[str, float]:
    last = self._last_counters or {}
    out = {}
    for key, name in zip(('examples', 'bytes', 'busy_seconds'),
                         stage_counter_names(stage)):
      out[key] = counters.get(name, 0.0) - last.get(name, 0.0)
    return out

  # -- the log-cadence pass --------------------------------------------------

  def observe(self, step: int, examples: float, window_seconds: float,
              goodput_seconds: Optional[Dict[str, float]] = None
              ) -> Tuple[Dict[str, object], List[Anomaly]]:
    """One window: (t2r.pipeline.v1 record, fired anomalies).

    ``examples`` is the count the trainer consumed this window (the e2e
    flow meter); ``goodput_seconds`` the tracker's CUMULATIVE seconds
    (differenced here, like the watchdog). All durations upstream come
    from ``time.perf_counter`` windows.
    """
    self._windows_seen += 1
    window_seconds = max(float(window_seconds), 1e-9)
    counters, gauges = self._snapshot()
    registry = self.registry

    # Goodput window: the data fraction is the starvation evidence.
    data_fraction = 0.0
    productive_s = None
    if goodput_seconds is not None:
      last = self._last_goodput or {}
      window = {k: goodput_seconds.get(k, 0.0) - last.get(k, 0.0)
                for k in goodput_seconds}
      self._last_goodput = dict(goodput_seconds)
      total = sum(window.values())
      if total > 0.0:
        data_fraction = window.get('data', 0.0) / total
        productive_s = window.get('productive', 0.0)

    workers = max(gauges.get(DECODE_WORKERS_GAUGE, 0.0), 1.0)
    min_busy = self.config.min_stage_busy_seconds
    stages: Dict[str, Dict[str, object]] = {}
    capacities: Dict[str, Optional[float]] = {}
    for stage in ('read', 'decode', 'batch', 'transfer'):
      window = self._stage_window(counters, stage)
      if not any(window.values()):
        continue  # stage not instrumented in this topology
      busy = window['busy_seconds']
      parallelism = workers if stage == 'decode' else 1.0
      capacity = None
      if window['examples'] > 0 and busy > min_busy:
        capacity = window['examples'] * parallelism / busy
      mb_per_sec = (window['bytes'] / busy / 1e6
                    if window['bytes'] > 0 and busy > min_busy else None)
      stages[stage] = {
          'examples': window['examples'],
          'bytes': window['bytes'],
          'busy_seconds': busy,
          'busy_fraction': busy / (window_seconds * parallelism),
          'examples_per_sec_capacity': capacity,
          'mb_per_sec': mb_per_sec,
      }
      capacities[stage] = capacity
    # Device stage: examples over the window's productive seconds — the
    # dispatch+compute rate with every host-side wait excluded.
    device_capacity = None
    if productive_s is not None and productive_s > min_busy and examples > 0:
      device_capacity = examples / productive_s
      stages['device'] = {
          'examples': float(examples),
          'busy_seconds': productive_s,
          'busy_fraction': productive_s / window_seconds,
          'examples_per_sec_capacity': device_capacity,
      }
    capacities['device'] = device_capacity

    attribution = attribute_stages(
        {stage: capacity for stage, capacity in capacities.items()})
    e2e_rate = float(examples) / window_seconds

    # Queue evidence: the prefetch-depth gauges at sample time.
    queues = {name: value for name, value in gauges.items()
              if name.startswith('data/prefetch_queue_depth')
              or name.endswith('buffer_occupancy')}

    anomalies = self._detect(step, e2e_rate, data_fraction, counters,
                             stages, attribution)

    # Derived per-stage gauges for TensorBoard (raw counters stay the
    # source of truth; these are the human-readable windowed view).
    for stage, info in stages.items():
      capacity = info.get('examples_per_sec_capacity')
      if capacity is not None:
        registry.gauge_family('pipeline/examples_per_sec', ('stage',)) \
            .series(stage).set(capacity)
      registry.gauge_family('pipeline/busy_fraction', ('stage',)) \
          .series(stage).set(float(info['busy_fraction']))
    if attribution['headroom_vs_device'] is not None:
      registry.gauge('pipeline/headroom_vs_device').set(
          attribution['headroom_vs_device'])

    record: Dict[str, object] = {
        'schema': PIPELINE_RECORD_SCHEMA,
        'window_seconds': window_seconds,
        'examples_per_sec': e2e_rate,
        'data_fraction': data_fraction,
        'stages': stages,
        'queues': queues,
        'bottleneck': attribution['bottleneck'],
        'headroom_vs_device': attribution['headroom_vs_device'],
        'anomalies': [anomaly.kind for anomaly in anomalies],
    }
    self.last_record = record

    if anomalies:
      family = registry.counter_family(ANOMALY_COUNTER, ('kind',))
      for anomaly in anomalies:
        family.series(anomaly.kind).inc()
    self._last_counters = counters
    return record, anomalies

  # -- detections ------------------------------------------------------------

  def _detect(self, step: int, e2e_rate: float, data_fraction: float,
              counters: Dict[str, float], stages: Dict[str, Dict[str, object]],
              attribution: Dict[str, object]) -> List[Anomaly]:
    config = self.config
    anomalies: List[Anomaly] = []

    # pipeline_stall: flow collapsed vs the healthy baseline while the
    # trainer starved on data — the host path stopped producing.
    rate_baseline = (statistics.median(self._rate_baseline)
                     if len(self._rate_baseline)
                     >= config.min_baseline_windows else None)
    stalled = (rate_baseline is not None and rate_baseline > 0.0
               and e2e_rate < rate_baseline / config.stall_ratio
               and data_fraction > config.stall_data_fraction)
    if stalled:
      gate = attribution.get('bottleneck') or 'unknown'
      anomalies.append(Anomaly(
          PIPELINE_STALL, step,
          'pipeline flow fell to {:.1f} ex/s ({:.1f}x below the {:.1f} ex/s '
          'baseline) with {:.0%} of the window lost to data; gating stage: '
          '{}'.format(e2e_rate, rate_baseline / max(e2e_rate, 1e-9),
                      rate_baseline, data_fraction, gate),
          {'examples_per_sec': e2e_rate, 'baseline': rate_baseline,
           'data_fraction': data_fraction, 'stage': gate}))
    else:
      self._rate_baseline.append(e2e_rate)

    # worker_starvation: the decode pool idled while the trainer starved
    # — record I/O (or upstream backpressure) cannot feed the workers.
    last = {} if self._last_counters is None else self._last_counters
    decode = stages.get('decode')
    if decode is not None:
      idle = (counters.get(DECODE_IDLE_COUNTER, 0.0)
              - last.get(DECODE_IDLE_COUNTER, 0.0))
      busy = float(decode['busy_seconds'])
      active = idle + busy
      if active > config.min_stage_busy_seconds:
        idle_fraction = idle / active
        if (idle_fraction > config.starvation_idle_fraction
            and data_fraction > config.starvation_data_fraction):
          anomalies.append(Anomaly(
              WORKER_STARVATION, step,
              'decode workers idled {:.0%} of their window while {:.0%} of '
              'trainer time was lost to data: the read stage cannot feed '
              'the pool'.format(idle_fraction, data_fraction),
              {'worker_idle_fraction': idle_fraction,
               'data_fraction': data_fraction}))

    # transfer_regression: host->device MB/s fell below its baseline.
    transfer = stages.get('transfer')
    if transfer is not None and transfer.get('mb_per_sec') is not None:
      busy_fraction = float(transfer['busy_fraction'])
      mb_per_sec = float(transfer['mb_per_sec'])
      if busy_fraction >= config.transfer_min_busy_fraction:
        baseline = (statistics.median(self._transfer_baseline)
                    if len(self._transfer_baseline)
                    >= config.min_baseline_windows else None)
        if baseline is not None and \
            mb_per_sec < baseline / config.transfer_regression_ratio:
          anomalies.append(Anomaly(
              TRANSFER_REGRESSION, step,
              'host->device transfer fell to {:.1f} MB/s ({:.1f}x below '
              'the {:.1f} MB/s baseline)'.format(
                  mb_per_sec, baseline / max(mb_per_sec, 1e-9), baseline),
              {'mb_per_sec': mb_per_sec, 'baseline': baseline}))
        else:
          self._transfer_baseline.append(mb_per_sec)
    return anomalies
