"""Trace spans: monotonic wall-time histograms that line up with xplane.

``span('data.next')`` times a region with ``time.perf_counter`` and
records milliseconds into the registry histogram ``span/data.next``.
When a ``jax.profiler`` trace window is active (the trainer's
``profile_steps`` bracket), the span additionally enters a
``jax.profiler.TraceAnnotation`` of the same name — so the host-side
seams (data wait, checkpoint save, step dispatch) appear as named rows
in the SAME capture ``utils/xplane.py`` attributes device ops from, and
goodput numbers can be cross-checked against the trace.

Outside a trace window the annotation path is skipped entirely (no jax
import, no TSL call): a span is then two ``perf_counter`` reads and one
histogram bump. The trainer toggles the window via ``set_trace_active``;
anything else that starts its own trace can do the same.

Use as a context manager or a decorator::

    with span('data.next'):
        batch = next(iterator)

    @span('policy.pack')
    def pack(...): ...

The context-manager form exposes ``elapsed`` (seconds) after exit, so
call sites that also feed goodput accounting time the region once.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Optional

from tensor2robot_tpu.observability import registry as registry_lib

__all__ = ['span', 'set_trace_active', 'trace_active']

_STATE_LOCK = threading.Lock()
_TRACE_ACTIVE = False

# Span histograms hold milliseconds: sub-ms histogram bumps up to minutes
# (a slow checkpoint commit, a cold data pipeline).
SPAN_BUCKETS_MS = registry_lib.exponential_buckets(0.01, 2.0, 25)


def set_trace_active(active: bool) -> None:
  """Marks a profiler trace window open/closed (trainer._maybe_profile)."""
  global _TRACE_ACTIVE
  with _STATE_LOCK:
    _TRACE_ACTIVE = bool(active)


def trace_active() -> bool:
  return _TRACE_ACTIVE


class span:  # noqa: N801 — reads as a keyword at call sites
  """Times one region into ``span/<name>`` (ms); annotates active traces."""

  __slots__ = ('_name', '_registry', '_start', '_annotation', 'elapsed')

  def __init__(self, name: str,
               registry: Optional[registry_lib.TelemetryRegistry] = None):
    self._name = name
    self._registry = registry
    self._start = 0.0
    self._annotation = None
    self.elapsed = 0.0

  def __enter__(self) -> 'span':
    if _TRACE_ACTIVE:
      try:
        import jax  # deferred: spans must work on jax-free hosts

        self._annotation = jax.profiler.TraceAnnotation(self._name)
        self._annotation.__enter__()
      except Exception:  # noqa: BLE001 — annotation is best-effort
        self._annotation = None
    self._start = time.perf_counter()
    return self

  def __exit__(self, exc_type, exc, tb) -> None:
    self.elapsed = time.perf_counter() - self._start
    if self._annotation is not None:
      try:
        self._annotation.__exit__(exc_type, exc, tb)
      except Exception:  # noqa: BLE001
        pass
      self._annotation = None
    registry = self._registry or registry_lib.get_registry()
    registry.histogram('span/' + self._name,
                       bounds=SPAN_BUCKETS_MS).record(self.elapsed * 1e3)

  def __call__(self, fn):
    """Decorator form: each call runs under a fresh span instance."""
    name = self._name
    registry = self._registry

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
      with span(name, registry=registry):
        return fn(*args, **kwargs)

    return wrapper
