"""Goodput accounting: where every second of training wall-clock went.

Podracer (arXiv:2104.06272) and the TPUv4 pjit scaling report
(arXiv:2204.06514) make the same observation: sustained accelerator
utilization is won by classifying wall time at the seams — an
unaccounted second is indistinguishable from a slow model. The trainer
charges every training-loop second to exactly one category:

  * ``productive``  — step dispatch + device compute + everything not
    claimed below (logging, hooks); the time that trains the model.
  * ``data``        — waiting on the input pipeline (``next(iterator)``
    plus host→device transfer). High => data-starved; scale the host
    pipeline, not the model.
  * ``checkpoint``  — blocking portions of checkpoint save (async
    commits only charge their synchronous tail).
  * ``retry``       — fault-recovery overhead: NaN-rollback restores,
    retried I/O waits, post-rollback re-fetches.

Because ``productive`` is defined as the remainder, the four categories
partition wall time exactly: fractions always sum to 1.0 (the invariant
tests assert). The trainer exports both ``goodput/<cat>_seconds``
(cumulative) and ``goodput/<cat>_fraction`` to TensorBoard and
``telemetry.jsonl`` at its log cadence.
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = ['GoodputTracker', 'PRODUCTIVE', 'DATA', 'CHECKPOINT', 'RETRY',
           'CATEGORIES']

PRODUCTIVE = 'productive'
DATA = 'data'
CHECKPOINT = 'checkpoint'
RETRY = 'retry'

CATEGORIES = (PRODUCTIVE, DATA, CHECKPOINT, RETRY)


class GoodputTracker:
  """Accumulates seconds per category; reports totals and fractions."""

  def __init__(self):
    self._lock = threading.Lock()
    self._seconds = {category: 0.0 for category in CATEGORIES}

  def add(self, category: str, seconds: float) -> None:
    if category not in self._seconds:
      raise ValueError('Unknown goodput category {!r}; expected one of {}.'
                       .format(category, CATEGORIES))
    if seconds < 0:
      seconds = 0.0  # clock-resolution jitter must not go negative
    with self._lock:
      self._seconds[category] += seconds

  def seconds(self) -> Dict[str, float]:
    with self._lock:
      return dict(self._seconds)

  def total_seconds(self) -> float:
    with self._lock:
      return sum(self._seconds.values())

  def fractions(self) -> Dict[str, float]:
    """{category: share of accounted wall time}; sums to 1.0 (or all zeros
    before any time is recorded)."""
    with self._lock:
      total = sum(self._seconds.values())
      if total <= 0.0:
        return {category: 0.0 for category in CATEGORIES}
      return {category: value / total
              for category, value in self._seconds.items()}

  def scalars(self, prefix: str = 'goodput/') -> Dict[str, float]:
    """The TensorBoard/telemetry export: fractions + cumulative seconds."""
    out = {}
    for category, value in self.seconds().items():
      out['{}{}_seconds'.format(prefix, category)] = value
    for category, value in self.fractions().items():
      out['{}{}_fraction'.format(prefix, category)] = value
    out[prefix + 'total_seconds'] = self.total_seconds()
    return out
