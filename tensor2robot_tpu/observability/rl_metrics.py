"""The RL loop's telemetry vocabulary (``t2r.rl.v1``), jax-free.

The closed actor<->learner loop (rl/loop.py, ISSUE 12) reports one
``kind="rl"`` record per report window; this module is the schema's
single home — record kind/schema, registry series names, the
``RL_LOOP_BENCH_KEYS`` tuple ``bench.py`` self-checks its closed-loop
axis against (and ``bin/check_rl_doctor`` schema-locks), and the
per-scenario success-spread rule — kept in ``observability/`` (like
``pipeline_xray.E2E_WIRE_BENCH_KEYS``) so the jax-free readers
(``doctor``, ``t2r_telemetry``, the CI gate) and the jax-heavy writer
share ONE definition without the gate importing jax.

Record fields (every rate is a window delta over ``window_seconds``):

  * ``actor_steps`` / ``actor_steps_per_sec`` — jitted acting steps
    (each advances EVERY env slot once).
  * ``env_steps`` / ``env_steps_per_sec`` — ``actor_steps * num_envs``.
  * ``episodes`` / ``episodes_per_sec`` — episodes completed (terminal
    or timeout) across all slots.
  * ``success_rate`` (window) / ``success_rate_cumulative`` — grasp
    successes over completed episodes.
  * ``transitions`` — replay records flushed this window.
  * ``learner_steps`` / ``learner_steps_per_sec`` — Bellman steps the
    concurrent learner completed.
  * ``actor_version`` / ``learner_version`` / ``swaps`` /
    ``dropped_swaps`` — the hot-swap protocol's observable state: the
    snapshot version the actor is acting under, the newest version the
    learner published, adopted swaps, and polls dropped (the
    ``learner.swap`` fault site; a drop is retried next poll).
  * ``act_step_ms`` — mean acting-step wall ms this window.
  * ``act_jit_cache`` — the acting program's jit executable-cache size;
    exactly 1 after warmup (the zero-request-time-compile invariant).
  * ``buckets`` — per scenario-difficulty bucket:
    ``{episodes, successes, success_rate, window_episodes}``
    (cumulative counts, windowed activity).
  * ``scenario_success_spread`` — max-min cumulative success rate
    across buckets that have completed at least one episode.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

__all__ = ['RL_RECORD_KIND', 'RL_RECORD_SCHEMA', 'RL_LOOP_BENCH_KEYS',
           'RL_EPISODES_COUNTER', 'RL_SUCCESSES_COUNTER',
           'RL_ENV_STEPS_COUNTER', 'RL_ACTOR_STEPS_COUNTER',
           'RL_LEARNER_STEPS_COUNTER', 'RL_TRANSITIONS_COUNTER',
           'RL_SWAPS_COUNTER', 'RL_DROPPED_SWAPS_COUNTER',
           'RL_ACTOR_VERSION_GAUGE', 'RL_LEARNER_VERSION_GAUGE',
           'RL_ACT_MS_HISTOGRAM', 'ACT_RECOMPILE_GAUGE',
           'scenario_success_spread', 'bucket_table']

RL_RECORD_KIND = 'rl'
RL_RECORD_SCHEMA = 't2r.rl.v1'

# Registry series the loop writes (docs/observability.md catalog).
RL_EPISODES_COUNTER = 'rl/episodes'          # family, label: bucket
RL_SUCCESSES_COUNTER = 'rl/successes'        # family, label: bucket
RL_ENV_STEPS_COUNTER = 'rl/env_steps'
RL_ACTOR_STEPS_COUNTER = 'rl/actor_steps'
RL_LEARNER_STEPS_COUNTER = 'rl/learner_steps'
RL_TRANSITIONS_COUNTER = 'rl/transitions'
RL_SWAPS_COUNTER = 'rl/swaps'
RL_DROPPED_SWAPS_COUNTER = 'rl/dropped_swaps'
RL_ACTOR_VERSION_GAUGE = 'rl/actor_param_version'
RL_LEARNER_VERSION_GAUGE = 'rl/learner_param_version'
RL_ACT_MS_HISTOGRAM = 'rl/act_step_ms'
# Same family as the trainer's recompiles/train_step: the acting
# program's jit cache size, ==1 healthy after warmup.
ACT_RECOMPILE_GAUGE = 'recompiles/act_step'

# The closed-loop bench axis keys a successful `bench.py` rl section
# must publish (bench self-checks; bin/check_rl_doctor schema-locks).
# The bars these keys carry — success measurably rising over wallclock
# (`rl_success_curve` samples), zero request-time compiles in the
# acting path (`rl_act_jit_cache` == 1) — ARE the loop's contract.
RL_LOOP_BENCH_KEYS = (
    'rl_num_envs',
    'rl_episodes_per_sec',
    'rl_episodes_per_sec_spread',
    'rl_env_steps_per_sec',
    'rl_success_rate_final',
    'rl_success_curve',
    'rl_swap_count',
    'rl_scenario_success_spread',
    'rl_act_jit_cache',
)


def scenario_success_spread(
    buckets: Mapping[str, Mapping[str, float]]) -> Optional[float]:
  """max - min cumulative success rate across active buckets.

  ``buckets`` is the record's per-bucket table; only buckets with at
  least one completed episode participate. Returns None until two
  buckets are active (a spread over one point is not a spread).
  """
  rates = [float(entry.get('success_rate', 0.0))
           for entry in buckets.values()
           if float(entry.get('episodes', 0)) > 0]
  if len(rates) < 2:
    return None
  return max(rates) - min(rates)


def bucket_table(episodes: Mapping[int, int],
                 successes: Mapping[int, int],
                 window_episodes: Optional[Mapping[int, int]] = None
                 ) -> Dict[str, Dict[str, float]]:
  """The record's ``buckets`` field from cumulative per-bucket counts."""
  table: Dict[str, Dict[str, float]] = {}
  for bucket in sorted(episodes):
    count = int(episodes[bucket])
    if count <= 0:
      continue
    wins = int(successes.get(bucket, 0))
    entry = {'episodes': count, 'successes': wins,
             'success_rate': round(wins / count, 4)}
    if window_episodes is not None:
      entry['window_episodes'] = int(window_episodes.get(bucket, 0))
    table[str(bucket)] = entry
  return table
