"""Telemetry registry: process-wide counters, gauges, and histograms.

The one pipeline every layer reports through (ISSUE 3): reliability
counters, data-pipeline gauges, span and inference-latency histograms all
live in a single ``TelemetryRegistry`` that the trainer exports to
TensorBoard scalars and ``telemetry.jsonl`` at its log cadence — instead
of each subsystem inventing an ad-hoc dict merge (the pre-PR-3 quarantine
counters) or staying log-only (rollbacks, preemptions).

Design constraints, in order:

  * **Thread-safe**: instruments are written from the train loop, data
    prefetch threads, async checkpoint commits, and robot-side predictor
    threads concurrently. Every instrument takes its own small lock; the
    registry lock is only held during (rare) instrument creation.
  * **Zero hot-path allocation**: ``Counter.inc`` / ``Gauge.set`` /
    ``Histogram.record`` build no containers and format no strings — a
    histogram observation is one bisect into a frozen boundary tuple plus
    an integer bump in a preallocated count list. Resolve labeled series
    (``family.series(...)``) once outside loops; the resolution itself is
    a dict lookup and only allocates on first use of a label set.
  * **Fixed buckets**: histograms never rebucket. Percentiles are
    estimated by linear interpolation inside the owning bucket, clamped
    to the observed min/max, so p50/p95/p99 are exact to within one
    bucket width (tests/test_observability.py checks against numpy).

Export surfaces:
  * ``scalars()``  — flat ``{tag: float}`` for ``MetricsWriter`` (labels
    become path segments: ``inference/latency_ms/CheckpointPredictor/p95``).
  * ``snapshot()`` — structured dict for ``telemetry.jsonl``; pair two
    snapshots with ``snapshot_delta`` for rate windows.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    'Counter',
    'Gauge',
    'Histogram',
    'TelemetryRegistry',
    'exponential_buckets',
    'get_registry',
    'set_registry',
    'snapshot_delta',
    'DEFAULT_LATENCY_BUCKETS_MS',
    'DEFAULT_SECONDS_BUCKETS',
    'SLO_LATENCY_BUCKETS_MS',
]


def exponential_buckets(start: float, factor: float, count: int
                        ) -> Tuple[float, ...]:
  """``count`` upper bounds: start, start*factor, ... (an +inf overflow
  bucket is implicit in every histogram)."""
  if start <= 0 or factor <= 1 or count < 1:
    raise ValueError('exponential_buckets needs start>0, factor>1, count>=1; '
                     'got ({}, {}, {}).'.format(start, factor, count))
  return tuple(start * factor ** i for i in range(count))


# 0.05ms .. ~105s in x2 steps: wide enough for an on-device CEM dispatch at
# the bottom and a cold-start XLA compile at the top.
DEFAULT_LATENCY_BUCKETS_MS = exponential_buckets(0.05, 2.0, 21)
# 1ms .. ~1000s in x2 steps: span durations (data waits, checkpoint saves).
DEFAULT_SECONDS_BUCKETS = exponential_buckets(0.001, 2.0, 20)
# SLO-resolution latency edges (ISSUE 8 satellite): the default x2 edges
# put the 30 Hz envelope between 26.2 and 52.4 ms — a 26 ms-wide bucket,
# which makes "p99 < 33 ms" unanswerable from the histogram. These edges
# keep sub-ms resolution at the bottom (0.05..0.8 ms, x2) and 1 ms
# resolution across 1..100 ms, so any percentile inside the SLO band is
# interpolated to within 1 ms; >100 ms lands in x2 overflow decades up
# to ~1.6 s (a wedged batch is still measured, just coarsely).
SLO_LATENCY_BUCKETS_MS = (exponential_buckets(0.05, 2.0, 5)
                          + tuple(float(i) for i in range(1, 101))
                          + exponential_buckets(200.0, 2.0, 4))


class Counter:
  """Monotonic float counter."""

  __slots__ = ('_lock', '_value')

  def __init__(self):
    self._lock = threading.Lock()
    self._value = 0.0

  def inc(self, amount: float = 1.0) -> None:
    if amount < 0:
      raise ValueError('Counter can only increase; got {}.'.format(amount))
    with self._lock:
      self._value += amount

  @property
  def value(self) -> float:
    with self._lock:
      return self._value

  def reset(self) -> None:
    with self._lock:
      self._value = 0.0


class Gauge:
  """Last-write-wins instantaneous value."""

  __slots__ = ('_lock', '_value')

  def __init__(self):
    self._lock = threading.Lock()
    self._value = 0.0

  def set(self, value: float) -> None:
    with self._lock:
      self._value = float(value)

  def inc(self, amount: float = 1.0) -> None:
    with self._lock:
      self._value += amount

  @property
  def value(self) -> float:
    with self._lock:
      return self._value

  def reset(self) -> None:
    with self._lock:
      self._value = 0.0


class Histogram:
  """Fixed-bucket histogram with interpolated percentiles.

  ``bounds`` are inclusive upper bucket edges; one overflow bucket
  (+inf) is appended. Observations are unitless here — by convention the
  registry's metric name carries the unit (``..._ms``, ``..._seconds``).
  """

  __slots__ = ('_lock', '_bounds', '_counts', '_count', '_sum', '_min',
               '_max')

  def __init__(self, bounds: Sequence[float]):
    bounds = tuple(float(b) for b in bounds)
    if not bounds:
      raise ValueError('Histogram needs at least one bucket bound.')
    if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
      raise ValueError('Histogram bounds must be strictly increasing.')
    self._lock = threading.Lock()
    self._bounds = bounds
    self._counts = [0] * (len(bounds) + 1)  # +1: overflow bucket
    self._count = 0
    self._sum = 0.0
    self._min = math.inf
    self._max = -math.inf

  def record(self, value: float) -> None:
    index = bisect.bisect_left(self._bounds, value)
    with self._lock:
      self._counts[index] += 1
      self._count += 1
      self._sum += value
      if value < self._min:
        self._min = value
      if value > self._max:
        self._max = value

  @property
  def count(self) -> int:
    with self._lock:
      return self._count

  @property
  def sum(self) -> float:
    with self._lock:
      return self._sum

  @property
  def mean(self) -> float:
    with self._lock:
      return self._sum / self._count if self._count else 0.0

  def percentile(self, p: float) -> float:
    """Interpolated percentile estimate, exact to one bucket width.

    The rank lands in some bucket; the estimate interpolates linearly
    between that bucket's edges (clamped to the observed min/max, so the
    first/overflow buckets stay finite and single-value distributions
    return the value itself).
    """
    if not 0.0 <= p <= 100.0:
      raise ValueError('percentile must be in [0, 100]; got {}.'.format(p))
    with self._lock:
      return self._percentile_locked(p)

  def _percentile_locked(self, p: float) -> float:
    if self._count == 0:
      return 0.0
    rank = (p / 100.0) * self._count
    cumulative = 0
    for index, bucket_count in enumerate(self._counts):
      if bucket_count == 0:
        continue
      if cumulative + bucket_count >= rank:
        lower = self._bounds[index - 1] if index > 0 else self._min
        upper = (self._bounds[index] if index < len(self._bounds)
                 else self._max)
        lower = max(lower, self._min)
        upper = min(upper, self._max)
        if upper <= lower:
          return lower
        fraction = (rank - cumulative) / bucket_count
        return lower + fraction * (upper - lower)
      cumulative += bucket_count
    return self._max  # numerically unreachable; guards fp drift

  def summary(self) -> Dict[str, float]:
    """The scalar digest the trainer exports: count/mean/p50/p95/p99.

    Computed under ONE lock acquisition so a concurrent record()/reset()
    can never produce a torn digest (count from one state, max from
    another, or a -inf sentinel leaking into TensorBoard).
    """
    with self._lock:
      if self._count == 0:
        return {'count': 0.0}
      return {
          'count': float(self._count),
          'mean': self._sum / self._count,
          'p50': self._percentile_locked(50.0),
          'p95': self._percentile_locked(95.0),
          'p99': self._percentile_locked(99.0),
          'max': self._max,
      }

  def state(self) -> Dict[str, object]:
    """Full bucket state for snapshot export / jsonl round-trips."""
    with self._lock:
      return {
          'bounds': list(self._bounds),
          'counts': list(self._counts),
          'count': self._count,
          'sum': self._sum,
          'min': None if self._count == 0 else self._min,
          'max': None if self._count == 0 else self._max,
      }

  def reset(self) -> None:
    with self._lock:
      self._counts = [0] * (len(self._bounds) + 1)
      self._count = 0
      self._sum = 0.0
      self._min = math.inf
      self._max = -math.inf


class _Family:
  """A named instrument family keyed by label values.

  Histogram families additionally support **per-series bucket edges**
  (ISSUE 8 satellite): ``series(*labels, bounds=...)`` creates that one
  series with its own edges while every other series keeps the family
  default — the serving latency series needs 1 ms SLO resolution, but
  re-bucketing every existing predictor series for it would invalidate
  their history. A later ``series()`` call without ``bounds`` returns
  the existing instrument whatever its edges; a later call with
  DIFFERENT explicit bounds raises (same torn-layout rationale as
  ``TelemetryRegistry`` re-registration).
  """

  def __init__(self, make, label_names: Tuple[str, ...],
               supports_bounds: bool = False):
    self._make = make
    self._label_names = label_names
    self._supports_bounds = supports_bounds
    self._lock = threading.Lock()
    self._series: Dict[Tuple[str, ...], object] = {}
    # key -> explicit per-series bounds (None = family default).
    self._series_bounds: Dict[Tuple[str, ...], Optional[Tuple[float, ...]]] \
        = {}

  @property
  def label_names(self) -> Tuple[str, ...]:
    return self._label_names

  def series(self, *label_values: str,
             bounds: Optional[Sequence[float]] = None):
    """The child instrument for one label combination (cached).

    Resolve once outside hot loops; the instrument handle itself is then
    allocation-free to write. ``bounds`` (histogram families only)
    overrides the family's bucket edges for THIS series at creation.
    """
    if len(label_values) != len(self._label_names):
      raise ValueError('Expected {} label value(s) {}; got {}.'.format(
          len(self._label_names), self._label_names, label_values))
    explicit = tuple(float(b) for b in bounds) if bounds is not None \
        else None
    if explicit is not None and not self._supports_bounds:
      raise ValueError('Per-series bounds are only supported on histogram '
                       'families.')
    key = tuple(str(v) for v in label_values)
    with self._lock:
      child = self._series.get(key)
      if child is None:
        child = self._make(explicit) if self._supports_bounds \
            else self._make()
        self._series[key] = child
        if self._supports_bounds:
          # Record the RESOLVED edges, so re-requesting with explicit
          # bounds equal to the family default is consistent, not an
          # error.
          self._series_bounds[key] = tuple(child._bounds)  # noqa: SLF001
      elif explicit is not None and \
          self._series_bounds.get(key) != explicit:
        raise ValueError(
            'Series {!r} already created with bounds={!r}; requested '
            '{!r}.'.format(key, self._series_bounds.get(key), explicit))
      return child

  def items(self) -> List[Tuple[Tuple[str, ...], object]]:
    with self._lock:
      return list(self._series.items())

  def reset(self) -> None:
    with self._lock:
      for child in self._series.values():
        child.reset()


class TelemetryRegistry:
  """Name -> instrument map with typed get-or-create registration.

  Re-registering a name with the same kind (and, when given, the same
  bounds/labels) returns the existing instrument, so call sites need no
  module-level caching discipline. Re-registering with a different kind,
  different explicit histogram bounds, or different label names is a bug
  and raises — a milliseconds histogram silently landing in a seconds
  bucket layout would corrupt every percentile with no error. Omitting
  ``bounds`` on a later lookup means "whatever it was registered with".
  """

  def __init__(self):
    self._lock = threading.Lock()
    # name -> (kind, config dict, instrument)
    self._instruments: Dict[str, Tuple[str, Dict[str, object], object]] = {}

  def _get_or_create(self, name: str, kind: str, make,
                     requested: Optional[Dict[str, object]] = None,
                     config: Optional[Dict[str, object]] = None):
    """``config`` is stored at creation; ``requested`` holds this call's
    explicit constraints (None values mean unconstrained) and must match
    the stored config on a re-registration."""
    with self._lock:
      existing = self._instruments.get(name)
      if existing is not None:
        existing_kind, existing_config, instrument = existing
        if existing_kind != kind:
          raise ValueError(
              'Telemetry name {!r} already registered as {} (requested {}).'
              .format(name, existing_kind, kind))
        for key, value in (requested or {}).items():
          if value is not None and existing_config.get(key) != value:
            raise ValueError(
                'Telemetry name {!r} already registered with {}={!r}; '
                'requested {!r}.'.format(name, key,
                                         existing_config.get(key), value))
        return instrument
      instrument = make()
      self._instruments[name] = (kind, dict(config or {}), instrument)
      return instrument

  def counter(self, name: str) -> Counter:
    return self._get_or_create(name, 'counter', Counter)

  def gauge(self, name: str) -> Gauge:
    return self._get_or_create(name, 'gauge', Gauge)

  def histogram(self, name: str,
                bounds: Optional[Sequence[float]] = None) -> Histogram:
    explicit = tuple(bounds) if bounds is not None else None
    resolved = explicit if explicit is not None else DEFAULT_SECONDS_BUCKETS
    return self._get_or_create(
        name, 'histogram', lambda: Histogram(resolved),
        requested={'bounds': explicit}, config={'bounds': resolved})

  def counter_family(self, name: str,
                     label_names: Sequence[str]) -> _Family:
    labels = tuple(label_names)
    return self._get_or_create(
        name, 'counter_family', lambda: _Family(Counter, labels),
        requested={'labels': labels}, config={'labels': labels})

  def gauge_family(self, name: str, label_names: Sequence[str]) -> _Family:
    labels = tuple(label_names)
    return self._get_or_create(
        name, 'gauge_family', lambda: _Family(Gauge, labels),
        requested={'labels': labels}, config={'labels': labels})

  def histogram_family(self, name: str, label_names: Sequence[str],
                       bounds: Optional[Sequence[float]] = None) -> _Family:
    """``bounds`` is the family DEFAULT; individual series may override
    it at creation via ``family.series(..., bounds=...)`` (per-series
    SLO-resolution edges without re-bucketing sibling series)."""
    labels = tuple(label_names)
    explicit = tuple(bounds) if bounds is not None else None
    resolved = explicit if explicit is not None else DEFAULT_SECONDS_BUCKETS
    return self._get_or_create(
        name, 'histogram_family',
        lambda: _Family(
            lambda series_bounds: Histogram(
                series_bounds if series_bounds is not None else resolved),
            labels, supports_bounds=True),
        requested={'labels': labels, 'bounds': explicit},
        config={'labels': labels, 'bounds': resolved})

  # -- export ----------------------------------------------------------------

  def _walk(self):
    """[(flat_name, kind, instrument)] with labels joined as path segments."""
    with self._lock:
      items = list(self._instruments.items())
    out = []
    for name, (kind, _, instrument) in items:
      if kind.endswith('_family'):
        base_kind = kind[:-len('_family')]
        for label_values, child in instrument.items():
          out.append(('/'.join((name,) + label_values), base_kind, child))
      else:
        out.append((name, kind, instrument))
    return out

  def scalars(self) -> Dict[str, float]:
    """Flat scalar view for the TensorBoard writer.

    Counters/gauges export their value under their own tag; histograms
    export ``<tag>/{count,mean,p50,p95,p99,max}`` (only once non-empty,
    so TensorBoard is not littered with dead series).
    """
    out: Dict[str, float] = {}
    for name, kind, instrument in self._walk():
      if kind == 'histogram':
        summary = instrument.summary()
        if summary.get('count'):
          for stat, value in summary.items():
            out['{}/{}'.format(name, stat)] = float(value)
      else:
        out[name] = float(instrument.value)
    return out

  def snapshot(self) -> Dict[str, Dict[str, object]]:
    """Structured state: {'counters': {...}, 'gauges': {...},
    'histograms': {name: full bucket state}} — the jsonl export form."""
    snap: Dict[str, Dict[str, object]] = {
        'counters': {}, 'gauges': {}, 'histograms': {},
    }
    for name, kind, instrument in self._walk():
      if kind == 'counter':
        snap['counters'][name] = instrument.value
      elif kind == 'gauge':
        snap['gauges'][name] = instrument.value
      else:
        snap['histograms'][name] = instrument.state()
    return snap

  def reset(self) -> None:
    """Zeroes every instrument (registrations survive). Test hook."""
    with self._lock:
      items = list(self._instruments.values())
    for _, _, instrument in items:
      instrument.reset()


def snapshot_delta(old: Dict[str, Dict[str, object]],
                   new: Dict[str, Dict[str, object]]
                   ) -> Dict[str, Dict[str, object]]:
  """Windowed difference of two ``TelemetryRegistry.snapshot`` results.

  Counters and histogram counts subtract (series absent from ``old``
  count from zero); gauges pass through ``new``'s instantaneous value.
  """
  delta: Dict[str, Dict[str, object]] = {
      'counters': {}, 'gauges': dict(new.get('gauges', {})),
      'histograms': {},
  }
  old_counters = old.get('counters', {})
  for name, value in new.get('counters', {}).items():
    delta['counters'][name] = value - old_counters.get(name, 0.0)
  old_histograms = old.get('histograms', {})
  for name, state in new.get('histograms', {}).items():
    prev = old_histograms.get(name)
    if prev is None or prev.get('bounds') != state.get('bounds'):
      delta['histograms'][name] = dict(state)
      continue
    delta['histograms'][name] = {
        'bounds': list(state['bounds']),
        'counts': [n - o for n, o in zip(state['counts'], prev['counts'])],
        'count': state['count'] - prev['count'],
        'sum': state['sum'] - prev['sum'],
        'min': state['min'],
        'max': state['max'],
    }
  return delta


_REGISTRY = TelemetryRegistry()
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> TelemetryRegistry:
  """The process-wide default registry every built-in layer reports to."""
  return _REGISTRY


def set_registry(registry: TelemetryRegistry) -> TelemetryRegistry:
  """Swaps the process default (test isolation); returns the previous one."""
  global _REGISTRY
  with _REGISTRY_LOCK:
    previous = _REGISTRY
    _REGISTRY = registry
  return previous
