"""Roofline observatory: live MFU ledger and per-op-family attribution.

The forensics top-op tables rank op families by measured milliseconds
only — enough to say WHERE the step time goes, not whether a family is
compute- or memory-bound, nor how much a hand-fused kernel could
recover. This module closes that gap with the standard roofline model
(arithmetic intensity = flops/bytes vs the device ridge point =
peak_flops/peak_bandwidth):

  * ``build_record`` joins a capture's measured op-family ms (from
    `utils/xplane`) with the per-family FLOPs/HBM-bytes cost table
    parsed from the SAME program's post-opt HLO
    (`parallel/hlo_analysis.op_cost_table`) and emits a
    ``t2r.roofline.v1`` record: ranked families with intensity, bound
    class (compute / memory / ragged), % of device peak, and roofline
    headroom — measured ms minus the roofline-bound ms, i.e. the
    predicted win from fusing that family to the roofline.
  * ``publish_perf_gauges`` turns MFU from a once-per-bench number into
    a LIVE signal: the trainer calls it every log window and the
    ``perf/mfu`` / ``perf/hbm_bw_util`` gauges feed TensorBoard,
    telemetry.jsonl, and the watchdog's ``mfu_regression`` anomaly.
  * ``PEAKS`` is the small per-``device_kind`` peaks table (dense bf16
    FLOP/s + HBM GB/s). Unknown kinds — CPU above all — degrade to
    ``mode='intensity-only'``: intensities still rank and classify by
    ratio ordering, but % peak / headroom / MFU are withheld rather
    than fabricated from a made-up peak.

Everything here is stdlib + `parallel/hlo_analysis` (pure re/hashlib) —
importable jax-free, so ``doctor`` and ``bin/check_roofline_doctor``
can render roofline verdicts offline.

Accounting invariant: the families table always sum-reconciles with the
program totals — cost-table families that no measured event joined
(fused away, renamed by the backend, or a host-executor capture whose
event names never match) aggregate into one ``unattributed`` pseudo-row
(ms=None), so ``sum(row.flops) == flops_per_step`` by construction and
a reader can SEE how much of the program the measurement explained.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

ROOFLINE_SCHEMA = 't2r.roofline.v1'

# Registry gauge names the trainer publishes every log window.
MFU_GAUGE = 'perf/mfu'
HBM_BW_GAUGE = 'perf/hbm_bw_util'

UNATTRIBUTED = 'unattributed'

# (device_kind substring, peak dense bf16 FLOP/s, peak HBM GB/s).
# Matched case-insensitively, first hit wins — keep more specific
# substrings (v5p) ahead of shorter ones that would shadow them.
# Sources: public TPU spec sheets; these are DENSE peaks, so MFU here is
# comparable with the training-at-scale literature's convention.
PEAKS: Tuple[Tuple[str, float, float], ...] = (
    ('v6e', 918e12, 1640.0),
    ('trillium', 918e12, 1640.0),
    ('v5p', 459e12, 2765.0),
    ('v5 lite', 197e12, 819.0),
    ('v5litepod', 197e12, 819.0),
    ('v5e', 197e12, 819.0),
    ('v4', 275e12, 1228.0),
    ('v3', 123e12, 900.0),
    ('v2', 46e12, 700.0),
)

# Bound-class hysteresis band around the ridge point: families within
# +/-25% of the ridge are 'ragged' — close enough that fusing them
# flips which wall they hit, so neither label would be honest.
_RAGGED_BAND = 0.25

_FAMILY_SUFFIX_RE = re.compile(r'\.\d+$')


def normalize_family(name: str) -> str:
  """Canonical op-family key used on BOTH sides of the ms<->cost join.

  Measured names (xplane event metadata, host-executor thunk names) and
  HLO instruction names differ in '%' prefix and '.N' uniquifier
  suffixes; fold both to ``'%' + bare name`` so they join.
  """
  bare = str(name).split(' = ')[0].strip().lstrip('%')
  return '%' + _FAMILY_SUFFIX_RE.sub('', bare)


def device_peaks(device_kind: str) -> Optional[Tuple[float, float]]:
  """(peak FLOP/s, peak HBM bytes/s) for a device kind, else None.

  None — the CPU case — selects intensity-only mode everywhere
  downstream: no entry is ever guessed.
  """
  kind = str(device_kind or '').lower()
  for substr, flops, gbps in PEAKS:
    if substr in kind:
      return flops, gbps * 1e9
  return None


def ridge_intensity(peak_flops: float, peak_bw: float) -> float:
  """Flops/byte at which a kernel leaves the bandwidth roof."""
  return peak_flops / peak_bw if peak_bw else 0.0


def classify_bound(intensity: Optional[float], ridge: float) -> Optional[str]:
  """'compute' | 'memory' | 'ragged' against a device ridge point."""
  if intensity is None or ridge <= 0:
    return None
  if intensity > ridge * (1.0 + _RAGGED_BAND):
    return 'compute'
  if intensity < ridge * (1.0 - _RAGGED_BAND):
    return 'memory'
  return 'ragged'


def mfu(flops_per_step: float, step_time_s: float, peak_flops: float,
        n_chips: int = 1) -> float:
  """Model-flops utilization: achieved FLOP/s over the installed peak."""
  if step_time_s <= 0 or peak_flops <= 0 or n_chips <= 0:
    return 0.0
  return flops_per_step / step_time_s / (peak_flops * n_chips)


def build_record(families: Sequence[Tuple[str, float]],
                 cost_table: Dict[str, Dict[str, float]],
                 device_kind: str,
                 *,
                 step: Optional[int] = None,
                 step_time_s: Optional[float] = None,
                 totals: Optional[Dict[str, float]] = None,
                 cost_source: str = 'hlo_parse',
                 top_k: int = 15) -> Dict[str, object]:
  """The ``t2r.roofline.v1`` record for one forensics capture.

  Args:
    families: ``[(name, ms_per_step)]`` measured device attribution
      (``utils/xplane.op_families`` order — or the host-executor
      fallback; names are normalized before joining).
    cost_table: ``parallel/hlo_analysis.op_cost_table(hlo_text)`` of
      the SAME program the capture timed.
    device_kind: ``signals.host_identity()['device_kind']``.
    step: trainer step the capture closed at.
    step_time_s: measured wall seconds per step — enables MFU and the
      bandwidth-utilization headline when peaks are known.
    totals: program totals ``{'flops','bytes',...}`` from the shared
      cost helper; defaults to summing ``cost_table`` (the two agree
      exactly when both come from the HLO parse — passing the
      ``cost_analysis()`` totals here keeps the record anchored to the
      backend's own count while the table explains it).
    cost_source: provenance label ('cost_analysis' | 'hlo_parse').
    top_k: measured rows kept (the tail folds into ``unattributed``).

  Never raises on ragged input — unjoined measurements get cost zeros,
  unjoined costs fold into ``unattributed`` — so forensics can call it
  inside the trainer's capture path.
  """
  peaks = device_peaks(device_kind)
  mode = 'roofline' if peaks else 'intensity-only'
  table_totals = {'flops': 0.0, 'bytes': 0.0}
  for row in cost_table.values():
    table_totals['flops'] += float(row.get('flops', 0.0))
    table_totals['bytes'] += float(row.get('bytes', 0.0))
  if totals is None:
    totals = table_totals
  flops_per_step = float(totals.get('flops', 0.0))
  bytes_per_step = float(totals.get('bytes', 0.0))

  costs = {}
  for name, row in cost_table.items():
    key = normalize_family(name)
    agg = costs.setdefault(key, {'flops': 0.0, 'bytes': 0.0})
    agg['flops'] += float(row.get('flops', 0.0))
    agg['bytes'] += float(row.get('bytes', 0.0))

  peak_flops, peak_bw = peaks if peaks else (0.0, 0.0)
  ridge = ridge_intensity(peak_flops, peak_bw) if peaks else 0.0

  def _row(family, ms, flops, nbytes):
    intensity = (flops / nbytes) if nbytes else None
    row = {
        'family': family,
        'ms': None if ms is None else round(float(ms), 6),
        'flops': flops,
        'bytes': nbytes,
        'intensity': None if intensity is None else round(intensity, 4),
        'bound': classify_bound(intensity, ridge) if peaks else None,
        'pct_peak': None,
        'roofline_ms': None,
        'headroom_ms': None,
    }
    if peaks:
      roofline_s = max(flops / peak_flops if peak_flops else 0.0,
                       nbytes / peak_bw if peak_bw else 0.0)
      row['roofline_ms'] = round(roofline_s * 1e3, 6)
      if ms:
        row['headroom_ms'] = round(float(ms) - roofline_s * 1e3, 6)
        achieved = flops / (float(ms) / 1e3) if ms else 0.0
        row['pct_peak'] = round(achieved / peak_flops, 6) if peak_flops else None
    return row

  # Aggregate measured ms BY family first: a capture times each
  # uniquified instruction (%dot.1, %dot.5, ...) separately, and a
  # per-event join would hand every event the whole family's cost —
  # double counting that breaks the sum-reconciliation invariant.
  measured: Dict[str, float] = {}
  for name, ms in families:
    key = normalize_family(name)
    measured[key] = measured.get(key, 0.0) + float(ms)

  rows: List[Dict[str, object]] = []
  matched = set()
  ranked = sorted(measured.items(), key=lambda kv: -kv[1])
  folded_ms = 0.0
  for key, ms in ranked:
    cost = costs.get(key)
    if len(rows) >= top_k:
      # Beyond-top_k tail: its ms AND its cost both fold into the
      # unattributed row (marking it matched without moving the cost
      # would silently drop flops from the table).
      folded_ms += ms
      continue
    if cost is not None:
      matched.add(key)
      rows.append(_row(key, ms, cost['flops'], cost['bytes']))
    else:
      rows.append(_row(key, ms, 0.0, 0.0))

  # Everything the measurement didn't explain — costs with no event
  # (plus beyond-top_k tails) — lands in ONE reconciling pseudo-row.
  rest_flops = sum(c['flops'] for k, c in costs.items() if k not in matched)
  rest_bytes = sum(c['bytes'] for k, c in costs.items() if k not in matched)
  # Anchor the reconciliation to the record's own totals: when `totals`
  # came from cost_analysis() the parse-vs-backend delta is real program
  # cost the table must not drop.
  rest_flops += max(flops_per_step - table_totals['flops'], 0.0)
  rest_bytes += max(bytes_per_step - table_totals['bytes'], 0.0)
  if rest_flops or rest_bytes or folded_ms:
    rows.append(_row(UNATTRIBUTED, folded_ms if folded_ms else None,
                     rest_flops, rest_bytes))

  gating = None
  best_headroom = 0.0
  for row in rows:
    if row['family'] == UNATTRIBUTED or row['bound'] != 'memory':
      continue
    headroom = row['headroom_ms'] if row['headroom_ms'] is not None else 0.0
    score = headroom if headroom > 0 else (row['ms'] or 0.0) * 1e-6
    if gating is None or score > best_headroom:
      gating = row['family']
      best_headroom = score

  record = {
      'schema': ROOFLINE_SCHEMA,
      'step': step,
      'device_kind': device_kind,
      'mode': mode,
      'cost_source': cost_source,
      'flops_per_step': flops_per_step,
      'bytes_per_step': bytes_per_step,
      'arithmetic_intensity': round(flops_per_step / bytes_per_step, 4)
                              if bytes_per_step else None,
      'peak_flops': peak_flops if peaks else None,
      'peak_hbm_gbps': (peak_bw / 1e9) if peaks else None,
      'ridge_intensity': round(ridge, 4) if peaks else None,
      'step_time_s': step_time_s,
      'mfu': None,
      'hbm_bw_util': None,
      'families': rows,
      'gating_memory_bound_family': gating,
  }
  if peaks and step_time_s:
    record['mfu'] = round(mfu(flops_per_step, step_time_s, peak_flops), 6)
    record['hbm_bw_util'] = round(
        bytes_per_step / step_time_s / peak_bw, 6) if peak_bw else None
  return record


def static_gating_family(cost_table: Dict[str, Dict[str, float]],
                         device_kind: str) -> Optional[str]:
  """Memory-bound family with the largest roofline-bound ms — from the
  cost table ALONE, no measurement. What bench.py publishes before any
  capture exists: the family whose best-case (roofline) time is the
  biggest memory-bound share of the step, i.e. where a fused kernel has
  the most predicted room. None when the device kind has no peaks entry
  (intensity alone cannot place the ridge) or nothing is memory-bound.
  """
  peaks = device_peaks(device_kind)
  if not peaks:
    return None
  peak_flops, peak_bw = peaks
  ridge = ridge_intensity(peak_flops, peak_bw)
  best = None
  best_s = 0.0
  for name, row in cost_table.items():
    flops = float(row.get('flops', 0.0))
    nbytes = float(row.get('bytes', 0.0))
    intensity = (flops / nbytes) if nbytes else None
    if classify_bound(intensity, ridge) != 'memory':
      continue
    bound_s = max(flops / peak_flops if peak_flops else 0.0,
                  nbytes / peak_bw if peak_bw else 0.0)
    if bound_s > best_s:
      best = normalize_family(name)
      best_s = bound_s
  return best


def publish_perf_gauges(registry, flops_per_step: float,
                        bytes_per_step: float, step_time_s: float,
                        device_kind: str,
                        n_chips: int = 1) -> Optional[Tuple[float, float]]:
  """Set ``perf/mfu`` + ``perf/hbm_bw_util`` gauges for one log window.

  Returns ``(mfu, hbm_bw_util)`` when the device kind has a peaks entry,
  else None WITHOUT touching the gauges — a zero would read as "0% MFU"
  on hosts where the truthful statement is "no peak known" (CPU), and
  the watchdog treats an absent/non-positive gauge as not-applicable.
  """
  peaks = device_peaks(device_kind)
  if not peaks or step_time_s <= 0:
    return None
  peak_flops, peak_bw = peaks
  value = mfu(flops_per_step, step_time_s, peak_flops, n_chips=1)
  bw_util = (bytes_per_step / step_time_s / peak_bw) if peak_bw else 0.0
  registry.gauge(MFU_GAUGE).set(value)
  registry.gauge(HBM_BW_GAUGE).set(bw_util)
  return value, bw_util


def telemetry_payload(record: Dict[str, object],
                      top_k: int = 5) -> Dict[str, object]:
  """Compact ``kind='roofline'`` telemetry.jsonl payload from a record.

  Full records live in the forensics report; the jsonl line keeps the
  headline + the top families so ``t2r_telemetry tail``/``summarize``
  and doctor stay useful without opening report files.
  """
  families = [
      {'family': row.get('family'), 'ms': row.get('ms'),
       'intensity': row.get('intensity'), 'bound': row.get('bound'),
       'headroom_ms': row.get('headroom_ms')}
      for row in list(record.get('families') or [])[:top_k]
  ]
  return {
      'schema': record.get('schema', ROOFLINE_SCHEMA),
      'mode': record.get('mode'),
      'device_kind': record.get('device_kind'),
      'mfu': record.get('mfu'),
      'hbm_bw_util': record.get('hbm_bw_util'),
      'flops_per_step': record.get('flops_per_step'),
      'bytes_per_step': record.get('bytes_per_step'),
      'arithmetic_intensity': record.get('arithmetic_intensity'),
      'gating_memory_bound_family': record.get('gating_memory_bound_family'),
      'families': families,
  }


# Keys bench.py publishes for the roofline axis (BENCH_r06+), self-
# checked like E2E_WIRE_BENCH_KEYS; -1/'' sentinels when an axis fails.
ROOFLINE_BENCH_KEYS = (
    'flops_per_step',
    'hbm_bytes_per_step',
    'arithmetic_intensity',
    'flops_source',
    'roofline_mode',
    'roofline_bound',
    'roofline_ridge_intensity',
    'roofline_gating_family',
    'mfu',
    'hbm_bw_util',
)
