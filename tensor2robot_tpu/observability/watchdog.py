"""Anomaly detection over the telemetry registry: symptom -> trigger.

PR 3 made the trainer *record* step times, goodput splits, and
reliability counters; this watchdog is the component that *reads* them
at the trainer's log cadence and decides "this run just got slower /
hungrier / recompile-happy" against its own rolling baseline — the
Podracer (arXiv:2104.06272) posture of treating utilization regressions
as monitored failures, not graphs someone may eyeball later. Detections
(docs/observability.md):

  * ``step_time_regression`` — the current log window's mean step time
    exceeds ``regression_ratio`` x the rolling-median baseline of recent
    healthy windows. Anomalous windows are NOT folded into the baseline,
    so a sustained slowdown keeps firing instead of normalizing itself.
  * ``goodput_drop`` — the window's productive fraction fell more than
    ``goodput_drop`` below the baseline median productive fraction.
  * ``recompile`` — ``recompiles/train_step`` (the trainer's jit-cache
    size) grew past its post-warmup value, or the device feed reports
    more than one distinct batch shape signature: the shape-stability
    invariant of data/device_feed.py, asserted instead of commented.
  * ``hbm_growth`` — a device's ``memory/device_bytes_in_use`` gauge
    grew monotonically for ``hbm_growth_windows`` consecutive windows by
    more than ``hbm_growth_bytes`` total: the leak signature (a stable
    training step reuses buffers; a watermark that climbs every window
    is retained state, not noise).
  * ``mfu_regression`` — the live ``perf/mfu`` gauge (published by the
    trainer every log window from the shared cost model,
    observability/roofline.py) fell below ``mfu_regression_ratio`` x
    its rolling-median healthy baseline. Same healthy-windows-only
    folding as step time — a sustained utilization collapse keeps
    firing. Hosts with no peaks entry (CPU) never publish the gauge, so
    the check is trivially quiet there instead of noisily wrong.
  * ``heartbeat_stale`` — out-of-process only (``check_heartbeat``):
    the heartbeat file's age exceeds ``heartbeat_stale_secs``. In-process
    the trainer loop IS the heartbeat writer, so staleness is checked by
    ``t2r_telemetry doctor`` / external monitors, not ``observe()``.

Three further kinds — ``pipeline_stall``, ``worker_starvation``, and
``transfer_regression`` — are detected by the pipeline X-ray
(observability/pipeline_xray.py) over the ``pipeline/<stage>/...``
counters and flow through the same ``watchdog/anomalies`` counter
family, telemetry ``anomaly`` records, and capture-request loop. Two
FLEET kinds — ``straggler`` (one host's step time >= 2x the fleet
median) and ``host_dead`` (one host's heartbeat stale while others
advance) — are detected by ``observability/fleet.py``'s FleetWatchdog
over the per-host heartbeat streams and flow through the same loop.

The watchdog holds no threads and does no I/O: ``observe()`` is a pure
in-memory pass the trainer calls at its log cadence, and every duration
it consumes comes from ``time.perf_counter`` windows upstream — the
monotonic-clock discipline tests/test_no_wallclock.py enforces.
"""

from __future__ import annotations

import collections
import statistics
from typing import Deque, Dict, List, Optional

from tensor2robot_tpu.observability import registry as registry_lib
# Writer of the MFU gauge this watchdog reads (stdlib-only import).
from tensor2robot_tpu.observability.roofline import MFU_GAUGE

__all__ = ['Anomaly', 'Watchdog', 'WatchdogConfig',
           'ANOMALY_COUNTER', 'RECOMPILE_GAUGE', 'FEED_SHAPES_GAUGE',
           'DEVICE_BYTES_GAUGE', 'MFU_GAUGE', 'MFU_REGRESSION',
           'STRAGGLER', 'HOST_DEAD', 'check_heartbeat']

# Metric names this watchdog reads (writers: trainer + data/device_feed +
# observability/signals.py) and writes (the anomaly counter family).
ANOMALY_COUNTER = 'watchdog/anomalies'
RECOMPILE_GAUGE = 'recompiles/train_step'
FEED_SHAPES_GAUGE = 'data/feed_shape_signatures'
DEVICE_BYTES_GAUGE = 'memory/device_bytes_in_use'

STEP_TIME_REGRESSION = 'step_time_regression'
GOODPUT_DROP = 'goodput_drop'
RECOMPILE = 'recompile'
HBM_GROWTH = 'hbm_growth'
MFU_REGRESSION = 'mfu_regression'
HEARTBEAT_STALE = 'heartbeat_stale'
# Fleet kinds, detected by observability/fleet.py (FleetWatchdog):
STRAGGLER = 'straggler'
HOST_DEAD = 'host_dead'


class Anomaly:
  """One detection: what fired, at which step, with the evidence."""

  __slots__ = ('kind', 'step', 'message', 'detail')

  def __init__(self, kind: str, step: int, message: str,
               detail: Optional[Dict[str, object]] = None):
    self.kind = kind
    self.step = int(step)
    self.message = message
    self.detail = dict(detail or {})

  def to_record(self) -> Dict[str, object]:
    """The telemetry.jsonl / forensics-report payload form."""
    return {'kind': self.kind, 'step': self.step, 'message': self.message,
            'detail': self.detail}

  def __repr__(self):
    return 'Anomaly({}, step={}, {!r})'.format(self.kind, self.step,
                                               self.message)


class WatchdogConfig:
  """Thresholds; defaults tuned to fire on sustained 2x regressions, not
  single-window jitter (shared-chip variance runs a few percent,
  docs/performance.md)."""

  def __init__(self,
               regression_ratio: float = 1.8,
               min_baseline_windows: int = 3,
               baseline_windows: int = 16,
               goodput_drop: float = 0.25,
               hbm_growth_windows: int = 4,
               hbm_growth_bytes: float = 64 * 2**20,
               recompile_warmup_windows: int = 1,
               heartbeat_stale_secs: float = 300.0,
               mfu_regression_ratio: float = 0.75):
    if regression_ratio <= 1.0:
      raise ValueError('regression_ratio must exceed 1.0; got {}.'.format(
          regression_ratio))
    if not 0.0 < goodput_drop < 1.0:
      raise ValueError('goodput_drop must be a fraction in (0, 1); got {}.'
                       .format(goodput_drop))
    if not 0.0 < mfu_regression_ratio < 1.0:
      raise ValueError('mfu_regression_ratio must be a fraction in (0, 1); '
                       'got {}.'.format(mfu_regression_ratio))
    self.regression_ratio = float(regression_ratio)
    self.min_baseline_windows = int(min_baseline_windows)
    self.baseline_windows = int(baseline_windows)
    self.goodput_drop = float(goodput_drop)
    self.hbm_growth_windows = int(hbm_growth_windows)
    self.hbm_growth_bytes = float(hbm_growth_bytes)
    self.recompile_warmup_windows = int(recompile_warmup_windows)
    self.heartbeat_stale_secs = float(heartbeat_stale_secs)
    self.mfu_regression_ratio = float(mfu_regression_ratio)


class Watchdog:
  """Rolling-baseline anomaly detector over one training run."""

  def __init__(self, config: Optional[WatchdogConfig] = None,
               registry: Optional[registry_lib.TelemetryRegistry] = None):
    self.config = config or WatchdogConfig()
    self._registry = registry
    self._step_times: Deque[float] = collections.deque(
        maxlen=self.config.baseline_windows)
    self._productive: Deque[float] = collections.deque(
        maxlen=self.config.baseline_windows)
    self._last_goodput_seconds: Optional[Dict[str, float]] = None
    self._mfu: Deque[float] = collections.deque(
        maxlen=self.config.baseline_windows)
    self._windows_seen = 0
    self._recompile_baseline: Optional[float] = None
    self._shapes_reported = 1.0  # highest signature count already reported
    # device label -> consecutive-growth count and last watermark.
    self._hbm_last: Dict[str, float] = {}
    self._hbm_streak: Dict[str, int] = {}
    self._hbm_streak_bytes: Dict[str, float] = {}

  @property
  def registry(self) -> registry_lib.TelemetryRegistry:
    return self._registry or registry_lib.get_registry()

  # -- in-process detections -------------------------------------------------

  def observe(self, step: int, step_time_s: Optional[float],
              goodput_seconds: Optional[Dict[str, float]] = None
              ) -> List[Anomaly]:
    """One log-cadence pass; returns (and counts) fired anomalies.

    ``step_time_s`` is the window's mean seconds/step; ``goodput_seconds``
    the tracker's CUMULATIVE seconds (the watchdog differences
    consecutive calls itself, so callers just pass ``tracker.seconds()``).
    """
    anomalies: List[Anomaly] = []
    self._windows_seen += 1
    if step_time_s is not None:
      anomalies.extend(self._observe_step_time(step, float(step_time_s)))
    if goodput_seconds is not None:
      anomalies.extend(self._observe_goodput(step, dict(goodput_seconds)))
    anomalies.extend(self._observe_recompiles(step))
    anomalies.extend(self._observe_hbm(step))
    anomalies.extend(self._observe_mfu(step))
    if anomalies:
      family = self.registry.counter_family(ANOMALY_COUNTER, ('kind',))
      for anomaly in anomalies:
        family.series(anomaly.kind).inc()
    return anomalies

  def _observe_step_time(self, step: int, step_time_s: float
                         ) -> List[Anomaly]:
    baseline = (statistics.median(self._step_times)
                if len(self._step_times) >= self.config.min_baseline_windows
                else None)
    if baseline is not None and baseline > 0.0 and \
        step_time_s > self.config.regression_ratio * baseline:
      return [Anomaly(
          STEP_TIME_REGRESSION, step,
          'step time {:.1f} ms/step is {:.1f}x the rolling baseline '
          '{:.1f} ms/step'.format(step_time_s * 1e3,
                                  step_time_s / baseline, baseline * 1e3),
          {'step_time_s': step_time_s, 'baseline_s': baseline,
           'ratio': step_time_s / baseline})]
    # Healthy window: fold into the baseline (anomalous ones stay out so a
    # sustained regression cannot normalize itself away).
    self._step_times.append(step_time_s)
    return []

  def _observe_goodput(self, step: int, seconds: Dict[str, float]
                       ) -> List[Anomaly]:
    last = self._last_goodput_seconds
    self._last_goodput_seconds = seconds
    if last is None:
      return []
    window = {k: seconds.get(k, 0.0) - last.get(k, 0.0) for k in seconds}
    total = sum(window.values())
    if total <= 0.0:
      return []
    productive = window.get('productive', 0.0) / total
    baseline = (statistics.median(self._productive)
                if len(self._productive) >= self.config.min_baseline_windows
                else None)
    if baseline is not None and \
        productive < baseline - self.config.goodput_drop:
      lost = {k: v / total for k, v in window.items()
              if k != 'productive' and v > 0.0}
      top = max(lost, key=lost.get) if lost else 'unknown'
      return [Anomaly(
          GOODPUT_DROP, step,
          'productive fraction {:.0%} fell below baseline {:.0%} - {:.0%}; '
          'largest loss: {} ({:.0%})'.format(
              productive, baseline, self.config.goodput_drop, top,
              lost.get(top, 0.0)),
          {'productive_fraction': productive, 'baseline_fraction': baseline,
           'window_fractions': {k: v / total for k, v in window.items()}})]
    self._productive.append(productive)
    return []

  def _observe_recompiles(self, step: int) -> List[Anomaly]:
    anomalies = []
    # The shape-stability invariant is independent of the cache-size
    # probe (which is absent on some jax versions): check it even while
    # the recompile gauge is still 0. Latched like the cache-size path —
    # one stale signature must not re-fire every window for the rest of
    # the run (burning the capture budget on a long-past incident).
    shapes = self.registry.gauge(FEED_SHAPES_GAUGE).value
    if shapes > self._shapes_reported and shapes > 1.0:
      anomalies.append(Anomaly(
          RECOMPILE, step,
          'device feed emitted {:g} distinct batch shape signatures; the '
          'dense post-unpack batch must be shape-stable'.format(shapes),
          {'shape_signatures': shapes}))
      self._shapes_reported = shapes
    gauge = self.registry.gauge(RECOMPILE_GAUGE)
    value = gauge.value
    if value <= 0.0:
      return anomalies  # trainer has not sampled its jit cache yet
    if self._windows_seen <= self.config.recompile_warmup_windows or \
        self._recompile_baseline is None:
      # The first compile lands during warmup; lock the baseline there.
      self._recompile_baseline = value
      return anomalies
    if value > self._recompile_baseline:
      anomalies.append(Anomaly(
          RECOMPILE, step,
          'train step recompiled: jit cache grew {:g} -> {:g} (shape-'
          'unstable batch reached the compiled step)'.format(
              self._recompile_baseline, value),
          {'cache_size': value, 'baseline': self._recompile_baseline}))
      self._recompile_baseline = value  # report each growth once
    return anomalies

  def _observe_hbm(self, step: int) -> List[Anomaly]:
    family = self.registry.gauge_family(DEVICE_BYTES_GAUGE, ('device',))
    anomalies = []
    for labels, gauge in family.items():
      device = labels[0]
      value = gauge.value
      last = self._hbm_last.get(device)
      self._hbm_last[device] = value
      if last is None or value <= last:
        self._hbm_streak[device] = 0
        self._hbm_streak_bytes[device] = 0.0
        continue
      self._hbm_streak[device] = self._hbm_streak.get(device, 0) + 1
      self._hbm_streak_bytes[device] = \
          self._hbm_streak_bytes.get(device, 0.0) + (value - last)
      if self._hbm_streak[device] >= self.config.hbm_growth_windows and \
          self._hbm_streak_bytes[device] >= self.config.hbm_growth_bytes:
        anomalies.append(Anomaly(
            HBM_GROWTH, step,
            'device {} HBM in use grew {} windows in a row (+{:.1f} MiB, '
            'now {:.1f} MiB): leak signature'.format(
                device, self._hbm_streak[device],
                self._hbm_streak_bytes[device] / 2**20, value / 2**20),
            {'device': device, 'windows': self._hbm_streak[device],
             'growth_bytes': self._hbm_streak_bytes[device],
             'bytes_in_use': value}))
        # Re-arm: keep watching, but don't fire every subsequent window.
        self._hbm_streak[device] = 0
        self._hbm_streak_bytes[device] = 0.0
    return anomalies

  def _observe_mfu(self, step: int) -> List[Anomaly]:
    # Published by the trainer from the shared cost model only on hosts
    # with a device-peaks entry; <= 0 means "not applicable", not "0%
    # utilized" — skip, never baseline it.
    value = self.registry.gauge(MFU_GAUGE).value
    if value <= 0.0:
      return []
    baseline = (statistics.median(self._mfu)
                if len(self._mfu) >= self.config.min_baseline_windows
                else None)
    if baseline is not None and baseline > 0.0 and \
        value < self.config.mfu_regression_ratio * baseline:
      return [Anomaly(
          MFU_REGRESSION, step,
          'MFU {:.1%} fell below {:.0%} of the rolling baseline {:.1%}: '
          'the device step is doing the same flops slower'.format(
              value, self.config.mfu_regression_ratio, baseline),
          {'mfu': value, 'baseline_mfu': baseline,
           'ratio': value / baseline})]
    # Healthy window: fold in (anomalous ones stay out, same rationale
    # as step time).
    self._mfu.append(value)
    return []

  # -- out-of-process detections ---------------------------------------------

  def check_heartbeat(self, heartbeat: Optional[Dict[str, object]],
                      now: float) -> List[Anomaly]:
    """Staleness of a run's heartbeat.json, for doctor/external monitors.

    ``now`` must come from the same clock as the heartbeat's ``time``
    field (wall clock — heartbeats cross process boundaries, so the
    monotonic discipline cannot apply; the comparison is best-effort by
    nature and documented as such).
    """
    if heartbeat is None:
      return [Anomaly(HEARTBEAT_STALE, -1,
                      'no heartbeat.json: the run never started its '
                      'telemetry, or the file was removed', {})]
    age = float(now) - float(heartbeat.get('time', 0.0))
    if age > self.config.heartbeat_stale_secs:
      step = heartbeat.get('step')
      step = -1 if step is None else int(step)  # step 0 is a real step
      return [Anomaly(
          HEARTBEAT_STALE, step,
          'heartbeat is {:.0f}s old (threshold {:.0f}s): process wedged, '
          'killed, or telemetry disabled'.format(
              age, self.config.heartbeat_stale_secs),
          {'age_seconds': age, 'pid': heartbeat.get('pid'),
           'hostname': heartbeat.get('hostname')})]
    return []


def check_heartbeat(heartbeat: Optional[Dict[str, object]], now: float,
                    stale_secs: float = 300.0) -> List[Anomaly]:
  """Module-level convenience for doctor: one-off staleness check."""
  return Watchdog(WatchdogConfig(heartbeat_stale_secs=stale_secs)) \
      .check_heartbeat(heartbeat, now)
