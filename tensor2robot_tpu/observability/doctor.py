"""Ranked run diagnosis from telemetry.jsonl + forensics reports.

The reading half of the forensics loop, for the operator who just got
paged: ``t2r_telemetry doctor <model_dir>`` answers "what is wrong with
this run" from the files alone — no jax import, no live process, works
on any box that sees the filesystem (the same contract as the rest of
``bin/t2r_telemetry``).

Evidence consumed, in rough severity order:

  * heartbeat.json age (watchdog staleness thresholds);
  * the run's last lifecycle record (``run_abort`` / ``preempted``);
  * the latest goodput split, with the data-loss case attributed across
    HISTORY — "prefetch queue empty in 81% of samples" needs the gauge
    series the trainer embeds in every ``train`` record, not one sample;
  * recompile + shape-signature gauges (the device_feed invariant);
  * device/host memory gauge trends across train records;
  * ``anomaly`` records the in-process watchdog wrote;
  * the newest forensics report's top op + occupancy;
  * the newest roofline attribution (report or ``roofline`` record):
    under the MFU floor the verdict names the gating memory-bound op
    family and its fusion headroom (CRITICAL on a live run).

``diagnose`` returns ``Finding`` dicts ranked most-severe-first; the CLI
prints them and exits non-zero only on CRITICAL findings so the command
can gate automation without lying about missing telemetry (missing
files are a diagnosis, not an error).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from tensor2robot_tpu.observability import fleet as fleet_lib
from tensor2robot_tpu.observability import forensics as forensics_lib
from tensor2robot_tpu.observability import telemetry_file
from tensor2robot_tpu.observability import watchdog as watchdog_lib

__all__ = ['CRITICAL', 'WARNING', 'INFO', 'OK', 'diagnose',
           'format_findings']

CRITICAL = 'critical'
WARNING = 'warning'
INFO = 'info'
OK = 'ok'

_SEVERITY_RANK = {CRITICAL: 0, WARNING: 1, INFO: 2, OK: 3}

# Goodput losses below this fraction are not worth a finding.
_GOODPUT_FLOOR = 0.10

# MFU below this on a device with a peaks entry earns a roofline
# verdict naming the gating memory-bound family (BENCH_r05's device
# headline sits at 36.5%; a healthy run should not be under 25%).
_MFU_FLOOR = 0.25


def _finding(severity: str, message: str, **detail) -> Dict[str, object]:
  return {'severity': severity, 'message': message, 'detail': detail}


def _queue_empty_fraction(trains: List[Dict[str, object]]
                          ) -> Optional[float]:
  """Share of train samples whose prefetch queues were ALL empty."""
  sampled = 0
  empty = 0
  for record in trains:
    gauges = record.get('gauges') or {}
    depths = [value for tag, value in gauges.items()
              if tag.startswith('data/prefetch_queue_depth')]
    if not depths:
      continue
    sampled += 1
    if all(value <= 0.0 for value in depths):
      empty += 1
  return (empty / sampled) if sampled else None


def _memory_trend(trains: List[Dict[str, object]], prefix: str
                  ) -> Dict[str, List[float]]:
  series: Dict[str, List[float]] = {}
  for record in trains:
    gauges = record.get('gauges') or {}
    for tag, value in gauges.items():
      if tag.startswith(prefix):
        series.setdefault(tag, []).append(float(value))
  return series


def diagnose(model_dir: str,
             now: Optional[float] = None,
             heartbeat_stale_secs: float = 300.0
             ) -> List[Dict[str, object]]:
  """All findings for one model_dir, ranked most-severe first."""
  if now is None:
    now = time.time()  # wall-clock: compared to heartbeat timestamps
  findings: List[Dict[str, object]] = []

  # Primary lifecycle stream: the lowest-index host per discover_hosts,
  # which applies the indexed-wins rule — in a model_dir holding BOTH a
  # leftover single-process telemetry.jsonl and a fleet's
  # telemetry.0.jsonl, the fleet's stream is the live one, and judging
  # run_ended from the old run would suppress live fleet CRITICALs.
  telemetry_path = os.path.join(model_dir,
                                telemetry_file.TELEMETRY_FILENAME)
  host_files = telemetry_file.discover_hosts(model_dir)
  for host in sorted(host_files):
    if host_files[host].get('telemetry'):
      telemetry_path = host_files[host]['telemetry']
      break
  records: List[Dict[str, object]] = []
  if not os.path.exists(telemetry_path) or \
      os.path.getsize(telemetry_path) == 0:
    findings.append(_finding(
        INFO, 'no telemetry.jsonl under {} — run never started its '
        'telemetry, or metrics are disabled'.format(model_dir)))
  else:
    try:
      records = telemetry_file.read_telemetry(telemetry_path)
    except ValueError as e:
      findings.append(_finding(
          WARNING, 'telemetry.jsonl is corrupt mid-file: {}'.format(e)))

  # Whole-run staleness judges the FRESHEST heartbeat across hosts: the
  # run is alive if any host is; one host gone quiet while others beat
  # is the fleet section's host_dead verdict, not a wedged run.
  beat = telemetry_file.read_heartbeat(model_dir)
  for host in sorted(host_files):
    candidate = telemetry_file.read_heartbeat(model_dir,
                                              process_index=host)
    if candidate and (beat is None or
                      candidate.get('time', 0) > beat.get('time', 0)):
      beat = candidate
  # 'serving_stop'/'replay_stop'/'rl_stop'/'serving_fleet_stop' count
  # as orderly ends: a PolicyServer, ReplayService, RL loop or serving
  # fleet that closed cleanly stops heartbeating by design, which is
  # not a wedged process. An elastic 'leave' event (ISSUE 15) is the
  # same: the host departed orderly and stopped writing by design.
  run_ended = bool(records) and (
      records[-1].get('kind') in (
          'run_end', 'run_abort', 'preempted', 'serving_stop',
          'replay_stop', 'rl_stop', 'serving_fleet_stop')
      or (records[-1].get('kind') == 'elastic'
          and records[-1].get('event') == 'leave'))
  if run_ended and beat is not None:
    findings.append(_finding(
        INFO, 'run finished ({}); heartbeat age not meaningful'.format(
            records[-1].get('kind'))))
  else:
    for anomaly in watchdog_lib.check_heartbeat(
        beat, now, stale_secs=heartbeat_stale_secs):
      findings.append(_finding(
          CRITICAL if beat is not None else INFO, anomaly.message,
          **anomaly.detail))

  trains = [r for r in records if r.get('kind') == 'train']
  last = records[-1] if records else None
  if last is not None and last.get('kind') == 'run_abort':
    findings.append(_finding(
        CRITICAL, 'run aborted at step {} with {}'.format(
            last.get('step'), last.get('error'))))
  elif last is not None and last.get('kind') == 'preempted':
    findings.append(_finding(
        WARNING, 'run was preempted at step {} (signal {}) and has not '
        'resumed'.format(last.get('step'), last.get('signum'))))

  # Goodput: rank the lost categories of the newest split, attributing
  # the data case across the whole history.
  goodput_records = [r for r in records
                     if r.get('kind') in ('train', 'run_end')
                     and r.get('goodput')]
  if goodput_records:
    latest = goodput_records[-1]
    for category, fraction in sorted(latest['goodput'].items(),
                                     key=lambda kv: -kv[1]):
      if category == 'productive' or fraction < _GOODPUT_FLOOR:
        continue
      message = 'goodput lost to {} {:.0%}'.format(category, fraction)
      if category == 'data':
        empty = _queue_empty_fraction(trains)
        if empty is not None:
          message += ' -> prefetch queue empty in {:.0%} of samples'.format(
              empty)
          if empty > 0.5:
            message += ' (host decode is the bottleneck; scale the input '
            message += 'pipeline, not the model)'
      findings.append(_finding(WARNING, message, category=category,
                               fraction=fraction))

  # Recompiles + the device_feed shape-stability invariant.
  latest_gauges: Dict[str, float] = {}
  for record in trains:
    latest_gauges.update(record.get('gauges') or {})
  recompiles = latest_gauges.get(watchdog_lib.RECOMPILE_GAUGE, 0.0)
  if recompiles > 1.0:
    findings.append(_finding(
        WARNING, 'train step compiled {:g} times — a shape-unstable batch '
        'reached the jitted step (expected exactly 1; see '
        'data/device_feed.py)'.format(recompiles), recompiles=recompiles))
  shapes = latest_gauges.get(watchdog_lib.FEED_SHAPES_GAUGE, 0.0)
  if shapes > 1.0:
    findings.append(_finding(
        WARNING, 'device feed emitted {:g} distinct batch shape '
        'signatures (must be 1)'.format(shapes)))

  # Memory trends across the sampled history.
  for tag, values in _memory_trend(
      trains, watchdog_lib.DEVICE_BYTES_GAUGE).items():
    if len(values) >= 4 and all(b > a for a, b in zip(values, values[1:])):
      findings.append(_finding(
          WARNING, '{} grew monotonically across {} samples '
          '({:.1f} -> {:.1f} MiB): leak signature'.format(
              tag, len(values), values[0] / 2**20, values[-1] / 2**20)))

  # Pipeline X-ray: the latest t2r.pipeline.v1 attribution + stalls.
  pipelines = [r for r in records if r.get('kind') == 'pipeline']
  if pipelines:
    latest = pipelines[-1]
    bottleneck = latest.get('bottleneck')
    headroom = latest.get('headroom_vs_device')
    if bottleneck and bottleneck != 'device' and headroom is not None \
        and headroom < 0.5:
      findings.append(_finding(
          WARNING, 'pipeline gated by {} at {:.0%} of the device rate '
          '(step {}): the input path, not the chip, caps e2e '
          'throughput'.format(bottleneck, headroom, latest.get('step')),
          bottleneck=bottleneck, headroom_vs_device=headroom))
    elif bottleneck:
      # Structured detail on the healthy case too: automation gates
      # (bin/check_pipeline_doctor's untransferred fixture) judge
      # detail.bottleneck / detail.headroom_vs_device, not prose.
      findings.append(_finding(
          INFO, 'pipeline@{}: gating stage {} (headroom vs device '
          '{})'.format(latest.get('step'), bottleneck,
                       'n/a' if headroom is None
                       else '{:.0%}'.format(headroom)),
          bottleneck=bottleneck, headroom_vs_device=headroom))
  stall_indices = [i for i, r in enumerate(records)
                   if r.get('kind') == 'anomaly'
                   and r.get('anomaly') == 'pipeline_stall']
  if stall_indices:
    last_index = stall_indices[-1]
    last_stall = records[last_index]
    stage = (last_stall.get('detail') or {}).get('stage', 'unknown')
    # Recovery check: a LATER pipeline record not itself flagging a
    # stall means flow resumed — one historical hiccup must not hold
    # the automation gate at exit 2 for the rest of a days-long run.
    # (The same window's train/pipeline records are co-emitted with the
    # anomaly, so only a subsequent HEALTHY window counts.)
    recovered = any(
        r.get('kind') == 'pipeline'
        and 'pipeline_stall' not in (r.get('anomalies') or [])
        for r in records[last_index + 1:])
    findings.append(_finding(
        # A CURRENTLY stalled pipeline halts training: CRITICAL while
        # the run is live and unrecovered; historical context otherwise.
        WARNING if (run_ended or recovered) else CRITICAL,
        'pipeline stalled {} time(s), last at step {}{} (gating stage: '
        '{})'.format(len(stall_indices), last_stall.get('step'),
                     ' — recovered since' if recovered else '', stage),
        stage=stage, count=len(stall_indices), recovered=recovered))

  # Serving section (ISSUE 8): kind='serving' SLO reports from a
  # PolicyServer. A p99 over the SLO in the newest evidence, while the
  # server is still live, is the one condition a serving fleet pages on.
  serving_indices = [i for i, r in enumerate(records)
                     if r.get('kind') == 'serving']
  if serving_indices:
    latest = records[serving_indices[-1]]
    breach_indices = [i for i in serving_indices
                      if records[i].get('over_slo')
                      and (records[i].get('requests') or 0) > 0]
    if breach_indices:
      last_breach = records[breach_indices[-1]]
      # Recovery check (same shape as pipeline_stall): a LATER serving
      # window that handled traffic back under the SLO means the breach
      # passed — history, not a live page. A 'serving_stop' after the
      # breach means nobody is being served out-of-SLO right now either.
      recovered = any(
          records[i].get('kind') == 'serving'
          and not records[i].get('over_slo')
          and (records[i].get('requests') or 0) > 0
          for i in range(breach_indices[-1] + 1, len(records)))
      stopped = any(r.get('kind') == 'serving_stop'
                    for r in records[breach_indices[-1] + 1:])
      findings.append(_finding(
          WARNING if (run_ended or recovered or stopped) else CRITICAL,
          'serving p99 {:.1f} ms exceeded the {:g} ms SLO in {} '
          'window(s), last at {:.1f} req/s{}'.format(
              last_breach.get('p99_ms', 0.0),
              last_breach.get('slo_ms', 0.0), len(breach_indices),
              last_breach.get('requests_per_sec', 0.0),
              ' — recovered since' if recovered
              else (' — server stopped' if stopped else ' (live)')),
          p99_ms=last_breach.get('p99_ms'),
          slo_ms=last_breach.get('slo_ms'),
          count=len(breach_indices), recovered=recovered))
    rejected = latest.get('rejected_total') or 0
    if rejected > 0:
      findings.append(_finding(
          WARNING, 'admission control shed {:g} request(s) (queue depth '
          'reached max): demand exceeds this replica\'s '
          'capacity'.format(rejected), rejected_total=rejected))
    if not breach_indices:
      findings.append(_finding(
          INFO, 'serving healthy: {:.1f} req/s, p99 {:.1f} ms vs SLO '
          '{:g} ms, batch fill {:.0%}, params v{}'.format(
              latest.get('requests_per_sec', 0.0),
              latest.get('p99_ms', 0.0), latest.get('slo_ms', 0.0),
              latest.get('batch_fill', 0.0),
              latest.get('params_version', 0))))

  # Serving-fleet section (ISSUE 14): kind='serving_fleet'
  # (t2r.serving_fleet.v1) windows from a ServingFleet router — the
  # primary stream of a fleet-shaped serving dir (the router owns
  # stream 0; replicas 1..N federate underneath). Two page-worthy
  # conditions, each NAMING the replica: a replica breaching its SLO in
  # the newest evidence while the fleet is live, and a replica ejected
  # from rotation (heartbeat stale / dead) that has not returned.
  fleet_serving = [r for r in records
                   if r.get('kind') == 'serving_fleet']
  if fleet_serving:
    latest = fleet_serving[-1]
    # Per-replica SLO breaches across the fleet history.
    breaches_by_replica: Dict[str, List[int]] = {}
    for index, record in enumerate(records):
      if record.get('kind') != 'serving_fleet':
        continue
      for replica, entry in sorted((record.get('replicas') or {}).items()):
        if entry.get('over_slo') and (entry.get('requests') or 0) > 0:
          breaches_by_replica.setdefault(replica, []).append(index)
    for replica, indices in sorted(breaches_by_replica.items()):
      last_index = indices[-1]
      entry = (records[last_index].get('replicas') or {}).get(replica, {})
      # Recovery check (the serving-section rule, per replica): a LATER
      # fleet window where THIS replica handled traffic back under its
      # SLO means the breach passed — history, not a live page.
      recovered = any(
          r.get('kind') == 'serving_fleet'
          and not ((r.get('replicas') or {}).get(replica) or {})
              .get('over_slo')
          and (((r.get('replicas') or {}).get(replica) or {})
               .get('requests') or 0) > 0
          for r in records[last_index + 1:])
      findings.append(_finding(
          WARNING if (run_ended or recovered) else CRITICAL,
          'serving fleet: replica {} p99 {:.1f} ms exceeded its {:g} ms '
          'SLO in {} window(s){} — one replica out of envelope drags '
          'every request routed to it'.format(
              replica, entry.get('p99_ms') or 0.0,
              entry.get('slo_ms') or 0.0, len(indices),
              ' — recovered since' if recovered
              else (' (run ended)' if run_ended else ' (live)')),
          kind='fleet_replica_over_slo', replica=replica,
          p99_ms=entry.get('p99_ms'), slo_ms=entry.get('slo_ms'),
          count=len(indices), recovered=recovered))
    ejected_now = [str(replica) for replica in latest.get('ejected') or []]
    if ejected_now:
      findings.append(_finding(
          WARNING if run_ended else CRITICAL,
          'serving fleet: replica{} {} ejected from rotation (heartbeat '
          'stale or dead) and {} not returned — the fleet serves on {} '
          'of {} replicas'.format(
              's' if len(ejected_now) > 1 else '',
              ', '.join(ejected_now),
              'have' if len(ejected_now) > 1 else 'has',
              latest.get('healthy_count'), latest.get('replica_count')),
          kind='fleet_replica_ejected', replicas=ejected_now,
          healthy_count=latest.get('healthy_count'),
          replica_count=latest.get('replica_count')))
    elif (latest.get('ejections_total') or 0) > 0:
      findings.append(_finding(
          WARNING, 'serving fleet: {:g} ejection(s) occurred (every '
          'ejected replica has since returned to rotation); retried '
          'requests so far: {:g}'.format(
              latest.get('ejections_total') or 0,
              latest.get('retries_total') or 0),
          kind='fleet_ejections_recovered',
          ejections_total=latest.get('ejections_total')))
    rejected = latest.get('rejected_total') or 0
    if rejected > 0:
      findings.append(_finding(
          WARNING, 'serving fleet: router shed {:g} request(s) at the '
          'door (fleet-wide pending cap): demand exceeds the replica '
          'set — scale up'.format(rejected), kind='fleet_shed',
          rejected_total=rejected))
    if not breaches_by_replica and not ejected_now:
      findings.append(_finding(
          INFO, 'serving fleet healthy: {} replica(s) ({} healthy), '
          '{:.1f} actions/s aggregate, fleet p99 {:.1f} ms vs SLO '
          '{:g} ms, versions serving {}'.format(
              latest.get('replica_count'), latest.get('healthy_count'),
              latest.get('actions_per_sec', 0.0),
              latest.get('p99_ms', 0.0), latest.get('slo_ms', 0.0),
              latest.get('versions_serving')),
          kind='fleet_healthy',
          replica_count=latest.get('replica_count'),
          healthy_count=latest.get('healthy_count'),
          actions_per_sec=latest.get('actions_per_sec'),
          p99_ms=latest.get('p99_ms'), slo_ms=latest.get('slo_ms')))

  # Replay section (ISSUE 11): kind='replay' (t2r.replay.v1) windows
  # from a ReplayService. The one condition a replay fleet pages on: a
  # shard holding examples that stopped serving draws while the service
  # as a whole still samples — every learner batch is now biased away
  # from that shard's experience, silently. Two consecutive windows
  # must agree (occupancy > 0, shard samples == 0, service samples > 0)
  # so one small-window multinomial fluke cannot page.
  replay_records = [r for r in records if r.get('kind') == 'replay']
  if replay_records:
    latest = replay_records[-1]
    stalled_shards = []
    window_pair = replay_records[-2:]
    if len(window_pair) == 2 and all(
        (r.get('samples') or 0) > 0 for r in window_pair):
      for shard, entry in sorted((latest.get('shards') or {}).items()):
        stalled = all(
            ((r.get('shards') or {}).get(shard) or {}).get(
                'occupancy_examples', 0) > 0
            and ((r.get('shards') or {}).get(shard) or {}).get(
                'samples', 0) == 0
            for r in window_pair)
        if stalled:
          stalled_shards.append(shard)
    if stalled_shards:
      findings.append(_finding(
          WARNING if run_ended else CRITICAL,
          'replay shard{} {} stalled: holding examples but served zero '
          'draws across the last 2 windows while the service sampled '
          '{}/s — learner batches are biased away from {} '
          'experience'.format(
              's' if len(stalled_shards) > 1 else '',
              ', '.join(stalled_shards),
              latest.get('samples_per_sec', 0.0),
              'their' if len(stalled_shards) > 1 else 'its'),
          kind='replay_shard_stalled', shards=stalled_shards,
          samples_per_sec=latest.get('samples_per_sec')))
    corrupt_by_shard = {
        shard: entry.get('corrupt', 0)
        for shard, entry in sorted((latest.get('shards') or {}).items())
        if entry.get('corrupt', 0) > 0}
    if corrupt_by_shard:
      findings.append(_finding(
          WARNING, 'replay quarantined {:g} corrupt append(s) ({}): a '
          'writer is shipping damaged records'.format(
              sum(corrupt_by_shard.values()),
              ', '.join('shard {} x{:g}'.format(shard, count)
                        for shard, count in corrupt_by_shard.items())),
          kind='replay_corrupt_appends', by_shard=corrupt_by_shard))
    rejected = latest.get('rejected_total') or 0
    if rejected > 0:
      findings.append(_finding(
          WARNING, 'replay admission control shed {:g} sample '
          'request(s): learners are outrunning this replica'.format(
              rejected), rejected_total=rejected))
    if not stalled_shards:
      findings.append(_finding(
          INFO, 'replay healthy: {} examples resident ({:.1f} MB, '
          '{:.0f} B/ex packed), {:.1f} appends/s, {:.1f} samples/s '
          'across {} shards'.format(
              latest.get('occupancy_examples', 0),
              (latest.get('occupancy_bytes') or 0) / 1e6,
              latest.get('bytes_per_example', 0.0),
              latest.get('appends_per_sec', 0.0),
              latest.get('samples_per_sec', 0.0),
              len(latest.get('shards') or {}))))

  # RL section (ISSUE 12): kind='rl' (t2r.rl.v1) windows from the
  # actor<->learner loop. The page-worthy condition is ONE SIDE of the
  # closed loop dying while the other runs on: an actor that stopped
  # stepping starves the learner of fresh experience (it silently
  # overfits the resident buffer); a learner that stopped stepping
  # freezes the policy while collection burns compute. Two consecutive
  # windows must agree, the side must have STARTED in an earlier window
  # — a learner still waiting for its first replay batch is a boot
  # order, not a stall — and the side must not have FINISHED its
  # configured target (the records' actor_done/learner_done flags): a
  # learner that completed --learner_steps while the actor collects on
  # is a documented healthy mode, not a page.
  rl_records = [r for r in records if r.get('kind') == 'rl']
  if rl_records:
    latest = rl_records[-1]
    window_pair = rl_records[-2:]
    actor_started = any((r.get('actor_steps') or 0) > 0
                        for r in rl_records)
    learner_started = any((r.get('learner_steps') or 0) > 0
                          for r in rl_records)
    stalled_side = None
    if len(window_pair) == 2:
      if actor_started and all(
          (r.get('actor_steps') or 0) == 0
          and (r.get('learner_steps') or 0) > 0
          and not r.get('actor_done') for r in window_pair):
        stalled_side = 'actor'
      elif learner_started and all(
          (r.get('learner_steps') or 0) == 0
          and (r.get('actor_steps') or 0) > 0
          and not r.get('learner_done') for r in window_pair):
        stalled_side = 'learner'
    if stalled_side is not None:
      other = 'learner' if stalled_side == 'actor' else 'actor'
      findings.append(_finding(
          WARNING if run_ended else CRITICAL,
          'rl loop: the {} side stalled — zero {} steps across the last '
          '2 windows while the {} kept stepping ({})'.format(
              stalled_side, stalled_side, other,
              'fresh experience has stopped flowing; the learner is '
              'training on a frozen buffer' if stalled_side == 'actor'
              else 'the policy is frozen while collection burns '
              'compute'),
          kind='rl_{}_stalled'.format(stalled_side), side=stalled_side,
          actor_steps=latest.get('actor_steps'),
          learner_steps=latest.get('learner_steps')))
    cache = latest.get('act_jit_cache')
    if cache is not None and cache > 1.0:
      findings.append(_finding(
          WARNING, 'rl loop: acting path compiled {:g} executables — a '
          'signature-unstable input reached the jitted acting step '
          '(expected exactly 1; see rl/loop.py make_act_step)'.format(
              cache), kind='rl_act_recompile', act_jit_cache=cache))
    if stalled_side is None:
      spread = latest.get('scenario_success_spread')
      findings.append(_finding(
          INFO, 'rl loop@{}: {:.1f} ep/s ({:.0f} env steps/s), success '
          '{:.0%} cumulative, actor v{} of learner v{} ({} swaps{}){}'
          .format(
              latest.get('step'), latest.get('episodes_per_sec', 0.0),
              latest.get('env_steps_per_sec', 0.0),
              latest.get('success_rate_cumulative', 0.0),
              latest.get('actor_version', 0),
              latest.get('learner_version', 0),
              latest.get('swaps', 0),
              ', {} dropped'.format(latest['dropped_swaps'])
              if latest.get('dropped_swaps') else '',
              '' if spread is None else
              ', scenario spread {:.0%}'.format(spread))))

  # Compile section (ISSUE 13): kind='compile' records from the unified
  # CompiledArtifact store, plus fingerprint-drift anomalies. Drift —
  # the same artifact key (workload, shapes, chip, jax version, config)
  # compiling to a DIFFERENT post-optimization program — means the
  # persisted-executable contract is broken for that workload: page
  # while live, evidence after the run ends.
  compile_records = [r for r in records if r.get('kind') == 'compile']
  drift_records = [r for r in records
                   if r.get('kind') == 'anomaly'
                   and r.get('anomaly') == 'fingerprint_drift']
  if drift_records:
    # One finding PER drifted workload — a run where two workloads
    # drift must name both, or the operator investigates only the last.
    drift_by_workload: Dict[str, int] = {}
    for record in drift_records:
      workload = (record.get('detail') or {}).get('workload') or \
          'unknown'
      drift_by_workload[workload] = drift_by_workload.get(workload,
                                                          0) + 1
    for workload, count in sorted(drift_by_workload.items()):
      findings.append(_finding(
          WARNING if run_ended else CRITICAL,
          'compile: post-optimization fingerprint drifted for workload '
          '{!r} ({} event(s)) — the same artifact key (shapes/chip/jax/'
          'config unchanged) now compiles to a different program; the '
          'toolchain moved under a pinned version string, or lowering '
          'went nondeterministic'.format(workload, count),
          kind='fingerprint_drift', workload=workload, count=count))
  if compile_records:
    hits = sum(1 for r in compile_records if r.get('outcome') == 'hit')
    misses = len(compile_records) - hits
    compile_ms = sum(float(r.get('compile_ms') or 0.0)
                     for r in compile_records)
    workloads = sorted({str(r.get('workload'))
                        for r in compile_records})
    findings.append(_finding(
        INFO, 'compile: {} artifact load(s) across {} workload(s) — '
        '{} deserialized (zero-compile), {} compiled ({:.0f} ms '
        'compiling)'.format(
            len(compile_records), len(workloads), hits, misses,
            compile_ms),
        hits=hits, misses=misses, compile_ms_total=compile_ms,
        workloads=workloads))

  # Fleet federation pass, computed BEFORE the elastic section: the
  # elastic event ladder may live in ANOTHER host's stream (after a
  # coordinator re-election the new coordinator narrates the shrink),
  # so both the elastic verdicts and the fleet section judge the
  # merged view.
  try:
    # Single-host dirs skip the federation pass: fleet_summary would
    # re-read every rotated generation this function already parsed,
    # doubling doctor's I/O for nothing (the only fleet-relevant facts
    # of a one-host dir — recovery records — are in ``records``).
    fsum = None
    if len(host_files) > 1:
      fsum = fleet_lib.fleet_summary(model_dir, now=now,
                                     stale_secs=heartbeat_stale_secs)
  except Exception as e:  # noqa: BLE001 — one torn stream, not a crash
    fsum = None
    findings.append(_finding(
        WARNING, 'fleet summary failed: {}'.format(e)))

  # Elastic section (ISSUE 15): t2r.elastic.v1 membership events from
  # the coordinator-led elastic driver. Two verdicts: a shrink that
  # BEGAN but never completed its ladder (emergency_save ->
  # mesh_rebuild -> artifact_rebind -> resume) has the fleet wedged
  # mid-rebuild — CRITICAL while live, naming the stalled phase and the
  # narrating host; otherwise an INFO summary of the world's history.
  # The departed-host classification feeds the fleet section below: a
  # host named departed by a shrink event must not page host_dead.
  elastic_events = (fsum.get('elastic_events') if fsum is not None
                    else None) or [r for r in records
                                   if r.get('kind') == 'elastic']
  orderly_departed: Dict[int, Dict[str, object]] = {}
  lapse_departed: Dict[int, Dict[str, object]] = {}
  if elastic_events:
    from tensor2robot_tpu.elastic.membership import (
        EVENT_GROW,
        EVENT_JOIN,
        EVENT_REBUILD,
        EVENT_SHRINK,
        EVENT_SHRINK_BEGIN,
        EVENT_SHRINK_PHASE,
        SHRINK_PHASES,
    )

    for event in elastic_events:
      name = event.get('event')
      if name in (EVENT_SHRINK_BEGIN, EVENT_SHRINK):
        for host in event.get('departed') or []:
          bucket = (orderly_departed if event.get('orderly')
                    else lapse_departed)
          bucket[int(host)] = event
      elif name == EVENT_GROW:
        for host in event.get('joined') or []:
          orderly_departed.pop(int(host), None)
          lapse_departed.pop(int(host), None)
      elif name == EVENT_JOIN and event.get('host') is not None:
        orderly_departed.pop(int(event['host']), None)
        lapse_departed.pop(int(event['host']), None)
    begins = [e for e in elastic_events
              if e.get('event') == EVENT_SHRINK_BEGIN]
    completed_epochs = {int(e.get('epoch') or 0) for e in elastic_events
                       if e.get('event') == EVENT_SHRINK}
    # A begin with no completion at its OWN epoch is only "wedged" while
    # the world never moved past it: when the declaring coordinator
    # itself dies mid-ladder, its shrink_begin is orphaned (only the
    # coordinator narrates the ladder) and a SUCCESSOR completes the
    # resize at a later epoch — any completed shrink or grow beyond the
    # begin's epoch proves the fleet reconfigured past it.
    resolved_epochs = completed_epochs | {
        int(e.get('epoch') or 0) for e in elastic_events
        if e.get('event') == EVENT_GROW}
    stalled = [b for b in begins
               if int(b.get('epoch') or 0) not in completed_epochs
               and not any(epoch > int(b.get('epoch') or 0)
                           for epoch in resolved_epochs)]
    if stalled:
      begin = stalled[-1]
      epoch = int(begin.get('epoch') or 0)
      done_phases = [e.get('phase') for e in elastic_events
                     if e.get('event') == EVENT_SHRINK_PHASE
                     and int(e.get('epoch') or 0) == epoch]
      stalled_phase = next(
          (phase for phase in SHRINK_PHASES if phase not in done_phases),
          'resume')
      reporter = begin.get('host', begin.get('process_index'))
      findings.append(_finding(
          WARNING if run_ended else CRITICAL,
          'elastic shrink (epoch {}, world {} -> {}) stalled in the '
          '{} phase: host {} declared host(s) {} departed but the '
          'rebuild ladder never completed — the fleet is wedged '
          'mid-resize'.format(
              epoch, begin.get('world_before'), begin.get('world_after'),
              stalled_phase, reporter, begin.get('departed')),
          kind='elastic_rebuild_stalled', phase=stalled_phase,
          host=reporter, epoch=epoch,
          departed=begin.get('departed'),
          completed_phases=done_phases))
    else:
      worlds = [int(e.get('world_after') or 0) for e in elastic_events
                if e.get('event') in (EVENT_GROW, EVENT_SHRINK_BEGIN)]
      shrinks = [e for e in elastic_events
                 if e.get('event') == EVENT_SHRINK]
      grows = [e for e in elastic_events if e.get('event') == EVENT_GROW]
      rebuilds = [e for e in elastic_events
                  if e.get('event') == EVENT_REBUILD
                  and int(e.get('epoch') or 0) > 1]
      rebuild_compiles = sum(float(e.get('compiles_delta') or 0.0)
                             for e in rebuilds)
      findings.append(_finding(
          INFO, 'elastic: world size {} after {} shrink(s) / {} grow(s)'
          ' ({} orderly departure(s)); {} post-epoch-1 rebuild(s) cost '
          '{:g} XLA compile(s)'.format(
              worlds[-1] if worlds else 'n/a', len(shrinks), len(grows),
              sum(1 for e in shrinks if e.get('orderly')),
              len(rebuilds), rebuild_compiles),
          kind='elastic_summary',
          world_size=worlds[-1] if worlds else None,
          shrinks=len(shrinks), grows=len(grows),
          rebuild_compiles=rebuild_compiles))

  # Fleet section (ISSUE 9): federated per-host view. A host whose
  # heartbeat is stale while others advance, or a straggler the fleet
  # has not recovered from, halts/gates the whole mesh: CRITICAL while
  # the run is live. Everything is recomputed from the per-host files —
  # doctor must name the host without a live process anywhere.
  fleet_records = [r for r in records if r.get('kind') == 'fleet']
  if fsum is not None and (fsum['host_count'] > 1 or fsum['recoveries']):
    if fsum['host_count'] > 1:
      parts = ['fleet: {} hosts'.format(fsum['host_count'])]
      if fsum.get('step_time_skew'):
        parts.append('step-time skew {:.2f}x (gating host {})'.format(
            fsum['step_time_skew'], fsum['gating_host']))
      if fsum.get('fleet_min_goodput') is not None:
        parts.append('fleet-min goodput {:.0%}'.format(
            fsum['fleet_min_goodput']))
      findings.append(_finding(
          INFO, ', '.join(parts), host_count=fsum['host_count'],
          step_time_skew=fsum.get('step_time_skew'),
          gating_host=fsum.get('gating_host'),
          fleet_min_goodput=fsum.get('fleet_min_goodput')))
    for host in fsum['dead_hosts']:
      entry = fsum['hosts'].get(str(host), {})
      if int(host) in orderly_departed:
        # ISSUE 15: the host departed in an ORDERLY elastic shrink — a
        # t2r.elastic.v1 shrink event names it, the fleet reconfigured
        # around it on purpose, and its silence is the design, not a
        # death. INFO, citing the shrink event.
        event = orderly_departed[int(host)]
        findings.append(_finding(
            INFO, 'fleet: host {} departed in an orderly elastic '
            'shrink (epoch {}, world {} -> {}); its stale heartbeat is '
            'expected, not a page'.format(
                host, event.get('epoch'), event.get('world_before'),
                event.get('world_after')),
            kind='host_departed_orderly', host=host,
            epoch=event.get('epoch')))
        continue
      if int(host) in lapse_departed:
        # Preempted, but the elastic shrink already reconfigured the
        # fleet around it: the outage is history (the recovery record
        # carries it), not a live page — unless it never resumed, which
        # the stuck-rebuild CRITICAL above owns.
        event = lapse_departed[int(host)]
        findings.append(_finding(
            WARNING, 'fleet: host {} was preempted and the elastic '
            'shrink (epoch {}, world {} -> {}) already closed around '
            'it — evidence, not a live page'.format(
                host, event.get('epoch'), event.get('world_before'),
                event.get('world_after')),
            kind='host_departed_preempted', host=host,
            epoch=event.get('epoch')))
        continue
      # WARNING (not INFO) after run end — same downgrade rule as the
      # straggler verdict: a host that died during a now-ended run is
      # still evidence worth surfacing, just not a live page.
      findings.append(_finding(
          WARNING if run_ended else CRITICAL,
          'fleet: host {} ({}) heartbeat is {:.0f}s stale while other '
          'hosts advance — dead or partitioned{}'.format(
              host, entry.get('hostname'),
              entry.get('heartbeat_age_s') or 0.0,
              '' if not run_ended else ' (run already ended)'),
          kind='host_dead', host=host, hostname=entry.get('hostname'),
          heartbeat_age_s=entry.get('heartbeat_age_s')))
    straggler_indices = [i for i, r in enumerate(records)
                         if r.get('kind') == 'anomaly'
                         and r.get('anomaly') == watchdog_lib.STRAGGLER]
    if straggler_indices:
      last_index = straggler_indices[-1]
      last_straggler = records[last_index]
      host = (last_straggler.get('detail') or {}).get('host')
      # Recovery check (same shape as pipeline_stall): a LATER fleet
      # window without a straggler means the skew passed — history,
      # not a live page.
      recovered = any(
          r.get('kind') == 'fleet'
          and watchdog_lib.STRAGGLER not in (r.get('anomalies') or [])
          for r in records[last_index + 1:])
      findings.append(_finding(
          WARNING if (run_ended or recovered) else CRITICAL,
          'fleet: host {} straggled {} window(s), last at step {}{} '
          '({:.1f}x the fleet median)'.format(
              host, len(straggler_indices), last_straggler.get('step'),
              ' — recovered since' if recovered else '',
              (last_straggler.get('detail') or {}).get('ratio') or 0.0),
          kind='straggler', host=host, count=len(straggler_indices),
          recovered=recovered))
    elif fleet_records:
      latest = fleet_records[-1]
      findings.append(_finding(
          INFO, 'fleet@{}: no straggler; gating host {} at skew '
          '{}'.format(
              latest.get('step'), latest.get('gating_host'),
              'n/a' if latest.get('step_time_skew') is None
              else '{:.2f}x'.format(latest['step_time_skew']))))
    for warning in fsum.get('warnings') or []:
      findings.append(_finding(WARNING, 'fleet: ' + warning))
  recoveries = (fsum['recoveries'] if fsum is not None else
                [r for r in records if r.get('kind') == 'recovery'])
  for recovery in recoveries:
    worlds = ''
    if recovery.get('world_before') is not None:
      worlds = ', world {} -> {}'.format(recovery.get('world_before'),
                                         recovery.get('world_after'))
    findings.append(_finding(
        INFO, 'recovered from preemption at step {} in {:.1f}s '
        '(save {:.1f}s, down {:.1f}s, restore {:.1f}s, first step '
        '{:.1f}s{})'.format(
            recovery.get('preempted_step'),
            recovery.get('preemption_recovery_seconds') or 0.0,
            (recovery.get('phases') or {}).get('emergency_save_s', 0.0),
            (recovery.get('phases') or {}).get('downtime_s', 0.0),
            (recovery.get('phases') or {}).get('restore_s', 0.0),
            (recovery.get('phases') or {}).get('first_step_s', 0.0),
            worlds),
        kind='recovery',
        preemption_recovery_seconds=recovery.get(
            'preemption_recovery_seconds'),
        world_before=recovery.get('world_before'),
        world_after=recovery.get('world_after')))

  # Watchdog anomaly records written in-process.
  anomalies = [r for r in records if r.get('kind') == 'anomaly']
  if anomalies:
    by_kind: Dict[str, int] = {}
    for record in anomalies:
      by_kind[str(record.get('anomaly'))] = \
          by_kind.get(str(record.get('anomaly')), 0) + 1
    findings.append(_finding(
        WARNING, 'watchdog fired {} anomaly record(s): {}'.format(
            len(anomalies),
            ', '.join('{} x{}'.format(kind, count)
                      for kind, count in sorted(by_kind.items()))),
        counts=by_kind))

  # Newest forensics report: the attribution evidence.
  reports = forensics_lib.read_reports(model_dir)
  if reports:
    step, report = reports[-1]
    top_ops = report.get('top_ops') or []
    if top_ops:
      top = top_ops[0]
      findings.append(_finding(
          INFO, 'forensics@{} ({}): top op {} {:.2f} ms/step '
          '({:.0%} of attributed time)'.format(
              step, report.get('reason'), top.get('name'),
              top.get('ms_per_step', 0.0), top.get('fraction', 0.0)),
          report='{}/{}.json'.format(forensics_lib.FORENSICS_DIRNAME,
                                     step)))
    occupancy = report.get('device_occupancy') or {}
    if occupancy.get('extent_ms'):
      findings.append(_finding(
          INFO, 'forensics@{}: device line {:.0%} occupied over a '
          '{:.0f} ms window'.format(step, occupancy.get('occupancy', 0.0),
                                    occupancy.get('extent_ms', 0.0))))
    for warning in report.get('warnings') or []:
      findings.append(_finding(INFO, 'forensics@{}: {}'.format(
          step, warning)))

  # Roofline verdict: the newest t2r.roofline.v1 evidence — the latest
  # capture report's attribution, else the compact telemetry record the
  # trainer logs alongside it. Under the MFU floor with a memory-bound
  # family in the table, the verdict NAMES that family: it is the op
  # the kernel work (ROADMAP item 1) should fuse first, and its
  # headroom is the predicted win.
  roofline = None
  roofline_step = None
  if reports and reports[-1][1].get('roofline'):
    roofline_step = reports[-1][0]
    roofline = reports[-1][1]['roofline']
  else:
    roofline_records = [r for r in records if r.get('kind') == 'roofline']
    if roofline_records:
      roofline = roofline_records[-1]
      roofline_step = roofline.get('step')
  if roofline:
    mfu_value = roofline.get('mfu')
    gating = roofline.get('gating_memory_bound_family')
    headroom_ms = None
    for row in roofline.get('families') or []:
      if row.get('family') == gating:
        headroom_ms = row.get('headroom_ms')
        break
    if roofline.get('mode') == 'intensity-only':
      families = roofline.get('families') or []
      top_family = families[0].get('family') if families else None
      findings.append(_finding(
          INFO, 'roofline@{}: intensity-only mode — device kind {!r} has '
          'no peaks entry (CPU or unknown), so %-peak/MFU/headroom are '
          'withheld; program intensity {} flops/byte{}'.format(
              roofline_step, roofline.get('device_kind'),
              roofline.get('arithmetic_intensity'),
              ', top measured family {}'.format(top_family)
              if top_family else ''),
          kind='roofline', mode='intensity-only',
          arithmetic_intensity=roofline.get('arithmetic_intensity')))
    elif mfu_value is not None and mfu_value < _MFU_FLOOR:
      if gating:
        findings.append(_finding(
            WARNING if run_ended else CRITICAL,
            'roofline@{}: MFU {:.1%} is under the {:.0%} floor and the '
            'gating memory-bound family is {}{} — a fused kernel for it '
            'is the predicted win'.format(
                roofline_step, mfu_value, _MFU_FLOOR, gating,
                ' (headroom {:.2f} ms/step)'.format(headroom_ms)
                if headroom_ms is not None else ''),
            kind='roofline', mfu=mfu_value,
            gating_memory_bound_family=gating, headroom_ms=headroom_ms))
      else:
        findings.append(_finding(
            WARNING,
            'roofline@{}: MFU {:.1%} is under the {:.0%} floor but no '
            'memory-bound family stands out — compute-bound or '
            'unattributed; inspect the capture'.format(
                roofline_step, mfu_value, _MFU_FLOOR),
            kind='roofline', mfu=mfu_value))
    else:
      findings.append(_finding(
          INFO, 'roofline@{}: MFU {}, HBM bandwidth {}, '
          'bound profile healthy{}'.format(
              roofline_step,
              '{:.1%}'.format(mfu_value) if mfu_value is not None
              else 'n/a',
              '{:.1%}'.format(roofline['hbm_bw_util'])
              if roofline.get('hbm_bw_util') is not None else 'n/a',
              ' (watch {})'.format(gating) if gating else ''),
          kind='roofline', mfu=mfu_value,
          gating_memory_bound_family=gating))

  if not any(f['severity'] in (CRITICAL, WARNING) for f in findings):
    findings.append(_finding(
        OK, 'no anomalies in the available telemetry' if not records else
        'no anomalies: heartbeat fresh, goodput healthy, no recompiles, '
        'no watchdog events'))
  findings.sort(key=lambda f: _SEVERITY_RANK.get(str(f['severity']), 9))
  return findings


def format_findings(findings: List[Dict[str, object]]) -> str:
  tags = {CRITICAL: 'CRIT', WARNING: 'WARN', INFO: 'INFO', OK: ' OK '}
  return '\n'.join('{} {}'.format(
      tags.get(str(f['severity']), '????'), f['message'])
      for f in findings)
