"""Fleet observatory: cross-host telemetry federation + attribution.

Every observability layer through PR 6 is single-process by
construction: the registry is process-wide, the watchdog reads one
process's gauges, and ``telemetry.jsonl`` names no host. At DCN x ICI
scale the dominant failure modes are exactly the cross-host ones —
per-host skew (one slow host gates every all-reduce, so a straggler's
wait is everyone's wait) and silently dead hosts (Scalable Training
with pjit on TPUv4, arXiv:2204.06514). This module is the fleet-level
lens over the per-host streams ``telemetry_file`` now emits:

  * **Federation** (`read_fleet` / `align_train_series` /
    `fleet_summary`) — merge ``telemetry.<i>.jsonl`` streams into one
    fleet view: per-host step-time/goodput series aligned by step,
    fleet goodput as the MIN across hosts (the gated quantity), skew,
    and the gating host. Torn or partial per-host files degrade to
    per-host warnings — one corrupt stream must not blind the fleet
    view of the others.
  * **FleetWatchdog** — the fleet analogue of `watchdog.Watchdog`:
    ``straggler`` fires when one host's step time reaches
    ``straggler_ratio`` (2x) times the rolling fleet-median baseline
    (anomalous windows never fold into the baseline, so a sustained
    straggler cannot normalize itself); ``host_dead`` fires when one
    host's heartbeat goes stale while at least one other host is still
    advancing (latched per host — a dead host is reported once, and
    re-armed only if it comes back). Both count into the same
    ``watchdog/anomalies`` family and flow through the same
    anomaly -> budgeted-capture -> forensics loop.
  * **FleetObserver** — the live in-trainer side: at the log cadence,
    host 0 (or any host asked to observe) reads every host's
    heartbeat file — heartbeats now carry ``step_time_s`` /
    ``examples_per_sec`` / ``productive_fraction``, so the whole fleet
    observation costs N tiny atomic-file reads, not N telemetry
    re-parses — and feeds the FleetWatchdog. Each window yields a
    ``t2r.fleet.v1`` telemetry record (per-host table, skew, gating
    host, fleet-min goodput).
  * **Recovery timeline** (``t2r.recovery.v1``) — the preemption ->
    emergency save -> mesh rebuild -> resume path, measured per phase.
    The preempting process writes an atomic recovery MARKER next to its
    checkpoint (wall-clock stamped: the resuming process is a different
    process); the resuming trainer consumes it and emits one
    ``recovery`` record with ``phases`` and the headline
    ``preemption_recovery_seconds`` — ROADMAP item 4's elastic-recovery
    metric, measured before the elastic machinery itself exists.

Everything here is jax-free (the ``bin/t2r_telemetry`` / doctor
contract): host identity comes in as a plain dict
(``signals.host_identity()`` on the trainer side).
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Dict, List, Optional, Tuple

from tensor2robot_tpu.observability import registry as registry_lib
from tensor2robot_tpu.observability import telemetry_file
from tensor2robot_tpu.observability.watchdog import (
    ANOMALY_COUNTER,
    Anomaly,
    HOST_DEAD,
    STRAGGLER,
)

__all__ = ['FLEET_RECORD_SCHEMA', 'RECOVERY_SCHEMA', 'FleetConfig',
           'FleetWatchdog', 'FleetObserver', 'read_fleet',
           'align_train_series', 'fleet_summary', 'dead_hosts',
           'recovery_marker_path', 'write_recovery_marker',
           'consume_recovery_marker', 'build_recovery_record',
           'RECOVERY_GAUGE']

FLEET_RECORD_SCHEMA = 't2r.fleet.v1'
RECOVERY_SCHEMA = 't2r.recovery.v1'

RECOVERY_GAUGE = 'reliability/preemption_recovery_seconds'

_RECOVERY_MARKER = 'recovery_pending{}.json'


class FleetConfig:
  """Fleet detection thresholds.

  ``straggler_ratio`` is deliberately 2x (not the watchdog's 1.8x):
  cross-host skew of a few percent is normal DCN weather; a straggler
  is a host that doubles everyone's step.
  """

  def __init__(self,
               straggler_ratio: float = 2.0,
               min_baseline_windows: int = 3,
               baseline_windows: int = 16,
               heartbeat_stale_secs: float = 300.0):
    if straggler_ratio <= 1.0:
      raise ValueError('straggler_ratio must exceed 1.0; got {}.'.format(
          straggler_ratio))
    self.straggler_ratio = float(straggler_ratio)
    self.min_baseline_windows = int(min_baseline_windows)
    self.baseline_windows = int(baseline_windows)
    self.heartbeat_stale_secs = float(heartbeat_stale_secs)


class FleetWatchdog:
  """Rolling-baseline straggler + dead-host detection over one fleet."""

  def __init__(self, config: Optional[FleetConfig] = None,
               registry: Optional[registry_lib.TelemetryRegistry] = None):
    self.config = config or FleetConfig()
    self._registry = registry
    self._medians: List[float] = []  # healthy fleet medians, rolling
    self._windows_seen = 0  # warm-up gate (fleet windows observed)
    self._dead: set = set()  # latched host_dead hosts

  @property
  def registry(self) -> registry_lib.TelemetryRegistry:
    return self._registry or registry_lib.get_registry()

  def _count(self, anomalies: List[Anomaly]) -> List[Anomaly]:
    if anomalies:
      family = self.registry.counter_family(ANOMALY_COUNTER, ('kind',))
      for anomaly in anomalies:
        family.series(anomaly.kind).inc()
    return anomalies

  def observe(self, step: int, host_step_times: Dict[int, float]
              ) -> List[Anomaly]:
    """One log-cadence pass over {host: window-mean seconds/step}.

    A ``straggler`` fires for a host whose step time reaches
    ``straggler_ratio`` x BOTH references:

      * the median of its PEERS' times this window — which is what
        makes a straggler a straggler (it lags the fleet, not some
        absolute bar): a host slow from its very first window is
        caught (no healthy history needed), and a fleet-WIDE slowdown
        — every host slow together — fires nothing here (that is the
        per-host watchdog's step_time_regression, not skew);
      * the rolling median of HEALTHY fleet medians, when armed —
        hysteresis against one noisy window moving both numbers.

    Like the watchdog's step-time regression, a sustained straggler
    keeps firing (the capture budget, not a latch, bounds the
    response) and anomalous windows never fold into the baseline.
    Needs >= 2 hosts reporting and ``min_baseline_windows`` observed
    fleet windows before anything can fire (startup jitter damping).
    """
    times = {int(host): float(t) for host, t in host_step_times.items()
             if t is not None and float(t) > 0.0}
    if len(times) < 2:
      return []
    self._windows_seen += 1
    window_median = statistics.median(times.values())
    baseline = (statistics.median(self._medians)
                if len(self._medians) >= self.config.min_baseline_windows
                else None)
    anomalies: List[Anomaly] = []
    ratio = self.config.straggler_ratio
    warmed_up = self._windows_seen > self.config.min_baseline_windows
    skewed = False  # any host peer-skewed (vetoes folding, warm-up too)
    for host, step_time_s in sorted(times.items()):
      peer_median = statistics.median(
          [t for h, t in times.items() if h != host])
      if peer_median <= 0.0 or step_time_s < ratio * peer_median:
        continue
      skewed = True
      if not warmed_up:
        continue  # startup jitter damping: veto the fold, fire later
      if baseline is not None and baseline > 0.0 and \
          step_time_s < ratio * baseline:
        continue
      reference = baseline if baseline is not None else peer_median
      anomalies.append(Anomaly(
          STRAGGLER, step,
          'host {} step time {:.1f} ms/step is {:.1f}x the fleet '
          'median {:.1f} ms/step — its collectives gate every other '
          'host'.format(host, step_time_s * 1e3,
                        step_time_s / reference, reference * 1e3),
          {'host': host, 'step_time_s': step_time_s,
           'fleet_median_s': reference,
           'peer_median_s': peer_median,
           'ratio': step_time_s / reference,
           'host_step_times': {str(h): t
                               for h, t in sorted(times.items())}}))
    if not anomalies and not skewed:
      # A peer-skewed window never folds — during warm-up either: a
      # host slow from boot would otherwise poison the baseline with
      # pre-skewed medians and read as normal forever.
      self._medians.append(window_median)
      if len(self._medians) > self.config.baseline_windows:
        self._medians.pop(0)
    return self._count(anomalies)

  def check_heartbeats(self, heartbeats: Dict[int, Optional[Dict]],
                       now: float,
                       live_hosts: Tuple[int, ...] = ()) -> List[Anomaly]:
    """``host_dead``: one host's heartbeat stale while others advance.

    ``now`` must come from the same wall clock as the heartbeat
    ``time`` fields (they cross process boundaries — same caveat as
    ``Watchdog.check_heartbeat``). ``live_hosts`` names hosts known
    fresh without a file read (the observing host itself). A host with
    NO heartbeat file yet is ignored (fleet startup is staggered); ALL
    hosts stale is the whole-run ``heartbeat_stale`` case the existing
    watchdog owns, not a fleet verdict. Latched per host: a dead host
    fires once, and re-arms only after its heartbeat comes back fresh.
    """
    ages: Dict[int, float] = {}
    for host, beat in heartbeats.items():
      if beat is None:
        continue
      ages[int(host)] = float(now) - float(beat.get('time', 0.0))
    for host in live_hosts:
      ages[int(host)] = 0.0
    stale_secs = self.config.heartbeat_stale_secs
    fresh = [host for host, age in ages.items() if age <= stale_secs]
    stale = [host for host, age in ages.items() if age > stale_secs]
    anomalies: List[Anomaly] = []
    if fresh:
      for host in sorted(stale):
        if host in self._dead:
          continue
        self._dead.add(host)
        beat = heartbeats.get(host) or {}
        step = beat.get('step')
        anomalies.append(Anomaly(
            HOST_DEAD, -1 if step is None else int(step),
            'host {} heartbeat is {:.0f}s old (threshold {:.0f}s) while '
            'host {} still advances: process dead or partitioned'.format(
                host, ages[host], stale_secs, min(fresh)),
            {'host': host, 'age_seconds': ages[host],
             'pid': beat.get('pid'), 'hostname': beat.get('hostname'),
             'fresh_hosts': sorted(fresh)}))
    for host in fresh:
      self._dead.discard(host)  # re-arm: the host came back
    return self._count(anomalies)


class FleetObserver:
  """Live fleet observation for one trainer process (the log cadence).

  Reads every host's heartbeat file under the shared model_dir —
  heartbeats carry the window stats the trainer stamps into them — and
  runs the FleetWatchdog over the result. The observing host's own
  window numbers come from the caller (its heartbeat for this window
  has not been written yet when ``observe`` runs).
  """

  def __init__(self, model_dir: str, identity: Dict[str, object],
               config: Optional[FleetConfig] = None,
               registry: Optional[registry_lib.TelemetryRegistry] = None):
    self.model_dir = model_dir
    self.identity = dict(identity or {})
    self.config = config or FleetConfig()
    self._watchdog = FleetWatchdog(self.config, registry=registry)
    self.last_record: Optional[Dict[str, object]] = None

  @property
  def own_host(self) -> int:
    return int(self.identity.get('process_index') or 0)

  def observe(self, step: int,
              step_time_s: Optional[float] = None,
              examples_per_sec: Optional[float] = None,
              productive_fraction: Optional[float] = None,
              now: Optional[float] = None
              ) -> Tuple[Optional[Dict[str, object]], List[Anomaly]]:
    """(t2r.fleet.v1 record payload or None, fired anomalies).

    Returns ``(None, [])`` while this model_dir holds only one host's
    stream — a single-process run must not grow fleet records.
    """
    if now is None:
      now = time.time()  # wall-clock: compared to heartbeat timestamps
    own = self.own_host
    hosts = telemetry_file.discover_hosts(self.model_dir)
    beats: Dict[int, Optional[Dict[str, object]]] = {}
    for host, files in hosts.items():
      if host == own:
        continue
      beats[host] = _read_heartbeat_path(files.get('heartbeat'))
    table: Dict[int, Dict[str, object]] = {own: {
        'step': int(step),
        'step_time_s': step_time_s,
        'examples_per_sec': examples_per_sec,
        'productive': productive_fraction,
        'heartbeat_age_s': 0.0,
        'hostname': self.identity.get('hostname'),
    }}
    for host, beat in beats.items():
      if beat is None:
        continue
      table[host] = {
          'step': beat.get('step'),
          'step_time_s': beat.get('step_time_s'),
          'examples_per_sec': beat.get('examples_per_sec'),
          'productive': beat.get('productive_fraction'),
          'heartbeat_age_s': float(now) - float(beat.get('time', 0.0)),
          'hostname': beat.get('hostname'),
      }
    if len(table) < 2:
      return None, []
    anomalies = self._watchdog.check_heartbeats(
        beats, now, live_hosts=(own,))
    # Stragglers are judged over hosts with a FRESH window: a dead
    # host's frozen step_time must not drag the fleet median.
    stale_secs = self.config.heartbeat_stale_secs
    times = {host: entry.get('step_time_s')
             for host, entry in table.items()
             if entry.get('step_time_s')
             and float(entry.get('heartbeat_age_s', 0.0)) <= stale_secs}
    anomalies.extend(self._watchdog.observe(step, times))
    record = _fleet_record(table, anomalies)
    self.last_record = record
    return record, anomalies


def _fleet_record(table: Dict[int, Dict[str, object]],
                  anomalies: List[Anomaly]) -> Dict[str, object]:
  times = {host: float(entry['step_time_s']) for host, entry in table.items()
           if entry.get('step_time_s')}
  productives = [float(entry['productive']) for entry in table.values()
                 if entry.get('productive') is not None]
  median = statistics.median(times.values()) if times else None
  gating_host = max(times, key=times.get) if times else None
  return {
      'schema': FLEET_RECORD_SCHEMA,
      'hosts': {str(host): entry for host, entry in sorted(table.items())},
      'host_count': len(table),
      'median_step_time_s': median,
      # Skew: the gating host's step time over the fleet median — 1.0
      # is a perfectly even fleet; the quantity straggler thresholds on.
      'step_time_skew': (times[gating_host] / median
                         if times and median else None),
      'gating_host': gating_host,
      # Min across hosts: a straggler's wait is everyone's wait, so the
      # fleet's productive fraction is its weakest member's.
      'fleet_min_goodput': min(productives) if productives else None,
      'anomalies': [anomaly.kind for anomaly in anomalies],
  }


def _read_heartbeat_path(path: Optional[str]
                         ) -> Optional[Dict[str, object]]:
  if not path or not os.path.exists(path):
    return None
  try:
    with open(path, encoding='utf-8') as f:
      return json.load(f)
  except (OSError, ValueError):
    return None  # mid-replace race: treat as absent this window


# -- offline federation ------------------------------------------------------


def _read_host_tolerant(path: str, warnings: List[str], host: int
                        ) -> List[Dict[str, object]]:
  """One host's records, salvaging around interior corruption.

  ``read_telemetry`` raises on malformed interior lines — right for a
  single-stream tool, wrong for a fleet merge where one host's torn
  file must not blind the view of the others. Bad lines are skipped
  and counted into ``warnings`` instead.
  """
  records: List[Dict[str, object]] = []
  bad = 0
  for generation in telemetry_file.rotated_paths(path):
    if not os.path.exists(generation):
      continue
    try:
      with open(generation, encoding='utf-8') as f:
        lines = f.read().splitlines()
    except OSError as e:
      warnings.append('host {}: unreadable {}: {}'.format(
          host, generation, e))
      continue
    for index, line in enumerate(lines):
      if not line.strip():
        continue
      try:
        records.append(json.loads(line))
      except ValueError:
        if index == len(lines) - 1:
          continue  # torn tail from a killed writer: expected
        bad += 1
  if bad:
    warnings.append('host {}: skipped {} malformed interior line(s) in '
                    '{}'.format(host, bad, path))
  return records


def read_fleet(model_dir: str) -> Dict[str, object]:
  """Merged per-host view of one model_dir.

  ``{'hosts': {index: [records]}, 'heartbeats': {index: beat|None},
  'warnings': [...]}``. Hosts with a heartbeat but no telemetry (or
  vice versa) still appear — a partially-written host is evidence, not
  an error.
  """
  warnings: List[str] = []
  hosts: Dict[int, List[Dict[str, object]]] = {}
  heartbeats: Dict[int, Optional[Dict[str, object]]] = {}
  for host, files in sorted(telemetry_file.discover_hosts(model_dir).items()):
    heartbeats[host] = _read_heartbeat_path(files.get('heartbeat'))
    if files.get('telemetry'):
      records = _read_host_tolerant(files['telemetry'], warnings, host)
      for record in records:
        record.setdefault('process_index', host)
      hosts[host] = records
    else:
      warnings.append('host {}: heartbeat but no telemetry stream'.format(
          host))
      hosts[host] = []
  return {'hosts': hosts, 'heartbeats': heartbeats, 'warnings': warnings}


def merged_records(fleet: Dict[str, object]) -> List[Dict[str, object]]:
  """All hosts' records interleaved by wall-clock record time."""
  out: List[Dict[str, object]] = []
  for records in fleet['hosts'].values():
    out.extend(records)
  out.sort(key=lambda record: record.get('time', 0.0))
  return out


def align_train_series(fleet: Dict[str, object]) -> Dict[str, object]:
  """Per-host train series aligned by step.

  ``{'hosts': {index: {step: {'step_time_s', 'examples_per_sec',
  'productive'}}}, 'steps': [aligned steps], 'fleet_goodput':
  {step: min-across-hosts productive}}``. Aligned steps are those every
  host reported — the only windows where min-across-hosts is a fleet
  fact rather than a race.
  """
  series: Dict[int, Dict[int, Dict[str, object]]] = {}
  for host, records in fleet['hosts'].items():
    per_step: Dict[int, Dict[str, object]] = {}
    for record in records:
      if record.get('kind') != 'train' or record.get('step') is None:
        continue
      goodput = record.get('goodput') or {}
      per_step[int(record['step'])] = {
          'step_time_s': record.get('step_time_s'),
          'examples_per_sec': record.get('examples_per_sec'),
          'productive': goodput.get('productive'),
      }
    if per_step:
      series[host] = per_step
  steps: List[int] = []
  if series:
    common = set.intersection(*(set(s) for s in series.values()))
    steps = sorted(common)
  fleet_goodput: Dict[int, float] = {}
  for step in steps:
    productives = [series[host][step].get('productive')
                   for host in series]
    productives = [p for p in productives if p is not None]
    if productives:
      fleet_goodput[step] = min(productives)
  return {'hosts': series, 'steps': steps, 'fleet_goodput': fleet_goodput}


def dead_hosts(heartbeats: Dict[int, Optional[Dict[str, object]]],
               now: float, stale_secs: float = 300.0) -> List[int]:
  """Hosts whose heartbeat is stale while at least one other is fresh.

  Read-only by contract: routed through a THROWAWAY registry so a
  summary/doctor pass never inflates the live ``watchdog/anomalies``
  counters — counting is the live observer's side effect, not a
  digest's.
  """
  probe = FleetWatchdog(FleetConfig(heartbeat_stale_secs=stale_secs),
                        registry=registry_lib.TelemetryRegistry())
  return sorted(anomaly.detail['host']
                for anomaly in probe.check_heartbeats(heartbeats, now)
                if anomaly.kind == HOST_DEAD)


def fleet_summary(model_dir: str, now: Optional[float] = None,
                  stale_secs: float = 300.0) -> Dict[str, object]:
  """The offline fleet digest doctor / ``t2r_telemetry fleet`` render.

  Independent of the live FleetObserver: recomputed from the merged
  per-host streams + heartbeat files alone, so it works on any box that
  sees the filesystem.
  """
  if now is None:
    now = time.time()  # wall-clock: heartbeat ages
  fleet = read_fleet(model_dir)
  aligned = align_train_series(fleet)
  merged = merged_records(fleet)
  hosts: Dict[str, Dict[str, object]] = {}
  for host, records in sorted(fleet['hosts'].items()):
    beat = fleet['heartbeats'].get(host)
    trains = [r for r in records if r.get('kind') == 'train']
    last = trains[-1] if trains else {}
    goodput = last.get('goodput') or {}
    identity = next(
        (r for r in records if r.get('device_kind') is not None), {})
    hosts[str(host)] = {
        'hostname': (beat or {}).get('hostname') or last.get('hostname'),
        'device_kind': identity.get('device_kind'),
        'last_step': last.get('step'),
        'step_time_s': last.get('step_time_s'),
        'examples_per_sec': last.get('examples_per_sec'),
        'productive': goodput.get('productive'),
        'heartbeat_age_s': (float(now) - float(beat.get('time', 0.0))
                            if beat else None),
        'records': len(records),
    }
  last_aligned = aligned['steps'][-1] if aligned['steps'] else None
  skew = None
  gating_host = None
  if last_aligned is not None:
    times = {host: series[last_aligned].get('step_time_s')
             for host, series in aligned['hosts'].items()
             if series[last_aligned].get('step_time_s')}
    if times:
      gating_host = max(times, key=times.get)
      median = statistics.median(times.values())
      if median:
        skew = times[gating_host] / median
  anomaly_counts: Dict[str, int] = {}
  for record in merged:
    if record.get('kind') == 'anomaly':
      kind = str(record.get('anomaly'))
      anomaly_counts[kind] = anomaly_counts.get(kind, 0) + 1
  recoveries = [r for r in merged if r.get('kind') == 'recovery']
  # Elastic membership events (t2r.elastic.v1, ISSUE 15): the merged
  # cross-host view, so doctor's shrink-aware verdicts (host_dead
  # downgrade, stuck-rebuild) see the coordinator's ladder whichever
  # host is coordinating after a re-election.
  elastic_events = [r for r in merged if r.get('kind') == 'elastic']
  return {
      'host_count': len(fleet['hosts']),
      'hosts': hosts,
      'aligned_steps': len(aligned['steps']),
      'last_aligned_step': last_aligned,
      'step_time_skew': skew,
      'gating_host': gating_host,
      'fleet_min_goodput': (aligned['fleet_goodput'].get(last_aligned)
                            if last_aligned is not None else None),
      'dead_hosts': dead_hosts(fleet['heartbeats'], now,
                               stale_secs=stale_secs),
      'anomaly_counts': anomaly_counts,
      'recoveries': [{
          'preempted_step': r.get('preempted_step'),
          'resume_step': r.get('resume_step'),
          'preemption_recovery_seconds':
              r.get('preemption_recovery_seconds'),
          'phases': r.get('phases'),
          'process_index': r.get('process_index', 0),
          'world_before': r.get('world_before'),
          'world_after': r.get('world_after'),
      } for r in recoveries],
      'elastic_events': elastic_events,
      'warnings': fleet['warnings'],
  }


# -- recovery timeline (t2r.recovery.v1) -------------------------------------


def recovery_marker_path(model_dir: str,
                         process_index: Optional[int] = None) -> str:
  suffix = '' if not process_index else '.{}'.format(int(process_index))
  return os.path.join(model_dir, _RECOVERY_MARKER.format(suffix))


def write_recovery_marker(model_dir: str, step: int, signum: int,
                          save_seconds: float,
                          process_index: Optional[int] = None,
                          **extra) -> str:
  """Atomically records "a preemption just happened here".

  Written by the PREEMPTING process after its emergency save commits;
  consumed by the RESUMING process (usually a different pid, possibly a
  different host booting the same model_dir), which is why the stamp is
  wall-clock. ``save_seconds`` is the emergency save's duration — the
  first phase of the recovery timeline, measurable only on this side.
  ``extra`` fields ride the marker into the recovery record — the
  elastic coordinator stamps ``world_before``/``world_after``/
  ``departed`` here so a shrink's ``t2r.recovery.v1`` carries the world
  change (``build_recovery_record`` forwards them).
  """
  path = recovery_marker_path(model_dir, process_index)
  marker = {
      'time': time.time(),  # wall-clock: read by the resuming process
      'step': int(step),
      'signum': int(signum),
      'save_seconds': float(save_seconds),
      'process_index': int(process_index or 0),
  }
  marker.update(extra)
  tmp = path + '.tmp'
  with open(tmp, 'w', encoding='utf-8') as f:
    json.dump(marker, f)
  os.replace(tmp, path)
  return path


def consume_recovery_marker(model_dir: str,
                            process_index: Optional[int] = None
                            ) -> Optional[Dict[str, object]]:
  """Reads AND removes the pending-recovery marker (None when absent).

  Removal is the idempotence guard: one preemption yields exactly one
  recovery record, however many restarts follow.
  """
  path = recovery_marker_path(model_dir, process_index)
  if not os.path.exists(path):
    return None
  try:
    with open(path, encoding='utf-8') as f:
      marker = json.load(f)
  except (OSError, ValueError):
    marker = None  # torn marker: drop it rather than crash the resume
  try:
    os.remove(path)
  except OSError:
    pass
  return marker


def build_recovery_record(marker: Dict[str, object],
                          restore_seconds: float,
                          first_step_seconds: float,
                          resume_step: int,
                          now: Optional[float] = None
                          ) -> Dict[str, object]:
  """The ``t2r.recovery.v1`` payload for one preemption->resume cycle.

  Phases partition the timeline end to end:

    * ``emergency_save_s`` — preemption detected -> checkpoint committed
      (measured by the preempting process, carried via the marker);
    * ``downtime_s``       — process death -> resuming trainer starts
      restoring (scheduler wait + process boot; the remainder);
    * ``restore_s``        — checkpoint restore + mesh/state rebuild;
    * ``first_step_s``     — restore done -> first trained step lands.

  ``preemption_recovery_seconds`` is their sum BY CONSTRUCTION: every
  second between the preemption signal and the first productive step
  afterwards. The marker-to-now span is wall-clock across two
  processes (possibly two hosts), so under cross-host clock skew the
  locally-measured monotonic durations (restore + first step) are the
  floor — the span is clamped up to them rather than letting a
  behind-running resume clock underreport the outage and break the
  phases-sum-to-total invariant.
  """
  if now is None:
    now = time.time()  # wall-clock: spans two processes
  save_s = float(marker.get('save_seconds', 0.0))
  since_marker = max(float(now) - float(marker.get('time', now)), 0.0)
  measured = float(restore_seconds) + float(first_step_seconds)
  span = max(since_marker, measured)
  total = save_s + span
  downtime = span - measured
  record = {
      'schema': RECOVERY_SCHEMA,
      'preempted_step': marker.get('step'),
      'resume_step': int(resume_step),
      'signum': marker.get('signum'),
      'phases': {
          'emergency_save_s': save_s,
          'downtime_s': downtime,
          'restore_s': float(restore_seconds),
          'first_step_s': float(first_step_seconds),
      },
      'preemption_recovery_seconds': total,
  }
  # Elastic markers (ISSUE 15) stamp the world change at declaration
  # time; forwarding them here is what makes the recovery record carry
  # world_before/world_after without the resuming trainer knowing
  # anything about membership.
  for key in ('world_before', 'world_after', 'departed', 'elastic'):
    if key in marker:
      record[key] = marker[key]
  return record
