"""Structured trace reports: raw xplane capture -> forensics/<step>.json.

A profiler window that ends as an unread ``.xplane.pb`` proto answered
nothing. This module turns each capture into the report a human (or
``t2r_telemetry doctor``) actually wants, using only in-tree readers:

  * top-k op families by device time (`utils/xplane.py` — the round-5
    attribution machinery, now automated), with a host-executor fallback
    for captures without a TPU plane (CPU runs name their XLA thunks on
    ``tf_...`` executor thread lines);
  * device occupancy + host-vs-device overlap from event offsets (the
    idle-gap complement of goodput's host-side view);
  * collective counts/bytes from the compiled step's HLO
    (`parallel/hlo_analysis.py`), when the trainer can provide it;
  * the goodput split of the surrounding run with a ranked attribution
    ("lost to data 34% -> prefetch queue empty at sample time");
  * the registry counter delta across the capture window.

``build_report`` NEVER raises: every section degrades to a ``warnings``
entry on torn/truncated/ambiguous captures (tests/test_xplane.py drives
those paths), because it runs inside the trainer loop where an exception
would cost the training run a profiler bug was supposed to explain.

Report schema (``schema`` field, versioned): docs/observability.md.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

from tensor2robot_tpu.observability import registry as registry_lib

__all__ = ['FORENSICS_DIRNAME', 'REPORT_SCHEMA', 'build_report',
           'write_report', 'read_reports', 'find_latest_xplane',
           'attribute_goodput', 'split_collective_wait']

FORENSICS_DIRNAME = 'forensics'
REPORT_SCHEMA = 't2r.forensics.v1'
DEFAULT_TOP_K = 15

# Fractions below this are noise, not a diagnosis.
_ATTRIBUTION_FLOOR = 0.05


def find_latest_xplane(model_dir: str,
                       newer_than: Optional[float] = None) -> Optional[str]:
  """Newest ``*.xplane.pb`` under model_dir's profile plugin dir, or None.

  ``newer_than`` (st_mtime) filters out captures from EARLIER windows of
  the same run — stop_trace always writes a fresh file.
  """
  pattern = os.path.join(model_dir, 'plugins', 'profile', '**',
                         '*.xplane.pb')
  best: Tuple[float, Optional[str]] = (-1.0, None)
  for path in glob.glob(pattern, recursive=True):
    try:
      mtime = os.stat(path).st_mtime
    except OSError:
      continue
    if newer_than is not None and mtime < newer_than:
      continue
    if mtime > best[0]:
      best = (mtime, path)
  return best[1]


def _device_top_ops(xplane_path: str, n_steps: int, top_k: int):
  """(top_ops, occupancy, overlap, warnings, families) from one capture.

  Prefers the TPU ``XLA Ops`` line (serial device stream). A capture
  with several TPU planes (multi-chip) is narrowed to the first plane —
  summing across chips would multiply ms/step by the chip count — with a
  warning naming the unanalyzed planes. Captures without a TPU plane
  (CPU backend) fall back to the busiest ``tf_...`` executor thread line
  so auto-analysis still names the hot thunks.
  """
  from tensor2robot_tpu.utils import xplane

  warnings: List[str] = []
  top_ops: List[Dict[str, object]] = []
  occupancy = None
  overlap = None
  source = None
  try:
    families = xplane.op_families(xplane_path, n_steps=n_steps)
    source = 'device'
  except ValueError as e:
    if 'matches' not in str(e):
      raise
    # Multi-chip capture: analyze exactly one plane, loudly.
    plane_names = [name for name, _, _ in xplane.parse_xspace(xplane_path)
                   if 'TPU' in name]
    warnings.append('multi-plane capture ({}); analyzed {} only'.format(
        ', '.join(plane_names), plane_names[0]))
    families = xplane.op_families(xplane_path, n_steps=n_steps,
                                  plane_substr=plane_names[0])
    source = 'device'
  stats = xplane.line_stats(xplane_path)
  if not families:
    # No TPU plane (CPU run): the executor thread lines hold the thunks.
    executor = [s for s in stats if str(s['line']).startswith('tf_')]
    if executor:
      busiest = max(executor, key=lambda s: s['busy_ms'])
      totals: Dict[str, float] = {}
      for name, lines, metadata in xplane.parse_xspace(xplane_path):
        if name != busiest['plane']:
          continue
        for line_name, events in lines:
          if line_name != busiest['line']:
            continue
          for metadata_id, duration_ps, _ in events:
            key = metadata.get(metadata_id, str(metadata_id))
            totals[key] = totals.get(key, 0.0) + duration_ps / 1e9 / n_steps
      families = sorted(totals.items(), key=lambda kv: -kv[1])
      source = 'host_executor'
      warnings.append('no TPU plane in capture; op times come from host '
                      'executor line {!r}'.format(busiest['line']))
  if families:
    total_ms = sum(ms for _, ms in families)
    top_ops = [{'name': name, 'ms_per_step': ms,
                'fraction': (ms / total_ms) if total_ms else 0.0,
                'source': source}
               for name, ms in families[:top_k]]
  # Occupancy of the analyzed serial line + host-vs-device overlap.
  device_lines = [s for s in stats
                  if (s['line'] == 'XLA Ops' and 'TPU' in str(s['plane']))
                  or (source == 'host_executor'
                      and str(s['line']).startswith('tf_'))]
  if device_lines:
    busiest = max(device_lines, key=lambda s: s['busy_ms'])
    occupancy = dict(busiest)
    host_lines = [s for s in stats if s['line'] == 'python']
    if host_lines:
      host = max(host_lines, key=lambda s: s['busy_ms'])
      extent = max(busiest['extent_ms'], 1e-9)
      overlap = {
          'device_busy_ms': busiest['busy_ms'],
          'device_extent_ms': busiest['extent_ms'],
          # Device idle inside its own active window == time the host
          # failed to keep it fed (dispatch gaps, data waits).
          'device_idle_fraction': 1.0 - min(
              busiest['busy_ms'] / extent, 1.0),
          'host_line_events': host['events'],
      }
  if not top_ops:
    warnings.append('capture held no attributable op events')
  return top_ops, occupancy, overlap, warnings, families


_COLLECTIVE_TOKENS = ('all-reduce', 'all-gather', 'all-to-all',
                      'collective-permute', 'reduce-scatter',
                      'collective-broadcast')


def _collective_kind(op_family: str) -> Optional[str]:
  """The collective kind an op family name carries, or None for compute."""
  for token in _COLLECTIVE_TOKENS:
    if token in op_family:
      return token
  return None


def split_collective_wait(families: List[Tuple[str, float]],
                          hlo_collectives: Optional[List[Dict[str, object]]]
                          = None) -> Dict[str, object]:
  """Device time split: compute vs. time spent inside collectives.

  ``families`` is the capture's full [(op family, ms/step)] table. A
  collective op's device time is transfer PLUS the wait for every
  other participant to arrive — which is exactly why this is the fleet
  straggler's signature: on the straggling host the step is long in
  COMPUTE, on every other host it is long in collective-wait. The
  fraction here, read per host across a fleet's captures, names which
  hosts waited and which one they waited for; ``gating_collective`` is
  the collective family that burned the most device time.
  ``hlo_collectives`` (``hlo_analysis.collective_ops``) attaches the
  per-step payload bytes each named collective moves.
  """
  hlo_bytes: Dict[str, int] = {}
  hlo_kind_bytes: Dict[str, int] = {}
  for op in hlo_collectives or []:
    family = '%' + _FAMILY_SUFFIX_RE.sub('', str(op.get('name', '')))
    hlo_bytes[family] = hlo_bytes.get(family, 0) + int(op.get('bytes', 0))
    kind = str(op.get('kind', ''))
    hlo_kind_bytes[kind] = hlo_kind_bytes.get(kind, 0) + \
        int(op.get('bytes', 0))
  compute_ms = 0.0
  collectives: List[Dict[str, object]] = []
  for name, ms in families:
    kind = _collective_kind(name)
    if kind is None:
      compute_ms += ms
      continue
    nbytes = hlo_bytes.get(name)
    if nbytes is None:
      # '-start' device events vs sync HLO names (or vice versa): fall
      # back to the kind's total payload as the best available figure.
      nbytes = hlo_kind_bytes.get(kind)
    collectives.append({'name': name, 'kind': kind, 'ms_per_step': ms,
                        'bytes': nbytes})
  collective_ms = sum(c['ms_per_step'] for c in collectives)
  total = compute_ms + collective_ms
  collectives.sort(key=lambda c: -c['ms_per_step'])
  for entry in collectives:
    entry['fraction'] = (entry['ms_per_step'] / total) if total else 0.0
  return {
      'compute_ms_per_step': compute_ms,
      'collective_ms_per_step': collective_ms,
      'collective_wait_fraction': (collective_ms / total) if total else 0.0,
      'collectives': collectives,
      'gating_collective': collectives[0]['name'] if collectives else None,
  }


_FAMILY_SUFFIX_RE = re.compile(r'\.\d+$')


def attribute_goodput(fractions: Dict[str, float],
                      scalars: Dict[str, float]
                      ) -> List[Dict[str, object]]:
  """Ranked non-productive goodput categories with evidence.

  ``fractions`` from ``GoodputTracker.fractions()``; ``scalars`` from
  ``TelemetryRegistry.scalars()`` — pure inputs so doctor can reuse this
  on telemetry.jsonl records without a live registry.
  """
  out: List[Dict[str, object]] = []
  lost = sorted(((cat, frac) for cat, frac in fractions.items()
                 if cat != 'productive' and frac >= _ATTRIBUTION_FLOOR),
                key=lambda kv: -kv[1])
  for category, fraction in lost:
    detail = ''
    if category == 'data':
      p95 = scalars.get('span/data.next/p95')
      depths = [(tag, value) for tag, value in scalars.items()
                if tag.startswith('data/prefetch_queue_depth')]
      parts = []
      if p95 is not None:
        parts.append('span/data.next p95 {:.1f} ms'.format(p95))
      if depths:
        if all(value <= 0.0 for _, value in depths):
          parts.append('prefetch queue empty at sample time: host decode '
                       'is the bottleneck')
        else:
          parts.append('prefetch depth ' + ', '.join(
              '{}={:g}'.format(tag.rsplit('/', 1)[-1], value)
              for tag, value in depths))
      detail = '; '.join(parts)
    elif category == 'checkpoint':
      p95 = scalars.get('span/ckpt.save/p95')
      count = scalars.get('span/ckpt.save/count')
      if p95 is not None:
        detail = 'span/ckpt.save p95 {:.1f} ms over {:g} saves'.format(
            p95, count or 0)
    elif category == 'retry':
      parts = []
      for tag, label in (('reliability/nan_rollbacks', 'nan rollbacks'),
                         ('reliability/preemptions', 'preemptions')):
        value = scalars.get(tag, 0.0)
        if value:
          parts.append('{} {:g}'.format(label, value))
      retries = sum(value for tag, value in scalars.items()
                    if tag.startswith('reliability/io_retries'))
      if retries:
        parts.append('io retries {:g}'.format(retries))
      detail = ', '.join(parts)
    out.append({'category': category, 'fraction': fraction,
                'detail': detail})
  return out


def build_report(step: int,
                 reason: str = 'static',
                 trigger: Optional[Dict[str, object]] = None,
                 window: Optional[Dict[str, object]] = None,
                 xplane_path: Optional[str] = None,
                 n_steps: int = 1,
                 hlo_text_fn: Optional[Callable[[], Optional[str]]] = None,
                 goodput_fractions: Optional[Dict[str, float]] = None,
                 counters_delta: Optional[Dict[str, float]] = None,
                 registry: Optional[registry_lib.TelemetryRegistry] = None,
                 tuned_config: Optional[str] = None,
                 pipeline: Optional[Dict[str, object]] = None,
                 host: Optional[Dict[str, object]] = None
                 ) -> Dict[str, object]:
  """Assembles the forensics report dict. Never raises: torn captures,
  missing HLO, or reader bugs each degrade to a ``warnings`` entry.

  ``tuned_config``: the active compile-config id (tuning/), or None for
  the stock compile — carried verbatim so a step-time regression is
  attributable to the config that compiled the step it profiled.
  ``pipeline``: the latest ``t2r.pipeline.v1`` X-ray record (stage
  capacity table + gating-stage attribution), carried verbatim so a
  data-path incident's report names the stage, not just the symptom.
  ``host``: this process's fleet identity (``signals.host_identity()``)
  — with the ``collective_wait`` split below, a straggler capture names
  WHICH host gated WHICH collective, not just that a step got slow."""
  registry = registry or registry_lib.get_registry()
  warnings: List[str] = []
  report: Dict[str, object] = {
      'schema': REPORT_SCHEMA,
      'step': int(step),
      'reason': reason,
      'trigger': dict(trigger or {}),
      'window': dict(window or {}),
      'xplane_path': xplane_path,
      'host': dict(host) if host else None,
      'top_ops': [],
      'device_occupancy': None,
      'host_device_overlap': None,
      'collectives': {},
      'collective_bytes_total': 0,
      'collective_wait': None,
      'goodput': dict(goodput_fractions or {}),
      'attribution': [],
      'counters_delta': dict(counters_delta or {}),
      'memory': {},
      'tuned_config': tuned_config,
      'pipeline': dict(pipeline) if pipeline else None,
      'roofline': None,
      'warnings': warnings,
  }
  try:
    scalars = registry.scalars()
  except Exception as e:  # noqa: BLE001
    scalars = {}
    warnings.append('registry scalars unavailable: {}'.format(e))
  families: List[Tuple[str, float]] = []
  if xplane_path is None:
    warnings.append('no xplane capture found for this window')
  else:
    try:
      top_ops, occupancy, overlap, op_warnings, families = \
          _device_top_ops(xplane_path, max(n_steps, 1), DEFAULT_TOP_K)
      report['top_ops'] = top_ops
      report['device_occupancy'] = occupancy
      report['host_device_overlap'] = overlap
      warnings.extend(op_warnings)
    except Exception as e:  # noqa: BLE001 — torn/truncated capture
      warnings.append('xplane analysis failed ({}: {}); raw capture kept '
                      'at {}'.format(type(e).__name__, e, xplane_path))
  hlo_collectives = None
  hlo_text = None
  if hlo_text_fn is not None:
    try:
      hlo_text = hlo_text_fn()
      if hlo_text:
        from tensor2robot_tpu.parallel import hlo_analysis
        stats = hlo_analysis.collective_stats(hlo_text)
        report['collectives'] = stats
        report['collective_bytes_total'] = \
            hlo_analysis.total_collective_bytes(stats)
        hlo_collectives = hlo_analysis.collective_ops(hlo_text)
    except Exception as e:  # noqa: BLE001 — HLO is best-effort evidence
      warnings.append('collective analysis failed: {}'.format(e))
  if hlo_text:
    # Roofline attribution (t2r.roofline.v1): join the capture's
    # measured op-family ms with the per-family FLOPs/bytes cost table
    # parsed from the same program's post-opt HLO. Works even when the
    # capture produced no families (record carries costs, all
    # unattributed) — the step's intensity profile is evidence either
    # way. MFU/bandwidth headlines come from the live gauges the
    # trainer publishes from the SAME shared cost model.
    try:
      from tensor2robot_tpu.observability import roofline as roofline_lib
      from tensor2robot_tpu.parallel import hlo_analysis
      record = roofline_lib.build_record(
          families,
          hlo_analysis.op_cost_table(hlo_text),
          str((host or {}).get('device_kind', 'unknown')),
          step=int(step),
          cost_source='hlo_parse')
      for key, gauge in (('mfu', roofline_lib.MFU_GAUGE),
                         ('hbm_bw_util', roofline_lib.HBM_BW_GAUGE)):
        if record.get(key) is None and scalars.get(gauge):
          record[key] = scalars[gauge]
      report['roofline'] = record
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
      warnings.append('roofline attribution failed: {}'.format(e))
  if families:
    try:
      report['collective_wait'] = split_collective_wait(
          families, hlo_collectives)
    except Exception as e:  # noqa: BLE001
      warnings.append('collective-wait split failed: {}'.format(e))
  try:
    report['attribution'] = attribute_goodput(
        report['goodput'], scalars)
  except Exception as e:  # noqa: BLE001
    warnings.append('goodput attribution failed: {}'.format(e))
  report['memory'] = {tag: value for tag, value in scalars.items()
                      if tag.startswith('memory/')}
  return report


def write_report(model_dir: str, step: int,
                 report: Dict[str, object]) -> str:
  """Atomically writes ``forensics/<step>.json``; returns the path."""
  directory = os.path.join(model_dir, FORENSICS_DIRNAME)
  os.makedirs(directory, exist_ok=True)
  path = os.path.join(directory, '{}.json'.format(int(step)))
  tmp = path + '.tmp'
  with open(tmp, 'w', encoding='utf-8') as f:
    json.dump(report, f, indent=2, sort_keys=True)
  os.replace(tmp, path)
  return path


def read_reports(model_dir: str) -> List[Tuple[int, Dict[str, object]]]:
  """All forensics reports under model_dir, sorted by step ascending.

  Unreadable/malformed report files are skipped (a doctor run must not
  die on one torn report), not raised.
  """
  directory = os.path.join(model_dir, FORENSICS_DIRNAME)
  out: List[Tuple[int, Dict[str, object]]] = []
  if not os.path.isdir(directory):
    return out
  for name in os.listdir(directory):
    base, ext = os.path.splitext(name)
    if ext != '.json':
      continue
    try:
      step = int(base)
      with open(os.path.join(directory, name), encoding='utf-8') as f:
        out.append((step, json.load(f)))
    except (ValueError, OSError):
      continue
  out.sort(key=lambda pair: pair[0])
  return out
