"""Trainer harness: mesh-sharded jitted train/eval loops + checkpointing."""

from tensor2robot_tpu.trainer.checkpointing import (
    CheckpointManager,
    checkpoints_iterator,
    create_warm_start_fn,
    latest_checkpoint_step,
)
from tensor2robot_tpu.trainer.train_eval import (
    Trainer,
    provide_input_generator_with_model_information,
    train_eval_model,
)

__all__ = [
    'CheckpointManager',
    'Trainer',
    'checkpoints_iterator',
    'create_warm_start_fn',
    'latest_checkpoint_step',
    'provide_input_generator_with_model_information',
    'train_eval_model',
]
