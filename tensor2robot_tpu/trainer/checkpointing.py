"""Checkpoint management: async Orbax save/restore with the reference's
retention and warm-start semantics.

Parity targets:
  * TF1 Saver registered in SAVERS + keep policy
    (/root/reference/models/abstract_model.py:782-793,:84-85)
  * async checkpointing via AsyncCheckpointSaverHook
    (/root/reference/hooks/async_export_hook_builder.py:128)
  * warm start / partial restore from a foreign checkpoint
    (/root/reference/models/abstract_model.py:88-118,:372-381)
  * eval-vs-GC race protection by snapshotting checkpoints
    (/root/reference/utils/train_eval.py:599-667)
  * continuous-eval checkpoints_iterator (/root/reference/utils/train_eval.py:570)

Orbax gives us atomic directory commits, so the reference's tmp-file
detection heuristics collapse to "is the step committed"; the polling
loops survive because robot-side consumers still discover checkpoints by
watching the filesystem (SURVEY.md §2.9 'filesystem as transport').
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterator, Optional, Sequence

import jax
import numpy as np
import orbax.checkpoint as ocp

CHECKPOINT_SUBDIR = 'checkpoints'


class CheckpointManager:
  """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

  def __init__(self,
               model_dir: str,
               keep_checkpoint_max: int = 5,
               save_interval_steps: int = 1,
               async_checkpoints: bool = True,
               best_fn: Optional[Callable[[Any], float]] = None,
               best_mode: str = 'min'):
    """Args mirror the reference's gin-exposed Saver/RunConfig knobs.

    Args:
      model_dir: root run directory; checkpoints live in
        ``<model_dir>/checkpoints``.
      keep_checkpoint_max: retention count (ref abstract_model.py:84).
      save_interval_steps: dedupe interval enforced by orbax.
      async_checkpoints: background commit thread — the
        AsyncCheckpointSaverHook equivalent.
      best_fn: optional metrics -> scalar for best-checkpoint retention.
      best_mode: 'min' | 'max'.
    """
    self.directory = os.path.join(model_dir, CHECKPOINT_SUBDIR)
    options = ocp.CheckpointManagerOptions(
        max_to_keep=keep_checkpoint_max,
        save_interval_steps=save_interval_steps,
        enable_async_checkpointing=async_checkpoints,
        best_fn=best_fn,
        best_mode=best_mode,
        create=True,
    )
    self._manager = ocp.CheckpointManager(self.directory, options=options)

  def save(self, step: int, state, metrics: Optional[dict] = None,
           force: bool = False) -> bool:
    return self._manager.save(
        int(step), args=ocp.args.StandardSave(state), metrics=metrics,
        force=force)

  def restore(self, state_template, step: Optional[int] = None):
    """Restores into the structure/shardings of ``state_template``.

    ``state_template`` may be a concrete pytree or one of
    ``jax.ShapeDtypeStruct`` leaves (from ``jax.eval_shape``).
    """
    if step is None:
      step = self.latest_step()
    if step is None:
      raise FileNotFoundError(
          'No checkpoint found in {}.'.format(self.directory))
    return self._manager.restore(
        int(step), args=ocp.args.StandardRestore(state_template))

  def latest_step(self) -> Optional[int]:
    return self._manager.latest_step()

  def reload(self) -> None:
    """Re-reads the step list from disk.

    Orbax caches the step list at construction; a concurrent trainer
    process writing checkpoints (the continuous-eval topology,
    ref train_eval.py:570) is invisible without this.
    """
    self._manager.reload()

  def all_steps(self) -> Sequence[int]:
    return sorted(self._manager.all_steps())

  def wait_until_finished(self) -> None:
    self._manager.wait_until_finished()

  def close(self) -> None:
    self._manager.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def latest_checkpoint_step(model_dir: str) -> Optional[int]:
  """Newest committed checkpoint step under model_dir, or None."""
  directory = os.path.join(model_dir, CHECKPOINT_SUBDIR)
  if not os.path.isdir(directory):
    return None
  # Orbax commits atomically by renaming; a bare numeric dir is live
  # (in-flight saves have an .orbax-checkpoint-tmp suffix and fail isdigit).
  steps = [int(name) for name in os.listdir(directory) if name.isdigit()]
  return max(steps) if steps else None


def checkpoints_iterator(model_dir: str,
                         timeout_secs: float = 600.0,
                         min_interval_secs: float = 1.0,
                         stop_fn: Optional[Callable[[], bool]] = None
                         ) -> Iterator[int]:
  """Yields new checkpoint steps as they appear (ref train_eval.py:570).

  Terminates when no new checkpoint arrives within ``timeout_secs`` or
  ``stop_fn`` returns True.
  """
  last_step = None
  deadline = time.time() + timeout_secs
  while True:
    if stop_fn is not None and stop_fn():
      return
    step = latest_checkpoint_step(model_dir)
    if step is not None and step != last_step:
      last_step = step
      deadline = time.time() + timeout_secs
      yield step
      continue
    if time.time() > deadline:
      return
    time.sleep(min_interval_secs)


# -- warm start -------------------------------------------------------------


def create_warm_start_fn(checkpoint_dir: str,
                         step: Optional[int] = None,
                         include: Optional[Callable[[str], bool]] = None):
  """Returns params -> params merging values restored from a foreign run.

  The JAX form of ``default_init_from_checkpoint_fn``'s partial restore
  (/root/reference/models/abstract_model.py:88-118): leaves present in the
  checkpoint under the same tree path (and passing ``include`` on the
  '/'-joined path) replace freshly-initialized values; everything else
  keeps its init. Shape mismatches are skipped, matching the reference's
  tolerance for evolving label spaces.
  """

  def warm_start(params):
    manager = CheckpointManager(checkpoint_dir, async_checkpoints=False)
    try:
      restore_step = step if step is not None else manager.latest_step()
      if restore_step is None:
        raise FileNotFoundError(
            'No checkpoint to warm start from in {}.'.format(checkpoint_dir))
      restored = manager.restore(None, step=restore_step)
    finally:
      manager.close()
    if isinstance(restored, dict) and 'params' in restored:
      restored = restored['params']

    flat_restored = _flatten_with_paths(restored)
    flat_params, treedef = jax.tree_util.tree_flatten_with_path(params)
    merged = []
    for path, value in flat_params:
      key = _path_str(path)
      candidate = flat_restored.get(key)
      if candidate is not None and (include is None or include(key)):
        if np.shape(candidate) == np.shape(value):
          value = jax.numpy.asarray(candidate, dtype=value.dtype)
      merged.append(value)
    return jax.tree_util.tree_unflatten(treedef, merged)

  return warm_start


def _path_str(path) -> str:
  parts = []
  for entry in path:
    if hasattr(entry, 'key'):
      parts.append(str(entry.key))
    elif hasattr(entry, 'idx'):
      parts.append(str(entry.idx))
    else:
      parts.append(str(entry))
  return '/'.join(parts)


def _flatten_with_paths(tree) -> dict:
  flat, _ = jax.tree_util.tree_flatten_with_path(tree)
  return {_path_str(path): value for path, value in flat}
