"""Checkpoint management: async Orbax save/restore with the reference's
retention and warm-start semantics.

Parity targets:
  * TF1 Saver registered in SAVERS + keep policy
    (/root/reference/models/abstract_model.py:782-793,:84-85)
  * async checkpointing via AsyncCheckpointSaverHook
    (/root/reference/hooks/async_export_hook_builder.py:128)
  * warm start / partial restore from a foreign checkpoint
    (/root/reference/models/abstract_model.py:88-118,:372-381)
  * eval-vs-GC race protection by snapshotting checkpoints
    (/root/reference/utils/train_eval.py:599-667)
  * continuous-eval checkpoints_iterator (/root/reference/utils/train_eval.py:570)

Orbax gives us atomic directory commits, so the reference's tmp-file
detection heuristics collapse to "is the step committed"; the polling
loops survive because robot-side consumers still discover checkpoints by
watching the filesystem (SURVEY.md §2.9 'filesystem as transport').
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Iterator, Optional, Sequence

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensor2robot_tpu.observability import span
from tensor2robot_tpu.reliability import fault_injection
from tensor2robot_tpu.reliability.errors import CorruptCheckpointError
from tensor2robot_tpu.reliability.logutil import log_warning as _log
from tensor2robot_tpu.reliability.retry import RetryPolicy, retry

CHECKPOINT_SUBDIR = 'checkpoints'

# Shared default for checkpoint I/O: 3 attempts, ~0.05/0.1s backoff. Kept
# short — checkpoint saves sit on the training hot loop, and a filesystem
# that stays down for longer than this should fail the run (RetryError)
# rather than stall it silently.
DEFAULT_CKPT_RETRY = RetryPolicy(max_attempts=3, base_delay_secs=0.05)

# Version of the in-checkpoint parameter LAYOUT (not the tree structure).
# Layout changes are shape-compatible but numerically incompatible — a
# silent restore would produce scrambled math — so the version is written
# next to the checkpoints and verified on restore. History:
#   2: transformer qkv columns head-major ([H, 3, Dh] groups, was
#      q|k|v-major) and pipelined pipe_blocks leaves [S, k, ...] (was
#      [L, ...]); layers/transformer.py round 4.
PARAM_LAYOUT_VERSION = 2
_FORMAT_FILENAME = 'format.json'


class CheckpointManager:
  """Thin wrapper over ocp.CheckpointManager for TrainState pytrees."""

  def __init__(self,
               model_dir: str,
               keep_checkpoint_max: int = 5,
               save_interval_steps: int = 1,
               async_checkpoints: bool = True,
               best_fn: Optional[Callable[[Any], float]] = None,
               best_mode: str = 'min',
               assume_param_layout: Optional[int] = None,
               retry_policy: Optional[RetryPolicy] = None,
               quarantine_damaged: bool = True):
    """Args mirror the reference's gin-exposed Saver/RunConfig knobs.

    Args:
      model_dir: root run directory; checkpoints live in
        ``<model_dir>/checkpoints``.
      keep_checkpoint_max: retention count (ref abstract_model.py:84).
      save_interval_steps: dedupe interval enforced by orbax.
      async_checkpoints: background commit thread — the
        AsyncCheckpointSaverHook equivalent.
      best_fn: optional metrics -> scalar for best-checkpoint retention.
      best_mode: 'min' | 'max'.
      assume_param_layout: the user's explicit assertion of the LAYOUT
        version of pre-marker checkpoints in this directory (the marker
        only exists from round 5 on, so an unmarked directory is
        ambiguous between the current layout and older ones). Passing
        the current ``PARAM_LAYOUT_VERSION`` stamps the marker and lets
        the run resume; any other value (or None, the default) keeps
        the loud failure.
      retry_policy: backoff policy for transient save/restore failures
        (flaky NFS/GCS); None uses DEFAULT_CKPT_RETRY. Non-transient
        errors (layout mismatch, bad template) propagate immediately.
      quarantine_damaged: rename visibly damaged step dirs aside
        (``<step>.corrupt``) when a restore trips over them. Only the
        manager that OWNS the directory (the trainer's) should do this;
        read-only consumers (predictors, warm starts) pass False so a
        polling reader never mutates a live training directory.
    """
    self._assume_param_layout = assume_param_layout
    self._retry_policy = retry_policy or DEFAULT_CKPT_RETRY
    self._quarantine_damaged = quarantine_damaged
    self._keep_checkpoint_max = keep_checkpoint_max
    self.directory = os.path.join(model_dir, CHECKPOINT_SUBDIR)
    self._options = ocp.CheckpointManagerOptions(
        max_to_keep=keep_checkpoint_max,
        save_interval_steps=save_interval_steps,
        enable_async_checkpointing=async_checkpoints,
        best_fn=best_fn,
        best_mode=best_mode,
        create=True,
    )
    self._manager = ocp.CheckpointManager(self.directory,
                                          options=self._options)

  def save(self, step: int, state, metrics: Optional[dict] = None,
           force: bool = False) -> bool:
    # Marker I/O hits the same flaky mount as the checkpoint itself:
    # retry it too. (Its deterministic ValueErrors are not retryable and
    # pass straight through.)
    retry(self._write_format_marker, self._retry_policy,
          site=fault_injection.SITE_CKPT_SAVE)

    def _save():
      fault_injection.maybe_fail(fault_injection.SITE_CKPT_SAVE)
      return self._manager.save(
          int(step), args=ocp.args.StandardSave(state), metrics=metrics,
          force=force)

    # The span holds only the SYNCHRONOUS portion; with async
    # checkpointing the background commit is invisible here (the trainer
    # sees it at wait_until_finished).
    with span('ckpt.save'):
      return retry(_save, self._retry_policy,
                   site=fault_injection.SITE_CKPT_SAVE)

  def restore(self, state_template, step: Optional[int] = None):
    """Restores into the structure/shardings of ``state_template``.

    ``state_template`` may be a concrete pytree or one of
    ``jax.ShapeDtypeStruct`` leaves (from ``jax.eval_shape``).
    """
    if step is None:
      step = self.latest_step()
    if step is None:
      raise FileNotFoundError(
          'No checkpoint found in {}.'.format(self.directory))
    retry(self._check_format_marker, self._retry_policy,
          site=fault_injection.SITE_CKPT_RESTORE)

    def _restore():
      fault_injection.maybe_fail(fault_injection.SITE_CKPT_RESTORE)
      return self._manager.restore(
          int(step), args=ocp.args.StandardRestore(state_template))

    try:
      with span('ckpt.restore'):
        return retry(_restore, self._retry_policy,
                     site=fault_injection.SITE_CKPT_RESTORE)
    except (ValueError, KeyError) as e:
      # Orbax reports a half-written or GC-gutted step dir as assorted
      # ValueErrors ('Must provide args of type Composite...') — these
      # are non-retryable, so they arrive here after the FIRST attempt
      # (a damaged dir does not get better with backoff). When a step is
      # visibly damaged on disk, quarantine it (rename aside — a damaged
      # dir also poisons the manager's item-layout inference for EVERY
      # step) and reclassify as CorruptCheckpointError so skip layers
      # can ride it out; a ValueError with all checkpoints intact (bad
      # template, layout mismatch) stays fatal.
      damage = self._step_damage(int(step))
      if damage is not None:
        self._quarantine_damaged_step(int(step), damage)
        raise CorruptCheckpointError(self.directory, int(step),
                                     damage) from e
      damaged_other = []
      for s in self._on_disk_steps():
        other_damage = self._step_damage(s)
        if other_damage is not None:
          damaged_other.append((s, other_damage))
      if damaged_other:
        # The requested step is intact; a DIFFERENT damaged step poisoned
        # the manager's construction-time item-layout inference. Clean up
        # (owner only), then read the requested step directly, bypassing
        # the poisoned manager — an intact newest checkpoint must never
        # be skipped because an older one is damaged.
        for other, other_damage in damaged_other:
          self._quarantine_damaged_step(other, other_damage)
        try:
          return self._restore_step_direct(int(step), state_template)
        except Exception as direct_error:  # noqa: BLE001 — reclassified
          raise CorruptCheckpointError(
              self.directory, damaged_other[0][0],
              damaged_other[0][1] + ' (poisoned the restore of step {}; '
              'direct read also failed: {})'.format(
                  step, direct_error)) from e
      raise

  def _restore_step_direct(self, step: int, state_template):
    """Reads one step's 'default' item without the (poisoned) manager."""
    item_dir = os.path.join(self.directory, str(step), 'default')
    checkpointer = ocp.StandardCheckpointer()
    try:
      return checkpointer.restore(item_dir, target=state_template)
    finally:
      checkpointer.close()

  def _on_disk_steps(self):
    if not os.path.isdir(self.directory):
      return []
    return sorted(int(name) for name in os.listdir(self.directory)
                  if name.isdigit())

  def _step_damage(self, step: int) -> Optional[str]:
    """Describes visible on-disk damage for ``step``, or None if intact.

    Conservative on purpose: only conditions an atomically-committed orbax
    step can never exhibit (missing/empty dir, no _CHECKPOINT_METADATA)
    count as damage — they arise from retention GC or a crashed commit.
    """
    step_dir = os.path.join(self.directory, str(step))
    if not os.path.isdir(step_dir):
      return 'step directory missing'
    entries = os.listdir(step_dir)
    if not entries:
      return 'step directory empty'
    if '_CHECKPOINT_METADATA' not in entries:
      return 'checkpoint metadata missing'
    return None

  def _quarantine_damaged_step(self, step: int, damage: str) -> None:
    """Renames a damaged step dir aside and rebuilds the orbax manager.

    The rename (never a delete — the bytes stay for forensics) both stops
    pollers from rediscovering the broken step and un-poisons orbax's
    construction-time item-layout inference; the rebuild makes the fresh
    layout visible to this manager. No-op unless this manager owns the
    directory (``quarantine_damaged``) — a read-only consumer must not
    mutate a training run's files out from under the trainer.
    """
    if not self._quarantine_damaged:
      return
    src = os.path.join(self.directory, str(step))
    if os.path.isdir(src):
      dest = src + '.corrupt'
      suffix = 1
      while os.path.exists(dest):
        dest = '{}.corrupt{}'.format(src, suffix)
        suffix += 1
      try:
        os.replace(src, dest)
        _log('Quarantined damaged checkpoint step %d (%s): %s -> %s',
             step, damage, src, dest)
      except OSError as e:
        _log('Could not quarantine damaged checkpoint %s: %s', src, e)
        return
    try:
      self._manager.close()
    except Exception as e:  # noqa: BLE001 — already on the failure path
      _log('Closing poisoned checkpoint manager failed: %s', e)
    self._manager = ocp.CheckpointManager(self.directory,
                                          options=self._options)

  def _stamp_marker(self) -> None:
    path = os.path.join(self.directory, _FORMAT_FILENAME)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump({'param_layout_version': PARAM_LAYOUT_VERSION}, f)
    os.replace(tmp, path)

  def _unmarked_steps(self):
    if not os.path.isdir(self.directory):
      return []
    if os.path.exists(os.path.join(self.directory, _FORMAT_FILENAME)):
      return []
    return sorted(int(name) for name in os.listdir(self.directory)
                  if name.isdigit())

  def _write_format_marker(self) -> None:
    path = os.path.join(self.directory, _FORMAT_FILENAME)
    if os.path.exists(path):
      return
    # An UNMARKED directory with checkpoints is ambiguous: the marker
    # only exists from round 5 on, so those steps may be the current
    # layout (round-4 builds) or an older one. Stamping the current
    # version over them would let a later restore of old-layout params
    # pass silently — refuse unless the caller asserts the layout.
    existing = self._unmarked_steps()
    if existing and self._assume_param_layout != PARAM_LAYOUT_VERSION:
      raise ValueError(
          'Checkpoint dir {} holds pre-marker checkpoints (steps {}) of '
          'UNKNOWN param layout. If they were written by a build with '
          'layout version {} (head-major qkv, [S, k] pipe_blocks), pass '
          'CheckpointManager(..., assume_param_layout={}) to stamp the '
          'marker and resume; otherwise migrate or clear the directory.'
          .format(self.directory, existing[:5], PARAM_LAYOUT_VERSION,
                  PARAM_LAYOUT_VERSION))
    self._stamp_marker()

  def _check_format_marker(self) -> None:
    """Fail loudly on checkpoints with an older/unknown parameter layout.

    Shape-compatible layout changes (see PARAM_LAYOUT_VERSION) restore
    without error but scramble the numerics; the marker turns that into
    an actionable exception instead. ``assume_param_layout`` is the
    explicit escape hatch for pre-marker directories whose layout the
    user knows.
    """
    path = os.path.join(self.directory, _FORMAT_FILENAME)
    if not os.path.exists(path):
      if self._assume_param_layout == PARAM_LAYOUT_VERSION:
        self._stamp_marker()
        return
      raise ValueError(
          'Checkpoint dir {} has no {} marker: its param layout is '
          'unknown (the marker exists from round 5 on). If these '
          'checkpoints were written with layout version {} (head-major '
          'qkv columns, [S, k] pipe_blocks), pass '
          'CheckpointManager(..., assume_param_layout={}) to proceed; '
          'older-layout checkpoints restore shape-compatibly but '
          'numerically SCRAMBLED — re-train or migrate those.'
          .format(self.directory, _FORMAT_FILENAME, PARAM_LAYOUT_VERSION,
                  PARAM_LAYOUT_VERSION))
    with open(path) as f:
      version = json.load(f).get('param_layout_version')
    if version != PARAM_LAYOUT_VERSION:
      raise ValueError(
          'Checkpoint dir {} has param-layout version {} but this build '
          'expects {}; restoring would scramble parameters. Re-train or '
          'migrate.'.format(self.directory, version, PARAM_LAYOUT_VERSION))

  def latest_step(self) -> Optional[int]:
    return self._manager.latest_step()

  def reload(self) -> None:
    """Re-reads the step list from disk.

    Orbax caches the step list at construction; a concurrent trainer
    process writing checkpoints (the continuous-eval topology,
    ref train_eval.py:570) is invisible without this.
    """
    self._manager.reload()

  def all_steps(self) -> Sequence[int]:
    return sorted(self._manager.all_steps())

  def wait_until_finished(self) -> None:
    self._manager.wait_until_finished()

  def close(self) -> None:
    self._manager.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def all_checkpoint_steps(model_dir: str) -> list:
  """All committed checkpoint steps under model_dir, newest first.

  Orbax commits atomically by renaming; a bare numeric dir is live
  (in-flight saves have an .orbax-checkpoint-tmp suffix and fail isdigit).
  """
  directory = os.path.join(model_dir, CHECKPOINT_SUBDIR)
  if not os.path.isdir(directory):
    return []
  return sorted((int(name) for name in os.listdir(directory)
                 if name.isdigit()), reverse=True)


def latest_checkpoint_step(model_dir: str) -> Optional[int]:
  """Newest committed checkpoint step under model_dir, or None."""
  steps = all_checkpoint_steps(model_dir)
  return steps[0] if steps else None


def checkpoints_iterator(model_dir: str,
                         timeout_secs: float = 600.0,
                         min_interval_secs: float = 1.0,
                         stop_fn: Optional[Callable[[], bool]] = None
                         ) -> Iterator[int]:
  """Yields new checkpoint steps as they appear (ref train_eval.py:570).

  Terminates when no new checkpoint arrives within ``timeout_secs`` or
  ``stop_fn`` returns True.
  """
  last_step = None
  # monotonic, not time.time(): a wall-clock jump (NTP step, DST) must not
  # spuriously expire — or indefinitely extend — the eval timeout.
  deadline = time.monotonic() + timeout_secs
  while True:
    if stop_fn is not None and stop_fn():
      return
    step = latest_checkpoint_step(model_dir)
    if step is not None and step != last_step:
      last_step = step
      deadline = time.monotonic() + timeout_secs
      yield step
      continue
    if time.monotonic() > deadline:
      return
    time.sleep(min_interval_secs)


# -- warm start -------------------------------------------------------------


def create_warm_start_fn(checkpoint_dir: str,
                         step: Optional[int] = None,
                         include: Optional[Callable[[str], bool]] = None):
  """Returns params -> params merging values restored from a foreign run.

  The JAX form of ``default_init_from_checkpoint_fn``'s partial restore
  (/root/reference/models/abstract_model.py:88-118): leaves present in the
  checkpoint under the same tree path (and passing ``include`` on the
  '/'-joined path) replace freshly-initialized values; everything else
  keeps its init. Shape mismatches are skipped, matching the reference's
  tolerance for evolving label spaces.
  """

  def warm_start(params):
    # Read-only against a foreign run's directory: never quarantine there.
    manager = CheckpointManager(checkpoint_dir, async_checkpoints=False,
                                quarantine_damaged=False)
    try:
      restore_step = step if step is not None else manager.latest_step()
      if restore_step is None:
        raise FileNotFoundError(
            'No checkpoint to warm start from in {}.'.format(checkpoint_dir))
      restored = manager.restore(None, step=restore_step)
    finally:
      manager.close()
    if isinstance(restored, dict) and 'params' in restored:
      restored = restored['params']

    flat_restored = _flatten_with_paths(restored)
    flat_params, treedef = jax.tree_util.tree_flatten_with_path(params)
    merged = []
    for path, value in flat_params:
      key = _path_str(path)
      candidate = flat_restored.get(key)
      if candidate is not None and (include is None or include(key)):
        if np.shape(candidate) == np.shape(value):
          value = jax.numpy.asarray(candidate, dtype=value.dtype)
      merged.append(value)
    return jax.tree_util.tree_unflatten(treedef, merged)

  return warm_start


def _path_str(path) -> str:
  parts = []
  for entry in path:
    if hasattr(entry, 'key'):
      parts.append(str(entry.key))
    elif hasattr(entry, 'idx'):
      parts.append(str(entry.idx))
    else:
      parts.append(str(entry))
  return '/'.join(parts)


def _flatten_with_paths(tree) -> dict:
  flat, _ = jax.tree_util.tree_flatten_with_path(tree)
  return {_path_str(path): value for path, value in flat}
