"""The training/eval harness: mesh-sharded jitted train loop.

Parity target: /root/reference/utils/train_eval.py:404-596 (train_eval_model
assembling Estimator/TPUEstimator + TrainSpec/EvalSpec + exporters + hooks)
and the model_fn skeleton it drives (/root/reference/models/abstract_model.py
:651-823). The TF1 machinery maps as:

  (TPU)Estimator + RunConfig          -> Trainer: one jitted train_step
      donated + sharded over a Mesh; iterations are plain Python around a
      fully-compiled XLA program (infeed == shard_batch on host arrays)
  CrossShardOptimizer all-reduce      -> psum inserted by XLA from the
      batch's 'data'-axis sharding — nothing to write
  TrainSpec/EvalSpec + exporters      -> train_eval_model(): alternating
      train/eval phases, exporters invoked after each eval
  continuous eval (checkpoints_iterator + backup ckpt) -> eval_continuously()
  TPU bf16 wrapper                    -> Bfloat16PreprocessorWrapper applied
      when model.is_device_tpu (host pipeline emits bf16 arrays directly)
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensor2robot_tpu.data.input_generators import AbstractInputGenerator
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel, TrainState
from tensor2robot_tpu.models.model_interface import ModelInterface
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.observability import (
    AutoProfiler,
    GoodputTracker,
    TelemetryLogger,
    Watchdog,
    WatchdogConfig,
    get_registry,
    span,
)
from tensor2robot_tpu.observability import fleet as fleet_lib
from tensor2robot_tpu.observability import goodput as goodput_lib
from tensor2robot_tpu.observability import pipeline_xray as xray_lib
from tensor2robot_tpu.observability import roofline as roofline_lib
from tensor2robot_tpu.observability import signals as signals_lib
from tensor2robot_tpu.observability import watchdog as watchdog_lib
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel import sharding as sharding_lib
from tensor2robot_tpu.preprocessors.bfloat16_wrapper import (
    Bfloat16PreprocessorWrapper,
)
from tensor2robot_tpu.reliability import fault_injection
from tensor2robot_tpu.reliability import quarantine as quarantine_lib
from tensor2robot_tpu.reliability.errors import (
    CHECKPOINT_SKIP_ERRORS,
    NonFiniteLossError,
    TrainingPreempted,
)
from tensor2robot_tpu.reliability.preemption import graceful_shutdown
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.trainer import checkpointing

NAN_POLICIES = ('off', 'skip', 'raise', 'rollback')

_logv = None


def _log(msg: str, *args) -> None:
  global _logv
  if _logv is None:
    from absl import logging as _absl_logging  # deferred: absl optional
    _logv = _absl_logging.info
  _logv(msg, *args)


def _json_scalar(value):
  """Host scalar -> JSON-safe float (NaN/inf become None; arrays mean)."""
  if value is None:
    return None
  value = float(np.mean(value))
  return value if np.isfinite(value) else None


def provide_input_generator_with_model_information(
    input_generator: AbstractInputGenerator,
    t2r_model: AbstractT2RModel,
    mode: str) -> AbstractInputGenerator:
  """Binds the model's (preprocessed) specs to the input generator.

  Ref: utils/train_eval.py:101 + abstract_input_generator.py:80.
  """
  input_generator.set_specification_from_model(t2r_model, mode)
  return input_generator


class Trainer:
  """Owns the mesh, the compiled step functions, and checkpointing."""

  def __init__(self,
               model: AbstractT2RModel,
               model_dir: str,
               mesh: Optional[Mesh] = None,
               use_fsdp: bool = False,
               tp_rules: Optional[Sequence[Tuple[str, Any]]] = None,
               seed: int = 0,
               keep_checkpoint_max: int = 5,
               save_checkpoints_steps: int = 500,
               async_checkpoints: bool = True,
               log_every_n_steps: int = 100,
               use_avg_params_for_eval: Optional[bool] = None,
               write_metrics: bool = True,
               eval_name: Optional[str] = None,
               profile_steps: Optional[Sequence[int]] = None,
               auto_profile: bool = True,
               profile_budget: int = 2,
               profile_window_steps: int = 5,
               profile_min_interval_secs: float = 600.0,
               enable_watchdog: bool = True,
               watchdog_config: Optional[WatchdogConfig] = None,
               enable_pipeline_xray: bool = True,
               xray_config: Optional[xray_lib.XrayConfig] = None,
               enable_fleet: Optional[bool] = None,
               fleet_config: Optional[fleet_lib.FleetConfig] = None,
               nan_policy: str = 'skip',
               nan_rollback_budget: int = 3,
               nan_check_every_n_steps: int = 1,
               owns_checkpoint_dir: bool = True,
               tuned_config: Optional[Any] = None,
               tuning_cache_path: Optional[str] = None,
               use_compiled_artifacts: bool = False,
               artifact_workload: Optional[str] = None,
               feed_depth: int = 1,
               host_identity: Optional[Dict[str, object]] = None,
               shared_telemetry: Optional[TelemetryLogger] = None):
    """write_metrics: emit TensorBoard events (train scalars under
    model_dir, eval under model_dir/eval[_<eval_name>] — the reference's
    per-eval-run dirs, ref utils/train_eval.py:539-547).
    profile_steps: (start, stop) global steps bracketing ONE static
    jax.profiler trace written under model_dir/plugins (SURVEY §5); the
    window now also produces a forensics/<step>.json report.
    auto_profile: let the watchdog trigger additional budgeted capture
    windows when it detects an anomaly (docs/observability.md): at most
    ``profile_budget`` triggered captures per run, each
    ``profile_window_steps`` steps long, at least
    ``profile_min_interval_secs`` (monotonic) apart.
    enable_watchdog / watchdog_config: rolling-baseline anomaly
    detection (step-time regression, goodput drop, recompiles, HBM
    growth) at the log cadence; detections are counted, written to
    telemetry.jsonl, and — with auto_profile — answered with a capture.
    enable_pipeline_xray / xray_config: per-stage host->device dataflow
    attribution at the log cadence (docs/observability.md "Pipeline
    X-ray"): each window emits a ``t2r.pipeline.v1`` telemetry record
    naming the gating stage and its headroom vs. the device rate, and
    the pipeline anomaly kinds (pipeline_stall / worker_starvation /
    transfer_regression) feed the same capture loop as the watchdog's.
    enable_fleet / fleet_config: fleet observation at the log cadence
    (docs/observability.md "Fleet observatory"): reads every host's
    heartbeat under the shared model_dir, emits a ``t2r.fleet.v1``
    telemetry record (per-host table, skew, gating host, fleet-min
    goodput), and routes ``straggler`` / ``host_dead`` anomalies into
    the same budgeted-capture loop. ``None`` (default) auto-enables on
    multi-process runs; ``True`` forces it on (single-process runs with
    simulated peers — tests, the MULTICHIP fleet phase).
    nan_policy: what the non-finite-loss sentinel does
    (docs/reliability.md): 'skip' (default) discards the poisoned update
    on device — params/opt state keep their pre-step values, only the
    step counter advances, zero host syncs; 'rollback' restores the last
    committed checkpoint (at most ``nan_rollback_budget`` times per
    train() call, then raises NonFiniteLossError); 'raise' fails
    immediately; 'off' reproduces the unguarded seed behavior.
    nan_check_every_n_steps: host-side loss check cadence for
    'raise'/'rollback' (each check syncs the device; 'skip' never does).
    owns_checkpoint_dir: whether this trainer is the writer of
    model_dir's checkpoints. False for eval-only jobs sharing a live
    training directory: their manager then never quarantines (renames)
    damaged step dirs out from under the owning trainer
    (checkpointing.CheckpointManager quarantine_damaged).
    tuned_config: autotuned compile config for the train step
    (docs/performance.md "Compile-config autotuner"). Accepts a
    ``tuning.CompileConfig``, its dict form, or a WORKLOAD NAME string —
    the string is looked up in the persistent config cache at first
    compile, keyed by this step's actual shapes/dtypes + device_kind +
    jax version, so a cache miss (never-tuned workload, changed batch
    size, different chip) silently runs the stock compile. Only the
    config's ``compiler_options`` apply here; ``model_overrides`` are
    layout changes that must come in through the model constructor and
    are ignored (logged) by this hook. The applied config id is exposed
    as ``active_config_id`` and stamped into forensics reports so a
    perf regression is attributable to the config that produced it.
    tuning_cache_path: cache file for the string form (default:
    tuning.default_cache_path()).
    use_compiled_artifacts: resolve the train step through the unified
    ``CompiledArtifact`` store (tensor2robot_tpu/compile, docs/
    performance.md "Cold start"): at first compile the trainer looks up
    the persisted executable for its REAL first-batch shapes — keyed by
    workload | device_kind | jax version | shapes | lowered-program
    hash | config — and a warm start deserializes it, so the first step
    EXECUTES without a single XLA compile. A miss, a stale payload, or
    a corrupt file degrades to the stock compile and persists the
    result for next time; a tuned-config winner resolved from the cache
    passes the same shared guard as the legacy hook (model-override
    winners refused, ``winner_ok=False`` placeholders ignored).
    artifact_workload: the store key's workload name. Defaults to the
    ``tuned_config`` string when one is given (so the autotuner sweep's
    persisted candidates are found — the winner's executable is free at
    train time), else ``trainer_<model class name>``. The
    lowered-program hash in the key makes name collisions harmless:
    a different program is a miss, never a wrong load.
    feed_depth: > 1 pipelines the train channel's host->device hop
    through an N-deep :class:`~tensor2robot_tpu.data.device_feed.
    PipelinedFeed`: a producer thread transfers batches k+1..k+depth
    (decode + copy, sparse/packed unpack dispatch) while the device runs
    step k, so on a transfer-limited host the copy hides under compute
    instead of serializing with it (docs/performance.md "Transfer
    path"). The goodput 'data' fraction then measures only the time the
    loop actually WAITED for a buffered batch; the X-ray transfer stage
    keeps timing each copy to completion in the producer thread, so
    MB/s attribution is unchanged. 1 (default) keeps the synchronous
    hop.
    host_identity: overrides the fleet identity stamp
    (``signals.host_identity()``) for this trainer's telemetry,
    heartbeat, recovery-marker and forensics records. The elastic
    driver (tensor2robot_tpu/elastic) uses it because each simulated
    host of the CPU federation is its own jax world —
    ``jax.process_index()`` is 0 everywhere — while the ELASTIC host
    index must route each process to its own ``telemetry.<i>.jsonl``.
    shared_telemetry: use this TelemetryLogger instead of constructing
    one, and do NOT close it in ``close()`` — the elastic driver keeps
    ONE per-host stream alive across the per-epoch trainers it builds
    (two loggers appending one file from one process would interleave
    buffered writes mid-line).
    """
    self.model = model
    self.model_dir = model_dir
    self.mesh = mesh if mesh is not None else mesh_lib.create_mesh()
    self.use_fsdp = use_fsdp
    # (path-regex, PartitionSpec) pairs for tensor-parallel params over the
    # mesh's 'model' axis (parallel/sharding.py TP_RULES_TRANSFORMER);
    # None = no TP. The model must also be built with the matching
    # tp_axis so activations carry the same placement.
    self.tp_rules = tp_rules
    self.seed = seed
    self.log_every_n_steps = log_every_n_steps
    self.save_checkpoints_steps = save_checkpoints_steps
    if use_avg_params_for_eval is None:
      use_avg_params_for_eval = model.use_avg_model_params
    self.use_avg_params_for_eval = use_avg_params_for_eval
    os.makedirs(model_dir, exist_ok=True)
    self.checkpoint_manager = checkpointing.CheckpointManager(
        model_dir,
        keep_checkpoint_max=keep_checkpoint_max,
        save_interval_steps=1,
        async_checkpoints=async_checkpoints,
        quarantine_damaged=owns_checkpoint_dir)
    self._state_sharding = None
    self._train_step_fn = None
    self._train_step_jitted = None  # the raw jit object (cache-size probe)
    self._step_abstract = None  # ShapeDtypeStruct args for AOT relowering
    self._eval_step_fn = None
    self._predict_step_fn = None
    self._throughput = None  # (examples/sec, step_time_s) from last train run
    self.last_eval_state = None  # state used by the most recent evaluate()
    self._write_metrics = write_metrics
    self._eval_name = eval_name
    self._auto_profiler = AutoProfiler(
        model_dir,
        static_window=profile_steps,
        window_steps=profile_window_steps,
        max_captures=profile_budget if auto_profile else 0,
        min_interval_secs=profile_min_interval_secs)
    self._watchdog = (Watchdog(watchdog_config) if enable_watchdog
                      else None)
    self._xray = (xray_lib.PipelineXray(xray_config)
                  if enable_pipeline_xray else None)
    self._enable_fleet = enable_fleet
    self._fleet_config = fleet_config
    self._fleet_observer: Optional[fleet_lib.FleetObserver] = None
    self._host_identity: Optional[Dict[str, object]] = (
        dict(host_identity) if host_identity else None)
    self._shared_telemetry = shared_telemetry
    # Compile-event accounting (jax/compiles, jax/compile_ms) feeds the
    # watchdog's recompile detection; idempotent per process.
    signals_lib.install_jax_listeners()
    if nan_policy not in NAN_POLICIES:
      raise ValueError('nan_policy must be one of {}; got {!r}.'.format(
          NAN_POLICIES, nan_policy))
    self._nan_policy = nan_policy
    self._nan_rollback_budget = int(nan_rollback_budget)
    self._nan_check_every_n_steps = max(1, int(nan_check_every_n_steps))
    self._train_writer = None
    self._eval_writer = None
    self._telemetry = None
    self._last_goodput = None
    self._device_feed = None
    self._device_feed_built = False
    self._tuned_config = tuned_config
    self._tuning_cache_path = tuning_cache_path
    self._use_compiled_artifacts = bool(use_compiled_artifacts)
    self._artifact_workload = artifact_workload
    self._feed_depth = max(1, int(feed_depth))
    self._train_step_compiled = None  # AOT executable under tuned options
    self._train_step_artifact = None  # CompiledArtifact (provenance+HLO)
    self._step_cost_cache = None  # cost-model totals (False = resolved none)
    self.active_config_id: Optional[str] = None

  def _put_batch(self, batch: dict, channel: str = 'train'):
    """Host batch -> sharded device batch, sparse-coef aware.

    With a DeviceDecodePreprocessor(sparse=True) pipeline the input
    batches carry bucketed sparse DCT streams; the feed unpacks them to
    the fixed-shape dense coefficient tensors right after transfer so the
    jitted step never recompiles (data/device_feed.py). Everything else
    is a plain shard_batch. ``channel`` scopes the feed's shape-stability
    accounting to the jitted program consuming the batch: the eval step
    is its own compile, so its (legitimately different) batch shape must
    not trip the train-step invariant.
    """
    if not self._device_feed_built:
      from tensor2robot_tpu.data.device_feed import (
          HostDeviceFeed,
          SparseCoefFeed,
      )
      # EVERY batch crosses a feed (plain HostDeviceFeed when no sparse
      # groups are in play) so the pipeline X-ray's transfer stage is
      # metered unconditionally.
      self._device_feed = (SparseCoefFeed.from_preprocessor(
          self.model.preprocessor, self.mesh)
          or HostDeviceFeed(self.mesh))
      self._device_feed_built = True
    return self._device_feed.put_batch(batch, channel=channel)

  @property
  def train_metrics_writer(self):
    """Lazy TensorBoard writer for the train run (None when disabled)."""
    if self._write_metrics and self._train_writer is None:
      from tensor2robot_tpu.trainer.metrics import MetricsWriter
      self._train_writer = MetricsWriter(self.model_dir)
    return self._train_writer

  @property
  def eval_metrics_writer(self):
    if self._write_metrics and self._eval_writer is None:
      from tensor2robot_tpu.trainer.metrics import MetricsWriter
      subdir = ('eval_' + self._eval_name) if self._eval_name else 'eval'
      self._eval_writer = MetricsWriter(os.path.join(self.model_dir, subdir))
    return self._eval_writer

  @property
  def host_identity(self) -> Dict[str, object]:
    """This process's fleet identity (cached): the host_meta stamp every
    telemetry record/heartbeat and forensics report carries."""
    if self._host_identity is None:
      self._host_identity = signals_lib.host_identity()
    return self._host_identity

  @property
  def telemetry_logger(self):
    """Lazy telemetry.jsonl + heartbeat writer (None when metrics are off).

    Multi-process runs get per-host filenames
    (``telemetry.<process_index>.jsonl``) via the identity host_meta —
    N processes sharing one model_dir must never append to one file.
    """
    if self._shared_telemetry is not None:
      return self._shared_telemetry
    if self._write_metrics and self._telemetry is None:
      self._telemetry = TelemetryLogger(self.model_dir,
                                        host_meta=self.host_identity)
    return self._telemetry

  @property
  def fleet_observer(self) -> Optional[fleet_lib.FleetObserver]:
    """Lazy fleet observer (None when disabled/single-process)."""
    enabled = self._enable_fleet
    if enabled is None:
      enabled = int(self.host_identity.get('process_count') or 1) > 1
    if not enabled or not self._write_metrics:
      return None
    if self._fleet_observer is None:
      self._fleet_observer = fleet_lib.FleetObserver(
          self.model_dir, self.host_identity, config=self._fleet_config)
    return self._fleet_observer

  @property
  def last_goodput(self):
    """The GoodputTracker of the most recent train() call (or None)."""
    return self._last_goodput

  @property
  def auto_profiler(self) -> AutoProfiler:
    """The capture-window owner (static profile_steps + triggered)."""
    return self._auto_profiler

  @property
  def watchdog(self) -> Optional[Watchdog]:
    return self._watchdog

  def _train_step_hlo(self) -> Optional[str]:
    """Compiled-HLO text of the train step for forensics collective
    stats. Under a tuned config the LIVE tuned executable's HLO is used
    (the report is stamped with its id — analyzing a stock recompile
    would attribute ops of a program that never ran); otherwise relowers
    from the recorded abstract args (one extra XLA compile — acceptable
    once per budgeted capture, never in the loop).
    """
    if self._train_step_artifact is not None and \
        self._train_step_artifact.hlo_text:
      # Unified-artifact path: the post-optimization HLO rode the
      # persisted payload, so forensics reads the STORED program — no
      # relowering, and it works even for a deserialized executable
      # whose backend cannot render text.
      return self._train_step_artifact.hlo_text
    if self._train_step_compiled is not None:
      try:
        return self._train_step_compiled.as_text()
      except Exception:  # noqa: BLE001 — fall through to the relower
        pass
    if self._train_step_jitted is None or self._step_abstract is None:
      return None
    return self._train_step_jitted.lower(
        *self._step_abstract).compile().as_text()

  def _sample_recompiles(self, registry) -> None:
    """``recompiles/train_step``: the jitted step's executable-cache
    size. Exactly 1 on a healthy run — the device_feed shape-stability
    contract as a number; growth means some batch silently triggered a
    full model recompile (the watchdog's ``recompile`` detection)."""
    if self._train_step_jitted is None:
      return
    if self._train_step_compiled is not None:
      # Tuned-config AOT path: exactly one executable exists by
      # construction and the jit cache stays empty — report the healthy 1.
      registry.gauge(watchdog_lib.RECOMPILE_GAUGE).set(1.0)
      return
    try:
      size = self._train_step_jitted._cache_size()
    except Exception:  # noqa: BLE001 — private probe; absent on old jax
      return
    registry.gauge(watchdog_lib.RECOMPILE_GAUGE).set(float(size))

  def _step_cost(self) -> Optional[Dict[str, object]]:
    """Per-device train-step FLOPs/bytes through THE shared cost model
    (parallel/hlo_analysis.program_cost) — the same helper bench.py's
    flops_per_step resolves through, so the live ``perf/mfu`` gauge and
    the bench headline agree by construction. Resolution order mirrors
    ``_train_step_hlo``: persisted artifact HLO, then the live tuned
    executable, then a one-off relower from the recorded abstract args.
    Resolved once and cached (False = resolved to nothing)."""
    if self._step_cost_cache is not None:
      return self._step_cost_cache or None
    cost = None
    try:
      from tensor2robot_tpu.parallel import hlo_analysis
      if self._train_step_artifact is not None and \
          self._train_step_artifact.hlo_text:
        cost = hlo_analysis.program_cost(self._train_step_artifact.hlo_text)
      elif self._train_step_compiled is not None:
        cost = hlo_analysis.program_cost(self._train_step_compiled)
      elif self._train_step_jitted is not None and \
          self._step_abstract is not None:
        cost = hlo_analysis.program_cost(
            self._train_step_jitted.lower(*self._step_abstract).compile())
    except Exception:  # noqa: BLE001 — perf accounting must never kill a run
      cost = None
    self._step_cost_cache = cost if cost and cost.get('flops') else False
    return self._step_cost_cache or None

  def _publish_perf(self, registry, step_time_s: float) -> None:
    """``perf/mfu`` + ``perf/hbm_bw_util`` for this log window.

    Only on hosts whose ``device_kind`` has a peaks-table entry — CPU
    (and unknown kinds) publish nothing rather than a fabricated 0, so
    the watchdog's ``mfu_regression`` check is trivially quiet there
    and CPU test runs pay no relower cost (the step cost is only
    resolved once a peaks entry exists)."""
    if step_time_s <= 0.0:
      return
    kind = str(self.host_identity.get('device_kind', 'unknown'))
    if roofline_lib.device_peaks(kind) is None:
      return
    cost = self._step_cost()
    if cost is None:
      return
    try:
      roofline_lib.publish_perf_gauges(
          registry, float(cost['flops']), float(cost['bytes']),
          step_time_s, kind)
    except Exception:  # noqa: BLE001
      pass

  # -- state ---------------------------------------------------------------

  def _batch_sharding(self):
    return sharding_lib.batch_sharding(self.mesh)

  def init_state(self, features: SpecStruct,
                 labels: Optional[SpecStruct],
                 mode: str = ModeKeys.TRAIN) -> TrainState:
    """Initializes (or restores) a sharded TrainState from a sample batch.

    ``features``/``labels`` are an IN-spec batch from the input pipeline;
    they are run through the preprocessor so variable shapes match what the
    (preprocessed) train step feeds the network.
    """
    rng = jax.random.PRNGKey(self.seed)
    features, labels = self.model.preprocessor.preprocess(
        features, labels, mode, rng=jax.random.PRNGKey(self.seed + 2))
    abstract_state = jax.eval_shape(
        lambda: self.model.create_train_state(rng, features, labels))
    self._state_sharding = sharding_lib.train_state_sharding(
        abstract_state, self.mesh, use_fsdp=self.use_fsdp,
        tp_rules=self.tp_rules)
    # Re-read disk: a concurrent trainer may have written checkpoints
    # since this manager was constructed (continuous-eval topology).
    self.checkpoint_manager.reload()
    steps = sorted(self.checkpoint_manager.all_steps(), reverse=True)
    if steps:
      template = jax.tree.map(
          lambda leaf, s: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                               sharding=s),
          abstract_state, self._state_sharding)
      # Newest first, skipping checkpoints that fail to restore for
      # transient reasons (half-written by a concurrent trainer, deleted
      # by retention GC between listing and read, flaky filesystem). A
      # restore problem that hits EVERY committed step is real: re-raise
      # rather than silently reinitializing and discarding the run.
      last_error = None
      for candidate in steps:
        _log('Restoring checkpoint at step %d from %s', candidate,
             self.model_dir)
        try:
          return self.checkpoint_manager.restore(template, step=candidate)
        except CHECKPOINT_SKIP_ERRORS as e:
          last_error = e
          _log('Checkpoint %d in %s failed to restore (%s); trying the '
               'previous one.', candidate, self.model_dir, e)
      raise last_error
    # No checkpoint: this is a FRESH state. Callers chaining train() calls
    # without checkpointing must thread the returned state explicitly or
    # each call restarts from initialization — log so that's visible.
    _log('No checkpoint in %s; initializing fresh train state.',
         self.model_dir)
    if getattr(self.model, 'warm_start_fn', None) is not None:
      # Warm start restores a foreign checkpoint (real I/O): run it eagerly
      # exactly once and shard the result, instead of tracing it under jit
      # where the restored weights would be baked in as XLA constants.
      state = self.model.create_train_state(rng, features, labels)
      return jax.device_put(state, self._state_sharding)
    init_fn = jax.jit(
        lambda f, l: self.model.create_train_state(rng, f, l),
        out_shardings=self._state_sharding)
    # shard_batch, not device_put: multi-process hosts hold only their
    # slice of the global batch (parallel/sharding.py:59-74).
    features = sharding_lib.shard_batch(features.to_dict(), self.mesh)
    labels = (sharding_lib.shard_batch(labels.to_dict(), self.mesh)
              if labels is not None else None)
    return init_fn(features, labels)

  # -- compiled steps -------------------------------------------------------

  def _compile_train_step(self):
    if self._train_step_fn is not None:
      return self._train_step_fn
    model = self.model
    nan_policy = self._nan_policy

    def step(state, features, labels, base_rng, force_nan):
      # Fold the step into the rng on-device: no host round-trip per step.
      rng = jax.random.fold_in(base_rng, state.step)
      pre_rng, step_rng = jax.random.split(rng)
      # The preprocessor runs INSIDE the jitted step: crops/distortions/casts
      # execute on device, fused by XLA into the forward pass (the TPU-native
      # replacement for the reference's host-side tf.data map,
      # utils/tfdata.py:572-574).
      features, labels = model.preprocessor.preprocess(
          SpecStruct(**features),
          SpecStruct(**labels) if labels is not None else None,
          ModeKeys.TRAIN, rng=pre_rng)
      new_state, metrics = model.train_step(state, features, labels,
                                            step_rng)
      metrics = dict(metrics)
      loss = metrics.get('loss')
      if loss is not None:
        # ``force_nan`` is the FaultInjector's 'step.nan' site: a traced
        # scalar (no recompile per toggle) poisoning the loss on device.
        loss = jnp.where(force_nan, jnp.nan, loss)
        metrics['loss'] = loss
        if nan_policy == 'skip':
          # Discard a poisoned update without leaving the device: every
          # leaf keeps its pre-step value when the loss is non-finite,
          # except the step counter, which advances so loop/bookkeeping
          # and checkpoint steps stay aligned ("batch dropped").
          good = jnp.all(jnp.isfinite(loss))
          guarded = jax.tree.map(
              lambda new, old: jnp.where(good, new, old), new_state, state)
          new_state = guarded.replace(step=new_state.step)
          metrics['nonfinite_loss_skipped'] = 1 - good.astype(jnp.int32)
      return new_state, metrics

    batch = self._batch_sharding()
    replicated = NamedSharding(self.mesh, P())
    # The artifact path compiles WITHOUT donation: a persisted
    # (serialize_executable) train step with input/output aliasing baked
    # in executes incorrectly after deserialization on this jaxlib's CPU
    # backend — an Orbax-restored state donated into a deserialized
    # executable comes back with a skewed step counter / rng fold
    # (pinned by tests/test_elastic.py's cross-process repro; the same
    # program self-compiled, or run on fresh-init state, is fine). The
    # cost is one transient state copy per step; the stock jit path
    # keeps the donation.
    jit_kwargs = {}
    if not self._use_compiled_artifacts:
      jit_kwargs['donate_argnums'] = (0,)
    jitted = jax.jit(
        step,
        in_shardings=(self._state_sharding, batch, batch, replicated,
                      replicated),
        out_shardings=(self._state_sharding, replicated),
        **jit_kwargs)

    def call(state, features, labels, base_rng, force_nan=None):
      # force_nan defaults off so external callers of the compiled step
      # (tests, rl/offpolicy) keep the pre-reliability 4-arg signature.
      if force_nan is None:
        force_nan = np.asarray(False)
      if self._step_abstract is None:
        # Shape/dtype skeleton BEFORE the call (state is donated): lets
        # forensics relower the exact compiled program without holding
        # any buffers alive.
        self._step_abstract = jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(jnp.shape(leaf),
                                              jnp.result_type(leaf)),
            (state, features, labels, base_rng, force_nan))
        self._bind_compiled_step(jitted, self._step_abstract)
      if self._train_step_compiled is not None:
        return self._train_step_compiled(state, features, labels, base_rng,
                                         force_nan)
      return jitted(state, features, labels, base_rng, force_nan)

    self._train_step_jitted = jitted
    self._train_step_fn = call
    return self._train_step_fn

  def bind_train_step(self, features: SpecStruct,
                      labels: Optional[SpecStruct]):
    """AOT-binds the train-step executable WITHOUT executing a step.

    The cold-start prewarm hook: resolves the step through the
    ``CompiledArtifact`` store (or the legacy tuned hook) from a sample
    host batch alone — tracing, lowering, and (on a store hit)
    deserializing, but never running the program. That split is what
    lets a multi-host bring-up stagger "host 0 compiles + persists,
    hosts 1..N deserialize" around a barrier even though the step
    itself is a collective no host can run alone
    (``parallel/multihost.py``), and it is how an elastic rebuild can
    bind before its first probe step. Returns the bound
    ``CompiledArtifact`` (None when binding fell back to the stock jit
    path). Idempotent: a later ``train()`` reuses the binding.
    """
    rng = jax.random.PRNGKey(self.seed)
    pre_features, pre_labels = self.model.preprocessor.preprocess(
        features, labels, ModeKeys.TRAIN,
        rng=jax.random.PRNGKey(self.seed + 2))
    abstract_state = jax.eval_shape(
        lambda: self.model.create_train_state(rng, pre_features,
                                              pre_labels))
    self._state_sharding = sharding_lib.train_state_sharding(
        abstract_state, self.mesh, use_fsdp=self.use_fsdp,
        tp_rules=self.tp_rules)
    self._compile_train_step()
    if self._step_abstract is None:
      # The batch crosses the real device feed so the abstract batch
      # carries GLOBAL shapes (a multi-process host's local slice is
      # only 1/Nth of what the step consumes).
      device_batch = self._put_batch(
          {'features': features.to_dict(),
           'labels': labels.to_dict() if labels is not None else None})
      base_rng = jax.random.PRNGKey(self.seed + 1)
      self._step_abstract = jax.tree.map(
          lambda leaf: jax.ShapeDtypeStruct(jnp.shape(leaf),
                                            jnp.result_type(leaf)),
          (abstract_state, device_batch['features'],
           device_batch['labels'], base_rng, np.asarray(False)))
      self._bind_compiled_step(self._train_step_jitted,
                               self._step_abstract)
    return self._train_step_artifact

  def _resolve_tuned_config(self, args):
    """tuned_config (CompileConfig | dict | workload-name str) ->
    (config, from_cache).

    The string form is the production hook: look the workload up in the
    persistent tuning cache under THIS step's shapes/dtypes + device_kind
    + jax version. A miss returns None — the trainer must run identically
    with and without a cache entry. ``from_cache`` distinguishes a
    cache-resolved winner from a directly-passed config: a direct
    config's ``model_overrides`` were applied by the caller at model
    construction (bench.py does), a cache-resolved one's were NOT.
    """
    from tensor2robot_tpu import tuning

    spec = self._tuned_config
    if spec is None:
      return None, False
    if isinstance(spec, tuning.CompileConfig):
      return spec, False
    if isinstance(spec, dict):
      return tuning.CompileConfig.from_dict(spec), False
    cache = tuning.ConfigCache(self._tuning_cache_path)
    key = tuning.cache_key(
        str(spec), tuning.abstract_signature(args),
        getattr(jax.devices()[0], 'device_kind', 'unknown'))
    entry = cache.lookup(key)
    # The shared stale-winner guard (compile/artifact.py): cache misses,
    # winner_ok=False placeholders, and winners carrying model_overrides
    # (which the trainer cannot re-apply at compile time — half-applying
    # just their flags would run an unmeasured hybrid attributed to the
    # winner's id) all resolve to the stock compile HERE, identically
    # for this legacy hook and the artifact load path.
    from tensor2robot_tpu.compile import artifact as artifact_lib
    config, reason = artifact_lib.resolve_cache_winner(entry)
    if config is None:
      _log('Tuning cache for workload %r (%s) yields no applicable '
           'winner (%s); using the stock compile.%s', spec, key, reason,
           ' Apply the overrides at model construction and pass the '
           'config directly to use this winner.'
           if reason == 'model_overrides' else '')
      return None, True
    return config, True

  def _bind_compiled_step(self, jitted, args) -> None:
    """Binds the train-step executable at first call: the unified
    CompiledArtifact cold-start path when ``use_compiled_artifacts``,
    else the legacy AOT-under-tuned-options hook.

    Best-effort by the same contract as the legacy hook: any store or
    compile failure costs a log line and falls back to the stock jit
    path, never the training run.
    """
    if not self._use_compiled_artifacts:
      self._apply_tuned_config(jitted, args)
      return
    try:
      from tensor2robot_tpu.compile import artifact as artifact_lib

      config, from_cache = self._resolve_tuned_config(args)
      if config is not None and config.model_overrides and not from_cache:
        # Direct-form config: the caller applied the layout overrides at
        # model construction (bench.py does); only the flags compile here.
        _log('Tuned config %s carries model_overrides %s — applied at '
             'model construction, not here.', config.config_id,
             sorted(config.model_overrides))
      workload = self._artifact_workload
      if workload is None:
        workload = (str(self._tuned_config)
                    if isinstance(self._tuned_config, str)
                    else 'trainer_' + type(self.model).__name__.lower())
      with span('train.artifact_load'):
        artifact = artifact_lib.load_or_compile(
            workload, jitted, args, config=config,
            cache_path=self._tuning_cache_path,
            telemetry=self.telemetry_logger, program_key=True)
      self._train_step_compiled = artifact.executable
      self._train_step_artifact = artifact
      if config is not None and (config.compiler_options
                                 or (config.model_overrides
                                     and not from_cache)):
        # Same attribution rule as the legacy hook: a config took effect
        # here (flags) or at model construction (direct-form overrides).
        self.active_config_id = config.config_id
      _log('Train step bound from CompiledArtifact store: workload=%s '
           '%s (config %s, key %s).', workload,
           'deserialized persisted executable' if artifact.from_cache
           else 'compiled + persisted', artifact.config_id, artifact.key)
    except Exception as e:  # noqa: BLE001 — store trouble must not kill
      # training: degrade to the legacy hook (which itself degrades to
      # the stock jit compile).
      _log('CompiledArtifact bind failed (%s); using the legacy tuned '
           'hook.', e)
      self._train_step_compiled = None
      self._train_step_artifact = None
      self._apply_tuned_config(jitted, args)

  def _apply_tuned_config(self, jitted, args) -> None:
    """AOT-compiles the train step under the tuned compiler options.

    Best-effort by contract: a stale cache entry naming a flag this
    jaxlib rejects must cost a log line and fall back to the stock
    compile, never the training run. ``active_config_id`` is set only
    when the config actually took effect.
    """
    try:
      config, from_cache = self._resolve_tuned_config(args)
    except Exception as e:  # noqa: BLE001 — cache I/O must never kill train
      _log('Tuned-config resolution failed (%s); using stock compile.', e)
      return
    if config is None:
      return
    if config.model_overrides:
      # Cache-resolved winners with overrides never reach here — the
      # shared resolve_cache_winner guard already refused them — so
      # this is the DIRECT form: the caller applied the overrides at
      # model construction; only the flags compile below.
      _log('Tuned config %s carries model_overrides %s — layout changes '
           'apply at model construction, not here; ignoring them.',
           config.config_id, sorted(config.model_overrides))
    if not config.compiler_options:
      # Overrides-only config: attributable only when the CALLER applied
      # the overrides at model construction (direct form). A
      # cache-resolved one took no effect here — stamping its id would
      # attribute runs to a config that never applied.
      if not from_cache:
        self.active_config_id = config.config_id
      return
    from tensor2robot_tpu.tuning import autotuner
    try:
      with span('train.tuned_compile'):
        self._train_step_compiled = autotuner.compile_with_config(
            jitted, args, config)
      self.active_config_id = config.config_id
      _log('Train step compiled under tuned config %s (%s).',
           config.config_id, config.compiler_options)
    except Exception as e:  # noqa: BLE001 — unknown flag on this backend
      self._train_step_compiled = None
      _log('Tuned config %s failed to compile (%s); using stock compile.',
           config.config_id, e)

  def _compile_eval_step(self):
    if self._eval_step_fn is not None:
      return self._eval_step_fn
    model = self.model
    use_avg = self.use_avg_params_for_eval

    def step(state, features, labels):
      features, labels = model.preprocessor.preprocess(
          SpecStruct(**features),
          SpecStruct(**labels) if labels is not None else None,
          ModeKeys.EVAL, rng=None)
      variables = state.variables(use_avg_params=use_avg)
      outputs, _ = model.inference_network_fn(
          variables, features, labels, ModeKeys.EVAL, None)
      metrics = model.model_eval_fn(
          variables, features, labels, outputs, ModeKeys.EVAL)
      return dict(metrics)

    batch = self._batch_sharding()
    self._eval_step_fn = jax.jit(
        step, in_shardings=(self._state_sharding, batch, batch),
        out_shardings=NamedSharding(self.mesh, P()))
    return self._eval_step_fn

  def _compile_predict_step(self):
    if self._predict_step_fn is not None:
      return self._predict_step_fn
    model = self.model

    def step(state, features):
      features, _ = model.preprocessor.preprocess(
          SpecStruct(**features), None, ModeKeys.PREDICT, rng=None)
      outputs = model.predict_step(state, features)
      return dict(outputs)

    self._predict_step_fn = jax.jit(
        step, in_shardings=(self._state_sharding, self._batch_sharding()))
    return self._predict_step_fn

  # -- loops ----------------------------------------------------------------

  def train(self,
            input_generator: AbstractInputGenerator,
            max_train_steps: int,
            state: Optional[TrainState] = None,
            hooks: Sequence[Any] = (),
            shard_index: Optional[int] = None,
            num_shards: Optional[int] = None) -> TrainState:
    """Runs the training loop up to global step ``max_train_steps``.

    ``shard_index``/``num_shards`` select this host's slice of the input
    files; they default to the JAX process index/count, so multi-host
    training reads per-host shards with no extra wiring (the PER_HOST_V2
    contract, ref utils/tfdata.py:43-66).
    """
    if shard_index is None:
      shard_index = jax.process_index()
    if num_shards is None:
      num_shards = jax.process_count()
    input_generator = provide_input_generator_with_model_information(
        input_generator, self.model, ModeKeys.TRAIN)
    iterator = input_generator.create_dataset_iterator(
        mode=ModeKeys.TRAIN, shard_index=shard_index, num_shards=num_shards)
    features, labels = next(iterator)
    restore_s = 0.0
    if state is None:
      # Timed for the recovery timeline: after a preemption this is the
      # mesh/state rebuild + checkpoint restore phase.
      restore_t0 = time.perf_counter()
      state = self.init_state(features, labels)
      restore_s = time.perf_counter() - restore_t0
    step_fn = self._compile_train_step()
    base_rng = jax.device_put(jax.random.PRNGKey(self.seed + 1),
                              NamedSharding(self.mesh, P()))
    start_step = int(jax.device_get(state.step))
    if start_step >= max_train_steps:
      _log('Checkpoint already at step %d >= max_train_steps %d; skipping.',
           start_step, max_train_steps)
      return state
    batch_size = int(jax.tree_util.tree_leaves(features.to_dict())[0].shape[0])
    for hook in hooks:
      hook.begin(self)
    # perf_counter, not time.time(): steps/sec and goodput must survive
    # wall-clock jumps (NTP step, DST) — the monotonic-deadline discipline
    # the reliability layer already follows (docs/reliability.md).
    t_last = time.perf_counter()
    steps_since_log = 0
    metrics = None
    step_i = start_step
    batch = (features, labels)
    # feed_depth > 1: route the train channel through the N-deep
    # pipelined feed — the producer thread decodes AND transfers batches
    # ahead while the device computes, so the loop below only ever waits
    # on an already-resident batch (the wait is the honest goodput
    # 'data' cost). The first batch — already drawn for init_state — is
    # chained back in so no data is skipped.
    pipelined = None
    if self._feed_depth > 1:
      import itertools

      from tensor2robot_tpu.data.device_feed import PipelinedFeed

      def _host_batch(pair):
        batch_features, batch_labels = pair
        return {'features': batch_features.to_dict(),
                'labels': (batch_labels.to_dict()
                           if batch_labels is not None else None)}

      pipelined = PipelinedFeed(
          map(_host_batch, itertools.chain([batch], iterator)),
          self._put_batch, depth=self._feed_depth)
    rollback_budget = self._nan_rollback_budget
    host_nan_check = self._nan_policy in ('raise', 'rollback')
    completed = False
    # Goodput accounting: every loop second lands in exactly one of
    # productive / data / checkpoint / retry (docs/observability.md).
    tracker = GoodputTracker()
    self._last_goodput = tracker
    registry = get_registry()
    # Pre-register the well-known reliability counters: a dashboard must
    # see an explicit 0.0 on a clean run (an absent tag is
    # indistinguishable from broken wiring — the guarantee the pre-registry
    # quarantine export already gave).
    registry.counter(quarantine_lib.RECORDS_SKIPPED_COUNTER)
    registry.counter(quarantine_lib.FILES_ABANDONED_COUNTER)
    registry.counter('reliability/nan_rollbacks')
    registry.counter('reliability/preemptions')
    registry.gauge(watchdog_lib.RECOMPILE_GAUGE)
    # Forensics wiring: reports carry the live goodput split plus the
    # active tuned-config id (attributable perf), and the collective
    # stats come from relowering the step we just compiled.
    self._auto_profiler.context_fn = \
        lambda: {'goodput': tracker.fractions(),
                 'tuned_config': self.active_config_id,
                 'host': self.host_identity,
                 'pipeline': (self._xray.last_record
                              if self._xray is not None else None)}
    self._auto_profiler.hlo_text_fn = self._train_step_hlo
    telemetry = self.telemetry_logger
    if telemetry is not None:
      telemetry.log('run_start', step=start_step,
                    max_train_steps=int(max_train_steps),
                    batch_size=batch_size, nan_policy=self._nan_policy)
      telemetry.flush()
    # A pending recovery marker means the previous incarnation of this
    # model_dir died in a preemption: the first completed step closes
    # the recovery timeline (t2r.recovery.v1, fleet.py).
    pending_recovery = None
    if telemetry is not None:
      marker = fleet_lib.consume_recovery_marker(
          self.model_dir,
          process_index=self.host_identity.get('process_index'))
      if marker is not None:
        pending_recovery = (marker, restore_s, time.perf_counter())

    def commit_goodput(iter_start, data_s, ckpt_s, retry_s):
      # ``productive`` is the remainder, so the categories partition the
      # iteration's wall time exactly and fractions sum to 1.0.
      total = time.perf_counter() - iter_start
      tracker.add(goodput_lib.DATA, data_s)
      tracker.add(goodput_lib.CHECKPOINT, ckpt_s)
      tracker.add(goodput_lib.RETRY, retry_s)
      tracker.add(goodput_lib.PRODUCTIVE,
                  total - data_s - ckpt_s - retry_s)

    with graceful_shutdown() as shutdown:
      try:
        while step_i < max_train_steps:
          iter_start = time.perf_counter()
          data_s = ckpt_s = retry_s = 0.0
          # try/finally, not explicit commit calls: an iteration that
          # exits via continue, preemption, OR an exception (NaN raise,
          # corruption budget, retry exhaustion — often the longest,
          # most interesting seconds) still lands in the accounting.
          try:
            report_path = self._auto_profiler.maybe_profile(step_i)
            if report_path is not None and telemetry is not None:
              telemetry.log('forensics', step=step_i, report=report_path)
              # The capture's roofline attribution also rides the jsonl
              # stream (compact t2r.roofline.v1 payload) so summarize/
              # tail/doctor see it without opening report files.
              try:
                with open(report_path, encoding='utf-8') as f:
                  roofline_record = json.load(f).get('roofline')
              except Exception:  # noqa: BLE001 — report is best-effort
                roofline_record = None
              if roofline_record:
                telemetry.log(
                    'roofline', step=step_i,
                    **roofline_lib.telemetry_payload(roofline_record))
              telemetry.flush()
            with span('data.put_batch') as sp:
              if pipelined is not None:
                # Blocks only while the buffer is EMPTY — the producer
                # thread owns decode + transfer; transfer telemetry and
                # the data.stall site fire there (device_feed.py).
                device_batch = pipelined.get()
              else:
                features, labels = batch
                device_batch = self._put_batch(
                    {'features': features.to_dict(),
                     'labels': labels.to_dict() if labels is not None
                     else None})
            data_s += sp.elapsed
            force_nan = np.asarray(
                fault_injection.fires(fault_injection.SITE_STEP_NAN))
            # NOTE: the step span measures dispatch, not device compute —
            # jax returns before the XLA program finishes. Device time
            # comes from the profiler trace (utils/xplane.py); host-side
            # blocking (donated-buffer backpressure) does land here.
            with span('train.step'):
              state, metrics = step_fn(state, device_batch['features'],
                                       device_batch['labels'], base_rng,
                                       force_nan)
            # The 'step.slow' injection site: a host-side stall the
            # watchdog must detect as a step-time regression — charged
            # to productive time exactly like a real slowdown would be.
            slow_s = fault_injection.slow_step_seconds()
            if slow_s > 0.0:
              time.sleep(slow_s)
            step_i += 1
            steps_since_log += 1
            if pending_recovery is not None:
              marker, marker_restore_s, resume_t0 = pending_recovery
              pending_recovery = None
              recovery = fleet_lib.build_recovery_record(
                  marker, marker_restore_s,
                  time.perf_counter() - resume_t0, step_i)
              registry.gauge(fleet_lib.RECOVERY_GAUGE).set(
                  recovery['preemption_recovery_seconds'])
              _log('Recovered from preemption at step %s in %.1f s '
                   '(save %.1fs, down %.1fs, restore %.1fs, first step '
                   '%.1fs).', recovery['preempted_step'],
                   recovery['preemption_recovery_seconds'],
                   recovery['phases']['emergency_save_s'],
                   recovery['phases']['downtime_s'],
                   recovery['phases']['restore_s'],
                   recovery['phases']['first_step_s'])
              if telemetry is not None:
                telemetry.log('recovery', step=step_i, **recovery)
                telemetry.flush()
            # The sentinel also fires on every step that is about to be
            # checkpointed (periodic or final): with nan_check_every_n_steps
            # > 1 an unvetted save could otherwise commit NaN params, and a
            # later rollback would restore the poison.
            if host_nan_check and (
                step_i % self._nan_check_every_n_steps == 0
                or step_i % self.save_checkpoints_steps == 0
                or step_i == max_train_steps):
              with span('train.nan_check') as sp:
                state, step_i, rolled_back = self._check_finite_loss(
                    state, metrics, step_i, rollback_budget)
              if rolled_back:
                # The whole check-and-restore, plus the re-fetch below, is
                # recovery overhead, not productive time.
                retry_s += sp.elapsed
                rollback_budget -= 1
                steps_since_log = 0
                t_last = time.perf_counter()
                if pipelined is None:
                  with span('data.next') as sp:
                    batch = next(iterator)
                  retry_s += sp.elapsed
                continue
            if (step_i % self.log_every_n_steps == 0
                or step_i == max_train_steps):
              metrics = jax.device_get(dict(metrics))
              dt = time.perf_counter() - t_last
              examples_per_sec = batch_size * steps_since_log / max(dt, 1e-9)
              step_time_s = dt / max(steps_since_log, 1)
              self._throughput = (examples_per_sec, step_time_s)
              _log('step %d: loss=%s (%.1f examples/sec)', step_i,
                   metrics.get('loss'), examples_per_sec)
              # Performance-forensics sampling, BEFORE the exports so
              # the same window's watermarks/anomaly counters land in
              # this very TensorBoard write and telemetry record.
              signals_lib.sample_memory(registry)
              self._sample_recompiles(registry)
              # Live MFU ledger: gauges land BEFORE the watchdog pass so
              # mfu_regression sees this very window's utilization, and
              # before the exports so TensorBoard + telemetry carry it.
              self._publish_perf(registry, step_time_s)
              pipeline_record = None
              if self._xray is not None:
                # X-ray before watchdog: a data-path incident should
                # claim the capture under its pipeline kind (with the
                # stage attribution in the trigger), not as the generic
                # step_time_regression the same stall also causes.
                pipeline_record, pipeline_anomalies = self._xray.observe(
                    step_i, examples=batch_size * steps_since_log,
                    window_seconds=dt,
                    goodput_seconds=tracker.seconds())
                for anomaly in pipeline_anomalies:
                  _log('Pipeline X-ray anomaly: %s', anomaly.message)
                  if telemetry is not None:
                    telemetry.log('anomaly', step=step_i,
                                  anomaly=anomaly.kind,
                                  message=anomaly.message,
                                  detail=anomaly.detail)
                  self._auto_profiler.request_capture(
                      anomaly.kind, step_i, anomaly.detail)
              fleet_record = None
              if self.fleet_observer is not None:
                # Fleet before watchdog: a straggler IS a step-time
                # regression locally, but the fleet kind carries the
                # host attribution — it should claim the capture.
                fleet_record, fleet_anomalies = \
                    self.fleet_observer.observe(
                        step_i, step_time_s=step_time_s,
                        examples_per_sec=examples_per_sec,
                        productive_fraction=tracker.fractions().get(
                            'productive'))
                for anomaly in fleet_anomalies:
                  _log('Fleet anomaly: %s', anomaly.message)
                  if telemetry is not None:
                    telemetry.log('anomaly', step=step_i,
                                  anomaly=anomaly.kind,
                                  message=anomaly.message,
                                  detail=anomaly.detail)
                  self._auto_profiler.request_capture(
                      anomaly.kind, step_i, anomaly.detail)
              if self._watchdog is not None:
                for anomaly in self._watchdog.observe(
                    step_i, step_time_s, tracker.seconds()):
                  _log('Watchdog anomaly: %s', anomaly.message)
                  if telemetry is not None:
                    telemetry.log('anomaly', step=step_i,
                                  anomaly=anomaly.kind,
                                  message=anomaly.message,
                                  detail=anomaly.detail)
                  self._auto_profiler.request_capture(
                      anomaly.kind, step_i, anomaly.detail)
              writer = self.train_metrics_writer
              if writer is not None:
                scalars = {k: float(np.mean(v)) for k, v in metrics.items()
                           if np.ndim(v) == 0}
                scalars['global_step/sec'] = 1.0 / max(
                    dt / max(steps_since_log, 1), 1e-9)
                scalars['examples/sec'] = examples_per_sec
                # The unified telemetry pipeline: every registry counter/
                # gauge/histogram-summary (quarantine, retries, rollbacks,
                # span and inference latencies) plus the goodput split —
                # tolerated damage and lost wall-clock are never invisible.
                scalars.update(registry.scalars())
                scalars.update(tracker.scalars())
                writer.write_scalars(step_i, scalars)
                writer.flush()
              if telemetry is not None:
                snapshot = registry.snapshot()
                # Gauges ride along so offline tooling (doctor) can
                # compute across SAMPLES — "prefetch queue empty in 81%
                # of samples" needs the series, not the last value.
                telemetry.log('train', step=step_i,
                              loss=_json_scalar(metrics.get('loss')),
                              examples_per_sec=examples_per_sec,
                              step_time_s=step_time_s,
                              goodput=tracker.fractions(),
                              goodput_seconds=tracker.seconds(),
                              counters=snapshot['counters'],
                              gauges=snapshot['gauges'])
                if pipeline_record is not None:
                  # The t2r.pipeline.v1 attribution record: gating stage
                  # + headroom vs. the device rate, per log window.
                  telemetry.log('pipeline', step=step_i, **pipeline_record)
                if fleet_record is not None:
                  # The t2r.fleet.v1 federation record: per-host table,
                  # skew, gating host, fleet-min goodput, per window.
                  telemetry.log('fleet', step=step_i, **fleet_record)
                # Window stats ride the heartbeat so a peer's
                # FleetObserver can read the whole fleet's health from
                # N tiny atomic files instead of N telemetry re-parses.
                telemetry.heartbeat(
                    step_i, step_time_s=step_time_s,
                    examples_per_sec=examples_per_sec,
                    productive_fraction=tracker.fractions().get(
                        'productive'))
                telemetry.flush()
              t_last = time.perf_counter()
              steps_since_log = 0
            if step_i % self.save_checkpoints_steps == 0:
              ckpt_t0 = time.perf_counter()
              self.save_checkpoint(state)
              ckpt_s += time.perf_counter() - ckpt_t0
            for hook in hooks:
              hook.after_step(self, state, step_i, metrics)
            preempt_signum = None
            if shutdown.requested:
              preempt_signum = int(shutdown.signum)
            elif fault_injection.fires(fault_injection.SITE_HOST_PREEMPT):
              # The injected host-preemption site: the SAME end-to-end
              # path a SIGTERM drives, deterministically — what makes
              # the recovery timeline a measurable, testable quantity.
              preempt_signum = fault_injection.INJECTED_PREEMPT_SIGNUM
            if preempt_signum is not None:
              # Commit everything before re-raising: the restart resumes
              # from this exact step instead of the last periodic save.
              ckpt_t0 = time.perf_counter()
              self.save_checkpoint(state, force=True)
              self.checkpoint_manager.wait_until_finished()
              save_s = time.perf_counter() - ckpt_t0
              ckpt_s += save_s
              registry.counter('reliability/preemptions').inc()
              if telemetry is not None:
                telemetry.log('preempted', step=step_i,
                              signum=preempt_signum)
                telemetry.heartbeat(step_i)
                telemetry.flush()
                # Start the recovery clock: the resuming process (a
                # different pid) consumes this marker and emits the
                # t2r.recovery.v1 record at its first completed step.
                fleet_lib.write_recovery_marker(
                    self.model_dir, step_i, preempt_signum, save_s,
                    process_index=self.host_identity.get('process_index'))
              raise TrainingPreempted(preempt_signum, step_i)
            if step_i < max_train_steps and pipelined is None:
              with span('data.next') as sp:
                batch = next(iterator)
              data_s += sp.elapsed
          finally:
            commit_goodput(iter_start, data_s, ckpt_s, retry_s)
        completed = True
      finally:
        if pipelined is not None:
          # Stop the producer on EVERY exit path — a live thread parked
          # inside the native loader's next() would otherwise race the
          # stream teardown below (and at interpreter exit).
          pipelined.close()
        # A dangling profiler trace breaks the next start_trace: close
        # it on EVERY exit path. Clean completion gets the full
        # forensics report; failure paths just stop the trace (the
        # report machinery must never mask the unwinding exception).
        if completed:
          report_path = self._auto_profiler.finish(step_i)
          if report_path is not None and telemetry is not None:
            telemetry.log('forensics', step=step_i, report=report_path)
        else:
          self._auto_profiler.abort()
        if not completed:
          # NonFiniteLossError means ``state`` holds the NaN-poisoned
          # update ('raise', or 'rollback' with the budget exhausted) —
          # committing it would make the poison the newest checkpoint
          # and wedge every restart. Flush writers only in that case.
          exc = sys.exc_info()[1]
          poisoned = isinstance(exc, NonFiniteLossError)
          if telemetry is not None and not isinstance(exc,
                                                      TrainingPreempted):
            # Preemption already wrote its own record above; everything
            # else gets a final abort marker (best-effort — the original
            # exception is unwinding and must stay the one raised).
            try:
              telemetry.log('run_abort', step=step_i,
                            error=type(exc).__name__,
                            goodput=tracker.fractions())
            except Exception as e:  # noqa: BLE001
              _log('Telemetry abort record failed: %s', e)
          self._flush_and_emergency_save(state, skip_save=poisoned)
    final_t0 = time.perf_counter()
    self.save_checkpoint(state, force=True)
    tracker.add(goodput_lib.CHECKPOINT, time.perf_counter() - final_t0)
    if telemetry is not None:
      telemetry.log('run_end', step=step_i, goodput=tracker.fractions(),
                    goodput_seconds=tracker.seconds())
      telemetry.heartbeat(step_i)
      telemetry.flush()
    for hook in hooks:
      hook.end(self, state)
    return state

  def _check_finite_loss(self, state, metrics, step_i: int,
                         rollback_budget: int):
    """Host-side non-finite-loss sentinel for 'raise'/'rollback'.

    Returns (state, step_i, rolled_back). Forces a device sync (the cost
    documented on ``nan_check_every_n_steps``).
    """
    loss = metrics.get('loss') if hasattr(metrics, 'get') else None
    if loss is None:
      return state, step_i, False
    loss_val = np.asarray(jax.device_get(loss))
    if np.all(np.isfinite(loss_val)):
      return state, step_i, False
    if self._nan_policy == 'raise':
      raise NonFiniteLossError(step_i, 'nan_policy="raise"')
    if rollback_budget <= 0:
      raise NonFiniteLossError(
          step_i, 'rollback budget exhausted after {} rollback(s)'.format(
              self._nan_rollback_budget))
    try:
      self.checkpoint_manager.wait_until_finished()
      self.checkpoint_manager.reload()
      latest = self.checkpoint_manager.latest_step()
      if latest is None:
        raise NonFiniteLossError(
            step_i, 'no committed checkpoint to roll back to')
      _log('Non-finite loss at step %d: rolling back to checkpoint %d '
           '(%d rollback(s) left).', step_i, latest, rollback_budget - 1)
      # The current (poisoned but shape-valid) state doubles as the
      # restore template: same tree, dtypes, and shardings.
      restored = self.checkpoint_manager.restore(state, step=latest)
    except NonFiniteLossError:
      raise
    except Exception as e:
      # A rollback that fails for ANY reason must still unwind as
      # NonFiniteLossError: the finally-block emergency save keys on that
      # type to know ``state`` is poisoned and must not be committed.
      raise NonFiniteLossError(
          step_i, 'rollback failed: {}'.format(e)) from e
    # Rollbacks were log-only before the telemetry layer; now they are a
    # first-class counter plus a jsonl event naming both steps.
    get_registry().counter('reliability/nan_rollbacks').inc()
    if self.telemetry_logger is not None:
      self.telemetry_logger.log('rollback', step=step_i,
                                restored_step=int(latest))
      self.telemetry_logger.flush()
    return restored, int(latest), True

  def _flush_and_emergency_save(self, state, skip_save: bool = False) -> None:
    """Failure-path cleanup: commit the state we have, flush writers.

    Best-effort by design — the original exception is already unwinding
    and must stay the one the caller sees. (If the failure happened
    inside the jitted step, ``state`` may hold donated buffers; the save
    then fails and is logged, never raised.) ``skip_save`` suppresses the
    checkpoint when the state is known-poisoned (non-finite loss).
    """
    if not skip_save:
      try:
        self.save_checkpoint(state, force=True)
        self.checkpoint_manager.wait_until_finished()
      except Exception as e:  # noqa: BLE001
        _log('Emergency checkpoint failed: %s', e)
    for writer in (self._train_writer, self._eval_writer, self._telemetry,
                   self._shared_telemetry):
      if writer is not None:
        try:
          writer.flush()
        except Exception as e:  # noqa: BLE001
          _log('Writer flush on failure path failed: %s', e)

  def evaluate(self,
               input_generator: AbstractInputGenerator,
               eval_steps: int,
               state: Optional[TrainState] = None) -> Dict[str, float]:
    """Averaged eval metrics over ``eval_steps`` batches (ref model_eval_fn)."""
    input_generator = provide_input_generator_with_model_information(
        input_generator, self.model, ModeKeys.EVAL)
    iterator = input_generator.create_dataset_iterator(mode=ModeKeys.EVAL)
    batch = next(iterator)
    if state is None:
      # The init batch is still scored below — no data is skipped.
      state = self.init_state(*batch, mode=ModeKeys.EVAL)
    self.last_eval_state = state
    eval_fn = self._compile_eval_step()
    totals: Dict[str, float] = {}
    count = 0
    last_batch = None
    for _ in range(eval_steps):
      if batch is None:
        try:
          batch = next(iterator)
        except StopIteration:
          break
      features, labels = batch
      batch = None
      device_batch = self._put_batch(
          {'features': features.to_dict(),
           'labels': labels.to_dict() if labels is not None else None},
          channel='eval')
      metrics = jax.device_get(
          eval_fn(state, device_batch['features'], device_batch['labels']))
      for key, value in metrics.items():
        totals[key] = totals.get(key, 0.0) + float(np.mean(value))
      count += 1
      last_batch = (features, labels)
    averaged = {k: v / max(count, 1) for k, v in totals.items()}
    writer = self.eval_metrics_writer
    if writer is not None:
      step = int(jax.device_get(state.step))
      writer.write_scalars(step, averaged)
      self._write_model_summaries(writer, state, last_batch, step)
      writer.flush()
    return averaged

  def _compile_summary_step(self):
    """Jitted (preprocess + forward) for add_summaries, like eval/predict."""
    if getattr(self, '_summary_step_fn', None) is not None:
      return self._summary_step_fn
    model = self.model
    use_avg = self.use_avg_params_for_eval

    def step(state, features, labels):
      features, labels = model.preprocessor.preprocess(
          SpecStruct(**features),
          SpecStruct(**labels) if labels is not None else None,
          ModeKeys.EVAL, rng=None)
      variables = state.variables(use_avg_params=use_avg)
      outputs, _ = model.inference_network_fn(
          variables, features, labels, ModeKeys.EVAL, None)
      return dict(features), (dict(labels) if labels is not None else None), \
          dict(outputs)

    batch = self._batch_sharding()
    self._summary_step_fn = jax.jit(
        step, in_shardings=(self._state_sharding, batch, batch))
    return self._summary_step_fn

  def _write_model_summaries(self, writer, state, batch, step: int) -> None:
    """Model-provided rich summaries for one eval batch (ref add_summaries).

    Runs one jitted forward pass on the last eval batch and hands host
    arrays to ``model.add_summaries``; whatever comes back lands in the
    eval events.
    """
    if batch is None or self.model.add_summaries.__func__ is \
        ModelInterface.add_summaries:
      return  # default no-op implementation: skip the extra forward pass
    try:
      raw_features, raw_labels = batch
      device_batch = self._put_batch(
          {'features': raw_features.to_dict(),
           'labels': raw_labels.to_dict() if raw_labels is not None
           else None},
          channel='summary')
      features, labels, outputs = self._compile_summary_step()(
          state, device_batch['features'], device_batch['labels'])
      host = jax.device_get
      summaries = self.model.add_summaries(
          host(features),
          host(labels) if labels is not None else None,
          host(outputs), ModeKeys.EVAL)
      if not summaries:
        return
      if summaries.get('scalars'):
        writer.write_scalars(step, summaries['scalars'])
      if summaries.get('images'):
        writer.write_images(step, summaries['images'])
      if summaries.get('histograms'):
        writer.write_histograms(step, summaries['histograms'])
    except Exception as e:  # noqa: BLE001 — summaries never fail an eval
      _log('add_summaries failed: %s', e)

  def predict(self, state: TrainState, features: SpecStruct
              ) -> Dict[str, np.ndarray]:
    """Numpy-in / numpy-out serving forward pass."""
    device_features = sharding_lib.shard_batch(
        SpecStruct(**features).to_dict()
        if not isinstance(features, SpecStruct) else features.to_dict(),
        self.mesh)
    return jax.device_get(self._compile_predict_step()(state,
                                                       device_features))

  # -- checkpoint/export ----------------------------------------------------

  def save_checkpoint(self, state: TrainState, force: bool = False) -> None:
    step = int(jax.device_get(state.step))
    # Settle our own in-flight async save first: reload() replaces orbax's
    # cached step list (which includes in-flight saves) with the on-disk
    # view (which does not), so reloading mid-commit would let the dedupe
    # below miss our own save and race it. This wait is also where a
    # transient failure of the PREVIOUS async commit surfaces — absorb it
    # (one lost intermediate checkpoint, logged) and let this save commit
    # the current, newer state instead of killing the run.
    try:
      self.checkpoint_manager.wait_until_finished()
      self._async_commit_failures = 0
    except Exception as e:  # noqa: BLE001 — async commit of an older step
      self._async_commit_failures = getattr(
          self, '_async_commit_failures', 0) + 1
      if self._async_commit_failures >= 3:
        # The filesystem is not blipping, it is down: losing every
        # intermediate checkpoint silently is worse than failing the run.
        raise
      _log('Async commit of a previous checkpoint failed (%s); '
           'continuing with the save of step %d (%d consecutive '
           'failure(s) tolerated before raising).', e, step,
           self._async_commit_failures)
    # Re-read disk before the dedupe check: a concurrent trainer (or a
    # previous incarnation of this one, pre-preemption) may have committed
    # this step already — re-saving would race its commit.
    self.checkpoint_manager.reload()
    if step in self.checkpoint_manager.all_steps():
      return
    if self.checkpoint_manager.save(step, state, force=force):
      # The t2r_assets contract: feature/label specs + global step live
      # next to the weights (ref utils/train_eval.py:296-370).
      assets_lib.write_t2r_assets_to_file(
          self.model.get_feature_specification(ModeKeys.TRAIN),
          self.model.get_label_specification(ModeKeys.TRAIN),
          step, os.path.join(self.model_dir,
                             assets_lib.EXTRA_ASSETS_DIRECTORY,
                             assets_lib.T2R_ASSETS_FILENAME))

  @property
  def last_throughput(self):
    return self._throughput

  def close(self) -> None:
    self.checkpoint_manager.wait_until_finished()
    self.checkpoint_manager.close()
    for writer in (self._train_writer, self._eval_writer, self._telemetry):
      if writer is not None:
        writer.close()
    if self._shared_telemetry is not None:
      # Shared stream: flush but never close — its owner (the elastic
      # driver) outlives this per-epoch trainer.
      self._shared_telemetry.flush()
    self._train_writer = self._eval_writer = self._telemetry = None


def _maybe_snapshot_config(model_dir: str,
                           filename: str = 'config_snapshot.gin',
                           operative: bool = False) -> None:
  """Writes the active config bindings into model_dir (the reference's
  GinConfigSaverHook, ref models/abstract_model.py:762-764)."""
  try:
    from tensor2robot_tpu.config import ginlike
    text = (ginlike.operative_config_str() if operative
            else ginlike.config_str())
    if text.strip():
      with open(os.path.join(model_dir, filename), 'w') as f:
        f.write(text)
  except Exception as e:  # noqa: BLE001 — snapshots must never kill a run
    _log('Config snapshot (%s) failed: %s', filename, e)


def train_eval_model(t2r_model: AbstractT2RModel,
                     model_dir: str,
                     input_generator_train: Optional[AbstractInputGenerator] = None,
                     input_generator_eval: Optional[AbstractInputGenerator] = None,
                     max_train_steps: int = 1000,
                     eval_steps: int = 100,
                     eval_throttle_steps: int = 500,
                     create_exporters_fn: Optional[Callable] = None,
                     train_hook_builders: Sequence[Any] = (),
                     mesh: Optional[Mesh] = None,
                     use_fsdp: bool = False,
                     tp_rules: Optional[Sequence[Tuple[str, Any]]] = None,
                     keep_checkpoint_max: int = 5,
                     save_checkpoints_steps: int = 500,
                     async_checkpoints: bool = True,
                     seed: int = 0,
                     eval_timeout_secs: float = 30.0,
                     write_metrics: bool = True,
                     eval_name: Optional[str] = None,
                     profile_steps: Optional[Sequence[int]] = None,
                     auto_profile: bool = True,
                     tuned_config: Optional[Any] = None,
                     use_compiled_artifacts: bool = False,
                     artifact_workload: Optional[str] = None
                     ) -> Dict[str, Any]:
  """Main entry point (ref utils/train_eval.py:404).

  Modes, mirroring the reference's Estimator dispatch:
    * train+eval: alternate train phases (``eval_throttle_steps`` apart)
      with ``eval_steps``-batch evals, exporters after each eval.
    * train-only (no eval generator): straight run to max_train_steps.
    * eval-only (no train generator): continuous eval — poll for new
      checkpoints until timeout (ref :552-594).
  Returns {'state', 'eval_metrics', 'trainer'}.
  """
  if t2r_model.is_device_tpu:
    # Host pipeline feeds bf16 directly (ref TPUPreprocessorWrapper).
    preprocessor = t2r_model.preprocessor
    if not isinstance(preprocessor, Bfloat16PreprocessorWrapper):
      t2r_model.set_preprocessor(Bfloat16PreprocessorWrapper(preprocessor))

  if eval_name is None and input_generator_eval is not None:
    # Multi-eval jobs route their events to eval_<name> dirs keyed by
    # TF_CONFIG.multi_eval_name (ref utils/train_eval.py:522-547).
    eval_name = getattr(input_generator_eval, 'multi_eval_name', None)
  trainer = Trainer(
      t2r_model, model_dir, mesh=mesh, use_fsdp=use_fsdp,
      tp_rules=tp_rules, seed=seed,
      keep_checkpoint_max=keep_checkpoint_max,
      save_checkpoints_steps=save_checkpoints_steps,
      async_checkpoints=async_checkpoints,
      write_metrics=write_metrics,
      eval_name=eval_name,
      profile_steps=profile_steps,
      auto_profile=auto_profile,
      tuned_config=tuned_config,
      use_compiled_artifacts=use_compiled_artifacts,
      artifact_workload=artifact_workload,
      # An eval-only job reads checkpoints a separate trainer process is
      # writing: it must never rename (quarantine) step dirs there.
      owns_checkpoint_dir=input_generator_train is not None)
  _maybe_snapshot_config(model_dir)

  hooks: List[Any] = []
  for builder in train_hook_builders:
    hooks.extend(builder.create_hooks(t2r_model, trainer))

  exporters = (create_exporters_fn(t2r_model) if create_exporters_fn
               else [])

  state = None
  eval_metrics: Dict[str, float] = {}

  def _run_exporters(current_state, metrics):
    for exporter in exporters:
      exporter.export(trainer, current_state, metrics)

  try:
    if input_generator_train is not None and input_generator_eval is not None:
      target = 0
      while target < max_train_steps:
        target = min(target + eval_throttle_steps, max_train_steps)
        state = trainer.train(input_generator_train, target, state=state,
                              hooks=hooks)
        eval_metrics = trainer.evaluate(input_generator_eval, eval_steps,
                                        state=state)
        _log('eval @ step %d: %s', target, eval_metrics)
        _run_exporters(state, eval_metrics)
    elif input_generator_train is not None:
      state = trainer.train(input_generator_train, max_train_steps,
                            hooks=hooks)
    elif input_generator_eval is not None:
      for step in checkpointing.checkpoints_iterator(
          model_dir, timeout_secs=eval_timeout_secs):
        try:
          # state=None: evaluate re-restores the newest checkpoint itself
          # (falling back to an older committed step when the newest is
          # half-written or GC'd, Trainer.init_state).
          eval_metrics = trainer.evaluate(input_generator_eval, eval_steps)
        except CHECKPOINT_SKIP_ERRORS as e:
          # No committed step was restorable right now — a concurrent
          # trainer may still be mid-commit; keep polling instead of
          # dying. The narrow tuple matters: a data-layer OSError from
          # the eval pipeline itself (missing dataset, corruption budget)
          # is NOT a checkpoint problem and propagates.
          _log('Continuous eval: checkpoint %d unrestorable (%s); '
               'skipping.', step, e)
          continue
        _log('continuous eval @ ckpt %d: %s', step, eval_metrics)
        state = trainer.last_eval_state
        _run_exporters(state, eval_metrics)
    else:
      raise ValueError('Provide at least one of train/eval input generators.')
  finally:
    _maybe_snapshot_config(model_dir, 'operative_config.gin', operative=True)
    trainer.close()
  return {'state': state, 'eval_metrics': eval_metrics, 'trainer': trainer}
