"""MetricsWriter: TensorBoard-format event files, dependency-free.

Parity target: the reference's tf.summary system — scalar/image summaries
from add_summaries (ref models/abstract_model.py:556-874), eval metric
events and per-eval-run dirs (ref utils/train_eval.py:539-547). The
tensorflow Event/Summary protos are tiny; they are emitted directly with
the same wire-format helpers as the TFRecord codec, so training produces
real `events.out.tfevents.*` files TensorBoard loads — without importing
TensorFlow on the trainer's hot path.

Event wire layout (tensorflow/core/util/event.proto):
  Event { double wall_time=1; int64 step=2; string file_version=3;
          Summary summary=5; }
  Summary { repeated Value value=1; }
  Value { string tag=1; float simple_value=2; Image image=4;
          HistogramProto histo=5; }
  Image { int32 height=1; int32 width=2; int32 colorspace=3;
          bytes encoded_image_string=4; }
  HistogramProto { double min=1..sum_squares=5;
                   repeated double bucket_limit=6, bucket=7 (packed); }
"""

from __future__ import annotations

import io
import os
import socket
import struct
import time
from typing import Dict, Optional, Sequence

import numpy as np

from tensor2robot_tpu.data.tfrecord import TFRecordWriter
from tensor2robot_tpu.data.wire import emit_bytes_field, write_varint


def _emit_varint_field(out: bytearray, field: int, value: int) -> None:
  write_varint(out, (field << 3) | 0)
  write_varint(out, value & 0xFFFFFFFFFFFFFFFF)


def _emit_double_field(out: bytearray, field: int, value: float) -> None:
  write_varint(out, (field << 3) | 1)
  out.extend(struct.pack('<d', value))


def _emit_float_field(out: bytearray, field: int, value: float) -> None:
  write_varint(out, (field << 3) | 5)
  out.extend(struct.pack('<f', value))


def _encode_image(image: np.ndarray) -> bytes:
  """Summary.Image message for one [H, W, C] array (PNG-encoded)."""
  from PIL import Image as PILImage

  if image.dtype != np.uint8:
    image = (np.clip(np.asarray(image, np.float32), 0.0, 1.0)
             * 255.0).astype(np.uint8)
  if image.ndim == 3 and image.shape[-1] == 1:
    image = image[..., 0]
  buf = io.BytesIO()
  PILImage.fromarray(image).save(buf, format='PNG')
  out = bytearray()
  height, width = image.shape[:2]
  colorspace = 1 if image.ndim == 2 else image.shape[-1]
  _emit_varint_field(out, 1, height)
  _emit_varint_field(out, 2, width)
  _emit_varint_field(out, 3, colorspace)
  emit_bytes_field(out, 4, buf.getvalue())
  return bytes(out)


# TF's default histogram bucket boundaries: exponential, 1e-12 * 1.1^k.
def _default_bucket_limits() -> np.ndarray:
  positive = []
  v = 1e-12
  while v < 1e20:
    positive.append(v)
    v *= 1.1
  positive = np.asarray(positive)
  return np.concatenate([-positive[::-1], [0.0], positive, [np.inf]])


_BUCKET_LIMITS = _default_bucket_limits()


def _encode_histogram(values: np.ndarray) -> bytes:
  """HistogramProto message for a 1-D sample array."""
  values = np.asarray(values, np.float64).ravel()
  counts, _ = np.histogram(
      values, bins=np.concatenate([[-np.inf], _BUCKET_LIMITS]))
  nonzero = np.nonzero(counts)[0]
  out = bytearray()
  _emit_double_field(out, 1, float(values.min()) if values.size else 0.0)
  _emit_double_field(out, 2, float(values.max()) if values.size else 0.0)
  _emit_double_field(out, 3, float(values.size))
  _emit_double_field(out, 4, float(values.sum()))
  _emit_double_field(out, 5, float(np.sum(values ** 2)))
  if nonzero.size:
    last = nonzero[-1] + 1
    limits = bytearray()
    buckets = bytearray()
    for i in range(last):
      limits.extend(struct.pack('<d', min(_BUCKET_LIMITS[i], 1e308)))
      buckets.extend(struct.pack('<d', float(counts[i])))
    emit_bytes_field(out, 6, bytes(limits))  # packed repeated double
    emit_bytes_field(out, 7, bytes(buckets))
  return bytes(out)


def _encode_value(tag: str, *, simple_value: Optional[float] = None,
                  image: Optional[np.ndarray] = None,
                  histogram: Optional[np.ndarray] = None) -> bytes:
  out = bytearray()
  emit_bytes_field(out, 1, tag.encode('utf-8'))
  if simple_value is not None:
    _emit_float_field(out, 2, float(simple_value))
  if image is not None:
    emit_bytes_field(out, 4, _encode_image(image))
  if histogram is not None:
    emit_bytes_field(out, 5, _encode_histogram(histogram))
  return bytes(out)


def _encode_event(step: int, values: Sequence[bytes] = (),
                  file_version: Optional[str] = None,
                  wall_time: Optional[float] = None) -> bytes:
  out = bytearray()
  # wall-clock timestamp: TensorBoard's event wall_time field.
  _emit_double_field(out, 1, time.time() if wall_time is None else wall_time)  # wall-clock
  _emit_varint_field(out, 2, int(step))
  if file_version is not None:
    emit_bytes_field(out, 3, file_version.encode('utf-8'))
  if values:
    summary = bytearray()
    for value in values:
      emit_bytes_field(summary, 1, value)
    emit_bytes_field(out, 5, bytes(summary))
  return bytes(out)


class MetricsWriter:
  """Writes TensorBoard event files into ``log_dir``."""

  def __init__(self, log_dir: str):
    os.makedirs(log_dir, exist_ok=True)
    self.log_dir = log_dir
    filename = 'events.out.tfevents.{:d}.{}'.format(
        int(time.time()), socket.gethostname())  # wall-clock filename stamp
    self._writer = TFRecordWriter(os.path.join(log_dir, filename))
    self._writer.write(_encode_event(0, file_version='brain.Event:2'))

  def write_scalars(self, step: int, scalars: Dict[str, float]) -> None:
    values = [_encode_value(tag, simple_value=float(np.mean(value)))
              for tag, value in scalars.items()]
    self._writer.write(_encode_event(step, values))

  def write_images(self, step: int, images: Dict[str, np.ndarray],
                   max_outputs: int = 3) -> None:
    """Each entry is [N, H, W, C] (first ``max_outputs`` logged) or [H, W, C]."""
    values = []
    for tag, batch in images.items():
      batch = np.asarray(batch)
      if batch.ndim == 3:
        batch = batch[None]
      for i, image in enumerate(batch[:max_outputs]):
        suffix = '' if batch.shape[0] == 1 else '/{:d}'.format(i)
        values.append(_encode_value(tag + suffix, image=image))
    self._writer.write(_encode_event(step, values))

  def write_histograms(self, step: int,
                       histograms: Dict[str, np.ndarray]) -> None:
    values = [_encode_value(tag, histogram=np.asarray(value))
              for tag, value in histograms.items()]
    self._writer.write(_encode_event(step, values))

  def flush(self) -> None:
    self._writer.flush()

  def close(self) -> None:
    self._writer.close()


def read_events(log_dir: str):
  """Parses all event files in a dir -> list of (step, {tag: value}).

  Scalar values come back as floats; images as {'png': bytes, 'height',
  'width'}; histograms as {'num', 'sum', 'min', 'max'}. Used by tests and
  by exporter compare-fns.
  """
  from tensor2robot_tpu.data.tfrecord import tfrecord_iterator
  from tensor2robot_tpu.data.wire import iter_fields

  events = []
  for name in sorted(os.listdir(log_dir)):
    if 'tfevents' not in name:
      continue
    for record in tfrecord_iterator(os.path.join(log_dir, name)):
      step = 0
      tags: Dict[str, object] = {}
      summary_payload = None
      for field, wire_type, value in iter_fields(record, 0, len(record)):
        if field == 2 and wire_type == 0:
          step = value
        elif field == 5 and wire_type == 2:
          summary_payload = record[value[0]:value[1]]
      if summary_payload is None:
        continue
      for field, wire_type, value in iter_fields(summary_payload, 0,
                                                  len(summary_payload)):
        if field != 1 or wire_type != 2:
          continue
        tag, parsed = _parse_summary_value(
            summary_payload[value[0]:value[1]])
        if tag is not None:
          tags[tag] = parsed
      events.append((step, tags))
  return events


def _parse_summary_value(payload: bytes):
  from tensor2robot_tpu.data.wire import iter_fields

  def _bytes(span):
    return payload[span[0]:span[1]]

  tag = None
  parsed = None
  for field, wire_type, value in iter_fields(payload, 0, len(payload)):
    if field == 1 and wire_type == 2:
      tag = _bytes(value).decode('utf-8')
    elif field == 2 and wire_type == 5:
      parsed = struct.unpack('<f', _bytes(value))[0]
    elif field == 4 and wire_type == 2:
      sub = _bytes(value)
      image = {}
      for f2, w2, v2 in iter_fields(sub, 0, len(sub)):
        if f2 == 1 and w2 == 0:
          image['height'] = v2
        elif f2 == 2 and w2 == 0:
          image['width'] = v2
        elif f2 == 4 and w2 == 2:
          image['png'] = sub[v2[0]:v2[1]]
      parsed = image
    elif field == 5 and wire_type == 2:
      sub = _bytes(value)
      histo = {}
      names = {1: 'min', 2: 'max', 3: 'num', 4: 'sum', 5: 'sum_squares'}
      for f2, w2, v2 in iter_fields(sub, 0, len(sub)):
        if f2 in names and w2 == 1:
          histo[names[f2]] = struct.unpack('<d', sub[v2[0]:v2[1]])[0]
      parsed = histo
  return tag, parsed
