"""MAML preprocessor: wraps any base preprocessor's specs into the meta layout.

Parity target: /root/reference/meta_learning/preprocessors.py:39-135
(create_maml_feature_spec :39, create_maml_label_spec :74, MAMLPreprocessorV2
:89). The meta layout (flat keys):

  condition/features/<k>   [num_tasks, num_condition_samples, ...]
  condition/labels/<k>     inner-loop adaptation data
  inference/features/<k>   [num_tasks, num_inference_samples, ...]
  <label k>                outer-loss labels (names prefixed 'meta_labels/')

The base preprocessor's transform is applied per sample via
``multi_batch_apply`` over the [task, sample] leading dims — inside the
jitted step, so image distortions etc. still run fused on device.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax

from tensor2robot_tpu.meta_learning import meta_data
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.specs.algebra import (
    copy_tensorspec,
    flatten_spec_structure,
)
from tensor2robot_tpu.specs.struct import SpecStruct


def create_maml_feature_spec(feature_spec, label_spec,
                             num_condition_samples_per_task: int = -1,
                             num_inference_samples_per_task: int = -1
                             ) -> SpecStruct:
  """Base feature+label specs -> meta feature spec (ref :39).

  Condition keeps the base names (so record parsing maps 1:1); specs gain a
  leading samples dim — unknown by default (the reference's batch_size=-1),
  or fixed when sample counts are given (the FixedLenMetaExample layout,
  ref preprocessors.py:346).
  """
  meta = SpecStruct()
  for key, spec in copy_tensorspec(
      feature_spec, batch_size=num_condition_samples_per_task,
      prefix='condition_features').items():
    meta['condition/features/' + key] = spec
  for key, spec in copy_tensorspec(
      label_spec, batch_size=num_condition_samples_per_task,
      prefix='condition_labels').items():
    meta['condition/labels/' + key] = spec
  for key, spec in copy_tensorspec(
      feature_spec, batch_size=num_inference_samples_per_task,
      prefix='inference_features').items():
    meta['inference/features/' + key] = spec
  return meta


def create_maml_label_spec(label_spec,
                           num_inference_samples_per_task: int = -1
                           ) -> SpecStruct:
  """Base label spec -> outer-loss label spec (ref :74)."""
  return flatten_spec_structure(
      copy_tensorspec(label_spec, batch_size=num_inference_samples_per_task,
                      prefix='meta_labels'))


class MAMLPreprocessorV2(AbstractPreprocessor):
  """Meta-wrapper around a base preprocessor (ref :89)."""

  def __init__(self, base_preprocessor: AbstractPreprocessor):
    super().__init__()
    self._base_preprocessor = base_preprocessor

  @property
  def base_preprocessor(self) -> AbstractPreprocessor:
    return self._base_preprocessor

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    return create_maml_feature_spec(
        self._base_preprocessor.get_in_feature_specification(mode),
        self._base_preprocessor.get_in_label_specification(mode))

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return create_maml_label_spec(
        self._base_preprocessor.get_in_label_specification(mode))

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return create_maml_feature_spec(
        self._base_preprocessor.get_out_feature_specification(mode),
        self._base_preprocessor.get_out_label_specification(mode))

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return create_maml_label_spec(
        self._base_preprocessor.get_out_label_specification(mode))

  def _preprocess_fn(self, features, labels, mode: str, rng=None
                     ) -> Tuple[SpecStruct, Optional[SpecStruct]]:
    """Base transform per sample over the [task, sample] dims."""
    base = self._base_preprocessor
    rngs = jax.random.split(rng, 3) if rng is not None else (None, None, None)

    def _sub(struct, prefix):
      out = SpecStruct()
      for key in struct:
        if key.startswith(prefix):
          out[key[len(prefix):]] = struct[key]
      return out

    def _apply(feats, labs, sub_rng):
      def fn(f, l):
        return base._preprocess_fn(SpecStruct(**f), SpecStruct(**l) if l
                                   else None, mode, sub_rng)
      out_f, out_l = meta_data.multi_batch_apply(
          fn, 2, dict(feats), dict(labs) if labs is not None else {})
      return out_f, out_l

    cond_f, cond_l = _apply(_sub(features, 'condition/features/'),
                            _sub(features, 'condition/labels/'), rngs[0])
    # Meta (outer-loss) labels are the base-preprocessed inference-split
    # labels: the reference splits AFTER base preprocessing (ref map_fn in
    # preprocessors.py), so they must see the same label transform
    # (cast/normalize/one-hot) the condition labels do — paired with the
    # inference features they belong to.
    inf_f, out_labels = _apply(_sub(features, 'inference/features/'),
                               labels, rngs[1])
    out = SpecStruct()
    for key in cond_f:
      out['condition/features/' + key] = cond_f[key]
    for key in (cond_l or {}):
      out['condition/labels/' + key] = cond_l[key]
    for key in inf_f:
      out['inference/features/' + key] = inf_f[key]
    return out, (SpecStruct(**out_labels) if labels is not None and out_labels
                 else None)


class FixedLenMetaExamplePreprocessor(MAMLPreprocessorV2):
  """Meta preprocessor with FIXED condition/inference sample counts.

  Parity: /root/reference/meta_learning/preprocessors.py:346
  (FixedLenMetaExamplePreprocessor). Standalone meta models (TEC, WTL
  trial/retrial) consume the meta layout directly with known episode
  counts, so their specs carry concrete sample dims instead of the
  MAMLPreprocessorV2's unknown dim.
  """

  def __init__(self, base_preprocessor: AbstractPreprocessor,
               num_condition_samples_per_task: int = 1,
               num_inference_samples_per_task: int = 1):
    super().__init__(base_preprocessor)
    self._num_condition_samples_per_task = num_condition_samples_per_task
    self._num_inference_samples_per_task = num_inference_samples_per_task

  @property
  def num_condition_samples_per_task(self) -> int:
    return self._num_condition_samples_per_task

  @property
  def num_inference_samples_per_task(self) -> int:
    return self._num_inference_samples_per_task

  def get_in_feature_specification(self, mode: str) -> SpecStruct:
    return create_maml_feature_spec(
        self._base_preprocessor.get_in_feature_specification(mode),
        self._base_preprocessor.get_in_label_specification(mode),
        self._num_condition_samples_per_task,
        self._num_inference_samples_per_task)

  def get_in_label_specification(self, mode: str) -> SpecStruct:
    return create_maml_label_spec(
        self._base_preprocessor.get_in_label_specification(mode),
        self._num_inference_samples_per_task)

  def get_out_feature_specification(self, mode: str) -> SpecStruct:
    return create_maml_feature_spec(
        self._base_preprocessor.get_out_feature_specification(mode),
        self._base_preprocessor.get_out_label_specification(mode),
        self._num_condition_samples_per_task,
        self._num_inference_samples_per_task)

  def get_out_label_specification(self, mode: str) -> SpecStruct:
    return create_maml_label_spec(
        self._base_preprocessor.get_out_label_specification(mode),
        self._num_inference_samples_per_task)
