"""Demo -> adaptation -> trial episode loop for meta-RL eval.

Parity target: /root/reference/meta_learning/run_meta_env.py:37-262. Per
task: reset the task, collect demonstration episodes (demo policy or the
env's own task data), ``policy.adapt(condition_data)``, then run
``num_adaptations_per_task`` rounds of trial episodes — re-adapting on the
growing condition set each round so per-step reward improvement measures
fast adaptation. Metrics land in ``metrics-<tag>.jsonl`` (the run_env
convention) instead of TF summaries.
"""

from __future__ import annotations

import collections
import copy
import datetime
import os
from typing import Callable, Optional

import numpy as np

from tensor2robot_tpu.rl.run_env import _log, _write_metrics


def run_meta_env(env,
                 policy=None,
                 demo_policy_cls: Optional[Callable] = None,
                 explore_schedule=None,
                 episode_to_transitions_fn: Optional[Callable] = None,
                 replay_writer=None,
                 root_dir: Optional[str] = None,
                 task: int = 0,
                 global_step: int = 0,
                 num_episodes=None,
                 num_tasks: int = 10,
                 num_adaptations_per_task: int = 2,
                 num_episodes_per_adaptation: int = 1,
                 num_demos: int = 1,
                 break_after_one_task: bool = False,
                 tag: str = 'collect',
                 write_summary: bool = False,
                 write_meta_examples: bool = False):
  """See module docstring; args mirror the reference (:54-88).

  ``write_meta_examples``: instead of writing per-episode transition
  records, group each task's episodes into ONE meta-example record —
  demos under condition_ep*, trials under inference_ep* (the reference's
  make_meta_example contract, meta_example.py:34-72) — which
  MetaExampleInputGenerator reads back for MAML training. Requires
  ``episode_to_transitions_fn`` (its per-episode examples are the merge
  inputs) plus ``replay_writer`` and ``root_dir``.
  """
  del num_episodes  # ref :90 — num_tasks drives the loop
  if write_meta_examples and episode_to_transitions_fn is None:
    raise ValueError(
        'write_meta_examples requires episode_to_transitions_fn.')

  task_step_rewards = collections.defaultdict(
      lambda: collections.defaultdict(list))
  episode_q_values = collections.defaultdict(list)

  def _run_demo_episode():
    obs = env.reset()
    demo_policy = demo_policy_cls(env)
    episode_data = []
    while True:
      action, _ = demo_policy.sample_action(obs, 0)
      if action is None:
        break
      next_obs, rew, done, debug = env.step(action)
      debug = dict(debug or {})
      debug['is_demo'] = True
      episode_data.append((obs, action, rew, next_obs, done, debug))
      obs = next_obs
      if done:
        break
    return episode_data

  for task_idx in range(num_tasks):
    if hasattr(policy, 'reset_task'):
      policy.reset_task()
    env.reset_task()
    record_name = None
    if root_dir and replay_writer:
      timestamp = datetime.datetime.now().strftime('%Y-%m-%d-%H-%M-%S')
      record_name = os.path.join(root_dir, 'gs{}_t{}_{}_{}'.format(
          global_step, task, timestamp, task_idx))
      os.makedirs(root_dir, exist_ok=True)
      replay_writer.open(record_name)

    condition_data = []
    condition_examples, inference_examples = [], []
    if demo_policy_cls is not None and hasattr(policy, 'adapt'):
      for _ in range(num_demos):
        episode_data = _run_demo_episode()
        condition_data.append(episode_data)
        # Gated on record_name (not just the writer): without root_dir
        # the writer was never opened (matches rl/run_env.py:96-100).
        if record_name and episode_to_transitions_fn:
          examples = episode_to_transitions_fn(episode_data)
          if write_meta_examples:
            condition_examples.extend(examples)
          else:
            replay_writer.write(examples)
      policy.adapt(copy.copy(condition_data))
    elif hasattr(env, 'task_data') and hasattr(policy, 'adapt'):
      # Record-backed envs carry their own conditioning episodes (ref :170).
      for episode_name, episode_data in env.task_data.items():
        if str(episode_name).startswith('condition_ep'):
          condition_data.append(episode_data)
          if write_meta_examples and record_name:
            condition_examples.extend(episode_to_transitions_fn(episode_data))
      policy.adapt(copy.copy(condition_data))

    for step_num in range(num_adaptations_per_task):
      if step_num != 0 and hasattr(policy, 'adapt'):
        policy.adapt(copy.copy(condition_data))
      for ep in range(num_episodes_per_adaptation):
        done, env_step, episode_reward, episode_data = False, 0, 0.0, []
        policy.reset()
        obs = env.reset()
        explore_prob = (explore_schedule.value(global_step)
                        if explore_schedule else 0)
        while not done:
          debug = {}
          action, policy_debug = policy.sample_action(obs, explore_prob)
          if policy_debug is not None:
            debug.update(policy_debug)
          if policy_debug and 'q_predicted' in policy_debug:
            episode_q_values[env_step].append(policy_debug['q_predicted'])
          new_obs, rew, done, env_debug = env.step(action)
          debug.update(env_debug or {})
          env_step += 1
          episode_reward += rew
          episode_data.append((obs, action, rew, new_obs, done, debug))
          obs = new_obs
          if done:
            _log('Step %d episode %d reward: %f', step_num, ep,
                 episode_reward)
            task_step_rewards[task_idx][step_num].append(episode_reward)
            if record_name and episode_to_transitions_fn:
              examples = episode_to_transitions_fn(episode_data)
              if write_meta_examples:
                inference_examples.extend(examples)
              else:
                replay_writer.write(examples)
        condition_data.append(episode_data)
    _log('Task %d avg reward: %f', task_idx,
         np.mean(task_step_rewards[task_idx][num_adaptations_per_task - 1]))

    if write_meta_examples and record_name:
      if not condition_examples or not inference_examples:
        # Silently dropping the task would leave an empty record file the
        # reader later rejects; fail with the actionable cause instead.
        raise ValueError(
            'write_meta_examples: task {} collected {} condition and {} '
            'inference examples; both sides need at least one (provide a '
            'demo_policy_cls or env.task_data conditioning episodes).'
            .format(task_idx, len(condition_examples),
                    len(inference_examples)))
      from tensor2robot_tpu.meta_learning.meta_example import (
          make_meta_example,
      )
      replay_writer.write(make_meta_example(condition_examples,
                                            inference_examples))
    if replay_writer and record_name:
      replay_writer.close()
    if break_after_one_task:
      break

  if root_dir and write_summary:
    values = {}
    ran_tasks = sorted(task_step_rewards)
    for step_num in range(num_adaptations_per_task):
      step_rewards = [np.mean(task_step_rewards[t][step_num])
                      for t in ran_tasks]
      values['step_{}_reward'.format(step_num)] = float(np.mean(step_rewards))
      if step_num > 0:
        delta = np.mean([
            np.mean(task_step_rewards[t][step_num]) -
            np.mean(task_step_rewards[t][step_num - 1]) for t in ran_tasks])
        values['step_{}_improvement'.format(step_num)] = float(delta)
    for step, q_values in episode_q_values.items():
      values['Q/{}'.format(step)] = float(np.mean(q_values))
    _write_metrics(os.path.join(root_dir, 'live_eval_{}'.format(task)), tag,
                   global_step, values)
  return task_step_rewards
