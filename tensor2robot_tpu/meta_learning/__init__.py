"""Meta-learning: MAML models, preprocessors, task-batched data utilities.

Deliberate non-port: the reference's legacy v1 meta models
(/root/reference/meta_learning/meta_tf_models.py:126,:244 —
MetaPreprocessor/MetalearningModel over TrainValPair) are deprecated
within the reference itself in favor of MAMLModel/MAMLPreprocessorV2,
which is the surface implemented here; nothing in the reference's research
workloads consumes the v1 API.
"""

from tensor2robot_tpu.meta_learning.maml_inner_loop import (
    MAMLInnerLoopGradientDescent,
)
from tensor2robot_tpu.meta_learning.maml_model import (
    MAMLModel,
    MAMLRegressionModel,
)
from tensor2robot_tpu.meta_learning.preprocessors import (
    MAMLPreprocessorV2,
    create_maml_feature_spec,
    create_maml_label_spec,
)
from tensor2robot_tpu.meta_learning import meta_data
from tensor2robot_tpu.meta_learning.meta_policies import (
    MAMLCEMPolicy,
    MAMLRegressionPolicy,
    MetaLearningPolicy,
    ScheduledExplorationMAMLRegressionPolicy,
)
from tensor2robot_tpu.meta_learning.run_meta_env import run_meta_env

__all__ = [
    'MAMLCEMPolicy',
    'MAMLInnerLoopGradientDescent',
    'MAMLModel',
    'MAMLPreprocessorV2',
    'MAMLRegressionModel',
    'MAMLRegressionPolicy',
    'MetaLearningPolicy',
    'ScheduledExplorationMAMLRegressionPolicy',
    'create_maml_feature_spec',
    'create_maml_label_spec',
    'meta_data',
    'run_meta_env',
]
