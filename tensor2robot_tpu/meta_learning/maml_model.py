"""MAMLModel: wraps any base T2RModel for gradient-based meta-learning.

Parity target: /root/reference/meta_learning/maml_model.py:76-554. The
reference vectorizes the per-task inner loop with tf.map_fn (inferring
output dtypes by building the base model in a throwaway graph, :154-189);
here the per-task adaptation is a pure function ``vmap``ped over the task
dim — dtypes are free, batch norm works, and the outer ``jax.grad``
differentiates straight through (second-order MAML) as one XLA program.

Predictions layout matches the reference (:327-359):
  full_condition_outputs/output_<i>/<k>  per-inner-step outputs (k+1 entries)
  full_condition_output/<k>              == output_0 (pre-adaptation)
  full_inference_output/<k>              post-adaptation val outputs
  full_inference_output_unconditioned/<k>
  inner_losses/step_<i>                  mean inner loss per step
plus 'condition_output'/'inference_output' assigned by
``_select_inference_output``.
"""

from __future__ import annotations

import abc
from typing import Any, Optional

import jax
import jax.numpy as jnp

from tensor2robot_tpu.meta_learning import meta_data
from tensor2robot_tpu.meta_learning import preprocessors as meta_preprocessors
from tensor2robot_tpu.meta_learning.maml_inner_loop import (
    MAMLInnerLoopGradientDescent,
)
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs.struct import SpecStruct

INNER_LRS_KEY = 'maml_inner_lrs'


def _sub_dict(struct, prefix: str) -> dict:
  out = {}
  for key in struct:
    if key.startswith(prefix):
      out[key[len(prefix):]] = struct[key]
  return out


class MAMLModel(AbstractT2RModel):
  """Base class for MAML-style meta models (ref :76)."""

  def __init__(self,
               base_model: AbstractT2RModel,
               preprocessor_cls=None,
               num_inner_loop_steps: int = 1,
               var_scope: Optional[str] = None,
               inner_loop: Optional[MAMLInnerLoopGradientDescent] = None,
               **kwargs):
    """Args mirror the reference (:79-103); ``use_parallel_for`` is gone —
    vmap is always the vectorization."""
    kwargs.setdefault('device_type', base_model.device_type)
    super().__init__(**kwargs)
    self._base_model = base_model
    self._maml_preprocessor_cls = preprocessor_cls
    self._num_inner_loop_steps = max(int(num_inner_loop_steps), 1)
    self._var_scope = var_scope
    self._inner_loop = inner_loop or MAMLInnerLoopGradientDescent(
        var_scope=var_scope)

  @property
  def base_model(self) -> AbstractT2RModel:
    return self._base_model

  # -- specs / preprocessor --------------------------------------------------

  @property
  def preprocessor(self):
    if self._preprocessor is None:
      cls = self._maml_preprocessor_cls or meta_preprocessors.MAMLPreprocessorV2
      self._preprocessor = cls(self._base_model.preprocessor)
    return self._preprocessor

  def get_feature_specification(self, mode: str) -> SpecStruct:
    return meta_preprocessors.create_maml_feature_spec(
        self._base_model.get_feature_specification(mode),
        self._base_model.get_label_specification(mode))

  def get_label_specification(self, mode: str) -> SpecStruct:
    return meta_preprocessors.create_maml_label_spec(
        self._base_model.get_label_specification(mode))

  # -- state -----------------------------------------------------------------

  def init_variables(self, rng, features, labels=None, mode: str = 'train'):
    """Initializes the BASE model on one task's condition batch."""
    cond_features = SpecStruct(
        **{k: v[0] for k, v in
           _sub_dict(features, 'condition/features/').items()})
    cond_labels = SpecStruct(
        **{k: v[0] for k, v in
           _sub_dict(features, 'condition/labels/').items()})
    variables = self._base_model.init_variables(rng, cond_features,
                                                cond_labels, mode)
    if self._inner_loop.learn_inner_lr:
      variables['params'] = {
          'base': variables['params'],
          INNER_LRS_KEY: self._inner_loop.create_inner_lr_params(
              variables['params']),
      }
    return variables

  def _split_params(self, params):
    if self._inner_loop.learn_inner_lr:
      return params['base'], params[INNER_LRS_KEY]
    return params, None

  # -- forward ---------------------------------------------------------------

  def inference_network_fn(self, variables, features, labels=None,
                           mode: str = 'train', rng=None):
    base_params, inner_lrs = self._split_params(variables['params'])
    model_state = {k: v for k, v in variables.items() if k != 'params'}

    cond_f = _sub_dict(features, 'condition/features/')
    cond_l = _sub_dict(features, 'condition/labels/')
    inf_f = _sub_dict(features, 'inference/features/')
    # The inner loop never uses the val labels; condition labels stand in
    # when the outer labels are absent (predict mode, ref :298-300).
    val_l = dict(labels) if labels is not None and len(labels) else cond_l

    # Domain-adaptive base models (e.g. DAML's learned loss) can declare a
    # dedicated inner-loop objective; the outer loss still uses
    # model_train_fn (ref vrgripper_env_models.py:414-448 is_outer_loss).
    inner_loss_fn = (getattr(self._base_model, 'inner_loop_loss_fn', None)
                     or self._base_model.model_train_fn)

    def task_learn(task_cond_f, task_cond_l, task_inf_f, task_val_l):
      inputs_list = ([(SpecStruct(**task_cond_f), SpecStruct(**task_cond_l))]
                     * self._num_inner_loop_steps +
                     [(SpecStruct(**task_inf_f), SpecStruct(**task_val_l))])
      return self._inner_loop.inner_loop(
          base_params, model_state, inputs_list,
          self._base_model.inference_network_fn,
          inner_loss_fn, mode, inner_lrs=inner_lrs,
          rng=rng)

    (outputs, inner_outputs, inner_losses, new_model_state) = jax.vmap(
        task_learn)(cond_f, cond_l, inf_f, val_l)
    unconditioned, conditioned = outputs
    # Mutable collections (batch_stats) come back with a leading task dim;
    # the running stats are EMAs, so the cross-task mean is the batched
    # analog of the reference's shared-variable BN update_ops.
    if (mode == ModeKeys.TRAIN and model_state and
        jax.tree_util.tree_leaves(model_state)):
      new_model_state = jax.tree.map(lambda x: jnp.mean(x, axis=0),
                                     new_model_state)
    else:
      new_model_state = None

    predictions = SpecStruct()
    for pos, step_outputs in enumerate(inner_outputs):
      for key in step_outputs:
        predictions['full_condition_outputs/output_{}/{}'.format(
            pos, key)] = step_outputs[key]
    for key in inner_outputs[0]:
      predictions['full_condition_output/' + key] = inner_outputs[0][key]
    for key in conditioned:
      predictions['full_inference_output/' + key] = conditioned[key]
    for key in unconditioned:
      predictions['full_inference_output_unconditioned/' + key] = (
          unconditioned[key])
    for pos, loss in enumerate(inner_losses):
      predictions['inner_losses/step_{}'.format(pos)] = jnp.mean(loss)

    predictions = self._select_inference_output(predictions)
    if 'condition_output' not in predictions:
      raise ValueError('_select_inference_output must assign '
                       'condition_output.')
    if 'inference_output' not in predictions:
      raise ValueError('_select_inference_output must assign '
                       'inference_output.')
    return predictions, new_model_state

  @abc.abstractmethod
  def _select_inference_output(self, predictions: SpecStruct) -> SpecStruct:
    """Assigns condition_output + inference_output (ref :361)."""

  # -- losses ----------------------------------------------------------------

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """Outer loss: base loss on flattened post-adaptation outputs (ref :420)."""
    inf_features = meta_data.flatten_batch_examples(
        SpecStruct(**_sub_dict(features, 'inference/features/')))
    inf_outputs = meta_data.flatten_batch_examples(
        SpecStruct(**_sub_dict(inference_outputs, 'full_inference_output/')))
    labels_flat = meta_data.flatten_batch_examples(SpecStruct(**dict(labels)))
    base_variables = dict(variables)
    base_variables['params'], _ = self._split_params(variables['params'])
    loss, train_outputs = self._base_model.model_train_fn(
        base_variables, inf_features, labels_flat, inf_outputs, mode)
    outputs = SpecStruct(**dict(train_outputs or {}))
    for key in inference_outputs:
      if key.startswith('inner_losses/'):
        outputs[key.replace('/', '_')] = inference_outputs[key]
    return loss, outputs

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    """ref :503 — base eval metrics on the flattened inference outputs."""
    inf_features = meta_data.flatten_batch_examples(
        SpecStruct(**_sub_dict(features, 'inference/features/')))
    inf_outputs = meta_data.flatten_batch_examples(
        SpecStruct(**_sub_dict(inference_outputs, 'full_inference_output/')))
    labels_flat = meta_data.flatten_batch_examples(SpecStruct(**dict(labels)))
    base_variables = dict(variables)
    base_variables['params'], _ = self._split_params(variables['params'])
    return self._base_model.model_eval_fn(
        base_variables, inf_features, labels_flat, inf_outputs, mode)


class MAMLRegressionModel(MAMLModel):
  """MAML over any regression-style base model: selects 'inference_output'
  (the concrete class of e.g. PoseEnvRegressionModelMAML, ref
  research/pose_env/pose_env_maml_models.py:47-54)."""

  output_key = 'inference_output'

  def _select_inference_output(self, predictions: SpecStruct) -> SpecStruct:
    predictions['condition_output'] = predictions[
        'full_condition_output/' + self.output_key]
    predictions['inference_output'] = predictions[
        'full_inference_output/' + self.output_key]
    return predictions
