"""Task-batched data utilities for meta-learning.

Parity target: /root/reference/meta_learning/meta_tfdata.py: flatten/
unflatten of the [num_tasks, num_samples] leading dims (:179, :206),
``multi_batch_apply`` (:266), and the one-file-per-task reader that batches
``num_condition + num_inference`` examples per task (:37, :135).

Host-side code is numpy; the flatten/unflatten helpers are dtype-agnostic
and jit-safe (pure reshapes), used on device by the MAML outer loss.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data.input_generators import AbstractInputGenerator
from tensor2robot_tpu.data.parser import ExampleParser
from tensor2robot_tpu.data.pipeline import parse_file_patterns
from tensor2robot_tpu.data.tfrecord import read_all_records
from tensor2robot_tpu.specs.struct import SpecStruct


def flatten_batch_examples(struct):
  """[num_tasks, num_samples, ...] -> [num_tasks * num_samples, ...] (ref :179).

  Leaves without both leading dims (per-task scalars such as aux losses)
  pass through unchanged.
  """
  def _merge(x):
    if getattr(x, 'ndim', 0) < 2:
      return x
    return x.reshape((x.shape[0] * x.shape[1],) + tuple(x.shape[2:]))
  if isinstance(struct, (dict, SpecStruct)):
    return SpecStruct(**{k: _merge(struct[k]) for k in struct})
  return _merge(struct)


def unflatten_batch_examples(struct, num_samples_per_task: int):
  """Inverse of flatten_batch_examples (ref :206)."""
  def _split(x):
    return x.reshape((-1, num_samples_per_task) + tuple(x.shape[1:]))
  if isinstance(struct, (dict, SpecStruct)):
    return SpecStruct(**{k: _split(struct[k]) for k in struct})
  return _split(struct)


def multi_batch_apply(fn: Callable, num_batch_dims: int, *args, **kwargs):
  """Applies ``fn`` (expecting one batch dim) over several batch dims (ref :266).

  Leading ``num_batch_dims`` dims of every array leaf in args/kwargs are
  merged, ``fn`` is applied, and its outputs' leading dim is split back.
  """
  import jax

  leaves = [leaf for a in (args, kwargs) for leaf in jax.tree_util.tree_leaves(a)
            if hasattr(leaf, 'shape')]
  if not leaves:
    raise ValueError('multi_batch_apply needs at least one array argument.')
  batch_dims = tuple(leaves[0].shape[:num_batch_dims])

  def _merge(x):
    if hasattr(x, 'shape') and len(x.shape) >= num_batch_dims:
      return x.reshape((-1,) + tuple(x.shape[num_batch_dims:]))
    return x

  def _split(x):
    if hasattr(x, 'shape'):
      return x.reshape(batch_dims + tuple(x.shape[1:]))
    return x

  merged_args = jax.tree.map(_merge, args,
                             is_leaf=lambda x: hasattr(x, 'shape'))
  merged_kwargs = jax.tree.map(_merge, kwargs,
                               is_leaf=lambda x: hasattr(x, 'shape'))
  outputs = fn(*merged_args, **merged_kwargs)
  return jax.tree.map(_split, outputs,
                      is_leaf=lambda x: hasattr(x, 'shape'))


def _stack_struct(structs: Sequence[SpecStruct], axis: int = 0) -> SpecStruct:
  out = SpecStruct()
  for key in structs[0]:
    out[key] = np.stack([np.asarray(s[key]) for s in structs], axis=axis)
  return out


def split_meta_in_spec(meta_in_spec):
  """Meta in-spec -> (base feature spec, base label spec).

  Inverts create_maml_feature_spec: drops the meta name prefix (so record
  parsing maps to the on-disk base names) and the prepended samples dim.
  """
  from tensor2robot_tpu.specs.tensor_spec import TensorSpec

  def _debase(spec):
    name = spec.name
    if name and name.startswith(('condition_features/', 'condition_labels/')):
      name = name.split('/', 1)[1]
    # The meta spec always prepends exactly one samples dim (unknown for
    # MAMLPreprocessorV2, fixed for the FixedLen layout) — strip it.
    shape = spec.shape[1:] if spec.shape else spec.shape
    return TensorSpec.from_spec(spec, name=name, shape=shape)

  feature_spec, label_spec = SpecStruct(), SpecStruct()
  for key in meta_in_spec:
    if key.startswith('condition/features/'):
      feature_spec[key[len('condition/features/'):]] = _debase(
          meta_in_spec[key])
    elif key.startswith('condition/labels/'):
      label_spec[key[len('condition/labels/'):]] = _debase(meta_in_spec[key])
  return feature_spec, label_spec


def to_meta_batch(features: SpecStruct, labels: SpecStruct,
                  num_condition: int):
  """[tasks, samples, ...] base batches -> (meta_features, meta_labels).

  The first ``num_condition`` samples of each task feed the inner loop;
  the rest feed the outer loss (ref meta_tfdata.split_train_val :135).
  """
  meta_features = SpecStruct()
  for key in features:
    meta_features['condition/features/' + key] = features[key][:, :num_condition]
    meta_features['inference/features/' + key] = features[key][:, num_condition:]
  for key in labels:
    meta_features['condition/labels/' + key] = labels[key][:, :num_condition]
  meta_labels = SpecStruct()
  for key in labels:
    meta_labels[key] = labels[key][:, num_condition:]
  return meta_features, meta_labels


class MetaRecordInputGenerator(AbstractInputGenerator):
  """One TFRecord file == one task (ref meta_tfdata.parallel_read :37).

  Each yielded batch groups ``num_tasks`` tasks; per task,
  ``num_condition_samples_per_task`` examples feed the inner loop and
  ``num_inference_samples_per_task`` the outer loss. Leaves are shaped
  [num_tasks, num_samples, ...] and packed into the MAML meta-spec layout
  (condition/features/..., condition/labels/..., inference/features/...,
  meta label keys) by the MAMLPreprocessorV2 in-spec this generator is
  bound to.
  """

  def __init__(self,
               file_patterns: str,
               num_condition_samples_per_task: int = 1,
               num_inference_samples_per_task: int = 1,
               num_tasks: Optional[int] = None,
               shuffle: bool = True,
               **kwargs):
    kwargs.setdefault('batch_size', num_tasks or 2)
    super().__init__(**kwargs)
    self._file_patterns = file_patterns
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task
    self._num_tasks = num_tasks or self._batch_size
    self._shuffle = shuffle

  def _create_iterator(self, mode, num_epochs, shard_index, num_shards, seed):
    _, files = parse_file_patterns(self._file_patterns)
    if not files:
      raise ValueError('No task files match {}.'.format(self._file_patterns))
    feature_spec, label_spec = split_meta_in_spec(self._feature_spec)
    parser = ExampleParser(feature_spec, label_spec)
    samples_per_task = self._num_condition + self._num_inference
    rng = np.random.RandomState(seed)

    def _read_task(path):
      records = read_all_records(path)
      if len(records) < samples_per_task:
        # Small tasks wrap around (sampling with replacement).
        records = records * ((samples_per_task // len(records)) + 1)
      idx = (rng.choice(len(records), samples_per_task, replace=False)
             if self._shuffle else np.arange(samples_per_task))
      features, labels = parser.parse_batch([records[i] for i in idx])
      return features, labels

    def _iter():
      epoch = 0
      while num_epochs is None or epoch < num_epochs:
        order = rng.permutation(len(files)) if self._shuffle else np.arange(
            len(files))
        for start in range(0, len(order) - self._num_tasks + 1,
                           self._num_tasks):
          task_feats, task_labels = [], []
          for file_idx in order[start:start + self._num_tasks]:
            features, labels = _read_task(files[file_idx])
            task_feats.append(features)
            task_labels.append(labels)
          features = _stack_struct(task_feats)     # [tasks, samples, ...]
          labels = _stack_struct(task_labels)
          yield to_meta_batch(features, labels, self._num_condition)
        epoch += 1

    return _iter()


class MAMLRandomInputGenerator(AbstractInputGenerator):
  """Spec-conforming random meta-batches — the meta test-data backbone."""

  def __init__(self,
               num_tasks: int = 2,
               num_condition_samples_per_task: int = 1,
               num_inference_samples_per_task: int = 1,
               **kwargs):
    kwargs.setdefault('batch_size', num_tasks)
    super().__init__(**kwargs)
    self._num_tasks = num_tasks
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task

  def _create_iterator(self, mode, num_epochs, shard_index, num_shards, seed):
    feature_spec, label_spec = split_meta_in_spec(self._feature_spec)
    samples = self._num_condition + self._num_inference

    def _iter():
      step = 0
      while num_epochs is None or step < num_epochs:
        features = unflatten_batch_examples(
            specs_lib.make_random_numpy(
                feature_spec, batch_size=self._num_tasks * samples,
                seed=None if seed is None else seed + step), samples)
        labels = unflatten_batch_examples(
            specs_lib.make_random_numpy(
                label_spec, batch_size=self._num_tasks * samples,
                seed=None if seed is None else seed + step + 977), samples)
        yield to_meta_batch(features, labels, self._num_condition)
        step += 1
    return _iter()
