"""Meta-learning policies: feed conditioning demos + inference state.

Parity target: /root/reference/meta_learning/meta_policies.py:32-207.
A MetaLearningPolicy carries per-task state: ``adapt(episode_data)`` stores
the conditioning episodes (demos/trials) that ``pack_features`` folds into
the meta feature layout at every SelectAction; ``reset_task`` clears them.
"""

from __future__ import annotations

import abc
from typing import Optional

import numpy as np

from tensor2robot_tpu.policies import policies


class MetaLearningPolicy(policies.Policy, abc.ABC):
  """Policies that adapt per task from collected episodes (ref :32)."""

  def reset_task(self) -> None:
    pass

  @abc.abstractmethod
  def adapt(self, episode_data) -> None:
    """Stores conditioning episode data for subsequent action selection."""


class MAMLRegressionPolicy(MetaLearningPolicy, policies.RegressionPolicy):
  """Regression policy with gradient-descent fast adaptation (ref :103)."""

  def __init__(self, *args, **kwargs):
    super().__init__(*args, **kwargs)
    self.reset_task()

  def reset_task(self) -> None:
    self._prev_episode_data = None

  def adapt(self, episode_data) -> None:
    self._prev_episode_data = episode_data

  def sample_action(self, obs, explore_prob):
    del explore_prob
    action = self.SelectAction(obs, None, None)
    # Replay writers require the is_demo flag when forming meta examples.
    return action, {'is_demo': False}

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    np_features = self._t2r_model.pack_features(state,
                                                self._prev_episode_data,
                                                timestep)
    action = np.asarray(
        self._predictor.predict(np_features)['inference_output'])
    # [task, samples, (T,) action] -> single action (ref :129-137).
    if action.ndim == 4:
      return action[0, 0, 0]
    if action.ndim == 3:
      return action[0, 0]
    raise ValueError('Invalid action rank {}.'.format(action.ndim))


class MAMLCEMPolicy(MetaLearningPolicy, policies.CEMPolicy):
  """CEM policy over an adapted critic (ref :45)."""

  def __init__(self, *args, **kwargs):
    super().__init__(*args, **kwargs)
    self.reset_task()

  def reset_task(self) -> None:
    self._prev_episode_data = None

  def adapt(self, episode_data) -> None:
    self._prev_episode_data = episode_data

  def _select_action_with_debug(self, state, context, timestep):
    prediction_key = ('inference_output' if self._prev_episode_data
                      else 'condition_output')

    def objective_fn(samples):
      cem_state = np.tile(np.expand_dims(state, 0),
                          [np.shape(samples)[0]] + [1] * np.ndim(state))
      np_inputs = self.pack_fn(self._t2r_model, cem_state,
                               self._prev_episode_data, timestep, samples)
      q_values = np.asarray(
          self._predictor.predict(np_inputs)[prediction_key])
      if not self._prev_episode_data:
        # Unadapted Q is meaningless for ranking; CEM degenerates to the
        # prior (ref :94-95 zeroes the values).
        q_values = q_values * 0
      return q_values.reshape(np.shape(samples)[0], -1)[:, 0]

    return self.get_cem_action(objective_fn)


class ScheduledExplorationMAMLRegressionPolicy(
    MetaLearningPolicy, policies.ScheduledExplorationRegressionPolicy):
  """MAMLRegressionPolicy + scheduled gaussian noise (ref :172)."""

  def __init__(self, *args, **kwargs):
    super().__init__(*args, **kwargs)
    self.reset_task()

  def reset_task(self) -> None:
    self._prev_episode_data = None

  def adapt(self, episode_data) -> None:
    self._prev_episode_data = episode_data

  def sample_action(self, obs, explore_prob):
    del explore_prob
    return self.SelectAction(obs, None, None), {'is_demo': False}

  def SelectAction(self, state, context, timestep):  # pylint: disable=invalid-name
    del context
    np_features = self._t2r_model.pack_features(state,
                                                self._prev_episode_data,
                                                timestep)
    action = np.asarray(
        self._predictor.predict(np_features)['inference_output'])
    if action.ndim == 4:
      action = action[0, 0, 0]
    elif action.ndim == 3:
      action = action[0, 0]
    else:
      raise ValueError('Invalid action rank {}.'.format(action.ndim))
    return action + self.get_noise()
