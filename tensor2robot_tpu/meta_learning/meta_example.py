"""Meta-example records: task-grouped condition/inference episode bundles.

Parity target: /root/reference/meta_learning/meta_example.py:34-72
(make_meta_example / append_example / append_sequence_example). The
reference merges per-episode tf.Examples into ONE record per task with
prefixed feature names::

    condition_ep0/<name>, condition_ep1/<name>, ...,
    inference_ep0/<name>, ...

which is how its meta-RL collect loop produces data the task-batched
reader can consume. Here the merge happens at the wire-codec level (no TF
proto objects): parse each episode record, re-emit with prefixed names.

The read side is :class:`MetaExampleInputGenerator` — one RECORD == one
task (complementing meta_data.MetaRecordInputGenerator's one FILE == one
task layout) — producing the same [tasks, samples, ...] meta-batch layout
the MAML models train on.

The write side plugs into run_meta_env via ``write_meta_examples=True``:
demo episodes become condition_ep*, trial episodes become inference_ep*,
one meta record per task.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from tensor2robot_tpu.data import wire
from tensor2robot_tpu.data.parser import ExampleParser
from tensor2robot_tpu.data.pipeline import parse_file_patterns
from tensor2robot_tpu.data.tfrecord import read_all_records
from tensor2robot_tpu.meta_learning import meta_data
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

CONDITION_PREFIX = 'condition_ep'
INFERENCE_PREFIX = 'inference_ep'


def _encodeable(feature_value):
  """wire FeatureValue (kind, values) -> a value wire.build_* accepts."""
  kind, values = feature_value
  if kind == 'bytes':
    return list(values)
  if kind == 'float':
    return np.asarray(values, np.float32)
  return np.asarray(values, np.int64)


def make_meta_example(condition_examples: Sequence[bytes],
                      inference_examples: Sequence[bytes]) -> bytes:
  """Merges serialized episode Examples into one meta-example record.

  Mirrors ref meta_example.py:34-50: feature names gain
  ``condition_ep{i}/`` / ``inference_ep{i}/`` prefixes. SequenceExamples
  merge both their context and their feature_lists sides (ref :62-72).
  """
  if not condition_examples or not inference_examples:
    raise ValueError('Need at least one condition and one inference example.')
  # Parse every record ONCE as a SequenceExample: a plain Example is
  # wire-identical to a SequenceExample with empty feature_lists (features
  # and context share field 1), so this reads both. The bundle merges in
  # sequence mode if ANY record has a feature_lists side — the earlier
  # first-record-only detection silently dropped the feature_lists of
  # sequence records bundled behind a plain first record.
  parsed = []
  for prefix, examples in ((CONDITION_PREFIX, condition_examples),
                           (INFERENCE_PREFIX, inference_examples)):
    for i, record in enumerate(examples):
      try:
        context, feature_lists = wire.parse_sequence_example(record)
      except Exception:  # noqa: BLE001 - plain-Example-only wire quirks
        context, feature_lists = wire.parse_example(record), {}
      parsed.append(('{}{}/'.format(prefix, i), context, feature_lists))
  sequence = any(feature_lists for _, _, feature_lists in parsed)
  merged_context: Dict[str, object] = {}
  merged_lists: Dict[str, list] = {}
  for tag, context, feature_lists in parsed:
    for name, value in context.items():
      merged_context[tag + name] = _encodeable(value)
    for name, steps in feature_lists.items():
      merged_lists[tag + name] = [_encodeable(s) for s in steps]
  if sequence:
    return wire.build_sequence_example(merged_context, merged_lists)
  return wire.build_example(merged_context)


def _prefixed_specs(feature_spec: SpecStruct, label_spec: SpecStruct,
                    prefix: str):
  """Copies of the base specs with on-disk names under ``prefix/``."""

  def _rename(struct):
    out = SpecStruct()
    for key in struct:
      spec = struct[key]
      name = spec.name if spec.name is not None else key
      out[key] = TensorSpec.from_spec(spec, name=prefix + '/' + name)
    return out

  return _rename(feature_spec), _rename(label_spec)


class MetaExampleInputGenerator(meta_data.AbstractInputGenerator):
  """Reads meta-example records: one RECORD == one task.

  Yields the same meta-batch layout as MetaRecordInputGenerator
  ([num_tasks, num_samples, ...] split into condition/inference by
  meta_data.to_meta_batch), so MAML models and their preprocessors consume
  both interchangeably.
  """

  def __init__(self,
               file_patterns: str,
               num_condition_samples_per_task: int = 1,
               num_inference_samples_per_task: int = 1,
               num_tasks: Optional[int] = None,
               shuffle: bool = True,
               **kwargs):
    kwargs.setdefault('batch_size', num_tasks or 2)
    super().__init__(**kwargs)
    self._file_patterns = file_patterns
    self._num_condition = num_condition_samples_per_task
    self._num_inference = num_inference_samples_per_task
    self._num_tasks = num_tasks or self._batch_size
    self._shuffle = shuffle

  def _create_iterator(self, mode, num_epochs, shard_index, num_shards, seed):
    _, files = parse_file_patterns(self._file_patterns)
    files = files[shard_index::num_shards]
    if not files:
      raise ValueError('No meta-example files match {}.'.format(
          self._file_patterns))
    feature_spec, label_spec = meta_data.split_meta_in_spec(
        self._feature_spec)
    parsers = []
    for i in range(self._num_condition + self._num_inference):
      prefix = (CONDITION_PREFIX + str(i) if i < self._num_condition
                else INFERENCE_PREFIX + str(i - self._num_condition))
      parsers.append(ExampleParser(
          *_prefixed_specs(feature_spec, label_spec, prefix)))
    rng = np.random.RandomState(seed)

    def _parse_chunk(chunk):
      sample_feats, sample_labels = [], []
      for parser in parsers:  # one parse per sample slot
        features, labels = parser.parse_batch(chunk)
        sample_feats.append(features)
        sample_labels.append(labels)
      features = meta_data._stack_struct(sample_feats, axis=1)
      labels = meta_data._stack_struct(sample_labels, axis=1)
      return meta_data.to_meta_batch(features, labels, self._num_condition)

    def _iter():
      # Lazy, one file resident at a time: meta records bundle whole image
      # episodes, so holding every matched file in RAM (and re-parsing all
      # of it each epoch) does not scale to real collect runs.
      epoch = 0
      while num_epochs is None or epoch < num_epochs:
        file_order = (rng.permutation(len(files)) if self._shuffle
                      else np.arange(len(files)))
        pending: List[bytes] = []
        yielded = False
        for file_idx in file_order:
          records = read_all_records(files[file_idx])
          rec_order = (rng.permutation(len(records)) if self._shuffle
                       else np.arange(len(records)))
          pending.extend(records[i] for i in rec_order)
          while len(pending) >= self._num_tasks:
            chunk, pending = (pending[:self._num_tasks],
                              pending[self._num_tasks:])
            yield _parse_chunk(chunk)
            yielded = True
        if not yielded:
          # Fewer records than num_tasks: an infinite epoch loop would
          # otherwise spin forever without producing a batch.
          raise ValueError(
              'Meta-example files {} hold fewer than num_tasks={} records; '
              'collect more tasks or lower num_tasks.'.format(
                  files, self._num_tasks))
        epoch += 1

    return _iter()
