"""MAML inner loop: functional gradient-descent adaptation over a params pytree.

Parity target: /root/reference/meta_learning/maml_inner_loop.py:33-333
(MAMLInnerLoopGradientDescent). The reference intercepts tf.get_variable via
a custom getter and substitutes ``var - lr * grad`` tensors on each of the k
adaptation steps, with a first/second-order switch (stop_gradient, :190) and
optional per-variable learned inner learning rates (:88-100).

In JAX the 900 lines of getter machinery reduce to ``jax.grad`` over the
params pytree and a tree-map SGD update; ``jax.grad`` through the whole
inner loop gives exact second-order MAML, and stop_gradient on the update
recovers the first-order variant. The loop is vmapped over tasks by
MAMLModel and differentiated again by the outer optimizer — all one XLA
program on TPU (no tf.map_fn / while_loop restrictions on batch norm or
summaries).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _path_str(path) -> str:
  parts = []
  for entry in path:
    parts.append(str(getattr(entry, 'key', getattr(entry, 'idx', entry))))
  return '/'.join(parts)


class MAMLInnerLoopGradientDescent:
  """Configurable inner-loop SGD (ref :33)."""

  def __init__(self,
               learning_rate: float = 0.001,
               use_second_order: bool = True,
               var_scope: Optional[str] = None,
               learn_inner_lr: bool = False):
    """Args mirror the reference (:56-79).

    Args:
      learning_rate: inner SGD step size (the init value when
        ``learn_inner_lr``).
      use_second_order: backprop through the inner gradients; False
        stop-gradients the update (first-order MAML).
      var_scope: '/'-joined params-path prefix; only matching leaves adapt
        in the inner loop (the outer loop still trains everything).
      learn_inner_lr: learn one inner LR per parameter leaf, trained by the
        outer loop.
    """
    self._learning_rate = learning_rate
    self._use_second_order = use_second_order
    self._var_scope = var_scope
    self._learn_inner_lr = learn_inner_lr

  @property
  def learn_inner_lr(self) -> bool:
    return self._learn_inner_lr

  def create_inner_lr_params(self, params) -> Any:
    """Per-leaf learned LRs initialized at ``learning_rate`` (ref :88-100)."""
    return jax.tree.map(
        lambda _: jnp.asarray(self._learning_rate, jnp.float32), params)

  def _adapt(self, params, grads, inner_lrs):
    """One SGD step over the pytree, honoring var_scope + order switch."""
    flat_params = jax.tree_util.tree_flatten_with_path(params)[0]
    adapt_mask = {
        _path_str(path): (self._var_scope is None or
                          _path_str(path).startswith(self._var_scope))
        for path, _ in flat_params
    }

    def _step(path, value, grad, lr):
      if not adapt_mask[_path_str(path)]:
        return value
      update = (lr if lr is not None else self._learning_rate) * grad
      if not self._use_second_order:
        update = jax.lax.stop_gradient(update)
      return value - update

    if inner_lrs is None:
      return jax.tree_util.tree_map_with_path(
          lambda path, v, g: _step(path, v, g, None), params, grads)
    return jax.tree_util.tree_map_with_path(_step, params, grads, inner_lrs)

  def inner_loop(self,
                 params,
                 model_state,
                 inputs_list: Sequence[Tuple[Any, Any]],
                 inference_network_fn: Callable,
                 model_train_fn: Callable,
                 mode: str,
                 inner_lrs=None,
                 rng=None):
    """k adaptation steps + conditioned/unconditioned val passes (ref :218).

    Args:
      params: the base model's params pytree (adapted copies are derived).
      model_state: non-param collections, held fixed through adaptation.
      inputs_list: ((cond_f, cond_l),) * k + ((val_f, val_l),) — one
        gradient step per entry except the last (ref :235).
      inference_network_fn / model_train_fn: the base model's pure fns.
      mode: ModeKeys value forwarded to the base model.
      inner_lrs: optional per-leaf learned LR pytree.
      rng: optional dropout rng for the base forward passes.

    Returns:
      ([unconditioned_outputs, conditioned_outputs], inner_outputs,
       inner_losses, new_model_state) — the first three exactly as the
       reference (:332): inner_outputs has k+1 entries (the extra final
       forward monitors adaptation) and inner_losses the matching k+1
       scalars. ``new_model_state`` carries the base model's mutable
       collections (batch_stats) threaded through every train-mode forward
       pass (the reference collects the matching BN update_ops); it equals
       ``model_state`` when nothing mutates.
    """

    def forward(p, state, features, labels):
      variables = {'params': p, **(state or {})}
      outputs, new_state = inference_network_fn(variables, features, labels,
                                                mode, rng)
      return outputs, (new_state if new_state is not None else state)

    def loss_fn(p, state, features, labels):
      variables = {'params': p, **(state or {})}
      outputs, new_state = forward(p, state, features, labels)
      loss, _ = model_train_fn(variables, features, labels, outputs, mode)
      return loss, (outputs, new_state)

    current = params
    current_state = model_state
    inner_outputs: List[Any] = []
    inner_losses: List[jnp.ndarray] = []
    for features, labels in inputs_list[:-1]:
      (loss, (outputs, current_state)), grads = jax.value_and_grad(
          loss_fn, has_aux=True)(current, current_state, features, labels)
      inner_outputs.append(outputs)
      inner_losses.append(loss)
      current = self._adapt(current, grads, inner_lrs)

    # One more conditioned forward + loss on the last condition batch to
    # monitor whether adaptation helped (ref :294-312) — no gradient step.
    final_features, final_labels = inputs_list[-2]
    final_loss, (final_outputs, current_state) = loss_fn(
        current, current_state, final_features, final_labels)
    inner_outputs.append(final_outputs)
    inner_losses.append(final_loss)

    val_features, val_labels = inputs_list[-1]
    conditioned, current_state = forward(current, current_state,
                                         val_features, val_labels)
    # The unconditioned diagnostic pass does not contribute state updates.
    unconditioned, _ = forward(params, current_state, val_features,
                               val_labels)
    return ([unconditioned, conditioned], inner_outputs, inner_losses,
            current_state)
