"""Optimizer and learning-rate factories (optax).

Parity target: /root/reference/models/optimizers.py:29-168. The reference's
MovingAverageOptimizer + swapping-saver machinery (:141-168) collapses into
``optax.ema`` tracked alongside the optimizer state: checkpoints carry both
raw and averaged params, and eval/serving read the averaged ones
(``use_avg_model_params`` on the model, ref models/abstract_model.py:836-844).
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import optax

ScalarOrSchedule = Union[float, Callable[[int], float]]


# -- learning rates ----------------------------------------------------------

def create_constant_learning_rate(learning_rate: float = 1e-4):
  """ref: optimizers.py:46."""
  return optax.constant_schedule(learning_rate)


def create_exponential_decay_learning_rate(
    initial_learning_rate: float = 1e-4,
    decay_steps: int = 10000,
    decay_rate: float = 0.9,
    staircase: bool = True):
  """ref: optimizers.py:52."""
  return optax.exponential_decay(
      init_value=initial_learning_rate, transition_steps=decay_steps,
      decay_rate=decay_rate, staircase=staircase)


def piecewise_constant_learning_rate(boundaries, values):
  boundaries_and_scales = {}
  prev = values[0]
  for boundary, value in zip(boundaries, values[1:]):
    boundaries_and_scales[int(boundary)] = value / prev
    prev = value
  return optax.piecewise_constant_schedule(values[0], boundaries_and_scales)


# -- optimizers --------------------------------------------------------------

def create_adam_optimizer(learning_rate: ScalarOrSchedule = 1e-4,
                          beta1: float = 0.9, beta2: float = 0.999,
                          epsilon: float = 1e-8):
  """ref: optimizers.py:29."""
  return optax.adam(learning_rate, b1=beta1, b2=beta2, eps=epsilon)


def create_sgd_optimizer(learning_rate: ScalarOrSchedule = 1e-4):
  """ref: optimizers.py:36."""
  return optax.sgd(learning_rate)


def create_momentum_optimizer(learning_rate: ScalarOrSchedule = 1e-4,
                              momentum: float = 0.9,
                              use_nesterov: bool = False):
  """ref: optimizers.py:39."""
  return optax.sgd(learning_rate, momentum=momentum, nesterov=use_nesterov)


def create_rms_prop_optimizer(learning_rate: ScalarOrSchedule = 1e-4,
                              decay: float = 0.9, momentum: float = 0.0,
                              epsilon: float = 1e-10):
  return optax.rmsprop(learning_rate, decay=decay, momentum=momentum,
                       eps=epsilon)


def maybe_clip_gradients(optimizer, clip_norm: Optional[float] = None):
  """Global-norm clipping chained ahead of the optimizer update."""
  if clip_norm is None:
    return optimizer
  return optax.chain(optax.clip_by_global_norm(clip_norm), optimizer)


def create_ema(decay: float = 0.9999, debias: bool = True):
  """Parameter averaging; the JAX form of MovingAverageOptimizer (ref :141)."""
  return optax.ema(decay=decay, debias=debias)
