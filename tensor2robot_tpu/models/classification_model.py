"""ClassificationModel: binary classification base (sigmoid log-loss).

Parity target: /root/reference/models/classification_model.py:48-242.
Subclasses declare specs and a network producing ``outputs['logits']``;
labels carry a {0,1} target under ``self.label_key``. Eval metrics mirror
the reference's mse/accuracy/precision/recall set (:203-242).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.specs.struct import SpecStruct


class ClassificationModel(AbstractT2RModel):

  label_key = 'target'
  logits_key = 'logits'

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    logits = inference_outputs[self.logits_key]
    targets = jnp.asarray(labels[self.label_key], logits.dtype).reshape(
        logits.shape)
    loss = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, targets))
    return loss, SpecStruct()

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    logits = inference_outputs[self.logits_key]
    targets = jnp.asarray(labels[self.label_key], logits.dtype).reshape(
        logits.shape)
    probabilities = jax.nn.sigmoid(logits.astype(jnp.float32))
    predictions = (probabilities > 0.5).astype(jnp.float32)
    targets_f = targets.astype(jnp.float32)
    true_positives = jnp.sum(predictions * targets_f)
    eps = 1e-8
    metrics = SpecStruct()
    metrics['loss'] = jnp.mean(
        optax.sigmoid_binary_cross_entropy(logits, targets))
    metrics['mean_squared_error'] = jnp.mean(
        (probabilities - targets_f) ** 2)
    metrics['accuracy'] = jnp.mean((predictions == targets_f).astype(
        jnp.float32))
    metrics['precision'] = true_positives / (jnp.sum(predictions) + eps)
    metrics['recall'] = true_positives / (jnp.sum(targets_f) + eps)
    return metrics

  def create_export_outputs_fn(self, features, inference_outputs,
                               mode: str) -> SpecStruct:
    logits = inference_outputs[self.logits_key]
    out = SpecStruct()
    out[self.logits_key] = logits
    out['probabilities'] = jax.nn.sigmoid(logits.astype(jnp.float32))
    return out
