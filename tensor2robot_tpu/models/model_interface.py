"""ModelInterface: the minimal protocol the training/serving infra needs.

Parity target: /root/reference/models/model_interface.py:53-151. The infra
(trainer, input generators, exporters, predictors) programs against this
interface, never against concrete models.

TPU-native redesign: instead of an Estimator ``model_fn`` returning
EstimatorSpecs, the interface exposes *pure functions* over explicit
parameters — ``init_variables`` / ``inference_network_fn`` /
``model_train_fn`` / ``model_eval_fn`` — which the trainer composes into one
jitted, mesh-sharded train step. Model instances hold configuration only;
all state (params, batch stats, optimizer slots) lives in the TrainState
pytree the trainer owns.
"""

from __future__ import annotations

import abc
from typing import Any, Optional

from tensor2robot_tpu.specs.struct import SpecStruct


class ModelInterface(abc.ABC):
  """What the infra requires of every model."""

  # -- specs ----------------------------------------------------------------

  @abc.abstractmethod
  def get_feature_specification(self, mode: str) -> SpecStruct:
    ...

  @abc.abstractmethod
  def get_label_specification(self, mode: str) -> SpecStruct:
    ...

  def get_feature_specification_for_packing(self, mode: str) -> SpecStruct:
    """Specs after preprocessing — what inference_network_fn consumes."""
    return self.preprocessor.get_out_feature_specification(mode)

  def get_label_specification_for_packing(self, mode: str) -> SpecStruct:
    return self.preprocessor.get_out_label_specification(mode)

  # -- preprocessor ---------------------------------------------------------

  @property
  @abc.abstractmethod
  def preprocessor(self):
    ...

  # -- pure model functions -------------------------------------------------

  @abc.abstractmethod
  def init_variables(self, rng, features: SpecStruct,
                     labels: Optional[SpecStruct], mode: str):
    """Creates the variable collections pytree for this model."""

  @abc.abstractmethod
  def inference_network_fn(self, variables, features: SpecStruct,
                           labels: Optional[SpecStruct], mode: str,
                           rng=None):
    """Forward pass. Returns (outputs SpecStruct, updated_variables)."""

  @abc.abstractmethod
  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    """Returns (scalar loss, train_outputs dict)."""

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    """Returns a dict of per-batch metric values (averaged by the harness)."""
    del variables, features, inference_outputs
    return SpecStruct()

  def create_export_outputs_fn(self, features, inference_outputs,
                               mode: str) -> SpecStruct:
    """Predictions served at inference time. Default: inference outputs."""
    del features, mode
    return inference_outputs

  def add_summaries(self, features, labels, inference_outputs,
                    mode: str) -> Optional[dict]:
    """Optional rich summaries (ref abstract_model.py:556 add_summaries).

    Called on HOST numpy data for one batch per eval; return
    {'images': {tag: [N, H, W, C]}, 'histograms': {tag: values},
    'scalars': {tag: value}} (any subset) for the metrics writer, or None.
    """
    del features, labels, inference_outputs, mode
    return None

  # -- device / precision ---------------------------------------------------

  @property
  def device_type(self) -> str:
    return 'tpu'

  @property
  def is_device_tpu(self) -> bool:
    return self.device_type == 'tpu'
