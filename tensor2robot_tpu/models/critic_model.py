"""CriticModel: Q(state, action) base for off-policy RL (QT-Opt style).

Parity target: /root/reference/models/critic_model.py:48-243. Subclasses
declare separate state and action specs (:77-93) and a network producing
``outputs['q_predicted']``. For CEM-based serving the predict path tiles the
state across an action batch (``action_batch_size``, :128-141): the robot
sends one state plus N candidate actions and gets N Q-values back in a single
device call — on TPU this keeps the MXU busy with one batched forward pass.
"""

from __future__ import annotations

import abc
from typing import Optional

import jax.numpy as jnp
import optax

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs.struct import SpecStruct


class CriticModel(AbstractT2RModel):

  q_key = 'q_predicted'
  reward_key = 'reward'

  def __init__(self, action_batch_size: Optional[int] = None, **kwargs):
    """action_batch_size: CEM sample count served per predict call."""
    super().__init__(**kwargs)
    self._action_batch_size = action_batch_size

  # -- spec split -----------------------------------------------------------

  @abc.abstractmethod
  def get_state_specification(self) -> SpecStruct:
    """ref critic_model.py:77."""

  @abc.abstractmethod
  def get_action_specification(self) -> SpecStruct:
    """ref critic_model.py:85."""

  def get_feature_specification(self, mode: str) -> SpecStruct:
    """state/ + action/ merged (ref :93)."""
    del mode
    spec = SpecStruct()
    for key, sub in (('state', self.get_state_specification()),
                     ('action', self.get_action_specification())):
      flat = algebra.flatten_spec_structure(sub)
      for k in flat:
        spec[key + '/' + k] = flat[k]
    return spec

  @property
  def action_batch_size(self) -> Optional[int]:
    return self._action_batch_size

  # -- default loss: cross entropy against in-[0,1] targets -----------------

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    q_predicted = inference_outputs[self.q_key]
    targets = jnp.asarray(labels[self.reward_key],
                          q_predicted.dtype).reshape(q_predicted.shape)
    loss = jnp.mean(optax.sigmoid_binary_cross_entropy(
        self.logit_of(inference_outputs), targets))
    return loss, SpecStruct()

  def logit_of(self, inference_outputs):
    """Networks may emit logits alongside q=sigmoid(logits)."""
    if 'q_logits' in inference_outputs:
      return inference_outputs['q_logits']
    q = jnp.clip(inference_outputs[self.q_key], 1e-6, 1 - 1e-6)
    return jnp.log(q) - jnp.log1p(-q)

  # -- CEM serving ----------------------------------------------------------

  def tile_state_for_action_batch(self, features: SpecStruct) -> SpecStruct:
    """Expands state [B, ...] to [B*action_batch_size, ...] (ref :128-141).

    The predictor feeds B states and B*action_batch_size candidate actions
    grouped per state; ``repeat`` keeps state i aligned with its contiguous
    block of actions, and the network scores them in one batched forward.
    """
    if self._action_batch_size is None:
      return features
    tiled = SpecStruct()
    for key in algebra.flatten_spec_structure(features):
      value = features[key]
      if key.startswith('state/'):
        value = jnp.repeat(value, self._action_batch_size, axis=0)
      tiled[key] = value
    return tiled

  def predict_step(self, state, features) -> SpecStruct:
    return super().predict_step(state,
                                self.tile_state_for_action_batch(features))
