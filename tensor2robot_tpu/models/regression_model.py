"""RegressionModel: continuous-output base (MSE loss).

Parity target: /root/reference/models/regression_model.py:50-172. Subclasses
declare specs and a network producing ``outputs['inference_output']``; labels
carry the regression target under ``self.label_key``.
"""

from __future__ import annotations

import jax.numpy as jnp

from tensor2robot_tpu.models.abstract_model import AbstractT2RModel
from tensor2robot_tpu.specs.struct import SpecStruct


class RegressionModel(AbstractT2RModel):

  label_key = 'target'
  output_key = 'inference_output'

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    predictions = inference_outputs[self.output_key]
    targets = jnp.asarray(labels[self.label_key],
                          predictions.dtype).reshape(predictions.shape)
    loss = jnp.mean((predictions - targets).astype(jnp.float32) ** 2)
    return loss, SpecStruct()

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    loss, _ = self.model_train_fn(variables, features, labels,
                                  inference_outputs, mode)
    return SpecStruct(loss=loss, mean_squared_error=loss)
