"""Model abstraction: spec-declaring models as pure functions + TrainState."""

from tensor2robot_tpu.models.model_interface import ModelInterface
from tensor2robot_tpu.models.abstract_model import AbstractT2RModel, TrainState
from tensor2robot_tpu.models.classification_model import ClassificationModel
from tensor2robot_tpu.models.critic_model import CriticModel
from tensor2robot_tpu.models.regression_model import RegressionModel
from tensor2robot_tpu.models import optimizers
