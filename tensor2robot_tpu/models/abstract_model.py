"""AbstractT2RModel: the user-facing model API, pure-functional for JAX.

Parity target: /root/reference/models/abstract_model.py:154-919 (the
Estimator-era template-method model). The TF1 responsibilities map as:

  reference model_fn (EstimatorSpec assembly :651-823)  -> trainer composes
      the pure fns below into one jitted train/eval/predict step
  create_train_op + optimizer creation (:327-370,:836)  -> create_optimizer()
      returning an optax chain; gradient psum is inserted by pjit sharding
  TPUT2RModelWrapper bf16 casts (tpu_model_wrapper.py)  -> deleted by
      construction: bf16 is first-class; models read self.compute_dtype
  MovingAverageOptimizer + swapping saver (:836-844)    -> optax.ema tracked
      in TrainState.avg_params; eval/serving read averaged params
  maybe_init_from_checkpoint warm start (:88-118,:372)  -> warm_start_fn
      merging a restored params subtree before training

Models hold *configuration only*. Parameters, mutable collections
(batch stats), optimizer slots, and the EMA live in :class:`TrainState`,
a pytree owned by the trainer and sharded over the mesh.

Subclasses implement either:
  * ``create_network() -> flax.linen.Module`` whose ``__call__(features,
    mode, train)`` returns an outputs dict — init/inference defaults then
    just work; or
  * ``init_variables`` + ``inference_network_fn`` directly for full control.
plus ``model_train_fn`` (the loss).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from tensor2robot_tpu.models import optimizers as opt_lib
from tensor2robot_tpu.models.model_interface import ModelInterface
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
    AbstractPreprocessor,
)
from tensor2robot_tpu.preprocessors.noop_preprocessor import NoOpPreprocessor
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import bfloat16


class TrainState(flax.struct.PyTreeNode):
  """All mutable training state, as one shardable pytree."""

  step: jnp.ndarray
  params: Any
  model_state: Any          # non-param collections (batch_stats, ...)
  opt_state: Any
  avg_params: Any = None    # EMA of params (use_avg_model_params)
  ema_state: Any = None

  def variables(self, use_avg_params: bool = False):
    params = self.avg_params if (use_avg_params and
                                 self.avg_params is not None) else self.params
    return {'params': params, **(self.model_state or {})}


class AbstractT2RModel(ModelInterface):
  """Base model: spec declarations + pure network/loss/metric functions."""

  def __init__(self,
               preprocessor_cls: Optional[Callable[..., AbstractPreprocessor]] = None,
               create_optimizer_fn: Callable[[], Any] = opt_lib.create_adam_optimizer,
               device_type: str = 'tpu',
               use_avg_model_params: bool = False,
               avg_model_params_decay: float = 0.9999,
               gradient_clip_norm: Optional[float] = None,
               warm_start_fn: Optional[Callable[[Any], Any]] = None,
               compute_dtype=None):
    """See class docstring.

    Args:
      preprocessor_cls: class constructed with the model's spec fns
        (ref abstract_model.py:255 — default NoOp).
      create_optimizer_fn: zero-arg factory returning an optax
        GradientTransformation (ref optimizer gin-injection :836).
      device_type: 'cpu' | 'gpu' | 'tpu' (ref :66-68).
      use_avg_model_params: serve/eval exponentially-averaged params
        (ref :836-844).
      avg_model_params_decay: EMA decay.
      gradient_clip_norm: optional global-norm clip (ref create_train_op).
      warm_start_fn: params -> params, merging restored values
        (ref maybe_init_from_checkpoint :372).
      compute_dtype: activations dtype for networks that honor it
        (default bfloat16 on TPU — the tpu_model_wrapper replacement).
    """
    self._preprocessor_cls = preprocessor_cls
    self._preprocessor: Optional[AbstractPreprocessor] = None
    self._create_optimizer_fn = create_optimizer_fn
    self._device_type = device_type
    self.use_avg_model_params = use_avg_model_params
    self.avg_model_params_decay = avg_model_params_decay
    self.gradient_clip_norm = gradient_clip_norm
    self._warm_start_fn = warm_start_fn
    if compute_dtype is None:
      compute_dtype = bfloat16 if device_type == 'tpu' else np.float32
    self.compute_dtype = compute_dtype

  # -- preprocessor ---------------------------------------------------------

  @property
  def preprocessor(self) -> AbstractPreprocessor:
    if self._preprocessor is None:
      cls = self._preprocessor_cls or NoOpPreprocessor
      self._preprocessor = cls(self.get_feature_specification,
                               self.get_label_specification)
    return self._preprocessor

  def set_preprocessor(self, preprocessor: AbstractPreprocessor) -> None:
    """Installs a (wrapped) preprocessor, e.g. the bf16 TPU wrapper."""
    self._preprocessor = preprocessor

  @property
  def warm_start_fn(self):
    return self._warm_start_fn

  @property
  def device_type(self) -> str:
    return self._device_type

  # -- network --------------------------------------------------------------

  def create_network(self) -> nn.Module:
    """Returns the flax module backing the default init/inference fns."""
    raise NotImplementedError(
        '{} must implement create_network() or override init_variables/'
        'inference_network_fn.'.format(type(self).__name__))

  def init_variables(self, rng, features, labels=None,
                     mode: str = ModeKeys.TRAIN):
    """Default: flax init through create_network (ref variable creation)."""
    del labels
    network = self.create_network()
    param_rng, dropout_rng = jax.random.split(rng)
    variables = network.init(
        {'params': param_rng, 'dropout': dropout_rng}, features, mode=mode,
        train=(mode == ModeKeys.TRAIN))
    variables = flax.core.unfreeze(variables)
    if self._warm_start_fn is not None and not any(
        isinstance(leaf, jax.core.Tracer)
        for leaf in jax.tree_util.tree_leaves(variables)):
      # Warm start does real checkpoint I/O; only run it on concrete values.
      # Under jit/eval_shape the trainer is responsible for applying it
      # eagerly exactly once (Trainer.init_state), never inside a trace
      # where the restored weights would be baked in as XLA constants.
      variables['params'] = self._warm_start_fn(variables['params'])
    return variables

  def inference_network_fn(self, variables, features, labels=None,
                           mode: str = ModeKeys.TRAIN, rng=None):
    """Default: flax apply; train mode updates batch stats.

    Returns (outputs, updated_model_state). ``updated_model_state`` is None
    outside train mode (nothing mutates).
    """
    del labels
    network = self.create_network()
    train = mode == ModeKeys.TRAIN
    rngs = {'dropout': rng} if rng is not None else None
    mutable = [k for k in variables if k != 'params'] if train else False
    if mutable:
      outputs, new_state = network.apply(
          variables, features, mode=mode, train=train, rngs=rngs,
          mutable=mutable)
      return outputs, flax.core.unfreeze(new_state)
    outputs = network.apply(variables, features, mode=mode, train=train,
                            rngs=rngs)
    return outputs, None

  # -- loss / metrics -------------------------------------------------------

  def model_train_fn(self, variables, features, labels, inference_outputs,
                     mode: str):
    raise NotImplementedError(
        '{} must implement model_train_fn.'.format(type(self).__name__))

  def model_eval_fn(self, variables, features, labels, inference_outputs,
                    mode: str) -> SpecStruct:
    """Default: the train loss as an eval metric (ref model_eval_fn :495)."""
    loss, _ = self.model_train_fn(variables, features, labels,
                                  inference_outputs, mode)
    return SpecStruct(loss=loss)

  # -- optimizer / state ----------------------------------------------------

  def create_optimizer(self):
    """optax chain per config (ref create_optimizer :836, clip :327)."""
    return opt_lib.maybe_clip_gradients(self._create_optimizer_fn(),
                                        self.gradient_clip_norm)

  def create_train_state(self, rng, features, labels=None,
                         mode: str = ModeKeys.TRAIN) -> TrainState:
    """Initializes variables + optimizer (+EMA) into one TrainState."""
    variables = self.init_variables(rng, features, labels, mode)
    params = variables.pop('params')
    model_state = variables
    optimizer = self.create_optimizer()
    opt_state = optimizer.init(params)
    avg_params = ema_state = None
    if self.use_avg_model_params:
      ema = opt_lib.create_ema(self.avg_model_params_decay)
      ema_state = ema.init(params)
      avg_params = params
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      model_state=model_state, opt_state=opt_state,
                      avg_params=avg_params, ema_state=ema_state)

  # -- pure step functions (composed & jitted by the trainer) ---------------

  def loss_fn(self, params, model_state, features, labels, mode, rng):
    variables = {'params': params, **(model_state or {})}
    outputs, new_model_state = self.inference_network_fn(
        variables, features, labels, mode, rng)
    loss, train_outputs = self.model_train_fn(
        variables, features, labels, outputs, mode)
    return loss, (train_outputs, outputs, new_model_state)

  def train_step(self, state: TrainState, features, labels, rng
                 ) -> Tuple[TrainState, SpecStruct]:
    """One SGD step. Pure; jit/pjit-sharded by the trainer.

    Under pjit with batch sharded over the mesh 'data' axis, the gradient
    all-reduce (the reference's CrossShardOptimizer, tpu_model_wrapper.py:50)
    is inserted automatically by XLA as a psum over ICI.
    """
    prng, _ = jax.random.split(rng)
    grad_fn = jax.value_and_grad(self.loss_fn, has_aux=True)
    (loss, (train_outputs, _, new_model_state)), grads = grad_fn(
        state.params, state.model_state, features, labels, ModeKeys.TRAIN,
        prng)
    optimizer = self.create_optimizer()
    updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
    new_params = optax.apply_updates(state.params, updates)
    avg_params, ema_state = state.avg_params, state.ema_state
    if self.use_avg_model_params:
      ema = opt_lib.create_ema(self.avg_model_params_decay)
      avg_params, ema_state = ema.update(new_params, state.ema_state)
    metrics = SpecStruct(loss=loss)
    if isinstance(train_outputs, (dict, SpecStruct)):
      for key in train_outputs:
        value = train_outputs[key]
        if hasattr(value, 'ndim') and value.ndim == 0:
          metrics[key] = value
    new_state = state.replace(
        step=state.step + 1, params=new_params,
        model_state=new_model_state if new_model_state is not None
        else state.model_state,
        opt_state=new_opt_state, avg_params=avg_params, ema_state=ema_state)
    return new_state, metrics

  def eval_step(self, state: TrainState, features, labels) -> SpecStruct:
    """Per-batch eval metrics (averaged across batches by the harness)."""
    variables = state.variables(use_avg_params=self.use_avg_model_params)
    outputs, _ = self.inference_network_fn(variables, features, labels,
                                           ModeKeys.EVAL, None)
    return self.model_eval_fn(variables, features, labels, outputs,
                              ModeKeys.EVAL)

  def predict_step(self, state: TrainState, features) -> SpecStruct:
    """Serving forward pass -> export outputs (ref create_export_outputs_fn)."""
    variables = state.variables(use_avg_params=self.use_avg_model_params)
    outputs, _ = self.inference_network_fn(variables, features, None,
                                           ModeKeys.PREDICT, None)
    return self.create_export_outputs_fn(features, outputs, ModeKeys.PREDICT)
