"""t2r_assets serialization: the spec contract shipped inside every export.

Every exported model directory contains ``assets.extra/t2r_assets.pbtxt``
describing the feature/label specs and global step, so robot-side predictors
can reconstruct feeds without the model's Python class. This module reads and
writes that file in protobuf text format, wire/text-compatible with the
reference schema (/root/reference/proto/t2r.proto:19-44 — messages
ExtendedTensorSpec / TensorSpecStruct / T2RAssets) without requiring protoc:
the grammar of the fixed schema is small enough to emit and parse directly.

A JSON twin (``t2r_assets.json``) is also written for tooling convenience.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Optional, Tuple

from tensor2robot_tpu.specs.algebra import flatten_spec_structure
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec

T2R_ASSETS_FILENAME = 't2r_assets.pbtxt'
T2R_ASSETS_JSON_FILENAME = 't2r_assets.json'
EXTRA_ASSETS_DIRECTORY = 'assets.extra'
GLOBAL_STEP_FILENAME = 'global_step.txt'


# -- pbtxt emission ----------------------------------------------------------

def _emit_scalar(value) -> str:
  if isinstance(value, bool):
    return 'true' if value else 'false'
  if isinstance(value, str):
    escaped = value.replace('\\', '\\\\').replace('"', '\\"')
    return '"{}"'.format(escaped)
  if isinstance(value, float):
    return repr(value)
  return str(int(value))


def _emit_message(fields, indent: int = 0) -> str:
  """fields: list of (name, value) where value is scalar, list-of-scalars, or dict."""
  pad = '  ' * indent
  lines = []
  for name, value in fields:
    if isinstance(value, dict):
      lines.append('{}{} {{'.format(pad, name))
      lines.append(_emit_message(list(value.items()), indent + 1))
      lines.append('{}}}'.format(pad))
    elif isinstance(value, list):
      for item in value:
        if isinstance(item, dict):
          lines.append('{}{} {{'.format(pad, name))
          lines.append(_emit_message(list(item.items()), indent + 1))
          lines.append('{}}}'.format(pad))
        else:
          lines.append('{}{}: {}'.format(pad, name, _emit_scalar(item)))
    else:
      lines.append('{}{}: {}'.format(pad, name, _emit_scalar(value)))
  return '\n'.join(lines)


def _spec_struct_to_fields(spec_structure) -> dict:
  flat = flatten_spec_structure(spec_structure)
  entries = []
  for key in flat:
    spec = flat[key]
    value = collections.OrderedDict()
    d = spec.to_dict()
    # Strictly the reference proto's fields 1-8 (t2r.proto:19-30) so the
    # reference stack's text_format.Parse accepts our files. is_sequence is
    # not part of that schema; it round-trips via the JSON twin instead.
    for field in ('shape', 'dtype', 'name', 'is_optional', 'is_extracted',
                  'data_format', 'dataset_key', 'varlen_default_value'):
      if field in d:
        value[field] = d[field]
    entries.append(collections.OrderedDict([('key', key), ('value', value)]))
  return {'key_value': entries}


def specs_to_pbtxt(feature_spec, label_spec,
                   global_step: Optional[int] = None) -> str:
  fields = []
  if feature_spec is not None:
    fields.append(('feature_spec', _spec_struct_to_fields(feature_spec)))
  if label_spec is not None:
    fields.append(('label_spec', _spec_struct_to_fields(label_spec)))
  if global_step is not None:
    fields.append(('global_step', int(global_step)))
  return _emit_message(fields) + '\n'


# -- pbtxt parsing -----------------------------------------------------------

def _tokenize(text: str):
  tokens = []
  i, n = 0, len(text)
  while i < n:
    c = text[i]
    if c in ' \t\r\n':
      i += 1
    elif c == '#':
      while i < n and text[i] != '\n':
        i += 1
    elif c in '{}:':
      tokens.append(c)
      i += 1
    elif c == '"':
      j = i + 1
      buf = []
      while j < n and text[j] != '"':
        if text[j] == '\\':
          j += 1
          buf.append(text[j])
        else:
          buf.append(text[j])
        j += 1
      tokens.append(('STR', ''.join(buf)))
      i = j + 1
    else:
      j = i
      while j < n and text[j] not in ' \t\r\n{}:#"':
        j += 1
      tokens.append(('ATOM', text[i:j]))
      i = j
  return tokens


def _parse_atom(atom: str):
  if atom == 'true':
    return True
  if atom == 'false':
    return False
  try:
    return int(atom)
  except ValueError:
    return float(atom)


def _parse_message(tokens, pos: int) -> Tuple[dict, int]:
  """Parses fields until '}' or EOF. Repeated fields accumulate into lists."""
  out = collections.OrderedDict()

  def _add(name, value):
    if name in out:
      if not isinstance(out[name], list):
        out[name] = [out[name]]
      out[name].append(value)
    else:
      out[name] = value

  while pos < len(tokens):
    tok = tokens[pos]
    if tok == '}':
      return out, pos + 1
    if not (isinstance(tok, tuple) and tok[0] == 'ATOM'):
      raise ValueError('pbtxt parse error near token {}'.format(tok))
    name = tok[1]
    pos += 1
    if tokens[pos] == ':':
      pos += 1
      vtok = tokens[pos]
      pos += 1
      _add(name, vtok[1] if vtok[0] == 'STR' else _parse_atom(vtok[1]))
    elif tokens[pos] == '{':
      sub, pos = _parse_message(tokens, pos + 1)
      _add(name, sub)
    else:
      raise ValueError('pbtxt parse error after field {}'.format(name))
  return out, pos


def parse_pbtxt(text: str) -> dict:
  try:
    msg, _ = _parse_message(_tokenize(text), 0)
  except (IndexError, KeyError) as e:
    raise ValueError('Malformed pbtxt: {}'.format(e))
  return msg


def _as_list(value):
  if value is None:
    return []
  return value if isinstance(value, list) else [value]


def _fields_to_spec_struct(msg) -> SpecStruct:
  out = SpecStruct()
  for entry in _as_list(msg.get('key_value')):
    value = dict(entry['value'])
    value['shape'] = [int(s) for s in _as_list(value.get('shape'))]
    out[entry['key']] = TensorSpec.from_dict(value)
  return out


def pbtxt_to_specs(text: str):
  """Returns (feature_spec, label_spec, global_step)."""
  msg = parse_pbtxt(text)
  feature_spec = label_spec = None
  if 'feature_spec' in msg:
    feature_spec = _fields_to_spec_struct(msg['feature_spec'])
  if 'label_spec' in msg:
    label_spec = _fields_to_spec_struct(msg['label_spec'])
  return feature_spec, label_spec, msg.get('global_step')


# -- file-level API (contract: assets.extra/t2r_assets.pbtxt) ----------------

def write_t2r_assets_to_file(feature_spec, label_spec, global_step,
                             filename: str) -> None:
  """ref: tensorspec_utils.py:1680."""
  if os.path.dirname(filename):
    os.makedirs(os.path.dirname(filename), exist_ok=True)
  with open(filename, 'w') as f:
    f.write(specs_to_pbtxt(feature_spec, label_spec, global_step))
  json_payload = {
      'feature_spec': {k: s.to_dict() for k, s in
                       flatten_spec_structure(feature_spec).items()},
      'label_spec': {k: s.to_dict() for k, s in
                     flatten_spec_structure(label_spec).items()},
      'global_step': int(global_step) if global_step is not None else None,
  }
  json_path = os.path.join(os.path.dirname(filename), T2R_ASSETS_JSON_FILENAME)
  with open(json_path, 'w') as f:
    json.dump(json_payload, f, indent=2)


def load_t2r_assets_from_file(filename: str):
  """ref: tensorspec_utils.py:1686. Returns (feature_spec, label_spec, step).

  Prefers the lossless JSON twin when present (it preserves is_sequence,
  which the reference pbtxt schema cannot carry); falls back to the pbtxt.
  """
  json_path = os.path.join(os.path.dirname(filename), T2R_ASSETS_JSON_FILENAME)
  if os.path.exists(json_path):
    try:
      with open(json_path) as f:
        payload = json.load(f)
      def _load(side):
        out = SpecStruct()
        for k, d in (payload.get(side) or {}).items():
          out[k] = TensorSpec.from_dict(d)
        return out
      return _load('feature_spec'), _load('label_spec'), payload.get('global_step')
    except (ValueError, KeyError):
      pass  # corrupt twin: fall back to the pbtxt source of truth
  with open(filename) as f:
    return pbtxt_to_specs(f.read())


def write_input_spec_to_file(feature_spec, label_spec, dirname: str) -> None:
  """ref: :1698 — writes specs (no step) into dirname/t2r_assets.pbtxt."""
  write_t2r_assets_to_file(feature_spec, label_spec, None,
                           os.path.join(dirname, T2R_ASSETS_FILENAME))


def load_input_spec_from_file(dirname_or_file: str):
  """ref: :1705."""
  path = dirname_or_file
  if os.path.isdir(path):
    path = os.path.join(path, T2R_ASSETS_FILENAME)
  feature_spec, label_spec, _ = load_t2r_assets_from_file(path)
  return feature_spec, label_spec


def write_global_step_to_file(global_step: int, dirname: str) -> None:
  """ref: :1716 — a bare step file next to exports for cheap reconciliation."""
  os.makedirs(dirname, exist_ok=True)
  with open(os.path.join(dirname, GLOBAL_STEP_FILENAME), 'w') as f:
    f.write(str(int(global_step)))


def load_global_step_from_file(dirname: str) -> int:
  """ref: :1722."""
  with open(os.path.join(dirname, GLOBAL_STEP_FILENAME)) as f:
    return int(f.read().strip())
