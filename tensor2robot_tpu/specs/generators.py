"""Spec→runtime generators: placeholders, random/constant numpy, feed helpers.

Parity target: /root/reference/utils/tensorspec_utils.py:778-1010. These are
the workhorses of the test strategy — any model can be trained/predicted on
spec-conforming synthetic data with zero data files.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from tensor2robot_tpu.specs.algebra import flatten_spec_structure
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec


def _concrete_shape(spec: TensorSpec, batch_size: Optional[int],
                    sequence_length: Optional[int]) -> tuple:
  shape = tuple(1 if s is None else int(s) for s in spec.shape)
  if spec.is_sequence:
    shape = ((3 if sequence_length is None else int(sequence_length)),) + shape
  if batch_size is not None:
    shape = (int(batch_size),) + shape
  return shape


def make_placeholders(spec_structure, batch_size: Optional[int] = None,
                      sequence_length: Optional[int] = None) -> SpecStruct:
  """jax.ShapeDtypeStructs per spec — the jit-trace analog of placeholders (ref: :778)."""
  import jax
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key in flat:
    spec = flat[key]
    out[key] = jax.ShapeDtypeStruct(
        _concrete_shape(spec, batch_size, sequence_length), spec.jax_dtype)
  return out


def make_random_numpy(spec_structure, batch_size: Optional[int] = 1,
                      sequence_length: Optional[int] = 3,
                      seed: Optional[int] = None) -> SpecStruct:
  """Spec-conforming random numpy batch (ref: :881)."""
  rng = np.random.RandomState(seed)
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key in flat:
    spec = flat[key]
    shape = _concrete_shape(spec, batch_size, sequence_length)
    dtype = spec.dtype
    if dtype == np.dtype(object):
      out[key] = np.full(shape, b'', dtype=object)
    elif dtype.kind in 'ui':
      high = 255 if dtype == np.uint8 else 10
      out[key] = rng.randint(0, high + 1, size=shape).astype(dtype)
    elif dtype == np.bool_:
      out[key] = rng.rand(*shape) > 0.5
    else:
      out[key] = rng.rand(*shape).astype(dtype)
  return out


def make_constant_numpy(spec_structure, constant_value: float,
                        batch_size: Optional[int] = 1,
                        sequence_length: Optional[int] = 3) -> SpecStruct:
  """Spec-conforming constant numpy batch (ref: :842)."""
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key in flat:
    spec = flat[key]
    shape = _concrete_shape(spec, batch_size, sequence_length)
    if spec.dtype == np.dtype(object):
      out[key] = np.full(shape, b'', dtype=object)
    else:
      out[key] = np.full(shape, constant_value, dtype=spec.dtype)
  return out


def map_feed_dict(spec_structure, numpy_struct, ignore_batch: bool = False):
  """Maps {spec.name: array} for serving-style name-keyed feeds (ref: :918)."""
  from tensor2robot_tpu.specs.algebra import validate_and_flatten
  flat_spec = flatten_spec_structure(spec_structure)
  flat_np = validate_and_flatten(spec_structure, numpy_struct,
                                 ignore_batch=ignore_batch)
  feed = {}
  for key in flat_np:
    name = flat_spec[key].name or key.replace('/', '_')
    feed[name] = flat_np[key]
  return feed
