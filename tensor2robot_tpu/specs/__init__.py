"""Spec core: declarative tensor specifications and their algebra.

The spec system is the framework's backbone (parity with the reference's
``utils/tensorspec_utils.py``): models declare feature/label requirements as
SpecStructs of TensorSpecs, and the data pipeline, preprocessors, trainer,
exporters, and predictors all derive their behavior from those declarations.
"""

from tensor2robot_tpu.specs.tensor_spec import (
    TensorSpec,
    ExtendedTensorSpec,
    bfloat16,
    canonical_dtype,
    dtype_name,
    dtype_enum,
)
from tensor2robot_tpu.specs.struct import SpecStruct, TensorSpecStruct
from tensor2robot_tpu.specs.algebra import (
    add_sequence_length_specs,
    assert_equal_spec_maps,
    assert_required,
    assert_valid_spec_structure,
    cast_to_dtype,
    copy_tensorspec,
    dataset_keys,
    filter_required_flat_tensor_spec,
    filter_spec_structure_by_dataset,
    flatten_spec_structure,
    is_encoded_image_spec,
    maybe_ignore_batch,
    pack_flat_sequence_to_spec_structure,
    pad_or_clip_tensor_to_spec_shape,
    replace_dtype,
    validate_and_flatten,
    validate_and_pack,
)
from tensor2robot_tpu.specs.generators import (
    make_constant_numpy,
    make_placeholders,
    make_random_numpy,
    map_feed_dict,
)
from tensor2robot_tpu.specs.assets import (
    EXTRA_ASSETS_DIRECTORY,
    T2R_ASSETS_FILENAME,
    load_global_step_from_file,
    load_input_spec_from_file,
    load_t2r_assets_from_file,
    write_global_step_to_file,
    write_input_spec_to_file,
    write_t2r_assets_to_file,
)
