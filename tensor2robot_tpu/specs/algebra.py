"""Spec algebra: flatten / pack / validate / filter / transform.

Parity target: the spec-manipulation layer of the reference
(/root/reference/utils/tensorspec_utils.py:685-1677). These functions are the
boundary-validation machinery the whole framework hangs off: the data pipeline
validates parsed batches against model in-specs, preprocessors validate both
sides, and the trainer validates at trace time (where validation is free since
JAX shapes are static).

All functions accept arbitrary nests (dict / namedtuple / SpecStruct) and
return :class:`SpecStruct`.
"""

from __future__ import annotations

import collections
from typing import Any, Mapping, Optional

import numpy as np

from tensor2robot_tpu.specs.struct import SpecStruct, _is_namedtuple
from tensor2robot_tpu.specs.tensor_spec import TensorSpec, canonical_dtype


def flatten_spec_structure(spec_or_tensors) -> SpecStruct:
  """Flattens any nest into a flat-path SpecStruct (ref: :1298)."""
  if spec_or_tensors is None:
    return SpecStruct()
  if isinstance(spec_or_tensors, SpecStruct):
    flat = SpecStruct()
    for k in spec_or_tensors:
      v = spec_or_tensors[k]
      if not isinstance(v, SpecStruct):
        flat[k] = v
    return flat
  if isinstance(spec_or_tensors, Mapping) or _is_namedtuple(spec_or_tensors):
    return SpecStruct(spec_or_tensors)
  # A single leaf (spec/array): wrap under its name or a default key.
  name = getattr(spec_or_tensors, 'name', None) or 'value'
  return SpecStruct(**{name: spec_or_tensors})


def assert_valid_spec_structure(spec_structure) -> None:
  """All leaves are TensorSpecs and equal spec-names imply equal specs (ref: :1458)."""
  flat = flatten_spec_structure(spec_structure)
  by_name = {}
  for key in flat:
    spec = flat[key]
    if not isinstance(spec, TensorSpec):
      raise ValueError(
          'Invalid spec structure: {} -> {} is not a TensorSpec.'.format(
              key, type(spec)))
    if spec.name is None:
      continue
    seen = by_name.get(spec.name)
    if seen is not None and seen != spec:
      raise ValueError(
          'Duplicate spec name {!r} with conflicting definitions: {} vs {}.'
          .format(spec.name, seen, spec))
    by_name[spec.name] = spec


def assert_equal_spec_maps(expected, actual) -> None:
  expected, actual = flatten_spec_structure(expected), flatten_spec_structure(actual)
  if set(expected.keys()) != set(actual.keys()):
    raise ValueError('Spec key sets differ: {} vs {}'.format(
        sorted(expected.keys()), sorted(actual.keys())))
  for key in expected:
    if expected[key] != actual[key]:
      raise ValueError('Spec {} differs: {} vs {}'.format(
          key, expected[key], actual[key]))


def maybe_ignore_batch(shape, ignore_batch: bool):
  """Strips the leading (batch) dim for validation (ref: :1067)."""
  if not ignore_batch:
    return tuple(shape)
  if len(shape) == 0:
    raise ValueError('Cannot ignore batch dimension of a scalar tensor.')
  return tuple(shape)[1:]


def _leaf_shape_dtype(value):
  if hasattr(value, 'shape') and hasattr(value, 'dtype'):
    return tuple(value.shape), canonical_dtype(value.dtype)
  if isinstance(value, (bytes, str)):
    return (), np.dtype(object)
  arr = np.asarray(value)
  if arr.dtype.kind in ('U', 'S', 'O'):
    return tuple(arr.shape), np.dtype(object)
  return tuple(arr.shape), arr.dtype


def _validate_leaf(key: str, spec: TensorSpec, value, ignore_batch: bool) -> None:
  shape, dtype = _leaf_shape_dtype(value)
  shape = maybe_ignore_batch(shape, ignore_batch)
  spec_shape = spec.shape
  if spec.is_sequence and len(shape) == len(spec_shape) + 1:
    # Ragged time major dim (after batch strip) is allowed for sequence specs.
    shape = shape[1:]
  if dtype != spec.dtype:
    raise ValueError(
        'Tensor {!r} dtype {} does not match spec {}.'.format(
            key, dtype, spec))
  if len(shape) != len(spec_shape):
    raise ValueError(
        'Tensor {!r} rank {} (shape {}) does not match spec {}'
        ' (ignore_batch={}).'.format(key, len(shape), shape, spec, ignore_batch))
  for mine, theirs in zip(spec_shape, shape):
    if mine is not None and theirs is not None and int(mine) != int(theirs):
      raise ValueError(
          'Tensor {!r} shape {} incompatible with spec {}.'.format(
              key, shape, spec))


def validate_and_flatten(spec_structure, tensors,
                         ignore_batch: bool = False) -> SpecStruct:
  """Validates tensors against specs; returns flat tensors keyed by spec paths.

  Required specs must be present; optional specs missing from ``tensors`` are
  dropped silently (ref: validate_and_flatten :1205).
  """
  spec_flat = flatten_spec_structure(spec_structure)
  tensor_flat = flatten_spec_structure(tensors)
  out = SpecStruct()
  for key in spec_flat:
    spec = spec_flat[key]
    if key not in tensor_flat:
      if spec.is_optional:
        continue
      raise ValueError(
          'Required tensor {!r} missing; available: {}.'.format(
              key, sorted(tensor_flat.keys())))
    value = tensor_flat[key]
    _validate_leaf(key, spec, value, ignore_batch)
    out[key] = value
  return out


def pack_flat_sequence_to_spec_structure(spec_structure, flat_tensors) -> SpecStruct:
  """Packs flat tensors into the hierarchy of ``spec_structure`` (ref: :1343).

  Optional specs with no tensor are dropped.
  """
  spec_flat = flatten_spec_structure(spec_structure)
  tensor_flat = flatten_spec_structure(flat_tensors)
  packed = SpecStruct()
  for key in spec_flat:
    spec = spec_flat[key]
    if key not in tensor_flat:
      if getattr(spec, 'is_optional', False):
        continue
      raise ValueError(
          'Cannot pack: required key {!r} missing from tensors {}.'.format(
              key, sorted(tensor_flat.keys())))
    packed[key] = tensor_flat[key]
  return packed


def validate_and_pack(spec_structure, tensors,
                      ignore_batch: bool = False) -> SpecStruct:
  """validate_and_flatten + pack (ref: :1239)."""
  flat = validate_and_flatten(spec_structure, tensors, ignore_batch)
  return pack_flat_sequence_to_spec_structure(spec_structure, flat)


def assert_required(spec_structure, tensors, ignore_batch: bool = False) -> None:
  """Raises unless every required spec has a valid tensor (ref: :1164)."""
  validate_and_flatten(spec_structure, tensors, ignore_batch)


def filter_required_flat_tensor_spec(spec_structure) -> SpecStruct:
  """Keeps only non-optional specs (ref: :1527)."""
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key in flat:
    if not flat[key].is_optional:
      out[key] = flat[key]
  return out


def filter_spec_structure_by_dataset(spec_structure, dataset_key: str) -> SpecStruct:
  """Keeps specs belonging to ``dataset_key`` (ref: :1286)."""
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key in flat:
    if flat[key].dataset_key == dataset_key:
      out[key] = flat[key]
  return out


def dataset_keys(spec_structure):
  """Sorted unique dataset keys present in the structure."""
  flat = flatten_spec_structure(spec_structure)
  return sorted({flat[key].dataset_key for key in flat})


def copy_tensorspec(spec_structure, batch_size: Optional[int] = None,
                    prefix: str = '') -> SpecStruct:
  """Deep-copies specs, optionally prepending batch dim + name prefix (ref: :750)."""
  flat = flatten_spec_structure(spec_structure)
  assert_valid_spec_structure(flat)
  out = SpecStruct()
  for key in flat:
    spec = flat[key]
    name = spec.name
    if prefix and name is not None:
      name = prefix + '/' + name
    out[key] = TensorSpec.from_spec(spec, name=name, batch_size=batch_size)
  return out


def add_sequence_length_specs(spec_structure) -> SpecStruct:
  """Adds an int64 ``<key>_length`` spec for every sequence spec (ref: :1275)."""
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key in flat:
    out[key] = flat[key]
    if flat[key].is_sequence:
      out[key + '_length'] = TensorSpec(
          shape=(), dtype=np.int64,
          name=(flat[key].name or key.replace('/', '_')) + '_length')
  return out


def replace_dtype(spec_structure, from_dtype, to_dtype) -> SpecStruct:
  """Re-types all specs of ``from_dtype`` (ref: :685)."""
  from_dtype = canonical_dtype(from_dtype)
  to_dtype = canonical_dtype(to_dtype)
  flat = flatten_spec_structure(spec_structure)
  out = SpecStruct()
  for key in flat:
    spec = flat[key]
    if spec.dtype == from_dtype:
      spec = TensorSpec.from_spec(spec, dtype=to_dtype)
    out[key] = spec
  return out


def cast_to_dtype(tensors, from_dtype, to_dtype):
  """Casts every array of ``from_dtype`` in a nest to ``to_dtype`` (ref: :708,:733).

  Works on numpy and jax arrays; under jit this is a free element-type change
  that XLA fuses into neighbors.
  """
  import jax.numpy as jnp  # local: keep module import light for data workers
  from_dtype = canonical_dtype(from_dtype)
  flat = flatten_spec_structure(tensors)
  out = SpecStruct()
  for key in flat:
    value = flat[key]
    vdtype = getattr(value, 'dtype', None)
    if vdtype is not None and canonical_dtype(vdtype) == from_dtype:
      if isinstance(value, np.ndarray):
        value = value.astype(to_dtype)
      else:
        value = jnp.asarray(value).astype(to_dtype)
    out[key] = value
  return out


def pad_or_clip_tensor_to_spec_shape(tensor, spec: TensorSpec):
  """Pads (with varlen_default_value) or clips dim-0 to spec.shape[0] (ref: :1626)."""
  target = spec.shape[0]
  if target is None:
    return tensor
  arr = np.asarray(tensor) if isinstance(tensor, (list, tuple)) else tensor
  length = arr.shape[0]
  if length >= target:
    return arr[:target]
  pad_value = spec.varlen_default_value
  pad_value = 0 if pad_value is None else pad_value
  pad_shape = (int(target) - length,) + tuple(arr.shape[1:])
  if isinstance(arr, np.ndarray):
    pad = np.full(pad_shape, pad_value, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)
  import jax.numpy as jnp
  pad = jnp.full(pad_shape, pad_value, dtype=arr.dtype)
  return jnp.concatenate([arr, pad], axis=0)


def is_encoded_image_spec(spec: TensorSpec) -> bool:
  return spec.is_encoded_image
