"""SpecStruct: one flat ordered mapping with hierarchical attribute views.

Parity target: the reference's ``TensorSpecStruct``
(/root/reference/utils/tensorspec_utils.py:306-682). A SpecStruct stores values
(tensor specs, arrays, jax tracers -- anything) under '/'-separated flat paths
and exposes:

  * flat dict access:       ``s['train/images']``
  * attribute access:       ``s.train.images``
  * hierarchical views:     ``s.train`` is a live view backed by the parent --
                            mutations through the view are visible everywhere.

Unlike the reference we also register SpecStruct as a JAX pytree, so a struct
of arrays flows through ``jit`` / ``grad`` / ``vmap`` unchanged, which is what
lets model code receive the same container at trace time and at numpy time.
"""

from __future__ import annotations

import collections
from typing import Any, Iterator, Mapping, Optional

import jax

_RESERVED = ('_root', '_prefix')


def _is_namedtuple(value) -> bool:
  return isinstance(value, tuple) and hasattr(value, '_fields')


class SpecStruct(collections.abc.MutableMapping):
  """Ordered flat mapping with live hierarchical views."""

  def __init__(self, *others, **kwargs):
    object.__setattr__(self, '_root', collections.OrderedDict())
    object.__setattr__(self, '_prefix', '')
    for other in others:
      self.update(_as_items(other))
    for key, value in kwargs.items():
      self[key] = value

  # -- view plumbing --------------------------------------------------------

  @classmethod
  def _view(cls, root: collections.OrderedDict, prefix: str) -> 'SpecStruct':
    view = cls.__new__(cls)
    object.__setattr__(view, '_root', root)
    object.__setattr__(view, '_prefix', prefix)
    return view

  def _abs(self, key: str) -> str:
    key = key.strip('/')
    return self._prefix + key if not self._prefix else self._prefix + '/' + key

  # -- MutableMapping interface ---------------------------------------------

  def __getitem__(self, key: str) -> Any:
    path = self._abs(key)
    if path in self._root:
      return self._root[path]
    # Sub-view if any flat key lives under this path.
    sub = path + '/'
    if any(k.startswith(sub) for k in self._root):
      return SpecStruct._view(self._root, path)
    raise KeyError(key)

  def __setitem__(self, key: str, value: Any) -> None:
    path = self._abs(key)
    if isinstance(value, SpecStruct) or isinstance(value, Mapping) or _is_namedtuple(value):
      items = list(_as_items(value))
      if not items:
        raise ValueError(
            'Cannot assign an empty mapping to {!r}; delete the key instead.'
            .format(key))
      # Setting a subtree: clear existing subtree then splice values in.
      sub = path + '/'
      for k in [k for k in self._root if k.startswith(sub)]:
        del self._root[k]
      self._root.pop(path, None)
      for rel, leaf in items:
        self._root[path + '/' + rel] = leaf
    else:
      if any(k.startswith(path + '/') for k in self._root):
        raise ValueError(
            'Cannot assign a leaf to {!r}: it is an existing subtree.'.format(key))
      self._root[path] = value

  def __delitem__(self, key: str) -> None:
    path = self._abs(key)
    if path in self._root:
      del self._root[path]
      return
    sub = path + '/'
    doomed = [k for k in self._root if k.startswith(sub)]
    if not doomed:
      raise KeyError(key)
    for k in doomed:
      del self._root[k]

  def __iter__(self) -> Iterator[str]:
    if not self._prefix:
      yield from list(self._root)
      return
    sub = self._prefix + '/'
    for k in list(self._root):
      if k.startswith(sub):
        yield k[len(sub):]

  def __len__(self) -> int:
    return sum(1 for _ in self.__iter__())

  def __contains__(self, key) -> bool:
    try:
      self[key]
      return True
    except (KeyError, TypeError):
      return False

  # -- attribute access ------------------------------------------------------

  def __getattr__(self, name: str) -> Any:
    if name.startswith('_'):
      raise AttributeError(name)
    try:
      return self[name]
    except KeyError:
      raise AttributeError(name)

  def __setattr__(self, name: str, value: Any) -> None:
    if name in _RESERVED:
      object.__setattr__(self, name, value)
    else:
      self[name] = value

  def __delattr__(self, name: str) -> None:
    try:
      del self[name]
    except KeyError:
      raise AttributeError(name)

  # -- conveniences ----------------------------------------------------------

  def to_dict(self) -> collections.OrderedDict:
    """Flat OrderedDict copy of (this view of) the struct."""
    return collections.OrderedDict((k, self[k]) for k in self)

  def to_nested_dict(self) -> collections.OrderedDict:
    """Recursive plain-dict copy."""
    out = collections.OrderedDict()
    for key in self:
      head = key.split('/', 1)[0]
      if head in out:
        continue
      value = self[head]
      out[head] = value.to_nested_dict() if isinstance(value, SpecStruct) else value
    return out

  def copy(self) -> 'SpecStruct':
    fresh = SpecStruct()
    for k in self:
      fresh[k] = self[k]
    return fresh

  def __eq__(self, other) -> bool:
    # Order-insensitive, like the reference's OrderedDict-vs-dict comparison.
    if not isinstance(other, (SpecStruct, Mapping)):
      return NotImplemented
    return dict(self.to_dict()) == dict(_as_flat_dict(other))

  def __ne__(self, other) -> bool:
    result = self.__eq__(other)
    return result if result is NotImplemented else not result

  def __repr__(self):
    return 'SpecStruct({})'.format(
        ', '.join('{}={!r}'.format(k, v) for k, v in self.to_dict().items()))


def _as_items(value):
  """Yields (flat_key, leaf) pairs from mappings/namedtuples/SpecStructs."""
  if isinstance(value, SpecStruct):
    for k in value:
      yield k, value._root[value._abs(k)]  # pylint: disable=protected-access
    return
  if _is_namedtuple(value):
    value = value._asdict()
  if isinstance(value, Mapping):
    for k, v in value.items():
      if isinstance(v, (SpecStruct, Mapping)) or _is_namedtuple(v):
        for rel, leaf in _as_items(v):
          yield str(k) + '/' + rel, leaf
      else:
        yield str(k), v
    return
  raise ValueError('Cannot build SpecStruct items from {}'.format(type(value)))


def _as_flat_dict(value) -> collections.OrderedDict:
  return collections.OrderedDict(_as_items(value))


# -- pytree registration -----------------------------------------------------

def _specstruct_flatten(struct: SpecStruct):
  items = list(struct.to_dict().items())
  keys = tuple(k for k, _ in items)
  values = tuple(v for _, v in items)
  return values, keys


def _specstruct_flatten_with_keys(struct: SpecStruct):
  items = list(struct.to_dict().items())
  keys = tuple(k for k, _ in items)
  keyed = tuple((jax.tree_util.DictKey(k), v) for k, v in items)
  return keyed, keys


def _specstruct_unflatten(keys, values) -> SpecStruct:
  fresh = SpecStruct()
  for k, v in zip(keys, values):
    # Bypass subtree splicing: leaves may themselves be mappings.
    fresh._root[k] = v  # pylint: disable=protected-access
  return fresh


jax.tree_util.register_pytree_with_keys(
    SpecStruct, _specstruct_flatten_with_keys, _specstruct_unflatten,
    _specstruct_flatten)


TensorSpecStruct = SpecStruct  # reference-familiar alias
