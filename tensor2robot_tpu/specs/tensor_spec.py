"""TensorSpec: a declarative description of a tensor an API accepts or returns.

This is the TPU-native redesign of the reference's ``ExtendedTensorSpec``
(see /root/reference/utils/tensorspec_utils.py:44-282 for the behavior we
provide parity with). Instead of subclassing ``tf.TensorSpec`` we use a frozen
dataclass that is hashable, pytree-friendly, and converts directly to
``jax.ShapeDtypeStruct`` for trace-time shape validation under ``jax.jit``.

Extended attributes beyond (shape, dtype, name):
  * ``is_optional``  -- the tensor may be absent from a batch; pipelines drop it.
  * ``is_sequence``  -- parsed from the sequence side of a SequenceExample
                        (ragged time dimension, auto ``<name>_length`` tensor).
  * ``is_extracted`` -- the spec was inferred from a concrete array.
  * ``data_format``  -- 'jpeg'/'png' etc: the on-disk bytes are an encoded image
                        that the data pipeline decodes to ``shape``/``dtype``.
  * ``dataset_key``  -- which of several zipped datasets this tensor comes from.
  * ``varlen_default_value`` -- treat the on-disk feature as variable length and
                        pad (with this value) or clip to ``shape[0]``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import numpy as np

# bfloat16 is a first-class dtype on TPU; ml_dtypes ships with jax.
import ml_dtypes

bfloat16 = np.dtype(ml_dtypes.bfloat16)

# The on-disk dtype enum used in t2r_assets.pbtxt. Values follow the
# TensorFlow DataType enum so that assets written by the reference stack can be
# loaded unchanged (serialization contract, not code, from proto/t2r.proto).
_DTYPE_TO_ENUM = {
    np.dtype(np.float16): 19,
    bfloat16: 14,
    np.dtype(np.float32): 1,
    np.dtype(np.float64): 2,
    np.dtype(np.int8): 6,
    np.dtype(np.int16): 5,
    np.dtype(np.int32): 3,
    np.dtype(np.int64): 9,
    np.dtype(np.uint8): 4,
    np.dtype(np.uint16): 17,
    np.dtype(np.uint32): 22,
    np.dtype(np.uint64): 23,
    np.dtype(np.bool_): 10,
    np.dtype(object): 7,  # string / bytes
}
_ENUM_TO_DTYPE = {v: k for k, v in _DTYPE_TO_ENUM.items()}

# Canonical names (numpy names except 'string' and 'bfloat16').
_DTYPE_TO_NAME = {k: k.name for k in _DTYPE_TO_ENUM}
_DTYPE_TO_NAME[np.dtype(object)] = 'string'
_DTYPE_TO_NAME[bfloat16] = 'bfloat16'
_NAME_TO_DTYPE = {v: k for k, v in _DTYPE_TO_NAME.items()}

ShapeLike = Sequence[Optional[int]]
DTypeLike = Any


def canonical_dtype(dtype: DTypeLike) -> np.dtype:
  """Normalizes tf/jax/numpy/string dtypes to a numpy dtype (object=string)."""
  if isinstance(dtype, str):
    if dtype in _NAME_TO_DTYPE:
      return _NAME_TO_DTYPE[dtype]
    return np.dtype(dtype)
  if isinstance(dtype, int):  # proto enum
    return _ENUM_TO_DTYPE[dtype]
  # tf.DType has as_numpy_dtype; jax dtypes convert via np.dtype.
  as_np = getattr(dtype, 'as_numpy_dtype', None)
  if as_np is not None:
    return np.dtype(as_np)
  if dtype is bytes or dtype is str:
    return np.dtype(object)
  return np.dtype(dtype)


def dtype_name(dtype: DTypeLike) -> str:
  return _DTYPE_TO_NAME[canonical_dtype(dtype)]


def dtype_enum(dtype: DTypeLike) -> int:
  return _DTYPE_TO_ENUM[canonical_dtype(dtype)]


def _canonical_shape(shape: Union[ShapeLike, int, None]) -> Tuple[Optional[int], ...]:
  if shape is None:
    return ()
  if isinstance(shape, (int, np.integer)):
    return (int(shape),)
  out = []
  for dim in shape:
    if dim is None or (isinstance(dim, (int, np.integer)) and int(dim) < 0):
      out.append(None)
    else:
      out.append(int(dim))
  return tuple(out)


@dataclasses.dataclass(frozen=True)
class TensorSpec:
  """Frozen, hashable tensor specification (parity: ExtendedTensorSpec)."""

  shape: Tuple[Optional[int], ...]
  dtype: np.dtype
  name: Optional[str] = None
  is_optional: bool = False
  is_sequence: bool = False
  is_extracted: bool = False
  data_format: Optional[str] = None
  dataset_key: str = ''
  varlen_default_value: Optional[float] = None

  def __init__(self,
               shape: Union[ShapeLike, int, None],
               dtype: DTypeLike,
               name: Optional[str] = None,
               is_optional: Optional[bool] = None,
               is_sequence: bool = False,
               is_extracted: bool = False,
               data_format: Optional[str] = None,
               dataset_key: Optional[str] = None,
               varlen_default_value: Optional[float] = None):
    object.__setattr__(self, 'shape', _canonical_shape(shape))
    object.__setattr__(self, 'dtype', canonical_dtype(dtype))
    object.__setattr__(self, 'name', name)
    object.__setattr__(self, 'is_optional', bool(is_optional) if is_optional is not None else False)
    object.__setattr__(self, 'is_sequence', bool(is_sequence))
    object.__setattr__(self, 'is_extracted', bool(is_extracted))
    object.__setattr__(self, 'data_format', data_format)
    object.__setattr__(self, 'dataset_key', dataset_key or '')
    if varlen_default_value is not None:
      varlen_default_value = float(varlen_default_value)
      if data_format is None and len(self.shape) != 1:
        raise ValueError(
            'varlen specs require rank-1 shapes (got {}) unless they are '
            'encoded images.'.format(self.shape))
      if data_format is not None and len(self.shape) != 4:
        raise ValueError(
            'varlen image specs require rank-4 shapes (got {}).'.format(
                self.shape))
    object.__setattr__(self, 'varlen_default_value', varlen_default_value)

  # -- Constructors ---------------------------------------------------------

  @classmethod
  def from_spec(cls, spec, **overrides) -> 'TensorSpec':
    """Copies ``spec`` (TensorSpec or anything with shape/dtype), overriding fields.

    Supports ``batch_size=N`` to prepend a batch dim (or -1/None for unknown),
    mirroring reference ExtendedTensorSpec.from_spec (tensorspec_utils.py:112).
    """
    batch_size = overrides.pop('batch_size', None)
    kwargs = dict(
        shape=tuple(getattr(spec, 'shape', ()) or ()),
        dtype=getattr(spec, 'dtype'),
        name=getattr(spec, 'name', None),
        is_optional=getattr(spec, 'is_optional', False),
        is_sequence=getattr(spec, 'is_sequence', False),
        is_extracted=getattr(spec, 'is_extracted', False),
        data_format=getattr(spec, 'data_format', None),
        dataset_key=getattr(spec, 'dataset_key', ''),
        varlen_default_value=getattr(spec, 'varlen_default_value', None),
    )
    for key, value in overrides.items():
      if value is not None or key in ('name', 'data_format'):
        kwargs[key] = value
    if batch_size is not None:
      batch = None if int(batch_size) < 0 else int(batch_size)
      kwargs['shape'] = (batch,) + tuple(kwargs['shape'])
    return cls(**kwargs)

  @classmethod
  def from_tensor(cls, tensor, name: Optional[str] = None) -> 'TensorSpec':
    """Infers a spec from a concrete array (marks is_extracted=True)."""
    arr = np.asarray(tensor) if not hasattr(tensor, 'shape') else tensor
    return cls(shape=tuple(arr.shape), dtype=arr.dtype, name=name,
               is_extracted=True)

  @classmethod
  def to_spec(cls, instance_or_spec, name: Optional[str] = None) -> 'TensorSpec':
    if isinstance(instance_or_spec, TensorSpec):
      return instance_or_spec
    if hasattr(instance_or_spec, 'shape') and hasattr(instance_or_spec, 'dtype'):
      # Covers np arrays, jax arrays, ShapeDtypeStruct, tf.TensorSpec.
      if type(instance_or_spec).__name__ in ('TensorSpec', 'BoundedTensorSpec'):
        return cls.from_spec(instance_or_spec, name=name)
      return cls.from_tensor(instance_or_spec, name=name)
    raise ValueError(
        'Cannot convert {} to TensorSpec.'.format(type(instance_or_spec)))

  # -- Serialization (t2r_assets contract) ----------------------------------

  def to_dict(self) -> dict:
    d = {
        'shape': [(-1 if s is None else int(s)) for s in self.shape],
        'dtype': dtype_enum(self.dtype),
    }
    if self.name is not None:
      d['name'] = self.name
    if self.is_optional:
      d['is_optional'] = True
    if self.is_extracted:
      d['is_extracted'] = True
    if self.is_sequence:
      d['is_sequence'] = True
    if self.data_format is not None:
      d['data_format'] = self.data_format
    if self.dataset_key:
      d['dataset_key'] = self.dataset_key
    if self.varlen_default_value is not None:
      d['varlen_default_value'] = float(self.varlen_default_value)
    return d

  @classmethod
  def from_dict(cls, d: dict) -> 'TensorSpec':
    return cls(
        shape=[(None if s < 0 else s) for s in d.get('shape', [])],
        dtype=d.get('dtype', 1),
        name=d.get('name'),
        is_optional=d.get('is_optional', False),
        is_sequence=d.get('is_sequence', False),
        is_extracted=d.get('is_extracted', False),
        data_format=d.get('data_format'),
        dataset_key=d.get('dataset_key'),
        varlen_default_value=d.get('varlen_default_value'),
    )

  # -- JAX interop ----------------------------------------------------------

  @property
  def jax_dtype(self):
    if self.dtype == np.dtype(object):
      raise ValueError('string spec {} has no jax dtype'.format(self.name))
    return jax.numpy.dtype(self.dtype)

  def shape_dtype_struct(self, batch_size: Optional[int] = None):
    """Returns jax.ShapeDtypeStruct, optionally prepending a batch dim."""
    shape = tuple(1 if s is None else s for s in self.shape)
    if batch_size is not None:
      shape = (batch_size,) + shape
    return jax.ShapeDtypeStruct(shape, self.jax_dtype)

  # -- Introspection --------------------------------------------------------

  @property
  def is_encoded_image(self) -> bool:
    return self.data_format is not None and self.data_format.lower() in (
        'jpeg', 'jpg', 'png', 'webp', 'bmp')

  def is_compatible_with(self, other) -> bool:
    """Shape/dtype compatibility. None dims match any size."""
    other_shape = tuple(getattr(other, 'shape', ()))
    other_dtype = canonical_dtype(getattr(other, 'dtype'))
    if other_dtype != self.dtype:
      return False
    if len(other_shape) != len(self.shape):
      return False
    for mine, theirs in zip(self.shape, other_shape):
      if mine is None or theirs is None:
        continue
      if int(mine) != int(theirs):
        return False
    return True

  def __repr__(self):
    extras = []
    for field in ('is_optional', 'is_sequence', 'is_extracted'):
      if getattr(self, field):
        extras.append('{}=True'.format(field))
    if self.data_format:
      extras.append('data_format={}'.format(self.data_format))
    if self.dataset_key:
      extras.append('dataset_key={}'.format(self.dataset_key))
    if self.varlen_default_value is not None:
      extras.append('varlen_default_value={}'.format(self.varlen_default_value))
    return 'TensorSpec(shape={}, dtype={}, name={}{})'.format(
        self.shape, dtype_name(self.dtype), self.name,
        (', ' + ', '.join(extras)) if extras else '')

  def __hash__(self):
    return hash((self.shape, self.dtype, self.name, self.is_optional,
                 self.is_sequence, self.data_format, self.dataset_key,
                 self.varlen_default_value))


# Alias matching the reference public name so user code reads familiarly.
ExtendedTensorSpec = TensorSpec
