"""Corrupt-record quarantine: bounded tolerance with loud exhaustion.

``skip_corrupt_records`` mode never hides damage: every skipped record and
abandoned file is counted per-file and process-wide, the counters surface
in train metrics (trainer/train_eval.py log path), and blowing either the
per-file or the global budget raises ``CorruptionBudgetExceeded`` naming
the offending file — dirty data degrades gracefully up to a configured
point, then fails the run on purpose.

Process-wide totals live in the telemetry registry
(``data/corrupt_records_skipped``, ``data/corrupt_files_abandoned``) —
the trainer's unified export pipeline picks them up without holding
references to generator instances (which may live behind prefetch
threads). ``aggregate_metrics`` remains as the stable read API.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.reliability.errors import CorruptionBudgetExceeded

RECORDS_SKIPPED_COUNTER = 'data/corrupt_records_skipped'
FILES_ABANDONED_COUNTER = 'data/corrupt_files_abandoned'


def aggregate_metrics() -> Dict[str, float]:
  """Counters for the train-metrics writer (monotonic within a process)."""
  registry = get_registry()
  return {
      RECORDS_SKIPPED_COUNTER:
          registry.counter(RECORDS_SKIPPED_COUNTER).value,
      FILES_ABANDONED_COUNTER:
          registry.counter(FILES_ABANDONED_COUNTER).value,
  }


def reset_aggregate_metrics() -> None:
  """Test hook: zero the process-wide counters."""
  registry = get_registry()
  registry.counter(RECORDS_SKIPPED_COUNTER).reset()
  registry.counter(FILES_ABANDONED_COUNTER).reset()


class RecordQuarantine:
  """Counts corrupt records against per-file and global budgets."""

  def __init__(self,
               max_corrupt_records: int = 100,
               max_corrupt_records_per_file: int = 10):
    """Budgets are inclusive tolerances: the (N+1)-th corrupt record over
    either limit raises. Pass 0 to fail on the first corruption (i.e.
    counting without tolerance); budgets never go negative."""
    self._lock = threading.Lock()
    self._max_total = int(max_corrupt_records)
    self._max_per_file = int(max_corrupt_records_per_file)
    self._skipped_by_file: Dict[str, int] = {}
    self._abandoned_files: Dict[str, str] = {}
    self._skipped_total = 0
    self._charged: set = set()  # (path, record_index) already counted

  @property
  def records_skipped(self) -> int:
    with self._lock:
      return self._skipped_total

  @property
  def files_abandoned(self) -> int:
    with self._lock:
      return len(self._abandoned_files)

  def skipped_in_file(self, path: str) -> int:
    with self._lock:
      return self._skipped_by_file.get(path, 0)

  def record_skipped(self, path: str, reason: str = '',
                     record_index: Optional[int] = None) -> None:
    """Charges one corrupt record to ``path``; raises when a budget blows.

    ``record_index`` (the record's position in the file) dedupes charges:
    multi-epoch runs re-read the same shards, and the same physically
    corrupt record must count against the budget once, not once per
    epoch — otherwise a small fixed amount of damage kills a long run.
    """
    with self._lock:
      if record_index is not None:
        key = (path, record_index)
        if key in self._charged:
          return
        self._charged.add(key)
      self._skipped_total += 1
      in_file = self._skipped_by_file.get(path, 0) + 1
      self._skipped_by_file[path] = in_file
      over_file = in_file > self._max_per_file
      over_total = self._skipped_total > self._max_total
    get_registry().counter(RECORDS_SKIPPED_COUNTER).inc()
    if over_file:
      raise CorruptionBudgetExceeded(path, 'file', self._max_per_file)
    if over_total:
      raise CorruptionBudgetExceeded(path, 'global', self._max_total)

  def file_abandoned(self, path: str, reason: str = '') -> None:
    """Marks the remainder of ``path`` unreadable (framing lost)."""
    newly = False
    with self._lock:
      if path not in self._abandoned_files:
        self._abandoned_files[path] = reason
        newly = True
    if newly:
      get_registry().counter(FILES_ABANDONED_COUNTER).inc()

  def summary(self) -> Dict[str, object]:
    with self._lock:
      return {
          'records_skipped': self._skipped_total,
          'by_file': dict(self._skipped_by_file),
          'abandoned_files': dict(self._abandoned_files),
      }
