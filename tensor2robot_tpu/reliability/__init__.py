"""Fault-tolerance substrate: retrying I/O, fault injection, quarantine.

The production T2R role (QT-Opt-scale off-policy RL feeding live robots,
SURVEY.md §2) assumes runs that survive weeks of preemptions, flaky
filesystems, and dirty logged data. This package holds the generic
machinery; the trainer/, data/, and predictors/ layers wire it in at the
named fault sites (docs/reliability.md):

  * ``retry`` / ``RetryPolicy`` — bounded exponential backoff + jitter
    around transient I/O (checkpoint save/restore, record reads).
  * ``FaultInjector`` — deterministic, site-addressed fault injection
    (``ckpt.save``, ``ckpt.restore``, ``data.read``, ``step.nan``) so the
    recovery paths are testable without real flaky hardware.
  * ``RecordQuarantine`` — corrupt-record accounting with per-file and
    global budgets; counters surface in train metrics.
  * ``graceful_shutdown`` — SIGTERM/SIGINT → emergency checkpoint.
"""

from tensor2robot_tpu.reliability.errors import (
    CorruptCheckpointError,
    CorruptionBudgetExceeded,
    CorruptRecordError,
    InjectedFault,
    NonFiniteLossError,
    RetryError,
    TrainingPreempted,
    TRANSIENT_IO_ERRORS,
)
from tensor2robot_tpu.reliability.fault_injection import (
    FaultInjector,
    SITE_CKPT_RESTORE,
    SITE_CKPT_SAVE,
    SITE_DATA_READ,
    SITE_STEP_NAN,
    configure_fault_injector,
    get_injector,
    set_injector,
)
from tensor2robot_tpu.reliability.preemption import graceful_shutdown
from tensor2robot_tpu.reliability.quarantine import (
    RecordQuarantine,
    aggregate_metrics,
    reset_aggregate_metrics,
)
from tensor2robot_tpu.reliability.retry import RetryPolicy, retry

__all__ = [
    'CorruptCheckpointError',
    'CorruptRecordError',
    'CorruptionBudgetExceeded',
    'FaultInjector',
    'InjectedFault',
    'NonFiniteLossError',
    'RecordQuarantine',
    'RetryError',
    'RetryPolicy',
    'SITE_CKPT_RESTORE',
    'SITE_CKPT_SAVE',
    'SITE_DATA_READ',
    'SITE_STEP_NAN',
    'TRANSIENT_IO_ERRORS',
    'TrainingPreempted',
    'aggregate_metrics',
    'configure_fault_injector',
    'get_injector',
    'graceful_shutdown',
    'reset_aggregate_metrics',
    'retry',
    'set_injector',
]
