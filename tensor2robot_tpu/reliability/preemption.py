"""Preemption-safe shutdown: turn SIGTERM/SIGINT into a clean save point.

Cluster schedulers preempt with SIGTERM and a grace window; a bare process
dies losing everything since the last periodic checkpoint. The trainer
wraps its loop in ``graceful_shutdown()``: the handler only sets a flag
(async-signal-safe), the loop notices it at the next step boundary, commits
an emergency checkpoint, and raises ``TrainingPreempted`` — so the restart
resumes exactly where the preemption landed.
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Iterator, Optional, Sequence


class ShutdownFlag:
  """Set by the signal handler, polled by the training loop."""

  def __init__(self):
    self.signum: Optional[int] = None

  @property
  def requested(self) -> bool:
    return self.signum is not None


@contextlib.contextmanager
def graceful_shutdown(
    signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT)
) -> Iterator[ShutdownFlag]:
  """Installs deferred handlers for ``signals``; restores them on exit.

  Signal handlers can only be installed from the main thread — from any
  other thread (e.g. a test harness or a hook running the trainer in a
  worker) this degrades to a no-op flag that never fires, which is safe:
  the default handlers stay in place.
  """
  flag = ShutdownFlag()
  if threading.current_thread() is not threading.main_thread():
    yield flag
    return
  previous = {}

  def _handler(signum, frame):  # noqa: ARG001 — signal API
    flag.signum = signum

  for sig in signals:
    try:
      previous[sig] = signal.signal(sig, _handler)
    except (ValueError, OSError):  # unsupported signal on this platform
      continue
  try:
    yield flag
  finally:
    for sig, handler in previous.items():
      signal.signal(sig, handler)
