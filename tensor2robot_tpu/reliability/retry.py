"""Bounded retry with exponential backoff + jitter.

The one generic retry primitive every I/O layer shares (checkpoint
save/restore, predictor restore, record reads). Deliberately synchronous
and dependency-free: callers wrap the *smallest* failing operation, not
whole loops, so a retry never replays side effects that already landed.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.reliability.errors import (
    RetryError,
    TRANSIENT_IO_ERRORS,
)

T = TypeVar('T')

# Every retried failure is charged here, labeled by site — fleet-visible
# evidence of a flaky mount long before a RetryError kills a run. The
# family resolves lazily so a swapped test registry is honored.
_RETRY_COUNTER_NAME = 'reliability/io_retries'


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
  """How to retry one site.

  Attributes:
    max_attempts: total tries (1 = no retry).
    base_delay_secs: delay before the first retry.
    backoff: multiplier per further retry.
    max_delay_secs: delay ceiling.
    jitter: extra uniform-random fraction of the delay in [0, jitter],
      decorrelating fleets that fail together. Seed the rng via ``retry``'s
      ``rng`` argument for determinism in tests; jitter=0 disables.
    retryable: exception types worth retrying. Everything else propagates
      immediately (a deterministic error does not get better with sleep).
  """

  max_attempts: int = 3
  base_delay_secs: float = 0.05
  backoff: float = 2.0
  max_delay_secs: float = 5.0
  jitter: float = 0.1
  retryable: Tuple[Type[BaseException], ...] = TRANSIENT_IO_ERRORS

  def delay_secs(self, retry_index: int,
                 rng: Optional[random.Random] = None) -> float:
    delay = min(self.base_delay_secs * (self.backoff ** retry_index),
                self.max_delay_secs)
    if self.jitter:
      delay *= 1.0 + self.jitter * (rng or random).random()
    return delay


def retry(fn: Callable[[], T],
          policy: Optional[RetryPolicy] = None,
          site: Optional[str] = None,
          sleep: Callable[[float], None] = time.sleep,
          rng: Optional[random.Random] = None,
          on_retry: Optional[Callable[[str, int, BaseException, float],
                                      None]] = None) -> T:
  """Calls ``fn`` until it succeeds or the policy is exhausted.

  Args:
    fn: zero-arg operation; its return value is passed through.
    policy: RetryPolicy; None uses the defaults.
    site: name for error messages / ``on_retry`` (e.g. 'ckpt.save').
    sleep: injectable for tests.
    rng: injectable random.Random for deterministic jitter.
    on_retry: callback(site, retry_index, exception, delay_secs) fired
      before each sleep.

  Raises:
    RetryError: wrapping the last retryable failure once attempts run out.
    Any non-retryable exception: immediately, unwrapped.
  """
  policy = policy or RetryPolicy()
  attempts = max(1, policy.max_attempts)
  last: Optional[BaseException] = None
  for attempt in range(attempts):
    try:
      return fn()
    except policy.retryable as e:  # pylint: disable=catching-non-exception
      last = e
      if attempt + 1 >= attempts:
        break
      delay = policy.delay_secs(attempt, rng=rng)
      get_registry().counter_family(
          _RETRY_COUNTER_NAME, ('site',)).series(site or 'unknown').inc()
      if on_retry is not None:
        on_retry(site or '', attempt, e, delay)
      sleep(delay)
  raise RetryError(site, attempts, last) from last
