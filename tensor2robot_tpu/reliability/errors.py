"""Exception taxonomy for the fault-tolerance layer.

One module with no intra-package imports so retry/, fault_injection/,
quarantine/ and the wired-up layers (trainer/, data/, predictors/) can all
share the same types without cycles.

The classification that matters operationally:

  * transient (retry): ``InjectedFault`` and real ``OSError``/``TimeoutError``
    from flaky filesystems — bounded retry with backoff, then ``RetryError``.
  * data-local (skip + budget): ``CorruptRecordError`` — quarantine the
    record (or the rest of the file when framing is lost) and keep going
    until ``CorruptionBudgetExceeded``.
  * run-level (stop or roll back): ``NonFiniteLossError``,
    ``TrainingPreempted``.
"""

from __future__ import annotations

from typing import Optional


class InjectedFault(IOError):
  """A failure forced by the FaultInjector at a named site.

  Subclasses IOError so the default RetryPolicy treats injected faults as
  the transient I/O errors they simulate.
  """

  def __init__(self, site: str, call_index: int):
    super().__init__(
        'Injected fault at site {!r} (call #{})'.format(site, call_index))
    self.site = site
    self.call_index = call_index


class RetryError(IOError):
  """All retry attempts exhausted; ``last`` holds the final cause."""

  def __init__(self, site: Optional[str], attempts: int,
               last: BaseException):
    super().__init__(
        'Gave up after {} attempt(s){}: {}'.format(
            attempts, ' at site {!r}'.format(site) if site else '', last))
    self.site = site
    self.attempts = attempts
    self.last = last


class CorruptRecordError(IOError):
  """One unreadable record (bad CRC, truncation, injected corruption)."""

  def __init__(self, path: str, reason: str,
               record_index: Optional[int] = None):
    at = '' if record_index is None else ' (record #{})'.format(record_index)
    super().__init__('Corrupt TFRecord {} in {}{}'.format(reason, path, at))
    self.path = path
    self.reason = reason
    self.record_index = record_index


class CorruptionBudgetExceeded(IOError):
  """skip_corrupt_records ran out of budget — fail loudly, name the file."""

  def __init__(self, path: str, scope: str, limit: int):
    super().__init__(
        'Corrupt-record budget exhausted: more than {} corrupt record(s) '
        '{} — last offender: {}. The data is damaged beyond the configured '
        'tolerance; repair or exclude it.'.format(
            limit, 'in one file' if scope == 'file' else 'across the run',
            path))
    self.path = path
    self.scope = scope
    self.limit = limit


class CorruptCheckpointError(IOError):
  """A checkpoint step whose on-disk state is visibly damaged
  (half-written commit, retention GC mid-read). Transient from the
  caller's perspective: skip to another step or wait for the next one."""

  def __init__(self, directory: str, step: int, detail: str):
    super().__init__(
        'Checkpoint step {} in {} is damaged ({}).'.format(
            step, directory, detail))
    self.directory = directory
    self.step = step


class NonFiniteLossError(RuntimeError):
  """The train loss went NaN/Inf and the policy says stop (or the
  rollback budget ran out)."""

  def __init__(self, step: int, detail: str = ''):
    super().__init__(
        'Non-finite train loss at step {}{}'.format(
            step, ': ' + detail if detail else ''))
    self.step = step


class TrainingPreempted(Exception):
  """SIGTERM/SIGINT received; an emergency checkpoint was committed
  before this was raised."""

  def __init__(self, signum: int, step: int):
    super().__init__(
        'Training preempted by signal {} at step {} (emergency checkpoint '
        'committed).'.format(signum, step))
    self.signum = signum
    self.step = step


# What the retrying wrappers treat as transient by default. IOError is an
# alias of OSError (and FileNotFoundError/InjectedFault subclass it);
# TimeoutError is separate on some paths.
TRANSIENT_IO_ERRORS = (OSError, TimeoutError)

# What a checkpoint CONSUMER may skip past (fall back to an older step,
# keep polling): transient restore failures come out of the retrying
# CheckpointManager exclusively as these two. Deliberately narrower than
# TRANSIENT_IO_ERRORS — a bare OSError out of an eval/data path (missing
# dataset, exhausted corruption budget) is NOT a checkpoint problem and
# must propagate.
CHECKPOINT_SKIP_ERRORS = (RetryError, CorruptCheckpointError)
