"""Deferred absl warning logger shared by the wired-up layers.

absl is optional at import time across this codebase (library modules
defer it); the reliability call sites all want the same warning-level
logger, so the deferral lives once here.
"""

from __future__ import annotations

_logv = None


def log_warning(msg: str, *args) -> None:
  global _logv
  if _logv is None:
    from absl import logging as _absl_logging  # deferred: absl optional
    _logv = _absl_logging.warning
  _logv(msg, *args)
