"""Deterministic, site-addressed fault injection.

A ``FaultInjector`` arms failures at named call sites; the wired-up layers
call ``maybe_fail(site)`` (raising sites) or ``fires(site)`` (boolean
sites) on every pass through. Determinism comes from counting calls per
site — "fail calls 3 and 4 of ``ckpt.save``" reproduces exactly, with no
randomness — which is what lets tests drive a specific recovery path.

Sites wired in this codebase (docs/reliability.md):
  * ``ckpt.save``     CheckpointManager.save, inside the retry loop
  * ``ckpt.restore``  CheckpointManager.restore, inside the retry loop
  * ``data.read``     tfrecord record reads → treated as a corrupt record
  * ``step.nan``      trainer train step → forces a non-finite loss
  * ``step.slow``     trainer loop → host-side sleep inflating the step
    time (``SLOW_STEP_SECONDS``), the symptom the observability
    watchdog must catch (docs/observability.md)
  * ``data.stall``    host→device feed (data/device_feed.py put_batch) →
    sleep stalling the data path (``DATA_STALL_SECONDS``), the symptom
    the pipeline X-ray must catch as ``pipeline_stall`` and attribute
    to the transfer stage (docs/observability.md "Pipeline X-ray")
  * ``host.preempt``  trainer loop → drives the FULL preemption path
    (emergency save → recovery marker → TrainingPreempted) without a
    real SIGTERM, so the recovery timeline (``t2r.recovery.v1``,
    docs/observability.md "Fleet observatory") is measurable
    deterministically — the injected-preemption half of ROADMAP item
    4's ``preemption_recovery_seconds`` metric
  * ``replay.append`` replay service append (replay/service.py) →
    deterministically CORRUPTS the arriving packed record (truncation),
    driving the per-shard quarantine-budget path without a bad writer
    (docs/replay.md)
  * ``replay.sample`` replay service sample → host-side sleep stalling
    the draw (``REPLAY_SAMPLE_STALL_SECONDS``), the symptom the
    learner's pipeline X-ray must catch as ``pipeline_stall`` when it
    trains from a replay endpoint instead of disk
  * ``actor.stall``  RL loop acting step (rl/loop.py) → host-side sleep
    inflating the acting step (``ACTOR_STALL_SECONDS``), the symptom
    the loop's own watchdog must catch as a step-time regression and
    turn into exactly one budgeted capture — while the concurrent
    learner keeps stepping (docs/rl_loop.md)
  * ``learner.swap`` RL loop weight poll (rl/loop.py) → DROPS one
    actor-side weight-swap poll (the snapshot is not adopted); the
    next poll retries, so the loop converges anyway — the protocol's
    at-least-once claim, driven deterministically
  * ``elastic.rebuild`` elastic mesh rebuild (elastic/driver.py) →
    host-side sleep wedging the shrink/grow rebuild
    (``ELASTIC_REBUILD_STALL_SECONDS``), the symptom the doctor's
    stuck-rebuild rule must catch and attribute to the stalled shrink
    phase (docs/elastic.md)

The injector is config-registrable: bind ``configure_fault_injector`` in a
gin file to arm faults for a whole run without touching code.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

from tensor2robot_tpu.reliability.errors import InjectedFault

SITE_CKPT_SAVE = 'ckpt.save'
SITE_CKPT_RESTORE = 'ckpt.restore'
SITE_DATA_READ = 'data.read'
SITE_STEP_NAN = 'step.nan'
SITE_STEP_SLOW = 'step.slow'
SITE_DATA_STALL = 'data.stall'
SITE_HOST_PREEMPT = 'host.preempt'
SITE_REPLAY_APPEND = 'replay.append'
SITE_REPLAY_SAMPLE = 'replay.sample'
SITE_ACTOR_STALL = 'actor.stall'
SITE_LEARNER_SWAP = 'learner.swap'
SITE_ELASTIC_REBUILD = 'elastic.rebuild'

KNOWN_SITES = (SITE_CKPT_SAVE, SITE_CKPT_RESTORE, SITE_DATA_READ,
               SITE_STEP_NAN, SITE_STEP_SLOW, SITE_DATA_STALL,
               SITE_HOST_PREEMPT, SITE_REPLAY_APPEND, SITE_REPLAY_SAMPLE,
               SITE_ACTOR_STALL, SITE_LEARNER_SWAP, SITE_ELASTIC_REBUILD)

# Signum stamped into preemption records driven by the injected
# 'host.preempt' site (no real signal was delivered).
INJECTED_PREEMPT_SIGNUM = -1

# How long one fired 'step.slow' stalls the loop. Module-level (not per
# armament) so tests tune it with a monkeypatch, matching the fixed
# deterministic character of the injector.
SLOW_STEP_SECONDS = 0.25

# How long one fired 'data.stall' wedges the host->device feed.
DATA_STALL_SECONDS = 0.25

# How long one fired 'replay.sample' stalls a replay draw.
REPLAY_SAMPLE_STALL_SECONDS = 0.25

# How long one fired 'actor.stall' wedges the RL loop's acting step.
ACTOR_STALL_SECONDS = 0.25

# How long one fired 'elastic.rebuild' wedges an elastic mesh rebuild.
ELASTIC_REBUILD_STALL_SECONDS = 0.25


class FaultInjector:
  """Counts calls per site and fires armed failures deterministically."""

  def __init__(self):
    self._lock = threading.Lock()
    # site -> list of call indices (0-based) that must fail.
    self._armed: Dict[str, List[int]] = {}
    self._calls: Dict[str, int] = {}
    self._fired: Dict[str, int] = {}

  def fail(self, site: str, times: int = 1, after: int = 0) -> 'FaultInjector':
    """Arms ``times`` consecutive failures at ``site``, skipping the first
    ``after`` calls. Returns self for chaining."""
    with self._lock:
      already = self._calls.get(site, 0)
      armed = self._armed.setdefault(site, [])
      start = already + after
      armed.extend(range(start, start + times))
    return self

  def fires(self, site: str) -> bool:
    """Consumes one call at ``site``; True when an armed failure fires.

    The boolean form for sites that do not raise (``step.nan``).
    """
    with self._lock:
      index = self._calls.get(site, 0)
      self._calls[site] = index + 1
      armed = self._armed.get(site, ())
      if index in armed:
        self._fired[site] = self._fired.get(site, 0) + 1
        return True
      return False

  def maybe_fail(self, site: str) -> None:
    """Consumes one call at ``site``; raises InjectedFault when armed."""
    if self.fires(site):
      raise InjectedFault(site, self._calls.get(site, 1) - 1)

  def call_count(self, site: str) -> int:
    with self._lock:
      return self._calls.get(site, 0)

  def fired_count(self, site: str) -> int:
    with self._lock:
      return self._fired.get(site, 0)

  def reset(self) -> None:
    with self._lock:
      self._armed.clear()
      self._calls.clear()
      self._fired.clear()


_INJECTOR: Optional[FaultInjector] = None
_INJECTOR_LOCK = threading.Lock()


def get_injector() -> Optional[FaultInjector]:
  """The process-wide injector, or None when fault injection is off."""
  return _INJECTOR


def set_injector(injector: Optional[FaultInjector]) -> None:
  global _INJECTOR
  with _INJECTOR_LOCK:
    _INJECTOR = injector


def maybe_fail(site: str) -> None:
  """Module-level hook the instrumented sites call; no-op when disabled."""
  injector = _INJECTOR
  if injector is not None:
    injector.maybe_fail(site)


def fires(site: str) -> bool:
  injector = _INJECTOR
  if injector is not None:
    return injector.fires(site)
  return False


def slow_step_seconds() -> float:
  """Seconds the 'step.slow' site stalls THIS step; 0.0 when unarmed."""
  injector = _INJECTOR
  if injector is not None and injector.fires(SITE_STEP_SLOW):
    return SLOW_STEP_SECONDS
  return 0.0


def stall_data_seconds() -> float:
  """Seconds the 'data.stall' site wedges THIS batch; 0.0 when unarmed."""
  injector = _INJECTOR
  if injector is not None and injector.fires(SITE_DATA_STALL):
    return DATA_STALL_SECONDS
  return 0.0


def replay_sample_stall_seconds() -> float:
  """Seconds the 'replay.sample' site stalls THIS draw; 0.0 when unarmed."""
  injector = _INJECTOR
  if injector is not None and injector.fires(SITE_REPLAY_SAMPLE):
    return REPLAY_SAMPLE_STALL_SECONDS
  return 0.0


def actor_stall_seconds() -> float:
  """Seconds the 'actor.stall' site wedges THIS acting step; 0.0 unarmed."""
  injector = _INJECTOR
  if injector is not None and injector.fires(SITE_ACTOR_STALL):
    return ACTOR_STALL_SECONDS
  return 0.0


def elastic_rebuild_stall_seconds() -> float:
  """Seconds the 'elastic.rebuild' site wedges THIS rebuild; 0.0 unarmed."""
  injector = _INJECTOR
  if injector is not None and injector.fires(SITE_ELASTIC_REBUILD):
    return ELASTIC_REBUILD_STALL_SECONDS
  return 0.0


FaultSpec = Union[Dict[str, int], Sequence[Union[Tuple[str, int],
                                                 Tuple[str, int, int]]]]


def configure_fault_injector(
    failures: Optional[FaultSpec] = None) -> Optional[FaultInjector]:
  """Installs a process-wide injector from a config-friendly spec.

  ``failures`` is either ``{'ckpt.save': 2}`` (fail the first 2 calls per
  site) or ``[('data.read', 1, 5), ...]`` tuples of
  ``(site, times[, after])``. ``None``/empty uninstalls the injector.
  Gin-registrable (config/registry.py) so a run can arm faults from its
  config file alone.
  """
  if not failures:
    set_injector(None)
    return None
  injector = FaultInjector()
  if isinstance(failures, dict):
    items = [(site, times, 0) for site, times in failures.items()]
  else:
    items = [tuple(entry) + (0,) * (3 - len(entry)) for entry in failures]
  for site, times, after in items:
    injector.fail(site, times=int(times), after=int(after))
  set_injector(injector)
  return injector
