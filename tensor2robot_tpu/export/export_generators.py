"""Export generators: write versioned, self-describing serving artifacts.

Parity targets:
  * AbstractExportGenerator  /root/reference/export_generators/abstract_export_generator.py:43
  * DefaultExportGenerator   /root/reference/export_generators/default_export_generator.py:47-138
  * t2r_assets in assets.extra  /root/reference/utils/train_eval.py:296-370

TPU-native redesign. The reference exports TF1 SavedModels whose graph bakes
in placeholders + preprocessing; robot-side predictors reload them with a
session. Here the serving artifact is:

    <export_root>/<version>/            (numeric version, ATOMICALLY renamed
      variables/                         from a tmp- prefix, so pollers never
        ...orbax checkpoint...           see partial exports — the reference's
      assets.extra/t2r_assets.pbtxt      tmp-dir filtering contract,
      assets.extra/t2r_assets.json       exported_savedmodel_predictor.py:238)
      global_step.txt
      predict_fn.jaxexport               (optional: serialized StableHLO of the
                                          full preprocess+forward predict step
                                          via jax.export — loadable WITHOUT the
                                          Python model class, the SavedModel
                                          analog)

``assets.extra/t2r_assets.pbtxt`` keeps the exact reference contract so any
tooling that reads specs from exports keeps working. The numpy receiver
semantics (feed a dict of arrays matching the preprocessor in-spec) live in
the predictor; the tf.Example receiver is the predictor parsing serialized
examples with the spec-driven wire parser before the same feed.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

try:
  # jax >= 0.4.30 ships the stable module; plain `jax.export` attribute
  # access is deprecation-gated on 0.4.x and raises AttributeError.
  from jax import export as jax_export
except ImportError:  # pragma: no cover - older jax without jax.export
  jax_export = None

from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs import generators as spec_generators
from tensor2robot_tpu.specs.struct import SpecStruct

VARIABLES_SUBDIR = 'variables'
PREDICT_FN_FILENAME = 'predict_fn.jaxexport'
WARMUP_REQUESTS_FILENAME = 'warmup_requests.npz'
SERVING_CONFIG_FILENAME = 'serving_config.json'
_TMP_PREFIX = 'tmp-'


def state_from_variables(variables, step: int = 0):
  """Variables pytree (an artifact/checkpoint's content) -> TrainState.

  The shared inverse of ``TrainState.variables()``: 'params' and optional
  'avg_params' split out, everything else is model_state.
  """
  from tensor2robot_tpu.models.abstract_model import TrainState
  variables = dict(variables)
  params = variables.pop('params')
  avg_params = variables.pop('avg_params', None)
  return TrainState(step=np.asarray(step, np.int32), params=params,
                    model_state=variables, opt_state=None,
                    avg_params=avg_params, ema_state=None)


def make_serve_fn(model, raw_receivers: bool = False):
  """The ONE serving function: (variables, features) -> outputs dict.

  Used by the export serializer and both predictors so serving semantics
  (PREDICT-mode preprocessing unless ``raw_receivers``, action tiling and
  avg-params selection via ``model.predict_step``) are defined exactly once.
  """

  def serve(variables, features):
    state = state_from_variables(variables)
    features = SpecStruct(**features)
    if not raw_receivers:
      features, _ = model.preprocessor.preprocess(
          features, None, ModeKeys.PREDICT, rng=None)
    return dict(model.predict_step(state, features))

  return serve


def garbage_collect_versions(export_root: str, keep: int) -> None:
  """Deletes all but the newest ``keep`` committed versions."""
  import shutil
  for version in list_exported_versions(export_root)[:-keep or None]:
    shutil.rmtree(os.path.join(export_root, str(version)),
                  ignore_errors=True)


def _is_version_dir(name: str) -> bool:
  return name.isdigit()


def list_exported_versions(export_root: str) -> List[int]:
  """Committed (atomically renamed) numeric version dirs, ascending."""
  if not os.path.isdir(export_root):
    return []
  return sorted(int(name) for name in os.listdir(export_root)
                if _is_version_dir(name))


def next_version(export_root: str) -> int:
  """Monotonic wall-clock version, bumped past any existing dir."""
  version = int(time.time())
  existing = list_exported_versions(export_root)
  if existing and version <= existing[-1]:
    version = existing[-1] + 1
  return version


def write_serving_artifact(export_root: str,
                           variables: Any,
                           feature_spec,
                           label_spec,
                           global_step: int,
                           predict_fn_bytes: Optional[bytes] = None,
                           warmup_features: Optional[Dict[str, np.ndarray]] = None,
                           version: Optional[int] = None,
                           raw_receivers: bool = False) -> str:
  """Writes one versioned artifact; returns its committed path.

  The write happens under a ``tmp-`` prefix and is committed with a single
  ``os.rename`` so concurrent pollers only ever observe complete exports
  (ref exported_savedmodel_predictor.py:238-274 tmp filtering + retries).
  """
  if version is None:
    version = next_version(export_root)
  os.makedirs(export_root, exist_ok=True)
  final_dir = os.path.join(export_root, str(version))
  tmp_dir = os.path.join(export_root, _TMP_PREFIX + str(version))

  host_variables = jax.tree.map(np.asarray, jax.device_get(variables))
  checkpointer = ocp.StandardCheckpointer()
  try:
    checkpointer.save(os.path.join(tmp_dir, VARIABLES_SUBDIR), host_variables)
    checkpointer.wait_until_finished()
  finally:
    checkpointer.close()

  assets_lib.write_t2r_assets_to_file(
      feature_spec, label_spec, global_step,
      os.path.join(tmp_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                   assets_lib.T2R_ASSETS_FILENAME))
  assets_lib.write_global_step_to_file(global_step, tmp_dir)
  if predict_fn_bytes is not None:
    with open(os.path.join(tmp_dir, PREDICT_FN_FILENAME), 'wb') as f:
      f.write(predict_fn_bytes)
  if warmup_features is not None:
    np.savez(os.path.join(tmp_dir, WARMUP_REQUESTS_FILENAME),
             **{k: np.asarray(v) for k, v in warmup_features.items()})
  import json
  with open(os.path.join(tmp_dir, SERVING_CONFIG_FILENAME), 'w') as f:
    json.dump({'raw_receivers': bool(raw_receivers)}, f)
  os.rename(tmp_dir, final_dir)
  return final_dir


def load_serving_config(version_dir: str) -> dict:
  import json
  try:
    with open(os.path.join(version_dir, SERVING_CONFIG_FILENAME)) as f:
      return json.load(f)
  except (OSError, ValueError):
    return {'raw_receivers': False}


def load_exported_variables(version_dir: str) -> Any:
  """Restores the raw variables pytree from one exported version."""
  checkpointer = ocp.StandardCheckpointer()
  try:
    return checkpointer.restore(os.path.join(version_dir, VARIABLES_SUBDIR))
  finally:
    checkpointer.close()


class AbstractExportGenerator:
  """Builds serving artifacts for a model (ref abstract_export_generator.py:43).

  ``export_raw_receivers`` mirrors the reference flag (:52): when True the
  artifact's declared in-spec is the MODEL's feature spec (client preprocesses);
  when False it is the PREPROCESSOR's in-spec and the exported predict function
  runs preprocessing in-graph.
  """

  def __init__(self, export_raw_receivers: bool = False):
    self._export_raw_receivers = export_raw_receivers
    self._model = None

  def set_specification_from_model(self, t2r_model) -> None:
    """ref abstract_export_generator.py:61 — binds specs (here: the model)."""
    self._model = t2r_model

  @property
  def model(self):
    if self._model is None:
      raise ValueError(
          'set_specification_from_model must be called before exporting.')
    return self._model

  def serving_feature_spec(self) -> SpecStruct:
    """The in-spec the serving client must feed."""
    if self._export_raw_receivers:
      return self.model.get_feature_specification_for_packing(ModeKeys.PREDICT)
    return self.model.preprocessor.get_in_feature_specification(
        ModeKeys.PREDICT)

  def create_serving_fn(self):
    """Pure (variables, features) -> outputs serving function."""
    return make_serve_fn(self.model, raw_receivers=self._export_raw_receivers)

  def serialize_predict_fn(self, variables, features) -> Optional[bytes]:
    """Best-effort StableHLO serialization of the serving function.

    Makes the artifact loadable with zero Python model code (the SavedModel
    property). The batch dimension is exported SYMBOLICALLY so the artifact
    serves any batch size (the reference's None-batch placeholders,
    default_export_generator.py:61). Returns None when the function cannot
    be lowered (e.g. host callbacks inside a custom model).
    """
    serve = self.create_serving_fn()
    variables_abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
        variables)

    def _features_abstract(batch_dim):
      return {k: jax.ShapeDtypeStruct((batch_dim,) + np.shape(v)[1:],
                                      np.asarray(v).dtype)
              for k, v in features.items()}

    if jax_export is None:
      return None
    try:
      (batch_dim,) = jax_export.symbolic_shape('b')
      exported = jax_export.export(jax.jit(serve))(
          variables_abstract, _features_abstract(batch_dim))
      return exported.serialize()
    except Exception:  # pylint: disable=broad-except
      pass
    try:
      # Models that can't trace with a symbolic batch (e.g. fixed CEM
      # tiling) fall back to the warmup batch's concrete shape.
      exported = jax_export.export(jax.jit(serve))(
          variables_abstract,
          _features_abstract(int(np.shape(next(iter(features.values())))[0])))
      return exported.serialize()
    except Exception:  # pylint: disable=broad-except
      return None

  def export(self, export_root: str, variables, global_step: int,
             batch_size: int = 1, version: Optional[int] = None) -> str:
    """Writes one artifact for the current variables; returns its path."""
    feature_spec = self.serving_feature_spec()
    label_spec = self.model.get_label_specification(ModeKeys.PREDICT)
    warmup = spec_generators.make_random_numpy(
        feature_spec, batch_size=batch_size).to_dict()
    predict_fn_bytes = self.serialize_predict_fn(variables, warmup)
    return write_serving_artifact(
        export_root, variables, feature_spec, label_spec, global_step,
        predict_fn_bytes=predict_fn_bytes, warmup_features=warmup,
        version=version, raw_receivers=self._export_raw_receivers)


class DefaultExportGenerator(AbstractExportGenerator):
  """The standard generator (ref default_export_generator.py:47): in-graph
  preprocessing + numpy receiver semantics."""


class VariablesExportGenerator(AbstractExportGenerator):
  """Variables-only artifact: no StableHLO predict fn, no warmup batch.

  For high-frequency export consumers that are in-process and already hold
  the model class — the filesystem target-network loop (rl/offpolicy.py
  polls the lagged dir every few train steps; re-lowering the serving
  function per export would dominate the update interval). The artifact
  keeps the directory contract (specs, global step, atomic commit), minus
  ``predict_fn.jaxexport`` and ``warmup_requests.npz``.
  """

  def serialize_predict_fn(self, variables, features):
    del variables, features
    return None

  def export(self, export_root: str, variables, global_step: int,
             batch_size: int = 1, version: Optional[int] = None) -> str:
    del batch_size
    return write_serving_artifact(
        export_root, variables, self.serving_feature_spec(),
        self.model.get_label_specification(ModeKeys.PREDICT), global_step,
        version=version, raw_receivers=self._export_raw_receivers)
