"""TF SavedModel export via jax2tf: serve JAX models on TF-Serving stacks.

Parity target: /root/reference/export_generators/default_export_generator.py
:47-138 — the numpy receiver (feed feature tensors, :61-87) and the
tf.Example receiver (feed serialized example strings parsed in-graph,
:89-138) — and the assets.extra/t2r_assets.pbtxt contract of
utils/train_eval.py:296-370.

The exported SavedModel contains:
  * signature 'serving_default': per-feature tensors (batch-polymorphic),
    running the SAME preprocess+predict function the native predictors use
    (make_serve_fn), staged through jax2tf;
  * signature 'tf_example': 1-D string tensor of serialized tf.Examples,
    parsed with tf.io.parse_example + in-graph JPEG decode per the in-spec
    (the reference's tf-example receiver);
  * assets.extra/t2r_assets.pbtxt (+json) — spec round-trip for predictors.

TensorFlow is imported inside functions: only this export path needs it.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from tensor2robot_tpu.export import export_generators
from tensor2robot_tpu.modes import ModeKeys
from tensor2robot_tpu.specs import algebra
from tensor2robot_tpu.specs import assets as assets_lib
from tensor2robot_tpu.specs import generators as spec_generators


def _tf_dtype(np_dtype):
  import tensorflow as tf
  return tf.dtypes.as_dtype(np.dtype(np_dtype))


# -- TF-Serving warmup requests ----------------------------------------------
#
# The reference writes assets.extra/tf_serving_warmup_requests — a TFRecord
# of tensorflow_serving PredictionLog protos (ref
# abstract_export_generator.py:114-147) that TF-Serving replays at model
# load to pre-trigger compilation. The tensorflow_serving proto package is
# not a dependency here; the messages involved are tiny and are emitted
# directly with the wire codec:
#
#   PredictionLog { PredictLog predict_log = 6; }
#   PredictLog    { PredictRequest request = 1; }
#   PredictRequest{ ModelSpec model_spec = 1;
#                   map<string, TensorProto> inputs = 2; }
#   ModelSpec     { string name = 1; string signature_name = 3; }
#
# TensorProto/TensorShapeProto come from TF core (dtype=1, tensor_shape=2,
# tensor_content=4) and are verified against tf.make_ndarray in tests.


def _encode_tensor_proto(value: np.ndarray) -> bytes:
  from tensor2robot_tpu.data.wire import emit_bytes_field, write_varint

  value = np.ascontiguousarray(value)
  is_string = value.dtype == np.dtype(object) or value.dtype.kind in 'SU'
  out = bytearray()
  write_varint(out, (1 << 3) | 0)  # dtype
  write_varint(out, 7 if is_string else  # DT_STRING
               int(_tf_dtype(value.dtype).as_datatype_enum))
  shape = bytearray()
  for size in value.shape:
    dim = bytearray()
    write_varint(dim, (1 << 3) | 0)
    write_varint(dim, int(size))
    emit_bytes_field(shape, 2, bytes(dim))
  emit_bytes_field(out, 2, bytes(shape))
  if is_string:
    # DT_STRING payloads live in string_val (field 8), NOT tensor_content.
    for item in value.ravel():
      data = item if isinstance(item, bytes) else str(item).encode('utf-8')
      emit_bytes_field(out, 8, data)
  else:
    emit_bytes_field(out, 4, value.tobytes())  # tensor_content, LE bytes
  return bytes(out)


def encode_prediction_log(inputs, model_name: str = 'default',
                          signature_name: str = 'serving_default') -> bytes:
  """One serialized PredictionLog carrying a PredictRequest of ``inputs``."""
  from tensor2robot_tpu.data.wire import emit_bytes_field

  model_spec = bytearray()
  emit_bytes_field(model_spec, 1, model_name.encode('utf-8'))
  emit_bytes_field(model_spec, 3, signature_name.encode('utf-8'))
  request = bytearray()
  emit_bytes_field(request, 1, bytes(model_spec))
  for key in sorted(inputs):
    entry = bytearray()
    emit_bytes_field(entry, 1, key.encode('utf-8'))
    emit_bytes_field(entry, 2,
                      _encode_tensor_proto(np.asarray(inputs[key])))
    emit_bytes_field(request, 2, bytes(entry))
  predict_log = bytearray()
  emit_bytes_field(predict_log, 1, bytes(request))
  prediction_log = bytearray()
  emit_bytes_field(prediction_log, 6, bytes(predict_log))
  return bytes(prediction_log)


def write_tf_serving_warmup_requests(path: str, inputs,
                                     model_name: str = 'default',
                                     signature_name: str = 'serving_default'
                                     ) -> None:
  """assets.extra/tf_serving_warmup_requests (ref :114-147)."""
  from tensor2robot_tpu.data import tfrecord

  tfrecord.write_records(path, [
      encode_prediction_log(inputs, model_name, signature_name)])


class TFSavedModelExportGenerator(export_generators.AbstractExportGenerator):
  """Exports versioned TF SavedModels instead of native artifacts."""

  def export(self, export_root: str, variables, global_step: int,
             batch_size: int = 1, version: Optional[int] = None) -> str:
    import tensorflow as tf
    from jax.experimental import jax2tf

    if version is None:
      version = export_generators.next_version(export_root)
    os.makedirs(export_root, exist_ok=True)
    final_dir = os.path.join(export_root, str(version))
    tmp_dir = os.path.join(export_root, 'tmp-' + str(version))

    serve = self.create_serving_fn()
    host_variables = jax.tree.map(np.asarray, jax.device_get(variables))
    feature_spec = self.serving_feature_spec()
    flat_spec = algebra.flatten_spec_structure(feature_spec)

    polymorphic = {key: '(b, ...)' for key in flat_spec}
    converted = jax2tf.convert(
        lambda feats: serve(host_variables, feats),
        polymorphic_shapes=[polymorphic],
        with_gradient=False)

    input_signature = [{
        key: tf.TensorSpec((None,) + tuple(flat_spec[key].shape),
                           _tf_dtype(flat_spec[key].dtype), name=key)
        for key in flat_spec
    }]
    serving_fn = tf.function(converted, input_signature=input_signature,
                             autograph=False)

    example_parser = self._make_example_parser(flat_spec)

    @tf.function(
        input_signature=[tf.TensorSpec([None], tf.string,
                                       name='input_example_tensor')],
        autograph=False)
    def tf_example_fn(serialized):
      return converted(example_parser(serialized))

    module = tf.Module()
    module.serving_fn = serving_fn
    module.tf_example_fn = tf_example_fn
    signatures = {
        'serving_default': serving_fn.get_concrete_function(
            *input_signature),
        'tf_example': tf_example_fn.get_concrete_function(),
    }
    tf.saved_model.save(module, tmp_dir, signatures=signatures)

    assets_lib.write_t2r_assets_to_file(
        feature_spec,
        self.model.get_label_specification(ModeKeys.PREDICT), global_step,
        os.path.join(tmp_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                     assets_lib.T2R_ASSETS_FILENAME))
    assets_lib.write_global_step_to_file(global_step, tmp_dir)
    warmup = spec_generators.make_random_numpy(
        feature_spec, batch_size=batch_size).to_dict()
    np.savez(os.path.join(tmp_dir,
                          export_generators.WARMUP_REQUESTS_FILENAME),
             **{k: np.asarray(v) for k, v in warmup.items()})
    write_tf_serving_warmup_requests(
        os.path.join(tmp_dir, assets_lib.EXTRA_ASSETS_DIRECTORY,
                     'tf_serving_warmup_requests'), warmup)
    os.rename(tmp_dir, final_dir)
    return final_dir

  def _make_example_parser(self, flat_spec):
    """In-graph tf.Example parsing + JPEG decode (ref :104-138)."""
    import tensorflow as tf

    fixed_features: Dict[str, Any] = {}
    for key in flat_spec:
      spec = flat_spec[key]
      name = spec.name or key
      if spec.is_encoded_image:
        fixed_features[name] = tf.io.FixedLenFeature([], tf.string)
      else:
        fixed_features[name] = tf.io.FixedLenFeature(
            list(spec.shape), _tf_dtype(spec.dtype))

    def parse(serialized):
      parsed = tf.io.parse_example(serialized, fixed_features)
      features = {}
      for key in flat_spec:
        spec = flat_spec[key]
        name = spec.name or key
        value = parsed[name]
        if spec.is_encoded_image:
          shape = tuple(spec.shape)
          value = tf.map_fn(
              lambda b, s=shape: tf.reshape(
                  tf.io.decode_image(b, channels=s[-1],
                                     expand_animations=False), s),
              value, fn_output_signature=tf.uint8)
          value = tf.cast(value, _tf_dtype(spec.dtype)) \
              if spec.dtype != np.uint8 else value
        features[key] = value
      return features

    return parse
