"""Latest/Best exporters invoked after each eval (ref utils/train_eval.py:296-370).

The reference wires Estimator ``LatestExporter``/``BestExporter`` pairs (numpy
and tf_example receivers) into the EvalSpec; each writes a SavedModel with
``t2r_assets.pbtxt``. Here exporters are plain objects called by
``train_eval_model`` after every eval phase with ``(trainer, state, metrics)``;
each writes a versioned serving artifact (export_generators.py) and applies
its retention policy. One artifact serves both receiver styles — the predictor
accepts numpy dicts or serialized examples against the same specs.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax

from tensor2robot_tpu.export import export_generators

EXPORT_SUBDIR = 'export'


def _loss_compare_fn(best: Optional[Dict[str, float]],
                     current: Dict[str, float],
                     key: str = 'loss') -> bool:
  """True when current beats best. Robust to missing keys (ref :207-292)."""
  if current is None or key not in current:
    return False
  if best is None or key not in best:
    return True
  return float(current[key]) < float(best[key])


class _ExporterBase:
  """Shared: resolve export root, write one artifact, GC old versions."""

  def __init__(self, name: str,
               export_generator: Optional[
                   export_generators.AbstractExportGenerator] = None,
               exports_to_keep: int = 5,
               use_avg_params: Optional[bool] = None):
    self.name = name
    self._export_generator = (export_generator or
                              export_generators.DefaultExportGenerator())
    self._exports_to_keep = exports_to_keep
    self._use_avg_params = use_avg_params

  def export_root(self, trainer) -> str:
    return os.path.join(trainer.model_dir, EXPORT_SUBDIR, self.name)

  def _write(self, trainer, state) -> str:
    model = trainer.model
    self._export_generator.set_specification_from_model(model)
    use_avg = (model.use_avg_model_params if self._use_avg_params is None
               else self._use_avg_params)
    variables = jax.device_get(state.variables(use_avg_params=use_avg))
    step = int(jax.device_get(state.step))
    path = self._export_generator.export(self.export_root(trainer), variables,
                                         step)
    export_generators.garbage_collect_versions(self.export_root(trainer),
                                               self._exports_to_keep)
    return path

  def export(self, trainer, state, eval_metrics) -> Optional[str]:
    raise NotImplementedError


class LatestModelExporter(_ExporterBase):
  """Exports after every eval, keeping the newest N (ref LatestExporter)."""

  def __init__(self, name: str = 'latest_exporter', **kwargs):
    super().__init__(name=name, **kwargs)

  def export(self, trainer, state, eval_metrics) -> Optional[str]:
    del eval_metrics
    return self._write(trainer, state)


class BestModelExporter(_ExporterBase):
  """Exports only on metric improvement (ref BestExporter + compare fns).

  The best metric survives process restarts via a json state file next to
  the exports, mirroring the reference's event-file-derived best tracking.
  """

  def __init__(self, name: str = 'best_exporter', metric_key: str = 'loss',
               **kwargs):
    super().__init__(name=name, **kwargs)
    self._metric_key = metric_key

  def _state_path(self, trainer) -> str:
    return os.path.join(self.export_root(trainer), 'best_metrics.json')

  def _load_best(self, trainer) -> Optional[Dict[str, Any]]:
    try:
      with open(self._state_path(trainer)) as f:
        return json.load(f)
    except (OSError, ValueError):
      return None

  def export(self, trainer, state, eval_metrics) -> Optional[str]:
    best = self._load_best(trainer)
    if not _loss_compare_fn(best, eval_metrics, self._metric_key):
      return None
    path = self._write(trainer, state)
    os.makedirs(self.export_root(trainer), exist_ok=True)
    with open(self._state_path(trainer), 'w') as f:
      json.dump({self._metric_key: float(eval_metrics[self._metric_key])}, f)
    return path


def create_default_exporters(t2r_model,
                             export_generator: Optional[
                                 export_generators.AbstractExportGenerator] = None,
                             exports_to_keep: int = 5,
                             metric_key: str = 'loss'):
  """Best + Latest exporter pair (ref utils/train_eval.py:296)."""
  del t2r_model  # bound per-export via set_specification_from_model
  return [
      BestModelExporter(export_generator=export_generator,
                        exports_to_keep=exports_to_keep,
                        metric_key=metric_key),
      LatestModelExporter(export_generator=export_generator,
                          exports_to_keep=exports_to_keep),
  ]
