"""Model export: versioned serving artifacts with the t2r_assets contract."""

from tensor2robot_tpu.export.export_generators import (
    AbstractExportGenerator,
    DefaultExportGenerator,
    VARIABLES_SUBDIR,
    list_exported_versions,
    load_exported_variables,
    write_serving_artifact,
)
from tensor2robot_tpu.export.exporters import (
    BestModelExporter,
    LatestModelExporter,
    create_default_exporters,
)

__all__ = [
    'AbstractExportGenerator',
    'BestModelExporter',
    'DefaultExportGenerator',
    'LatestModelExporter',
    'VARIABLES_SUBDIR',
    'create_default_exporters',
    'list_exported_versions',
    'load_exported_variables',
    'write_serving_artifact',
]
