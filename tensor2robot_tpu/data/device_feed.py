"""Sparse-aware host->device batch feed.

The last hop of the split-decode input path (SURVEY hard-part #3). A
``DeviceDecodePreprocessor(sparse=True)`` pipeline ships images as sparse
DCT entry streams (``key/{sd,sv,qt,n}``, data/native/record_loader.cc) whose
second dim is BUCKETED per batch — the format's transfer savings come from
slicing buffers to the batch's actual entry count. Unpacking them inside the
jitted train step would therefore recompile the whole model per bucket;
instead this feed converts sparse groups to the fixed-shape dense
coefficient tensors (``key/{y,cb,cr}``) the preprocessor consumes, in a
SEPARATE tiny jit cached per (batch, bucket) shape, right after the
host->device transfer:

    host batch (sparse, ~8x fewer bytes) --transfer--> device
      --unpack jit (cumsum + scatter-add, ~15 ms / 64 frames)-->
    dense coef batch --train step (shape-stable, never recompiles)-->

Non-sparse batches pass through as a plain ``shard_batch``, so the Trainer
routes every batch through :meth:`SparseCoefFeed.put_batch` unconditionally.

The shape-stability contract above is ASSERTED as telemetry, not just
documented: every emitted batch's shape signature lands in the
``data/feed_shape_signatures`` gauge (must stay 1 — the observability
watchdog's ``recompile`` trigger fires otherwise) and the per-bucket
unpack-jit cache size in ``recompiles/coef_unpack`` (expected to grow
once per bucket, then plateau).
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from tensor2robot_tpu.data import jpeg_device
from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.parallel import sharding as sharding_lib

FEED_SHAPES_GAUGE = 'data/feed_shape_signatures'
UNPACK_COMPILES_GAUGE = 'recompiles/coef_unpack'


class SparseCoefFeed:
  """Converts host batches with sparse coef groups into device batches."""

  def __init__(self, image_shapes: Dict[str, Tuple[int, int]], mesh):
    self._shapes = dict(image_shapes)
    self._mesh = mesh
    self._jit_cache = {}
    self._signatures: Dict[str, Set[Tuple]] = {}
    registry = get_registry()
    self._shape_gauge = registry.gauge(FEED_SHAPES_GAUGE)
    self._unpack_gauge = registry.gauge(UNPACK_COMPILES_GAUGE)

  @classmethod
  def from_preprocessor(cls, preprocessor, mesh
                        ) -> Optional['SparseCoefFeed']:
    """A feed for a DeviceDecodePreprocessor-wrapped model, else None."""
    from tensor2robot_tpu.preprocessors.device_decode import (
        DeviceDecodePreprocessor,
    )

    # Unwrap decorators (e.g. the TPU Bfloat16PreprocessorWrapper, which
    # train_eval_model installs OUTSIDE the device-decode wrapper) via
    # their ``preprocessor`` property.
    seen = 0
    while (not isinstance(preprocessor, DeviceDecodePreprocessor)
           and seen < 8):
      nxt = getattr(type(preprocessor), 'preprocessor', None)
      if nxt is None:
        return None
      preprocessor = preprocessor.preprocessor
      seen += 1
    if not isinstance(preprocessor, DeviceDecodePreprocessor):
      return None
    spec = preprocessor.raw_in_feature_specification('train')
    from tensor2robot_tpu.specs import algebra
    flat = algebra.flatten_spec_structure(spec)
    shapes = {key: (flat[key].shape[0], flat[key].shape[1])
              for key in preprocessor.image_keys('train')}
    return cls(shapes, mesh=mesh)

  def _unpack_fn(self, height: int, width: int, shape):
    import jax

    cache_key = (height, width, tuple(shape))
    fn = self._jit_cache.get(cache_key)
    if fn is None:
      # Explicit batch-sharded outputs: the train step is jitted with
      # explicit in_shardings, and on a multi-device mesh an INFERRED
      # unpack output sharding need not match it (jax then errors
      # instead of resharding). No donation: the uint8/int8 inputs can't
      # alias the int16 outputs, so donating only produces "donated
      # buffers were not usable" spam.
      out_sharding = sharding_lib.batch_sharding(self._mesh)
      fn = jax.jit(
          lambda sd, sv: jpeg_device.unpack_sparse_coefficients(
              sd, sv, height, width),
          out_shardings=out_sharding)
      self._jit_cache[cache_key] = fn
    return fn

  def _record_signature(self, features: dict, channel: str) -> None:
    """Counts distinct emitted batch-shape signatures into the gauges.

    The signature covers NAME and SHAPE of every feature the jitted step
    will see — exactly the recompile key. Signatures are tracked per
    ``channel`` because one feed serves several independently-jitted
    programs (train step, eval step, summary pass), each shape-stable on
    its own: an eval batch sized differently from train is legitimate
    and must not trip the train invariant. The exported gauge covers
    only the ``'train'`` channel — the contract the watchdog asserts.
    """
    signature = tuple(sorted(
        (key, tuple(getattr(value, 'shape', ()))
         ) for key, value in features.items()))
    self._signatures.setdefault(channel, set()).add(signature)
    self._shape_gauge.set(float(len(self._signatures.get('train', ()))))
    self._unpack_gauge.set(float(len(self._jit_cache)))

  def put_batch(self, batch: dict, channel: str = 'train') -> dict:
    """shard_batch + on-device sparse->dense coef unpack where present."""
    device = sharding_lib.shard_batch(batch, self._mesh)
    features = device.get('features')
    if not features or not any(
        key + '/sd' in features for key in self._shapes):
      if features:
        self._record_signature(features, channel)
      return device
    features = dict(features)
    for key, (height, width) in self._shapes.items():
      if key + '/sd' not in features:
        continue
      sd = features.pop(key + '/sd')
      sv = features.pop(key + '/sv')
      features.pop(key + '/n', None)
      y, cb, cr = self._unpack_fn(height, width, sd.shape)(sd, sv)
      features[key + '/y'] = y
      features[key + '/cb'] = cb
      features[key + '/cr'] = cr
    self._record_signature(features, channel)
    device = dict(device)
    device['features'] = features
    return device
