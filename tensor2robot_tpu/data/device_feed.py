"""Host->device batch feeds: the transfer hop, instrumented and sparse-aware.

Two jobs live here:

1. **The transfer stage of the pipeline X-ray** (ISSUE 7,
   observability/pipeline_xray.py). Every batch the trainer ships crosses
   ``put_batch``, so this is the one place the host->device hop is
   metered: ``pipeline/transfer/{examples,bytes,busy_seconds}`` counters,
   a ``pipeline/transfer/ms`` per-batch histogram, and — via
   :class:`PipelinedFeed` (N-deep; ``DoubleBufferedFeed`` is its depth-2
   name) — the ``pipeline/transfer/buffer_occupancy`` gauge. The
   reliability ``data.stall`` FaultInjector site also lives on this hop:
   an armed stall is indistinguishable from a wedged transfer, which is
   exactly the symptom the X-ray must attribute.

2. **The sparse/packed-coef unpack** (SURVEY hard-part #3). A
   ``DeviceDecodePreprocessor(sparse=True)`` pipeline ships images as
   sparse DCT entry streams (``key/{sd,sv,qt,n}``,
   data/native/record_loader.cc); ``wire_format='packed'`` tightens that
   to the bit-packed wire (``key/{pw,se,dcn}`` + one batch-hoisted
   ``key/qt``, ~1.8x fewer bytes again — docs/performance.md "Transfer
   path"). Either way the stream dims are BUCKETED per batch — the
   format's transfer savings come from slicing buffers to the batch's
   actual entry count. Unpacking them inside the jitted train step would
   recompile the whole model per bucket; instead
   :class:`SparseCoefFeed` converts sparse groups to the fixed-shape
   dense coefficient tensors (``key/{y,cb,cr}``) in a SEPARATE tiny jit
   cached per (batch, bucket) shape, right after the host->device
   transfer:

    host batch (sparse, ~8x fewer bytes) --transfer--> device
      --unpack jit (cumsum + scatter-add, ~15 ms / 64 frames)-->
    dense coef batch --train step (shape-stable, never recompiles)-->

The Trainer routes EVERY batch through a feed's :meth:`put_batch`
(:class:`HostDeviceFeed` when no sparse groups are in play), so the
transfer stage is metered unconditionally.

The shape-stability contract is ASSERTED as telemetry, not just
documented: every emitted batch's shape signature lands in the
``data/feed_shape_signatures`` gauge (must stay 1 — the observability
watchdog's ``recompile`` trigger fires otherwise) and the per-bucket
unpack-jit cache size in ``recompiles/coef_unpack`` (expected to grow
once per bucket, then plateau).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Set, Tuple

from tensor2robot_tpu.data import jpeg_device
from tensor2robot_tpu.observability import get_registry
from tensor2robot_tpu.observability.pipeline_xray import StageMeter
from tensor2robot_tpu.observability.spans import SPAN_BUCKETS_MS
from tensor2robot_tpu.parallel import sharding as sharding_lib
from tensor2robot_tpu.reliability import fault_injection

FEED_SHAPES_GAUGE = 'data/feed_shape_signatures'
UNPACK_COMPILES_GAUGE = 'recompiles/coef_unpack'
TRANSFER_MS_HISTOGRAM = 'pipeline/transfer/ms'
BUFFER_OCCUPANCY_GAUGE = 'pipeline/transfer/buffer_occupancy'


def _batch_examples_and_bytes(batch: dict) -> Tuple[int, int]:
  """(leading dim, total host bytes) of a {'features', 'labels'} batch.

  A leading dim of 1 only wins when NO other leaf disagrees: the packed
  wire ships its batch-hoisted quant table as [1, 3, 64], which must not
  masquerade as the batch size (a true batch of 1 still reports 1).
  """
  examples = 0
  nbytes = 0
  for side in ('features', 'labels'):
    values = batch.get(side)
    if not values:
      continue
    for value in values.values():
      size = getattr(value, 'nbytes', 0)
      nbytes += int(size or 0)
      shape = getattr(value, 'shape', None)
      if shape and (not examples or examples == 1):
        examples = int(shape[0])
  return examples, nbytes


class HostDeviceFeed:
  """The plain host->device hop: shard_batch + transfer-stage telemetry."""

  def __init__(self, mesh):
    self._mesh = mesh
    registry = get_registry()
    self._transfer_meter = StageMeter('transfer', registry)
    self._transfer_ms = registry.histogram(TRANSFER_MS_HISTOGRAM,
                                           bounds=SPAN_BUCKETS_MS)

  def put_batch(self, batch: dict, channel: str = 'train') -> dict:
    """Ships one host batch to the device, metering the hop.

    The hop is timed to COMPLETION (``block_until_ready``), not to
    dispatch: ``device_put`` returns after enqueueing the copy, and on a
    transfer-limited link (BENCH_r05: 24.6 MB/s tunneled) a
    dispatch-only measurement would overestimate transfer capacity by
    orders of magnitude and the X-ray could never attribute the stage
    bench names. Blocking here costs no overlap: this host thread waits
    while the device still runs the PREVIOUS step (and the production
    e2e path calls this from :class:`DoubleBufferedFeed`'s producer
    thread, where the wait is free by construction).

    Only the ``'train'`` channel feeds the ``pipeline/transfer`` stage
    counters — the X-ray's e2e flow meter counts train batches, so an
    in-process eval's batches must not inflate the same window's
    transfer capacity. Every channel still lands in the per-batch
    ``pipeline/transfer/ms`` histogram.

    The ``data.stall`` FaultInjector site fires here (the loader/feed
    path's stall injection, docs/reliability.md): a stalled transfer is
    the symptom the pipeline X-ray must catch as ``pipeline_stall`` and
    attribute to this stage.
    """
    examples, nbytes = _batch_examples_and_bytes(batch)
    t0 = time.perf_counter()
    stall_s = fault_injection.stall_data_seconds()
    if stall_s > 0.0:
      time.sleep(stall_s)
    device = self._transfer(batch)
    elapsed = time.perf_counter() - t0
    self._transfer_ms.record(elapsed * 1e3)
    if channel == 'train':
      self._transfer_meter.add(examples=examples, nbytes=nbytes,
                               busy_s=elapsed)
    return self._finish(device, channel)

  def _transfer(self, batch: dict) -> dict:
    """The timed hop: shard + copy, synchronized. Subclass work that is
    NOT the wire (e.g. the sparse unpack jit, whose per-bucket
    compilation costs seconds) belongs in ``_finish`` — inside this
    window it would collapse the measured MB/s and fire a spurious
    ``transfer_regression``."""
    device = sharding_lib.shard_batch(batch, self._mesh)
    try:
      import jax

      jax.block_until_ready(device)
    except Exception:  # noqa: BLE001 — non-array leaves etc.: keep feeding
      pass
    return device

  def _finish(self, device: dict, channel: str) -> dict:
    """Post-transfer device-side work; identity for the plain feed."""
    return device


class SparseCoefFeed(HostDeviceFeed):
  """Converts host batches with sparse coef groups into device batches."""

  def __init__(self, image_shapes: Dict[str, Tuple[int, int]], mesh):
    super().__init__(mesh)
    self._shapes = dict(image_shapes)
    self._jit_cache = {}
    self._signatures: Dict[str, Set[Tuple]] = {}
    registry = get_registry()
    self._shape_gauge = registry.gauge(FEED_SHAPES_GAUGE)
    self._unpack_gauge = registry.gauge(UNPACK_COMPILES_GAUGE)

  @classmethod
  def from_preprocessor(cls, preprocessor, mesh
                        ) -> Optional['SparseCoefFeed']:
    """A feed for a DeviceDecodePreprocessor-wrapped model, else None."""
    from tensor2robot_tpu.preprocessors.device_decode import (
        DeviceDecodePreprocessor,
    )

    # Unwrap decorators (e.g. the TPU Bfloat16PreprocessorWrapper, which
    # train_eval_model installs OUTSIDE the device-decode wrapper) via
    # their ``preprocessor`` property.
    seen = 0
    while (not isinstance(preprocessor, DeviceDecodePreprocessor)
           and seen < 8):
      nxt = getattr(type(preprocessor), 'preprocessor', None)
      if nxt is None:
        return None
      preprocessor = preprocessor.preprocessor
      seen += 1
    if not isinstance(preprocessor, DeviceDecodePreprocessor):
      return None
    spec = preprocessor.raw_in_feature_specification('train')
    from tensor2robot_tpu.specs import algebra
    flat = algebra.flatten_spec_structure(spec)
    shapes = {key: (flat[key].shape[0], flat[key].shape[1])
              for key in preprocessor.image_keys('train')}
    return cls(shapes, mesh=mesh)

  def _unpack_fn(self, height: int, width: int, shape):
    import jax

    cache_key = (height, width, tuple(shape))
    fn = self._jit_cache.get(cache_key)
    if fn is None:
      # Explicit batch-sharded outputs: the train step is jitted with
      # explicit in_shardings, and on a multi-device mesh an INFERRED
      # unpack output sharding need not match it (jax then errors
      # instead of resharding). No donation: the uint8/int8 inputs can't
      # alias the int16 outputs, so donating only produces "donated
      # buffers were not usable" spam.
      out_sharding = sharding_lib.batch_sharding(self._mesh)
      fn = jax.jit(
          lambda sd, sv: jpeg_device.unpack_sparse_coefficients(
              sd, sv, height, width),
          out_shardings=out_sharding)
      self._jit_cache[cache_key] = fn
    return fn

  def _packed_unpack_fn(self, height: int, width: int, pw_shape, se_shape):
    """The packed-wire unpack jit, cached per (geometry, bucket shapes).

    One program covers the whole packed group: AC/DC/escape streams to
    dense coefficient planes (jpeg_device.unpack_packed_coefficients)
    PLUS the broadcast of the batch-hoisted [1, 3, 64] quant table back
    to the per-example [B, 3, 64] the jitted train step consumes — so
    the step's input signature is IDENTICAL to the 'coef' and
    'coef_sparse' paths (same recompile key, same HLO).
    """
    import jax

    cache_key = ('packed', height, width, tuple(pw_shape), tuple(se_shape))
    fn = self._jit_cache.get(cache_key)
    if fn is None:
      import jax.numpy as jnp

      out_sharding = sharding_lib.batch_sharding(self._mesh)

      def unpack(pw, se, dcn, qt):
        y, cb, cr = jpeg_device.unpack_packed_coefficients(
            pw, se, dcn, height, width)
        if qt.shape[0] != pw.shape[0]:
          qt = jnp.broadcast_to(qt[0], (pw.shape[0],) + tuple(qt.shape[1:]))
        return y, cb, cr, qt

      fn = jax.jit(unpack, out_shardings=out_sharding)
      self._jit_cache[cache_key] = fn
    return fn

  def _record_signature(self, features: dict, channel: str) -> None:
    """Counts distinct emitted batch-shape signatures into the gauges.

    The signature covers NAME and SHAPE of every feature the jitted step
    will see — exactly the recompile key. Signatures are tracked per
    ``channel`` because one feed serves several independently-jitted
    programs (train step, eval step, summary pass), each shape-stable on
    its own: an eval batch sized differently from train is legitimate
    and must not trip the train invariant. The exported gauge covers
    only the ``'train'`` channel — the contract the watchdog asserts.
    """
    signature = tuple(sorted(
        (key, tuple(getattr(value, 'shape', ()))
         ) for key, value in features.items()))
    self._signatures.setdefault(channel, set()).add(signature)
    self._shape_gauge.set(float(len(self._signatures.get('train', ()))))
    self._unpack_gauge.set(float(len(self._jit_cache)))

  def _transfer(self, batch: dict) -> dict:
    """The timed hop, hoisted-table aware: the packed wire ships ONE
    [1, 3, 64] quant table per batch, which must ride the wire
    REPLICATED — shard_batch would try to split its leading dim of 1
    over the mesh's data axis. Still inside the timed window: the table
    is wire bytes like everything else (all 384 of them)."""
    features = batch.get('features')
    hoisted = {}
    if features and any(key + '/pw' in features for key in self._shapes):
      features = dict(features)
      for key in self._shapes:
        qt = features.get(key + '/qt')
        shape = getattr(qt, 'shape', None)
        if (key + '/pw' in features and shape and shape[0] == 1):
          hoisted[key + '/qt'] = features.pop(key + '/qt')
      batch = dict(batch)
      batch['features'] = features
    device = super()._transfer(batch)
    if hoisted:
      import jax

      replicated = sharding_lib.replicated(self._mesh)
      if jax.process_count() == 1:
        put = jax.device_put(hoisted, replicated)
      else:
        import numpy as np
        put = {key: jax.make_array_from_process_local_data(
            replicated, np.asarray(value))
               for key, value in hoisted.items()}
      jax.block_until_ready(put)
      features = dict(device['features'])
      features.update(put)
      device = dict(device)
      device['features'] = features
    return device

  def _finish(self, device: dict, channel: str) -> dict:
    """On-device sparse/packed->dense coef unpack where present (untimed:
    the unpack is device compute riding AFTER the metered wire hop)."""
    features = device.get('features')
    if not features or not any(
        key + '/sd' in features or key + '/pw' in features
        for key in self._shapes):
      if features:
        self._record_signature(features, channel)
      return device
    features = dict(features)
    for key, (height, width) in self._shapes.items():
      if key + '/sd' in features:
        sd = features.pop(key + '/sd')
        sv = features.pop(key + '/sv')
        features.pop(key + '/n', None)
        y, cb, cr = self._unpack_fn(height, width, sd.shape)(sd, sv)
      elif key + '/pw' in features:
        pw = features.pop(key + '/pw')
        se = features.pop(key + '/se')
        dcn = features.pop(key + '/dcn')
        qt = features[key + '/qt']
        y, cb, cr, qt = self._packed_unpack_fn(
            height, width, pw.shape, se.shape)(pw, se, dcn, qt)
        features[key + '/qt'] = qt
      else:
        continue
      features[key + '/y'] = y
      features[key + '/cb'] = cb
      features[key + '/cr'] = cr
    self._record_signature(features, channel)
    device = dict(device)
    device['features'] = features
    return device


class PipelinedFeed:
  """N-deep background host->device producer: transfer overlaps compute.

  Wraps a host-batch iterator and a feed: a daemon producer thread
  decodes and ships batches k+1..k+depth while the device runs step k.
  Depth 2 is the classic double buffer; deeper pipelines (the e2e bench
  runs 4) keep the host->device link busy CONTINUOUSLY — with a shallow
  buffer, any decode hiccup drains it and the link then idles while the
  device computes, so the achieved MB/s sits below the link's capacity.

  Design invariants:

    * ONE producer thread, copies serialized and timed to completion
      inside ``put_batch`` — the X-ray's transfer stage meters the hop
      in this thread, so its busy-time MB/s stays an honest link
      estimate (concurrent producers would overlap their busy windows
      and inflate it).
    * Strict FIFO: batches are delivered in the exact order the wrapped
      iterator produced them, each handed off only after its device
      transfer (and any in-feed finishing, e.g. the sparse/packed coef
      unpack dispatch) completed — a consumer can never observe a torn
      or reordered batch, at any depth, even mid-``data.stall``.
    * Device buffers are RELEASED on hand-off: the feed holds at most
      ``depth`` transferred batches plus the one in flight, so HBM cost
      is bounded at ``(depth + 1) x batch bytes`` and the freed buffers
      recycle through the allocator for the next copies. (The unpack
      jits deliberately do NOT donate their stream inputs — mismatched
      dtypes/shapes make XLA refuse the aliasing with per-call spam.)

  The ``pipeline/transfer/buffer_occupancy`` gauge holds the
  buffered-batch fraction at the last hand-off: pinned near 0 means the
  consumer (device) outruns the host path — the pipeline gates; near 1
  means the host comfortably leads.

  Errors from the producer (including the wrapped iterator's
  StopIteration) surface on the consumer side at ``get()``;
  ``close()`` stops the thread without draining it.
  """

  def __init__(self, batch_iterator, feed,
               depth: int = 2, channel: str = 'train'):
    """``feed``: a :class:`HostDeviceFeed` (or anything with its
    ``put_batch(batch, channel=...)``), or a bare callable with the same
    signature (e.g. ``Trainer._put_batch``). ``depth``: how many
    transferred batches may wait ahead of the consumer."""
    put_batch = feed.put_batch if hasattr(feed, 'put_batch') else feed
    self._depth = max(1, int(depth))
    self._buffer = []
    self._lock = threading.Condition()
    self._stopped = False
    self._done = False
    self._errors = []
    self._occupancy = get_registry().gauge(BUFFER_OCCUPANCY_GAUGE)

    def _producer():
      try:
        for batch in batch_iterator:
          device_batch = put_batch(batch, channel=channel)
          with self._lock:
            while len(self._buffer) >= self._depth and not self._stopped:
              self._lock.wait(0.05)
            if self._stopped:
              return
            self._buffer.append(device_batch)
            self._occupancy.set(len(self._buffer) / self._depth)
            self._lock.notify_all()
      except BaseException as e:  # surfaced on the consumer side
        with self._lock:
          self._errors.append(e)
          self._lock.notify_all()
      finally:
        with self._lock:
          self._done = True
          self._lock.notify_all()

    self._thread = threading.Thread(target=_producer, daemon=True,
                                    name='t2r-device-feed')
    self._thread.start()

  def get(self):
    """The next device batch; raises StopIteration at end of data."""
    with self._lock:
      while True:
        if self._buffer:
          batch = self._buffer.pop(0)
          self._occupancy.set(len(self._buffer) / self._depth)
          self._lock.notify_all()
          return batch
        if self._errors:
          raise self._errors[0]
        if self._done:
          raise StopIteration
        self._lock.wait(0.05)

  def __iter__(self):
    return self

  def __next__(self):
    return self.get()

  def close(self, timeout: float = 60.0) -> bool:
    """Stops the producer; returns whether its thread exited in time."""
    with self._lock:
      self._stopped = True
      self._buffer.clear()
      self._occupancy.set(0.0)
      self._lock.notify_all()
    self._thread.join(timeout=timeout)
    return not self._thread.is_alive()


class DoubleBufferedFeed(PipelinedFeed):
  """The depth-2 :class:`PipelinedFeed` under its original name."""
