"""Host-side record pipeline: glob → interleave → shuffle → batch → prefetch.

Parity target: /root/reference/utils/tfdata.py:97-219,527-606
(default_input_fn_tmpl). A deliberately simple, dependency-free pipeline:
records stream from TFRecord shards with round-robin interleave, a bounded
shuffle buffer, per-dataset zip, spec-driven parse, and a background-thread
prefetch queue that overlaps host decode with device steps. Multi-host
sharding slices the file list per process (the JAX analog of the reference's
per-host input_fn invocation, utils/tfdata.py:43-66).
"""

from __future__ import annotations

import glob as glob_lib
import itertools
import queue
import random
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from tensor2robot_tpu.data import tfrecord
from tensor2robot_tpu.data.parser import ExampleParser
from tensor2robot_tpu.observability import get_registry, span

_SUPPORTED_FORMATS = ('tfrecord',)


def parse_file_patterns(file_patterns: Union[str, Sequence[str]]):
  """Resolves 'tfrecord:/path/a*,-/path/b*' style patterns to (format, files).

  ref: utils/tfdata.py:97-119 — patterns may carry a '<format>:' prefix and
  be comma-separated.
  """
  if isinstance(file_patterns, str):
    patterns = [p for p in file_patterns.split(',') if p]
  else:
    patterns = list(file_patterns)
  data_format = 'tfrecord'
  filenames: List[str] = []
  for pattern in patterns:
    if ':' in pattern and pattern.split(':', 1)[0] in _SUPPORTED_FORMATS:
      data_format, pattern = pattern.split(':', 1)
    matched = sorted(glob_lib.glob(pattern))
    if not matched and glob_lib.has_magic(pattern):
      raise ValueError('No files match pattern {!r}.'.format(pattern))
    filenames.extend(matched if matched else [pattern])
  if not filenames:
    raise ValueError('Empty file pattern {!r}.'.format(file_patterns))
  return data_format, filenames


def _interleaved_records(filenames: List[str], cycle_length: int = 4,
                         shuffle_files: bool = False,
                         seed: Optional[int] = None,
                         skip_corrupt: bool = False,
                         quarantine=None) -> Iterator[bytes]:
  """Round-robin interleave of records across shards (ref :548-558)."""
  files = list(filenames)
  if shuffle_files:
    random.Random(seed).shuffle(files)

  def _reader(path):
    # CRC verification is cheap (C impl) and turns silent shard corruption
    # into a clear 'Corrupt TFRecord' error instead of misframed garbage;
    # skip_corrupt downgrades that error to a budgeted quarantine skip.
    return tfrecord.tfrecord_iterator(path, verify_crc=True,
                                      skip_corrupt=skip_corrupt,
                                      quarantine=quarantine)

  active = []
  pending = iter(files)
  for _ in range(cycle_length):
    path = next(pending, None)
    if path is not None:
      active.append(_reader(path))
  while active:
    done = []
    for it in active:
      record = next(it, None)
      if record is None:
        done.append(it)
      else:
        yield record
    for it in done:
      active.remove(it)
      path = next(pending, None)
      if path is not None:
        active.append(_reader(path))


def _shuffled(records: Iterator[bytes], buffer_size: int,
              seed: Optional[int]) -> Iterator[bytes]:
  """Bounded reservoir shuffle (ref shuffle(500), :560)."""
  rng = random.Random(seed)
  buf: List[bytes] = []
  for record in records:
    buf.append(record)
    if len(buf) >= buffer_size:
      idx = rng.randrange(len(buf))
      buf[idx], buf[-1] = buf[-1], buf[idx]
      yield buf.pop()
  rng.shuffle(buf)
  yield from buf


class RecordDataset:
  """One logical dataset: a set of TFRecord shards."""

  def __init__(self, file_patterns: Union[str, Sequence[str]],
               dataset_key: str = '',
               shard_index: int = 0, num_shards: int = 1,
               skip_corrupt_records: bool = False,
               quarantine=None):
    """``skip_corrupt_records``/``quarantine``: budgeted corrupt-record
    tolerance (reliability.RecordQuarantine); off = corruption raises."""
    self.data_format, filenames = parse_file_patterns(file_patterns)
    # Multi-host: each process reads its slice of the shard list.
    self.filenames = filenames[shard_index::num_shards]
    if not self.filenames:
      raise ValueError(
          'Host {} of {} has no files: only {} shard file(s) matched. '
          'Provide at least num_shards files for multi-host reads.'.format(
              shard_index, num_shards, len(filenames)))
    self.dataset_key = dataset_key
    self.skip_corrupt_records = skip_corrupt_records
    if skip_corrupt_records and quarantine is None:
      from tensor2robot_tpu.reliability.quarantine import RecordQuarantine
      quarantine = RecordQuarantine()
    self.quarantine = quarantine

  def iter_records(self, shuffle: bool = False, shuffle_buffer: int = 500,
                   num_epochs: Optional[int] = None,
                   seed: Optional[int] = None) -> Iterator[bytes]:
    epoch = 0
    while num_epochs is None or epoch < num_epochs:
      records = _interleaved_records(
          self.filenames, shuffle_files=shuffle,
          seed=None if seed is None else seed + epoch,
          skip_corrupt=self.skip_corrupt_records,
          quarantine=self.quarantine)
      if shuffle:
        records = _shuffled(records, shuffle_buffer,
                            None if seed is None else seed + epoch)
      yield from records
      epoch += 1


class BatchedExampleStream:
  """Zips datasets, parses with specs, batches, and prefetches on a thread."""

  def __init__(self,
               datasets: Union[RecordDataset, Dict[str, RecordDataset]],
               parser: ExampleParser,
               batch_size: int,
               shuffle: bool = False,
               shuffle_buffer: int = 500,
               num_epochs: Optional[int] = None,
               seed: Optional[int] = None,
               drop_remainder: bool = True,
               prefetch: int = 2):
    if isinstance(datasets, RecordDataset):
      datasets = {datasets.dataset_key: datasets}
    self._datasets = datasets
    self._parser = parser
    self._batch_size = int(batch_size)
    self._shuffle = shuffle
    self._shuffle_buffer = shuffle_buffer
    self._num_epochs = num_epochs
    self._seed = seed
    self._drop_remainder = drop_remainder
    self._prefetch = prefetch

  def _record_tuples(self) -> Iterator[Dict[str, bytes]]:
    iters = {
        key: ds.iter_records(self._shuffle, self._shuffle_buffer,
                             self._num_epochs, self._seed)
        for key, ds in self._datasets.items()
    }
    while True:
      tup = {}
      for key, it in iters.items():
        record = next(it, None)
        if record is None:
          return  # zip ends with the shortest dataset
        tup[key] = record
      yield tup

  def _batches(self):
    import time

    from tensor2robot_tpu.observability.pipeline_xray import StageMeter

    # Pipeline X-ray stages for the pure-Python path (the analog of the
    # C++ loader's t2r_loader_stats export): 'read' is record I/O +
    # interleave + shuffle, 'decode' is the spec-driven parse (which for
    # this single-threaded parser includes batch assembly — np.stack
    # inside parse_batch). Flushed once per batch, never per record.
    read_meter = StageMeter('read')
    decode_meter = StageMeter('decode')
    pending: List[Dict[str, bytes]] = []
    pending_bytes = 0
    read_s = 0.0
    tuples = self._record_tuples()
    while True:
      t0 = time.perf_counter()
      tup = next(tuples, None)
      read_s += time.perf_counter() - t0
      if tup is None:
        break
      pending.append(tup)
      pending_bytes += sum(len(record) for record in tup.values())
      if len(pending) == self._batch_size:
        read_meter.add(examples=len(pending), nbytes=pending_bytes,
                       busy_s=read_s)
        with span('data.parse') as sp:
          batch = self._parse(pending)
        decode_meter.add(examples=len(pending), nbytes=pending_bytes,
                         busy_s=sp.elapsed)
        yield batch
        pending = []
        pending_bytes = 0
        read_s = 0.0
    if pending and not self._drop_remainder:
      read_meter.add(examples=len(pending), nbytes=pending_bytes,
                     busy_s=read_s)
      with span('data.parse') as sp:
        batch = self._parse(pending)
      decode_meter.add(examples=len(pending), nbytes=pending_bytes,
                       busy_s=sp.elapsed)
      yield batch

  def _parse(self, tuples: List[Dict[str, bytes]]):
    by_key = {key: [t[key] for t in tuples] for key in tuples[0]}
    if list(by_key.keys()) == ['']:
      return self._parser.parse_batch(by_key[''])
    return self._parser.parse_batch(by_key)

  def __iter__(self):
    """Yields (features, labels) batches, decoded ahead on a worker thread."""
    if self._prefetch <= 0:
      yield from self._batches()
      return
    q: queue.Queue = queue.Queue(maxsize=self._prefetch)
    sentinel = object()
    error: List[BaseException] = []
    stop = threading.Event()
    # Resolve instruments once — the per-batch path then only bumps them.
    # Labeled 'pipeline' to keep this stream's internal queue distinct
    # from the generators' per-mode prefetch_iterator queues.
    registry = get_registry()
    decoded = registry.counter('data/batches_decoded')
    depth = registry.gauge_family(
        'data/prefetch_queue_depth', ('queue',)).series('pipeline')

    def _worker():
      try:
        for batch in self._batches():
          decoded.inc()
          # Bounded put so an abandoned consumer lets the worker exit
          # instead of pinning the thread and open file handles forever.
          while not stop.is_set():
            try:
              q.put(batch, timeout=0.1)
              # Queue depth ~0 under a fast consumer means the host
              # decode is the bottleneck (the goodput 'data' fraction
              # names the cost; this gauge names the culprit).
              depth.set(q.qsize())
              break
            except queue.Full:
              continue
          if stop.is_set():
            return
      except BaseException as e:  # surfaced on the consumer side
        error.append(e)
      finally:
        while not stop.is_set():
          try:
            q.put(sentinel, timeout=0.1)
            break
          except queue.Full:
            continue
        # Stale nonzero depth from a drained stream reads as a healthy
        # full queue — zero it when this worker exits.
        depth.set(0)

    thread = threading.Thread(target=_worker, daemon=True)
    thread.start()
    try:
      while True:
        item = q.get()
        if item is sentinel:
          if error:
            raise error[0]
          return
        yield item
    finally:
      stop.set()
