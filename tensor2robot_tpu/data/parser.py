"""Spec-driven parsing of serialized Examples into numpy batches.

Parity target: /root/reference/utils/tfdata.py:194-524
(create_parse_tf_example_fn / serialized_to_parsed). Given feature/label spec
structures, an :class:`ExampleParser` decodes serialized tf.Example or
tf.SequenceExample records into spec-conforming numpy, handling:

  * features keyed by ``spec.name`` (specs without a name are not parsed);
  * bfloat16-declared specs parsed as float32 then cast (ref :367-372);
  * JPEG/PNG decode, with empty-string -> zeros fallback (ref :444-455);
  * fixed lists of images (rank-4 specs) and varlen image lists;
  * varlen specs padded (with ``varlen_default_value``) or clipped (ref :467);
  * sequence specs from the SequenceExample feature_lists side, padded across
    the batch with auto ``<name>_length`` tensors (ref :350-364);
  * multi-dataset zip: a dict of serialized records keyed by ``dataset_key``;
  * final validate_and_pack against the specs (ref :508-520).

Decoding runs on host CPU; the arrays then flow to device untouched (bf16
casts excepted, which are fused into the first device op by XLA).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

_POOL = None
_POOL_LOCK = threading.Lock()


def _decode_pool() -> ThreadPoolExecutor:
  """Shared decode pool, sized to the host's cores (lazy, fork-safe-ish)."""
  global _POOL
  with _POOL_LOCK:
    if _POOL is None:
      _POOL = ThreadPoolExecutor(
          max_workers=min(16, (os.cpu_count() or 4)),
          thread_name_prefix='t2r-decode')
    return _POOL

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data import wire
from tensor2robot_tpu.specs.struct import SpecStruct
from tensor2robot_tpu.specs.tensor_spec import TensorSpec, bfloat16


def decode_image(data: bytes, spec: TensorSpec) -> np.ndarray:
  """Decodes one encoded image; empty bytes -> zeros (reference parity)."""
  channels = spec.shape[-1] if len(spec.shape) >= 3 else 3
  height, width = spec.shape[-3], spec.shape[-2]
  if not data:
    return np.zeros((height or 1, width or 1, channels), dtype=spec.dtype)
  flat = np.frombuffer(data, dtype=np.uint8)
  try:
    import cv2
    flag = cv2.IMREAD_COLOR if channels == 3 else cv2.IMREAD_GRAYSCALE
    if spec.dtype == np.uint16:
      flag |= cv2.IMREAD_ANYDEPTH
    img = cv2.imdecode(flat, flag)
    if img is None:
      raise ValueError('cv2 could not decode image')
    if channels == 3:
      img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    elif img.ndim == 2:
      img = img[..., None]
  except ImportError:  # pragma: no cover
    import io
    from PIL import Image
    pil = Image.open(io.BytesIO(data))
    img = np.asarray(pil)
    if img.ndim == 2:
      img = img[..., None]
  return img.astype(spec.dtype, copy=False)


def _parse_dtype_kind(spec: TensorSpec) -> str:
  """Which Example list kind a spec's values live in on disk."""
  if spec.is_encoded_image or spec.dtype == np.dtype(object):
    return 'bytes'
  if spec.dtype.kind in 'uib':
    return 'int64'
  return 'float'  # float32/float64/bfloat16 all serialize as FloatList f32


class ExampleParser:
  """Parses serialized records according to feature/label specs."""

  def __init__(self, feature_spec, label_spec=None, decode_images: bool = True):
    self._decode_images = decode_images
    self._feature_spec = specs_lib.flatten_spec_structure(feature_spec)
    self._label_spec = specs_lib.flatten_spec_structure(label_spec)
    specs_lib.assert_valid_spec_structure(self._feature_spec)
    specs_lib.assert_valid_spec_structure(self._label_spec)
    merged = SpecStruct()
    for key in self._feature_spec:
      merged['features/' + key] = self._feature_spec[key]
    for key in self._label_spec:
      merged['labels/' + key] = self._label_spec[key]
    # name -> spec for parsing; skip unnamed specs (reference behavior).
    self._by_name: Dict[str, TensorSpec] = {}
    for key in merged:
      spec = merged[key]
      if spec.name is None:
        continue
      self._by_name[spec.name] = spec
    self._dataset_keys = sorted({s.dataset_key for s in self._by_name.values()})
    self._has_sequence = any(s.is_sequence for s in self._by_name.values())

  @property
  def dataset_keys(self):
    return self._dataset_keys

  # -- single example --------------------------------------------------------

  def _decode_value(self, spec: TensorSpec, kind_values, is_step: bool = False):
    """Converts one Feature payload to a numpy array per the spec."""
    kind, values = kind_values
    shape = spec.shape
    if spec.is_encoded_image and not self._decode_images:
      # Pass encoded bytes through untouched (client-side decode).
      if kind != 'bytes':
        raise ValueError('Encoded image {} stored as {}'.format(spec.name, kind))
      if len(values) == 1 and len(shape) <= 3:
        return values[0]
      out = np.empty(len(values), dtype=object)
      out[:] = values
      return out
    if self._decode_images and spec.is_encoded_image:
      if kind != 'bytes':
        raise ValueError('Encoded image {} stored as {}'.format(spec.name, kind))
      if spec.varlen_default_value is not None:
        images = [decode_image(v, spec) for v in values]
        if not images:
          images = [np.zeros(tuple(s or 1 for s in shape[1:]), spec.dtype)]
        arr = np.stack(images)
        return specs_lib.pad_or_clip_tensor_to_spec_shape(arr, spec)
      if len(shape) > 3 and not is_step:
        # Fixed-length list of images.
        images = [decode_image(v, spec) for v in values]
        return np.stack(images)
      return decode_image(values[0], spec)
    if kind == 'bytes':
      if spec.dtype == np.dtype(object):
        out = np.empty(len(values), dtype=object)
        out[:] = values
        if shape == () or shape == (1,):
          return out[0] if shape == () else out
        return out
      raise ValueError(
          'Spec {} has dtype {} but on-disk bytes.'.format(spec.name, spec.dtype))
    arr = np.asarray(values)
    target_dtype = spec.dtype if spec.dtype != bfloat16 else np.float32
    arr = arr.astype(target_dtype, copy=False)
    if spec.varlen_default_value is not None:
      arr = specs_lib.pad_or_clip_tensor_to_spec_shape(arr, spec)
    else:
      wanted = tuple(s for s in shape if s is not None)
      expected = int(np.prod(wanted)) if wanted else 1
      if arr.size != expected and not spec.is_sequence:
        raise ValueError(
            'Feature {!r}: got {} values, spec {} expects {}.'.format(
                spec.name, arr.size, spec, expected))
      arr = arr.reshape(tuple(s or 1 for s in shape))
    if spec.dtype == bfloat16:
      arr = arr.astype(bfloat16)
    return arr

  def parse_single(self, serialized: Union[bytes, Dict[str, bytes]]):
    """Parses one (possibly multi-dataset) record -> flat {name: array}."""
    if not isinstance(serialized, dict):
      serialized = {key: serialized for key in self._dataset_keys}
    out: Dict[str, np.ndarray] = {}
    for dataset_key, record in serialized.items():
      names = [n for n, s in self._by_name.items()
               if s.dataset_key == dataset_key]
      if not names:
        continue
      if self._has_sequence:
        context, feature_lists = wire.parse_sequence_example(record)
      else:
        context, feature_lists = wire.parse_example(record), {}
      for name in names:
        spec = self._by_name[name]
        if spec.is_sequence:
          if name not in feature_lists:
            if spec.is_optional:
              continue
            raise ValueError(
                'Required sequence feature {!r} missing from record; '
                'available: {}.'.format(name, sorted(feature_lists)))
          steps = [self._decode_value(spec, step, is_step=True)
                   for step in feature_lists[name]]
          if steps and isinstance(steps[0], bytes):
            # Raw encoded frames: keep dtype=object (np.stack would coerce
            # to fixed-width 'S', NUL-padding/stripping the payloads).
            arr = np.empty(len(steps), dtype=object)
            arr[:] = steps
          elif steps:
            arr = np.stack(steps)
          else:
            arr = np.zeros((0,) + tuple(s or 1 for s in spec.shape),
                           spec.dtype)
          out[name] = arr
          out[name + '_length'] = np.asarray(len(steps), dtype=np.int64)
        else:
          if name not in context:
            if spec.is_optional:
              continue
            raise ValueError(
                'Required feature {!r} missing from record; available: {}.'
                .format(name, sorted(context)))
          out[name] = self._decode_value(spec, context[name])
    return out

  # -- batches ---------------------------------------------------------------

  def parse_batch(self, serialized_batch,
                  validate: bool = True):
    """Parses a list of records -> (features, labels) batched SpecStructs.

    ``serialized_batch``: list of bytes, or dict dataset_key -> list of bytes.
    Sequence tensors are padded to the longest sequence in the batch.
    """
    if isinstance(serialized_batch, dict):
      keys = list(serialized_batch)
      n = len(serialized_batch[keys[0]])
      records = [{k: serialized_batch[k][i] for k in keys} for i in range(n)]
    else:
      records = list(serialized_batch)
    # JPEG decode dominates the host path (SURVEY §7 hard-part #3) and cv2
    # releases the GIL, so per-record parsing fans out over a thread pool
    # (the reference's tf.data num_parallel_calls, utils/tfdata.py:215-219).
    if len(records) > 1 and self._decode_images and any(
        s.is_encoded_image for s in self._by_name.values()):
      parsed = list(_decode_pool().map(self.parse_single, records))
    else:
      parsed = [self.parse_single(r) for r in records]
    names = set()
    for p in parsed:
      names.update(p)
    batched: Dict[str, np.ndarray] = {}
    for name in names:
      rows = [p[name] for p in parsed if name in p]
      if len(rows) != len(parsed):
        # Optional feature present in only part of the batch: dropped for the
        # whole batch (a batch is dense). Note: with shuffling this makes the
        # feature's availability vary batch-to-batch on datasets with partial
        # coverage; declare such features non-optional (with defaults written
        # at collection time) if models depend on them.
        continue
      if isinstance(rows[0], bytes):
        # Bare bytes rows: np.stack would coerce to fixed-width 'S' dtype,
        # silently stripping trailing NULs; keep dtype=object instead.
        arr = np.empty(len(rows), dtype=object)
        arr[:] = rows
        batched[name] = arr
        continue
      spec = self._by_name.get(name)
      if spec is not None and spec.is_sequence:
        max_len = max(r.shape[0] for r in rows)
        pad_value = spec.varlen_default_value or 0
        padded = []
        for r in rows:
          if r.shape[0] < max_len:
            pad = np.full((max_len - r.shape[0],) + r.shape[1:], pad_value,
                          dtype=r.dtype)
            r = np.concatenate([r, pad], axis=0)
          padded.append(r)
        rows = padded
      batched[name] = np.stack(rows)
    features = self._pack_side(self._feature_spec, batched)
    labels = self._pack_side(self._label_spec, batched)
    if validate:
      features = self._validate_side(self._feature_spec, features)
      if len(self._label_spec):
        labels = self._validate_side(self._label_spec, labels)
    return features, labels

  def _validate_side(self, side_spec, tensors) -> SpecStruct:
    spec = specs_lib.add_sequence_length_specs(side_spec)
    if not self._decode_images:
      # Raw encoded bytes intentionally mismatch image specs; validate the
      # rest and carry the image tensors through unvalidated.
      checked = SpecStruct()
      passthrough = SpecStruct()
      flat = specs_lib.flatten_spec_structure(spec)
      for key in flat:
        if flat[key].is_encoded_image:
          if key in tensors:
            passthrough[key] = tensors[key]
          elif not flat[key].is_optional:
            raise ValueError(
                'Required encoded-image tensor {!r} missing; available: {}.'
                .format(key, sorted(tensors.keys())))
        else:
          checked[key] = flat[key]
      out = specs_lib.validate_and_pack(checked, tensors, ignore_batch=True)
      for key in passthrough:
        out[key] = passthrough[key]
      return out
    return specs_lib.validate_and_pack(spec, tensors, ignore_batch=True)

  def _pack_side(self, side_spec, batched_by_name) -> SpecStruct:
    out = SpecStruct()
    for key in side_spec:
      spec = side_spec[key]
      if spec.name is None or spec.name not in batched_by_name:
        continue
      out[key] = batched_by_name[spec.name]
      if spec.is_sequence and spec.name + '_length' in batched_by_name:
        out[key + '_length'] = batched_by_name[spec.name + '_length']
    return out


def build_example_for_specs(spec_structure, numpy_struct) -> bytes:
  """Inverse of parsing: serializes spec-conforming numpy into a tf.Example.

  Used by replay writers and test fixtures. Encoded-image specs expect raw
  ``bytes`` values. Sequence specs produce a SequenceExample.
  """
  flat_spec = specs_lib.flatten_spec_structure(spec_structure)
  flat_np = specs_lib.flatten_spec_structure(numpy_struct)
  context: Dict[str, object] = {}
  feature_lists: Dict[str, List[object]] = {}
  has_sequence = False
  for key in flat_spec:
    spec = flat_spec[key]
    if spec.name is None or key not in flat_np:
      continue
    value = flat_np[key]
    if spec.is_sequence:
      has_sequence = True
      steps = np.asarray(value)
      if steps.dtype == bfloat16:
        steps = steps.astype(np.float32)
      feature_lists[spec.name] = [np.asarray(step).ravel() for step in steps]
    elif spec.is_encoded_image or spec.dtype == np.dtype(object):
      if isinstance(value, (bytes, str)):
        value = [value]
      else:
        value = [bytes(v) if not isinstance(v, (bytes, str)) else v
                 for v in np.asarray(value, dtype=object).ravel()]
      context[spec.name] = value
    else:
      arr = np.asarray(value)
      if arr.dtype == bfloat16:
        arr = arr.astype(np.float32)
      context[spec.name] = arr.ravel()
  if has_sequence:
    return wire.build_sequence_example(context, feature_lists)
  return wire.build_example(context)
