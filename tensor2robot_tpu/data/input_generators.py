"""Input generators: bind model specs to data sources, yield numpy batches.

Parity targets:
  * AbstractInputGenerator     ref input_generators/abstract_input_generator.py:38
  * DefaultRecordInputGenerator / FractionalRecordInputGenerator /
    MultiEvalRecordInputGenerator  ref input_generators/default_input_generator.py:54,118,141
  * GeneratorInputGenerator / DefaultRandomInputGenerator /
    DefaultConstantInputGenerator  ref default_input_generator.py:156,210,223

Redesign note: the reference returns Estimator ``input_fn``s; here a generator
yields ``(features, labels)`` numpy batches sized for the *global* batch. The
trainer shards each batch over the mesh data axis and runs the preprocessor
inside the jitted train step (device-side, XLA-fused) — so generators stay
pure host-side decode.
"""

from __future__ import annotations

import abc
import json
import os
from typing import Callable, Dict, Iterator, Optional, Sequence, Union

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data.parser import ExampleParser
from tensor2robot_tpu.data.pipeline import (
    BatchedExampleStream,
    RecordDataset,
    parse_file_patterns,
)
from tensor2robot_tpu.modes import ModeKeys, assert_valid_mode


def prefetch_iterator(iterator: Iterator, depth: int,
                      label: str = 'default') -> Iterator:
  """Wraps an iterator with a ``depth``-deep background prefetch queue.

  Producer uses timed puts against a stop event (same discipline as
  BatchedExampleStream, data/pipeline.py): when the consumer abandons or
  closes the generator, the worker thread exits instead of blocking in
  q.put forever holding decoded batches and open readers.

  ``label`` names this queue's telemetry series (the generators pass the
  mode), so a train and an eval queue in one process report separately.
  """
  import queue
  import threading

  from tensor2robot_tpu.observability import get_registry
  from tensor2robot_tpu.observability.pipeline_xray import StageMeter

  q: 'queue.Queue' = queue.Queue(maxsize=depth)
  sentinel = object()
  error: list = []
  stop = threading.Event()
  # Resolved once per iterator; the per-batch path only bumps them. The
  # gauge reads near zero when the trainer outruns the pipeline (data-
  # starved — matches a high goodput 'data' fraction) and near ``depth``
  # when decode comfortably leads the device.
  registry = get_registry()
  prefetched = registry.counter_family(
      'data/batches_prefetched', ('queue',)).series(label)
  queue_depth = registry.gauge_family(
      'data/prefetch_queue_depth', ('queue',)).series(label)
  # Pipeline X-ray 'batch' stage: this producer is the ONE batch-handoff
  # point every generator path (native, Python parser, synthetic) runs
  # through, so it owns the stage's example count — the flow meter.
  # No busy time is charged here: the handoff is a queue put whose only
  # real cost is downstream backpressure (queue-full waits), which must
  # NOT be attributed to this stage; the stage's health signals are the
  # flow count and the prefetch-depth gauge, and it never competes in
  # the capacity argmin (native pack cost is pipeline/batch/pack_ms).
  batch_meter = StageMeter('batch')

  def _put(item) -> bool:
    while not stop.is_set():
      try:
        q.put(item, timeout=0.1)
        queue_depth.set(q.qsize())
        return True
      except queue.Full:
        continue
    return False

  def _batch_examples(item) -> int:
    """Leading dim of a (features, labels) item's array leaves.

    A leading dim of 1 only wins when every leaf agrees: the packed
    coef wire ships its batch-hoisted quant table as [1, 3, 64], which
    must not masquerade as the batch size.
    """
    features = item[0] if isinstance(item, tuple) else item
    examples = 0
    try:
      for key in features:
        shape = getattr(features[key], 'shape', None)
        if shape and (not examples or examples == 1):
          examples = int(shape[0])
          if examples > 1:
            break
    except TypeError:
      pass
    return examples

  def _producer():
    try:
      for item in iterator:
        prefetched.inc()
        batch_meter.add(examples=_batch_examples(item))
        if not _put(item):
          return
    except BaseException as e:  # surfaced on the consumer side
      error.append(e)
    finally:
      _put(sentinel)
      # A finished/abandoned queue must not advertise its last depth
      # forever: stale nonzero depth reads as a healthy full pipeline.
      queue_depth.set(0)

  thread = threading.Thread(target=_producer, daemon=True,
                            name='t2r-prefetch')
  thread.start()

  def _consume():
    try:
      while True:
        item = q.get()
        if item is sentinel:
          if error:
            raise error[0]
          return
        yield item
    finally:
      stop.set()

  return _consume()


class AbstractInputGenerator(abc.ABC):
  """Binds a model's (preprocessor's) in-specs to a batch source."""

  def __init__(self, batch_size: int = 32, prefetch: int = 2):
    self._batch_size = int(batch_size)
    self._prefetch = int(prefetch)
    self._feature_spec = None
    self._label_spec = None
    self._raw_feature_spec = None  # device-decode: on-disk JPEG specs
    self._device_decode_preprocessor = None
    self._preprocess_fn = None

  @property
  def batch_size(self) -> int:
    return self._batch_size

  @batch_size.setter
  def batch_size(self, value: int) -> None:
    self._batch_size = int(value)

  def set_specification_from_model(self, model, mode: str) -> None:
    """Pulls the in-feature/in-label specs from the model's preprocessor.

    ref: abstract_input_generator.py:80 — the input pipeline produces what the
    preprocessor consumes, not what the model consumes.

    A DeviceDecodePreprocessor wrapper is recognized: the generator then
    plans the native loader in COEF mode against the raw (on-disk JPEG)
    specs and ships DCT coefficient tensors the wrapper finishes decoding
    on device.
    """
    assert_valid_mode(mode)
    preprocessor = model.preprocessor
    self._feature_spec = preprocessor.get_in_feature_specification(mode)
    self._label_spec = preprocessor.get_in_label_specification(mode)
    specs_lib.assert_valid_spec_structure(self._feature_spec)
    specs_lib.assert_valid_spec_structure(self._label_spec)
    self._raw_feature_spec = None
    self._device_decode_preprocessor = None
    if hasattr(preprocessor, 'raw_in_feature_specification'):
      self._raw_feature_spec = preprocessor.raw_in_feature_specification(
          mode)
      self._device_decode_preprocessor = preprocessor

  def set_specification(self, feature_spec, label_spec) -> None:
    self._feature_spec = specs_lib.flatten_spec_structure(feature_spec)
    self._label_spec = specs_lib.flatten_spec_structure(label_spec)
    # Plain specs: clear any device-decode plan a previous
    # set_specification_from_model(wrapped_model) installed.
    self._raw_feature_spec = None
    self._device_decode_preprocessor = None

  @property
  def feature_spec(self):
    return self._feature_spec

  @property
  def label_spec(self):
    return self._label_spec

  def create_dataset_iterator(
      self, mode: str,
      num_epochs: Optional[int] = None,
      shard_index: int = 0, num_shards: int = 1,
      seed: Optional[int] = None,
      prefetch: Optional[int] = None) -> Iterator:
    """Yields (features, labels) numpy batch SpecStructs.

    ``prefetch``: batches decoded ahead in a background thread so host
    parsing overlaps the device step (the reference's
    prefetch(AUTOTUNE), utils/tfdata.py:575). None uses the generator's
    default; 0 disables.
    """
    assert_valid_mode(mode)
    if self._feature_spec is None:
      raise ValueError(
          'set_specification(_from_model) must be called before creating '
          'a dataset iterator.')
    iterator = self._create_iterator(mode=mode, num_epochs=num_epochs,
                                     shard_index=shard_index,
                                     num_shards=num_shards, seed=seed)
    depth = self._prefetch if prefetch is None else prefetch
    if depth and depth > 0:
      iterator = prefetch_iterator(iterator, depth, label=mode)
    return iterator

  @abc.abstractmethod
  def _create_iterator(self, mode: str, num_epochs, shard_index, num_shards,
                       seed) -> Iterator:
    ...


class DefaultRecordInputGenerator(AbstractInputGenerator):
  """TFRecord-backed input generator, optionally joining multiple datasets.

  ``file_patterns``: 'path/a*' or 'tfrecord:path/a*,path/b*'.
  ``dataset_map``: {dataset_key: file_patterns} for multi-dataset zip driven
  by the specs' ``dataset_key`` attributes.

  When the specs qualify (plain tf.Example, fixed shapes, JPEG images), the
  hot path runs on the native C++ loader (data/native/record_loader.cc):
  multithreaded record read + proto parse + JPEG decode outside the GIL,
  the analog of the reference's C++ tf.data pipeline
  (utils/tfdata.py:527-575). ``use_native=False`` (or T2R_NATIVE_LOADER=0)
  forces the pure-Python pipeline; 'auto' falls back silently when specs
  are unsupported or the toolchain can't build the library.
  """

  def __init__(self, file_patterns: Optional[str] = None,
               dataset_map: Optional[Dict[str, str]] = None,
               batch_size: int = 32,
               shuffle_buffer_size: int = 500,
               prefetch: int = 2,
               use_native: Union[bool, str] = 'auto',
               num_native_threads: Optional[int] = None,
               sequence_max_len: Optional[int] = None,
               skip_corrupt_records: bool = False,
               max_corrupt_records: int = 100,
               max_corrupt_records_per_file: int = 10):
    """``sequence_max_len``: step capacity bound for SequenceExample
    (is_sequence) specs on the native fast path — e.g. the workload's
    episode-length bound. Without it sequence datasets read through the
    Python parser (native_loader.plan_for_specs).

    ``skip_corrupt_records``: quarantine corrupt/truncated records instead
    of raising, up to ``max_corrupt_records`` across the run and
    ``max_corrupt_records_per_file`` in any one file; exhausting either
    budget raises CorruptionBudgetExceeded naming the offending file
    (docs/reliability.md). Counters surface in train metrics. Only the
    Python pipeline can skip, so this disables the native fast path.
    """
    super().__init__(batch_size=batch_size)
    if not file_patterns and not dataset_map:
      raise ValueError('file_patterns or dataset_map is required.')
    if file_patterns and dataset_map:
      raise ValueError('file_patterns and dataset_map are mutually exclusive.')
    if skip_corrupt_records and use_native is True:
      raise ValueError(
          'use_native=True is incompatible with skip_corrupt_records: '
          'only the Python pipeline can quarantine corrupt records.')
    self._file_patterns = file_patterns
    self._dataset_map = dataset_map
    self._shuffle_buffer_size = shuffle_buffer_size
    self._prefetch = prefetch
    self._use_native = use_native
    self._num_native_threads = num_native_threads
    self._sequence_max_len = sequence_max_len
    self._skip_corrupt_records = skip_corrupt_records
    self._quarantine = None
    if skip_corrupt_records:
      from tensor2robot_tpu.reliability.quarantine import RecordQuarantine
      self._quarantine = RecordQuarantine(
          max_corrupt_records=max_corrupt_records,
          max_corrupt_records_per_file=max_corrupt_records_per_file)

  @property
  def quarantine(self):
    """The RecordQuarantine counting this generator's skips (or None)."""
    return self._quarantine

  def _dataset_files(self) -> Dict[str, str]:
    if self._dataset_map is not None:
      return dict(self._dataset_map)
    return {'': self._file_patterns}

  def _native_iterator(self, mode, num_epochs, shard_index, num_shards, seed):
    """Returns a native-loader batch iterator, or None to fall back."""
    from tensor2robot_tpu.data import native_loader

    if self._skip_corrupt_records and self._raw_feature_spec is None:
      # Corrupt-record quarantine only exists in the Python reader; the
      # native loader hard-fails on bad CRCs. (use_native=True was
      # already rejected in __init__; device-decode streams have no
      # Python fallback, so they cannot combine with skip mode either.)
      return None
    if self._raw_feature_spec is not None:
      if self._skip_corrupt_records:
        raise ValueError(
            'skip_corrupt_records is not supported with a '
            'DeviceDecodePreprocessor (native-only stream).')
      # Device-decode wrapper in play: plan against the on-disk JPEG specs
      # in coef mode; the stream's key/{y,cb,cr,qt} outputs match the
      # wrapper's in-specs. No Python fallback exists for coef shipping —
      # every unavailability is a hard error, never a silent fallthrough
      # to a parser that cannot produce coefficient tensors.
      if self._use_native is False or not native_loader.native_loader_enabled():
        raise ValueError(
            'DeviceDecodePreprocessor requires the native loader '
            '(use_native must not be False; T2R_NATIVE_LOADER must not '
            'disable it).')
      if self._dataset_map is not None:
        raise ValueError(
            'DeviceDecodePreprocessor does not support multi-dataset zip.')
      wire_format = getattr(self._device_decode_preprocessor,
                            'wire_format', None)
      if wire_format is None:  # pre-wire_format wrappers: sparse bool
        wire_format = 'sparse' if getattr(
            self._device_decode_preprocessor, 'sparse', False) else 'dense'
      image_mode = {'packed': 'coef_packed', 'sparse': 'coef_sparse',
                    'dense': 'coef'}[wire_format]
      plan = native_loader.plan_for_specs(
          self._raw_feature_spec, self._label_spec,
          image_mode=image_mode,
          sparse_density=float(getattr(self._device_decode_preprocessor,
                                       'sparse_density', 0.5)))
      if plan is None:
        raise ValueError(
            'DeviceDecodePreprocessor requires the native loader fast path '
            '(plain Example, fixed shapes, 4:2:0-eligible JPEG specs).')
      _, files = parse_file_patterns(self._dataset_files()[''])
      files = files[shard_index::num_shards]
      if not files:
        raise ValueError(
            'Host {} of {} has no record files for the device-decode '
            'stream; provide at least num_shards files.'.format(
                shard_index, num_shards))
      import jax

      stream = native_loader.NativeBatchedStream(
          plan, files, batch_size=self._batch_size,
          shuffle=(mode == ModeKeys.TRAIN),
          shuffle_buffer=self._shuffle_buffer_size,
          num_epochs=num_epochs, seed=seed,
          num_threads=self._num_native_threads, validate=False,
          # Per-host buckets diverge across processes; multi-host SPMD
          # needs the host-invariant full-capacity shape.
          bucket_sparse=jax.process_count() == 1)
      return iter(stream)
    if self._use_native is False or not native_loader.native_loader_enabled():
      return None
    plan = native_loader.plan_for_specs(
        self._feature_spec, self._label_spec,
        sequence_max_len=self._sequence_max_len)
    if plan is None:
      if self._use_native is True:
        raise ValueError(
            'use_native=True but the specs are not supported by the native '
            'loader (sequences without sequence_max_len, PNG images, '
            'duplicate or unnamed feature names).')
      return None
    try:
      # Through _dataset_files() so subclass overrides (e.g. Fractional's
      # file_fraction truncation) apply to the native path too. One file
      # list per dataset key: the native loader zips multi-dataset plans
      # itself (record_loader.cc file groups).
      files_by_key = {}
      for key, patterns in self._dataset_files().items():
        _, files = parse_file_patterns(patterns)
        files = files[shard_index::num_shards]
        if not files:
          return None
        files_by_key[key] = files
      if set(plan.dataset_keys) != set(files_by_key):
        # Specs reference dataset keys with no configured files (the
        # Python path raises the clear error), OR the dataset_map names
        # datasets no spec reads — the Python pipeline still ZIPS those
        # (epoch ends at the shortest dataset), so the native path must
        # not silently change epoch length/pairing by ignoring them.
        return None
      stream_files = (files_by_key[''] if plan.dataset_keys == ['']
                      else files_by_key)
      stream = native_loader.NativeBatchedStream(
          plan, stream_files, batch_size=self._batch_size,
          shuffle=(mode == ModeKeys.TRAIN),
          shuffle_buffer=self._shuffle_buffer_size,
          num_epochs=num_epochs, seed=seed,
          num_threads=self._num_native_threads)
    except RuntimeError:
      if self._use_native is True:
        raise
      return None  # toolchain missing etc. — silent fallback
    return iter(stream)

  def _create_iterator(self, mode, num_epochs, shard_index, num_shards, seed):
    native = self._native_iterator(mode, num_epochs, shard_index,
                                   num_shards, seed)
    if native is not None:
      return native
    parser = ExampleParser(self._feature_spec, self._label_spec)
    datasets = {
        key: RecordDataset(patterns, dataset_key=key,
                           shard_index=shard_index, num_shards=num_shards,
                           skip_corrupt_records=self._skip_corrupt_records,
                           quarantine=self._quarantine)
        for key, patterns in self._dataset_files().items()
    }
    missing = set(parser.dataset_keys) - set(datasets)
    if missing:
      raise ValueError(
          'Specs reference dataset keys {} with no configured files; have {}.'
          .format(sorted(missing), sorted(datasets)))
    # prefetch=0: the base class's prefetch_iterator wrapper is the ONE
    # background-decode mechanism (stacking the stream's own worker on top
    # would double the threads and the buffered-batch memory).
    stream = BatchedExampleStream(
        datasets, parser, batch_size=self._batch_size,
        shuffle=(mode == ModeKeys.TRAIN),
        shuffle_buffer=self._shuffle_buffer_size,
        num_epochs=num_epochs, seed=seed, prefetch=0)
    return iter(stream)


class FractionalRecordInputGenerator(DefaultRecordInputGenerator):
  """Uses only a fraction of the matched files (data ablations, ref :118)."""

  def __init__(self, file_fraction: float = 1.0, **kwargs):
    super().__init__(**kwargs)
    if not 0.0 < file_fraction <= 1.0:
      raise ValueError('file_fraction must be in (0, 1].')
    self._file_fraction = file_fraction

  def _dataset_files(self) -> Dict[str, str]:
    out = {}
    for key, patterns in super()._dataset_files().items():
      if self._file_fraction < 1.0:
        _, files = parse_file_patterns(patterns)
        n = max(1, int(self._file_fraction * len(files)))
        patterns = ','.join(files[:n])
      out[key] = patterns
    return out


def get_multi_eval_name(default: Optional[str] = None) -> Optional[str]:
  """Reads the eval-dataset selector from TF_CONFIG (ref :42-50)."""
  tf_config = os.environ.get('TF_CONFIG')
  if not tf_config:
    return default
  try:
    return json.loads(tf_config).get('multi_eval_name', default)
  except (ValueError, AttributeError):
    return default


class MultiEvalRecordInputGenerator(DefaultRecordInputGenerator):
  """Picks the eval dataset named by TF_CONFIG.multi_eval_name (ref :141)."""

  def __init__(self, eval_map: Dict[str, str], **kwargs):
    multi_eval_name = get_multi_eval_name()
    if multi_eval_name is None:
      raise ValueError('TF_CONFIG.multi_eval_name must be set for '
                       'MultiEvalRecordInputGenerator.')
    if multi_eval_name not in eval_map:
      raise ValueError('multi_eval_name {!r} not in eval_map {}.'.format(
          multi_eval_name, sorted(eval_map)))
    self.multi_eval_name = multi_eval_name
    super().__init__(file_patterns=eval_map[multi_eval_name], **kwargs)


class GeneratorInputGenerator(AbstractInputGenerator):
  """Wraps a python generator of spec-conforming numpy batches (ref :156)."""

  def __init__(self, batch_generator_fn: Optional[Callable] = None,
               batch_size: int = 32, sequence_length: Optional[int] = None):
    super().__init__(batch_size=batch_size)
    self._batch_generator_fn = batch_generator_fn
    self._sequence_length = sequence_length

  def _generate_batch(self, seed: Optional[int]):
    if self._batch_generator_fn is None:
      raise NotImplementedError(
          'Provide batch_generator_fn or override _generate_batch.')
    return self._batch_generator_fn(self._batch_size)

  def _create_iterator(self, mode, num_epochs, shard_index, num_shards, seed):
    def _iter():
      step = 0
      while num_epochs is None or step < num_epochs:
        batch = self._generate_batch(None if seed is None else seed + step)
        if isinstance(batch, tuple):
          features, labels = batch
        else:
          features, labels = batch, None
        features = specs_lib.validate_and_pack(
            self._feature_spec, features, ignore_batch=True)
        if labels is not None and len(self._label_spec):
          labels = specs_lib.validate_and_pack(
              self._label_spec, labels, ignore_batch=True)
        yield features, labels
        step += 1
    return _iter()


class DefaultRandomInputGenerator(GeneratorInputGenerator):
  """Spec-conforming random batches — the test-data backbone (ref :210)."""

  def _generate_batch(self, seed: Optional[int]):
    features = specs_lib.make_random_numpy(
        self._feature_spec, batch_size=self._batch_size,
        sequence_length=self._sequence_length or 3, seed=seed)
    labels = specs_lib.make_random_numpy(
        self._label_spec, batch_size=self._batch_size,
        sequence_length=self._sequence_length or 3,
        seed=None if seed is None else seed + 977)
    return features, labels


class DefaultConstantInputGenerator(GeneratorInputGenerator):
  """Spec-conforming constant batches (ref :223)."""

  def __init__(self, constant_value: float, **kwargs):
    super().__init__(**kwargs)
    self._constant_value = constant_value

  def _generate_batch(self, seed: Optional[int]):
    features = specs_lib.make_constant_numpy(
        self._feature_spec, self._constant_value, batch_size=self._batch_size,
        sequence_length=self._sequence_length or 3)
    labels = specs_lib.make_constant_numpy(
        self._label_spec, self._constant_value, batch_size=self._batch_size,
        sequence_length=self._sequence_length or 3)
    return features, labels
