// Native TFRecord -> batched-tensor loader.
//
// The reference feeds its models with a C++ tf.data pipeline
// (/root/reference/utils/tfdata.py:527-575 drives TF's native record reader,
// parallel_interleave and JPEG decode kernels). This is the equivalent native
// runtime component for the TPU framework: a dependency-light C++ loader that
// reads TFRecord shards, parses tf.Example protos straight off the wire
// format, decodes JPEG frames with libjpeg(-turbo), and assembles batches
// into a ring of preallocated buffers — all on a worker thread pool that
// scales with host cores, entirely outside the Python GIL.
//
// Architecture:
//   reader thread:  epoch loop -> framed record read -> bounded shuffle
//                   buffer -> (slot, row) work items
//   N worker threads: proto wire walk -> field extract / JPEG decode ->
//                   write into slot row (no locks on the hot path; each row
//                   is owned by exactly one worker)
//   consumer (Python via ctypes): t2r_loader_next() blocks for a READY slot,
//                   wraps the slot buffers as numpy arrays (zero copy),
//                   t2r_loader_release() returns the slot to the pool.
//
// Decode modes per image field:
//   image_full: full libjpeg decode to uint8 [H, W, C] rows.
//   image_coef: entropy (Huffman) decode ONLY via jpeg_read_coefficients —
//     the host-side half of the DCT-domain split-decode path. Outputs
//     quantized DCT coefficient blocks + quant tables; dequant + IDCT +
//     chroma upsample + YCbCr->RGB run on the TPU inside the jitted train
//     step (see data/jpeg_device.py), putting the IDCT matmuls on the MXU
//     and cutting host CPU cost to the entropy decode (measured ~1.5x less
//     host time per frame than full decode).
//
// Wire-format notes (proto2/proto3 compatible, no protobuf dependency):
//   Example        = { 1: Features }
//   Features       = { 1: repeated map entry { 1: key-bytes, 2: Feature } }
//   Feature        = oneof { 1: BytesList, 2: FloatList, 3: Int64List }
//   BytesList      = { 1: repeated bytes }
//   FloatList      = { 1: repeated float (packed or unpacked) }
//   Int64List      = { 1: repeated varint (packed or unpacked) }
//
// TFRecord framing: [u64 len][u32 masked-crc32c(len)][data][u32 masked-crc32c
// (data)] — see data/tfrecord.py for the Python twin of this reader.

#include <pthread.h>
#include <setjmp.h>
#include <stddef.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <jpeglib.h>  // requires <stddef.h>/<stdio.h> first

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#if defined(__SSE4_2__)
#include <nmmintrin.h>
#endif
#if defined(__SSE2__)
#include <emmintrin.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli) for TFRecord frame verification.
// ---------------------------------------------------------------------------

uint32_t crc32c_table[256];
std::once_flag crc_table_once;

void init_crc_table() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int k = 0; k < 8; k++)
      crc = (crc & 1) ? (crc >> 1) ^ 0x82F63B78u : crc >> 1;
    crc32c_table[i] = crc;
  }
}

uint32_t crc32c(const uint8_t* data, size_t n) {
#if defined(__SSE4_2__)
  uint64_t crc = 0xFFFFFFFFu;
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    uint64_t v;
    memcpy(&v, data + i, 8);
    crc = _mm_crc32_u64(crc, v);
  }
  for (; i < n; i++) crc = _mm_crc32_u8((uint32_t)crc, data[i]);
  return (uint32_t)crc ^ 0xFFFFFFFFu;
#else
  std::call_once(crc_table_once, init_crc_table);
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    crc = crc32c_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
#endif
}

uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// Protobuf wire walking.
// ---------------------------------------------------------------------------

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  // Returns field number, sets wire type; 0 on end/error.
  uint32_t tag(uint32_t* wire_type) {
    if (p >= end) return 0;
    uint64_t t = varint();
    if (!ok) return 0;
    *wire_type = (uint32_t)(t & 7);
    return (uint32_t)(t >> 3);
  }

  // Length-delimited payload; returns view.
  Cursor bytes() {
    uint64_t n = varint();
    if (!ok || p + n > end) {
      ok = false;
      return {end, end};
    }
    Cursor c{p, p + n};
    p += n;
    return c;
  }

  void skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0: varint(); break;
      case 1: p += 8; break;
      case 2: bytes(); break;
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }

  size_t size() const { return end - p; }
};

// ---------------------------------------------------------------------------
// Config.
// ---------------------------------------------------------------------------

enum FieldKind {
  kFloat = 0,
  kInt = 1,
  kImageFull = 2,
  kImageCoef = 3,
  kImageCoefSparse = 4,
  kImageCoefPacked = 5,
};

struct FieldSpec {
  std::string name;
  FieldKind kind;
  int dtype_size;  // int fields: output width in bytes (1, 4, 8)
  int h = 0, w = 0, c = 0;  // image fields
  // float/int fields: elements per row (per STEP for sequence fields).
  // image_full fields: number of frames (a rank-4 [T, H, W, C] spec
  // stores T JPEGs as a bytes list; 0/1 means a single [H, W, C] image).
  // image_coef_sparse fields: the per-row entry capacity of the sparse
  // (delta, value) streams.
  long long count = 0;
  // > 0: a SequenceExample feature_lists field (float/int only) with this
  // step CAPACITY; rows are [seq_cap, count] with zero padding past the
  // record's actual step count, which lands in buf_n.
  long long seq_cap = 0;
  // Varlen (VarLenFeature semantics): the on-disk value list may hold
  // any number of elements; the row is CLIPPED to ``count`` (extras
  // dropped) or PADDED with ``pad_value`` (parser.py pad_or_clip
  // parity). Float/int rank-1 fields and image_full frame lists only.
  int varlen = 0;
  double pad_value = 0.0;
  // Optional (is_optional specs): a record may omit the feature. The
  // per-row presence flag lands in buf_p; the Python side drops the key
  // from any batch where presence is not all-ones (the Python parser's
  // dense-batch drop semantics).
  int optional_field = 0;
  // Dataset index for multi-dataset zip: this field parses from the
  // row's dsi-th record (one record per file group per row).
  int dsi = 0;
  // Buffer indices into Slot::buffers (filled at config time).
  int buf0 = -1;            // primary (float/int/u8 pixels, coef Y,
                            // sparse deltas, or the packed nibble stream)
  int buf_cb = -1, buf_cr = -1, buf_qt = -1;  // image_coef extras; sparse
                            // mode reuses buf_cb for values; packed mode
                            // reuses buf_cb for the int16 escape stream
                            // and buf_cr for the nibble DC-delta plane
  int buf_n = -1;           // per-row counts: sparse entry counts, packed
                            // stream bytes, or sequence step counts
  int buf_n2 = -1;          // packed mode: per-row escape entry counts
  int buf_p = -1;           // per-row presence flags (optional fields)

  // Packed mode derived sizes (filled at config time).
  long long packed_escape_cap() const { return count / 4; }
  long long packed_dc_count() const {
    return (long long)(h / 8) * (w / 8) + 2LL * (h / 16) * (w / 16);
  }
};

struct Config {
  int batch_size = 0;
  int ring = 3;
  int threads = 2;
  bool shuffle = false;
  int shuffle_buffer = 500;
  long long seed = -1;
  long long epochs = -1;  // -1: infinite
  bool verify_crc = false;
  bool any_seq = false;   // any sequence field: records parse as
                          // SequenceExample (context + feature_lists)
  // One file list per dataset; row r of a batch is built from one record
  // of EACH group (multi-dataset zip, ending with the shortest group).
  // The single-dataset case is one group.
  std::vector<std::vector<std::string>> groups;
  std::vector<FieldSpec> fields;
  std::vector<long long> buffer_sizes;  // per-slot bytes for each buffer
};

bool parse_config(const std::string& text, Config* cfg, std::string* err) {
  std::istringstream in(text);
  std::string key;
  while (in >> key) {
    if (key == "batch_size") in >> cfg->batch_size;
    else if (key == "ring") in >> cfg->ring;
    else if (key == "threads") in >> cfg->threads;
    else if (key == "shuffle") { int v; in >> v; cfg->shuffle = v != 0; }
    else if (key == "shuffle_buffer") in >> cfg->shuffle_buffer;
    else if (key == "seed") in >> cfg->seed;
    else if (key == "epochs") in >> cfg->epochs;
    else if (key == "verify_crc") { int v; in >> v; cfg->verify_crc = v != 0; }
    else if (key == "files" || key == "group") {
      // 'files N' (legacy single dataset) and 'group N' (one zip group
      // per occurrence) both append one file group.
      int n; in >> n;
      in.ignore(1);
      std::vector<std::string> group;
      for (int i = 0; i < n; i++) {
        std::string path;
        std::getline(in, path);
        if (path.empty()) { *err = "empty file path"; return false; }
        group.push_back(path);
      }
      cfg->groups.push_back(std::move(group));
    } else if (key == "fields") {
      int m; in >> m;
      for (int i = 0; i < m; i++) {
        FieldSpec f;
        int kind, name_len;
        in >> name_len >> kind >> f.dtype_size >> f.h >> f.w >> f.c
            >> f.count >> f.seq_cap >> f.varlen >> f.optional_field
            >> f.dsi >> f.pad_value;
        f.kind = (FieldKind)kind;
        in.ignore(1);  // single separating space
        f.name.resize(name_len);
        in.read(&f.name[0], name_len);
        cfg->fields.push_back(f);
      }
    } else {
      *err = "unknown config key: " + key;
      return false;
    }
  }
  if (cfg->batch_size <= 0 || cfg->groups.empty() || cfg->fields.empty()) {
    *err = "config requires batch_size, files/groups, fields";
    return false;
  }
  for (const auto& g : cfg->groups) {
    if (g.empty()) {  // an empty group would spin the zip reader on an
                      // empty file list; reject at create like 'files 0'
      *err = "empty file group";
      return false;
    }
  }
  for (const auto& f : cfg->fields) {
    if (f.dsi < 0 || f.dsi >= (int)cfg->groups.size()) {
      *err = "field dataset index out of range: " + f.name;
      return false;
    }
    if (f.varlen && (f.seq_cap > 0 || f.kind == kImageCoef ||
                     f.kind == kImageCoefSparse ||
                     f.kind == kImageCoefPacked)) {
      *err = "varlen unsupported for sequence/coef fields: " + f.name;
      return false;
    }
    if (f.optional_field && (f.kind == kImageCoef ||
                             f.kind == kImageCoefSparse ||
                             f.kind == kImageCoefPacked)) {
      *err = "optional unsupported for coef fields: " + f.name;
      return false;
    }
  }
  if (cfg->ring < 2) cfg->ring = 2;
  if (cfg->threads < 1) cfg->threads = 1;
  // shuffle_buffer <= 0 with shuffle on would never admit a record into
  // the reservoir and end the stream empty; 1 degrades to pass-through.
  if (cfg->shuffle_buffer < 1) cfg->shuffle_buffer = 1;
  // Assign buffers. Layout mirrored in native_loader.py (_buffer_layout).
  long long B = cfg->batch_size;
  for (auto& f : cfg->fields) {
    if (f.seq_cap > 0) {
      if (f.kind != kFloat && f.kind != kInt) {
        *err = "sequence fields must be float/int: " + f.name;
        return false;
      }
      cfg->any_seq = true;
      int width = f.kind == kFloat ? 4 : f.dtype_size;
      f.buf0 = (int)cfg->buffer_sizes.size();
      cfg->buffer_sizes.push_back(B * f.seq_cap * f.count * width);
      f.buf_n = (int)cfg->buffer_sizes.size();  // step counts, int32
      cfg->buffer_sizes.push_back(B * 4);
      if (f.optional_field) {
        f.buf_p = (int)cfg->buffer_sizes.size();  // presence, uint8
        cfg->buffer_sizes.push_back(B);
      }
      continue;
    }
    switch (f.kind) {
      case kFloat:
        f.buf0 = (int)cfg->buffer_sizes.size();
        cfg->buffer_sizes.push_back(B * f.count * 4);
        break;
      case kInt:
        f.buf0 = (int)cfg->buffer_sizes.size();
        cfg->buffer_sizes.push_back(B * f.count * f.dtype_size);
        break;
      case kImageFull: {
        // count > 0: a rank-4 [T, H, W, C] spec — strict frame count
        // (even T=1). count == 0: rank-3 single image, first bytes
        // element wins (Python parser parity).
        long long frames = f.count > 0 ? f.count : 1;
        f.buf0 = (int)cfg->buffer_sizes.size();
        cfg->buffer_sizes.push_back(B * frames * (long long)f.h * f.w *
                                    f.c);
        break;
      }
      case kImageCoef: {
        if (f.h % 16 || f.w % 16 || f.c != 3) {
          *err = "image_coef requires HxW multiple of 16 and c=3: " + f.name;
          return false;
        }
        long long yblocks = (long long)(f.h / 8) * (f.w / 8);
        long long cblocks = (long long)(f.h / 16) * (f.w / 16);
        f.buf0 = (int)cfg->buffer_sizes.size();
        cfg->buffer_sizes.push_back(B * yblocks * 64 * 2);
        f.buf_cb = (int)cfg->buffer_sizes.size();
        cfg->buffer_sizes.push_back(B * cblocks * 64 * 2);
        f.buf_cr = (int)cfg->buffer_sizes.size();
        cfg->buffer_sizes.push_back(B * cblocks * 64 * 2);
        f.buf_qt = (int)cfg->buffer_sizes.size();
        cfg->buffer_sizes.push_back(B * 3 * 64 * 2);
        break;
      }
      case kImageCoefSparse: {
        if (f.h % 16 || f.w % 16 || f.c != 3) {
          *err = "image_coef_sparse requires HxW multiple of 16 and c=3: " +
                 f.name;
          return false;
        }
        if (f.count <= 0) {
          *err = "image_coef_sparse requires a positive entry capacity: " +
                 f.name;
          return false;
        }
        f.buf0 = (int)cfg->buffer_sizes.size();        // deltas, uint8
        cfg->buffer_sizes.push_back(B * f.count);
        f.buf_cb = (int)cfg->buffer_sizes.size();      // values, int8
        cfg->buffer_sizes.push_back(B * f.count);
        f.buf_qt = (int)cfg->buffer_sizes.size();      // quant tables
        cfg->buffer_sizes.push_back(B * 3 * 64 * 2);
        f.buf_n = (int)cfg->buffer_sizes.size();       // entry counts, int32
        cfg->buffer_sizes.push_back(B * 4);
        break;
      }
      case kImageCoefPacked: {
        if (f.h % 16 || f.w % 16 || f.c != 3) {
          *err = "image_coef_packed requires HxW multiple of 16 and c=3: " +
                 f.name;
          return false;
        }
        // count is the per-row BYTE capacity of the packed nibble stream;
        // the escape stream rides at count/4 int16 entries (generous:
        // high-quality encodes of noisy content escape ~30% of entries)
        // and the DC plane is one nibble per block. Multiple-of-8 keeps
        // the derived escape capacity exact.
        if (f.count <= 0 || f.count % 8) {
          *err = "image_coef_packed requires a positive byte capacity "
                 "divisible by 8: " + f.name;
          return false;
        }
        f.buf0 = (int)cfg->buffer_sizes.size();        // nibble stream, u8
        cfg->buffer_sizes.push_back(B * f.count);
        f.buf_cb = (int)cfg->buffer_sizes.size();      // escapes, int16
        cfg->buffer_sizes.push_back(B * f.packed_escape_cap() * 2);
        f.buf_cr = (int)cfg->buffer_sizes.size();      // DC nibbles, u8
        cfg->buffer_sizes.push_back(B * (f.packed_dc_count() / 2));
        f.buf_qt = (int)cfg->buffer_sizes.size();      // quant tables
        cfg->buffer_sizes.push_back(B * 3 * 64 * 2);
        f.buf_n = (int)cfg->buffer_sizes.size();       // stream bytes, i32
        cfg->buffer_sizes.push_back(B * 4);
        f.buf_n2 = (int)cfg->buffer_sizes.size();      // escape counts, i32
        cfg->buffer_sizes.push_back(B * 4);
        break;
      }
    }
    if (f.optional_field) {
      f.buf_p = (int)cfg->buffer_sizes.size();  // presence, uint8
      cfg->buffer_sizes.push_back(B);
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// JPEG decode.
// ---------------------------------------------------------------------------

struct JerrMgr {
  jpeg_error_mgr pub;
  jmp_buf jb;
  char msg[JMSG_LENGTH_MAX];
};

void jerr_exit(j_common_ptr cinfo) {
  JerrMgr* e = (JerrMgr*)cinfo->err;
  (*cinfo->err->format_message)(cinfo, e->msg);
  longjmp(e->jb, 1);
}

// Full decode into row (H*W*C uint8). Returns error string or empty.
std::string decode_jpeg_full(const uint8_t* data, size_t n,
                             const FieldSpec& f, uint8_t* out) {
  if (n == 0) {  // empty payload -> zeros (reference tfdata.py:444-455 parity)
    memset(out, 0, (size_t)f.h * f.w * f.c);
    return "";
  }
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return std::string("jpeg: ") + jerr.msg;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, n);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = f.c == 1 ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if ((int)cinfo.output_width != f.w || (int)cinfo.output_height != f.h ||
      (int)cinfo.output_components != f.c) {
    jpeg_destroy_decompress(&cinfo);
    char buf[160];
    snprintf(buf, sizeof buf, "jpeg dims %dx%dx%d != spec %dx%dx%d for %s",
             cinfo.output_height, cinfo.output_width, cinfo.output_components,
             f.h, f.w, f.c, f.name.c_str());
    return buf;
  }
  size_t stride = (size_t)f.w * f.c;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW rows[8];
    int base = cinfo.output_scanline;
    int navail = (int)(cinfo.output_height - base);
    int nrows = navail < 8 ? navail : 8;
    for (int k = 0; k < nrows; k++) rows[k] = out + (base + k) * stride;
    jpeg_read_scanlines(&cinfo, rows, nrows);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return "";
}

// Entropy-only decode: quantized DCT coefficients + quant tables.
// Requires baseline 4:2:0 (2x2,1x1,1x1 sampling) or 4:4:4 handled as error.
std::string decode_jpeg_coef(const uint8_t* data, size_t n,
                             const FieldSpec& f, int16_t* y, int16_t* cb,
                             int16_t* cr, uint16_t* qt) {
  const long long yblocks = (long long)(f.h / 8) * (f.w / 8);
  const long long cblocks = (long long)(f.h / 16) * (f.w / 16);
  if (n == 0) {
    memset(y, 0, yblocks * 64 * 2);
    memset(cb, 0, cblocks * 64 * 2);
    memset(cr, 0, cblocks * 64 * 2);
    // All-zero quant tables would decode to zeros regardless; use 1s so the
    // device path's dequant multiply is well-defined.
    for (int i = 0; i < 3 * 64; i++) qt[i] = 1;
    return "";
  }
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return std::string("jpeg: ") + jerr.msg;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, n);
  jpeg_read_header(&cinfo, TRUE);
  jvirt_barray_ptr* coefs = jpeg_read_coefficients(&cinfo);
  if (cinfo.num_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef: not a 3-component JPEG: " + f.name;
  }
  if ((int)cinfo.image_width != f.w || (int)cinfo.image_height != f.h) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef: dims mismatch for " + f.name;
  }
  jpeg_component_info* ci = cinfo.comp_info;
  if (ci[0].h_samp_factor != 2 || ci[0].v_samp_factor != 2 ||
      ci[1].h_samp_factor != 1 || ci[1].v_samp_factor != 1 ||
      ci[2].h_samp_factor != 1 || ci[2].v_samp_factor != 1) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef: requires 4:2:0 chroma subsampling: " + f.name;
  }
  int16_t* outs[3] = {y, cb, cr};
  int bw[3] = {f.w / 8, f.w / 16, f.w / 16};
  int bh[3] = {f.h / 8, f.h / 16, f.h / 16};
  for (int comp = 0; comp < 3; comp++) {
    // Quant table for this component.
    JQUANT_TBL* tbl = ci[comp].quant_table
                          ? ci[comp].quant_table
                          : cinfo.quant_tbl_ptrs[ci[comp].quant_tbl_no];
    if (!tbl) {
      jpeg_destroy_decompress(&cinfo);
      return "image_coef: missing quant table: " + f.name;
    }
    for (int i = 0; i < 64; i++) qt[comp * 64 + i] = tbl->quantval[i];
    int16_t* out = outs[comp];
    for (int br = 0; br < bh[comp]; br++) {
      JBLOCKARRAY rows = (*cinfo.mem->access_virt_barray)(
          (j_common_ptr)&cinfo, coefs[comp], br, 1, FALSE);
      // libjpeg pads width_in_blocks to the MCU boundary; copy only the
      // blocks covering the image (bw), dropping pad columns.
      memcpy(out + (long long)br * bw[comp] * 64, rows[0][0],
             (size_t)bw[comp] * 64 * 2);
    }
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return "";
}

// Entropy decode + sparse packing: the quantized DCT coefficients of a
// camera JPEG are overwhelmingly zero (measured ~12% nonzero on realistic
// 512x640 frames), so shipping them dense to the device wastes ~8x the
// bytes on a bandwidth-limited host->device link. This mode emits a
// (delta, value) entry stream per image over a unified flat coefficient
// space [y blocks | cb blocks | cr blocks] in block-row-major natural
// order:
//
//   entry (d, v): advance the cursor by d positions, then ADD v at the
//   cursor. d is uint8, v is int8. Long zero gaps become (255, 0) skip
//   entries; values outside int8 become (0, piece) continuation entries
//   that add onto the same position; buffer tail padding is (0, 0),
//   a no-op. The device reconstructs with one cumsum + one scatter-add
//   (data/jpeg_device.py, unpack_sparse_coefficients) — every entry kind
//   including padding is handled by the same two ops, no branches.
//
// ~2 bytes per nonzero coefficient vs 2 bytes per coefficient dense.
std::string decode_jpeg_coef_sparse(const uint8_t* data, size_t n,
                                    const FieldSpec& f, uint8_t* sd,
                                    int8_t* sv, uint16_t* qt,
                                    int32_t* count_out) {
  const long long cap = f.count;
  if (n == 0) {  // empty payload -> all-zero image (tfdata.py:444 parity)
    memset(sd, 0, cap);
    memset(sv, 0, cap);
    for (int i = 0; i < 3 * 64; i++) qt[i] = 1;
    *count_out = 0;
    return "";
  }
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return std::string("jpeg: ") + jerr.msg;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, n);
  jpeg_read_header(&cinfo, TRUE);
  jvirt_barray_ptr* coefs = jpeg_read_coefficients(&cinfo);
  if (cinfo.num_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef_sparse: not a 3-component JPEG: " + f.name;
  }
  if ((int)cinfo.image_width != f.w || (int)cinfo.image_height != f.h) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef_sparse: dims mismatch for " + f.name;
  }
  jpeg_component_info* ci = cinfo.comp_info;
  if (ci[0].h_samp_factor != 2 || ci[0].v_samp_factor != 2 ||
      ci[1].h_samp_factor != 1 || ci[1].v_samp_factor != 1 ||
      ci[2].h_samp_factor != 1 || ci[2].v_samp_factor != 1) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef_sparse: requires 4:2:0 chroma subsampling: " + f.name;
  }
  long long cur = -1, cnt = 0;
  bool overflow = false;
  // Slow path: long gaps (>255) and wide values (|v|>127) via skip /
  // continuation entries. Rare — the inlined fast path in the scan loop
  // below handles the ~99% case with two stores.
  auto emit_slow = [&](long long pos, int v) {
    long long diff = pos - cur;
    while (diff > 255) {
      if (cnt >= cap) { overflow = true; return; }
      sd[cnt] = 255;
      sv[cnt] = 0;
      cnt++;
      diff -= 255;
    }
    int piece = v < -128 ? -128 : (v > 127 ? 127 : v);
    if (cnt >= cap) { overflow = true; return; }
    sd[cnt] = (uint8_t)diff;
    sv[cnt] = (int8_t)piece;
    cnt++;
    v -= piece;
    while (v != 0) {  // |coef| > 127: add onto the same position
      piece = v < -128 ? -128 : (v > 127 ? 127 : v);
      if (cnt >= cap) { overflow = true; return; }
      sd[cnt] = 0;
      sv[cnt] = (int8_t)piece;
      cnt++;
      v -= piece;
    }
    cur = pos;
  };
  auto emit = [&](long long pos, int v) {
    long long diff = pos - cur;
    if (diff <= 255 && v >= -128 && v <= 127 && cnt < cap) {
      sd[cnt] = (uint8_t)diff;
      sv[cnt] = (int8_t)v;
      cnt++;
      cur = pos;
      return;
    }
    emit_slow(pos, v);
  };
  int bw[3] = {f.w / 8, f.w / 16, f.w / 16};
  int bh[3] = {f.h / 8, f.h / 16, f.h / 16};
  long long base = 0;
  for (int comp = 0; comp < 3 && !overflow; comp++) {
    JQUANT_TBL* tbl = ci[comp].quant_table
                          ? ci[comp].quant_table
                          : cinfo.quant_tbl_ptrs[ci[comp].quant_tbl_no];
    if (!tbl) {
      jpeg_destroy_decompress(&cinfo);
      return "image_coef_sparse: missing quant table: " + f.name;
    }
    for (int i = 0; i < 64; i++) qt[comp * 64 + i] = tbl->quantval[i];
    for (int br = 0; br < bh[comp] && !overflow; br++) {
      JBLOCKARRAY rows = (*cinfo.mem->access_virt_barray)(
          (j_common_ptr)&cinfo, coefs[comp], br, 1, FALSE);
      for (int bc = 0; bc < bw[comp] && !overflow; bc++) {
        const JCOEF* block = rows[0][bc];
        long long block_base = base + ((long long)br * bw[comp] + bc) * 64;
        // Zero coefficients dominate (~88%); scan for nonzeros with wide
        // compares instead of per-coefficient branches. With the
        // two-store emit fast path this cut the sparse-pack overhead vs
        // plain coef mode from ~0.6 ms to ~0.1 ms per 512x640 frame
        // (580 -> 925 ex/s single-worker on the bench host).
        static_assert(sizeof(JCOEF) == 2,
                      "group scan assumes 16-bit coefficients");
#if defined(__SSE2__)
        for (int g = 0; g < 4; g++) {
          __m128i a = _mm_loadu_si128((const __m128i*)(block + g * 16));
          __m128i b = _mm_loadu_si128(
              (const __m128i*)(block + g * 16 + 8));
          __m128i zero = _mm_setzero_si128();
          // Per-16-bit-lane zero masks, packed to one byte per lane.
          uint32_t z = (uint32_t)_mm_movemask_epi8(
              _mm_packs_epi16(_mm_cmpeq_epi16(a, zero),
                              _mm_cmpeq_epi16(b, zero)));
          uint32_t nz = ~z & 0xFFFFu;  // bit i set <=> block[g*16+i] != 0
          while (nz) {
            int k = g * 16 + __builtin_ctz(nz);
            nz &= nz - 1;
            emit(block_base + k, block[k]);
            if (overflow) break;
          }
          if (overflow) break;
        }
#else
        for (int g = 0; g < 16; g++) {
          uint64_t group;
          memcpy(&group, block + g * 4, 8);
          if (!group) continue;
          for (int k = g * 4; k < g * 4 + 4; k++) {
            if (block[k]) {
              emit(block_base + k, block[k]);
              if (overflow) break;
            }
          }
          if (overflow) break;
        }
#endif
      }
    }
    base += (long long)bh[comp] * bw[comp] * 64;
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  if (overflow) {
    char buf[192];
    snprintf(buf, sizeof buf,
             "image_coef_sparse: entry capacity %lld exceeded for '%s' "
             "(unusually dense JPEG); raise sparse_density or use "
             "image_mode='coef'",
             cap, f.name.c_str());
    return buf;
  }
  // Tail padding MUST be zeroed: buffers are recycled across batches and a
  // stale nonzero delta would silently corrupt positions on the device.
  memset(sd + cnt, 0, cap - cnt);
  memset(sv + cnt, 0, cap - cnt);
  *count_out = (int32_t)cnt;
  return "";
}

// Entropy decode + PACKED sparse wire: the round-10 tightening of the
// coef_sparse format. The loose format spends 2 bytes per nonzero (uint8
// delta + int8 value); the measured streams say that is ~40% air —
// 84% of entries have gap <= 15 AND |value| <= 7, and the large values
// concentrate in the DC coefficients, whose CROSS-BLOCK deltas are small
// (91% within +/-7 on camera-like frames). The packed wire exploits both:
//
//   * AC nibble stream (buf0, uint8): one byte per AC nonzero in the
//     unified flat space [y | cb | cr] (natural order, DC slots skipped).
//     High nibble d = position gap (0..15), low nibble v = value code:
//       v in 1..7            -> value +v
//       v in 9..15           -> value v-16 (i.e. -7..-1)
//       v == 8               -> ESCAPE: value is the next int16 of the
//                               escape stream (AC region)
//       v == 0, d > 0        -> skip byte: advance d*16, no value
//       0x00                 -> no-op (tail padding)
//     Gaps > 15 emit skip bytes (one covers up to 240); every byte kind
//     falls out of the same cumsum + scatter-add on device.
//   * DC nibble plane (buf_cr, uint8): one 4-bit code per block, packed
//     two-per-byte low-nibble-first, carrying the cross-block DC delta
//     chain (previous DC starts at 0, runs across component boundaries):
//       code in 0..7   -> delta +code     code in 9..15 -> delta code-16
//       code == 8      -> ESCAPE: delta is the next int16 of the escape
//                         stream (DC region)
//     The device undoes the chain with one cumsum over blocks.
//   * Escape stream (buf_cb, int16): DC escapes first (frame order),
//     then AC escapes (stream order) — two regions so the device can
//     index each with an independent cumsum of its escape markers.
//   * Quant tables (buf_qt): per-row here, but the packed wire contract
//     is batch-uniform tables — the Python pack stage verifies and ships
//     ONE (3, 64) table per batch (the hoist that removes 384 B/example
//     from the wire). Empty payloads write all-zero tables (a "no
//     table" sentinel the uniformity check ignores).
//
// Measured on the bench's camera-like 512x640 frames: ~59 KB AC stream +
// ~3.8 KB DC plane + ~3 KB escapes vs ~120 KB loose sparse — 1.8x fewer
// wire bytes for the same bit-exact coefficients.
std::string decode_jpeg_coef_packed(const uint8_t* data, size_t n,
                                    const FieldSpec& f, uint8_t* pw,
                                    int16_t* se, uint8_t* dcn, uint16_t* qt,
                                    int32_t* n_out, int32_t* ne_out) {
  const long long cap = f.count;
  const long long esc_cap = f.packed_escape_cap();
  const long long n_dc = f.packed_dc_count();
  if (n == 0) {  // empty payload -> all-zero image (tfdata.py:444 parity)
    memset(pw, 0, cap);
    memset(se, 0, esc_cap * 2);
    memset(dcn, 0, n_dc / 2);
    // Zero tables: the "no table" sentinel — the pack stage's batch
    // uniformity check skips these rows (a 1s table here would falsely
    // conflict with the batch's real table).
    memset(qt, 0, 3 * 64 * 2);
    *n_out = 0;
    *ne_out = 0;
    return "";
  }
  jpeg_decompress_struct cinfo;
  JerrMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return std::string("jpeg: ") + jerr.msg;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, n);
  jpeg_read_header(&cinfo, TRUE);
  jvirt_barray_ptr* coefs = jpeg_read_coefficients(&cinfo);
  if (cinfo.num_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef_packed: not a 3-component JPEG: " + f.name;
  }
  if ((int)cinfo.image_width != f.w || (int)cinfo.image_height != f.h) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef_packed: dims mismatch for " + f.name;
  }
  jpeg_component_info* ci = cinfo.comp_info;
  if (ci[0].h_samp_factor != 2 || ci[0].v_samp_factor != 2 ||
      ci[1].h_samp_factor != 1 || ci[1].v_samp_factor != 1 ||
      ci[2].h_samp_factor != 1 || ci[2].v_samp_factor != 1) {
    jpeg_destroy_decompress(&cinfo);
    return "image_coef_packed: requires 4:2:0 chroma subsampling: " + f.name;
  }
  long long cur = -1, na = 0;
  bool overflow = false;
  // Escape regions buffered separately: the wire contract is
  // [DC escapes | AC escapes] but the scan discovers them interleaved.
  std::vector<int16_t> dc_esc, ac_esc;
  auto emit_ac = [&](long long pos, int v) {
    long long gap = pos - cur;
    cur = pos;
    while (gap > 15) {
      long long s = gap >> 4;
      if (s > 15) s = 15;
      if (na >= cap) { overflow = true; return; }
      pw[na++] = (uint8_t)(s << 4);
      gap -= s * 16;
    }
    if (na >= cap) { overflow = true; return; }
    if (v >= -7 && v <= 7)
      pw[na++] = (uint8_t)((gap << 4) | (v & 0xF));
    else {
      pw[na++] = (uint8_t)((gap << 4) | 8);
      ac_esc.push_back((int16_t)v);
    }
  };
  int bw[3] = {f.w / 8, f.w / 16, f.w / 16};
  int bh[3] = {f.h / 8, f.h / 16, f.h / 16};
  long long base = 0, block_index = 0;
  int prev_dc = 0;
  memset(dcn, 0, n_dc / 2);
  for (int comp = 0; comp < 3 && !overflow; comp++) {
    JQUANT_TBL* tbl = ci[comp].quant_table
                          ? ci[comp].quant_table
                          : cinfo.quant_tbl_ptrs[ci[comp].quant_tbl_no];
    if (!tbl) {
      jpeg_destroy_decompress(&cinfo);
      return "image_coef_packed: missing quant table: " + f.name;
    }
    for (int i = 0; i < 64; i++) qt[comp * 64 + i] = tbl->quantval[i];
    for (int br = 0; br < bh[comp] && !overflow; br++) {
      JBLOCKARRAY rows = (*cinfo.mem->access_virt_barray)(
          (j_common_ptr)&cinfo, coefs[comp], br, 1, FALSE);
      for (int bc = 0; bc < bw[comp] && !overflow; bc++) {
        const JCOEF* block = rows[0][bc];
        long long block_base = base + ((long long)br * bw[comp] + bc) * 64;
        // DC: cross-block delta chain into the nibble plane.
        int dc_delta = block[0] - prev_dc;
        prev_dc = block[0];
        uint8_t code;
        if (dc_delta >= -7 && dc_delta <= 7)
          code = (uint8_t)(dc_delta & 0xF);
        else {
          code = 8;
          dc_esc.push_back((int16_t)dc_delta);
        }
        dcn[block_index >> 1] |=
            (block_index & 1) ? (uint8_t)(code << 4) : code;
        block_index++;
        // AC: same group-scan as the loose sparse mode, k=0 excluded via
        // a mask on the first lane group.
        static_assert(sizeof(JCOEF) == 2,
                      "group scan assumes 16-bit coefficients");
#if defined(__SSE2__)
        for (int g = 0; g < 4; g++) {
          __m128i a = _mm_loadu_si128((const __m128i*)(block + g * 16));
          __m128i b = _mm_loadu_si128(
              (const __m128i*)(block + g * 16 + 8));
          __m128i zero = _mm_setzero_si128();
          uint32_t z = (uint32_t)_mm_movemask_epi8(
              _mm_packs_epi16(_mm_cmpeq_epi16(a, zero),
                              _mm_cmpeq_epi16(b, zero)));
          uint32_t nz = ~z & 0xFFFFu;
          if (g == 0) nz &= ~1u;  // k == 0 is the DC slot
          while (nz) {
            int k = g * 16 + __builtin_ctz(nz);
            nz &= nz - 1;
            emit_ac(block_base + k, block[k]);
            if (overflow) break;
          }
          if (overflow) break;
        }
#else
        for (int k = 1; k < 64; k++) {
          if (block[k]) {
            emit_ac(block_base + k, block[k]);
            if (overflow) break;
          }
        }
#endif
      }
    }
    base += (long long)bh[comp] * bw[comp] * 64;
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  long long ne = (long long)(dc_esc.size() + ac_esc.size());
  if (overflow || ne > esc_cap) {
    char buf[192];
    snprintf(buf, sizeof buf,
             "image_coef_packed: %s capacity %lld exceeded for '%s' "
             "(unusually dense JPEG); raise sparse_density or use "
             "image_mode='coef'",
             overflow ? "stream byte" : "escape", overflow ? cap : esc_cap,
             f.name.c_str());
    return buf;
  }
  if (!dc_esc.empty())
    memcpy(se, dc_esc.data(), dc_esc.size() * 2);
  if (!ac_esc.empty())
    memcpy(se + dc_esc.size(), ac_esc.data(), ac_esc.size() * 2);
  // Tails MUST be zeroed: buffers recycle across batches, and a stale
  // nonzero nibble would silently corrupt positions on the device.
  memset(pw + na, 0, cap - na);
  memset(se + ne, 0, (esc_cap - ne) * 2);
  *n_out = (int32_t)na;
  *ne_out = (int32_t)ne;
  return "";
}

// ---------------------------------------------------------------------------
// Loader.
// ---------------------------------------------------------------------------

// Monotonic microseconds for the pipeline-stats busy/idle accounting:
// steady_clock, never wall time — the same discipline the Python side
// enforces with tests/test_no_wallclock.py.
inline long long now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

enum SlotState { kFree, kFilling, kReady, kInUse };

struct Slot {
  std::vector<uint8_t*> buffers;
  std::atomic<int> remaining{0};
  SlotState state = kFree;
  long long seq = -1;  // batch sequence number, for ordered hand-off
  // First row error in this batch, if any (guarded by Loader::mu). The
  // fail/discard decision is deferred to batch COMPLETION so that an
  // error in the EOF-discarded partial batch (drop_remainder semantics)
  // is swallowed deterministically — at completion time the reader has
  // either marked the slot seq = -2 or never will.
  std::string row_error;
};

struct WorkItem {
  std::vector<std::string> records;  // one record per dataset group
  int slot;
  int row;
};

struct Loader {
  Config cfg;
  std::deque<Slot> slots;
  std::mutex mu;
  std::condition_variable cv_ready;    // consumer waits
  std::condition_variable cv_free;     // reader waits for a free slot
  std::condition_variable cv_work;     // workers wait
  std::condition_variable cv_space;    // reader waits for queue space
  std::deque<WorkItem> work;
  std::deque<int> ready;               // READY slot indices in seq order
  bool eof = false;                    // reader finished dispatching
  std::atomic<bool> stop{false};
  std::string error;
  long long dispatched_batches = 0;
  long long completed_batches = 0;
  long long next_seq_out = 0;          // strict batch delivery order
  std::vector<std::thread> threads;
  std::thread reader;
  // Worker/reader threads launch lazily on the FIRST next_slot() call,
  // not at create time: create-time work is config parsing + buffer
  // allocation only (errors surface synchronously), and every data/parse
  // error has exactly ONE surfacing point — iteration. This is what
  // makes error delivery deterministic instead of a race between the
  // eagerly-parsing workers and the constructor's last_error poll.
  std::once_flag launch_once;

  // ---- pipeline stats (t2r_loader_stats export) ---------------------------
  // Cumulative, relaxed atomics written from the reader/worker threads
  // and read racily by the consumer — the Python X-ray layer windows the
  // deltas, so torn cross-field reads only cost sub-window skew. Safe to
  // read BEFORE the lazy thread launch (all zeros) and after EOF.
  std::atomic<long long> st_records_read{0};   // records framed off disk
  std::atomic<long long> st_bytes_read{0};     // incl. TFRecord framing
  std::atomic<long long> st_reader_busy_us{0}; // read + shuffle time
  std::atomic<long long> st_reader_wait_us{0}; // blocked on slots/space
  std::atomic<long long> st_rows_parsed{0};    // batch rows completed
  std::atomic<long long> st_parse_bytes{0};    // record bytes parsed
  std::atomic<long long> st_worker_busy_us{0}; // parse/decode, pool total
  std::atomic<long long> st_worker_idle_us{0}; // waiting for work, total
  std::unique_ptr<std::atomic<long long>[]> st_per_worker_busy_us;

  long long stats_snapshot(long long* out, int n) {
    long long min_busy = 0, max_busy = 0;
    if (st_per_worker_busy_us && cfg.threads > 0) {
      min_busy = max_busy =
          st_per_worker_busy_us[0].load(std::memory_order_relaxed);
      for (int i = 1; i < cfg.threads; i++) {
        long long v =
            st_per_worker_busy_us[i].load(std::memory_order_relaxed);
        if (v < min_busy) min_busy = v;
        if (v > max_busy) max_busy = v;
      }
    }
    long long completed;
    {
      std::lock_guard<std::mutex> lk(mu);
      completed = completed_batches;
    }
    const long long vals[12] = {
        st_records_read.load(std::memory_order_relaxed),
        st_bytes_read.load(std::memory_order_relaxed),
        st_reader_busy_us.load(std::memory_order_relaxed),
        st_reader_wait_us.load(std::memory_order_relaxed),
        st_rows_parsed.load(std::memory_order_relaxed),
        st_parse_bytes.load(std::memory_order_relaxed),
        st_worker_busy_us.load(std::memory_order_relaxed),
        st_worker_idle_us.load(std::memory_order_relaxed),
        (long long)cfg.threads,
        completed,
        min_busy,
        max_busy,
    };
    int m = n < 12 ? n : 12;
    for (int i = 0; i < m; i++) out[i] = vals[i];
    return m;
  }

  ~Loader() { shutdown(); }

  void shutdown() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_work.notify_all();
    cv_free.notify_all();
    cv_space.notify_all();
    cv_ready.notify_all();
    if (reader.joinable()) reader.join();
    for (auto& t : threads)
      if (t.joinable()) t.join();
    for (auto& s : slots)
      for (auto* b : s.buffers) free(b);
    slots.clear();
  }

  void fail(const std::string& msg) {
    std::lock_guard<std::mutex> lk(mu);
    if (error.empty()) error = msg;
    stop = true;
    cv_ready.notify_all();
    cv_work.notify_all();
    cv_free.notify_all();
    cv_space.notify_all();
  }

  // ---- reader ------------------------------------------------------------

  bool dispatch_row(std::vector<std::string>&& recs, int* cur_slot,
                    int* cur_row, long long* seq) {
    if (*cur_slot < 0) {  // acquire a free slot
      long long t0 = now_us();
      std::unique_lock<std::mutex> lk(mu);
      cv_free.wait(lk, [&] {
        if (stop) return true;
        for (auto& s : slots)
          if (s.state == kFree) return true;
        return false;
      });
      st_reader_wait_us.fetch_add(now_us() - t0, std::memory_order_relaxed);
      if (stop) return false;
      for (size_t i = 0; i < slots.size(); i++) {
        if (slots[i].state == kFree) {
          slots[i].state = kFilling;
          slots[i].remaining.store(cfg.batch_size);
          slots[i].seq = (*seq)++;
          slots[i].row_error.clear();
          *cur_slot = (int)i;
          *cur_row = 0;
          break;
        }
      }
    }
    {
      long long t0 = now_us();
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] {
        return stop || work.size() < (size_t)(4 * cfg.threads + 64);
      });
      st_reader_wait_us.fetch_add(now_us() - t0, std::memory_order_relaxed);
      if (stop) return false;
      work.push_back(WorkItem{std::move(recs), *cur_slot, *cur_row});
    }
    cv_work.notify_one();
    if (++*cur_row == cfg.batch_size) {
      *cur_slot = -1;
      std::lock_guard<std::mutex> lk(mu);
      dispatched_batches++;
    }
    return true;
  }

  // One dataset group's record source: its file list looped over the
  // configured epochs, with the group's OWN bounded reservoir shuffle —
  // the Python pipeline shuffles each zipped dataset independently
  // before pairing (pipeline.py _record_tuples), so the native zip does
  // too. reader_main pulls the groups in lockstep to form zip tuples
  // (one record per group per row); the single-dataset case is one
  // stream, where the per-stream reservoir is exactly the old
  // emit-level one.
  struct RecordStream {
    Loader* loader = nullptr;
    const std::vector<std::string>* files = nullptr;
    std::mt19937_64* rng = nullptr;
    long long epoch = 0;
    size_t file_idx = 0;
    std::vector<std::string> order;
    FILE* f = nullptr;
    long file_size = 0;
    std::vector<std::string> shuffle_buf;
    bool exhausted = false;

    ~RecordStream() {
      if (f) fclose(f);
    }

    // 1 = record read, 0 = clean end of data (or stop), -1 = error.
    int next(std::string* rec, std::string* err) {
      const Config& cfg = loader->cfg;
      if (!cfg.shuffle) return read_raw(rec, err);
      while (!exhausted &&
             (int)shuffle_buf.size() < cfg.shuffle_buffer) {
        std::string r;
        int status = read_raw(&r, err);
        if (status < 0) return -1;
        if (status == 0) {
          exhausted = true;
          break;
        }
        shuffle_buf.push_back(std::move(r));
      }
      if (shuffle_buf.empty()) return 0;
      size_t idx = (*rng)() % shuffle_buf.size();
      std::swap(shuffle_buf[idx], shuffle_buf.back());
      *rec = std::move(shuffle_buf.back());
      shuffle_buf.pop_back();
      return 1;
    }

    int read_raw(std::string* rec, std::string* err) {
      const Config& cfg = loader->cfg;
      for (;;) {
        if (loader->stop.load()) return 0;
        if (f == nullptr) {
          if (order.empty() || file_idx >= order.size()) {
            if (!order.empty()) epoch++;
            if (cfg.epochs >= 0 && epoch >= cfg.epochs) return 0;
            if (order.empty()) order = *files;
            if (cfg.shuffle) std::shuffle(order.begin(), order.end(), *rng);
            file_idx = 0;
          }
          const std::string& path = order[file_idx];
          f = fopen(path.c_str(), "rb");
          if (!f) {
            *err = "cannot open " + path;
            return -1;
          }
          fseek(f, 0, SEEK_END);
          file_size = ftell(f);
          fseek(f, 0, SEEK_SET);
        }
        const std::string& path = order[file_idx];
        uint8_t header[12];
        if (fread(header, 1, 12, f) != 12) {  // end of this file
          fclose(f);
          f = nullptr;
          file_idx++;
          continue;
        }
        uint64_t len;
        memcpy(&len, header, 8);
        // Sanity-cap the untrusted length BEFORE resize: a corrupt frame
        // (or a non-TFRecord file matched by the glob) must surface as a
        // loader error, not a std::bad_alloc escaping the thread.
        long pos = ftell(f);
        if (pos < 0 || len > (uint64_t)(file_size - pos)) {
          *err = "corrupt or non-TFRecord frame in " + path +
                 " (record length exceeds file size)";
          return -1;
        }
        if (cfg.verify_crc) {
          uint32_t expect;
          memcpy(&expect, header + 8, 4);
          if (masked_crc(header, 8) != expect) {
            *err = "corrupt TFRecord length CRC in " + path;
            return -1;
          }
        }
        rec->resize(len);
        if (len > 0 && fread(&(*rec)[0], 1, len, f) != len) {
          *err = "truncated TFRecord in " + path;
          return -1;
        }
        uint8_t footer[4];
        if (fread(footer, 1, 4, f) != 4) {
          *err = "truncated TFRecord in " + path;
          return -1;
        }
        if (cfg.verify_crc) {
          uint32_t expect;
          memcpy(&expect, footer, 4);
          if (masked_crc((const uint8_t*)rec->data(), rec->size()) !=
              expect) {
            *err = "corrupt TFRecord data CRC in " + path;
            return -1;
          }
        }
        loader->st_records_read.fetch_add(1, std::memory_order_relaxed);
        loader->st_bytes_read.fetch_add(16 + (long long)len,
                                        std::memory_order_relaxed);
        return 1;
      }
    }
  };

  void reader_main() {
    std::mt19937_64 rng(cfg.seed >= 0 ? (uint64_t)cfg.seed
                                      : std::random_device{}());
    int cur_slot = -1, cur_row = 0;
    long long seq = 0;

    const size_t n_groups = cfg.groups.size();
    std::vector<RecordStream> streams(n_groups);
    for (size_t g = 0; g < n_groups; g++) {
      streams[g].loader = this;
      streams[g].files = &cfg.groups[g];
      streams[g].rng = &rng;
    }
    for (;;) {
      std::vector<std::string> tuple(n_groups);
      bool end_of_data = false;
      long long t0 = now_us();
      for (size_t g = 0; g < n_groups; g++) {
        std::string err;
        int status = streams[g].next(&tuple[g], &err);
        if (status < 0) {
          fail(err);
          return;
        }
        if (status == 0) {  // zip ends with the shortest dataset
          end_of_data = true;
          break;
        }
      }
      st_reader_busy_us.fetch_add(now_us() - t0, std::memory_order_relaxed);
      if (end_of_data) break;
      if (!dispatch_row(std::move(tuple), &cur_slot, &cur_row, &seq))
        return;
    }
    if (stop) return;
    // Partial batch at end of data is dropped (drop_remainder=True parity,
    // utils/tfdata.py:560-564): mark the half-filled slot free again.
    {
      std::lock_guard<std::mutex> lk(mu);
      if (cur_slot >= 0 && cur_row > 0) {
        // 'remaining' was initialized to batch_size; subtract the rows that
        // were never dispatched. Whoever's subtraction transitions the count
        // to exactly 0 owns recycling the slot: if our fetch_sub consumed the
        // whole residue (prev == subtracted), every dispatched row already
        // finished and no worker will touch the slot again; otherwise the
        // last in-flight worker sees prev==1 and checks seq == -2 (set here,
        // under the same mutex its check takes).
        int sub = cfg.batch_size - cur_row;
        int prev = slots[cur_slot].remaining.fetch_sub(sub);
        if (prev == sub)
          slots[cur_slot].state = kFree;
        else
          slots[cur_slot].seq = -2;  // sentinel: discard on completion
      }
      eof = true;
    }
    cv_ready.notify_all();
  }

  // ---- workers -----------------------------------------------------------

  // Walks one map entry ({1: key-bytes, 2: value-message}) shared by the
  // Features and FeatureLists sides. Returns the matched field index among
  // fields of dataset ``dsi`` whose (seq_cap > 0) equals ``sequence``, or
  // -1; *value_out gets the value message cursor.
  int match_entry(Cursor entry, bool sequence, int dsi, Cursor* value_out) {
    const uint8_t* key_p = nullptr;
    size_t key_n = 0;
    Cursor value{nullptr, nullptr};
    uint32_t wt;
    while (uint32_t f3 = entry.tag(&wt)) {
      if (f3 == 1 && wt == 2) {
        Cursor k = entry.bytes();
        key_p = k.p;
        key_n = k.size();
      } else if (f3 == 2 && wt == 2) {
        value = entry.bytes();
      } else {
        entry.skip(wt);
      }
    }
    if (!key_p || !value.p) return -1;
    // Linear scan: few fields, avoids hashing every record key.
    for (size_t i = 0; i < cfg.fields.size(); i++) {
      const FieldSpec& f = cfg.fields[i];
      if ((f.seq_cap > 0) != sequence || f.dsi != dsi) continue;
      if (f.name.size() == key_n &&
          memcmp(f.name.data(), key_p, key_n) == 0) {
        *value_out = value;
        return (int)i;
      }
    }
    return -1;
  }

  // Zeroes one row of an optional field that the record omitted. The
  // Python side drops the whole key from any batch whose presence flags
  // are not all-ones (the Python parser's dense-batch semantics), so the
  // zeros are recycling hygiene, never observable data.
  void zero_field_row(const FieldSpec& f, Slot& slot, int row) {
    if (f.seq_cap > 0) {
      int width = f.kind == kFloat ? 4 : f.dtype_size;
      long long bytes = f.seq_cap * f.count * width;
      memset(slot.buffers[f.buf0] + (long long)row * bytes, 0,
             (size_t)bytes);
      ((int32_t*)slot.buffers[f.buf_n])[row] = 0;
      return;
    }
    switch (f.kind) {
      case kFloat:
        memset(slot.buffers[f.buf0] + (long long)row * f.count * 4, 0,
               (size_t)(f.count * 4));
        break;
      case kInt:
        memset(slot.buffers[f.buf0] +
                   (long long)row * f.count * f.dtype_size,
               0, (size_t)(f.count * f.dtype_size));
        break;
      case kImageFull: {
        long long frames = f.count > 0 ? f.count : 1;
        long long bytes = frames * (long long)f.h * f.w * f.c;
        memset(slot.buffers[f.buf0] + (long long)row * bytes, 0,
               (size_t)bytes);
        break;
      }
      default:
        break;  // coef modes cannot be optional (parse_config rejects)
    }
  }

  std::string parse_record(const std::string& rec, int dsi, Slot& slot,
                           int row, std::vector<bool>* found) {
    Cursor ex{(const uint8_t*)rec.data(),
              (const uint8_t*)rec.data() + rec.size()};
    uint32_t wt;
    while (uint32_t fnum = ex.tag(&wt)) {
      if (fnum == 1 && wt == 2) {
        // Example.features / SequenceExample.context (wire-identical).
        Cursor features = ex.bytes();
        while (uint32_t f2 = features.tag(&wt)) {
          if (f2 != 1 || wt != 2) {
            features.skip(wt);
            continue;
          }
          Cursor value{nullptr, nullptr};
          int fi = match_entry(features.bytes(), /*sequence=*/false, dsi,
                               &value);
          if (fi < 0) continue;
          (*found)[fi] = true;
          std::string err = extract_field(cfg.fields[fi], value, slot, row);
          if (!err.empty()) return err;
        }
      } else if (fnum == 2 && wt == 2 && cfg.any_seq) {
        // SequenceExample.feature_lists = {1: entry {1: key, 2: FeatureList}}.
        Cursor lists = ex.bytes();
        while (uint32_t f2 = lists.tag(&wt)) {
          if (f2 != 1 || wt != 2) {
            lists.skip(wt);
            continue;
          }
          Cursor value{nullptr, nullptr};
          int fi = match_entry(lists.bytes(), /*sequence=*/true, dsi,
                               &value);
          if (fi < 0) continue;
          (*found)[fi] = true;
          std::string err =
              extract_sequence_field(cfg.fields[fi], value, slot, row);
          if (!err.empty()) return err;
        }
      } else {
        ex.skip(wt);
      }
    }
    if (!ex.ok) return "malformed Example record";
    return "";
  }

  std::string parse_into(const std::vector<std::string>& recs, int slot_idx,
                         int row) {
    Slot& slot = slots[slot_idx];
    // Track which fields were found across all zipped records.
    std::vector<bool> found(cfg.fields.size(), false);
    for (size_t d = 0; d < recs.size(); d++) {
      std::string err = parse_record(recs[d], (int)d, slot, row, &found);
      if (!err.empty()) return err;
    }
    for (size_t i = 0; i < cfg.fields.size(); i++) {
      const FieldSpec& f = cfg.fields[i];
      if (found[i]) {
        if (f.buf_p >= 0) slot.buffers[f.buf_p][row] = 1;
        continue;
      }
      if (!f.optional_field)
        return "feature '" + f.name + "' missing from record";
      if (f.buf_p >= 0) slot.buffers[f.buf_p][row] = 0;
      zero_field_row(f, slot, row);
    }
    return "";
  }

  std::string extract_field(const FieldSpec& f, Cursor value, Slot& slot,
                            int row) {
    // value is a Feature message: 1=BytesList, 2=FloatList, 3=Int64List.
    uint32_t wt;
    while (uint32_t fnum = value.tag(&wt)) {
      if (wt != 2) {
        value.skip(wt);
        continue;
      }
      Cursor list = value.bytes();
      switch (fnum) {
        case 1: {  // BytesList
          if (f.kind != kImageFull && f.kind != kImageCoef &&
              f.kind != kImageCoefSparse && f.kind != kImageCoefPacked)
            return "feature '" + f.name + "' is bytes but spec is numeric";
          bool frame_list = f.kind == kImageFull && f.count > 0;
          bool strict_list = frame_list && !f.varlen;
          long long frames = frame_list ? f.count : 1;
          long long got = 0;
          uint32_t wt2;
          while (uint32_t f2 = list.tag(&wt2)) {
            if (f2 == 1 && wt2 == 2) {
              Cursor payload = list.bytes();
              if (got >= frames) {
                if (!strict_list) continue;  // rank-3 spec: first element
                                             // wins; varlen list: clip —
                                             // extras ignored either way
                                             // (Python parser parity)
                char buf[128];
                snprintf(buf, sizeof buf, "feature '%s': more than %lld "
                         "encoded frames", f.name.c_str(), frames);
                return buf;
              }
              if (f.kind == kImageFull) {
                uint8_t* out = slot.buffers[f.buf0] +
                               ((size_t)row * frames + got) *
                                   f.h * f.w * f.c;
                std::string err =
                    decode_jpeg_full(payload.p, payload.size(), f, out);
                if (!err.empty()) return err;
                got++;
                continue;
              }
              if (f.kind == kImageCoefSparse)
                return decode_jpeg_coef_sparse(
                    payload.p, payload.size(), f,
                    slot.buffers[f.buf0] + (long long)row * f.count,
                    (int8_t*)slot.buffers[f.buf_cb] +
                        (long long)row * f.count,
                    (uint16_t*)slot.buffers[f.buf_qt] +
                        (long long)row * 3 * 64,
                    (int32_t*)slot.buffers[f.buf_n] + row);
              if (f.kind == kImageCoefPacked)
                return decode_jpeg_coef_packed(
                    payload.p, payload.size(), f,
                    slot.buffers[f.buf0] + (long long)row * f.count,
                    (int16_t*)slot.buffers[f.buf_cb] +
                        (long long)row * f.packed_escape_cap(),
                    slot.buffers[f.buf_cr] +
                        (long long)row * (f.packed_dc_count() / 2),
                    (uint16_t*)slot.buffers[f.buf_qt] +
                        (long long)row * 3 * 64,
                    (int32_t*)slot.buffers[f.buf_n] + row,
                    (int32_t*)slot.buffers[f.buf_n2] + row);
              long long yb = (long long)(f.h / 8) * (f.w / 8) * 64;
              long long cb_n = (long long)(f.h / 16) * (f.w / 16) * 64;
              return decode_jpeg_coef(
                  payload.p, payload.size(), f,
                  (int16_t*)slot.buffers[f.buf0] + (long long)row * yb,
                  (int16_t*)slot.buffers[f.buf_cb] + (long long)row * cb_n,
                  (int16_t*)slot.buffers[f.buf_cr] + (long long)row * cb_n,
                  (uint16_t*)slot.buffers[f.buf_qt] + (long long)row * 3 * 64);
            }
            list.skip(wt2);
          }
          if (strict_list && got != frames) {
            char buf[128];
            snprintf(buf, sizeof buf, "feature '%s': got %lld encoded "
                     "frames, want %lld", f.name.c_str(), got, frames);
            return buf;
          }
          if (f.varlen && frame_list && got < frames) {
            // parser.py varlen-image parity: an EMPTY list decodes one
            // all-zeros frame first, then pad_or_clip fills the rest
            // with the varlen default value.
            long long frame_bytes = (long long)f.h * f.w * f.c;
            uint8_t* base = slot.buffers[f.buf0] +
                            (size_t)row * frames * frame_bytes;
            if (got == 0) {
              memset(base, 0, (size_t)frame_bytes);
              got = 1;
            }
            memset(base + got * frame_bytes,
                   (uint8_t)(long long)f.pad_value,
                   (size_t)((frames - got) * frame_bytes));
            return "";
          }
          if (got == 0) return "empty bytes list for '" + f.name + "'";
          return "";
        }
        case 2: {  // FloatList
          if (f.kind != kFloat)
            return "feature '" + f.name + "' is float but spec is not";
          return parse_float_list(
              f, list, (float*)slot.buffers[f.buf0] + (long long)row * f.count);
        }
        case 3: {  // Int64List
          if (f.kind != kInt)
            return "feature '" + f.name + "' is int64 but spec is not";
          return parse_int_list(
              f, list,
              slot.buffers[f.buf0] + (long long)row * f.count * f.dtype_size);
        }
        default:
          value.skip(wt);
      }
    }
    return "feature '" + f.name + "' has no value list";
  }

  // FloatList message -> exactly f.count floats at ``out``. Varlen
  // fields instead CLIP extras and PAD a short list with f.pad_value
  // (parser.py pad_or_clip_tensor_to_spec_shape parity).
  std::string parse_float_list(const FieldSpec& f, Cursor list, float* out) {
    long long got = 0;
    uint32_t wt2;
    // Packed encoding: field 1 wiretype 2 (bulk) or repeated wiretype 5.
    while (uint32_t f2 = list.tag(&wt2)) {
      if (f2 == 1 && wt2 == 2) {
        Cursor packed = list.bytes();
        long long n = packed.size() / 4;
        if (got + n > f.count) {
          if (!f.varlen)
            return "too many floats for '" + f.name + "'";
          n = f.count - got;  // clip
        }
        memcpy(out + got, packed.p, n * 4);
        got += n;
        if (f.varlen && got >= f.count) break;
      } else if (f2 == 1 && wt2 == 5) {
        if (got >= f.count) {
          if (!f.varlen)
            return "too many floats for '" + f.name + "'";
          list.p += 4;  // clip
          if (list.p > list.end) list.p = list.end;
          continue;
        }
        if (list.end - list.p < 4)
          return "truncated float in '" + f.name + "'";
        memcpy(out + got, list.p, 4);
        list.p += 4;
        got++;
      } else {
        list.skip(wt2);
      }
    }
    if (f.varlen) {
      for (long long i = got; i < f.count; i++)
        out[i] = (float)f.pad_value;
      return "";
    }
    if (got != f.count) {
      char buf[128];
      snprintf(buf, sizeof buf, "feature '%s': got %lld floats, want %lld",
               f.name.c_str(), got, f.count);
      return buf;
    }
    return "";
  }

  // Int64List message -> exactly f.count ints at ``base``; varlen fields
  // clip/pad like parse_float_list.
  std::string parse_int_list(const FieldSpec& f, Cursor list, uint8_t* base) {
    long long got = 0;
    uint32_t wt2;
    auto store = [&](uint64_t v) {
      switch (f.dtype_size) {
        case 1: base[got] = (uint8_t)v; break;
        case 4: ((int32_t*)base)[got] = (int32_t)v; break;
        default: ((int64_t*)base)[got] = (int64_t)v; break;
      }
      got++;
    };
    while (uint32_t f2 = list.tag(&wt2)) {
      if (f2 == 1 && wt2 == 2) {
        Cursor packed = list.bytes();
        while (packed.p < packed.end && got < f.count)
          store(packed.varint());
        if (packed.p < packed.end) {
          if (!f.varlen)
            return "too many ints for '" + f.name + "'";
          while (packed.p < packed.end) packed.varint();  // clip
        }
      } else if (f2 == 1 && wt2 == 0) {
        if (got >= f.count) {
          if (!f.varlen)
            return "too many ints for '" + f.name + "'";
          list.varint();  // clip
          continue;
        }
        store(list.varint());
      } else {
        list.skip(wt2);
      }
    }
    if (f.varlen) {
      // np.full-style C cast of the (float) default into the int dtype.
      while (got < f.count) store((uint64_t)(int64_t)f.pad_value);
      return "";
    }
    if (got != f.count) {
      char buf[128];
      snprintf(buf, sizeof buf, "feature '%s': got %lld ints, want %lld",
               f.name.c_str(), got, f.count);
      return buf;
    }
    return "";
  }

  // One step Feature inside a FeatureList -> f.count elements at ``out``.
  std::string extract_step(const FieldSpec& f, Cursor feature, uint8_t* out) {
    uint32_t wt;
    while (uint32_t fnum = feature.tag(&wt)) {
      if (wt != 2) {
        feature.skip(wt);
        continue;
      }
      Cursor list = feature.bytes();
      if (fnum == 2 && f.kind == kFloat)
        return parse_float_list(f, list, (float*)out);
      if (fnum == 3 && f.kind == kInt)
        return parse_int_list(f, list, out);
      if (fnum == 1)
        return "sequence feature '" + f.name + "' has bytes steps (not "
               "supported natively)";
      return "sequence feature '" + f.name + "' step kind mismatch";
    }
    return "sequence feature '" + f.name + "' has an empty step";
  }

  // FeatureList message ({1: repeated Feature}) -> [seq_cap, count] row
  // with zero padding past the record's step count (the Python parser's
  // batch-pad semantics; pad value 0 — varlen defaults fall back).
  std::string extract_sequence_field(const FieldSpec& f, Cursor fl,
                                     Slot& slot, int row) {
    int width = f.kind == kFloat ? 4 : f.dtype_size;
    long long step_bytes = f.count * width;
    uint8_t* base = slot.buffers[f.buf0] +
                    (long long)row * f.seq_cap * step_bytes;
    long long step = 0;
    uint32_t wt;
    while (uint32_t fnum = fl.tag(&wt)) {
      if (fnum == 1 && wt == 2) {
        if (step >= f.seq_cap) {
          char buf[160];
          snprintf(buf, sizeof buf, "sequence feature '%s': more than %lld "
                   "steps (raise sequence_max_len)", f.name.c_str(),
                   f.seq_cap);
          return buf;
        }
        std::string err = extract_step(f, fl.bytes(),
                                       base + step * step_bytes);
        if (!err.empty()) return err;
        step++;
      } else {
        fl.skip(wt);
      }
    }
    ((int32_t*)slot.buffers[f.buf_n])[row] = (int32_t)step;
    if (step < f.seq_cap)
      memset(base + step * step_bytes, 0, (f.seq_cap - step) * step_bytes);
    return "";
  }

  void worker_main(int worker_index) {
    for (;;) {
      WorkItem item;
      {
        long long t_idle = now_us();
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return stop.load() || !work.empty(); });
        st_worker_idle_us.fetch_add(now_us() - t_idle,
                                    std::memory_order_relaxed);
        if (stop.load()) return;
        if (work.empty()) continue;
        item = std::move(work.front());
        work.pop_front();
      }
      cv_space.notify_one();
      long long t_busy = now_us();
      std::string err = parse_into(item.records, item.slot, item.row);
      long long busy = now_us() - t_busy;
      st_worker_busy_us.fetch_add(busy, std::memory_order_relaxed);
      st_per_worker_busy_us[worker_index].fetch_add(
          busy, std::memory_order_relaxed);
      st_rows_parsed.fetch_add(1, std::memory_order_relaxed);
      long long record_bytes = 0;
      for (const auto& rec : item.records)
        record_bytes += (long long)rec.size();
      st_parse_bytes.fetch_add(record_bytes, std::memory_order_relaxed);
      Slot& slot = slots[item.slot];
      if (!err.empty()) {
        // Record the error but DEFER the fail/swallow decision to batch
        // completion: whether this batch is the EOF-discarded partial
        // batch (drop_remainder semantics — error irrelevant) is only
        // known for sure once all its rows are in, making the swallow
        // deterministic rather than a race against the reader reaching
        // EOF and marking seq = -2.
        std::lock_guard<std::mutex> lk(mu);
        if (slot.row_error.empty()) slot.row_error = err;
      }
      if (slot.remaining.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk(mu);
        if (slot.seq == -2) {  // discarded partial batch at EOF
          slot.state = kFree;
          cv_free.notify_one();
          cv_ready.notify_all();  // consumer may be waiting on the EOF check
        } else if (!slot.row_error.empty()) {
          // fail() under mu would deadlock; set the error state inline.
          if (error.empty()) error = slot.row_error;
          stop = true;
          cv_ready.notify_all();
          cv_work.notify_all();
          cv_free.notify_all();
          cv_space.notify_all();
          return;
        } else {
          slot.state = kReady;
          // Insert in seq order so batches come out deterministically.
          auto it = ready.begin();
          while (it != ready.end() && slots[*it].seq < slot.seq) ++it;
          ready.insert(it, item.slot);
          completed_batches++;
          cv_ready.notify_all();
        }
      }
    }
  }

  // ---- consumer API ------------------------------------------------------

  void ensure_launched() {
    // Thread launch deferred from create to the first next_slot() call:
    // all data/parse/decode errors then have ONE surfacing point
    // (iteration), deterministically — see the launch_once field note.
    std::call_once(launch_once, [this] {
      if (stop.load()) return;  // config already failed at create
      reader = std::thread([this] { reader_main(); });
      for (int i = 0; i < cfg.threads; i++)
        threads.emplace_back([this, i] { worker_main(i); });
    });
  }

  int next_slot() {
    ensure_launched();
    std::unique_lock<std::mutex> lk(mu);
    cv_ready.wait(lk, [&] {
      if (!error.empty()) return true;
      // Deliver strictly in dispatch order: batch assembly is deterministic
      // (single reader assigns rows in stream order), so ordered delivery
      // makes the whole pipeline reproducible under a fixed seed even
      // though decode is parallel.
      if (!ready.empty() && slots[ready.front()].seq == next_seq_out)
        return true;
      if (eof && next_seq_out >= dispatched_batches) return true;
      return false;
    });
    if (!error.empty()) return -2;
    if (ready.empty() || slots[ready.front()].seq != next_seq_out)
      return -1;  // end of data
    int slot = ready.front();
    ready.pop_front();
    slots[slot].state = kInUse;
    next_seq_out++;
    return slot;
  }

  void release(int slot) {
    {
      std::lock_guard<std::mutex> lk(mu);
      if (slot < 0 || slot >= (int)slots.size()) return;
      slots[slot].state = kFree;
    }
    cv_free.notify_one();
  }

  bool start(std::string* err) {
    // Buffers only — threads launch on the first next_slot() call
    // (ensure_launched), so create-time errors are config errors ONLY.
    st_per_worker_busy_us.reset(
        new std::atomic<long long>[cfg.threads > 0 ? cfg.threads : 1]);
    for (int i = 0; i < cfg.threads; i++) st_per_worker_busy_us[i] = 0;
    slots.resize(cfg.ring);
    for (auto& s : slots) {
      for (long long sz : cfg.buffer_sizes) {
        void* p = nullptr;
        if (posix_memalign(&p, 64, (size_t)sz) != 0) {
          *err = "allocation failed";
          return false;
        }
        s.buffers.push_back((uint8_t*)p);
      }
    }
    return true;
  }
};

}  // namespace

extern "C" {

void* t2r_loader_create(const char* config, int config_len) {
  auto* loader = new Loader();
  std::string err;
  if (!parse_config(std::string(config, config_len), &loader->cfg, &err) ||
      !loader->start(&err)) {
    loader->error = err.empty() ? "config error" : err;
    loader->stop = true;
    return loader;  // caller must check last_error
  }
  return loader;
}

const char* t2r_loader_last_error(void* h) {
  auto* loader = (Loader*)h;
  std::lock_guard<std::mutex> lk(loader->mu);
  return loader->error.c_str();
}

int t2r_loader_num_buffers(void* h) {
  return (int)((Loader*)h)->cfg.buffer_sizes.size();
}

long long t2r_loader_buffer_size(void* h, int buf) {
  auto* loader = (Loader*)h;
  if (buf < 0 || buf >= (int)loader->cfg.buffer_sizes.size()) return -1;
  return loader->cfg.buffer_sizes[buf];
}

void* t2r_loader_buffer_ptr(void* h, int slot, int buf) {
  auto* loader = (Loader*)h;
  if (slot < 0 || slot >= (int)loader->slots.size()) return nullptr;
  if (buf < 0 || buf >= (int)loader->slots[slot].buffers.size())
    return nullptr;
  return loader->slots[slot].buffers[buf];
}

int t2r_loader_ring_size(void* h) { return (int)((Loader*)h)->slots.size(); }

int t2r_loader_next(void* h) { return ((Loader*)h)->next_slot(); }

// Pipeline X-ray stats: fills up to n slots of `out` with the cumulative
// counters [records_read, bytes_read, reader_busy_us, reader_wait_us,
// rows_parsed, parse_bytes, worker_busy_us, worker_idle_us, n_workers,
// completed_batches, min_worker_busy_us, max_worker_busy_us]; returns the
// count written. Never launches the worker threads (lazy-launch boundary
// preserved): before the first next() every value is 0.
long long t2r_loader_stats(void* h, long long* out, int n) {
  return ((Loader*)h)->stats_snapshot(out, n);
}

void t2r_loader_release(void* h, int slot) { ((Loader*)h)->release(slot); }

void t2r_loader_destroy(void* h) { delete (Loader*)h; }

}  // extern "C"
