"""TFRecord container I/O without TensorFlow.

The TFRecord framing is public and tiny: each record is
``[uint64 length][uint32 masked_crc32c(length)][bytes data][uint32
masked_crc32c(data)]`` (little-endian). Keeping the reader dependency-free
lets data workers avoid importing the TF runtime; a C++ fast path can slot in
underneath later without changing callers.

Parity: the reference reads TFRecords via tf.data (utils/tfdata.py:155-219)
and writes them with tf.python_io.TFRecordWriter (utils/writer.py:31).
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional

from tensor2robot_tpu.reliability import fault_injection
from tensor2robot_tpu.reliability.errors import (
    CorruptRecordError,
    InjectedFault,
)
from tensor2robot_tpu.reliability.quarantine import RecordQuarantine

try:
  import google_crc32c

  def _crc32c(data: bytes) -> int:
    return google_crc32c.value(data)
except ImportError:  # pragma: no cover - google_crc32c ships in this image
  import zlib

  _CRC_TABLE = None

  def _crc32c(data: bytes) -> int:
    # Table-driven CRC32C (Castagnoli). Slow-path fallback only.
    global _CRC_TABLE
    if _CRC_TABLE is None:
      poly = 0x82F63B78
      table = []
      for i in range(256):
        crc = i
        for _ in range(8):
          crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
      _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
      crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
  crc = _crc32c(data)
  return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


class TFRecordWriter:
  """Appends framed records to a file."""

  def __init__(self, path: str):
    dirname = os.path.dirname(path)
    if dirname:
      os.makedirs(dirname, exist_ok=True)
    self._file = open(path, 'wb')

  def write(self, record: bytes) -> None:
    length = struct.pack('<Q', len(record))
    self._file.write(length)
    self._file.write(struct.pack('<I', _masked_crc(length)))
    self._file.write(record)
    self._file.write(struct.pack('<I', _masked_crc(record)))

  def flush(self) -> None:
    self._file.flush()

  def close(self) -> None:
    self._file.close()

  def __enter__(self):
    return self

  def __exit__(self, *_):
    self.close()


def tfrecord_iterator(path: str,
                      verify_crc: bool = False,
                      skip_corrupt: bool = False,
                      quarantine: Optional[RecordQuarantine] = None
                      ) -> Iterator[bytes]:
  """Yields raw record payloads from one TFRecord file.

  Fault model (docs/reliability.md): a bad *data* CRC leaves the framing
  intact — with ``skip_corrupt`` the record is charged to ``quarantine``
  and skipped. A bad *length* CRC or a truncated frame means the framing
  itself is untrustworthy, so the remainder of the file is abandoned (one
  record charge + a file-abandoned mark). Without ``skip_corrupt`` every
  corruption raises ``CorruptRecordError`` (an IOError) as before. The
  ``data.read`` FaultInjector site fires per record and is handled exactly
  like a data-CRC corruption.
  """
  if skip_corrupt and quarantine is None:
    quarantine = RecordQuarantine()
  with open(path, 'rb') as f:
    index = 0
    while True:
      header = f.read(12)
      if len(header) == 0:
        return
      if len(header) < 12:
        # Trailing partial frame: a truncated write (e.g. a crashed
        # writer). Historically silent; in skip mode it is accounted.
        if skip_corrupt:
          quarantine.record_skipped(path, 'truncated header', index)
          quarantine.file_abandoned(path, 'truncated header')
        return
      (length,) = struct.unpack('<Q', header[:8])
      if verify_crc:
        (expected,) = struct.unpack('<I', header[8:12])
        if _masked_crc(header[:8]) != expected:
          if skip_corrupt:
            # Framing lost: the length field itself is suspect, so there
            # is no trustworthy way to find the next record boundary.
            quarantine.record_skipped(path, 'length CRC', index)
            quarantine.file_abandoned(path, 'length CRC')
            return
          raise CorruptRecordError(path, 'length CRC', index)
      data = f.read(length)
      if len(data) < length:
        if skip_corrupt:
          quarantine.record_skipped(path, 'truncated data', index)
          quarantine.file_abandoned(path, 'truncated data')
          return
        raise CorruptRecordError(path, 'truncation', index)
      footer = f.read(4)
      if len(footer) < 4:
        if skip_corrupt:
          quarantine.record_skipped(path, 'truncated footer', index)
          quarantine.file_abandoned(path, 'truncated footer')
          return
        raise CorruptRecordError(path, 'truncation', index)
      if verify_crc:
        (expected,) = struct.unpack('<I', footer)
        if _masked_crc(data) != expected:
          index += 1
          if skip_corrupt:
            # Frame boundaries are still valid — only this record's
            # payload is damaged; skip it and keep reading.
            quarantine.record_skipped(path, 'data CRC', index - 1)
            continue
          raise CorruptRecordError(path, 'data CRC', index - 1)
      try:
        fault_injection.maybe_fail(fault_injection.SITE_DATA_READ)
      except InjectedFault:
        index += 1
        if skip_corrupt:
          quarantine.record_skipped(path, 'injected', index - 1)
          continue
        raise CorruptRecordError(path, 'injected', index - 1)
      index += 1
      yield data


def read_all_records(path: str) -> List[bytes]:
  return list(tfrecord_iterator(path))


def write_records(path: str, records) -> None:
  with TFRecordWriter(path) as writer:
    for record in records:
      writer.write(record)
