"""Replay writer: serializes spec-conforming numpy episodes to TFRecords.

Parity: TFRecordReplayWriter, /root/reference/utils/writer.py:31 — the
collect loop's half of the filesystem actor↔learner transport.
"""

from __future__ import annotations

from typing import Optional

from tensor2robot_tpu.data.tfrecord import TFRecordWriter


class TFRecordReplayWriter:
  """Writes serialized tf.Example bytes (or encodes numpy via specs)."""

  def __init__(self):
    self._writer: Optional[TFRecordWriter] = None

  def open(self, path: str) -> None:
    self.close()
    self._writer = TFRecordWriter(path)

  def write(self, serialized_records) -> None:
    """Writes one record or a list of records (bytes)."""
    if self._writer is None:
      raise ValueError('open() must be called before write().')
    if isinstance(serialized_records, bytes):
      serialized_records = [serialized_records]
    for record in serialized_records:
      self._writer.write(record)

  def write_numpy(self, spec_structure, numpy_struct) -> None:
    from tensor2robot_tpu.data.parser import build_example_for_specs
    self.write(build_example_for_specs(spec_structure, numpy_struct))

  def flush(self) -> None:
    if self._writer is not None:
      self._writer.flush()

  def close(self) -> None:
    if self._writer is not None:
      self._writer.close()
      self._writer = None

  def __enter__(self):
    return self

  def __exit__(self, *_):
    self.close()
