"""Device-side JPEG finishing: DCT coefficients -> RGB pixels, in-jit.

The TPU half of the split-decode input path. The native loader's
``image_mode='coef'`` stops after entropy (Huffman) decode — the only
inherently sequential stage of JPEG — and ships quantized DCT coefficient
blocks to the device (data/native/record_loader.cc, decode_jpeg_coef). This
module finishes the decode inside the jitted train step:

    dequantize -> 8x8 IDCT (einsum, MXU) -> block reassembly -> chroma
    upsample -> YCbCr -> RGB

Why: host JPEG decode is the input bottleneck on CPU-poor hosts (SURVEY.md
hard-part #3). Measured on one host core, entropy-only decode runs ~1.5x
faster than full decode (the IDCT/upsample/color stages are the pixel-domain
majority of decode cost), and the device-side finish is ~8 MFLOP per
512x640 frame — noise next to the 25 GFLOP the QT-Opt critic spends per
example. The reference has no analog (its tf.data pipeline decodes fully on
host); this is a TPU-first redesign of the ingest path.

Caveats: baseline 4:2:0 JPEGs with dims divisible by 16 (what the replay
writer and any camera pipeline produce). Chroma upsampling matches
libjpeg's default triangle filter in float arithmetic; together with the
float YCbCr->RGB conversion (libjpeg uses fixed-point), decoded pixels sit
within +/-4 of a host decode, 98% within +/-1 — below JPEG's own
quantization noise (verified in tests/test_native_loader.py).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def _idct_matrix() -> np.ndarray:
  """8x8 DCT-III (inverse DCT-II) basis: x = B @ F @ B.T per block."""
  k = np.arange(8)
  n = np.arange(8)
  basis = np.cos((2 * n[:, None] + 1) * k[None, :] * np.pi / 16)
  alpha = np.full(8, np.sqrt(2.0 / 8.0))
  alpha[0] = np.sqrt(1.0 / 8.0)
  return (basis * alpha[None, :]).astype(np.float32)  # [n, k]


_IDCT_B = _idct_matrix()


def _blocks_to_plane(blocks: jnp.ndarray) -> jnp.ndarray:
  """[B, Hb, Wb, 8, 8] pixel blocks -> [B, Hb*8, Wb*8] plane."""
  b, hb, wb, _, _ = blocks.shape
  return blocks.transpose(0, 1, 3, 2, 4).reshape(b, hb * 8, wb * 8)


def _idct_plane(coef: jnp.ndarray, quant: jnp.ndarray) -> jnp.ndarray:
  """Dequantize + 2D IDCT + level shift for one component.

  coef: [B, Hb, Wb, 64] int16 quantized coefficients (natural order).
  quant: [B, 64] uint16 quantization table.
  Returns [B, Hb*8, Wb*8] float32 in [0, 255] (unclipped).
  """
  f = coef.astype(jnp.float32) * quant.astype(jnp.float32)[:, None, None, :]
  f = f.reshape(f.shape[:3] + (8, 8))
  basis = jnp.asarray(_IDCT_B)
  # x[n, m] = sum_{k, l} B[n, k] F[k, l] B[m, l]
  x = jnp.einsum('nk,bhwkl,ml->bhwnm', basis, f, basis)
  return _blocks_to_plane(x) + 128.0


def _upsample2x_nearest(plane: jnp.ndarray) -> jnp.ndarray:
  """Nearest-neighbor 2x chroma upsample ([B, h, w] -> [B, 2h, 2w])."""
  return jnp.repeat(jnp.repeat(plane, 2, axis=1), 2, axis=2)


def _upsample2x_triangle(plane: jnp.ndarray) -> jnp.ndarray:
  """libjpeg's default h2v2 'fancy' upsample: 3:1 triangle filter.

  Vertical pass then horizontal pass; each output pixel is 3 parts nearest
  input pixel, 1 part next-nearest, edges replicated (jdsample.c
  h2v2_fancy_upsample, in float arithmetic).
  """
  p = plane
  shift_up = jnp.concatenate([p[:, :1], p[:, :-1]], axis=1)
  shift_dn = jnp.concatenate([p[:, 1:], p[:, -1:]], axis=1)
  v_even = (3.0 * p + shift_up) * 0.25
  v_odd = (3.0 * p + shift_dn) * 0.25
  v = jnp.stack([v_even, v_odd], axis=2).reshape(
      p.shape[0], -1, p.shape[2])
  shift_l = jnp.concatenate([v[:, :, :1], v[:, :, :-1]], axis=2)
  shift_r = jnp.concatenate([v[:, :, 1:], v[:, :, -1:]], axis=2)
  h_even = (3.0 * v + shift_l) * 0.25
  h_odd = (3.0 * v + shift_r) * 0.25
  return jnp.stack([h_even, h_odd], axis=3).reshape(
      v.shape[0], v.shape[1], -1)


def decode_jpeg_coefficients(y: jnp.ndarray, cb: jnp.ndarray,
                             cr: jnp.ndarray, qt: jnp.ndarray,
                             dtype=jnp.uint8,
                             fancy_upsample: bool = True) -> jnp.ndarray:
  """Finishes a batch of 4:2:0 JPEGs from quantized DCT coefficients.

  Args:
    y:  [B, H/8, W/8, 64] int16 luma coefficient blocks.
    cb: [B, H/16, W/16, 64] int16 chroma-blue blocks.
    cr: [B, H/16, W/16, 64] int16 chroma-red blocks.
    qt: [B, 3, 64] uint16 quant tables (luma, cb, cr — natural order).
    dtype: output dtype; uint8 matches a host decode, float32 skips the
      round-trip when the consumer immediately normalizes.
    fancy_upsample: triangle-filter chroma upsample (libjpeg default
      parity); False uses nearest (cheaper, coarser chroma edges).

  Returns: [B, H, W, 3] RGB image batch.
  """
  upsample = _upsample2x_triangle if fancy_upsample else _upsample2x_nearest
  luma = _idct_plane(y, qt[:, 0])
  cb_p = upsample(_idct_plane(cb, qt[:, 1]))
  cr_p = upsample(_idct_plane(cr, qt[:, 2]))
  cb_c = cb_p - 128.0
  cr_c = cr_p - 128.0
  r = luma + 1.402 * cr_c
  g = luma - 0.344136 * cb_c - 0.714136 * cr_c
  b = luma + 1.772 * cb_c
  rgb = jnp.stack([r, g, b], axis=-1)
  rgb = jnp.clip(jnp.round(rgb), 0.0, 255.0)
  return rgb.astype(dtype)


def unpack_sparse_coefficients(sd: jnp.ndarray, sv: jnp.ndarray,
                               height: int, width: int):
  """Sparse (delta, value) entry streams -> dense coefficient planes.

  Inverse of the native loader's ``image_mode='coef_sparse'`` packing
  (record_loader.cc, decode_jpeg_coef_sparse): each entry advances a
  cursor through the unified flat coefficient space [y | cb | cr] by
  ``sd`` positions and adds ``sv`` there. Skip entries (255, 0),
  value-continuation entries (0, piece) and tail padding (0, 0) all fall
  out of the same cumsum + scatter-add — measured ~15 ms for a 64-frame
  512x640 batch on one v5e (4,270 frames/s), ~17x the post-compression
  transfer rate it serves.

  Args:
    sd: [B, C] uint8 position deltas.
    sv: [B, C] int8 value pieces.
    height, width: frame geometry (divisible by 16).

  Returns: (y, cb, cr) int16 dense blocks shaped like the 'coef' mode
  outputs ([B, H/8, W/8, 64], [B, H/16, W/16, 64] x2, natural order).
  """
  b, _ = sd.shape
  yb = (height // 8) * (width // 8)
  cbn = (height // 16) * (width // 16)
  total = (yb + 2 * cbn) * 64
  pos = jnp.cumsum(sd.astype(jnp.int32), axis=1) - 1
  # Rows with zero entries keep the cursor at -1; jnp negative indices
  # WRAP, so route them out of bounds for mode='drop' instead.
  pos = jnp.where(pos < 0, total, pos)
  dense = jnp.zeros((b, total), jnp.int16)
  dense = dense.at[jnp.arange(b)[:, None], pos].add(
      sv.astype(jnp.int16), mode='drop')
  y = dense[:, :yb * 64].reshape(b, height // 8, width // 8, 64)
  cb = dense[:, yb * 64:(yb + cbn) * 64].reshape(
      b, height // 16, width // 16, 64)
  cr = dense[:, (yb + cbn) * 64:].reshape(b, height // 16, width // 16, 64)
  return y, cb, cr


def unpack_packed_coefficients(pw: jnp.ndarray, se: jnp.ndarray,
                               dcn: jnp.ndarray, height: int, width: int):
  """PACKED wire streams -> dense coefficient planes (bit-exact).

  Inverse of the native loader's ``image_mode='coef_packed'`` encoding
  (record_loader.cc, decode_jpeg_coef_packed). Three streams per image:

    * ``pw`` [B, C] uint8 — AC nibble stream: high nibble = position gap,
      low nibble = value code (1..7 -> +v, 9..15 -> v-16, 8 -> escape,
      0 with gap > 0 -> skip gap*16, 0x00 -> padding no-op).
    * ``se`` [B, E] int16 — escape values: per row, the DC escapes first
      (frame order) then the AC escapes (stream order).
    * ``dcn`` [B, nblocks/2] uint8 — per-block DC-delta nibbles, packed
      two per byte low-first; code 8 escapes to ``se``; the chain starts
      at 0 and is undone with one cumsum over blocks.

  Every byte kind reduces to the same (delta, value) pair shape, so the
  reconstruction stays the loose format's cumsum + scatter-add plus two
  ``take_along_axis`` gathers for the escapes and one cumsum for the DC
  chain — all static-shape, all fused into the same unpack jit the feed
  already caches per bucket (data/device_feed.py).

  Returns: (y, cb, cr) int16 dense blocks, shaped like the 'coef' mode
  outputs, bit-exact vs both the 'coef' and 'coef_sparse' paths.
  """
  b = pw.shape[0]
  yb = (height // 8) * (width // 8)
  cbn = (height // 16) * (width // 16)
  total = (yb + 2 * cbn) * 64
  nblocks = total // 64

  d4 = (pw >> 4).astype(jnp.int32)
  v4 = (pw & 15).astype(jnp.int32)
  is_esc = v4 == 8
  is_skip = (v4 == 0) & (d4 > 0)
  delta = jnp.where(is_skip, d4 << 4, d4)
  vnib = jnp.where(v4 < 8, v4, v4 - 16)

  # DC-delta nibble plane -> per-block codes (low nibble first).
  lo = (dcn & 15).astype(jnp.int32)
  hi = (dcn >> 4).astype(jnp.int32)
  codes = jnp.stack([lo, hi], axis=2).reshape(b, nblocks)
  dmark = codes == 8
  dnib = jnp.where(codes < 8, codes, codes - 16)

  # Escape gathers: region [0, n_dc_esc) holds DC escapes, the rest AC.
  n_esc = se.shape[1]
  dce_idx = jnp.cumsum(dmark.astype(jnp.int32), axis=1) - 1
  dce = jnp.take_along_axis(se, jnp.clip(dce_idx, 0, n_esc - 1), axis=1)
  n_dc_esc = jnp.sum(dmark.astype(jnp.int32), axis=1, keepdims=True)
  ace_idx = n_dc_esc + jnp.cumsum(is_esc.astype(jnp.int32), axis=1) - 1
  ace = jnp.take_along_axis(se, jnp.clip(ace_idx, 0, n_esc - 1), axis=1)

  val = jnp.where(is_esc, ace.astype(jnp.int32),
                  jnp.where(is_skip, 0, vnib))
  pos = jnp.cumsum(delta, axis=1) - 1
  # Rows with zero entries keep the cursor at -1; negative indices WRAP,
  # so route them out of bounds for mode='drop' (same as the loose path).
  pos = jnp.where(pos < 0, total, pos)
  dense = jnp.zeros((b, total), jnp.int16)
  dense = dense.at[jnp.arange(b)[:, None], pos].add(
      val.astype(jnp.int16), mode='drop')

  dcd = jnp.where(dmark, dce.astype(jnp.int32), dnib)
  dcv = jnp.cumsum(dcd, axis=1).astype(jnp.int16)
  dense = dense.reshape(b, nblocks, 64).at[:, :, 0].add(dcv)
  dense = dense.reshape(b, total)

  y = dense[:, :yb * 64].reshape(b, height // 8, width // 8, 64)
  cb = dense[:, yb * 64:(yb + cbn) * 64].reshape(
      b, height // 16, width // 16, 64)
  cr = dense[:, (yb + cbn) * 64:].reshape(b, height // 16, width // 16, 64)
  return y, cb, cr


def unpack_packed_features(features, image_shapes):
  """Replaces ``key/{pw,se,dcn}`` packed groups with dense ``key/{y,cb,cr}``.

  The hoisted ``key/qt`` [1, 3, 64] table is broadcast back to the batch
  dim, leaving exactly the 'coef' mode feature set decode_coef_features
  consumes. Jittable; callers cache one jit per bucket shape
  (data/device_feed.py) so the train step itself never recompiles.
  """
  for key, (height, width) in image_shapes.items():
    pw = features.pop(key + '/pw')
    se = features.pop(key + '/se')
    dcn = features.pop(key + '/dcn')
    y, cb, cr = unpack_packed_coefficients(pw, se, dcn, height, width)
    features[key + '/y'] = y
    features[key + '/cb'] = cb
    features[key + '/cr'] = cr
    qt = features[key + '/qt']
    if qt.shape[0] != y.shape[0]:
      features[key + '/qt'] = jnp.broadcast_to(
          qt[0], (y.shape[0],) + tuple(qt.shape[1:]))
  return features


def unpack_sparse_features(features, image_shapes):
  """Replaces ``key/{sd,sv}`` sparse groups with dense ``key/{y,cb,cr}``.

  ``image_shapes`` maps image key -> (height, width). The ``key/qt``
  tables pass through unchanged and ``key/n`` entry counts are dropped,
  leaving exactly the 'coef' mode feature set decode_coef_features
  consumes. Jittable; callers cache one jit per (batch, bucket) shape
  (data/device_feed.py) so the train step itself never recompiles.
  """
  for key, (height, width) in image_shapes.items():
    sd = features.pop(key + '/sd')
    sv = features.pop(key + '/sv')
    features.pop(key + '/n', None)
    y, cb, cr = unpack_sparse_coefficients(sd, sv, height, width)
    features[key + '/y'] = y
    features[key + '/cb'] = cb
    features[key + '/cr'] = cr
  return features


def decode_coef_features(features, image_keys, dtype=jnp.uint8):
  """Replaces ``key/{y,cb,cr,qt}`` coefficient groups with decoded ``key``.

  The native loader in coef mode emits four arrays per image spec; call
  this first inside the jitted step (before the preprocessor) to
  materialize the spec's actual image tensor on device.
  """
  for key in image_keys:
    y = features.pop(key + '/y')
    cb = features.pop(key + '/cb')
    cr = features.pop(key + '/cr')
    qt = features.pop(key + '/qt')
    features[key] = decode_jpeg_coefficients(y, cb, cr, qt, dtype=dtype)
  return features
