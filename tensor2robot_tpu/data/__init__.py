"""Data pipeline: TFRecord I/O, spec-driven Example parsing, input generators."""

from tensor2robot_tpu.data.tfrecord import (
    TFRecordWriter,
    read_all_records,
    tfrecord_iterator,
    write_records,
)
from tensor2robot_tpu.data.wire import (
    build_example,
    build_sequence_example,
    parse_example,
    parse_sequence_example,
)
from tensor2robot_tpu.data.parser import (
    ExampleParser,
    build_example_for_specs,
    decode_image,
)
from tensor2robot_tpu.data.pipeline import (
    BatchedExampleStream,
    RecordDataset,
    parse_file_patterns,
)
from tensor2robot_tpu.data.input_generators import (
    AbstractInputGenerator,
    DefaultConstantInputGenerator,
    DefaultRandomInputGenerator,
    DefaultRecordInputGenerator,
    FractionalRecordInputGenerator,
    GeneratorInputGenerator,
    MultiEvalRecordInputGenerator,
    get_multi_eval_name,
)
from tensor2robot_tpu.data.writer import TFRecordReplayWriter
from tensor2robot_tpu.data.native_loader import (
    NativeBatchedStream,
    build_native,
    plan_for_specs,
)
from tensor2robot_tpu.data.jpeg_device import (
    decode_coef_features,
    decode_jpeg_coefficients,
)
