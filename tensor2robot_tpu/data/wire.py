"""Protobuf wire-format codec for tf.Example / tf.SequenceExample.

A minimal, numpy-first encoder/decoder for the public Example schema
(tensorflow/core/example/{example,feature}.proto):

  Example         { Features features = 1; }
  Features        { map<string, Feature> feature = 1; }
  Feature         { oneof: BytesList=1 | FloatList=2 | Int64List=3 }
  BytesList       { repeated bytes value = 1; }
  FloatList       { repeated float value = 1 [packed]; }
  Int64List       { repeated int64 value = 1 [packed]; }
  SequenceExample { Features context = 1; FeatureLists feature_lists = 2; }
  FeatureLists    { map<string, FeatureList> feature_list = 1; }
  FeatureList     { repeated Feature feature = 1; }

Hand-rolling the codec keeps the TF runtime out of data workers entirely and
doubles as the executable spec for the native C++ loader. Packed float lists
decode via ``np.frombuffer`` (zero-copy views onto the record buffer).

Parity: the decode side replaces tf.io.parse_example /
parse_sequence_example as driven by the reference's spec-derived feature
dicts (utils/tfdata.py:357-366).
"""

from __future__ import annotations

from typing import Dict, List, Tuple, Union

import numpy as np

# Feature payload: ('bytes', [bytes]) | ('float', f32 array) | ('int64', i64 array)
FeatureValue = Tuple[str, Union[List[bytes], np.ndarray]]

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_BYTES = 2
_WIRE_FIXED32 = 5


# -- varint primitives -------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
  result = 0
  shift = 0
  while True:
    b = buf[pos]
    pos += 1
    result |= (b & 0x7F) << shift
    if not b & 0x80:
      return result, pos
    shift += 7
    if shift > 63:
      raise ValueError('Malformed varint')


def _write_varint(out: bytearray, value: int) -> None:
  value &= 0xFFFFFFFFFFFFFFFF
  while True:
    bits = value & 0x7F
    value >>= 7
    if value:
      out.append(bits | 0x80)
    else:
      out.append(bits)
      return


def _iter_fields(buf, start: int, end: int):
  """Yields (field_number, wire_type, value). BYTES fields yield (s, e) spans."""
  pos = start
  while pos < end:
    tag, pos = _read_varint(buf, pos)
    field, wire = tag >> 3, tag & 0x7
    if wire == _WIRE_VARINT:
      value, pos = _read_varint(buf, pos)
    elif wire == _WIRE_BYTES:
      length, pos = _read_varint(buf, pos)
      value = (pos, pos + length)
      pos += length
    elif wire == _WIRE_FIXED32:
      value = (pos, pos + 4)
      pos += 4
    elif wire == _WIRE_FIXED64:
      value = (pos, pos + 8)
      pos += 8
    else:
      raise ValueError('Unsupported wire type {}'.format(wire))
    yield field, wire, value


# -- Feature decode ----------------------------------------------------------

def _decode_varint_list(buf, start: int, end: int) -> np.ndarray:
  values = []
  pos = start
  while pos < end:
    v, pos = _read_varint(buf, pos)
    # Interpret as signed int64 (two's complement).
    if v >= 1 << 63:
      v -= 1 << 64
    values.append(v)
  return np.asarray(values, dtype=np.int64)


def _decode_feature(buf, start: int, end: int) -> FeatureValue:
  kind = None
  payload = None
  for field, wire, value in _iter_fields(buf, start, end):
    s, e = value
    if field == 1:  # BytesList
      items = []
      for f2, _, v2 in _iter_fields(buf, s, e):
        if f2 == 1:
          items.append(bytes(buf[v2[0]:v2[1]]))
      kind, payload = 'bytes', items
    elif field == 2:  # FloatList
      if wire == _WIRE_BYTES:
        chunks = []
        floats = None
        for f2, w2, v2 in _iter_fields(buf, s, e):
          if f2 == 1 and w2 == _WIRE_BYTES:  # packed
            chunks.append(np.frombuffer(buf, dtype='<f4', count=(v2[1] - v2[0]) // 4, offset=v2[0]))
          elif f2 == 1 and w2 == _WIRE_FIXED32:  # unpacked
            chunks.append(np.frombuffer(buf, dtype='<f4', count=1, offset=v2[0]))
        floats = np.concatenate(chunks) if chunks else np.zeros((0,), np.float32)
        kind, payload = 'float', floats
    elif field == 3:  # Int64List
      chunks = []
      for f2, w2, v2 in _iter_fields(buf, s, e):
        if f2 == 1 and w2 == _WIRE_BYTES:  # packed varints
          chunks.append(_decode_varint_list(buf, v2[0], v2[1]))
        elif f2 == 1 and w2 == _WIRE_VARINT:  # unpacked
          v = v2 if isinstance(v2, int) else 0
          if v >= 1 << 63:
            v -= 1 << 64
          chunks.append(np.asarray([v], dtype=np.int64))
      ints = np.concatenate(chunks) if chunks else np.zeros((0,), np.int64)
      kind, payload = 'int64', ints
  if kind is None:
    return 'bytes', []
  return kind, payload


def _decode_features_message(buf, start: int, end: int) -> Dict[str, FeatureValue]:
  """Decodes a Features message (map<string, Feature>)."""
  out = {}
  for field, _, value in _iter_fields(buf, start, end):
    if field != 1:
      continue
    s, e = value
    key = None
    feat = None
    for f2, _, v2 in _iter_fields(buf, s, e):
      if f2 == 1:
        key = bytes(buf[v2[0]:v2[1]]).decode('utf-8')
      elif f2 == 2:
        feat = v2
    if key is not None and feat is not None:
      out[key] = _decode_feature(buf, feat[0], feat[1])
  return out


def parse_example(serialized: bytes) -> Dict[str, FeatureValue]:
  """Decodes a tf.Example into {feature_name: (kind, values)}."""
  buf = memoryview(serialized)
  for field, _, value in _iter_fields(buf, 0, len(buf)):
    if field == 1:
      return _decode_features_message(buf, value[0], value[1])
  return {}


def parse_sequence_example(serialized: bytes):
  """Decodes a tf.SequenceExample.

  Returns:
    (context, feature_lists): context is {name: (kind, values)};
    feature_lists is {name: [(kind, values), ...]} one entry per step.
  """
  buf = memoryview(serialized)
  context: Dict[str, FeatureValue] = {}
  feature_lists: Dict[str, List[FeatureValue]] = {}
  for field, _, value in _iter_fields(buf, 0, len(buf)):
    if field == 1:
      context = _decode_features_message(buf, value[0], value[1])
    elif field == 2:
      s, e = value
      for f2, _, v2 in _iter_fields(buf, s, e):
        if f2 != 1:
          continue
        ks, ke = v2
        key = None
        steps: List[FeatureValue] = []
        for f3, _, v3 in _iter_fields(buf, ks, ke):
          if f3 == 1:
            key = bytes(buf[v3[0]:v3[1]]).decode('utf-8')
          elif f3 == 2:  # FeatureList
            for f4, _, v4 in _iter_fields(buf, v3[0], v3[1]):
              if f4 == 1:
                steps.append(_decode_feature(buf, v4[0], v4[1]))
        if key is not None:
          feature_lists[key] = steps
  return context, feature_lists


# -- encode ------------------------------------------------------------------

def _emit_bytes_field(out: bytearray, field: int, data: bytes) -> None:
  _write_varint(out, (field << 3) | _WIRE_BYTES)
  _write_varint(out, len(data))
  out.extend(data)


def encode_feature(value) -> bytes:
  """Encodes one Feature from numpy array / bytes / str / list thereof."""
  out = bytearray()
  if isinstance(value, (bytes, str)):
    value = [value]
  if isinstance(value, (list, tuple)) and value and isinstance(value[0], (bytes, str)):
    inner = bytearray()
    for item in value:
      if isinstance(item, str):
        item = item.encode('utf-8')
      _emit_bytes_field(inner, 1, item)
    _emit_bytes_field(out, 1, bytes(inner))
    return bytes(out)
  if isinstance(value, (list, tuple)) and not value:
    _emit_bytes_field(out, 1, b'')  # empty BytesList
    return bytes(out)
  arr = np.asarray(value)
  if arr.dtype.kind == 'f':
    data = arr.astype('<f4').ravel().tobytes()
    inner = bytearray()
    _emit_bytes_field(inner, 1, data)  # packed floats
    _emit_bytes_field(out, 2, bytes(inner))
  elif arr.dtype.kind in 'uib':
    inner = bytearray()
    packed = bytearray()
    for v in arr.ravel().tolist():
      _write_varint(packed, int(v))
    _emit_bytes_field(inner, 1, bytes(packed))
    _emit_bytes_field(out, 3, bytes(inner))
  else:
    raise ValueError('Cannot encode feature of dtype {}'.format(arr.dtype))
  return bytes(out)


def _encode_features(features: Dict[str, object]) -> bytes:
  out = bytearray()
  for name, value in features.items():
    entry = bytearray()
    _emit_bytes_field(entry, 1, name.encode('utf-8'))
    _emit_bytes_field(entry, 2, encode_feature(value))
    _emit_bytes_field(out, 1, bytes(entry))
  return bytes(out)


def build_example(features: Dict[str, object]) -> bytes:
  """Encodes {name: array|bytes|list} into a serialized tf.Example."""
  out = bytearray()
  _emit_bytes_field(out, 1, _encode_features(features))
  return bytes(out)


def build_sequence_example(context: Dict[str, object],
                           feature_lists: Dict[str, List[object]]) -> bytes:
  """Encodes a serialized tf.SequenceExample.

  ``feature_lists`` maps name -> list of per-step values.
  """
  out = bytearray()
  if context:
    _emit_bytes_field(out, 1, _encode_features(context))
  lists = bytearray()
  for name, steps in feature_lists.items():
    entry = bytearray()
    _emit_bytes_field(entry, 1, name.encode('utf-8'))
    fl = bytearray()
    for step in steps:
      _emit_bytes_field(fl, 1, encode_feature(step))
    _emit_bytes_field(entry, 2, bytes(fl))
    _emit_bytes_field(lists, 1, bytes(entry))
  _emit_bytes_field(out, 2, bytes(lists))
  return bytes(out)


# -- public low-level codec surface ------------------------------------------
# Consumers outside the Example codec (metrics events, TF-Serving warmup
# protos) emit/walk wire-format messages with these.

emit_bytes_field = _emit_bytes_field
write_varint = _write_varint
iter_fields = _iter_fields
